#!/bin/bash
# Round-5 staged hardware evidence chain (VERDICT.md round-5 #1).
# Shortest-first so even a brief healthy-tunnel window leaves committed
# JSON; capacity runs LAST (its killed-subprocess probes are the known
# tunnel-wedge risk, BENCH_NOTES.md round 3).  Commits after EVERY
# artifact with a retry loop (another workflow may hold the git index).
cd /root/repo
log=bench_chain_r05.log
echo "=== chain start $(date -u) ===" >> "$log"

bank() {  # bank <msg> <files...>: stage+commit artifacts, retrying index locks
  msg=$1; shift
  ok=0
  for i in 1 2 3 4 5; do
    for f in "$@"; do [ -e "$f" ] && git add "$f" >> "$log" 2>&1 || true; done
    git commit -q -m "$msg" >> "$log" 2>&1 && { ok=1; break; }
    sleep 7
  done
  [ "$ok" = 1 ] || echo "!!! commit FAILED after retries: $msg" >> "$log"
}

run() {  # run <name> <outfile> <cmd...>
  name=$1; out=$2; shift 2
  echo "=== $name start $(date -u +%H:%M:%S) ===" >> "$log"
  "$@" > "$out" 2>> "$log"
  rc=$?
  echo "=== $name rc=$rc $(date -u +%H:%M:%S) ===" >> "$log"
}

# 1. cpu_adam: host-only, fastest, tunnel-independent
run cpu_adam BENCH_cpu_adam.txt python bench_cpu_adam.py
bank "Bench artifact: CPU-Adam kernel microbench (hardware window)" \
  BENCH_cpu_adam.txt "$log"

# 2-5. short TPU benches
run flash BENCH_flash_raw.json python bench_flash.py
bank "Bench artifact: flash-attention block sweep on TPU" \
  BENCH_flash.json BENCH_flash_raw.json "$log"

run sparse BENCH_sparse_raw.json python bench_sparse.py
bank "Bench artifact: block-sparse vs flash vs dense on TPU" \
  BENCH_sparse.json BENCH_sparse_raw.json "$log"

run bert BENCH_bert_raw.json python bench_bert.py
bank "Bench artifact: BERT-large TFLOPS on TPU" \
  BENCH_bert.json BENCH_bert_raw.json "$log"

run moe BENCH_moe_raw.json python bench_moe.py
bank "Bench artifact: MoE dispatch overhead on TPU" \
  BENCH_moe.json BENCH_moe_raw.json "$log"

# 6. the north star: GPT-2 1.5B ZeRO-Offload (suite chain disabled - already ran)
run north_star BENCH_r05_raw.json env BENCH_SUITE=0 python bench.py
bank "Bench artifact: GPT-2 1.5B north-star run on TPU" \
  BENCH_north_star.json BENCH_r05_raw.json "$log"

# 7. capacity LAST (wedge risk)
run capacity BENCH_capacity_raw.json python bench_capacity.py
bank "Bench artifact: measured single-chip capacity ratio (ZeRO-Offload)" \
  BENCH_capacity.json BENCH_capacity_raw.json "$log"

# 8. hostperf + offload diagnostics if the tunnel is still alive
run hostperf DIAG_hostperf_run.log python diag_hostperf.py
bank "Diag artifact: host-offload bandwidth/remat diagnostics" \
  DIAG_hostperf_run.log DIAG_hostperf.json "$log"

echo "=== chain done $(date -u) ===" >> "$log"
