// CPU Adam — the ZeRO-Offload host optimizer kernel.
//
// TPU-native equivalent of the reference's AVX/OpenMP CPU Adam
// (reference: csrc/adam/cpu_adam.cpp:21-113, csrc/includes/cpu_adam.h).
// The reference hand-writes AVX-512/AVX-2 intrinsics behind a SIMD macro
// layer; here the inner loop is written so the compiler's vectorizer emits
// the same code for whatever the host ISA is (x86 AVX on TPU-VM hosts,
// NEON on ARM) — `#pragma omp simd` + restrict pointers + -O3 -march=native.
// OpenMP threads split the parameter range exactly like the reference's
// tiled loop (cpu_adam.cpp:64-113).
//
// The fused low-precision copy-back (reference writes fp16 params for the
// GPU while updating, cpu_adam.cpp:101-112 + param_update kernel) is the
// `out_lowp` argument: the updated fp32 master is converted to bf16
// (round-to-nearest-even) or fp16 in the same pass, ready for upload to
// TPU HBM.
//
// C ABI (consumed via ctypes — no pybind11 in this image).

#include <cmath>
#include <cstdint>
#include <cstring>

namespace {

inline uint16_t f32_to_bf16(float f) {
  uint32_t bits;
  std::memcpy(&bits, &f, sizeof(bits));
  // round to nearest even
  uint32_t rounding = 0x7FFFu + ((bits >> 16) & 1u);
  return static_cast<uint16_t>((bits + rounding) >> 16);
}

inline uint16_t f32_to_f16(float f) {
  // scalar IEEE fp16 conversion, round to nearest even, NaN-preserving
  uint32_t x;
  std::memcpy(&x, &f, sizeof(x));
  uint32_t sign = (x >> 16) & 0x8000u;
  uint32_t src_exp = (x >> 23) & 0xFFu;
  uint32_t mant = x & 0x7FFFFFu;
  if (src_exp == 0xFFu) {  // inf or NaN — NaN must stay NaN
    return static_cast<uint16_t>(sign | 0x7C00u | (mant ? 0x200u : 0u));
  }
  int32_t exp = static_cast<int32_t>(src_exp) - 127 + 15;
  if (exp <= 0) {
    // fp16 subnormal (or underflow to zero), round to nearest even
    if (exp < -10) return static_cast<uint16_t>(sign);
    uint32_t full_mant = mant | 0x800000u;  // implicit leading 1
    uint32_t shift = static_cast<uint32_t>(14 - exp);
    uint32_t half_mant = full_mant >> shift;
    uint32_t round_bits = full_mant & ((1u << shift) - 1u);
    uint32_t halfway = 1u << (shift - 1);
    if (round_bits > halfway ||
        (round_bits == halfway && (half_mant & 1u))) {
      half_mant += 1;  // may become the smallest normal — correct
    }
    return static_cast<uint16_t>(sign | half_mant);
  }
  if (exp >= 31) {
    return static_cast<uint16_t>(sign | 0x7C00u);  // overflow → inf
  }
  uint32_t half = sign | (static_cast<uint32_t>(exp) << 10) | (mant >> 13);
  // round to nearest even on the 13 dropped bits
  uint32_t round_bits = mant & 0x1FFFu;
  if (round_bits > 0x1000u || (round_bits == 0x1000u && (half & 1u))) {
    half += 1;  // may carry into the exponent — that is correct rounding
  }
  return static_cast<uint16_t>(half);
}

}  // namespace

extern "C" {

// Fused Adam/AdamW step over contiguous fp32 buffers.
//   adamw:            1 → decoupled decay (update += wd*p), 0 → L2 into grad
//   bias_correction:  1 → divide moments by (1-beta^t)
//   lowp_kind:        0 none, 1 bf16, 2 fp16 — fused low-precision copy-out
// Matches deepspeed_tpu/ops/adam.py fused_adam bit-for-bit in fp32 math.
void ds_cpu_adam_step(int64_t n,
                      float* __restrict p,
                      const float* __restrict g,
                      float* __restrict m,
                      float* __restrict v,
                      float lr, float beta1, float beta2, float eps,
                      float weight_decay, int adamw, int bias_correction,
                      int64_t step,
                      uint16_t* __restrict out_lowp, int lowp_kind) {
  float c1 = 1.0f, c2 = 1.0f;
  if (bias_correction) {
    c1 = 1.0f - std::pow(beta1, static_cast<float>(step));
    c2 = 1.0f - std::pow(beta2, static_cast<float>(step));
  }
  const float one_m_b1 = 1.0f - beta1;
  const float one_m_b2 = 1.0f - beta2;
  const float inv_c1 = 1.0f / c1;
  const float inv_sqrt_c2 = 1.0f / std::sqrt(c2);

#pragma omp parallel for schedule(static)
  for (int64_t i = 0; i < n; ++i) {
    float grad = g[i];
    if (!adamw && weight_decay > 0.0f) grad += weight_decay * p[i];
    float mi = beta1 * m[i] + one_m_b1 * grad;
    float vi = beta2 * v[i] + one_m_b2 * grad * grad;
    m[i] = mi;
    v[i] = vi;
    float update = (mi * inv_c1) / (std::sqrt(vi) * inv_sqrt_c2 + eps);
    if (adamw && weight_decay > 0.0f) update += weight_decay * p[i];
    float pi = p[i] - lr * update;
    p[i] = pi;
    if (lowp_kind == 1) {
      out_lowp[i] = f32_to_bf16(pi);
    } else if (lowp_kind == 2) {
      out_lowp[i] = f32_to_f16(pi);
    }
  }
}

// Standalone fp32 → bf16 buffer conversion (upload staging).
void ds_f32_to_bf16(int64_t n, const float* __restrict src,
                    uint16_t* __restrict dst) {
#pragma omp parallel for schedule(static)
  for (int64_t i = 0; i < n; ++i) dst[i] = f32_to_bf16(src[i]);
}

int ds_cpu_ops_version() { return 1; }

}  // extern "C"
