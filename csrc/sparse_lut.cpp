// Block-sparse attention lookup-table build.
//
// TPU-native equivalent of the reference's C++ segmentation pass
// (reference: csrc/sparse_attention/utils.cpp:14 segment_blocks — it
// greedily packs the block layout into max-width LUTs for the Triton
// kernels).  The Pallas kernel here consumes a simpler row-gather LUT:
// for every (head, query-block-row), the list of active key-block columns
// padded to the global max row population.  This file is that build as a
// single O(H*nb*nb) native pass (the numpy fallback lives in
// ops/sparse_attention/sparse_self_attention.py).
//
// C ABI via ctypes, matching csrc/cpu_adam.cpp.

#include <cstdint>

extern "C" {

// Max active blocks in any (head, row) — the LUT width.
int64_t ds_lut_width(int64_t H, int64_t nb, const int32_t* layout) {
  int64_t width = 1;
  for (int64_t h = 0; h < H; ++h) {
    for (int64_t r = 0; r < nb; ++r) {
      const int32_t* row = layout + (h * nb + r) * nb;
      int64_t count = 0;
      for (int64_t c = 0; c < nb; ++c) count += (row[c] != 0);
      if (count > width) width = count;
    }
  }
  return width;
}

// Fill cols [H, nb, width] (int32, zero-padded) and valid [H, nb, width]
// (0/1 bytes) from layout [H, nb, nb].
void ds_build_lut(int64_t H, int64_t nb, const int32_t* layout,
                  int64_t width, int32_t* cols, uint8_t* valid) {
#pragma omp parallel for collapse(2)
  for (int64_t h = 0; h < H; ++h) {
    for (int64_t r = 0; r < nb; ++r) {
      const int32_t* row = layout + (h * nb + r) * nb;
      int32_t* out_c = cols + (h * nb + r) * width;
      uint8_t* out_v = valid + (h * nb + r) * width;
      int64_t k = 0;
      for (int64_t c = 0; c < nb; ++c) {
        if (row[c] != 0 && k < width) {
          out_c[k] = static_cast<int32_t>(c);
          out_v[k] = 1;
          ++k;
        }
      }
      for (; k < width; ++k) {
        out_c[k] = 0;
        out_v[k] = 0;
      }
    }
  }
}

}  // extern "C"
