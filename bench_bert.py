"""BERT-large pretrain throughput on one TPU chip — the reference's
fastest-BERT headline (BASELINE.md:8-9: 64 TFLOPS/GPU = >50% of V100 peak
at seq 128; 53 TFLOPS at seq 512, fused-kernel claims).

Runs the shipped ``BertModel`` (MLM+NSP loss, fused DeepSpeedTransformerLayer
blocks under lax.scan) at seq 128 and 512, reports samples/s, sustained
TFLOPs and fraction-of-peak.  Writes BENCH_bert.json; prints one JSON line
per sequence length.  Beating the reference here means a higher fraction of
chip peak than its >50%/V100.
"""
import json
import os
import sys
import time

import numpy as np


def _flops_per_sample(cfg, seq):
    # fwd+bwd matmul flops per token: 6*N_block + attention 12*L*d*T
    # (embedding/MLM-head gathers excluded, matching the reference's
    # TFLOPs accounting which counts GEMM work)
    d, L = cfg.hidden_size, cfg.num_hidden_layers
    inter = cfg.intermediate_size
    per_layer = 4 * d * d + 2 * d * inter      # qkv+proj + ffn weights
    n_block = L * per_layer + cfg.vocab_size * d  # + tied MLM decoder
    return (6 * n_block + 12 * L * d * seq) * seq


def main():
    import jax

    sys.path.insert(0, ".")
    from bench import _resolve_peak, _mark, guarded_devices
    from deepspeed_tpu.config import DeepSpeedConfig
    from deepspeed_tpu.models.bert import BERT_LARGE, BertModel
    from deepspeed_tpu.parallel import build_mesh
    from deepspeed_tpu.runtime.engine import DeepSpeedEngine

    devices = guarded_devices()
    on_tpu = devices[0].platform != "cpu"
    peak = _resolve_peak(devices[0]) if on_tpu else 0.0

    import dataclasses
    cases = ([(128, 64), (512, 16)] if on_tpu else [(64, 4)])
    # BENCH_BERT_BATCH="128:96,512:24" overrides per-seq batch for
    # tuning experiments in a hardware window (no remat -> activations
    # scale linearly with batch; headroom depends on what else resides)
    override = os.environ.get("BENCH_BERT_BATCH", "")
    if override and on_tpu:
        ovr = dict(tuple(map(int, pair.split(":")))
                   for pair in override.split(","))
        unknown = set(ovr) - {seq for seq, _ in cases}
        if unknown:
            # a typo'd seq key must fail loudly, not silently measure
            # the default batch under the operator's label
            raise ValueError(
                f"BENCH_BERT_BATCH keys {sorted(unknown)} match no "
                f"benched seq ({sorted(s for s, _ in cases)})")
        cases = [(seq, ovr.get(seq, b)) for seq, b in cases]
    cfg_model = BERT_LARGE if on_tpu else dataclasses.replace(
        BERT_LARGE, num_hidden_layers=2, hidden_size=128,
        num_attention_heads=4, intermediate_size=512, vocab_size=1024)
    # Headline = the perf configuration, matching how the reference
    # benches its fused-kernel BERT (no activation checkpointing;
    # docs/_posts/2020-05-28-fastest-bert-training.md there).  remat
    # recomputes the forward (executed flops 8PT vs the 6PT counted) and
    # lax.scan blocks cross-layer XLA optimization — both are memory
    # knobs, not throughput ones.
    cfg_model = dataclasses.replace(cfg_model, remat=None,
                                    scan_layers=False)

    results = []
    for seq, batch in cases:
        ds_cfg = DeepSpeedConfig({
            "train_micro_batch_size_per_gpu": batch,
            "gradient_accumulation_steps": 1,
            "steps_per_print": 10 ** 9,
            "bf16": {"enabled": True},
            "optimizer": {"type": "Adam", "params": {"lr": 1e-4}},
            "zero_optimization": {"stage": 0},
        }, world_size=1)
        _mark(f"bert-large seq{seq}: constructing engine")
        engine = DeepSpeedEngine(BertModel(cfg_model), ds_cfg,
                                 mesh=build_mesh(devices=devices[:1]))
        rng = np.random.default_rng(0)
        ids = rng.integers(0, cfg_model.vocab_size, (batch, seq),
                           dtype=np.int32)
        labels = np.where(rng.random((batch, seq)) < 0.15, ids, -100
                          ).astype(np.int32)
        batch_dict = {
            "input_ids": ids,
            "masked_lm_labels": labels,
            "next_sentence_label": rng.integers(0, 2, (batch,),
                                                dtype=np.int32),
        }
        from bench import _device_resident
        batch_dict = _device_resident(engine, batch_dict)
        _mark(f"bert-large seq{seq}: compiling + warmup")
        np.asarray(engine.train_batch(batch_dict))
        steps = 10 if on_tpu else 2
        t0 = time.perf_counter()
        for _ in range(steps):
            loss = engine.train_batch(batch_dict)
        loss = float(np.asarray(loss))
        dt = (time.perf_counter() - t0) / steps
        assert np.isfinite(loss), loss
        sps = batch / dt
        tflops = sps * _flops_per_sample(cfg_model, seq) / 1e12
        frac = tflops * 1e12 / peak if peak else 0.0
        _mark(f"bert-large seq{seq}: {sps:.1f} samples/s "
              f"{tflops:.1f} TFLOPs ({frac:.1%} of peak)")
        rec = {
            "metric": f"bert_large_seq{seq}_samples_per_sec",
            "value": round(sps, 1),
            "unit": "samples/s",
            "tflops": round(tflops, 1),
            "fraction_of_peak": round(frac, 4),
            # reference fraction-of-peak is >0.50 on V100 (BASELINE.md:8)
            "vs_baseline": round(frac / 0.50, 4) if peak else 0.0,
        }
        results.append(rec)
        print(json.dumps(rec))
        del engine

    if on_tpu:
        with open("BENCH_bert.json", "w") as f:
            json.dump(results, f, indent=1)


if __name__ == "__main__":
    main()
