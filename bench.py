"""Headline benchmark: GPT-2 1.5B training throughput on one TPU chip.

The BASELINE.json north star is tokens/sec/chip for GPT-2 1.5B with
ZeRO-2 semantics at >=45% MFU.  A 1.5B fp32 master + Adam moments
(~18.7 GB) cannot live in one chip's HBM, so the single-chip 1.5B run
uses the ZeRO-Offload XLA tier (fp32 master + moments in pinned host
memory, reference ZeRO-Offload's exact resource trade: host RAM buys
trainable params/chip) with block rematerialization.

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline"}.
vs_baseline = measured MFU / 0.45 (>=1 means the target is met).

Environment knobs:
  BENCH_SMALL=1   force the GPT-2 124M single-chip path (fast; also the
                  automatic fallback if the 1.5B path fails)
"""
import json
import os
import signal
import sys
import time
import traceback

import numpy as np

_T0 = time.perf_counter()


def _mark(msg):
    """Timestamped progress marker on stderr — the driver's log tail shows
    where time went if a phase is slow (compile, init, transfers)."""
    print(f"[bench {time.perf_counter() - _T0:7.1f}s] {msg}",
          file=sys.stderr, flush=True)


class _Watchdog:
    """SIGALRM deadline around the 1.5B attempt so a pathologically slow
    phase degrades to the 124M fallback instead of eating the driver's
    whole time budget.  Limitation: the handler fires between Python
    bytecodes, so a hang fully inside one native call (a wedged tunnel
    RPC) is not interruptible — but then the fallback's device calls would
    hang on the same dead tunnel anyway, which is why this is in-process
    rather than a kill-subprocess design (killing a TPU client mid-step
    can wedge the tunnel for the fallback too)."""

    def __init__(self, seconds: int):
        self.seconds = seconds

    def __enter__(self):
        def on_alarm(signum, frame):
            raise TimeoutError(f"bench watchdog fired after {self.seconds}s")
        self._prev = signal.signal(signal.SIGALRM, on_alarm)
        signal.alarm(self.seconds)
        return self

    def __exit__(self, *exc):
        signal.alarm(0)
        signal.signal(signal.SIGALRM, self._prev)
        return False

# Published bf16 peak FLOPs per chip by device kind.  Resolution must be
# loud: an assumed peak silently misstates MFU (round-1 verdict).
_PEAKS = {
    "TPU v4": 275e12,
    "TPU v5 lite": 197e12,   # v5e
    "TPU v5e": 197e12,
    "TPU v5": 459e12,        # v5p
    "TPU v5p": 459e12,
    "TPU v6 lite": 918e12,   # v6e (Trillium)
    "TPU v6e": 918e12,
}


def _chip_peak_bf16_flops(device) -> float:
    kind = getattr(device, "device_kind", "")
    for name, peak in sorted(_PEAKS.items(), key=lambda kv: -len(kv[0])):
        if kind.lower().startswith(name.lower()):
            return peak
    raise RuntimeError(
        f"unknown device_kind {kind!r}: refusing to assume a peak-FLOPs "
        f"figure (MFU would be meaningless). Known kinds: "
        f"{sorted(_PEAKS)}. Set BENCH_PEAK_FLOPS to override.")


def _resolve_peak(device) -> float:
    env = os.environ.get("BENCH_PEAK_FLOPS")
    if env:
        return float(env)
    return _chip_peak_bf16_flops(device)


def _flops_per_token(cfg, seq):
    # fwd+bwd matmul flops: 6N + causal attention 12*L*d*T.  Remat
    # recompute is NOT counted — MFU measures useful flops only.
    return 6 * cfg.num_params + 12 * cfg.n_layer * cfg.d_model * seq


def calibrated_time(fn, iters=None, min_window_s=None):
    """Time fn() with an iteration count calibrated so the measured window
    dwarfs dispatch/tunnel jitter — 20 iters of a ~35us kernel measures
    noise, not the kernel (the round-5 first-window flash numbers exceeded
    chip peak because of exactly this).  On CPU smoke runs the window is
    skipped (accuracy there is irrelevant and calibration would inflate
    cheap cases to thousands of iterations).  Shared by bench_flash /
    bench_sparse."""
    import jax
    on_tpu = jax.devices()[0].platform != "cpu"
    if iters is None:
        iters = 10 if on_tpu else 2
    if min_window_s is None:
        min_window_s = 0.2 if on_tpu else 0.0
    out = fn()
    jax.block_until_ready(out)
    t0 = time.perf_counter()
    for _ in range(iters):
        out = fn()
    jax.block_until_ready(out)
    dt = time.perf_counter() - t0
    while dt < min_window_s and iters < 1 << 16:
        iters = int(iters * max(2.0, min_window_s / max(dt, 1e-6) * 1.3))
        t0 = time.perf_counter()
        for _ in range(iters):
            out = fn()
        jax.block_until_ready(out)
        dt = time.perf_counter() - t0
    return dt / iters


def _device_resident(engine, batch):
    """Upload a repeating batch ONCE: _shard_batch passes device arrays
    through, so steps pay zero H2D (per-step uploads ride the same
    stall-prone tunnel as everything else on this platform).  Single-
    process only — multi-host _shard_batch assembles from process-local
    numpy, so there we leave the batch alone."""
    import jax
    from jax.sharding import NamedSharding, PartitionSpec as P
    if jax.process_count() > 1:
        return batch
    return jax.device_put(batch, NamedSharding(engine.mesh, P()))


def _run(engine, tokens, steps, warmup=1):
    tokens = _device_resident(engine, tokens)
    for _ in range(warmup):
        np.asarray(engine.train_batch(tokens))
    t0 = time.perf_counter()
    loss = None
    for _ in range(steps):
        loss = engine.train_batch(tokens)
    loss = float(np.asarray(loss))
    dt = (time.perf_counter() - t0) / steps
    assert np.isfinite(loss), f"non-finite loss {loss}"
    if os.environ.get("BENCH_PROFILE") == "1":
        # one traced step AFTER measurement (tracing skews timing):
        # the xplane shows host-section vs device vs transfer time —
        # the data that decides whether delayed-param-update is needed
        try:
            import jax
            _mark("profiling one step -> bench_trace/")
            with jax.profiler.trace("bench_trace"):
                np.asarray(engine.train_batch(tokens))
            _mark("profile captured")
        except Exception as e:  # profiling must never kill the bench
            _mark(f"profile failed: {e}")
    return dt, loss


def _15b_knobs():
    """Tuning knobs, validated EAGERLY (main calls this before entering the
    watchdog-guarded attempt): a typo'd env var must fail loudly, not get
    swallowed into a silent 124M fallback.  Larger ga amortizes the
    per-step host<->HBM master/moment traffic over more compute."""
    micro = int(os.environ.get("BENCH_15B_MICRO", "4"))
    # ga=32 → 128 seqs × 1024 = 131k tokens per optimizer step, ~1/4 of
    # GPT-2 1.5B's real 0.5M-token batches — a legitimate config that
    # amortizes the once-per-step host master/moment traffic 2× better
    # than the previous default of 16.
    ga = int(os.environ.get("BENCH_15B_GA", "32"))
    steps = int(os.environ.get("BENCH_15B_STEPS", "2"))
    deadline = int(os.environ.get("BENCH_15B_TIMEOUT", "1500"))
    if micro < 1 or ga < 1 or steps < 1 or deadline < 1:
        raise ValueError(
            f"bad BENCH_15B knobs: {micro=} {ga=} {steps=} {deadline=}")
    return micro, ga, steps, deadline


def _bench_15b(jax, impl: str = "xla"):
    """North star: GPT-2 1.5B, ZeRO-2 + host offload, one chip.

    ``impl``: 'xla_split' — pinned_host master/moments with the optimizer
    update as one compiled program per piece (program boundaries bound
    HBM liveness; the fused update program OOM'd at compile on the AOT
    path, round-5 window); 'xla' — same residency with ONE fused
    host-compute update program (fastest when the compiler honors host
    placement end to end); 'host' — numpy staging + native C++ Adam
    (plan B: plain jit step, no host-compute sections)."""
    import jax.numpy as jnp  # noqa: F401
    from deepspeed_tpu.models import GPT2Config, GPT2Model
    from deepspeed_tpu.parallel import build_mesh
    from deepspeed_tpu.config import DeepSpeedConfig
    from deepspeed_tpu.runtime.engine import DeepSpeedEngine

    micro, ga, steps, _ = _15b_knobs()
    # OOM insurance: BENCH_15B_CHUNKS=K bounds device grad bytes to the
    # largest of K groups (offload_grad_chunks capacity mode) at K
    # forward recomputes — a fallback knob, not the default
    chunks = int(os.environ.get("BENCH_15B_CHUNKS", "0"))
    # BENCH_15B_DPU=1 overlaps the host Adam with the next step's
    # compute (one-step param staleness) — flip on if the measured gap
    # to 45% MFU matches the host-section time
    dpu = os.environ.get("BENCH_15B_DPU", "0") == "1"
    # BENCH_15B_STREAM=1: ZeRO-Infinity-style param streaming (host-
    # resident stacked block params, one layer fetched per scan tick) —
    # the deepest OOM fallback, and the capacity mode's throughput
    # number when measured deliberately (xla tier only)
    split = impl.startswith("xla_split")
    impl_cfg = "xla" if split else impl
    # 'xla_split_dpu': split update + delayed parameter update — the
    # per-piece host Adam overlaps the next step's grad program (the
    # reference's peak-throughput offload mode; ~10-15% of step time
    # at 1.5B if the update runs serially)
    dpu = dpu or impl == "xla_split_dpu"
    # 'xla_split4': split update + 4 gradient chunks — the fallback when
    # the single grad program's liveness (bf16 params + grads + packed
    # pieces + activations ≈ 14 GB at 1.5B) is still too tight.  With
    # BENCH_15B_CHUNKS pinned by the operator the leg is redundant (the
    # xla_split leg already ran that chunk count): fail loudly so the
    # chain logs it and moves on instead of re-running an identical or
    # silently-different program.
    if impl == "xla_split4":
        if os.environ.get("BENCH_15B_CHUNKS") is not None:
            raise RuntimeError(
                "BENCH_15B_CHUNKS pins the chunk count for every leg; "
                "the xla_split4 leg is redundant under it — set "
                "BENCH_15B_IMPL explicitly instead")
        chunks = 4
    stream = (os.environ.get("BENCH_15B_STREAM", "0") == "1"
              and impl_cfg == "xla")
    cfg_model = GPT2Config(d_model=1600, n_layer=48, n_head=25,
                           vocab_size=50257, n_positions=1024,
                           remat="block", scan_layers=True,
                           stream_scan=stream)
    seq = 1024
    mesh = build_mesh(devices=jax.devices()[:1])
    ds_cfg = DeepSpeedConfig({
        "train_micro_batch_size_per_gpu": micro,
        "gradient_accumulation_steps": ga,
        "steps_per_print": 10 ** 9,
        "bf16": {"enabled": True},
        "optimizer": {"type": "Adam", "params": {"lr": 1e-4}},
        "zero_optimization": dict(
            {"stage": 2, "cpu_offload": True, "offload_impl": impl_cfg},
            **({"offload_grad_chunks": chunks}
               if impl_cfg == "xla" and chunks > 1 else {}),
            **({"param_streaming": True} if stream else {}),
            **({"offload_split_update": True} if split else {}),
            **({"delayed_param_update": True} if dpu else {})),
    }, world_size=1)
    if impl == "host":
        # strict probe semantics for the bench: a slow-but-working link
        # must fall through to the next tier, not eat the measurement
        # window at minutes/step (library default is warn-and-proceed)
        os.environ.setdefault("DS_OFFLOAD_SLOW_LINK", "error")
    _mark(f"1.5B[{impl}]: constructing engine (param init + host staging)")
    engine = DeepSpeedEngine(GPT2Model(cfg_model), ds_cfg, mesh=mesh)
    _mark(f"1.5B[{impl}]: engine ready; compiling + first step")
    tokens = np.random.default_rng(0).integers(
        0, cfg_model.vocab_size, (micro * ga, seq + 1), dtype=np.int32)
    dt, _ = _run(engine, tokens, steps)
    _mark(f"1.5B[{impl}]: measured {dt:.2f}s/step")
    tokens_per_sec = micro * ga * seq / dt
    return cfg_model, seq, tokens_per_sec, f"gpt2_1p5b_zero2_offload_{impl}"


def _bench_124m(jax):
    """Fallback / BENCH_SMALL path (the round-1 bench, known-good)."""
    from deepspeed_tpu.models import GPT2Config, GPT2Model
    from deepspeed_tpu.parallel import build_mesh
    from deepspeed_tpu.config import DeepSpeedConfig
    from deepspeed_tpu.runtime.engine import DeepSpeedEngine

    cfg_model = GPT2Config(d_model=768, n_layer=12, n_head=12,
                           vocab_size=50257, n_positions=1024,
                           remat=None, scan_layers=False)
    batch, seq, steps = 16, 1024, 10
    mesh = build_mesh(devices=jax.devices()[:1])
    ds_cfg = DeepSpeedConfig({
        "train_micro_batch_size_per_gpu": batch,
        "gradient_accumulation_steps": 1,
        "steps_per_print": 10 ** 9,
        "bf16": {"enabled": True},
        "optimizer": {"type": "Adam", "params": {"lr": 1e-4}},
        "zero_optimization": {"stage": 0},
    }, world_size=1)
    _mark("124M: constructing engine")
    engine = DeepSpeedEngine(GPT2Model(cfg_model), ds_cfg, mesh=mesh)
    _mark("124M: engine ready; compiling + warmup")
    tokens = np.random.default_rng(0).integers(
        0, cfg_model.vocab_size, (batch, seq + 1), dtype=np.int32)
    dt, _ = _run(engine, tokens, steps, warmup=2)
    _mark(f"124M: measured {dt:.3f}s/step")
    tokens_per_sec = batch * seq / dt
    return cfg_model, seq, tokens_per_sec, "gpt2_124m_zero0"


def bench_offload_pipeline(jax, pipeline_on: bool, steps: int = None):
    """A/B one leg of the streaming offload update pipeline (host tier):
    per-stage step-time breakdown (d2h / cpu_adam / h2d / hidden) plus
    measured step wall time.  The breakdown comes from the engine's
    ``last_offload_breakdown`` host timestamps — d2h is the prefetch
    puller's transfer time (already overlapped with the Adam), h2d the
    per-leaf upload time, hidden the part of h2d that ran under the Adam
    window (the pipeline's win; 0 by construction on the serial leg).

    Size is platform-scaled: tiny on CPU (a smoke the tier-1 suite runs
    with an injected slow-transfer delay to prove overlap > 0), mid-size
    on TPU via BENCH_PIPE_* knobs so one healthy tunnel window banks the
    A/B number in a single run."""
    from deepspeed_tpu.models import GPT2Config, GPT2Model
    from deepspeed_tpu.parallel import build_mesh
    from deepspeed_tpu.config import DeepSpeedConfig
    from deepspeed_tpu.runtime.engine import DeepSpeedEngine

    on_tpu = jax.devices()[0].platform != "cpu"
    if on_tpu:
        d_model = int(os.environ.get("BENCH_PIPE_D_MODEL", "1024"))
        n_layer = int(os.environ.get("BENCH_PIPE_LAYERS", "12"))
        micro = int(os.environ.get("BENCH_PIPE_MICRO", "4"))
        seq, vocab, remat = 1024, 50257, "block"
        steps = steps or int(os.environ.get("BENCH_PIPE_STEPS", "3"))
    else:
        d_model, n_layer, micro = 64, 2, 2
        seq, vocab, remat = 64, 256, None
        steps = steps or 2
    cfg_model = GPT2Config(d_model=d_model, n_layer=n_layer,
                           n_head=max(2, d_model // 64), vocab_size=vocab,
                           n_positions=seq, remat=remat)
    mesh = build_mesh(devices=jax.devices()[:1])
    ds_cfg = DeepSpeedConfig({
        "train_micro_batch_size_per_gpu": micro,
        "gradient_accumulation_steps": 1,
        "steps_per_print": 10 ** 9,
        "bf16": {"enabled": True},
        "optimizer": {"type": "Adam", "params": {"lr": 1e-4}},
        "zero_optimization": {"stage": 2, "cpu_offload": True,
                              "offload_impl": "host",
                              "offload_pipeline": pipeline_on},
    }, world_size=1)
    _mark(f"offload-pipeline[{'on' if pipeline_on else 'off'}]: "
          "constructing engine")
    engine = DeepSpeedEngine(GPT2Model(cfg_model), ds_cfg, mesh=mesh)
    tokens = np.random.default_rng(0).integers(
        0, vocab, (micro, seq + 1), dtype=np.int32)
    tokens = _device_resident(engine, tokens)
    np.asarray(engine.train_batch(tokens))  # warmup/compile
    acc = {"d2h_s": 0.0, "cpu_adam_s": 0.0, "h2d_s": 0.0,
           "h2d_hidden_s": 0.0, "h2d_tail_s": 0.0, "overlap_ratio": 0.0}
    t0 = time.perf_counter()
    for _ in range(steps):
        loss = float(np.asarray(engine.train_batch(tokens)))
        bd = engine.last_offload_breakdown
        for k in acc:
            acc[k] += bd[k]
    dt = (time.perf_counter() - t0) / steps
    assert np.isfinite(loss), f"non-finite loss {loss}"
    out = {k: round(v / steps, 6) for k, v in acc.items()}
    out["step_s"] = round(dt, 6)
    out["pipeline"] = "on" if pipeline_on else "off"
    _mark(f"offload-pipeline[{out['pipeline']}]: {dt:.3f}s/step, "
          f"overlap {out['overlap_ratio'] * 100:.0f}%")
    return out


def _offload_pipeline_ab(jax, mode: str):
    """``--offload-pipeline={on,off,ab}``: run the requested leg(s) and
    print ONE JSON line with the per-stage breakdown(s)."""
    legs = {"on": [True], "off": [False], "ab": [True, False]}[mode]
    results = [bench_offload_pipeline(jax, leg) for leg in legs]
    rec = {"metric": "offload_pipeline_step_breakdown",
           "unit": "s/step",
           "legs": results}
    if len(results) == 2:
        off_t, on_t = results[1]["step_s"], results[0]["step_s"]
        rec["speedup"] = round(off_t / on_t, 4) if on_t > 0 else 0.0
    try:
        with open("BENCH_offload_pipeline.json", "w") as f:
            json.dump(rec, f, indent=1)
    except OSError:
        pass
    print(json.dumps(rec), flush=True)


def bench_offload_tier(jax, tier: str, steps: int = None,
                       disk_delay_s: float = None):
    """One leg of the offload-tier A/B (host RAM vs ZeRO-Infinity disk
    tier, runtime/disk_offload.py): measured step wall time, final
    loss, and — on the disk leg — the state-I/O overlap breakdown from
    the engine's host timestamps, with ``DS_STAGE_DELAY_S`` injecting
    per-leaf disk latency so a CPU run proves the three-tier pipeline
    hides real I/O time under the C++ Adam (the repo's established
    injected-delay overlap idiom).  Also records the capacity
    accounting: ``total_state_bytes`` (master+moments on disk) vs
    ``peak_resident_bytes`` (the io_depth-bounded host window).

    Size is platform-scaled like ``bench_offload_pipeline``: tiny on
    CPU (the tier-1 smoke), mid-size on TPU via BENCH_PIPE_* knobs."""
    import shutil
    import tempfile

    from deepspeed_tpu.models import GPT2Config, GPT2Model
    from deepspeed_tpu.parallel import build_mesh
    from deepspeed_tpu.config import DeepSpeedConfig
    from deepspeed_tpu.runtime.engine import DeepSpeedEngine

    on_tpu = jax.devices()[0].platform != "cpu"
    if on_tpu:
        d_model = int(os.environ.get("BENCH_PIPE_D_MODEL", "1024"))
        n_layer = int(os.environ.get("BENCH_PIPE_LAYERS", "12"))
        micro = int(os.environ.get("BENCH_PIPE_MICRO", "4"))
        seq, vocab, remat = 1024, 50257, "block"
        steps = steps or int(os.environ.get("BENCH_PIPE_STEPS", "3"))
    else:
        d_model, n_layer, micro = 64, 2, 2
        seq, vocab, remat = 64, 256, None
        steps = steps or 2
    if disk_delay_s is None:
        disk_delay_s = float(os.environ.get("BENCH_DISK_DELAY_S",
                                            "0.003"))
    cfg_model = GPT2Config(d_model=d_model, n_layer=n_layer,
                           n_head=max(2, d_model // 64), vocab_size=vocab,
                           n_positions=seq, remat=remat)
    mesh = build_mesh(devices=jax.devices()[:1])
    ds = {
        "train_micro_batch_size_per_gpu": micro,
        "gradient_accumulation_steps": 1,
        "steps_per_print": 10 ** 9,
        "bf16": {"enabled": True},
        "optimizer": {"type": "Adam", "params": {"lr": 1e-4}},
        "zero_optimization": {"stage": 2, "cpu_offload": True,
                              "offload_impl": "host"},
    }
    disk_dir = None
    prev_delay = os.environ.get("DS_STAGE_DELAY_S")
    try:
        if tier == "disk":
            disk_dir = tempfile.mkdtemp(prefix="ds_bench_disk_")
            ds["offload"] = {"tier": "disk", "disk_dir": disk_dir,
                             "io_depth": 2}
            if disk_delay_s > 0:
                # injected per-leaf disk latency: the overlap claim is
                # then about REAL I/O time, not 9p-filesystem noise
                os.environ["DS_STAGE_DELAY_S"] = (
                    f"disk_read:{disk_delay_s},"
                    f"disk_write:{disk_delay_s}")
        _mark(f"offload-tier[{tier}]: constructing engine")
        engine = DeepSpeedEngine(GPT2Model(cfg_model),
                                 DeepSpeedConfig(ds, world_size=1),
                                 mesh=mesh)
        tokens = np.random.default_rng(0).integers(
            0, vocab, (micro, seq + 1), dtype=np.int32)
        tokens = _device_resident(engine, tokens)
        np.asarray(engine.train_batch(tokens))  # warmup/compile
        t0 = time.perf_counter()
        acc = {"disk_read_s": 0.0, "disk_write_s": 0.0,
               "disk_hidden_s": 0.0, "disk_overlap_ratio": 0.0}
        for _ in range(steps):
            loss = float(np.asarray(engine.train_batch(tokens)))
            bd = engine.last_offload_breakdown
            for k in acc:
                acc[k] += bd.get(k, 0.0)
        dt = (time.perf_counter() - t0) / steps
        assert np.isfinite(loss), f"non-finite loss {loss}"
        out = {"tier": tier, "step_s": round(dt, 6),
               "loss": loss}
        if tier == "disk":
            out.update({k: round(v / steps, 6) for k, v in acc.items()})
            opt = engine._host_opt
            out["total_state_bytes"] = int(opt.total_state_bytes)
            out["peak_resident_bytes"] = int(opt.peak_resident_bytes)
        engine.close()
        _mark(f"offload-tier[{tier}]: {dt:.3f}s/step"
              + (f", disk overlap "
                 f"{out.get('disk_overlap_ratio', 0) * 100:.0f}%"
                 if tier == "disk" else ""))
        return out
    finally:
        if prev_delay is None:
            os.environ.pop("DS_STAGE_DELAY_S", None)
        else:
            os.environ["DS_STAGE_DELAY_S"] = prev_delay
        if disk_dir is not None:
            shutil.rmtree(disk_dir, ignore_errors=True)


def _offload_tier_ab(jax, mode: str):
    """``--offload-tier={host,disk,ab}``: run the requested leg(s),
    print ONE JSON line, and (ab) pin the headline — the disk leg's
    measured state-I/O overlap ratio under injected latency — into
    ``BENCH_offload_disk.json`` for the benchgate.  The ab legs also
    assert the correctness bar: disk-tier loss BITWISE == host-tier."""
    legs = {"host": ["host"], "disk": ["disk"],
            "ab": ["disk", "host"]}[mode]
    results = [bench_offload_tier(jax, leg) for leg in legs]
    rec = {"metric": "offload_disk_overlap_ratio",
           "unit": "ratio",
           "value": next((r.get("disk_overlap_ratio", 0.0)
                          for r in results if r["tier"] == "disk"), 0.0),
           "legs": results}
    if len(results) == 2:
        losses = {r["tier"]: r["loss"] for r in results}
        rec["loss_bitwise_equal"] = losses["disk"] == losses["host"]
        assert rec["loss_bitwise_equal"], (
            f"disk-tier loss diverged from host tier: {losses}")
        # only the full A/B pins the benchgate artifact: a single-leg
        # host run has no disk overlap and would clobber the committed
        # headline with 0.0 (read as a regression)
        try:
            with open("BENCH_offload_disk.json", "w") as f:
                json.dump(rec, f, indent=1)
        except OSError:
            pass
    print(json.dumps(rec), flush=True)


def bench_prefetch(jax, prefetch_on: bool, steps: int = None,
                   collate_delay_s: float = None):
    """A/B one leg of the async input pipeline: the same seeded
    dataloader (with a deliberately slow collate emulating real
    tokenize/augment cost — both legs pay it) feeds the engine with
    prefetch ON (collate + H2D placement on the daemon worker, hidden
    under the previous step) vs OFF (inline on the step path).  Reports
    measured step wall time plus the pipeline's own numbers
    (``prefetch_wait_s`` per step, ``hit_ratio``) from the engine's
    prefetcher stats.

    Size is platform-scaled like ``bench_offload_pipeline``: tiny on
    CPU (the tier-1 smoke injects BENCH_PREFETCH_COLLATE_S to prove
    hiding), mid-size on TPU via BENCH_PREFETCH_* knobs."""
    from deepspeed_tpu.models import GPT2Config, GPT2Model
    from deepspeed_tpu.parallel import build_mesh
    from deepspeed_tpu.config import DeepSpeedConfig
    from deepspeed_tpu.runtime.engine import DeepSpeedEngine
    from deepspeed_tpu.runtime.dataloader import RepeatingLoader

    on_tpu = jax.devices()[0].platform != "cpu"
    if on_tpu:
        d_model = int(os.environ.get("BENCH_PREFETCH_D_MODEL", "768"))
        n_layer = int(os.environ.get("BENCH_PREFETCH_LAYERS", "8"))
        micro = int(os.environ.get("BENCH_PREFETCH_MICRO", "8"))
        seq, vocab = 1024, 50257
        steps = steps or int(os.environ.get("BENCH_PREFETCH_STEPS", "5"))
    else:
        d_model, n_layer, micro = 64, 2, 2
        seq, vocab = 64, 256
        steps = steps or 3
    if collate_delay_s is None:
        collate_delay_s = float(
            os.environ.get("BENCH_PREFETCH_COLLATE_S",
                           "0" if on_tpu else "0.02"))

    def slow_collate(samples):
        # emulated host-side collate cost (tokenize/augment/pad) — paid
        # by BOTH legs; the on leg hides it on the worker
        if collate_delay_s > 0:
            time.sleep(collate_delay_s)
        return np.stack([np.asarray(s) for s in samples])

    cfg_model = GPT2Config(d_model=d_model, n_layer=n_layer,
                           n_head=max(2, d_model // 64), vocab_size=vocab,
                           n_positions=seq, remat=None)
    mesh = build_mesh(devices=jax.devices()[:1])
    ds_cfg = DeepSpeedConfig({
        "train_micro_batch_size_per_gpu": micro,
        "gradient_accumulation_steps": 1,
        "steps_per_print": 10 ** 9,
        "bf16": {"enabled": True},
        "optimizer": {"type": "Adam", "params": {"lr": 1e-4}},
        "data_prefetch": {"enabled": prefetch_on, "depth": 2},
    }, world_size=1)
    rng = np.random.default_rng(0)
    dataset = [rng.integers(0, vocab, (seq + 1,), dtype=np.int32)
               for _ in range(micro * 4)]
    _mark(f"prefetch[{'on' if prefetch_on else 'off'}]: "
          "constructing engine")
    engine = DeepSpeedEngine(GPT2Model(cfg_model), ds_cfg, mesh=mesh,
                             training_data=dataset,
                             collate_fn=slow_collate)
    # finite dataset, repeated: the A/B must measure steady state, not
    # epoch boundaries
    engine.training_dataloader = RepeatingLoader(engine.training_dataloader)
    np.asarray(engine.train_batch())  # warmup/compile
    pf = engine._train_prefetcher
    s0 = pf.stats() if pf is not None else None
    t0 = time.perf_counter()
    for _ in range(steps):
        loss = float(np.asarray(engine.train_batch()))
    dt = (time.perf_counter() - t0) / steps
    assert np.isfinite(loss), f"non-finite loss {loss}"
    out = {"prefetch": "on" if prefetch_on else "off",
           "step_s": round(dt, 6),
           "collate_delay_s": collate_delay_s}
    if pf is not None:
        s1 = pf.stats()
        n = max(s1["consumed"] - s0["consumed"], 1)
        out["prefetch_wait_s"] = round(
            (s1["wait_s"] - s0["wait_s"]) / n, 6)
        hm = (s1["hits"] - s0["hits"]) + (s1["misses"] - s0["misses"])
        out["hit_ratio"] = round(
            (s1["hits"] - s0["hits"]) / hm, 4) if hm else 0.0
    engine.close()
    _mark(f"prefetch[{out['prefetch']}]: {dt:.3f}s/step"
          + (f", wait {out['prefetch_wait_s']:.3f}s"
             if "prefetch_wait_s" in out else ""))
    return out


def _prefetch_ab(jax, mode: str):
    """``--prefetch={on,off,ab}``: run the requested leg(s) and print
    ONE JSON line; the A/B also records the off/on speedup."""
    legs = {"on": [True], "off": [False], "ab": [True, False]}[mode]
    results = [bench_prefetch(jax, leg) for leg in legs]
    rec = {"metric": "input_prefetch_step_breakdown",
           "unit": "s/step",
           "legs": results}
    if len(results) == 2:
        off_t, on_t = results[1]["step_s"], results[0]["step_s"]
        rec["speedup"] = round(off_t / on_t, 4) if on_t > 0 else 0.0
    try:
        with open("BENCH_prefetch.json", "w") as f:
            json.dump(rec, f, indent=1)
    except OSError:
        pass
    print(json.dumps(rec), flush=True)


def bench_ckpt(jax, use_async: bool, steps: int = None,
               interval: int = None):
    """A/B one leg of the fault-tolerant checkpoint pipeline: the same
    training loop with a checkpoint interval active, saving sync vs
    async.  Reports steps/sec, the exposed per-save stall (the stall the
    step loop actually paid — async pays only the snapshot D2H), and the
    background write time the async writer hid, PROVEN from tracer
    timestamps: hidden = how far each ``checkpoint/write`` span ran past
    its originating ``checkpoint/save`` span's end.

    Size is platform-scaled like the other A/B benches: tiny on CPU with
    ``DS_CKPT_DELAY_S`` injected write latency (the tier-1 smoke's
    overlap proof), mid-size on TPU via BENCH_CKPT_* knobs."""
    import tempfile
    from deepspeed_tpu.models import GPT2Config, GPT2Model
    from deepspeed_tpu.parallel import build_mesh
    from deepspeed_tpu.config import DeepSpeedConfig
    from deepspeed_tpu.runtime.engine import DeepSpeedEngine

    on_tpu = jax.devices()[0].platform != "cpu"
    if on_tpu:
        d_model = int(os.environ.get("BENCH_CKPT_D_MODEL", "1024"))
        n_layer = int(os.environ.get("BENCH_CKPT_LAYERS", "12"))
        micro = int(os.environ.get("BENCH_CKPT_MICRO", "4"))
        seq, vocab = 1024, 50257
        steps = steps or int(os.environ.get("BENCH_CKPT_STEPS", "8"))
        interval = interval or int(os.environ.get("BENCH_CKPT_INTERVAL",
                                                  "4"))
    else:
        d_model, n_layer, micro = 64, 2, 2
        seq, vocab = 64, 256
        steps = steps or 6
        interval = interval or 2
        # injected write latency: the thing the async leg hides (both
        # legs pay it; operators can override/disable)
        os.environ.setdefault("DS_CKPT_DELAY_S", "0.15")
    cfg_model = GPT2Config(d_model=d_model, n_layer=n_layer,
                           n_head=max(2, d_model // 64), vocab_size=vocab,
                           n_positions=seq, remat=None)
    mesh = build_mesh(devices=jax.devices()[:1])
    save_dir = tempfile.mkdtemp(prefix="bench_ckpt_")
    tel_dir = tempfile.mkdtemp(prefix="bench_ckpt_tel_")
    ds_cfg = DeepSpeedConfig({
        "train_micro_batch_size_per_gpu": micro,
        "gradient_accumulation_steps": 1,
        "steps_per_print": 10 ** 9,
        "bf16": {"enabled": True},
        "optimizer": {"type": "Adam", "params": {"lr": 1e-4}},
        "telemetry": {"enabled": True, "output_path": tel_dir,
                      "compile_events": False, "memory": False},
        "checkpoint": {"keep_last_n": 2},
    }, world_size=1)
    mode = "async" if use_async else "sync"
    _mark(f"ckpt[{mode}]: constructing engine")
    engine = DeepSpeedEngine(GPT2Model(cfg_model), ds_cfg, mesh=mesh)
    tokens = np.random.default_rng(0).integers(
        0, vocab, (micro, seq + 1), dtype=np.int32)
    tokens = _device_resident(engine, tokens)
    np.asarray(engine.train_batch(tokens))  # warmup/compile
    save_stall = 0.0
    saves = 0
    t0 = time.perf_counter()
    for i in range(steps):
        loss = engine.train_batch(tokens)
        if (i + 1) % interval == 0:
            s0 = time.perf_counter()
            engine.save_checkpoint(save_dir, async_write=use_async)
            save_stall += time.perf_counter() - s0
            saves += 1
    loss = float(np.asarray(loss))
    dt = (time.perf_counter() - t0) / steps
    assert np.isfinite(loss), f"non-finite loss {loss}"
    engine._ckpt_writer.drain()  # async leg: land the last write
    # overlap proof from tracer timestamps: each checkpoint/async_write
    # span's originating save is the LATEST checkpoint/save span that
    # started before the write did (coalescing can drop intermediate
    # saves, so a positional zip would misalign); hidden time = how far
    # the write ran past that save call's return, averaged over WRITTEN
    # checkpoints (submissions that coalesced away never wrote)
    hidden = 0.0
    ev = [e for e in engine.telemetry.tracer.events() if e.get("ph") == "X"]
    save_spans = [e for e in ev if e["name"] == "checkpoint/save"]
    write_spans = [e for e in ev if e["name"] == "checkpoint/async_write"]
    for w in write_spans:
        cands = [s for s in save_spans if s["ts"] <= w["ts"]]
        if not cands:
            continue
        s = max(cands, key=lambda e: e["ts"])
        hidden += max(0.0, (w["ts"] + w["dur"]) - (s["ts"] + s["dur"])) / 1e6
    engine.close()
    out = {"ckpt": mode,
           "step_s": round(dt, 6),
           "saves": saves,
           "writes": len(write_spans) if use_async else saves,
           "save_exposed_s": round(save_stall / max(saves, 1), 6),
           "ckpt_hidden_s": round(hidden / max(len(write_spans), 1), 6),
           "delay_s": float(os.environ.get("DS_CKPT_DELAY_S", "0") or 0)}
    _mark(f"ckpt[{mode}]: {dt:.3f}s/step, exposed "
          f"{out['save_exposed_s']:.3f}s/save, hidden "
          f"{out['ckpt_hidden_s']:.3f}s/save")
    return out


def _ckpt_ab(jax, mode: str):
    """``--ckpt={sync,async,ab}``: steps/sec with a checkpoint interval
    active; the A/B records the exposed-stall comparison and speedup."""
    legs = {"async": [True], "sync": [False],
            "ab": [True, False]}[mode]
    results = [bench_ckpt(jax, leg) for leg in legs]
    rec = {"metric": "ckpt_step_breakdown",
           "unit": "s/step",
           "legs": results}
    if len(results) == 2:
        sync_t, async_t = results[1]["step_s"], results[0]["step_s"]
        rec["speedup"] = round(sync_t / async_t, 4) if async_t > 0 else 0.0
        rec["exposed_stall_ratio"] = round(
            results[0]["save_exposed_s"]
            / max(results[1]["save_exposed_s"], 1e-9), 4)
    try:
        with open("BENCH_ckpt.json", "w") as f:
            json.dump(rec, f, indent=1)
    except OSError:
        pass
    print(json.dumps(rec), flush=True)


def bench_stage_chaos_leg(jax, chaos: bool, steps: int = 6):
    """One leg of ``--stage-chaos`` (docs/stages.md).  A tiny host-
    offload GPT-2 engine with every async plane active — input
    prefetch, the streamed offload update pipeline, an async save
    submitted every step.  ``chaos=True`` arms a STICKY injected fault
    at every stage boundary (``DS_STAGE_FAULT``), so each stage
    exhausts its failure budget and degrades to its inline/serial
    equivalent mid-run; ``chaos=False`` is the serial/inline/sync
    reference the degraded run must match bitwise."""
    import shutil
    import tempfile

    from deepspeed_tpu.models import GPT2Config, GPT2Model
    from deepspeed_tpu.parallel import build_mesh
    from deepspeed_tpu.config import DeepSpeedConfig
    from deepspeed_tpu.runtime.engine import DeepSpeedEngine
    from deepspeed_tpu.runtime.dataloader import RepeatingLoader
    from deepspeed_tpu.runtime.stages import reset_fault_injection

    d_model, n_layer, micro, seq, vocab = 64, 2, 2, 64, 256
    cfg_model = GPT2Config(d_model=d_model, n_layer=n_layer, n_head=2,
                           vocab_size=vocab, n_positions=seq, remat=None)
    mesh = build_mesh(devices=jax.devices()[:1])
    ds_cfg = DeepSpeedConfig({
        "train_micro_batch_size_per_gpu": micro,
        "gradient_accumulation_steps": 1,
        "steps_per_print": 10 ** 9,
        "bf16": {"enabled": True},
        "optimizer": {"type": "Adam", "params": {"lr": 1e-4}},
        "zero_optimization": {"stage": 2, "cpu_offload": True,
                              "offload_impl": "host",
                              # the reference leg IS the serial update
                              "offload_pipeline": chaos},
        "data_prefetch": {"enabled": chaos},
    }, world_size=1)
    leg = "chaos" if chaos else "reference"
    reset_fault_injection()
    # pop the FULL chaos env set: a stray DS_CKPT_FAULT / delay knob in
    # the operator's shell must not leak into either leg of the proof
    saved_env = {k: os.environ.pop(k, None)
                 for k in ("DS_STAGE_FAULT", "DS_STAGE_DELAY_S",
                           "DS_CKPT_FAULT", "DS_CKPT_DELAY_S",
                           "DS_PREFETCH_DELAY_S",
                           "DS_OFFLOAD_H2D_DELAY_S", "DS_PREFETCH",
                           "DS_OFFLOAD_PIPELINE")}
    if chaos:
        # sticky: every hit of every async boundary fails until the
        # stage's budget (default 3) is exhausted and it degrades
        os.environ["DS_STAGE_FAULT"] = ("prefetch:place:1+,"
                                        "offload_h2d:put:1+,"
                                        "ckpt_writer:job:1+")
    save_dir = tempfile.mkdtemp(prefix="bench_stage_chaos_")
    try:
        rng = np.random.default_rng(0)
        dataset = [rng.integers(0, vocab, (seq + 1,), dtype=np.int32)
                   for _ in range(micro * 4)]
        _mark(f"stage-chaos[{leg}]: constructing engine")
        engine = DeepSpeedEngine(GPT2Model(cfg_model), ds_cfg, mesh=mesh,
                                 training_data=dataset)
        try:
            engine.training_dataloader = RepeatingLoader(
                engine.training_dataloader)
            losses, failed_saves = [], 0
            t0 = time.perf_counter()
            for i in range(steps):
                losses.append(float(np.asarray(engine.train_batch())))
                # chaos leg: async until the writer degrades to sync
                engine.save_checkpoint(save_dir, tag=f"s{i}",
                                       async_write=chaos)
                err = engine._ckpt_writer.drain()
                if err is not None:
                    failed_saves += 1
            wall = time.perf_counter() - t0
            degraded = sorted(n for n, st in engine._stage_records.items()
                              if st.degraded)
            # the post-degradation save must have LANDED (sync fallback)
            final_saved = os.path.isdir(
                os.path.join(save_dir, f"s{steps - 1}"))
        finally:
            # an exception mid-leg must not leave the degraded engine's
            # daemon workers alive into the next leg (GC-finalizer luck)
            engine.close()
        out = {"leg": leg, "losses": losses,
               "steps_per_s": round(steps / wall, 4),
               "degraded_stages": degraded,
               "failed_async_saves": failed_saves,
               "final_save_landed": bool(final_saved)}
        _mark(f"stage-chaos[{leg}]: {steps / wall:.2f} steps/s, "
              f"degraded={degraded}, failed saves={failed_saves}")
        return out
    finally:
        shutil.rmtree(save_dir, ignore_errors=True)
        os.environ.pop("DS_STAGE_FAULT", None)
        for k, v in saved_env.items():
            if v is not None:
                os.environ[k] = v
        reset_fault_injection()


def _stage_chaos(jax):
    """``--stage-chaos``: the graceful-degradation CI proof — repeated
    sticky faults on every async stage; training must complete DEGRADED
    (all three stages fell back, the post-degradation save landed) with
    throughput > 0 and the final loss bitwise-equal to the serial/
    inline/sync reference leg."""
    from deepspeed_tpu.runtime.stages import DEFAULT_MAX_STAGE_FAILURES
    chaos = bench_stage_chaos_leg(jax, chaos=True)
    ref = bench_stage_chaos_leg(jax, chaos=False)
    ok = (chaos["degraded_stages"] == ["ckpt_writer", "offload_h2d",
                                       "prefetch"]
          # the writer fails one save per budget unit before degrading
          and chaos["failed_async_saves"] == DEFAULT_MAX_STAGE_FAILURES
          and chaos["final_save_landed"]
          and chaos["steps_per_s"] > 0
          and chaos["losses"] == ref["losses"])
    rec = {"metric": "stage_chaos_degraded_run",
           "unit": "bool",
           "value": int(ok),
           "steps_per_s_degraded": chaos["steps_per_s"],
           "degraded_stages": chaos["degraded_stages"],
           "failed_async_saves": chaos["failed_async_saves"],
           "final_save_landed": chaos["final_save_landed"],
           "loss_bitwise_equal_serial": chaos["losses"] == ref["losses"],
           "final_loss": chaos["losses"][-1]}
    try:
        with open("BENCH_stage_chaos.json", "w") as f:
            json.dump(rec, f, indent=1)
    except OSError:
        pass
    print(json.dumps(rec), flush=True)
    if not ok:
        raise RuntimeError(f"stage chaos smoke FAILED: {rec}")


def _elastic_smoke():
    """``--elastic-smoke``: the elastic-training kill/resume proof as a
    bench leg (docs/elastic.md).  Launches ``ds --elastic`` supervising
    the tests/elastic_worker.py trainer on localhost at 4 slots, the
    worker hard-kills itself after step 3 (prefetcher ON at depth 2 —
    in-flight batches genuinely abandoned), the probe reports the host
    shrunk to 2 slots, and the supervisor relaunches.  Asserts resume
    at the REDUCED width with trajectory continuity against a
    dp2-from-start reference given the same sample order, plus
    sample-exactness (no replay, no skip).  CPU-only by design — it
    proves supervisor/resume mechanics, not throughput — so it never
    touches the TPU tunnel."""
    import shutil
    import subprocess
    import tempfile

    repo = os.path.dirname(os.path.abspath(__file__))
    work = tempfile.mkdtemp(prefix="bench_elastic_")
    env = dict(os.environ)
    env["PYTHONPATH"] = (repo + os.pathsep + os.path.join(repo, "tests")
                         + os.pathsep + env.get("PYTHONPATH", ""))
    env["JAX_PLATFORMS"] = "cpu"
    env["DS_CKPT_FSYNC"] = "0"
    flags = env.get("XLA_FLAGS", "")
    if "xla_force_host_platform_device_count" not in flags:
        # the workers shard dp4 -> dp2 over virtual CPU devices
        env["XLA_FLAGS"] = (flags
                            + " --xla_force_host_platform_device_count=8")
    for k in ("DS_ELASTIC_RESTART", "DS_ELASTIC_WORLD_SLOTS",
              "DS_HEARTBEAT_DIR"):
        env.pop(k, None)
    worker = os.path.join(repo, "tests", "elastic_worker.py")

    def lines(path):
        with open(path) as f:
            return [json.loads(l) for l in f]

    try:
        hf = os.path.join(work, "hostfile")
        with open(hf, "w") as f:
            f.write("localhost slots=4\n")
        probe = os.path.join(work, "probe.sh")
        with open(probe, "w") as f:
            f.write("#!/bin/sh\necho slots=2\n")
        os.chmod(probe, 0o755)
        out = os.path.join(work, "out")
        ckpt = os.path.join(work, "ckpt")
        os.makedirs(out), os.makedirs(ckpt)
        _mark("elastic-smoke: supervised run (kill after step 3 of 6)")
        t0 = time.perf_counter()
        r = subprocess.run(
            [sys.executable, os.path.join(repo, "bin", "ds"),
             "--hostfile", hf, "--launcher", "local", "--elastic",
             "--max-restarts", "2", "--backoff-base", "0.1",
             "--probe-cmd", f"{probe} {{host}}",
             worker, out, ckpt, "6", "3"],
            env=env, timeout=600, capture_output=True, text=True)
        if r.returncode != 0:
            raise RuntimeError(
                f"elastic supervised run failed rc={r.returncode}: "
                f"{(r.stderr or r.stdout)[-1500:]}")
        supervised_s = time.perf_counter() - t0
        _mark("elastic-smoke: dp2-from-start reference run")
        ref_out = os.path.join(work, "ref")
        ref_ckpt = os.path.join(work, "refck")
        os.makedirs(ref_out), os.makedirs(ref_ckpt)
        e = dict(env)
        e["DS_ELASTIC_WORLD_SLOTS"] = "2"
        r = subprocess.run(
            [sys.executable, worker, ref_out, ref_ckpt, "6", "0"],
            env=e, timeout=600, capture_output=True, text=True)
        if r.returncode != 0:
            raise RuntimeError(
                f"reference run failed: {(r.stderr or r.stdout)[-1500:]}")

        t1 = lines(os.path.join(out, "traj_r1.jsonl"))
        ref = lines(os.path.join(ref_out, "traj_r0.jsonl"))
        widths = sorted({rec["dp"] for rec in t1})
        resumed_at_reduced = widths == [2]
        steps = [rec["step"]
                 for rec in lines(os.path.join(out, "traj_r0.jsonl"))] \
            + [rec["step"] for rec in t1]
        continuous = steps == list(range(6))
        drift = max(abs(a["loss"] - b["loss"])
                    for a, b in zip(t1, ref[3:]))
        samples = (lines(os.path.join(out, "samples_r0.jsonl"))[:3]
                   + lines(os.path.join(out, "samples_r1.jsonl")))[:6]
        sample_exact = samples == lines(
            os.path.join(ref_out, "samples_r0.jsonl"))[:6]
        rec = {"metric": "elastic_kill_resume_smoke",
               "unit": "bool",
               "value": int(resumed_at_reduced and continuous
                            and sample_exact and drift < 1e-4),
               "resumed_at_dp": widths,
               "trajectory_continuous": continuous,
               "sample_exact": sample_exact,
               "max_loss_drift_vs_dp2_from_start": round(drift, 9),
               "supervised_wall_s": round(supervised_s, 3)}
        try:
            with open("BENCH_elastic.json", "w") as f:
                json.dump(rec, f, indent=1)
        except OSError:
            pass
        print(json.dumps(rec), flush=True)
        if not rec["value"]:
            raise RuntimeError(f"elastic smoke FAILED: {rec}")
    finally:
        shutil.rmtree(work, ignore_errors=True)


def _enable_compile_cache():
    """Persistent XLA compilation cache shared across bench runs.  The
    1.5B program (48-layer scan + offload staging) is compile-heavy and
    this environment's compiles go through a remote tunnel; a warm cache
    turns a multi-minute compile into a disk read on the driver's re-runs.
    Best-effort: unsupported backends just miss the cache."""
    import jax
    d = os.environ.get("BENCH_COMPILE_CACHE") or os.path.join(
        os.path.dirname(os.path.abspath(__file__)), ".jax_cache")
    if d == "0":
        return
    try:
        os.makedirs(d, exist_ok=True)
        jax.config.update("jax_compilation_cache_dir", d)
        jax.config.update("jax_persistent_cache_min_compile_time_secs", 1.0)
        _mark(f"compile cache at {d}")
    except Exception as e:  # never let the cache kill a bench
        print(f"compile cache unavailable: {e}", file=sys.stderr)


def _probe_tunnel(deadline: int):
    """Backend-init health probe in a SUBPROCESS.  A wedged TPU tunnel
    blocks ``jax.devices()`` inside a native call forever — SIGALRM never
    fires — so the parent must not be the first process to touch it.  The
    child only *initializes* a backend (it never runs a step), so killing
    it at the deadline cannot wedge device state the way killing a mid-step
    job does (BENCH_NOTES.md); the parent then fails loudly with one JSON
    line instead of hanging the driver's whole bench slot."""
    import subprocess
    _mark(f"probing TPU tunnel (subprocess, {deadline}s deadline)")
    try:
        r = subprocess.run(
            [sys.executable, "-c",
             "import jax; d = jax.devices(); "
             "print('PROBE_OK', len(d), d[0].platform)"],
            capture_output=True, text=True, timeout=deadline)
    except subprocess.TimeoutExpired:
        raise RuntimeError(
            f"TPU tunnel unresponsive: backend init did not complete "
            f"within {deadline}s (wedged-tunnel signature, BENCH_NOTES.md)")
    if "PROBE_OK" not in r.stdout:
        tail = (r.stderr or r.stdout).strip().splitlines()[-5:]
        raise RuntimeError(
            "TPU backend init failed in probe subprocess: "
            + " | ".join(tail))
    _mark(f"tunnel probe: {r.stdout.strip().splitlines()[-1]}")


def guarded_devices():
    """jax.devices() with wedged-tunnel protection — enumeration itself
    can hang indefinitely when the tunnel is down (observed).  Shared by
    every bench script.  Layer 1: subprocess probe (catches the native
    hang SIGALRM can't).  Layer 2: in-process SIGALRM watchdog (catches
    slow-but-returning paths)."""
    import jax
    _enable_compile_cache()
    deadline = int(os.environ.get("BENCH_DEVICES_TIMEOUT", "300"))
    # probe unless the RESOLVED platform list is cpu-ONLY.  Only
    # jax.config is authoritative: this image's sitecustomize rewrites
    # even an explicit JAX_PLATFORMS=cpu env var to 'axon,cpu', which
    # still initializes the TPU tunnel first.
    plats = str(jax.config.jax_platforms or "")
    cpu_pinned = [p.strip() for p in plats.split(",") if p.strip()] == ["cpu"]
    if not cpu_pinned and os.environ.get("BENCH_SKIP_TUNNEL_PROBE") != "1":
        _probe_tunnel(deadline)
    _mark("enumerating devices")
    with _Watchdog(deadline):
        devices = jax.devices()
    _mark(f"devices: {[d.device_kind for d in devices]}")
    return devices


def main():
    import argparse

    import jax

    parser = argparse.ArgumentParser(
        description="GPT-2 1.5B ZeRO-2 offload north-star bench "
                    "(one JSON line); env knobs in the module docstring")
    parser.add_argument("--offload-pipeline", choices=("on", "off", "ab"),
                        default=None,
                        help="A/B the streaming offload update pipeline: "
                             "per-stage step-time breakdown (d2h / "
                             "cpu_adam / h2d / hidden) instead of the "
                             "north-star bench")
    parser.add_argument("--offload-tier", choices=("host", "disk", "ab"),
                        default=None, dest="offload_tier",
                        help="A/B the offload state tier (host RAM vs "
                             "the ZeRO-Infinity disk tier): step time, "
                             "bitwise-loss check, and the disk leg's "
                             "state-I/O overlap ratio under injected "
                             "per-leaf disk latency "
                             "(BENCH_offload_disk.json)")
    parser.add_argument("--prefetch", choices=("on", "off", "ab"),
                        default=None,
                        help="A/B the async input pipeline (prefetched "
                             "collate + H2D batch placement): step time "
                             "+ prefetch wait/hit breakdown instead of "
                             "the north-star bench")
    parser.add_argument("--ckpt", choices=("sync", "async", "ab"),
                        default=None,
                        help="A/B fault-tolerant checkpointing: steps/sec "
                             "with a checkpoint interval active, sync vs "
                             "async saves (exposed-stall comparison + "
                             "tracer-proven hidden write time) instead "
                             "of the north-star bench")
    parser.add_argument("--stage-chaos", action="store_true",
                        dest="stage_chaos",
                        help="graceful-degradation smoke: sticky "
                             "injected faults at every async stage "
                             "boundary (prefetch/offload-upload/async "
                             "save); asserts training completes "
                             "degraded, throughput > 0, final loss "
                             "bitwise-equal to the serial reference "
                             "(docs/stages.md)")
    parser.add_argument("--elastic-smoke", action="store_true",
                        dest="elastic_smoke",
                        help="kill/resume supervisor smoke: ds --elastic "
                             "on localhost, worker hard-killed mid-run, "
                             "assert resume at reduced width with "
                             "trajectory continuity + sample-exactness "
                             "(CPU subprocesses only; never probes the "
                             "TPU tunnel)")
    # strict parse: a typo'd flag must fail loudly, not silently launch
    # the multi-hour north-star run (the _15b_knobs eager-validation rule)
    args = parser.parse_args()

    if args.elastic_smoke:
        # dispatched BEFORE device enumeration: the smoke is pure CPU
        # subprocess supervision and must not touch (or wedge on) the
        # TPU tunnel
        _elastic_smoke()
        return

    devices = guarded_devices()
    on_tpu = devices[0].platform != "cpu"
    sys.path.insert(0, ".")

    if args.offload_pipeline is not None:
        _offload_pipeline_ab(jax, args.offload_pipeline)
        return

    if args.offload_tier is not None:
        _offload_tier_ab(jax, args.offload_tier)
        return

    if args.prefetch is not None:
        _prefetch_ab(jax, args.prefetch)
        return

    if args.ckpt is not None:
        _ckpt_ab(jax, args.ckpt)
        return

    if args.stage_chaos:
        _stage_chaos(jax)
        return

    if not on_tpu:  # CPU smoke (driver runs the real thing on TPU)
        from deepspeed_tpu.models import GPT2Config, GPT2Model
        from deepspeed_tpu.parallel import build_mesh
        from deepspeed_tpu.config import DeepSpeedConfig
        from deepspeed_tpu.runtime.engine import DeepSpeedEngine
        cfg = GPT2Config(d_model=128, n_layer=2, n_head=4, vocab_size=512,
                         n_positions=128, remat=None)
        mesh = build_mesh(devices=devices[:1])
        ds_cfg = DeepSpeedConfig({
            "train_micro_batch_size_per_gpu": 2,
            "gradient_accumulation_steps": 1,
            "steps_per_print": 10 ** 9,
            "bf16": {"enabled": True},
            "optimizer": {"type": "Adam", "params": {"lr": 1e-4}},
            "zero_optimization": {"stage": 0},
        }, world_size=1)
        eng = DeepSpeedEngine(GPT2Model(cfg), ds_cfg, mesh=mesh)
        toks = np.random.default_rng(0).integers(0, 512, (2, 65),
                                                 dtype=np.int32)
        dt, _ = _run(eng, toks, 3)
        print(json.dumps({
            "metric": "gpt2_tiny_cpu_smoke_tokens_per_sec",
            "value": round(2 * 64 / dt, 1), "unit": "tokens/s",
            "vs_baseline": 0.0}))
        return

    peak = _resolve_peak(devices[0])
    result = None
    if not os.environ.get("BENCH_SMALL"):
        # parse/validate ALL env knobs outside the fallback guard: a typo
        # must fail loudly, not silently demote the run to 124M
        _, _, _, deadline = _15b_knobs()
        # xla tier first — root-caused round 3 (BENCH_NOTES.md): the
        # round-2 "xla stall" was not tier-specific, it was (a) eager
        # per-leaf init (~15 sequential remote compiles, now ONE jitted
        # program) and (b) bulk device<->container transfers, which the
        # websocket relay tunnel stalls on indefinitely.  The host tier
        # pulls the 6.2 GB master through that tunnel at construction and
        # again every step, so ON THIS TUNNELED PLATFORM it cannot work;
        # the xla tier's pinned_host staging stays on the remote TPU VM
        # (no bulk tunnel traffic at all).  The host tier now fast-fails
        # on a bandwidth probe instead of stalling, so it is safe to
        # keep as the chain's closer (it IS the right tier on a real
        # TPU VM).  xla_split opens: the fused update program OOM'd at
        # AOT compile (22.76G fp32 HLO temps, round-5 window);
        # per-piece programs carry a hard liveness bound.  xla_split4
        # adds grad chunking if the grad program is still too tight.
        # 'xla' (fused) left out of the default chain — request it via
        # BENCH_15B_IMPL where the compiler honors host placement.
        impls = [s.strip() for s in
                 os.environ.get(
                     "BENCH_15B_IMPL",
                     "xla_split_dpu,xla_split,xla_split4,host"
                 ).split(",")]
        valid = ("xla_split_dpu", "xla_split", "xla_split4", "xla",
                 "host")
        bad = [s for s in impls if s not in valid]
        if bad:
            raise ValueError(f"BENCH_15B_IMPL contains {bad}; valid: "
                             + ", ".join(valid))
        # ONE deadline shared across the whole chain: two wedged attempts
        # must not double the worst-case bound before the 124M fallback
        chain_deadline = time.monotonic() + deadline
        for impl in impls:
            left = int(chain_deadline - time.monotonic())
            if left <= 0:
                print("1.5B chain deadline exhausted", file=sys.stderr)
                break
            try:
                with _Watchdog(left):
                    result = _bench_15b(jax, impl=impl)
                break
            except Exception:
                # fall through OUTSIDE the except block: the live traceback
                # pins the failed attempt's engine/HBM buffers, which would
                # make an OOM fallback OOM too
                traceback.print_exc(file=sys.stderr)
                print(f"1.5B offload bench (impl={impl}) failed; "
                      "trying next fallback", file=sys.stderr)
    if result is None:
        result = _bench_124m(jax)
    cfg, seq, tps, name = result

    mfu = tps * _flops_per_token(cfg, seq) / peak
    rec = {
        "metric": f"{name}_seq{seq}_tokens_per_sec_per_chip",
        "value": round(tps, 1),
        "unit": "tokens/s",
        "vs_baseline": round(mfu / 0.45, 4),
    }
    # artifact BEFORE stdout: survives even if a later phase wedges
    try:
        with open("BENCH_north_star.json", "w") as f:
            json.dump(rec, f, indent=1)
    except OSError:
        pass
    print(json.dumps(rec), flush=True)
    _run_suite_benches()


def _run_suite_benches():
    """Opportunistic: after the north-star line is safely out, produce
    the rest of the hardware evidence in the same healthy-tunnel window
    (the driver only ever runs bench.py — if the tunnel is up only
    during that run, these artifacts would otherwise never exist).
    Each bench runs in a subprocess with stdout to a file (this
    process's stdout stays exactly one JSON line) and writes its own
    BENCH_*.json on TPU.  BENCH_SUITE=0 disables; each gets a bounded
    timeout — by this point the main number is banked, so a worst-case
    wedge costs only the driver's remaining slot."""
    import subprocess
    if os.environ.get("BENCH_SUITE", "1") == "0":
        return
    per_bench = int(os.environ.get("BENCH_SUITE_TIMEOUT", "1500"))
    here = os.path.dirname(os.path.abspath(__file__))
    for name in ("bench_bert", "bench_sparse", "bench_flash",
                 "bench_moe", "bench_capacity"):
        _mark(f"suite: {name} (timeout {per_bench}s)")
        out = os.path.join(here, f"BENCH_{name[6:]}_raw.json")
        try:
            with open(out, "w") as fh:
                subprocess.run(
                    [sys.executable, os.path.join(here, name + ".py")],
                    stdout=fh, stderr=sys.stderr, timeout=per_bench,
                    cwd=here)
        except subprocess.TimeoutExpired:
            _mark(f"suite: {name} timed out; stopping the suite (a "
                  "killed TPU client can wedge the tunnel)")
            break
        except Exception as e:
            _mark(f"suite: {name} failed: {e}")


if __name__ == "__main__":
    try:
        main()
    except Exception:
        # The driver parses exactly one JSON line; a crash must still
        # produce one (value 0) rather than an empty record.
        traceback.print_exc(file=sys.stderr)
        print(json.dumps({"metric": "bench_failed", "value": 0.0,
                          "unit": "tokens/s", "vs_baseline": 0.0}))
        sys.exit(0)
