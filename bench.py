"""Headline benchmark: GPT-2 training throughput on one TPU chip.

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline"}.
vs_baseline is measured MFU / 0.45 — the BASELINE.json north star is >=45%
MFU for GPT-2-class ZeRO training on TPU, so vs_baseline >= 1.0 means the
target is met on this chip.
"""
import json
import sys
import time

import numpy as np


def _chip_peak_bf16_flops(device) -> float:
    kind = getattr(device, "device_kind", "").lower()
    # published bf16 peak per chip
    if "v5p" in kind or "v5 p" in kind:
        return 459e12
    if "v5" in kind:      # v5e / v5 lite
        return 197e12
    if "v4" in kind:
        return 275e12
    if "v6" in kind:
        return 918e12
    return 197e12  # conservative default


def main():
    import jax
    import jax.numpy as jnp

    devices = jax.devices()
    on_tpu = devices[0].platform != "cpu"

    sys.path.insert(0, ".")
    from deepspeed_tpu.models import GPT2Config, GPT2Model
    from deepspeed_tpu.parallel import build_mesh
    from deepspeed_tpu.config import DeepSpeedConfig
    from deepspeed_tpu.runtime.engine import DeepSpeedEngine

    if on_tpu:
        # flash attention keeps memory O(T·D), so B=16 fits with no remat;
        # unrolled layers let XLA optimize across block boundaries
        cfg_model = GPT2Config(d_model=768, n_layer=12, n_head=12,
                               vocab_size=50257, n_positions=1024,
                               remat=None, scan_layers=False)
        batch, seq, steps = 16, 1024, 10
    else:  # smoke fallback (driver runs this on real TPU)
        cfg_model = GPT2Config(d_model=128, n_layer=2, n_head=4,
                               vocab_size=512, n_positions=128, remat=None)
        batch, seq, steps = 2, 64, 3

    model = GPT2Model(cfg_model)
    mesh = build_mesh(devices=devices[:1])
    ds_cfg = DeepSpeedConfig({
        "train_micro_batch_size_per_gpu": batch,
        "gradient_accumulation_steps": 1,
        "steps_per_print": 10 ** 9,
        "bf16": {"enabled": True},
        "optimizer": {"type": "Adam", "params": {"lr": 1e-4}},
        "zero_optimization": {"stage": 0},
    }, world_size=1)
    engine = DeepSpeedEngine(model, ds_cfg, mesh=mesh)

    rng = np.random.default_rng(0)
    tokens = rng.integers(0, cfg_model.vocab_size, (batch, seq + 1),
                          dtype=np.int32)

    np.asarray(engine.train_batch(tokens))  # compile + warmup
    np.asarray(engine.train_batch(tokens))

    # loss is returned lazily (device value): steps queue back-to-back and
    # the single sync below covers the whole timed region
    t0 = time.perf_counter()
    loss = None
    for _ in range(steps):
        loss = engine.train_batch(tokens)
    np.asarray(loss)
    dt = (time.perf_counter() - t0) / steps

    tokens_per_sec = batch * seq / dt
    n_params = cfg_model.num_params
    # Model flops per token (fwd+bwd matmuls): 6N + causal attention 12LdT.
    # Remat recompute is NOT counted — MFU measures useful flops only.
    flops_per_token = (6 * n_params +
                       12 * cfg_model.n_layer * cfg_model.d_model * seq)
    achieved = tokens_per_sec * flops_per_token
    peak = _chip_peak_bf16_flops(devices[0])
    mfu = achieved / peak

    print(json.dumps({
        "metric": "gpt2_124m_seq1024_tokens_per_sec_per_chip"
        if on_tpu else "gpt2_tiny_cpu_smoke_tokens_per_sec",
        "value": round(tokens_per_sec, 1),
        "unit": "tokens/s",
        "vs_baseline": round(mfu / 0.45, 4),
    }))


if __name__ == "__main__":
    main()
