"""Quantized serving plane (docs/serving.md "quantized serving"):

* numeric-bounds per-op tests — ``|q(x) - x| <= scale/2`` for the KV
  row quantizer and the per-channel weight quantizer (the scale-
  derived bound of inference/quantize.py),
* int8-domain parity — the dense paged arms are BITWISE the reference
  over the dequantized gathered view (the semantics anchor), the
  pallas fused-dequant arms match dense at the established kernel
  tolerance, single- and multi-query,
* default-off is bitwise-unchanged: the explicit fp16 arm emits the
  same streams as no quantization block at all, no scale leaves, no
  dtype changes,
* engine tolerance tier — kv-int8 first tokens are EXACT (prefill
  computes fp; only storage quantizes), full greedy streams' agreement
  reported against a pinned floor,
* zero-recompile + COW/eviction under quantized pages (scale sidecars
  ride the copy_page program; pool accounting stays clean),
* quantized-draft speculation: spec stream == non-spec stream at
  k in {1, 4} under weights+kv int8 (and the unpaged weights arm),
* config validation, the serve_param_bytes/serve_kv_bytes memory
  plane -> summarize row, benchgate direction pin, and the
  ``bench_serve.py --quant`` smoke (>= 2x admitted at fixed KV bytes,
  0 truncations, params-HBM >= 1.8x).
"""
import json
import os

import numpy as np
import jax
import jax.numpy as jnp
import pytest
from jax.sharding import PartitionSpec as P

from deepspeed_tpu.config import DeepSpeedConfigError
from deepspeed_tpu.config.config import DeepSpeedServingConfig
from deepspeed_tpu.inference import ServeEngine
from deepspeed_tpu.inference.quantize import (
    dequantize_channels, dequantize_rows, param_nbytes,
    quantize_channels, quantize_gpt2_params, quantize_rows,
    quantized_partition_specs)
from deepspeed_tpu.models.gpt2 import GPT2Config, GPT2Model, gpt2_prefill
from deepspeed_tpu.ops.pallas.decode_attention import (
    decode_attention_paged, decode_attention_paged_multi,
    decode_attention_reference, dequantize_paged)
from deepspeed_tpu.runtime.stages import reset_fault_injection

TINY = GPT2Config(vocab_size=128, n_positions=64, d_model=32, n_layer=2,
                  n_head=4, remat=None, attn_impl="dense")
TINY_FLASH = GPT2Config(**{**TINY.__dict__, "attn_impl": "flash"})

_CHAOS_ENVS = ("DS_STAGE_FAULT", "DS_STAGE_DELAY_S")


@pytest.fixture(autouse=True)
def _clean_faults(monkeypatch):
    for env in _CHAOS_ENVS:
        monkeypatch.delenv(env, raising=False)
    reset_fault_injection()
    yield
    reset_fault_injection()


def _tokens(n, vocab=128, seed=0):
    return np.random.default_rng(seed).integers(
        0, vocab, (n,)).astype(np.int32)


def _serve_cfg(slots=4, max_seq=32, prefill=24, telemetry_path=None,
               **serving_extra):
    cfg = {"serving": {"slots": slots, "max_seq_len": max_seq,
                       "prefill_len": prefill, **serving_extra}}
    if telemetry_path is not None:
        cfg["telemetry"] = {"enabled": True,
                            "output_path": str(telemetry_path)}
    return cfg


def _streams(model, params, serving_extra, prompts, gen=6,
             draft_params=None):
    eng = ServeEngine(model, _serve_cfg(**serving_extra), params=params,
                      draft_params=draft_params)
    rs = [eng.submit(p, max_new_tokens=gen) for p in prompts]
    eng.run_until_idle()
    assert all(r.error is None for r in rs), [r.error for r in rs]
    out = [r.tokens for r in rs]
    eng.close()
    return out


def _agreement(a, b):
    total = same = 0
    for ta, tb in zip(a, b):
        for x, y in zip(ta, tb):
            total += 1
            same += x == y
    return same / max(total, 1)


# ---------------------------------------------------------------------------
# numeric bounds: the scale-derived error contract, per op
# ---------------------------------------------------------------------------


def test_quantize_rows_numeric_bounds():
    rng = np.random.RandomState(0)
    x = jnp.asarray(rng.randn(5, 4, 16) * rng.lognormal(0, 2, (5, 4, 1)),
                    jnp.float32)
    q, s = quantize_rows(x)
    assert q.dtype == jnp.int8 and s.shape == (5, 4)
    err = jnp.abs(dequantize_rows(q, s) - x)
    # round-to-nearest within the symmetric range: |q*s - x| <= s/2,
    # and the absmax element itself is EXACT (maps to +-127)
    assert (err <= s[..., None] / 2 + 1e-6).all()
    flat = np.asarray(jnp.abs(x)).reshape(-1, 16)
    deq = np.asarray(jnp.abs(dequantize_rows(q, s))).reshape(-1, 16)
    idx = flat.argmax(axis=1)
    np.testing.assert_allclose(deq[np.arange(len(idx)), idx],
                               flat[np.arange(len(idx)), idx], rtol=1e-6)
    # all-zero rows: scale 1.0, exact-zero round trip
    qz, sz = quantize_rows(jnp.zeros((2, 3, 8)))
    assert (np.asarray(sz) == 1.0).all()
    assert (np.asarray(dequantize_rows(qz, sz)) == 0).all()


def test_quantize_channels_numeric_bounds():
    rng = np.random.RandomState(1)
    w = jnp.asarray(rng.randn(2, 32, 3, 32), jnp.float32)  # qkv shape
    q, s = quantize_channels(w)
    assert q.dtype == jnp.int8 and s.shape == (2, 1, 3, 32)
    err = jnp.abs(dequantize_channels(q, s) - w)
    assert (err <= s / 2 + 1e-6).all()
    # the fused matmul's error obeys the per-channel bound too:
    # |x·w8·s - x·w| <= sum|x| * s/2 per output channel
    x = jnp.asarray(rng.randn(4, 32), jnp.float32)
    got = jnp.einsum("bd,dke->bke", x, q[0].astype(jnp.float32)) * s[0]
    ref = jnp.einsum("bd,dke->bke", x, w[0])
    bound = jnp.sum(jnp.abs(x), axis=1)[:, None, None] * (s[0] / 2)
    assert (jnp.abs(got - ref) <= bound + 1e-5).all()


def test_quantized_param_tree_and_specs():
    model = GPT2Model(TINY)
    params = model.init(jax.random.PRNGKey(0))
    qp = quantize_gpt2_params(params)
    for name in ("qkv_w", "out_w", "fc_w", "proj_w"):
        assert qp["blocks"][name].dtype == jnp.int8
        assert qp["blocks"][name + "_scale"].dtype == jnp.float32
    # the input tree is never mutated; non-covered leaves untouched
    assert params["blocks"]["qkv_w"].dtype == jnp.float32
    assert qp["wte"] is params["wte"]
    assert qp["blocks"]["ln1_scale"] is params["blocks"]["ln1_scale"]
    # int8 + scales beat the fp32 master by > 2x on this config
    assert param_nbytes(params) / param_nbytes(qp) > 2.0
    specs = quantized_partition_specs(model.param_partition_specs(params))
    # column-parallel scales keep the output-channel shard; the
    # contracted (size-1) axis is never sharded
    assert specs["blocks"]["qkv_w_scale"] == P(None, None, None, "model")
    assert specs["blocks"]["fc_w_scale"] == P(None, None, "model")
    assert specs["blocks"]["out_w_scale"] == P(None, None, None)
    assert specs["blocks"]["proj_w_scale"] == P(None, None, None)


def test_quant_weights_prefill_logits_close():
    """The whole-model weights-arm bound: tiny logits drift, greedy
    argmax preserved on this seed (reported tier, pinned loose)."""
    model = GPT2Model(TINY)
    params = model.init(jax.random.PRNGKey(0))
    toks = jnp.asarray(_tokens(12, seed=3)[None])
    ref, _, _ = gpt2_prefill(TINY, params, toks)
    got, _, _ = gpt2_prefill(TINY, quantize_gpt2_params(params), toks)
    assert float(jnp.max(jnp.abs(got - ref))) < 0.05


# ---------------------------------------------------------------------------
# kernel parity: int8 domain, dense defines the semantics
# ---------------------------------------------------------------------------


def _quant_pool(S, H, page_len, max_pages, Dh, seed=0):
    rng = np.random.RandomState(seed)
    P_ = 1 + S * max_pages
    k8, ks = quantize_rows(jnp.asarray(rng.randn(P_, H, page_len, Dh),
                                       jnp.float32))
    v8, vs = quantize_rows(jnp.asarray(rng.randn(P_, H, page_len, Dh),
                                       jnp.float32))
    pt = jnp.asarray(np.arange(1, P_).reshape(S, max_pages), jnp.int32)
    return k8, ks, v8, vs, pt


def test_quant_kernel_parity_single_query():
    S, H, page_len, M, Dh = 4, 3, 16, 3, 32
    k8, ks, v8, vs, pt = _quant_pool(S, H, page_len, M, Dh)
    q = jnp.asarray(np.random.RandomState(1).randn(S, H, Dh),
                    jnp.float32)
    lengths = jnp.asarray([0, 7, 16, 2 * 16 + 5], jnp.int32)
    out_d = decode_attention_paged(q, k8, v8, pt, lengths, impl="dense",
                                   k_scale=ks, v_scale=vs)
    out_p = decode_attention_paged(q, k8, v8, pt, lengths,
                                   impl="pallas", interpret=True,
                                   k_scale=ks, v_scale=vs)
    # int8-domain semantics anchor: dense == reference over the
    # dequantized gathered view, BITWISE
    ref = decode_attention_reference(q, dequantize_paged(k8, ks, pt),
                                     dequantize_paged(v8, vs, pt),
                                     lengths)
    np.testing.assert_array_equal(np.asarray(out_d), np.asarray(ref))
    # fused kernel vs dense: the established kernel tolerance
    np.testing.assert_allclose(out_p, out_d, atol=2e-6, rtol=2e-6)
    # free slot -> exact zeros on both arms
    assert (np.asarray(out_d[0]) == 0).all()
    assert (np.asarray(out_p[0]) == 0).all()


def test_quant_kernel_parity_multi_query():
    S, H, page_len, M, Dh, W = 3, 2, 8, 4, 16, 5
    k8, ks, v8, vs, pt = _quant_pool(S, H, page_len, M, Dh, seed=2)
    q = jnp.asarray(np.random.RandomState(3).randn(S, H, W, Dh),
                    jnp.float32)
    base = np.asarray([0, 6, 2 * 8 + 3])
    lens = np.where(base[:, None] > 0,
                    base[:, None] + np.arange(W)[None] + 1, 0)
    lens = jnp.asarray(np.minimum(lens, M * page_len), jnp.int32)
    md = decode_attention_paged_multi(q, k8, v8, pt, lens, impl="dense",
                                      k_scale=ks, v_scale=vs)
    mp = decode_attention_paged_multi(q, k8, v8, pt, lens,
                                      impl="pallas", interpret=True,
                                      k_scale=ks, v_scale=vs)
    # the multi dense arm is DEFINED as W stacked single-query dense
    # calls over the same int8 domain — bitwise by construction
    for i in range(W):
        one = decode_attention_paged(q[:, :, i], k8, v8, pt, lens[:, i],
                                     impl="dense", k_scale=ks,
                                     v_scale=vs)
        np.testing.assert_array_equal(np.asarray(md[:, :, i]),
                                      np.asarray(one))
    np.testing.assert_allclose(mp, md, atol=2e-6, rtol=2e-6)
    # masked rows (slot 0, every row) -> exact zeros
    assert (np.asarray(mp[0]) == 0).all()


def test_quant_kernel_arg_validation():
    S, H, page_len, M, Dh = 2, 2, 8, 2, 16
    k8, ks, v8, vs, pt = _quant_pool(S, H, page_len, M, Dh)
    lengths = jnp.asarray([3, 5], jnp.int32)
    q = jnp.zeros((S, H, Dh), jnp.float32)
    with pytest.raises(ValueError, match="together"):
        decode_attention_paged(q, k8, v8, pt, lengths, impl="dense",
                               k_scale=ks)
    fp = jnp.zeros((1 + S * M, H, page_len, Dh), jnp.float32)
    with pytest.raises(ValueError, match="int8"):
        decode_attention_paged_multi(
            jnp.zeros((S, H, 2, Dh), jnp.float32), fp, fp, pt,
            jnp.zeros((S, 2), jnp.int32), impl="dense", k_scale=ks,
            v_scale=vs)


# ---------------------------------------------------------------------------
# engine: default-off bitwise, tolerance tiers, zero recompiles
# ---------------------------------------------------------------------------


BOUNDARY_PROMPTS = [1, 3, 8, 17, 20]


def test_quant_default_off_is_bitwise_unchanged():
    """The acceptance bar: no quantization block, the explicit fp16
    arm, and an empty dict all emit the SAME streams (they are the
    same compiled programs), with no scale leaves and no dtype
    changes anywhere."""
    model = GPT2Model(TINY)
    params = model.init(jax.random.PRNGKey(0))
    prompts = [list(_tokens(n, seed=10 + n)) for n in BOUNDARY_PROMPTS]
    absent = _streams(model, params, dict(page_len=8), prompts)
    explicit = _streams(
        model, params,
        dict(page_len=8,
             quantization={"weights": "fp16", "kv": "fp16"}), prompts)
    empty = _streams(model, params, dict(page_len=8, quantization={}),
                     prompts)
    assert absent == explicit == empty
    eng = ServeEngine(model, _serve_cfg(
        page_len=8, quantization={"weights": "fp16", "kv": "fp16"}),
        params=params)
    assert set(eng.cache) == {"k", "v", "lengths"}
    assert eng.cache["k"].dtype == jnp.float32
    assert eng.params["blocks"]["qkv_w"].dtype == jnp.float32
    assert "qkv_w_scale" not in eng.params["blocks"]
    assert not eng.cache_spec.quant
    eng.close()


@pytest.mark.parametrize("cfg", [TINY, TINY_FLASH],
                         ids=["dense", "flash"])
def test_quant_engine_tolerance_tier(cfg):
    """The documented tolerance tier (docs/serving.md): kv-int8 FIRST
    tokens are exact (prefill attends fp; only storage quantizes),
    and full greedy streams agree with the fp engine above the pinned
    floor on fixed seeds (reported-not-asserted-equal in the bench;
    pinned here so a numerics regression is loud)."""
    model = GPT2Model(cfg)
    params = model.init(jax.random.PRNGKey(0))
    prompts = [list(_tokens(n, seed=20 + n)) for n in BOUNDARY_PROMPTS]
    fp = _streams(model, params, dict(page_len=8), prompts)
    for quant in ({"kv": "int8"}, {"weights": "int8", "kv": "int8"}):
        qs = _streams(model, params,
                      dict(page_len=8, quantization=quant), prompts)
        if "weights" not in quant:
            # prefill computes full-precision K/V -> exact first token
            assert [t[0] for t in qs] == [t[0] for t in fp]
        assert _agreement(fp, qs) >= 0.9, (quant, fp, qs)
    # engine shape checks for the quantized cache
    eng = ServeEngine(model, _serve_cfg(
        page_len=8, quantization={"weights": "int8", "kv": "int8"}),
        params=params)
    assert eng.cache["k"].dtype == jnp.int8
    assert eng.cache["k_scale"].shape == eng.cache["k"].shape[:-1]
    assert eng.params["blocks"]["qkv_w"].dtype == jnp.int8
    assert eng.cache_spec.quant and eng.cache_spec.bytes == eng.kv_bytes
    eng.close()


def test_quant_weights_unpaged_engine():
    """The weights arm is independent of paging: the slot-cache engine
    serves int8 weights with the same tolerance tier."""
    model = GPT2Model(TINY)
    params = model.init(jax.random.PRNGKey(0))
    prompts = [list(_tokens(n, seed=30 + n)) for n in (2, 9, 15)]
    fp = _streams(model, params, {}, prompts)
    w8 = _streams(model, params,
                  dict(quantization={"weights": "int8"}), prompts)
    assert _agreement(fp, w8) >= 0.9
    with pytest.raises(DeepSpeedConfigError, match="page_len"):
        # kv int8 without pages must fail loudly at config parse
        ServeEngine(model, {"serving": {
            "slots": 2, "quantization": {"kv": "int8"}}}, params=params)


def test_quant_zero_recompiles_mixed_waves(tmp_path):
    """Acceptance bar: the quantized programs compile ONCE across
    waves of mixed page counts / lengths — recompiles_total == 0 and
    jit cache size 1 for decode_step, prefill and copy_page."""
    eng = ServeEngine(GPT2Model(TINY), _serve_cfg(
        slots=3, page_len=8, telemetry_path=tmp_path,
        quantization={"weights": "int8", "kv": "int8"}))
    rng = np.random.default_rng(7)
    reqs = []
    for wave in range(3):
        for i in range(5):
            n = int(rng.integers(1, 24))
            reqs.append(eng.submit(
                list(_tokens(n, seed=100 * wave + i)),
                max_new_tokens=int(rng.integers(1, 9))))
        eng.run_until_idle()
    assert all(r.error is None for r in reqs)
    eng.telemetry.compile_monitor.sample()
    reg = eng.telemetry.registry
    for prog in ("decode_step", "prefill", "copy_page"):
        assert reg.counter("recompiles_total").value(program=prog) == 0
    assert eng._decode_fn._cache_size() == 1
    assert eng._prefill_fn._cache_size() == 1
    eng.close()


# ---------------------------------------------------------------------------
# COW + prefix eviction over quantized pages
# ---------------------------------------------------------------------------


def test_quant_cow_copies_scale_sidecars():
    """copy_page must move the scale rows WITH the int8 rows, or the
    copied page dequantizes with the wrong scales."""
    eng = ServeEngine(GPT2Model(TINY), _serve_cfg(
        page_len=8, quantization={"kv": "int8"}))
    r = eng.submit(list(_tokens(10, seed=40)), max_new_tokens=2)
    eng.run_until_idle()
    assert r.error is None
    # snapshot before the call: copy_fn DONATES the cache
    before = {k: np.asarray(v) for k, v in eng.cache.items()}
    src, dst = 1, eng.cache_spec.pages - 1
    eng.cache = eng._copy_fn(eng.cache, np.int32(src), np.int32(dst))
    for key in ("k", "v", "k_scale", "v_scale"):
        np.testing.assert_array_equal(
            np.asarray(eng.cache[key][:, dst]), before[key][:, src])
    np.testing.assert_array_equal(np.asarray(eng.cache["lengths"]),
                                  before["lengths"])
    eng.close()


def test_quant_prefix_cow_eviction_accounting():
    """Prefix sharing + divergent-append COW + leaf eviction under the
    quantized pool: streams match the no-prefix quantized run token
    for token (the COW'd page carries its scales), and the pool's
    refcounts drain clean."""
    model = GPT2Model(TINY)
    params = model.init(jax.random.PRNGKey(0))
    # IDENTICAL prompts (the existing COW test's shape): sharing runs
    # down INTO the partial tail page, so each later admission COWs it
    # before its divergent append
    prompt = list(_tokens(13, seed=50))         # 1 full + 4-token tail
    prompts = [prompt] * 3
    quant = {"weights": "int8", "kv": "int8"}

    def run(prefix_cache):
        eng = ServeEngine(model, _serve_cfg(
            page_len=8, prefix_cache=prefix_cache, quantization=quant),
            params=params)
        rs = [eng.submit(p, max_new_tokens=5) for p in prompts]
        eng.run_until_idle()
        assert all(r.error is None for r in rs)
        out = [r.tokens for r in rs]
        cow = eng.prefix.cow if eng.prefix else 0
        hits = eng.prefix.hits if eng.prefix else 0
        eng.prefix and eng.prefix.clear()
        assert eng.pool.refs == {}, eng.pool.refs
        eng.close()
        return out, cow, hits

    on, cow, hits = run(True)
    off, _, _ = run(False)
    # the COW'd shared page dequantizes identically to the original:
    # prefix on/off stay token-identical on the quantized engine too
    assert on == off
    assert hits == 2 and cow >= 1
    # eviction under pool pressure: a pool too small to hold the
    # prefix cache + live slots still serves (leaf-LRU eviction frees
    # quantized pages), accounting clean
    eng = ServeEngine(model, _serve_cfg(
        slots=2, page_len=8, pages=8, quantization=quant),
        params=params)
    rs = [eng.submit(list(_tokens(12, seed=60 + i)), max_new_tokens=3)
          for i in range(5)]
    eng.run_until_idle()
    assert all(r.error is None for r in rs)
    eng.prefix.clear()
    assert eng.pool.refs == {}
    eng.close()


# ---------------------------------------------------------------------------
# quantized-draft speculation
# ---------------------------------------------------------------------------


@pytest.mark.parametrize("k", [1, 4])
def test_quant_spec_draft_stream_parity(k):
    """Speculation under full quantization (int8 target weights, int8
    KV pages, int8 DRAFT weights — the 'quantized draft is nearly
    free' composition): the speculative greedy stream equals the
    non-speculative stream of the SAME quantized engine at k in
    {1, 4}."""
    model = GPT2Model(TINY)
    params = model.init(jax.random.PRNGKey(0))
    prompts = [list(_tokens(n, seed=70 + n)) for n in (2, 7, 12)]
    quant = {"weights": "int8", "kv": "int8"}
    base = _streams(model, params,
                    dict(page_len=8, quantization=quant), prompts,
                    gen=2 * (k + 1) + 1)
    spec = _streams(
        model, params,
        dict(page_len=8, quantization=quant, speculate_k=k,
             draft={"d_model": 32, "n_layer": 2, "n_head": 4}),
        prompts, gen=2 * (k + 1) + 1, draft_params=params)
    assert spec == base
    # unpaged weights-only arm composes with speculation too
    b2 = _streams(model, params,
                  dict(quantization={"weights": "int8"}), prompts,
                  gen=2 * (k + 1) + 1)
    s2 = _streams(
        model, params,
        dict(quantization={"weights": "int8"}, speculate_k=k,
             draft={"d_model": 32, "n_layer": 2, "n_head": 4}),
        prompts, gen=2 * (k + 1) + 1, draft_params=params)
    assert s2 == b2


def test_quant_spec_draft_params_are_quantized():
    model = GPT2Model(TINY)
    eng = ServeEngine(model, _serve_cfg(
        quantization={"weights": "int8"}, speculate_k=2,
        draft={"d_model": 32, "n_layer": 2, "n_head": 4}))
    assert eng.draft_params["blocks"]["qkv_w"].dtype == jnp.int8
    # the draft cache keeps the master dtype (slot layout, fp rollback)
    assert eng._draft_cache["k"].dtype == jnp.float32
    # param-bytes plane counts target + draft (both quantized)
    assert eng.param_bytes == param_nbytes(eng.params) + \
        param_nbytes(eng.draft_params)
    eng.close()


def test_quant_tp_dp_sharded_matches_single_device():
    """The sharding story survives quantization: int8 weights' scale
    rows keep the Megatron column split, int8 pages + sidecars keep
    the DP-pages/TP-heads split — dp2×tp2 streams == single device."""
    from deepspeed_tpu.parallel import build_mesh
    model = GPT2Model(TINY_FLASH)
    params = model.init(jax.random.PRNGKey(0))
    prompts = [list(_tokens(5, seed=i)) for i in range(4)]
    quant = {"weights": "int8", "kv": "int8"}

    def run(mesh):
        eng = ServeEngine(model, _serve_cfg(
            page_len=8, quantization=quant), mesh=mesh, params=params)
        rs = [eng.submit(p, max_new_tokens=6) for p in prompts]
        eng.run_until_idle()
        assert all(r.error is None for r in rs)
        toks = [r.tokens for r in rs]
        eng.close()
        return toks

    base = run(None)
    sharded = run(build_mesh(dp=2, tp=2, devices=jax.devices()[:4]))
    assert base == sharded


# ---------------------------------------------------------------------------
# config validation + memory plane + tooling
# ---------------------------------------------------------------------------


def test_quant_config_validation():
    ok = DeepSpeedServingConfig({"serving": {
        "page_len": 8, "quantization": {"weights": "int8",
                                        "kv": "int8"}}})
    assert ok.quantization == {"weights": "int8", "kv": "int8"}
    dflt = DeepSpeedServingConfig({"serving": {}})
    assert dflt.quantization == {"weights": "fp16", "kv": "fp16"}
    with pytest.raises(DeepSpeedConfigError, match="unknown key"):
        DeepSpeedServingConfig({"serving": {
            "quantization": {"wieghts": "int8"}}})
    with pytest.raises(DeepSpeedConfigError, match="fp16"):
        DeepSpeedServingConfig({"serving": {
            "quantization": {"weights": "int4"}}})
    with pytest.raises(DeepSpeedConfigError, match="page_len"):
        DeepSpeedServingConfig({"serving": {
            "quantization": {"kv": "int8"}}})
    # page_len beyond the kernels' one-scale-lane-per-row limit must
    # fail at config parse, not on the first decode tick (the fp pool
    # keeps accepting any page_len)
    with pytest.raises(DeepSpeedConfigError, match="128"):
        DeepSpeedServingConfig({"serving": {
            "page_len": 256, "quantization": {"kv": "int8"}}})
    DeepSpeedServingConfig({"serving": {"page_len": 256}})
    with pytest.raises(DeepSpeedConfigError, match="dict"):
        DeepSpeedServingConfig({"serving": {"quantization": "int8"}})


def test_quant_memory_gauges_flow_to_summarize(tmp_path, capsys):
    from deepspeed_tpu.telemetry.cli import summarize
    model = GPT2Model(TINY)
    params = model.init(jax.random.PRNGKey(0))

    def run(tel, quant):
        eng = ServeEngine(model, _serve_cfg(
            page_len=8, telemetry_path=tel, flush_interval_ticks=2,
            quantization=quant), params=params)
        eng.submit(list(_tokens(6, seed=80)), max_new_tokens=4)
        eng.run_until_idle()
        reg = eng.telemetry.registry
        pb = reg.gauge("serve_param_bytes").value()
        kb = reg.gauge("serve_kv_bytes").value()
        assert pb == eng.param_bytes and kb == eng.kv_bytes
        assert kb == eng.cache_spec.bytes
        eng.close()
        return pb, kb

    fp_dir, q_dir = tmp_path / "fp", tmp_path / "q"
    pb_fp, kb_fp = run(fp_dir, None)
    pb_q, kb_q = run(q_dir, {"weights": "int8", "kv": "int8"})
    # the whole point, measured on the exported plane
    assert pb_fp / pb_q >= 1.8
    assert kb_fp / kb_q >= 2.0
    report = summarize(os.path.join(str(q_dir), "events.jsonl"))
    out = capsys.readouterr().out
    assert report["serve_param_bytes"] == pb_q
    assert report["serve_kv_bytes"] == kb_q
    assert "serving memory" in out


def test_benchgate_quant_ratio_is_higher_better():
    from tools.benchgate import compare, is_lower_better
    assert not is_lower_better("serve_quant_admitted_ratio")
    fresh = {"metric": "serve_quant_admitted_ratio", "value": 1.2}
    base = {"metric": "serve_quant_admitted_ratio", "value": 2.9}
    assert compare(fresh, base)["regressed"]
    assert not compare(base, fresh)["regressed"]


def test_bench_serve_quant_smoke(tmp_path):
    import importlib.util
    path = os.path.join(os.path.dirname(__file__), "..",
                        "bench_serve.py")
    spec = importlib.util.spec_from_file_location(
        "bench_serve_for_quant_test", path)
    bench_serve = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(bench_serve)
    rec = bench_serve.run_quant_ab(
        kv_budget_slots=2, max_seq_len=32, page_len=8, slots=32,
        n_requests=48, out_dir=str(tmp_path))
    assert rec["metric"] == "serve_quant_admitted_ratio"
    # the acceptance bars: >= 2x admitted at fixed KV bytes with 0
    # truncations; params HBM >= 1.8x down on the weights leg
    assert rec["value"] >= 2.0
    assert rec["truncations"] == 0
    assert rec["weights"]["params_hbm_ratio"] >= 1.8
    # agreement is REPORTED (and high on this seed) — never == 1.0
    # asserted
    assert rec["token_agreement_vs_fp"]["kv_int8"] >= 0.9
    assert rec["token_agreement_vs_fp"]["weights_int8"] >= 0.9
    art = json.load(open(os.path.join(str(tmp_path),
                                      "BENCH_serve_quant.json")))
    assert art["value"] == rec["value"]
