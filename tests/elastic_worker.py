"""Worker script for the elastic-training e2e tests (and a template for
``bench.py --elastic-smoke``): trains a tiny linear model through the
engine's OWN data-iterator chain (DeepSpeedDataLoader → RepeatingLoader
→ DevicePrefetcher), records per-step losses and every PRODUCED batch's
sample indices, checkpoints every step, and optionally hard-kills
itself mid-run on the first attempt (``DS_ELASTIC_RESTART=0``).

The dp width comes from ``DS_ELASTIC_WORLD_SLOTS`` (the supervisor's
export), so a shrunk relaunch automatically re-forms a smaller mesh and
the reshard-on-load checkpoint restore does the rest.

argv: out_dir ckpt_dir total_steps crash_at [default_slots]
  crash_at > 0: os._exit(3) after completing (and checkpointing) step
  crash_at, first attempt only — a hard kill, not a graceful close, so
  prefetched in-flight batches are genuinely abandoned.
"""
import json
import os
import sys

import jax

jax.config.update("jax_platforms", "cpu")

import numpy as np  # noqa: E402

import deepspeed_tpu  # noqa: E402
from deepspeed_tpu.parallel import build_mesh  # noqa: E402
from deepspeed_tpu.runtime.dataloader import (DeepSpeedDataLoader,  # noqa: E402
                                              RepeatingLoader)
from deepspeed_tpu.runtime.module import TrainModule  # noqa: E402

HIDDEN = 8
GLOBAL_BS = 8
DATASET_N = 48  # 6 batches/epoch: multi-epoch runs exercise reshuffle


class TinyModel(TrainModule):
    def init(self, rng):
        import jax.numpy as jnp
        k1, _ = jax.random.split(rng)
        return {"w": jax.random.normal(k1, (HIDDEN, HIDDEN),
                                       jnp.float32) * 0.1,
                "b": jnp.zeros((HIDDEN,), jnp.float32)}

    def loss_fn(self, params, batch, rng, train=True):
        import jax.numpy as jnp
        x, y = batch
        h = x @ params["w"].astype(x.dtype) + params["b"].astype(x.dtype)
        return jnp.mean((h.astype(jnp.float32)
                         - y.astype(jnp.float32)) ** 2)


def build_dataset():
    rng = np.random.default_rng(0)
    xs = rng.standard_normal((DATASET_N, HIDDEN)).astype(np.float32)
    # feature 0 IS the sample index — the identity channel the
    # sample-exactness assertions read back out of the collate log
    xs[:, 0] = np.arange(DATASET_N, dtype=np.float32)
    return [(xs[i], (0.5 * xs[i]).astype(np.float32))
            for i in range(DATASET_N)]


def main():
    out_dir, ckpt_dir = sys.argv[1], sys.argv[2]
    total_steps = int(sys.argv[3])
    crash_at = int(sys.argv[4])
    default_slots = int(sys.argv[5]) if len(sys.argv) > 5 else 1
    restart = int(os.environ.get("DS_ELASTIC_RESTART", "0"))
    slots = int(os.environ.get("DS_ELASTIC_WORLD_SLOTS", default_slots))
    dp = max(min(slots, len(jax.devices())), 1)

    os.makedirs(out_dir, exist_ok=True)
    samples_log = open(
        os.path.join(out_dir, f"samples_r{restart}.jsonl"), "a")

    def collate(samples):
        xs = np.stack([np.asarray(s[0]) for s in samples])
        ys = np.stack([np.asarray(s[1]) for s in samples])
        # production-order log: prefetched-but-unconsumed batches appear
        # here too — the assertions trim to the consumed count
        samples_log.write(
            json.dumps([int(v) for v in xs[:, 0]]) + "\n")
        samples_log.flush()
        return (xs, ys)

    mesh = build_mesh(dp=dp, devices=jax.devices()[:dp])
    cfg = {
        "train_batch_size": GLOBAL_BS,
        "gradient_accumulation_steps": 1,
        "steps_per_print": 10 ** 9,
        # fp32 end to end: the dp4-vs-dp2 trajectory equivalence
        # tolerates only reduction-order noise
        "optimizer": {"type": "Adam", "params": {"lr": 1e-2}},
        "data_prefetch": {"enabled": True, "depth": 2},
    }
    engine, _, _, _ = deepspeed_tpu.initialize(
        model=TinyModel(), config=cfg, mesh=mesh)
    engine.training_dataloader = RepeatingLoader(DeepSpeedDataLoader(
        build_dataset(), batch_size=GLOBAL_BS, collate_fn=collate,
        shuffle=True, seed=5))

    path, _ = engine.load_checkpoint(ckpt_dir)  # fallback chain; None=fresh
    start = engine.global_steps
    traj = open(os.path.join(out_dir, f"traj_r{restart}.jsonl"), "a")
    for step in range(start, total_steps):
        loss = float(np.asarray(engine.train_batch()))
        engine.save_checkpoint(ckpt_dir)
        traj.write(json.dumps({"step": step, "loss": loss, "dp": dp})
                   + "\n")
        traj.flush()
        if crash_at and restart == 0 and step + 1 == crash_at:
            os._exit(3)  # hard kill: no close(), prefetched batches die
    engine.close()
    print("ELASTIC_WORKER_DONE", flush=True)


if __name__ == "__main__":
    main()
