"""Worker body for the 2-process × 4-device CPU integration test — the
analogue of the reference's @distributed_test harness
(reference: tests/unit/common.py:14-100, which forks NCCL workers on
localhost).  Launched by test_multiprocess.py with the launcher env
contract set; everything here goes through the PUBLIC multi-host path:
deepspeed_tpu.initialize -> init_distributed -> per-process batches ->
sharded checkpoint save/load.
"""
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))

import jax

jax.config.update("jax_platforms", "cpu")

import numpy as np  # noqa: E402

import deepspeed_tpu  # noqa: E402
from deepspeed_tpu.parallel import build_mesh  # noqa: E402
from simple_model import SimpleModel  # noqa: E402


def main():
    out_dir = sys.argv[1]
    # initialize() consumes JAX_COORDINATOR_ADDRESS / JAX_NUM_PROCESSES /
    # JAX_PROCESS_ID from the env (the launcher contract)
    deepspeed_tpu.init_distributed()
    assert jax.process_count() == 2, jax.process_count()
    assert len(jax.devices()) == 8, jax.devices()
    pid = jax.process_index()

    mesh = build_mesh(dp=8, devices=jax.devices())
    cfg = {
        "train_micro_batch_size_per_gpu": 2,
        "gradient_accumulation_steps": 2,
        "steps_per_print": 10 ** 9,
        "bf16": {"enabled": True},
        "optimizer": {"type": "Adam", "params": {"lr": 1e-2}},
        "zero_optimization": {"stage": 2},
    }
    engine, _, _, _ = deepspeed_tpu.initialize(
        model=SimpleModel(hidden_dim=32), config=cfg, mesh=mesh)

    # per-process batch slices: global batch 32, each process feeds 16
    rng = np.random.default_rng(0)
    gx = rng.normal(size=(32, 32)).astype(np.float32)
    gy = (0.5 * gx).astype(np.float32)
    lo, hi = (0, 16) if pid == 0 else (16, 32)
    losses = []
    for _ in range(5):
        loss = engine.train_batch((gx[lo:hi], gy[lo:hi]))
        losses.append(float(np.asarray(loss)))
    assert losses[-1] < losses[0], losses

    # sharded checkpoint: every process writes its ZeRO shards
    engine.save_checkpoint(out_dir, tag="mp")
    ref = float(np.asarray(engine.train_batch((gx[lo:hi], gy[lo:hi]))))

    engine2, _, _, _ = deepspeed_tpu.initialize(
        model=SimpleModel(hidden_dim=32), config=cfg, mesh=mesh, seed=9)
    path, _ = engine2.load_checkpoint(out_dir, tag="mp")
    assert path is not None
    got = float(np.asarray(engine2.train_batch((gx[lo:hi], gy[lo:hi]))))
    assert abs(got - ref) < 1e-6, (got, ref)

    print(f"WORKER_{pid}_OK loss={losses[-1]:.6f} resume={got:.6f}")


if __name__ == "__main__":
    main()
