"""Workload plane: deterministic loadgen + goodput/SLO accounting.

Covers the contracts docs/serving.md "workload plane" commits to:

* ``Workload.build(seed)`` is byte-deterministic, and two workloads
  differing ONLY in arrival shape serve byte-identical payloads.
* The goodput reader reconstructs per-request phases from completion
  records alone — including pre-PR-17 records without ``arrival_s``
  (regression-pinned) and fleet-ledger records — and tolerates the
  torn final line of a killed run with the skipped count reported.
* The live ``GoodputTracker`` exports through every hub plane, and
  ``telemetry summarize`` reads the verdict back from events.jsonl.
"""
import json
import os

import numpy as np
import pytest

from tools.loadgen.workload import (ArrivalSpec, LengthSpec, Workload,
                                    load_trace, schedule_fingerprint)
from deepspeed_tpu.telemetry.goodput import (GoodputTracker,
                                             phases_from_record,
                                             read_goodput, score)
from deepspeed_tpu.telemetry.cli import summarize


# ---------------------------------------------------------------------------
# workload generation: determinism + the arrival/length algebra
# ---------------------------------------------------------------------------


def test_workload_build_is_byte_deterministic():
    w = Workload(24, arrival=ArrivalSpec("poisson", rate=20.0),
                 prompt_len=LengthSpec("lognormal", median=6.0),
                 gen_tokens=LengthSpec("choice",
                                       choices=((4, 1.0), (12, 3.0))))
    fp = schedule_fingerprint(w.build(seed=7))
    assert fp == schedule_fingerprint(w.build(seed=7))
    assert fp != schedule_fingerprint(w.build(seed=8))


def test_arrival_shape_never_changes_the_payload():
    """The two-generator contract: uniform and burst schedules with
    the same seed serve byte-identical prompts/budgets, so a goodput
    A/B isolates arrival shape and nothing else."""
    kw = dict(prompt_len=LengthSpec("lognormal", median=5.0),
              gen_tokens=LengthSpec(value=6))
    uni = Workload(16, arrival=ArrivalSpec("uniform", period=0.1),
                   **kw).build(seed=3)
    burst = Workload(16, arrival=ArrivalSpec("gamma_burst", rate=10.0,
                                             cv=6.0), **kw).build(seed=3)
    assert [i.prompt for i in uni] == [i.prompt for i in burst]
    assert [i.max_new_tokens for i in uni] \
        == [i.max_new_tokens for i in burst]
    assert [i.at_s for i in uni] != [i.at_s for i in burst]


def test_arrival_kinds():
    rng = np.random.default_rng(0)
    assert ArrivalSpec("uniform", period=0.5).offsets(3, rng) \
        == [0.0, 0.5, 1.0]
    offs = ArrivalSpec("poisson", rate=100.0).offsets(
        50, np.random.default_rng(0))
    assert offs[0] == 0.0 and offs == sorted(offs)
    # trace offsets are normalized to first-arrival-at-t0
    tr = ArrivalSpec("trace", trace=(2.0, 2.5, 4.0))
    assert tr.offsets(3, rng) == [0.0, 0.5, 2.0]
    with pytest.raises(ValueError):
        tr.offsets(4, rng)
    with pytest.raises(ValueError):
        ArrivalSpec("weibull").offsets(1, rng)


def test_gamma_burst_clumps():
    """cv >> 1 must actually produce clumping: many near-zero gaps and
    a max gap far above the mean (that is the entire point of the
    arrival-shape A/B)."""
    offs = ArrivalSpec("gamma_burst", rate=10.0, cv=6.0).offsets(
        200, np.random.default_rng(1))
    gaps = np.diff(offs)
    assert (gaps < 0.01).mean() > 0.5
    assert gaps.max() > 5 * 0.1


def test_mix_template_and_session_gaps():
    w = Workload(8, arrival=ArrivalSpec("uniform", period=0.1),
                 mix=((3, 2), (3, 2), (10, 4)),
                 session_len=4, idle_gap_s=1.0)
    items = w.build(seed=0)
    assert [len(i.prompt) for i in items] == [3, 3, 10] * 2 + [3, 3]
    assert [i.max_new_tokens for i in items] == [2, 2, 4] * 2 + [2, 2]
    # one idle gap inserted at the session boundary, sessions labelled
    assert [i.session for i in items] == [0] * 4 + [1] * 4
    assert items[4].at_s == pytest.approx(0.4 + 1.0)
    # template mix: every prompt starts with the shared prefix
    tw = Workload(6, prompt_len=LengthSpec(value=12),
                  template_ratio=1.0, template_len=8).build(seed=0)
    heads = {i.prompt[:8] for i in tw}
    assert len(heads) == 1
    assert len({i.prompt for i in tw}) == 6   # unique suffixes


def test_load_trace_tolerates_torn_lines(tmp_path):
    p = tmp_path / "trace.jsonl"
    with open(p, "w") as f:
        f.write(json.dumps({"at_s": 0.0, "prompt_len": 4}) + "\n")
        f.write(json.dumps({"at_s": 0.25}) + "\n")
        f.write('{"at_s": 0.5, "prompt_')        # torn final line
    arrival, records = load_trace(str(p))
    assert arrival.kind == "trace" and arrival.trace == (0.0, 0.25)
    assert len(records) == 2
    items = Workload(2, arrival=arrival,
                     prompt_len=LengthSpec(value=4)).build(seed=0)
    assert [i.at_s for i in items] == [0.0, 0.25]


# ---------------------------------------------------------------------------
# trace converter: Azure CSV / Mooncake JSONL -> load_trace shape
# ---------------------------------------------------------------------------


_DATA = os.path.join(os.path.dirname(__file__), "data")


def test_convert_azure_csv_to_trace(tmp_path):
    """Azure LLM-inference CSV rows (ISO timestamps at 7-digit
    precision, blank length cells, a torn timestamp) convert
    tolerantly into the replayable shape load_trace reads."""
    from tools.loadgen.convert import convert_trace
    dst = tmp_path / "azure.jsonl"
    summary = convert_trace(os.path.join(_DATA, "azure_llm_sample.csv"),
                            str(dst))
    assert summary["format"] == "azure"
    assert summary["rows"] == 5 and summary["skipped"] == 1
    arrival, records = load_trace(str(dst))
    assert arrival.kind == "trace" and len(arrival.trace) == 5
    assert arrival.trace[0] == 0.0
    assert list(arrival.trace) == sorted(arrival.trace)
    assert arrival.trace[-1] == pytest.approx(3.27, abs=1e-3)
    assert records[0]["prompt_len"] == 448
    assert records[0]["gen_tokens"] == 84
    assert "prompt_len" not in records[3]      # blank cell dropped
    assert records[3]["gen_tokens"] == 25
    assert "gen_tokens" not in records[4]


def test_convert_mooncake_jsonl_to_trace(tmp_path):
    """Mooncake open-trace JSONL (millisecond timestamps, torn lines,
    rows without lengths) converts tolerantly, and converting the
    OUTPUT again is byte-idempotent (native rows pass through)."""
    from tools.loadgen.convert import convert_trace, detect_format
    src = os.path.join(_DATA, "mooncake_sample.jsonl")
    assert detect_format(src) == "mooncake"
    dst = tmp_path / "mooncake.jsonl"
    summary = convert_trace(src, str(dst))
    assert summary["format"] == "mooncake"
    assert summary["rows"] == 4 and summary["skipped"] == 2
    arrival, records = load_trace(str(dst))
    assert arrival.trace == (0.0, 21.5, 31.0, 45.0)
    assert records[0]["prompt_len"] == 655
    assert records[0]["gen_tokens"] == 52
    assert records[2]["prompt_len"] == 88
    assert "gen_tokens" not in records[2]
    dst2 = tmp_path / "again.jsonl"
    convert_trace(str(dst), str(dst2))
    assert dst2.read_bytes() == dst.read_bytes()


def test_convert_cli_subcommand(tmp_path, monkeypatch, capsys):
    """``python -m tools.loadgen convert`` dispatches past the
    scenario parser; --limit truncates after the time sort."""
    from tools.loadgen.__main__ import main
    dst = tmp_path / "out.jsonl"
    monkeypatch.setattr("sys.argv", [
        "loadgen", "convert",
        os.path.join(_DATA, "mooncake_sample.jsonl"), str(dst),
        "--format", "mooncake", "--limit", "2"])
    main()
    summary = json.loads(capsys.readouterr().out)
    assert summary["rows"] == 2
    arrival, _ = load_trace(str(dst))
    assert arrival.trace == (0.0, 21.5)


def test_convert_unknown_format_raises(tmp_path):
    from tools.loadgen.convert import convert_trace
    with pytest.raises(ValueError, match="unknown trace format"):
        convert_trace(os.path.join(_DATA, "mooncake_sample.jsonl"),
                      str(tmp_path / "x.jsonl"), fmt="splitwise")


# ---------------------------------------------------------------------------
# goodput: phase attribution + SLO scoring from records alone
# ---------------------------------------------------------------------------


def _serve_rec(rid, ttft, tpot, tokens=5, **extra):
    rec = {"kind": "serve_request", "rid": rid, "tokens": tokens,
           "queue_wait_s": 0.01, "ttft_s": ttft,
           "decode_tokens": tokens - 1,
           "decode_s_sum": tpot * (tokens - 1)}
    rec.update(extra)
    return rec


def test_score_verdicts():
    phases = [phases_from_record(r) for r in [
        _serve_rec(1, ttft=0.05, tpot=0.02),            # good
        _serve_rec(2, ttft=0.50, tpot=0.02),            # ttft miss
        _serve_rec(3, ttft=0.05, tpot=0.30),            # tpot miss
        _serve_rec(4, ttft=0.05, tpot=0.0, tokens=1,
                   decode_tokens=0, decode_s_sum=0.0),  # vacuous tpot
        _serve_rec(5, ttft=0.05, tpot=0.02,
                   error="ReplicaFailure('boom')"),     # errored
    ]]
    rep = score(phases, slo_ttft_s=0.1, slo_tpot_s=0.1)
    assert rep["requests"] == 5 and rep["failed"] == 1
    assert rep["ttft_miss"] == 1 and rep["tpot_miss"] == 1
    # good = {1, 4}: one-token request passes TPOT vacuously; the
    # errored request counts in n but can never be good
    assert rep["goodput"] == pytest.approx(2 / 5)
    assert rep["ttft_p99_s"] == pytest.approx(0.5, rel=0.05)


def test_phases_from_fleet_ledger_record():
    """Fleet-ledger completions carry no decode_s_sum; TPOT is
    reconstructed as (total - queue_wait - ttft) / (tokens - 1)."""
    ph = phases_from_record({
        "kind": "fleet_request", "rid": 9, "tokens": 5,
        "queue_wait_s": 0.2, "ttft_s": 0.1, "total_s": 0.7,
        "failovers": 1, "started": True})
    assert ph["tpot_s"] == pytest.approx(0.4 / 4)
    assert ph["queue_wait_s"] == pytest.approx(0.2)
    # other ledger kinds are not requests
    assert phases_from_record({"kind": "fleet_submit", "rid": 9}) is None
    assert phases_from_record({"kind": "replica_dead"}) is None


def test_read_goodput_tolerates_torn_tail(tmp_path):
    """A killed run tears its final events.jsonl line mid-write; the
    reader skips it and REPORTS the skip, never silently drops it."""
    p = tmp_path / "events.jsonl"
    with open(p, "w") as f:
        f.write(json.dumps(_serve_rec(1, ttft=0.05, tpot=0.02)) + "\n")
        f.write(json.dumps(_serve_rec(2, ttft=0.50, tpot=0.02)) + "\n")
        f.write(json.dumps(_serve_rec(3, ttft=0.05, tpot=0.02))[:37])
    rep = read_goodput(str(p), slo_ttft_s=0.1, slo_tpot_s=0.1)
    assert rep["skipped_lines"] == 1
    assert rep["requests"] == 2
    assert rep["goodput"] == pytest.approx(0.5)


def test_summarize_tolerates_records_without_arrival_s(tmp_path):
    """Regression pin: pre-PR-17 serve_request records carry no
    arrival_s — summarize must still parse them, report the goodput
    row from the SLO scalars, and leave the arrival span None."""
    p = tmp_path / "events.jsonl"
    with open(p, "w") as f:
        f.write(json.dumps({
            "kind": "sync", "step": 10,
            "scalars": {"serve_goodput": 0.5,
                        "serve_goodput_requests": 2.0,
                        "serve_slo_ttft_s": 0.1,
                        "serve_slo_tpot_s": 0.1}}) + "\n")
        for rec in (_serve_rec(1, ttft=0.05, tpot=0.02),
                    _serve_rec(2, ttft=0.50, tpot=0.02)):
            rec.pop("arrival_s", None)
            f.write(json.dumps(rec) + "\n")
    rep = summarize(str(p))
    assert rep["serve_arrival_span_s"] is None
    assert rep["serve_goodput"] == pytest.approx(0.5)
    # the record-derived verdict independently agrees with the scalar
    assert rep["serve_goodput_from_records"] == pytest.approx(0.5)
    assert rep["serve_slo_ttft_miss"] == 1
    assert rep["serve_slo_tpot_miss"] == 0
    assert rep["serve_tpot_p99_s"] == pytest.approx(0.02)


def test_goodput_tracker_round_trips_through_the_hub(tmp_path):
    """Live tracker -> hub planes -> events.jsonl -> summarize: the
    counters/gauge land in the registry, the scalar flush lands in the
    artifact, and the summarize goodput section reads it back."""
    from deepspeed_tpu.telemetry import TelemetryHub
    hub = TelemetryHub(str(tmp_path), compile_events=False,
                       memory=False)
    tracker = GoodputTracker(0.1, 0.1, hub=hub)
    assert tracker.observe(phases_from_record(
        _serve_rec(1, ttft=0.05, tpot=0.02))) is True
    assert tracker.observe(phases_from_record(
        _serve_rec(2, ttft=0.50, tpot=0.02))) is False
    rep = tracker.flush(step=2)
    assert rep["goodput"] == pytest.approx(0.5)
    assert hub.registry.counter(
        "serve_slo_ttft_miss_total").value() == 1
    assert hub.registry.gauge("serve_goodput_ratio").value() \
        == pytest.approx(0.5)
    hub.close()
    out = summarize(os.path.join(str(tmp_path), "events.jsonl"))
    assert out["serve_goodput"] == pytest.approx(0.5)
    assert out["serve_goodput_requests"] == 2
    assert out["serve_slo_ttft_s"] == pytest.approx(0.1)


def test_engine_records_carry_arrival_s(tmp_path):
    """Post-PR-17 engines stamp the open-loop submit offset into every
    completion record, so queueing is reconstructible from the
    artifact alone."""
    from deepspeed_tpu.inference import ServeEngine
    from deepspeed_tpu.models.gpt2 import GPT2Config, GPT2Model
    model = GPT2Model(GPT2Config(
        vocab_size=128, n_positions=64, d_model=32, n_layer=2,
        n_head=4, remat=None, attn_impl="dense"))
    eng = ServeEngine(model, {
        "serving": {"slots": 2, "max_seq_len": 32, "prefill_len": 4},
        "telemetry": {"enabled": True, "output_path": str(tmp_path),
                      "memory": False}})
    eng.submit([1, 2, 3], max_new_tokens=2)
    eng.submit([4, 5], max_new_tokens=2)
    eng.run_until_idle()
    eng.close()
    rep = read_goodput(os.path.join(str(tmp_path), "events.jsonl"),
                       slo_ttft_s=60.0, slo_tpot_s=60.0)
    assert rep["requests"] == 2 and rep["goodput"] == 1.0
    arrivals = [r["arrival_s"] for r in _records(tmp_path)
                if r.get("kind") == "serve_request"]
    assert len(arrivals) == 2
    assert all(a is not None and a >= 0.0 for a in arrivals)
    assert arrivals == sorted(arrivals)


def _records(tel_dir):
    with open(os.path.join(str(tel_dir), "events.jsonl")) as f:
        return [json.loads(line) for line in f if line.strip()]


# ---------------------------------------------------------------------------
# multi-tenant dimension (docs/serving.md "multi-tenant serving")
# ---------------------------------------------------------------------------


def test_tenants_leave_payload_and_arrivals_bitwise_unchanged():
    """The third-generator contract: enabling TenantSpec draws tenant
    ids from their own rng stream, so the prompts, budgets, AND
    arrival offsets of a tenantless build stay byte for byte what
    they were — a lora A/B isolates the tenant dimension."""
    from tools.loadgen.workload import TenantSpec
    kw = dict(arrival=ArrivalSpec("gamma_burst", rate=10.0, cv=4.0),
              prompt_len=LengthSpec("lognormal", median=5.0),
              gen_tokens=LengthSpec(value=6))
    off = Workload(24, **kw).build(seed=5)
    on = Workload(24, tenants=TenantSpec(n_tenants=6), **kw).build(seed=5)
    assert [i.prompt for i in off] == [i.prompt for i in on]
    assert [i.max_new_tokens for i in off] == [i.max_new_tokens for i in on]
    assert [i.at_s for i in off] == [i.at_s for i in on]
    assert all(i.tenant == 0 for i in off)
    assert all(1 <= i.tenant <= 6 for i in on)


def test_tenant_sequence_is_arrival_shape_independent():
    """Same seed, different arrival process: the tenant draw must not
    move — it rides its own stream, like the payload."""
    from tools.loadgen.workload import TenantSpec
    kw = dict(tenants=TenantSpec(n_tenants=8, s=1.2),
              prompt_len=LengthSpec(value=5),
              gen_tokens=LengthSpec(value=4))
    uni = Workload(32, arrival=ArrivalSpec("uniform", period=0.1),
                   **kw).build(seed=9)
    burst = Workload(32, arrival=ArrivalSpec("gamma_burst", rate=5.0,
                                             cv=6.0), **kw).build(seed=9)
    assert [i.tenant for i in uni] == [i.tenant for i in burst]


def test_tenant_zipf_shape_and_determinism():
    """The Zipf draw is deterministic per seed and actually skewed:
    tenant 1 is the modal tenant and every id is in range."""
    from tools.loadgen.workload import TenantSpec
    w = Workload(300, arrival=ArrivalSpec("uniform", period=0.0),
                 prompt_len=LengthSpec(value=4),
                 gen_tokens=LengthSpec(value=4),
                 tenants=TenantSpec(n_tenants=10, s=1.5))
    ten = [i.tenant for i in w.build(seed=2)]
    assert ten == [i.tenant for i in w.build(seed=2)]
    assert set(ten) <= set(range(1, 11))
    counts = {t: ten.count(t) for t in set(ten)}
    assert max(counts, key=counts.get) == 1
    assert counts[1] > counts.get(10, 0)
