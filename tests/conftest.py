"""Test harness configuration.

Mirrors the reference's multi-process-without-a-cluster strategy
(reference: tests/unit/common.py:14-100 forks NCCL workers on localhost):
on TPU-less CI we instead expose an 8-device virtual CPU mesh via
``--xla_force_host_platform_device_count`` so every sharding/collective
path (ZeRO, pipeline ppermute, tensor-parallel psum) executes for real,
single-process SPMD, no cluster needed.

Note: this image's sitecustomize force-registers the ``axon`` TPU platform
before conftest runs, so the env var JAX_PLATFORMS alone is not enough —
we must also override jax.config before any backend initializes.
"""
import os

_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (
        _flags + " --xla_force_host_platform_device_count=8"
    ).strip()

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")
assert len(jax.devices()) == 8, (
    f"expected 8 virtual CPU devices, got {jax.devices()}")
