"""Test harness configuration.

Mirrors the reference's multi-process-without-a-cluster strategy
(reference: tests/unit/common.py:14-100 forks NCCL workers on localhost):
on TPU-less CI we instead expose an 8-device virtual CPU mesh via
``--xla_force_host_platform_device_count`` so every sharding/collective
path (ZeRO, pipeline ppermute, tensor-parallel psum) executes for real,
single-process SPMD, no cluster needed.

Note: this image's sitecustomize force-registers the ``axon`` TPU platform
before conftest runs, so the env var JAX_PLATFORMS alone is not enough —
we must also override jax.config before any backend initializes.
"""
import os

_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (
        _flags + " --xla_force_host_platform_device_count=8"
    ).strip()

# Checkpoint fsync off for the suite: unit tests simulate process death
# (which the page cache survives), and this image's 9p filesystem makes
# each fsync cost ~50ms/file — ~1.3s per tiny save.  The production
# default stays ON; tests/test_resilience.py pins that default.
os.environ.setdefault("DS_CKPT_FSYNC", "0")
# Same rule for the disk offload tier's per-leaf state files: its
# tmp+rename + CRC plane is what the tests exercise; the ~50ms/fsync 9p
# cost is not.  Production default stays ON;
# tests/test_disk_offload.py::test_fsync_on_by_default pins it.
os.environ.setdefault("DS_DISK_FSYNC", "0")

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")
assert len(jax.devices()) == 8, (
    f"expected 8 virtual CPU devices, got {jax.devices()}")

# ---------------------------------------------------------------------------
# Test-tier guard.  pytest.ini defines two tiers (core = `-m "not slow"`,
# full = everything); this guard keeps the core tier honest by failing any
# test that builds a compile-bound mesh without carrying the ``slow`` marker,
# and (opt-in, for CI) any unmarked test whose call phase overruns a wall
# budget.  Mirrors the reference's CI split into per-PR unit jobs vs nightly
# model tests (reference: azure-pipelines.yml runs tests/unit per PR and
# gates tests/model behind a nightly trigger).
# ---------------------------------------------------------------------------
import pytest  # noqa: E402

HEAVY_PIPE = 4  # pp>=4 programs compile multi-stage scans: always slow-tier

_current_item = None
_duration_offenders = []


def heavy_mesh_violation(mesh_shape, has_slow_marker):
    """Tier policy, pure so tests can exercise it: building a mesh with a
    ``pipe`` axis >= HEAVY_PIPE means compiling a multi-stage pipeline scan
    (the dominant compile cost in this suite — see pytest.ini's slow-tier
    description); such a test must be in the slow tier."""
    pipe = int(mesh_shape.get("pipe", 1))
    if pipe >= HEAVY_PIPE and not has_slow_marker:
        return (f"this test builds a pipe={pipe} mesh but is not marked "
                "@pytest.mark.slow; pp>=4 programs are compile-bound and "
                "belong in the slow tier (see pytest.ini / tests/README.md)")
    return None


def duration_violation(duration_s, has_slow_marker, budget_s):
    """Opt-in (TIER_GUARD=1) wall-clock policy: an unmarked test whose call
    phase overruns the budget must move to the slow tier."""
    if not has_slow_marker and duration_s > budget_s:
        return (f"call phase took {duration_s:.1f}s > TIER_GUARD_SECONDS="
                f"{budget_s:.0f}s without @pytest.mark.slow")
    return None


@pytest.fixture(autouse=True)
def _tier_guard_track_item(request):
    global _current_item
    _current_item = request.node
    yield
    _current_item = None


# Mesh construction goes through __new__ (cached), not __init__.
_orig_mesh_new = jax.sharding.Mesh.__new__


def _guarded_mesh_new(cls, *args, **kwargs):
    mesh = _orig_mesh_new(cls, *args, **kwargs)
    item = _current_item
    if item is None:
        return mesh
    try:
        shape = dict(mesh.shape)
    except Exception:
        return mesh
    msg = heavy_mesh_violation(
        shape, item.get_closest_marker("slow") is not None)
    if msg:
        pytest.fail(msg, pytrace=False)
    return mesh


jax.sharding.Mesh.__new__ = _guarded_mesh_new


def pytest_sessionstart(session):
    """jaxlint --contracts-only pre-flight: the cross-artifact contract
    rules (stages, metrics, fault points, config keys — JL102-JL104)
    run in seconds and catch docs/code drift before the suite spends
    minutes compiling.  DS_SKIP_LINT_PREFLIGHT=1 skips it (while
    iterating on a fix the gate itself is pinning)."""
    if os.environ.get("DS_SKIP_LINT_PREFLIGHT") == "1":
        return
    import subprocess
    import sys
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    proc = subprocess.run(
        [sys.executable, "-m", "tools.jaxlint", "--contracts-only",
         "deepspeed_tpu", "tools"],
        cwd=repo, capture_output=True, text=True, timeout=60)
    if proc.returncode != 0:
        pytest.exit("jaxlint --contracts-only pre-flight failed "
                    "(DS_SKIP_LINT_PREFLIGHT=1 to bypass):\n"
                    + proc.stdout + proc.stderr, returncode=1)


def pytest_runtest_logreport(report):
    if os.environ.get("TIER_GUARD") != "1":
        return
    if report.when != "call":
        return
    budget = float(os.environ.get("TIER_GUARD_SECONDS", "60"))
    msg = duration_violation(
        report.duration, "slow" in report.keywords, budget)
    if msg:
        _duration_offenders.append(f"{report.nodeid}: {msg}")


def pytest_sessionfinish(session, exitstatus):
    if _duration_offenders:
        tr = session.config.pluginmanager.get_plugin("terminalreporter")
        lines = ["tier guard: unmarked tests overran the core-tier budget "
                 "(mark them @pytest.mark.slow):"] + _duration_offenders
        for line in lines:
            if tr is not None:
                tr.write_line(line, red=True)
            else:
                print(line)
        if session.exitstatus == 0:
            session.exitstatus = 1
