"""Launcher end-to-end: ``bin/ds`` → per-node launch → user script, all on
localhost (the reference tests only the parsing layer, test_run.py; the
spawn chain itself is exercised here — single-node, ``--launcher local``).
Also the argparse-injection analogue of reference test_ds_arguments.py."""
import json
import os
import subprocess
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _run_ds(tmp_path, extra_args, script_body, hostfile_lines=None,
            timeout=60):
    script = tmp_path / "user_script.py"
    script.write_text(script_body)
    out = tmp_path / "out.json"
    args = [sys.executable, os.path.join(REPO, "bin", "ds")]
    if hostfile_lines is not None:
        hf = tmp_path / "hostfile"
        hf.write_text("\n".join(hostfile_lines) + "\n")
        args += ["--hostfile", str(hf)]
    else:
        args += ["--hostfile", str(tmp_path / "missing_hostfile")]
    args += extra_args + [str(script), str(out)]
    env = dict(os.environ)
    env["PYTHONPATH"] = REPO + os.pathsep + env.get("PYTHONPATH", "")
    proc = subprocess.run(args, env=env, timeout=timeout,
                          capture_output=True, text=True)
    assert proc.returncode == 0, proc.stderr[-2000:]
    return json.loads(out.read_text())


_ENV_DUMP = """\
import json, os, sys
keys = ["RANK", "WORLD_SIZE", "MASTER_ADDR", "MASTER_PORT",
        "JAX_COORDINATOR_ADDRESS", "JAX_NUM_PROCESSES", "JAX_PROCESS_ID",
        "TPU_VISIBLE_CHIPS"]
json.dump({k: os.environ.get(k) for k in keys}, open(sys.argv[1], "w"))
"""


def test_ds_single_node_hostfile_spawn_chain(tmp_path):
    """hostfile path: runner encodes world info, launch decodes it and
    execs the user script with the jax.distributed env contract."""
    env = _run_ds(tmp_path, ["--launcher", "local"], _ENV_DUMP,
                  hostfile_lines=["localhost slots=2"])
    assert env["JAX_PROCESS_ID"] == "0"
    assert env["JAX_NUM_PROCESSES"] == "1"
    assert env["JAX_COORDINATOR_ADDRESS"].startswith("localhost:")
    assert env["RANK"] == "0"
    assert env["TPU_VISIBLE_CHIPS"] == "0,1"


def test_ds_no_hostfile_direct_exec(tmp_path):
    """No hostfile → in-place single-host exec with chip visibility."""
    env = _run_ds(tmp_path, ["--num_gpus", "2"], _ENV_DUMP)
    assert env["TPU_VISIBLE_CHIPS"] == "0,1"
    assert env["RANK"] is None  # no multi-host contract in this mode


def test_ds_num_gpus_slices_slots(tmp_path):
    env = _run_ds(tmp_path, ["--launcher", "local", "--num_gpus", "1"],
                  _ENV_DUMP, hostfile_lines=["localhost slots=4"])
    assert env["TPU_VISIBLE_CHIPS"] == "0"


def test_add_config_arguments_parsing():
    """reference: tests/unit/test_ds_arguments.py — argparse injection."""
    import argparse
    import deepspeed_tpu

    parser = argparse.ArgumentParser()
    parser.add_argument("--local_rank", type=int, default=0)
    parser = deepspeed_tpu.add_config_arguments(parser)

    args = parser.parse_args(
        ["--deepspeed", "--deepspeed_config", "ds.json"])
    assert args.deepspeed is True
    assert args.deepspeed_config == "ds.json"
    assert args.local_rank == 0

    # defaults when absent
    args = parser.parse_args([])
    assert args.deepspeed is False
    assert args.deepspeed_config is None

    # deprecated aliases accepted
    args = parser.parse_args(["--deepscale", "--deepscale_config", "x.json"])
    assert args.deepscale is True
    assert args.deepscale_config == "x.json"


def test_openmpi_runner_command_construction(tmp_path):
    """The openmpi launcher builds ONE mpirun command: -n <nodes>, the
    hostfile, -x env exports, and --node_rank=-1 (resolved per-rank from
    OMPI_COMM_WORLD_RANK) — the reference OpenMPIRunner grammar
    (multinode_runner.py:78-134) minus the CUDA/IB MCA tuning."""
    from deepspeed_tpu.launcher.multinode_runner import (MVAPICHRunner,
                                                         OpenMPIRunner)
    from deepspeed_tpu.launcher.runner import parse_args

    hostfile = tmp_path / "hostfile"
    hostfile.write_text("nodeA slots=4\nnodeB slots=4\n")
    args = parse_args(["--hostfile", str(hostfile), "--launcher",
                       "openmpi", "train.py", "--lr", "0.1"])
    args.master_addr = "nodeA"
    runner = OpenMPIRunner(args, "WORLDINFO")
    cmd = runner.get_cmd({"PYTHONPATH": "/x"},
                         {"nodeA": [0, 1, 2, 3], "nodeB": [0, 1, 2, 3]})
    assert cmd[:3] == ["mpirun", "-n", "2"]
    assert "--hostfile" in cmd and str(hostfile) in cmd
    i = cmd.index("-x")
    assert cmd[i + 1] == "PYTHONPATH=/x"
    assert "--node_rank=-1" in cmd
    assert cmd[-3:] == ["train.py", "--lr", "0.1"]
    assert "--world_info=WORLDINFO" in cmd

    # MVAPICH speaks Hydra's dialect: -ppn/-env/plain hostfile, not
    # orterun's --map-by/-x/slots grammar
    mv = MVAPICHRunner(args, "WORLDINFO")
    mcmd = mv.get_cmd({"PYTHONPATH": "/x"}, {"nodeA": [0], "nodeB": [0]})
    assert mcmd[:5] == ["mpirun", "-n", "2", "-ppn", "1"]
    assert "--map-by" not in mcmd and "-x" not in mcmd
    i = mcmd.index("-env")
    assert "MV2_SMP_USE_CMA" in mcmd and "PYTHONPATH" in mcmd
    hf_path = mcmd[mcmd.index("-hostfile") + 1]
    with open(hf_path) as fh:
        assert fh.read() == "nodeA\nnodeB\n"

    # reference parity: MPI runners reject include/exclude filters
    args2 = parse_args(["--hostfile", str(hostfile), "--launcher",
                        "openmpi", "--include", "nodeA", "train.py"])
    import pytest
    with pytest.raises(ValueError, match="placement"):
        OpenMPIRunner(args2, "W").validate_args()


def test_launch_node_rank_from_mpi_env():
    """--node_rank=-1 resolves from the MPI rank variable (one broadcast
    command per mpirun; each rank self-identifies)."""
    import pytest
    from deepspeed_tpu.launcher.launch import resolve_node_rank

    assert resolve_node_rank(3) == 3
    assert resolve_node_rank(-1, {"OMPI_COMM_WORLD_RANK": "2"}) == 2
    assert resolve_node_rank(-1, {"MV2_COMM_WORLD_RANK": "1"}) == 1
    assert resolve_node_rank(-1, {"PMI_RANK": "0"}) == 0
    with pytest.raises(ValueError, match="MPI rank"):
        resolve_node_rank(-1, {})


# ---------------------------------------------------------------------------
# two-host rehearsal: ds --hostfile → multinode_runner → launch.py →
# jax.distributed, with the ssh/pdsh transport faked to run locally
# ---------------------------------------------------------------------------
_TRAIN_WORKER = """\
import json, os, sys
import jax
jax.config.update("jax_platforms", "cpu")
import numpy as np
import deepspeed_tpu
from deepspeed_tpu.parallel import build_mesh
from simple_model import SimpleModel

out_dir = sys.argv[1]
# launch.py's env contract feeds jax.distributed through the PUBLIC API
deepspeed_tpu.init_distributed()
assert jax.process_count() == 2, jax.process_count()
assert len(jax.devices()) == 8, jax.devices()
pid = jax.process_index()
mesh = build_mesh(dp=8, devices=jax.devices())
cfg = {"train_micro_batch_size_per_gpu": 2,
       "gradient_accumulation_steps": 1,
       "steps_per_print": 10 ** 9,
       "bf16": {"enabled": True},
       "optimizer": {"type": "Adam", "params": {"lr": 1e-2}},
       "zero_optimization": {"stage": 2}}
engine, _, _, _ = deepspeed_tpu.initialize(
    model=SimpleModel(hidden_dim=16), config=cfg, mesh=mesh)
rng = np.random.default_rng(0)
gx = rng.normal(size=(16, 16)).astype(np.float32)
gy = (0.5 * gx).astype(np.float32)
lo, hi = (0, 8) if pid == 0 else (8, 16)
losses = [float(np.asarray(engine.train_batch((gx[lo:hi], gy[lo:hi]))))
          for _ in range(3)]
assert losses[-1] < losses[0], losses
json.dump({"rank": pid, "node_rank": os.environ.get("JAX_PROCESS_ID"),
           "world": os.environ.get("JAX_NUM_PROCESSES"),
           "losses": losses},
          open(os.path.join(out_dir, f"rank{pid}.json"), "w"))
print(f"REHEARSAL_{pid}_OK")
"""


def _free_port():
    import socket
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    return port


def _fake_transport(tmp_path, flavor):
    """A PATH-shadowing ssh/pdsh that executes the remote command
    locally (the remote command string is EXACTLY what a real transport
    would run on the target host) and logs which host it was for."""
    bin_dir = tmp_path / "fakebin"
    bin_dir.mkdir(exist_ok=True)
    log = tmp_path / f"{flavor}_hosts.log"
    if flavor == "ssh":
        body = ("#!/bin/bash\n"
                f"echo \"$1\" >> {log}\n"
                "shift\n"
                "exec bash -c \"$*\"\n")
    else:  # pdsh: argv = -w <host> <cmd...>
        body = ("#!/bin/bash\n"
                "shift\n"                      # drop -w
                f"echo \"$1\" >> {log}\n"
                "shift\n"
                "exec bash -c \"$*\"\n")
    exe = bin_dir / flavor
    exe.write_text(body)
    exe.chmod(0o755)
    return bin_dir, log


import pytest


@pytest.mark.parametrize("flavor", ["ssh", "pdsh"])
def test_ds_two_host_rehearsal_trains_one_job(tmp_path, flavor):
    """The full multinode chain, end to end on localhost: bin/ds parses
    the hostfile, the PDSH/SSH runner builds one remote command per
    host, the (faked) transport runs them, launch.py establishes the
    jax.distributed contract, and BOTH processes join ONE job and train
    (reference chain: runner.py → multinode_runner.py:35-75 →
    launch.py)."""
    script = tmp_path / "worker.py"
    script.write_text(_TRAIN_WORKER)
    out_dir = tmp_path / "out"
    out_dir.mkdir()
    hf = tmp_path / "hostfile"
    hf.write_text("nodeA slots=4\nnodeB slots=4\n")
    bin_dir, host_log = _fake_transport(tmp_path, flavor)

    env = dict(os.environ)
    env["PATH"] = str(bin_dir) + os.pathsep + env["PATH"]
    env["PYTHONPATH"] = (REPO + os.pathsep
                         + os.path.join(REPO, "tests") + os.pathsep
                         + env.get("PYTHONPATH", ""))
    # EXPORT_ENVS propagates the XLA_/JAX_ families into the remote cmds
    env["XLA_FLAGS"] = "--xla_force_host_platform_device_count=4"
    env.pop("JAX_PLATFORMS", None)

    args = [sys.executable, os.path.join(REPO, "bin", "ds"),
            "--hostfile", str(hf), "--launcher", flavor,
            "--master_addr", "127.0.0.1",
            "--master_port", str(_free_port()),
            str(script), str(out_dir)]
    proc = subprocess.run(args, env=env, timeout=420, capture_output=True,
                          text=True)
    assert proc.returncode == 0, (proc.stdout[-1500:], proc.stderr[-1500:])

    # both hosts were dispatched through the transport...
    hosts = host_log.read_text().split()
    assert sorted(hosts) == ["nodeA", "nodeB"], hosts
    # ...and both ranks joined one 2-process job and trained
    results = {}
    for r in (0, 1):
        f = out_dir / f"rank{r}.json"
        assert f.exists(), f"rank {r} produced no result"
        results[r] = json.loads(f.read_text())
    assert results[0]["world"] == results[1]["world"] == "2"
    assert {results[0]["node_rank"], results[1]["node_rank"]} == {"0", "1"}
    for r in (0, 1):
        assert results[r]["losses"][-1] < results[r]["losses"][0]
