"""Config parsing + batch triangle (mirrors reference tests/unit/test_config.py
and test_ds_config.py coverage)."""
import json

import pytest

from deepspeed_tpu.config import DeepSpeedConfig, DeepSpeedConfigError


def test_triangle_all_given_ok():
    cfg = DeepSpeedConfig({
        "train_batch_size": 32,
        "train_micro_batch_size_per_gpu": 4,
        "gradient_accumulation_steps": 2,
    }, world_size=4)
    assert cfg.train_batch_size == 32


def test_triangle_all_given_mismatch_raises():
    with pytest.raises(DeepSpeedConfigError):
        DeepSpeedConfig({
            "train_batch_size": 33,
            "train_micro_batch_size_per_gpu": 4,
            "gradient_accumulation_steps": 2,
        }, world_size=4)


def test_triangle_solve_grad_acc():
    cfg = DeepSpeedConfig({
        "train_batch_size": 32,
        "train_micro_batch_size_per_gpu": 4,
    }, world_size=4)
    assert cfg.gradient_accumulation_steps == 2


def test_triangle_solve_micro():
    cfg = DeepSpeedConfig({
        "train_batch_size": 32,
        "gradient_accumulation_steps": 2,
    }, world_size=4)
    assert cfg.train_micro_batch_size_per_gpu == 4


def test_triangle_solve_train():
    cfg = DeepSpeedConfig({
        "train_micro_batch_size_per_gpu": 4,
        "gradient_accumulation_steps": 2,
    }, world_size=4)
    assert cfg.train_batch_size == 32


def test_triangle_only_train_batch():
    cfg = DeepSpeedConfig({"train_batch_size": 8}, world_size=2)
    assert cfg.train_micro_batch_size_per_gpu == 4
    assert cfg.gradient_accumulation_steps == 1


def test_triangle_nothing_given_raises():
    with pytest.raises(DeepSpeedConfigError):
        DeepSpeedConfig({}, world_size=1)


def test_zero_requires_mixed_precision():
    with pytest.raises(DeepSpeedConfigError):
        DeepSpeedConfig({
            "train_batch_size": 8,
            "zero_optimization": {"stage": 2},
        }, world_size=1)


def test_zero_with_bf16_ok():
    cfg = DeepSpeedConfig({
        "train_batch_size": 8,
        "bf16": {"enabled": True},
        "zero_optimization": {"stage": 2},
    }, world_size=1)
    assert cfg.zero_enabled
    assert cfg.zero_optimization_stage == 2


def test_zero_stage3_supported():
    cfg = DeepSpeedConfig({
        "train_batch_size": 8,
        "bf16": {"enabled": True},
        "zero_optimization": {"stage": 3},
    }, world_size=1)
    assert cfg.zero_optimization_stage == 3


def test_zero_bool_deprecated_form():
    cfg = DeepSpeedConfig({
        "train_batch_size": 8,
        "fp16": {"enabled": True},
        "zero_optimization": True,
    }, world_size=1)
    assert cfg.zero_optimization_stage == 1


def test_cpu_offload_requires_stage2():
    with pytest.raises(DeepSpeedConfigError):
        DeepSpeedConfig({
            "train_batch_size": 8,
            "fp16": {"enabled": True},
            "zero_optimization": {"stage": 1, "cpu_offload": True},
        }, world_size=1)


def test_fp16_dynamic_loss_scale_default():
    cfg = DeepSpeedConfig({
        "train_batch_size": 8,
        "fp16": {"enabled": True},
    }, world_size=1)
    assert cfg.fp16.dynamic_loss_scale
    assert cfg.fp16.initial_dynamic_scale == 2 ** 32


def test_fp16_static_loss_scale():
    cfg = DeepSpeedConfig({
        "train_batch_size": 8,
        "fp16": {"enabled": True, "loss_scale": 128},
    }, world_size=1)
    assert not cfg.fp16.dynamic_loss_scale
    assert cfg.loss_scale == 128


def test_duplicate_json_keys_rejected(tmp_path):
    p = tmp_path / "ds_config.json"
    p.write_text('{"train_batch_size": 8, "train_batch_size": 16}')
    with pytest.raises(DeepSpeedConfigError):
        DeepSpeedConfig(str(p), world_size=1)


def test_config_from_file(tmp_path):
    p = tmp_path / "ds_config.json"
    p.write_text(json.dumps({
        "train_batch_size": 16,
        "optimizer": {"type": "Adam", "params": {"lr": 0.001}},
        "scheduler": {"type": "WarmupLR",
                      "params": {"warmup_num_steps": 10}},
    }))
    cfg = DeepSpeedConfig(str(p), world_size=2)
    assert cfg.optimizer_name == "adam"
    assert cfg.optimizer_params["lr"] == 0.001
    assert cfg.scheduler_name == "WarmupLR"


def test_sparse_attention_mode_validation():
    with pytest.raises(DeepSpeedConfigError):
        DeepSpeedConfig({
            "train_batch_size": 8,
            "sparse_attention": {"mode": "bogus"},
        }, world_size=1)


def test_unknown_optimizer_params_passthrough():
    cfg = DeepSpeedConfig({
        "train_batch_size": 8,
        "optimizer": {"type": "Lamb",
                      "params": {"lr": 0.1, "max_coeff": 5.0}},
    }, world_size=1)
    assert cfg.optimizer_name == "lamb"
    assert cfg.optimizer_params["max_coeff"] == 5.0


def test_config_writer_roundtrip(tmp_path):
    """reference: runtime/config.py:468-482."""
    from deepspeed_tpu.config import DeepSpeedConfigWriter

    w = DeepSpeedConfigWriter({"train_batch_size": 8})
    w.add_config("gradient_clipping", 1.0)
    path = str(tmp_path / "ds_config.json")
    w.write_config(path)

    r = DeepSpeedConfigWriter()
    r.load_config(path)
    assert r.data == {"train_batch_size": 8, "gradient_clipping": 1.0}

    # duplicate keys rejected on load, same as DeepSpeedConfig
    bad = tmp_path / "dup.json"
    bad.write_text('{"a": 1, "a": 2}')
    with pytest.raises(Exception):
        r.load_config(str(bad))


def test_ops_optimizer_aliases():
    from deepspeed_tpu.ops import FusedAdam, FusedLamb, fused_adam, fused_lamb
    assert FusedAdam is fused_adam and FusedLamb is fused_lamb


def test_amp_block_maps_to_bf16():
    """Apex AMP block accepted for ds_config compatibility; enabled maps
    to native bf16 (reference constants.py:162-172)."""
    from deepspeed_tpu.config import DeepSpeedConfig, DeepSpeedConfigError

    cfg = DeepSpeedConfig({
        "train_micro_batch_size_per_gpu": 2,
        "optimizer": {"type": "Adam", "params": {"lr": 1e-3}},
        "amp": {"enabled": True, "opt_level": "O1"},
    }, world_size=1)
    assert cfg.amp_enabled and cfg.bf16.enabled
    assert cfg.amp_params == {"opt_level": "O1"}

    with pytest.raises(DeepSpeedConfigError, match="mutually exclusive"):
        DeepSpeedConfig({
            "train_micro_batch_size_per_gpu": 2,
            "fp16": {"enabled": True},
            "amp": {"enabled": True},
        }, world_size=1)


def test_zero_allow_untested_optimizer_key():
    from deepspeed_tpu.config import DeepSpeedConfig
    cfg = DeepSpeedConfig({
        "train_micro_batch_size_per_gpu": 2,
        "zero_allow_untested_optimizer": True,
    }, world_size=1)
    assert cfg.zero_allow_untested_optimizer is True


def test_checkpoint_block_defaults():
    cfg = DeepSpeedConfig({"train_batch_size": 1}, world_size=1)
    ck = cfg.checkpoint_config
    assert ck.async_save is False
    assert ck.keep_last_n == 0          # unlimited — retention is opt-in
    assert ck.load_fallback == 2
    assert ck.io_retry_attempts == 3
    assert ck.sigterm_save is False
    assert ck.save_dir == ""


def test_checkpoint_block_parses():
    cfg = DeepSpeedConfig({
        "train_batch_size": 1,
        "checkpoint": {"async_save": True, "keep_last_n": 3,
                       "load_fallback": 1, "io_retry_attempts": 5,
                       "io_retry_base_s": 0.2, "sigterm_save": False,
                       "save_dir": "/ckpt"},
    }, world_size=1)
    ck = cfg.checkpoint_config
    assert ck.async_save and ck.keep_last_n == 3
    assert ck.load_fallback == 1 and ck.io_retry_attempts == 5
    assert ck.io_retry_base_s == 0.2 and ck.save_dir == "/ckpt"


@pytest.mark.parametrize("bad", [
    {"keep_last_n": -1}, {"keep_last_n": True}, {"keep_last_n": "3"},
    {"load_fallback": -2},
    {"io_retry_attempts": 0}, {"io_retry_attempts": 1.5},
    {"io_retry_base_s": -0.1}, {"io_retry_base_s": "fast"},
    {"save_dir": 7},
])
def test_checkpoint_block_validation(bad):
    """A typo'd retention/retry knob must fail at config parse, not at
    the 40-hour mark when the first GC or retry runs."""
    with pytest.raises(DeepSpeedConfigError):
        DeepSpeedConfig({"train_batch_size": 1, "checkpoint": bad},
                        world_size=1)


@pytest.mark.parametrize("bad", [{"async_save": "false"},
                                 {"sigterm_save": "no"},
                                 {"async_save": 1}])
def test_checkpoint_bool_knobs_reject_truthy_strings(bad):
    """'\"false\"' is truthy: silently flipping every save async (or
    installing the SIGTERM hook) would be the opposite of what was
    configured — bools must BE bools."""
    with pytest.raises(DeepSpeedConfigError):
        DeepSpeedConfig({"train_batch_size": 1, "checkpoint": bad},
                        world_size=1)


def test_sparse_attention_layout_knobs_route_with_defaults():
    """The 12 per-mode sparse layout keys route through the config
    block with their constants.py defaults (they were dead schema keys
    before the jaxlint JL104 sweep)."""
    cfg = DeepSpeedConfig({
        "train_batch_size": 8,
        "sparse_attention": {"mode": "bigbird", "block": 32},
    }, world_size=1)
    sa = cfg.sparse_attention_config
    assert sa.block == 32                       # explicit override
    assert sa.different_layout_per_head is False
    assert sa.num_local_blocks == 4
    assert sa.num_global_blocks == 1
    assert sa.attention == "bidirectional"
    assert sa.horizontal_global_attention is False
    assert sa.num_different_global_patterns == 1
    assert sa.num_random_blocks == 0
    assert sa.local_window_blocks == [4]
    assert sa.global_block_indices == [0]
    assert sa.global_block_end_indices is None
    assert sa.num_sliding_window_blocks == 3


def test_dead_schema_constants_removed():
    """OPTIMIZER_TYPE_DEFAULT / SCHEDULER_TYPE_DEFAULT (defaults whose
    keys never existed) and MAX_GRAD_NORM (a key nothing read) are gone
    — jaxlint JL104 keeps them from coming back."""
    from deepspeed_tpu.config import constants as C
    for name in ("OPTIMIZER_TYPE_DEFAULT", "SCHEDULER_TYPE_DEFAULT",
                 "MAX_GRAD_NORM"):
        assert not hasattr(C, name), name
