"""Differential tests for fused optimizers vs reference implementations —
the reference's pattern of checking DeepSpeedCPUAdam vs torch.optim.Adam
(reference: tests/unit/test_cpu_adam.py), here vs optax."""
import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest

from deepspeed_tpu.ops.adam import fused_adam
from deepspeed_tpu.ops.lamb import fused_lamb


def _params(seed=0):
    k = jax.random.PRNGKey(seed)
    k1, k2 = jax.random.split(k)
    return {"w": jax.random.normal(k1, (8, 8)),
            "b": jax.random.normal(k2, (8,))}


def _grads(seed=1):
    return _params(seed)


def _run(opt, params, grads, steps=5):
    state = opt.init(params)
    for _ in range(steps):
        updates, state = opt.update(grads, state, params)
        params = optax.apply_updates(params, updates)
    return params


def test_fused_adamw_matches_optax():
    params = _params()
    grads = _grads()
    mine = _run(fused_adam(lr=1e-2, weight_decay=0.01, adam_w_mode=True),
                params, grads)
    ref = _run(optax.adamw(1e-2, b1=0.9, b2=0.999, eps=1e-8,
                           weight_decay=0.01), params, grads)
    for k in params:
        np.testing.assert_allclose(np.asarray(mine[k]), np.asarray(ref[k]),
                                   rtol=1e-5, atol=1e-6)


def test_fused_adam_no_decay_matches_optax():
    params = _params()
    grads = _grads()
    mine = _run(fused_adam(lr=1e-3, weight_decay=0.0), params, grads)
    ref = _run(optax.adam(1e-3), params, grads)
    for k in params:
        np.testing.assert_allclose(np.asarray(mine[k]), np.asarray(ref[k]),
                                   rtol=1e-5, atol=1e-6)


def test_adam_l2_mode_differs_from_adamw():
    params = _params()
    grads = _grads()
    l2 = _run(fused_adam(lr=1e-2, weight_decay=0.1, adam_w_mode=False),
              params, grads)
    aw = _run(fused_adam(lr=1e-2, weight_decay=0.1, adam_w_mode=True),
              params, grads)
    assert not np.allclose(np.asarray(l2["w"]), np.asarray(aw["w"]))


def test_adam_lr_schedule_callable():
    params = _params()
    grads = _grads()
    sched = lambda count: 1e-2 / count.astype(jnp.float32)
    out = _run(fused_adam(lr=sched), params, grads, steps=3)
    assert np.isfinite(np.asarray(out["w"])).all()


def test_lamb_trust_ratio_clamps():
    params = _params()
    grads = _grads()
    out = _run(fused_lamb(lr=1e-2, max_coeff=10.0, min_coeff=0.01),
               params, grads, steps=3)
    assert np.isfinite(np.asarray(out["w"])).all()
    # trust ratio keeps update magnitude proportional to weight norm
    delta = np.abs(np.asarray(out["w"]) - np.asarray(params["w"])).max()
    assert delta < 1.0


def test_lamb_zero_grad_no_nan():
    params = _params()
    zeros = jax.tree.map(jnp.zeros_like, params)
    out = _run(fused_lamb(lr=1e-2), params, zeros, steps=2)
    assert np.isfinite(np.asarray(out["w"])).all()
