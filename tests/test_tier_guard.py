"""Tier-guard tests: the conftest guard that keeps the core tier
(`pytest -m "not slow"`) fast by forcing compile-bound tests into the
slow tier.  Mirrors the reference's CI split (per-PR unit jobs vs
nightly model tests, reference: azure-pipelines.yml)."""
import conftest
import pytest

from deepspeed_tpu.parallel import build_mesh


def test_policy_pp4_unmarked_flagged():
    msg = conftest.heavy_mesh_violation({"pipe": 4, "data": 2}, False)
    assert msg is not None and "slow" in msg


def test_policy_pp4_marked_ok():
    assert conftest.heavy_mesh_violation({"pipe": 4, "data": 2}, True) is None


def test_policy_small_meshes_ok():
    assert conftest.heavy_mesh_violation({"pipe": 2, "data": 4}, False) is None
    assert conftest.heavy_mesh_violation({"data": 8}, False) is None


def test_policy_duration():
    assert conftest.duration_violation(90.0, False, 60.0) is not None
    assert conftest.duration_violation(90.0, True, 60.0) is None
    assert conftest.duration_violation(10.0, False, 60.0) is None


def test_unmarked_pp4_mesh_fails_at_construction():
    """The live guard: this test carries no slow marker, so building a
    pp=4 mesh must fail immediately (mesh construction is where the
    guard hooks — before any compile cost is paid)."""
    with pytest.raises(pytest.fail.Exception, match="pipe=4"):
        build_mesh(pp=4, dp=2, tp=1)


@pytest.mark.slow
def test_marked_pp4_mesh_allowed():
    """With the slow marker the same construction passes (construction
    only — no program is compiled here, so this 'slow' test is cheap)."""
    mesh = build_mesh(pp=4, dp=2, tp=1)
    assert mesh.shape["pipe"] == 4
