"""Mixture-of-Experts / expert parallelism.

The reference snapshot has no MoE (SURVEY.md §2.4: EP absent in v0.3.2);
these tests pin the modern-slot implementation (moe/layer.py,
models/gpt2_moe.py): routing math, capacity drops, load-balance loss,
and expert-parallel training through the engine on the 8-device mesh.
"""
import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import PartitionSpec as P

from deepspeed_tpu.config import DeepSpeedConfig
from deepspeed_tpu.models import GPT2MoEConfig, GPT2MoEModel
from deepspeed_tpu.moe import MoEConfig, init_moe_params, moe_ffn
from deepspeed_tpu.parallel import build_mesh
from deepspeed_tpu.runtime.engine import DeepSpeedEngine


def _x(rng, g=2, s=8, d=16):
    return jax.random.normal(rng, (g, s, d), jnp.float32)


def test_single_expert_is_dense_ffn():
    """E=1 top-1: the router has one choice with prob 1, ample capacity —
    the MoE layer IS the dense FFN."""
    cfg = MoEConfig(n_experts=1, d_model=16, d_ff=32,
                    capacity_factor=1.0)
    rng = jax.random.PRNGKey(0)
    mp = init_moe_params(rng, cfg)
    x = _x(jax.random.PRNGKey(1))
    y, aux = moe_ffn(cfg, mp, x, jax.random.PRNGKey(2), train=True)
    h = x @ mp["wi"][0] + mp["bi"][0]
    dense = jax.nn.gelu(h, approximate=True) @ mp["wo"][0] + mp["bo"][0]
    np.testing.assert_allclose(np.asarray(y), np.asarray(dense),
                               rtol=2e-5, atol=2e-5)
    # one expert gets all tokens with prob 1: aux = w * E * 1 * 1
    np.testing.assert_allclose(float(aux), cfg.aux_loss_weight, rtol=1e-5)


def test_top2_identical_experts_match_dense():
    """Two byte-identical experts under top-2: renormalized gates sum to
    1, so the combined output equals the single dense FFN."""
    cfg = MoEConfig(n_experts=2, d_model=16, d_ff=32, top_k=2,
                    capacity_factor=2.0)
    mp = init_moe_params(jax.random.PRNGKey(0), cfg)
    for k in ("wi", "bi", "wo", "bo"):
        mp[k] = jnp.stack([mp[k][0], mp[k][0]])
    x = _x(jax.random.PRNGKey(1))
    y, _ = moe_ffn(cfg, mp, x, jax.random.PRNGKey(2), train=True)
    h = x @ mp["wi"][0] + mp["bi"][0]
    dense = jax.nn.gelu(h, approximate=True) @ mp["wo"][0] + mp["bo"][0]
    np.testing.assert_allclose(np.asarray(y), np.asarray(dense),
                               rtol=2e-4, atol=2e-4)


def test_capacity_drops_overflow_tokens():
    """Router forced to expert 0 with capacity 1: the first token per
    group goes through, the rest are dropped (zero output)."""
    cfg = MoEConfig(n_experts=4, d_model=16, d_ff=32,
                    capacity_factor=1e-9)  # capacity clamps to 1
    assert cfg.capacity(8, train=True) == 1
    mp = init_moe_params(jax.random.PRNGKey(0), cfg)
    mp["wg"] = jnp.zeros_like(mp["wg"])  # uniform logits → argmax = 0
    x = _x(jax.random.PRNGKey(1))
    y, _ = moe_ffn(cfg, mp, x, jax.random.PRNGKey(2), train=True)
    y = np.asarray(y)
    assert np.abs(y[:, 0]).max() > 0, "first token must be routed"
    np.testing.assert_array_equal(y[:, 1:], 0.0)


def test_aux_loss_balanced_is_one():
    """Uniform router probs: Σ_e density_e · proxy_e = 1/E, aux = E·1/E·1
    = 1 (times the weight)."""
    cfg = MoEConfig(n_experts=4, d_model=16, d_ff=32,
                    aux_loss_weight=1.0, capacity_factor=4.0)
    mp = init_moe_params(jax.random.PRNGKey(0), cfg)
    mp["wg"] = jnp.zeros_like(mp["wg"])
    y, aux = moe_ffn(cfg, mp, _x(jax.random.PRNGKey(1)),
                     jax.random.PRNGKey(2), train=True)
    np.testing.assert_allclose(float(aux), 1.0, rtol=1e-5)


def test_router_grads_flow():
    cfg = MoEConfig(n_experts=4, d_model=16, d_ff=32)
    mp = init_moe_params(jax.random.PRNGKey(0), cfg)
    x = _x(jax.random.PRNGKey(1))

    def loss(mp):
        y, aux = moe_ffn(cfg, mp, x, jax.random.PRNGKey(2), train=True)
        return jnp.sum(y ** 2) + aux

    g = jax.grad(loss)(mp)
    for k in ("wg", "wi", "wo"):
        assert float(jnp.abs(g[k]).max()) > 0, f"zero grad for {k}"


def _moe_model(n_layer=2, n_experts=4, **kw):
    # remat=None: these are routing/placement tests, and skipping the
    # checkpoint-policy tracing roughly halves their compile time
    kw.setdefault("remat", None)
    kw.setdefault("attn_impl", "dense")
    cfg = GPT2MoEConfig(vocab_size=128, n_positions=32, d_model=32,
                        n_layer=n_layer, n_head=4,
                        n_experts=n_experts, **kw)
    return GPT2MoEModel(cfg), cfg


def _engine(model, mesh, zero_stage=2, micro=1, ga=2):
    ds = DeepSpeedConfig({
        "train_micro_batch_size_per_gpu": micro,
        "gradient_accumulation_steps": ga,
        "steps_per_print": 10 ** 9,
        "bf16": {"enabled": True},
        "optimizer": {"type": "Adam", "params": {"lr": 1e-3}},
        "zero_optimization": {"stage": zero_stage},
    }, world_size=int(np.prod([mesh.shape[a] for a in ("data",)])))
    return DeepSpeedEngine(model, ds, mesh=mesh)


def _tokens(batch, seq=16, vocab=128, seed=0):
    return np.random.default_rng(seed).integers(
        0, vocab, (batch, seq + 1), dtype=np.int32)


def test_moe_engine_ep8_zero2_trains():
    """EP over the full 8-way data axis; ZeRO-2; loss decreases."""
    model, cfg = _moe_model(n_experts=8)
    mesh = build_mesh(dp=8)
    eng = _engine(model, mesh, zero_stage=2, micro=1, ga=2)
    # expert-stacked weights are sharded over 'data' on the expert dim
    spec = eng.state.master_params["moe"]["wi"].sharding.spec
    assert spec[1] == "data", f"expert dim not EP-sharded: {spec}"
    losses = [float(np.asarray(eng.train_batch(_tokens(16, seed=s))))
              for s in range(4)]
    assert all(np.isfinite(losses))
    assert losses[-1] < losses[0]


def test_moe_engine_ep_tp_compose():
    """EP (data) × TP (model): expert hidden dim sharded over 'model'."""
    model, cfg = _moe_model(n_experts=4)
    mesh = build_mesh(dp=4, tp=2)
    eng = _engine(model, mesh, zero_stage=1, micro=2, ga=1)
    spec = eng.state.master_params["moe"]["wi"].sharding.spec
    assert spec[1] == "data" and spec[3] == "model", str(spec)
    loss = float(np.asarray(eng.train_batch(_tokens(8))))
    assert np.isfinite(loss)


def test_moe_config_rejects_top_k_over_n_experts():
    """top_k > n_experts would silently double-assign tokens to expert 0
    with half gates — rejected at config time."""
    with pytest.raises(ValueError, match="top_k"):
        MoEConfig(n_experts=1, d_model=16, d_ff=32, top_k=2)


def test_moe_indivisible_experts_fall_back_to_replication():
    """4 experts on a dp=8 mesh: the EP spec's expert dim is indivisible,
    so it must be dropped (replicated) rather than failing NamedSharding
    validation — ZeRO then shards a divisible dim of the master copy."""
    model, cfg = _moe_model(n_experts=4)
    mesh = build_mesh(dp=8)
    eng = _engine(model, mesh, zero_stage=2, micro=1, ga=1)
    spec = eng.state.master_params["moe"]["wi"].sharding.spec
    assert "data" not in (spec[1],), f"indivisible expert dim kept: {spec}"
    loss = float(np.asarray(eng.train_batch(_tokens(8))))
    assert np.isfinite(loss)


def test_scan_groups_matches_unrolled():
    """scan_groups compiles one group body (compile O(1) in depth); its
    forward must be numerically identical to the unrolled loop — same
    params, same per-layer RNG keys."""
    import dataclasses
    # dropout + jitter ON: identical outputs then require identical
    # per-layer RNG keys, so a scan-path key-stream off-by-one fails
    model_u, cfg_u = _moe_model(n_layer=4, n_experts=4, dropout=0.1,
                                router_jitter=0.1)
    cfg_s = dataclasses.replace(cfg_u, scan_groups=True)
    model_s = GPT2MoEModel(cfg_s)
    params = model_u.init(jax.random.PRNGKey(0))
    toks = jnp.asarray(_tokens(2)[:, :-1])
    rng = jax.random.PRNGKey(3)
    lu, au = model_u.apply(params, toks, rng, train=True)
    ls, as_ = model_s.apply(params, toks, rng, train=True)
    np.testing.assert_allclose(np.asarray(ls), np.asarray(lu),
                               rtol=1e-5, atol=1e-5)
    np.testing.assert_allclose(float(as_), float(au), rtol=1e-6)
    # indivisible depth is rejected at config time
    with pytest.raises(ValueError, match="divisible"):
        dataclasses.replace(cfg_u, scan_groups=True, n_layer=3)


def test_moe_matches_dense_when_single_expert():
    """A 1-expert MoE GPT-2 trains to the same loss trajectory as an
    equivalent routing-free computation (smoke parity, bf16 tolerance)."""
    model, cfg = _moe_model(n_layer=2, n_experts=1, capacity_factor=4.0)
    mesh = build_mesh(dp=1, devices=jax.devices()[:1])
    eng = _engine(model, mesh, zero_stage=0, micro=2, ga=1)
    l0 = float(np.asarray(eng.train_batch(_tokens(2, seed=1))))
    l1 = float(np.asarray(eng.train_batch(_tokens(2, seed=2))))
    assert np.isfinite(l0) and np.isfinite(l1) and l1 < l0 + 1.0


@pytest.mark.slow
def test_scan_groups_trains_with_remat():
    """Deep-model shape: scanned groups + group remat through the engine
    (the config a real multi-layer MoE run would use)."""
    model, _ = _moe_model(n_layer=4, n_experts=8, scan_groups=True,
                          remat="block")
    mesh = build_mesh(dp=8)
    eng = _engine(model, mesh, zero_stage=2, micro=1, ga=2)
    losses = [float(np.asarray(eng.train_batch(_tokens(16, seed=s))))
              for s in range(3)]
    assert all(np.isfinite(losses))


@pytest.mark.slow
def test_moe_sequence_parallel_composes():
    """EP (data) × SP (seq, ring attention): the MoE dispatch einsums run
    under GSPMD while attention shard_maps over 'seq' only."""
    model, _ = _moe_model(n_experts=4, attn_impl="ring")
    mesh = build_mesh(dp=4, sp=2, tp=1)
    eng = _engine(model, mesh, zero_stage=2, micro=1, ga=1)
    losses = [float(np.asarray(eng.train_batch(_tokens(4, seed=s))))
              for s in range(3)]
    assert all(np.isfinite(losses))


@pytest.mark.slow
def test_moe_offload_xla_composes():
    """MoE × ZeRO-Offload (xla tier): expert weights join the flat
    dp-sharded host staging; the routed forward still trains."""
    model, _ = _moe_model(n_experts=8)
    mesh = build_mesh(dp=8)
    ds = DeepSpeedConfig({
        "train_micro_batch_size_per_gpu": 1,
        "gradient_accumulation_steps": 1,
        "steps_per_print": 10 ** 9,
        "bf16": {"enabled": True},
        "optimizer": {"type": "Adam", "params": {"lr": 1e-3}},
        "zero_optimization": {"stage": 2, "cpu_offload": True,
                              "offload_impl": "xla"},
    }, world_size=8)
    eng = DeepSpeedEngine(model, ds, mesh=mesh)
    losses = [float(np.asarray(eng.train_batch(_tokens(8, seed=s))))
              for s in range(3)]
    assert all(np.isfinite(losses))


@pytest.mark.slow
def test_moe_fp16_loss_scaling():
    """MoE under fp16 dynamic loss scaling: the aux loss rides the scaled
    objective and steps complete without overflow-skips on tame data."""
    model, _ = _moe_model(n_experts=4)
    mesh = build_mesh(dp=4, tp=2)
    ds = DeepSpeedConfig({
        "train_micro_batch_size_per_gpu": 2,
        "gradient_accumulation_steps": 1,
        "steps_per_print": 10 ** 9,
        "fp16": {"enabled": True, "initial_scale_power": 8},
        "optimizer": {"type": "Adam", "params": {"lr": 1e-3}},
        "zero_optimization": {"stage": 1},
    }, world_size=4)
    eng = DeepSpeedEngine(model, ds, mesh=mesh)
    losses = [float(np.asarray(eng.train_batch(_tokens(8, seed=s))))
              for s in range(3)]
    assert all(np.isfinite(losses))
    assert eng.get_skipped_steps() == 0


@pytest.mark.slow
def test_moe_elastic_mesh_resize(tmp_path):
    """An EP checkpoint reshards on load into a different mesh (dp=8 EP →
    dp=4×tp=2 EP×TP): sharding is load-time policy, not file layout —
    the reference's elastic restore extended to expert-parallel state."""
    model, _ = _moe_model(n_experts=8)
    eng = _engine(model, build_mesh(dp=8), zero_stage=2, micro=1, ga=1)
    eng.train_batch(_tokens(8))
    eng.save_checkpoint(str(tmp_path), tag="ep")

    eng2 = _engine(model, build_mesh(dp=4, tp=2), zero_stage=1,
                   micro=2, ga=1)
    path, _ = eng2.load_checkpoint(str(tmp_path), tag="ep")
    assert path is not None
    for a, b in zip(jax.tree.leaves(eng.state.master_params),
                    jax.tree.leaves(eng2.state.master_params)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=1e-6, atol=1e-6)
    loss = float(np.asarray(eng2.train_batch(_tokens(8, seed=9))))
    assert np.isfinite(loss)


@pytest.mark.slow
def test_moe_checkpoint_roundtrip(tmp_path):
    model, _ = _moe_model(n_experts=4)
    mesh = build_mesh(dp=4, tp=2)
    eng = _engine(model, mesh, zero_stage=1, micro=2, ga=1)
    eng.train_batch(_tokens(8))
    eng.save_checkpoint(str(tmp_path), tag="m")
    eng2 = _engine(model, mesh, zero_stage=1, micro=2, ga=1)
    eng2.load_checkpoint(str(tmp_path), tag="m")
    a = jax.tree.leaves(eng.state.master_params)
    b = jax.tree.leaves(eng2.state.master_params)
    for x, y in zip(a, b):
        np.testing.assert_array_equal(np.asarray(x), np.asarray(y))


@pytest.mark.parametrize("top_k", [1, 2])
def test_scatter_dispatch_matches_einsum(top_k):
    """dispatch_impl='scatter' (O(S·d) scatter/gather) must be numerically
    identical to the one-hot einsum formulation, including over-capacity
    drops (cf small enough to force them) and top-2 queueing order."""
    kw = dict(n_experts=4, d_model=16, d_ff=32, top_k=top_k,
              capacity_factor=0.5)  # forces drops
    mp = init_moe_params(jax.random.PRNGKey(0),
                         MoEConfig(dispatch_impl="einsum", **kw))
    x = _x(jax.random.PRNGKey(1), s=16)
    outs = {}
    for impl in ("einsum", "scatter"):
        cfg = MoEConfig(dispatch_impl=impl, **kw)
        y, aux = moe_ffn(cfg, mp, x, jax.random.PRNGKey(2), train=True)
        outs[impl] = (np.asarray(y), float(aux))
    np.testing.assert_allclose(outs["scatter"][0], outs["einsum"][0],
                               rtol=1e-5, atol=1e-5)
    assert outs["scatter"][1] == pytest.approx(outs["einsum"][1])


def test_scatter_dispatch_grads_match_einsum():
    """Backward equivalence: same loss gradients w.r.t. params and input
    through either dispatch implementation."""
    kw = dict(n_experts=4, d_model=16, d_ff=32, top_k=2,
              capacity_factor=0.75)
    mp = init_moe_params(jax.random.PRNGKey(0),
                         MoEConfig(dispatch_impl="einsum", **kw))
    x = _x(jax.random.PRNGKey(1), s=16)

    def loss(params, xin, impl):
        cfg = MoEConfig(dispatch_impl=impl, **kw)
        y, aux = moe_ffn(cfg, params, xin, jax.random.PRNGKey(2),
                         train=True)
        return jnp.sum(y ** 2) + aux

    for arg in (0, 1):
        g_e = jax.grad(loss, argnums=arg)(mp, x, "einsum")
        g_s = jax.grad(loss, argnums=arg)(mp, x, "scatter")
        jax.tree.map(
            lambda a, b: np.testing.assert_allclose(
                np.asarray(a), np.asarray(b), rtol=1e-4, atol=1e-5),
            g_e, g_s)


def test_scatter_dispatch_bad_impl_rejected():
    with pytest.raises(ValueError, match="dispatch_impl"):
        MoEConfig(n_experts=2, d_model=8, d_ff=16, dispatch_impl="sorted")
