"""tools/benchgate — the BENCH_*.json regression tripwire (fixture
pairs; stdlib-only, no jax needed)."""
import json

import pytest

from tools.benchgate import (compare, headline, is_lower_better,
                             load_committed, main)


def _art(value, metric="tokens_per_s_speedup"):
    return {"metric": metric, "value": value}


# ---------------------------------------------------------------------------
# compare() over fixture pairs
# ---------------------------------------------------------------------------

def test_improvement_and_small_drift_pass():
    assert not compare(_art(5.5), _art(5.0))["regressed"]     # faster
    assert not compare(_art(4.5), _art(5.0))["regressed"]     # -10% ok


def test_regression_beyond_threshold_fails():
    res = compare(_art(3.9), _art(5.0))                       # -22%
    assert res["regressed"]
    assert res["change"] == pytest.approx(-0.22)
    # tighter threshold trips earlier
    assert compare(_art(4.5), _art(5.0), threshold=0.05)["regressed"]


def test_lower_better_metrics_invert_direction():
    assert is_lower_better("serve_token_p99_latency")
    assert not is_lower_better("serve_continuous_batching_speedup")
    lat = "decode_p99_latency"
    assert compare(_art(0.5, lat), _art(1.0, lat))["regressed"] is False
    assert compare(_art(1.3, lat), _art(1.0, lat))["regressed"] is True
    # explicit override beats the name heuristic
    assert compare(_art(1.3), _art(1.0),
                   lower_better=True)["regressed"] is True


def test_bool_metric_one_to_zero_fails():
    m = "stage_chaos_degraded_run"
    assert compare(_art(0, m), _art(1, m))["regressed"] is True
    assert compare(_art(1, m), _art(1, m))["regressed"] is False


def test_zero_baseline_and_metric_rename_are_not_failures():
    # a committed failed bench (value=0) cannot regress further down on
    # a higher-is-better metric
    assert compare(_art(5.0), _art(0.0))["regressed"] is False
    res = compare(_art(5.0, "new_metric"), _art(1.0, "old_metric"))
    assert res["comparable"] is False and res["regressed"] is False


def test_headline_rejects_non_bench_docs():
    with pytest.raises(ValueError):
        headline({"not": "a bench"})


# ---------------------------------------------------------------------------
# CLI exit codes (the run_bench_suite.sh --gate contract)
# ---------------------------------------------------------------------------

def _write(path, doc):
    with open(path, "w") as f:
        json.dump(doc, f)
    return str(path)


def test_cli_pass_fail_and_missing_inputs(tmp_path, capsys):
    fresh = _write(tmp_path / "BENCH_x.json", _art(5.0))
    good = _write(tmp_path / "base_good.json", _art(4.8))
    bad = _write(tmp_path / "base_bad.json", _art(8.0))
    assert main([fresh, "--baseline", good]) == 0
    assert main([fresh, "--baseline", bad]) == 1
    out = capsys.readouterr().out
    assert "REGRESSED" in out
    assert main([str(tmp_path / "missing.json"),
                 "--baseline", good]) == 2
    assert main([fresh, "--baseline",
                 str(tmp_path / "missing.json")]) == 2


def test_cli_pre_gate_artifacts_skip_not_fail(tmp_path, capsys):
    """Regression: legacy BENCH files without a headline metric/value
    (raw result tables, lists) are SKIPPED (exit 0), never treated as a
    regression — --gate must not wedge the hardware suite on them."""
    fresh = _write(tmp_path / "BENCH_flash.json",
                   {"fwd_ms": 1.2, "bwd_ms": 3.4})   # no value key
    base = _write(tmp_path / "base.json", {"fwd_ms": 1.0})
    assert main([fresh, "--baseline", base]) == 0
    assert "not a gateable artifact" in capsys.readouterr().out
    listy = _write(tmp_path / "BENCH_bert.json", [{"rows": 1}])
    assert main([listy, "--baseline", base]) == 0


def test_cli_no_committed_predecessor_passes(tmp_path, capsys):
    # tmp_path is not a git repo: load_committed degrades to None and
    # the gate passes with a first-run note (a renamed/new bench must
    # not wedge the suite; tier-1 stays hermetic — fixture pairs only,
    # never the live working-tree artifacts)
    fresh = _write(tmp_path / "BENCH_new.json", _art(1.0))
    assert load_committed(fresh) is None
    assert main([fresh]) == 0
    assert "no committed predecessor" in capsys.readouterr().out


def test_every_committed_artifact_has_a_direction(capsys):
    """--list-unpinned reuses the jaxlint project registry's bench
    scan: every committed BENCH_*.json headline metric must be pinned
    in METRIC_DIRECTIONS or matched by a LOWER_BETTER_HINTS substring —
    a gate judging direction by a heuristic that matched nothing is a
    coin flip."""
    assert main(["--list-unpinned"]) == 0
    err = capsys.readouterr().err
    assert "0 unpinned" in err


def test_fresh_path_required_without_list_unpinned(capsys):
    with pytest.raises(SystemExit):
        main([])


def test_new_headline_pins_are_higher_better():
    """Pin the three throughput/boolean headlines the registry sweep
    found unpinned (all higher-is-better; none name-hint matched)."""
    for name in ("gpt2_124m_zero0_seq1024_tokens_per_sec_per_chip",
                 "serve_continuous_batching_speedup",
                 "stage_chaos_degraded_run"):
        assert is_lower_better(name) is False, name
