"""Shared dense-attention dropout oracle.

One implementation of "dense attention with the flash kernel's
position-hashed keep mask" pins the dropout semantics that the Pallas
kernel, ring attention, and Ulysses must all reproduce — a single
source so the oracle cannot drift between test families.
"""
import jax
import jax.numpy as jnp

from deepspeed_tpu.ops.pallas.flash_attention import dense_keep_mask


def dense_dropout_oracle(q, k, v, rate, seed, causal=True, key_mask=None):
    """q/k/v: [B, H, T, D]; ``seed``: uint32 scalar (callers holding a
    PRNGKey derive it with jax.random.bits(key, (), jnp.uint32), the same
    derivation flash_attention uses).  ``key_mask``: optional [B, Tk]
    boolean (True = attend), the kernel's padding-mask semantics."""
    b, h, t, d = q.shape
    tk = k.shape[2]
    scale = float(d) ** -0.5
    s = jnp.einsum("bhqd,bhkd->bhqk", q.astype(jnp.float32),
                   k.astype(jnp.float32)) * scale
    if key_mask is not None:
        s = s + jnp.where(key_mask, 0.0, -1e9)[:, None, None, :]
    if causal:
        s = jnp.where(jnp.tril(jnp.ones((t, tk), bool)), s, -1e30)
    p = jax.nn.softmax(s, axis=-1)
    keep = dense_keep_mask(b, h, t, tk, seed, rate)
    pd = p * keep.astype(p.dtype) / (1.0 - rate)
    return jnp.einsum("bhqk,bhkd->bhqd", pd.astype(q.dtype), v)
