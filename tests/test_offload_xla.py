"""ZeRO-Offload, XLA tier (piece-wise host staging) — correctness on the
CPU mesh.

The tier stores fp32 master + Adam moments as one partition-major
(dp, w_i) piece per parameter, row-sharded over ``data`` (the piece-wise
analogue of the reference's per-rank fp32 partitions,
deepspeed/runtime/zero/stage2.py:262-269,743-900).  On real TPUs the
pieces live in ``pinned_host`` memory and the update runs as an XLA host
computation; on the CPU test mesh the same program runs with a single
memory space (engine._offload_real_host gates the memory kind only), so
everything here — pack/unpack, masking, checkpoint conversion — is the
code that runs on hardware.
"""
import numpy as np
import jax
import pytest

from deepspeed_tpu.config import DeepSpeedConfig
from deepspeed_tpu.parallel import build_mesh
from deepspeed_tpu.runtime.engine import DeepSpeedEngine

from simple_model import SimpleModel


def _cfg(offload: bool, lr=1e-2, wd=0.0):
    zero = {"stage": 2}
    if offload:
        zero.update({"cpu_offload": True, "offload_impl": "xla"})
    return DeepSpeedConfig({
        "train_micro_batch_size_per_gpu": 2,
        "gradient_accumulation_steps": 2,
        "steps_per_print": 10 ** 9,
        "bf16": {"enabled": True},
        "optimizer": {"type": "Adam",
                      "params": {"lr": lr, "weight_decay": wd}},
        "zero_optimization": zero,
    }, world_size=4)


@pytest.fixture(scope="module")
def mesh():
    return build_mesh(dp=4, devices=jax.devices()[:4])


def _batch(seed=0):
    rng = np.random.default_rng(seed)
    x = rng.normal(size=(16, 32)).astype(np.float32)
    return x, (0.5 * x).astype(np.float32)


def test_matches_device_path(mesh):
    """Flat host staging must reproduce the plain fused-Adam trajectory."""
    ex = DeepSpeedEngine(SimpleModel(hidden_dim=32), _cfg(True), mesh=mesh,
                         seed=3)
    ep = DeepSpeedEngine(SimpleModel(hidden_dim=32), _cfg(False), mesh=mesh,
                         seed=3)
    x, y = _batch()
    for _ in range(5):
        lx = float(np.asarray(ex.train_batch((x, y))))
        lp = float(np.asarray(ep.train_batch((x, y))))
        assert abs(lx - lp) < 1e-4, (lx, lp)
    assert lx < 0.95  # actually learning


def test_weight_decay_paths(mesh):
    """adam_w decoupled decay is inlined in the host section — keep it in
    sync with ops/adam.py numerics."""
    ex = DeepSpeedEngine(SimpleModel(hidden_dim=32), _cfg(True, wd=0.1),
                         mesh=mesh, seed=3)
    ep = DeepSpeedEngine(SimpleModel(hidden_dim=32), _cfg(False, wd=0.1),
                         mesh=mesh, seed=3)
    x, y = _batch()
    for _ in range(3):
        lx = float(np.asarray(ex.train_batch((x, y))))
        lp = float(np.asarray(ep.train_batch((x, y))))
    assert abs(lx - lp) < 1e-4, (lx, lp)


def test_flat_padding_and_sharding(mesh):
    """Partition-major layout contract: the master is one (dp, w_i) piece
    per parameter, row-sharded over data; leaves without a leading data
    shard are padded per-leaf to a multiple of dp, and the pack/unpack
    pair is an exact inverse."""
    eng = DeepSpeedEngine(SimpleModel(hidden_dim=32), _cfg(True), mesh=mesh)
    n_raw = sum(int(np.prod(s)) for s in eng._flat_shapes)
    assert eng._flat_n % 4 == 0              # dp rows of equal width
    assert eng._flat_n == 4 * eng._flat_w
    assert eng._flat_n - n_raw == eng._flat_pad  # per-leaf padding total
    assert all(rec.pad < 4 for rec in eng._flat_layout)
    pieces = eng.state.master_params
    assert isinstance(pieces, tuple)
    assert len(pieces) == len(eng._flat_layout)
    for p, rec in zip(pieces, eng._flat_layout):
        assert p.shape == (4, rec.w)
        assert "data" in str(p.sharding.spec)  # per-rank host partitions
    # exact numpy roundtrip through the layout
    tree = eng._unflatten_numpy(pieces)
    again = eng._flatten_numpy(tree)
    for a, p in zip(again, pieces):
        np.testing.assert_array_equal(a, np.asarray(jax.device_get(p)))


def test_checkpoint_roundtrip_and_cross_load(mesh, tmp_path):
    """Offload checkpoints are written in canonical tree form: they restore
    exactly into another offload engine AND into a plain device engine
    (reference elastic merge/re-partition analogue, stage2.py:1712-1778)."""
    x, y = _batch()
    ex = DeepSpeedEngine(SimpleModel(hidden_dim=32), _cfg(True), mesh=mesh,
                         seed=3)
    for _ in range(3):
        ex.train_batch((x, y))
    ex.save_checkpoint(str(tmp_path), tag="t0")
    ref = float(np.asarray(ex.train_batch((x, y))))

    e2 = DeepSpeedEngine(SimpleModel(hidden_dim=32), _cfg(True), mesh=mesh,
                         seed=9)
    path, _ = e2.load_checkpoint(str(tmp_path), tag="t0")
    assert path is not None
    assert float(np.asarray(e2.train_batch((x, y)))) == pytest.approx(
        ref, abs=1e-6)

    ec = DeepSpeedEngine(SimpleModel(hidden_dim=32), _cfg(False), mesh=mesh,
                         seed=9)
    path, _ = ec.load_checkpoint(str(tmp_path), tag="t0")
    assert path is not None
    assert float(np.asarray(ec.train_batch((x, y)))) == pytest.approx(
        ref, abs=1e-4)


def test_module_only_load(mesh, tmp_path):
    x, y = _batch()
    ex = DeepSpeedEngine(SimpleModel(hidden_dim=32), _cfg(True), mesh=mesh,
                         seed=3)
    for _ in range(2):
        ex.train_batch((x, y))
    ex.save_checkpoint(str(tmp_path), tag="t0")
    e2 = DeepSpeedEngine(SimpleModel(hidden_dim=32), _cfg(True), mesh=mesh,
                         seed=9)
    path, _ = e2.load_checkpoint(str(tmp_path), tag="t0",
                                 load_module_only=True)
    assert path is not None
    # fresh moments, weights restored: loss continues from the saved model
    l2 = float(np.asarray(e2.train_batch((x, y))))
    assert np.isfinite(l2) and l2 < 1.0


def test_zero3_offload_composition(mesh):
    """ZeRO-3 × XLA offload (the GPT-3 13B ladder rung, BASELINE.json
    configs[4]): master/moments stay flat in (pinned) host memory AND the
    compute params stay data-sharded — no full replica materialized by the
    cast-up path — while training matches the stage-2 offload engine."""
    cfg3 = DeepSpeedConfig({
        "train_micro_batch_size_per_gpu": 2,
        "gradient_accumulation_steps": 2,
        "steps_per_print": 10 ** 9,
        "bf16": {"enabled": True},
        "optimizer": {"type": "Adam", "params": {"lr": 1e-2}},
        "zero_optimization": {"stage": 3, "cpu_offload": True,
                              "offload_impl": "xla"},
    }, world_size=4)
    eng3 = DeepSpeedEngine(SimpleModel(hidden_dim=32), cfg3, mesh=mesh)
    eng2 = DeepSpeedEngine(SimpleModel(hidden_dim=32), _cfg(True),
                           mesh=mesh)

    # stage-3 compute specs are data-sharded (the plan the cast-up honors)
    from jax.sharding import PartitionSpec as P
    specs = eng3.zero_plan.compute_param_specs(
        {"w0": np.zeros((32, 32), np.float32)})
    assert specs["w0"] == P("data", None)

    for i in range(4):
        l3 = float(np.asarray(eng3.train_batch(_batch(i))))
        l2 = float(np.asarray(eng2.train_batch(_batch(i))))
    assert np.isfinite(l3)
    # same math, different placement: both tiers converge identically
    assert abs(l3 - l2) < 2e-2

    # ZeRO-3 gathers a param only AT USE, inside the grad-accum loop
    # (while body), so the replica lives one layer at a time; stage 2
    # gathers params whole once per step OUTSIDE the loop (the fused
    # cast-up).  A param-sized gather at stage-3's top level would mean
    # the cast-up replicated the master — the bug this guards against.
    import re
    piece_n = 4 * max(rec.w for rec in eng3._flat_layout)

    def param_gathers(text):
        """(inside_loop, outside_loop) param-sized all-gather lines."""
        inside, outside = [], []
        for line in text.splitlines():
            if "all-gather" not in line:
                continue
            m = re.search(
                r"= *\(?[a-z0-9]*f\d+\[([0-9,]+)\][^=]*all-gather\(", line)
            if not m:
                continue
            n = int(np.prod([int(d) for d in m.group(1).split(",")]))
            if n >= piece_n:
                (inside if "while/body" in line else outside).append(line)
        return inside, outside

    sharded = eng3._shard_batch(_batch(9))
    hlo = eng3._train_step.lower(eng3.state, sharded).compile().as_text()
    in3, out3 = param_gathers(hlo)
    assert not out3, f"stage-3 gathered params outside the loop: {out3[:1]}"
    assert in3, "stage-3 should gather params at use inside the loop"
    # control: the stage-2 engine's fused cast-up gather is at top level
    sharded2 = eng2._shard_batch(_batch(9))
    hlo2 = eng2._train_step.lower(eng2.state, sharded2).compile().as_text()
    _, out2 = param_gathers(hlo2)
    assert out2, "stage-2 control should gather params outside the loop"


def test_zero3_layout_roundtrip_is_collective_free(mesh):
    """The partition-major flat layout makes the stage-3 unflatten (flat
    P('data') → per-leaf data-sharded compute params) and the reverse
    flatten sharding-natural: the compiled roundtrip must contain NO
    collectives at all.  The naive offset-major layout compiled this to an
    involuntary full rematerialization (replicate + re-partition) of every
    param — the SPMD warning the r02 dryrun log carried."""
    import jax.numpy as jnp
    cfg3 = DeepSpeedConfig({
        "train_micro_batch_size_per_gpu": 2,
        "gradient_accumulation_steps": 2,
        "steps_per_print": 10 ** 9,
        "bf16": {"enabled": True},
        "optimizer": {"type": "Adam", "params": {"lr": 1e-2}},
        "zero_optimization": {"stage": 3, "cpu_offload": True,
                              "offload_impl": "xla"},
    }, world_size=4)
    eng = DeepSpeedEngine(SimpleModel(hidden_dim=32), cfg3, mesh=mesh)

    def roundtrip(pieces):
        return eng._offload_flatten(eng._offload_unflatten(pieces),
                                    jnp.float32)

    n_pieces = len(eng._flat_layout)
    in_sh = (eng._piece_dev_sharding,) * n_pieces
    fn = jax.jit(roundtrip, in_shardings=(in_sh,), out_shardings=in_sh)
    structs = tuple(jax.ShapeDtypeStruct((4, rec.w), jnp.float32)
                    for rec in eng._flat_layout)
    hlo = fn.lower(structs).compile().as_text()
    for op in ("all-gather", "all-reduce", "all-to-all",
               "collective-permute", "reduce-scatter"):
        assert op not in hlo, f"stage-3 layout roundtrip emits {op}"
    # and it is an exact identity on the data
    xs = tuple(
        np.arange(4 * rec.w, dtype=np.float32).reshape(4, rec.w) + i
        for i, rec in enumerate(eng._flat_layout))
    ys = fn(tuple(jax.device_put(x, eng._piece_dev_sharding) for x in xs))
    for x, y in zip(xs, ys):
        np.testing.assert_array_equal(x, np.asarray(jax.device_get(y)))


def test_large_tree_inits_in_compute_dtype(mesh, monkeypatch):
    """Above DS_OFFLOAD_FP32_INIT_LIMIT the init runs in compute dtype
    (halving construction's device peak — what bounds params/chip); the
    staged fp32 master is then the cast of bf16-rounded draws."""
    import jax.numpy as jnp
    monkeypatch.setenv("DS_OFFLOAD_FP32_INIT_LIMIT", "1")
    eng = DeepSpeedEngine(SimpleModel(hidden_dim=32), _cfg(True), mesh=mesh,
                          seed=3)
    monkeypatch.delenv("DS_OFFLOAD_FP32_INIT_LIMIT")
    ref = DeepSpeedEngine(SimpleModel(hidden_dim=32), _cfg(True), mesh=mesh,
                          seed=3)
    got = eng._unflatten_numpy(eng.state.master_params)
    want = ref._unflatten_numpy(ref.state.master_params)
    for k in want:
        rounded = np.asarray(want[k]).astype(jnp.bfloat16).astype(np.float32)
        np.testing.assert_array_equal(np.asarray(got[k]), rounded)
    # and it still trains
    x, y = _batch()
    l0 = float(np.asarray(eng.train_batch((x, y))))
    for _ in range(4):
        l1 = float(np.asarray(eng.train_batch((x, y))))
    assert np.isfinite(l1) and l1 < l0


def test_ga1_scanless_grads_match(mesh):
    """grad_acc=1 skips the fp32 accumulation scan (capacity: the fp32
    loop carry would pin 4N live); trajectory must match ga=1 WITH the
    scan-equivalent non-offload engine."""
    def cfg(off):
        zero = {"stage": 2}
        if off:
            zero.update({"cpu_offload": True, "offload_impl": "xla"})
        return DeepSpeedConfig({
            "train_micro_batch_size_per_gpu": 4,
            "gradient_accumulation_steps": 1,
            "steps_per_print": 10 ** 9,
            "bf16": {"enabled": True},
            "optimizer": {"type": "Adam", "params": {"lr": 1e-2}},
            "zero_optimization": zero,
        }, world_size=4)
    ex = DeepSpeedEngine(SimpleModel(hidden_dim=32), cfg(True), mesh=mesh,
                         seed=3)
    ep = DeepSpeedEngine(SimpleModel(hidden_dim=32), cfg(False), mesh=mesh,
                         seed=3)
    x, y = _batch()
    for _ in range(5):
        lx = float(np.asarray(ex.train_batch((x, y))))
        lp = float(np.asarray(ep.train_batch((x, y))))
        assert abs(lx - lp) < 1e-4, (lx, lp)


@pytest.mark.parametrize("chunks,ga", [(2, 1), (3, 2)])
def test_chunked_grads_match_single_program(mesh, chunks, ga):
    """offload_grad_chunks splits the gradient computation into K
    programs (device grad liveness bounded by the largest group); the
    trajectory must match the single-program tier.  Host-side fp32 clip
    vs on-device bf16 clip is the only divergence, hence exercising
    clipping explicitly."""
    def cfg(k):
        zero = {"stage": 2, "cpu_offload": True, "offload_impl": "xla"}
        if k > 1:
            zero["offload_grad_chunks"] = k
        return DeepSpeedConfig({
            "train_micro_batch_size_per_gpu": 2,
            "gradient_accumulation_steps": ga,
            "steps_per_print": 10 ** 9,
            "bf16": {"enabled": True},
            "gradient_clipping": 0.5,
            "optimizer": {"type": "Adam", "params": {"lr": 1e-2}},
            "zero_optimization": zero,
        }, world_size=4)
    ek = DeepSpeedEngine(SimpleModel(hidden_dim=32, nlayers=4), cfg(chunks),
                         mesh=mesh, seed=3)
    e1 = DeepSpeedEngine(SimpleModel(hidden_dim=32, nlayers=4), cfg(1),
                         mesh=mesh, seed=3)
    rng = np.random.default_rng(0)
    x = rng.normal(size=(8 * ga, 32)).astype(np.float32)
    y = (0.5 * x).astype(np.float32)
    for _ in range(4):
        lk = float(np.asarray(ek.train_batch((x, y))))
        l1 = float(np.asarray(e1.train_batch((x, y))))
        assert abs(lk - l1) < 3e-4, (lk, l1)
    # masters agree leaf-for-leaf after training
    mk = ek._unflatten_numpy(ek.state.master_params)
    m1 = e1._unflatten_numpy(e1.state.master_params)
    for k in m1:
        np.testing.assert_allclose(np.asarray(mk[k]), np.asarray(m1[k]),
                                   rtol=0, atol=5e-4)


def test_chunked_grads_config_sanity():
    with pytest.raises(Exception, match="offload_grad_chunks"):
        DeepSpeedConfig({
            "train_micro_batch_size_per_gpu": 2,
            "bf16": {"enabled": True},
            "optimizer": {"type": "Adam", "params": {"lr": 1e-2}},
            "zero_optimization": {"stage": 2,
                                  "offload_grad_chunks": 2},
        }, world_size=4)


def test_grad_group_partition_is_balanced(mesh):
    """Greedy size-balanced partition: every leaf appears exactly once
    and the heaviest group is within 2x of the ideal share."""
    eng = DeepSpeedEngine(SimpleModel(hidden_dim=32, nlayers=6),
                          _cfg(True), mesh=mesh)
    for k in (2, 3, 5):
        groups = eng._grad_group_indices(k)
        flat = sorted(i for g in groups for i in g)
        assert flat == list(range(len(eng._flat_sizes)))
        loads = [sum(eng._flat_sizes[i] for i in g) for g in groups]
        ideal = sum(eng._flat_sizes) / len(groups)
        assert max(loads) <= 2 * ideal + max(eng._flat_sizes)


def test_xla_dpu_staleness_and_flush(mesh, tmp_path):
    """xla-tier delayed parameter update: steps 0/1 compute at the same
    (initial) master with a fixed batch; save_checkpoint flushes the
    pending update and the loaded engine continues identically."""
    def cfg(dpu):
        return DeepSpeedConfig({
            "train_micro_batch_size_per_gpu": 4,
            "gradient_accumulation_steps": 1,
            "steps_per_print": 10 ** 9,
            "bf16": {"enabled": True},
            "optimizer": {"type": "Adam", "params": {"lr": 1e-2}},
            "zero_optimization": {"stage": 2, "cpu_offload": True,
                                  "offload_impl": "xla",
                                  "delayed_param_update": dpu},
        }, world_size=4)
    x, y = _batch()
    ed = DeepSpeedEngine(SimpleModel(hidden_dim=32), cfg(True), mesh=mesh,
                         seed=3)
    l0 = float(np.asarray(ed.train_batch((x, y))))
    l1 = float(np.asarray(ed.train_batch((x, y))))
    assert l0 == pytest.approx(l1, abs=1e-7), "DPU steps 0/1 share params"
    en = DeepSpeedEngine(SimpleModel(hidden_dim=32), cfg(False), mesh=mesh,
                         seed=3)
    n0 = float(np.asarray(en.train_batch((x, y))))
    n1 = float(np.asarray(en.train_batch((x, y))))
    assert n0 == pytest.approx(l0, abs=1e-6)
    assert abs(n1 - n0) > 1e-6

    losses = [float(np.asarray(ed.train_batch((x, y)))) for _ in range(20)]
    assert losses[-1] < l0 * 0.95, (l0, losses[-3:])

    ed.save_checkpoint(str(tmp_path), tag="t")
    assert ed._xla_dpu_pending is None  # flushed
    ref = float(np.asarray(ed.train_batch((x, y))))
    e2 = DeepSpeedEngine(SimpleModel(hidden_dim=32), cfg(True), mesh=mesh,
                         seed=9)
    path, _ = e2.load_checkpoint(str(tmp_path), tag="t")
    assert path is not None
    got = float(np.asarray(e2.train_batch((x, y))))
    assert got == pytest.approx(ref, abs=1e-6)


def test_xla_dpu_overflow_costs_one_skip(mesh):
    """fp16 + dynamic scale under DPU: finite(t-1) is synced before
    dispatching step t, so one overflow event produces exactly one
    skipped step and one halving — not the double penalty of grads
    dispatched at a stale scale."""

    cfg = DeepSpeedConfig({
        "train_micro_batch_size_per_gpu": 4,
        "gradient_accumulation_steps": 1,
        "steps_per_print": 10 ** 9,
        "fp16": {"enabled": True, "initial_scale_power": 8,
                 "hysteresis": 1, "loss_scale_window": 1000},
        "optimizer": {"type": "Adam", "params": {"lr": 1e-2}},
        "zero_optimization": {"stage": 2, "cpu_offload": True,
                              "offload_impl": "xla",
                              "delayed_param_update": True},
    }, world_size=4)
    eng = DeepSpeedEngine(SimpleModel(hidden_dim=32), cfg, mesh=mesh,
                          seed=3)
    x, y = _batch()
    bad_x = x.copy()
    bad_x[0, 0] = np.float32(3e38)  # inf in fp16 compute -> inf grads
    eng.train_batch((bad_x, y))     # step 0: overflowing grads (pending)
    eng.train_batch((x, y))         # step 1: syncs finite(0) -> skip+halve
    eng.train_batch((x, y))         # step 2: applies step 1's good grads
    eng._xla_dpu_flush()            # apply the last pending
    assert eng.get_skipped_steps() == 1, eng.get_skipped_steps()
    assert float(eng.state.scaler.loss_scale) == 2 ** 7
    # applied steps: 2 good updates landed (steps 1 and 2)
    assert int(eng.state.opt_state.count) == 2


def test_chunked_plus_dpu_compose(mesh):
    """offload_grad_chunks > 1 and delayed_param_update share one
    builder; together they keep the staleness signature and converge."""
    cfg = DeepSpeedConfig({
        "train_micro_batch_size_per_gpu": 4,
        "gradient_accumulation_steps": 1,
        "steps_per_print": 10 ** 9,
        "bf16": {"enabled": True},
        "optimizer": {"type": "Adam", "params": {"lr": 1e-2}},
        "zero_optimization": {"stage": 2, "cpu_offload": True,
                              "offload_impl": "xla",
                              "offload_grad_chunks": 2,
                              "delayed_param_update": True},
    }, world_size=4)
    eng = DeepSpeedEngine(SimpleModel(hidden_dim=32, nlayers=4), cfg,
                          mesh=mesh, seed=3)
    x, y = _batch()
    l0 = float(np.asarray(eng.train_batch((x, y))))
    l1 = float(np.asarray(eng.train_batch((x, y))))
    assert l0 == pytest.approx(l1, abs=1e-7)  # staleness signature
    losses = [float(np.asarray(eng.train_batch((x, y)))) for _ in range(20)]
    assert losses[-1] < l0 * 0.95


@pytest.mark.parametrize("chunks", [1, 2])
def test_split_update_matches_fused(mesh, chunks):
    """offload_split_update turns the optimizer update into one compiled
    program per master piece (HBM liveness bounded by the largest piece
    even where the compiler materializes host placements in HBM — the
    observed 1.5B AOT failure).  Trajectory and final masters must match
    the fused-update tier exactly."""
    def cfg(split):
        zero = {"stage": 2, "cpu_offload": True, "offload_impl": "xla"}
        if split:
            zero["offload_split_update"] = True
        if chunks > 1:
            zero["offload_grad_chunks"] = chunks
        return DeepSpeedConfig({
            "train_micro_batch_size_per_gpu": 2,
            "gradient_accumulation_steps": 2,
            "steps_per_print": 10 ** 9,
            "bf16": {"enabled": True},
            "gradient_clipping": 0.5,
            "optimizer": {"type": "Adam",
                          "params": {"lr": 1e-2, "weight_decay": 0.01}},
            "zero_optimization": zero,
        }, world_size=4)
    es = DeepSpeedEngine(SimpleModel(hidden_dim=32, nlayers=4), cfg(True),
                         mesh=mesh, seed=3)
    ef = DeepSpeedEngine(SimpleModel(hidden_dim=32, nlayers=4), cfg(False),
                         mesh=mesh, seed=3)
    rng = np.random.default_rng(1)
    x = rng.normal(size=(16, 32)).astype(np.float32)
    y = (0.5 * x).astype(np.float32)
    for _ in range(4):
        ls = float(np.asarray(es.train_batch((x, y))))
        lf = float(np.asarray(ef.train_batch((x, y))))
        assert abs(ls - lf) < 3e-4, (ls, lf)
    ms = es._unflatten_numpy(es.state.master_params)
    mf = ef._unflatten_numpy(ef.state.master_params)
    for k in mf:
        np.testing.assert_allclose(np.asarray(ms[k]), np.asarray(mf[k]),
                                   rtol=0, atol=1e-5)
    # counters advanced identically through the split tail program
    assert int(np.asarray(es.state.opt_state.count)) == \
        int(np.asarray(ef.state.opt_state.count))
    assert es.global_steps == ef.global_steps


def test_split_update_overflow_skips_whole_step(mesh):
    """A non-finite gradient must leave every piece untouched (the select
    runs inside each per-piece program) and count one skip."""
    cfgd = {
        "train_micro_batch_size_per_gpu": 2,
        "gradient_accumulation_steps": 2,
        "steps_per_print": 10 ** 9,
        "fp16": {"enabled": True, "initial_scale_power": 4},
        "optimizer": {"type": "Adam", "params": {"lr": 1e-2}},
        "zero_optimization": {"stage": 2, "cpu_offload": True,
                              "offload_impl": "xla",
                              "offload_split_update": True},
    }
    eng = DeepSpeedEngine(SimpleModel(hidden_dim=32),
                          DeepSpeedConfig(cfgd, world_size=4),
                          mesh=mesh, seed=3)
    before = eng._unflatten_numpy(eng.state.master_params)
    x, y = _batch()
    eng.train_batch((np.full_like(x, 1e30), y))   # overflow step
    after = eng._unflatten_numpy(eng.state.master_params)
    for k in before:
        np.testing.assert_array_equal(np.asarray(before[k]),
                                      np.asarray(after[k]))
    assert eng.get_skipped_steps() == 1


def test_split_update_composes_with_dpu(mesh):
    """split update x DPU: the deferred per-piece programs run without
    donation so the next step's grad program keeps reading the old
    pieces.  DPU's defining semantics must hold: steps 0 and 1 compute
    at the INITIAL params (the first update applies during step 1's
    dispatch), so their losses on a fixed batch are identical — and the
    split-DPU trajectory must equal the fused-DPU trajectory."""
    def cfg(split):
        zero = {"stage": 2, "cpu_offload": True, "offload_impl": "xla",
                "delayed_param_update": True}
        if split:
            zero["offload_split_update"] = True
        return DeepSpeedConfig({
            "train_micro_batch_size_per_gpu": 2,
            "gradient_accumulation_steps": 2,
            "steps_per_print": 10 ** 9,
            "bf16": {"enabled": True},
            "optimizer": {"type": "Adam", "params": {"lr": 1e-2}},
            "zero_optimization": zero,
        }, world_size=4)
    es = DeepSpeedEngine(SimpleModel(hidden_dim=32), cfg(True), mesh=mesh,
                         seed=3)
    ef = DeepSpeedEngine(SimpleModel(hidden_dim=32), cfg(False), mesh=mesh,
                         seed=3)
    x, y = _batch()
    ls = [float(np.asarray(es.train_batch((x, y)))) for _ in range(5)]
    lf = [float(np.asarray(ef.train_batch((x, y)))) for _ in range(5)]
    assert abs(ls[0] - ls[1]) < 1e-6, "DPU staleness: steps 0,1 equal"
    np.testing.assert_allclose(ls, lf, rtol=0, atol=3e-4)
    # flush applies the pending update before a save
    es._xla_dpu_flush()
    assert es._xla_dpu_pending is None


def test_split_update_env_knob_rejected_on_host_tier(monkeypatch):
    """DS_OFFLOAD_SPLIT_UPDATE=1 must fail as loudly on the host tier as
    the config flag does — a hardware experiment silently measuring the
    fused/host path is the exact confusion the raise prevents."""
    monkeypatch.setenv("DS_OFFLOAD_SPLIT_UPDATE", "1")
    cfg = DeepSpeedConfig({
        "train_micro_batch_size_per_gpu": 2,
        "gradient_accumulation_steps": 1,
        "steps_per_print": 10 ** 9,
        "bf16": {"enabled": True},
        "optimizer": {"type": "Adam", "params": {"lr": 1e-2}},
        "zero_optimization": {"stage": 2, "cpu_offload": True,
                              "offload_impl": "host"},
    }, world_size=1)
    with pytest.raises(ValueError, match="xla-tier"):
        DeepSpeedEngine(SimpleModel(hidden_dim=32), cfg,
                        mesh=build_mesh(dp=1, devices=jax.devices()[:1]))


def test_split_update_env_knob_scoped_to_offload_engines(monkeypatch,
                                                         caplog):
    """DS_OFFLOAD_SPLIT_UPDATE=1 is process-wide; a comparison/eval
    engine without cpu_offload built alongside the experiment engine must
    construct (with a warning), not die — while an offload engine under
    the same env var actually engages the split update (ADVICE.md round
    5, engine.py:291)."""
    monkeypatch.setenv("DS_OFFLOAD_SPLIT_UPDATE", "1")

    def cfgd(offload):
        zero = {"stage": 2}
        if offload:
            zero.update({"cpu_offload": True, "offload_impl": "xla"})
        return DeepSpeedConfig({
            "train_micro_batch_size_per_gpu": 2,
            "gradient_accumulation_steps": 1,
            "steps_per_print": 10 ** 9,
            "bf16": {"enabled": True},
            "optimizer": {"type": "Adam", "params": {"lr": 1e-2}},
            "zero_optimization": zero,
        }, world_size=1)

    mesh = build_mesh(dp=1, devices=jax.devices()[:1])
    import logging
    from deepspeed_tpu.utils.logging import logger as ds_logger
    monkeypatch.setattr(ds_logger, "propagate", True)  # let caplog see it
    with caplog.at_level(logging.WARNING, logger="DeepSpeedTPU"):
        plain = DeepSpeedEngine(SimpleModel(hidden_dim=32), cfgd(False),
                                mesh=mesh)
    assert any("DS_OFFLOAD_SPLIT_UPDATE=1 ignored" in r.message
               for r in caplog.records)
    # the experiment engine in the same process still gets the split
    # update (one compiled program per piece) from the env knob
    off = DeepSpeedEngine(SimpleModel(hidden_dim=32), cfgd(True),
                          mesh=mesh)
    assert "_build_chunked_offload_steps" in off._train_step.__qualname__
    x, y = _batch()
    x, y = x[:2], y[:2]   # dp=1, grad_acc=1: one 2-row micro batch
    l_plain = float(np.asarray(plain.train_batch((x, y))))
    l_off = float(np.asarray(off.train_batch((x, y))))
    assert np.isfinite(l_plain) and np.isfinite(l_off)


def test_poisoned_engine_recovers_via_load_checkpoint(mesh, tmp_path):
    """The poison message tells users to load_checkpoint; a successful
    load rebuilds the whole TrainState, so it must clear the poison."""
    def cfg():
        return DeepSpeedConfig({
            "train_micro_batch_size_per_gpu": 2,
            "gradient_accumulation_steps": 2,
            "steps_per_print": 10 ** 9,
            "bf16": {"enabled": True},
            "optimizer": {"type": "Adam", "params": {"lr": 1e-2}},
            "zero_optimization": {"stage": 2, "cpu_offload": True,
                                  "offload_impl": "xla",
                                  "offload_split_update": True},
        }, world_size=4)
    eng = DeepSpeedEngine(SimpleModel(hidden_dim=32), cfg(), mesh=mesh,
                          seed=3)
    x, y = _batch()
    eng.train_batch((x, y))
    eng.save_checkpoint(str(tmp_path), tag="ok")
    eng._fatal_state_error = "simulated mid-piece donation failure"
    with pytest.raises(RuntimeError, match="simulated"):
        eng.train_batch((x, y))
    with pytest.raises(RuntimeError, match="simulated"):
        eng.save_checkpoint(str(tmp_path), tag="nope")
    eng.load_checkpoint(str(tmp_path), tag="ok")
    loss = float(np.asarray(eng.train_batch((x, y))))   # healthy again
    assert np.isfinite(loss)


def _split_cfg():
    return DeepSpeedConfig({
        "train_micro_batch_size_per_gpu": 2,
        "gradient_accumulation_steps": 2,
        "steps_per_print": 10 ** 9,
        "bf16": {"enabled": True},
        "optimizer": {"type": "Adam", "params": {"lr": 1e-2}},
        "zero_optimization": {"stage": 2, "cpu_offload": True,
                              "offload_impl": "xla",
                              "offload_split_update": True},
    }, world_size=4)


def test_split_update_keyboard_interrupt_poisons_state(mesh, monkeypatch):
    """A KeyboardInterrupt mid piece-loop deletes donated buffers exactly
    like a crash does: it must poison _fatal_state_error (and keep its
    own exception type) so a later save_checkpoint refuses with the
    recovery message instead of 'Array has been deleted' (ADVICE.md
    round 5, engine.py:1709)."""
    eng = DeepSpeedEngine(SimpleModel(hidden_dim=32), _split_cfg(),
                          mesh=mesh, seed=3)
    x, y = _batch()
    eng.train_batch((x, y))   # compile + one healthy step

    # Ctrl-C lands inside the piece update (the donating program)
    real = eng._host_adam_pieces

    def interrupted(*a, **k):
        raise KeyboardInterrupt

    monkeypatch.setattr(eng, "_host_adam_pieces", interrupted)
    monkeypatch.setattr(eng, "_train_step",
                        eng._build_chunked_offload_steps(
                            eng._grad_group_indices(1),
                            split_update=True))
    with pytest.raises(KeyboardInterrupt):
        eng.train_batch((x, y))
    assert eng._fatal_state_error is not None
    assert "donated" in eng._fatal_state_error
    with pytest.raises(RuntimeError, match="load_checkpoint"):
        eng.save_checkpoint("/tmp/never-written")
    monkeypatch.setattr(eng, "_host_adam_pieces", real)


def test_poisoned_engine_refuses_eval_and_forward(mesh):
    """eval_batch/forward read self.state too: after a mid-piece donation
    failure they must surface the recovery message, not the raw
    deleted-buffer error (ADVICE.md round 5, engine.py:2425)."""
    eng = DeepSpeedEngine(SimpleModel(hidden_dim=32), _split_cfg(),
                          mesh=mesh, seed=3)
    x, y = _batch()
    eng._fatal_state_error = "simulated mid-piece donation failure"
    with pytest.raises(RuntimeError, match="simulated"):
        eng.eval_batch((x, y))
    with pytest.raises(RuntimeError, match="simulated"):
        eng.forward((x, y))


def test_split_update_tail_outputs_pinned_replicated(mesh):
    """The split tail program must pin scaler/counter outputs to the same
    replicated sharding the fused update uses — without out_shardings
    they ride default placement and their avals diverge from the fused
    state on a multi-device mesh (ADVICE.md round 5, engine.py:1685 —
    jaxlint JL003's first confirmed catch)."""
    from jax.sharding import NamedSharding, PartitionSpec
    eng = DeepSpeedEngine(SimpleModel(hidden_dim=32), _split_cfg(),
                          mesh=mesh, seed=3)
    x, y = _batch()
    eng.train_batch((x, y))
    replicated = NamedSharding(mesh, PartitionSpec())
    for name, arr in [("global_steps", eng.state.global_steps),
                      ("skipped_steps", eng.state.skipped_steps),
                      ("count", eng.state.opt_state.count),
                      ("loss_scale", eng.state.scaler.loss_scale)]:
        assert arr.sharding.is_equivalent_to(replicated, arr.ndim), \
            (name, arr.sharding)
