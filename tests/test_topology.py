"""Topology / grid math (mirrors reference tests/unit/test_topology.py)."""
import pytest

from deepspeed_tpu.parallel import (
    ProcessTopology, PipeDataParallelTopology, PipeModelDataParallelTopology,
    ParallelGrid,
)


def test_topology_2d_ranks():
    topo = ProcessTopology(axes=["row", "col"], dims=[2, 2])
    assert topo.world_size() == 4
    assert topo.get_rank(row=0, col=0) == 0
    assert topo.get_rank(row=0, col=1) == 1
    assert topo.get_rank(row=1, col=0) == 2
    assert topo.get_rank(row=1, col=1) == 3


def test_topology_coords_roundtrip():
    topo = PipeModelDataParallelTopology(num_pp=2, num_mp=2, num_dp=2)
    for r in range(topo.world_size()):
        c = topo.get_coord(r)
        assert topo.get_rank(**c._asdict()) == r


def test_topology_missing_axis_raises():
    topo = ProcessTopology(axes=["a", "b"], dims=[2, 2])
    with pytest.raises(ValueError):
        topo.get_rank(a=0)


def test_axis_comm_lists():
    topo = PipeDataParallelTopology(num_pp=2, num_dp=4)
    data_lists = topo.get_axis_comm_lists("data")
    assert data_lists == [[0, 1, 2, 3], [4, 5, 6, 7]]
    pipe_lists = topo.get_axis_comm_lists("pipe")
    assert pipe_lists == [[0, 4], [1, 5], [2, 6], [3, 7]]


def test_filter_match():
    topo = PipeModelDataParallelTopology(num_pp=2, num_mp=2, num_dp=2)
    ranks = topo.filter_match(pipe=0)
    assert ranks == [0, 1, 2, 3]
    ranks = topo.filter_match(pipe=1, model=1)
    assert len(ranks) == 2


def test_rank_repr():
    topo = PipeModelDataParallelTopology(num_pp=2, num_mp=2, num_dp=2)
    # data axis omitted by default → checkpoint naming stable across DP
    r = topo.get_rank(pipe=1, data=0, model=1)
    assert "pipe_01" in topo.get_rank_repr(r)
    assert "model_01" in topo.get_rank_repr(r)
    assert "data" not in topo.get_rank_repr(r)


def test_grid_queries():
    topo = PipeModelDataParallelTopology(num_pp=2, num_mp=2, num_dp=2)
    grid = ParallelGrid(topo, rank=topo.get_rank(pipe=1, data=1, model=0))
    assert grid.get_pipe_parallel_rank() == 1
    assert grid.get_data_parallel_rank() == 1
    assert grid.get_model_parallel_rank() == 0
    assert grid.get_pipe_parallel_world_size() == 2
    assert grid.get_data_parallel_world_size() == 2
    assert grid.get_slice_parallel_world_size() == 2
    assert grid.is_last_stage()
    assert not grid.is_first_stage()


def test_grid_stage_to_global():
    topo = PipeDataParallelTopology(num_pp=4, num_dp=2)
    grid = ParallelGrid(topo, rank=topo.get_rank(pipe=1, data=1))
    nxt = grid.stage_to_global(2)
    assert topo.get_coord(nxt).pipe == 2
    assert topo.get_coord(nxt).data == 1


def test_grid_missing_axis_defaults():
    topo = PipeDataParallelTopology(num_pp=2, num_dp=2)
    grid = ParallelGrid(topo, rank=0)
    assert grid.get_model_parallel_world_size() == 1
    assert grid.get_model_parallel_rank() == 0
