"""Test fixtures — the JAX analogue of the reference's
tests/unit/simple_model.py (SimpleModel :9-25, random_dataloader :115).
"""
from __future__ import annotations

import numpy as np
import jax
import jax.numpy as jnp

from deepspeed_tpu.runtime.module import TrainModule


class SimpleModel(TrainModule):
    """Stack of linear layers + MSE loss (loss-returning model, like the
    reference fixture)."""

    def __init__(self, hidden_dim: int = 16, nlayers: int = 2):
        self.hidden_dim = hidden_dim
        self.nlayers = nlayers

    def init(self, rng):
        params = {}
        for i in range(self.nlayers):
            rng, k = jax.random.split(rng)
            params[f"w{i}"] = jax.random.normal(
                k, (self.hidden_dim, self.hidden_dim), jnp.float32) * 0.1
            params[f"b{i}"] = jnp.zeros((self.hidden_dim,), jnp.float32)
        return params

    def loss_fn(self, params, batch, rng, train: bool = True):
        x, y = batch
        h = x
        for i in range(self.nlayers):
            h = h @ params[f"w{i}"].astype(h.dtype) + \
                params[f"b{i}"].astype(h.dtype)
            if i < self.nlayers - 1:
                h = jax.nn.relu(h)
        return jnp.mean((h.astype(jnp.float32) - y.astype(jnp.float32)) ** 2)


def random_batches(batch_size: int, hidden_dim: int, num_batches: int = 8,
                   seed: int = 0, dtype=np.float32):
    rng = np.random.default_rng(seed)
    for _ in range(num_batches):
        x = rng.standard_normal((batch_size, hidden_dim)).astype(dtype)
        # a learnable linear target keeps the loss reducible
        y = (0.5 * x).astype(dtype)
        yield (x, y)


def base_config(micro_bs=4, grad_acc=1, stage=0, precision="bf16", **over):
    cfg = {
        "train_micro_batch_size_per_gpu": micro_bs,
        "gradient_accumulation_steps": grad_acc,
        "steps_per_print": 1000,
        "optimizer": {"type": "Adam", "params": {"lr": 1e-2}},
        "zero_optimization": {"stage": stage},
    }
    if precision == "bf16":
        cfg["bf16"] = {"enabled": True}
    elif precision == "fp16":
        cfg["fp16"] = {"enabled": True}
    elif precision == "fp32":
        pass
    cfg.update(over)
    return cfg
