"""Engine-integrated 1-bit Adam: the compressed collective REPLACES the
gradient reduction on the wire.

The reference gets its communication saving by disabling the engine's
allreduce once frozen and exchanging error-compensated 1-bit momentum via
MPI (reference: deepspeed/runtime/fp16/onebit_adam.py:104-228, engine
handoff :366-372).  Here the engine compiles two shard_map programs (warm /
frozen) selected host-side at the freeze boundary; these tests assert BOTH
convergence across the boundary at dp=8 AND — from the compiled HLO — that
the frozen program's only gradient-sized collectives are uint8.
"""
import re

import numpy as np
import jax
import pytest

from deepspeed_tpu.config import DeepSpeedConfig
from deepspeed_tpu.parallel import build_mesh
from deepspeed_tpu.runtime.engine import DeepSpeedEngine

from simple_model import SimpleModel, base_config, random_batches

FREEZE = 5


def _engine(freeze=FREEZE, nlayers=2, hidden=16, lr=5e-3):
    cfg_dict = base_config(micro_bs=8, grad_acc=1)
    cfg_dict["optimizer"] = {
        "type": "OneBitAdam",
        "params": {"lr": lr, "freeze_step": freeze}}
    cfg = DeepSpeedConfig(cfg_dict, world_size=8)
    mesh = build_mesh(dp=8, devices=jax.devices())
    return DeepSpeedEngine(
        SimpleModel(hidden_dim=hidden, nlayers=nlayers), cfg,
        mesh=mesh), cfg


def _collectives(hlo_text):
    """[(op, dtype, elems)] for every collective in an HLO dump."""
    out = []
    for m in re.finditer(
            r"(all-reduce|all-to-all|all-gather|reduce-scatter|"
            r"collective-permute)[^=]*\"?\s*=?\s*", hlo_text):
        # the op's result type precedes the op name: scan the line
        line = hlo_text[hlo_text.rfind("\n", 0, m.start()) + 1:
                        hlo_text.find("\n", m.end())]
        tm = re.search(r"(\w+)\[([\d,]*)\]", line)
        if not tm:
            continue
        dtype = tm.group(1)
        dims = tm.group(2)
        elems = int(np.prod([int(d) for d in dims.split(",") if d])) \
            if dims else 1
        op = m.group(1)
        out.append((op, dtype, elems))
    return out


def test_converges_across_freeze_boundary():
    eng, cfg = _engine(freeze=10, lr=5e-3)
    losses = []
    for b in random_batches(cfg.train_batch_size, 16, num_batches=40,
                            seed=7):
        losses.append(float(np.asarray(eng.train_batch(b))))
    assert losses[-1] < losses[0] * 0.5, losses
    assert eng.get_skipped_steps() == 0
    # the frozen program really took over
    assert eng.global_steps > 10
    st = eng.state.opt_state
    # error feedback engaged: worker error buffers are nonzero post-freeze
    we = np.concatenate([np.asarray(l).ravel()
                         for l in jax.tree.leaves(st.worker_error)])
    assert np.abs(we).max() > 0


def _float_collective_elems(hlo_text):
    """Largest per-shard element count over float-typed collectives."""
    return max((n for op, dt, n in _collectives(hlo_text)
                if dt in ("f32", "bf16", "f16", "f64")), default=0)


def test_frozen_hlo_wire_bytes_are_uint8():
    """VERDICT #3's done-criterion: in the compiled frozen program every
    float collective is scalar-sized bookkeeping (loss/overflow/norm
    psums, the per-worker scale all-gathers — O(dp) elements); the
    momentum exchange itself is uint8 all-to-all/all-gather.  The warm
    program still carries the fp32 gradient reduction (biggest leaf)."""
    eng, cfg = _engine(hidden=32)
    biggest_leaf = max(int(np.prod(l.shape)) for l in
                       jax.tree.leaves(eng.state.master_params))
    assert biggest_leaf >= 1024

    batch = next(random_batches(cfg.train_batch_size, 32, num_batches=1))
    sharded = eng._shard_batch(batch)
    warm_fn, frozen_fn, _ = eng._onebit_steps

    frozen_txt = frozen_fn.lower(eng.state, sharded).compile().as_text()
    warm_txt = warm_fn.lower(eng.state, sharded).compile().as_text()

    # u8 momentum exchange is present...
    u8 = [(op, n) for op, dt, n in _collectives(frozen_txt) if dt == "u8"]
    assert any(op == "all-to-all" for op, _ in u8), u8
    # ...and NO float collective approaches gradient size: the largest is
    # the dp-sized scale gather, orders of magnitude below the fp32 grad
    # reduction the warm program performs.
    f_frozen = _float_collective_elems(frozen_txt)
    f_warm = _float_collective_elems(warm_txt)
    assert f_frozen <= 4 * 8, (
        f"frozen program still moves float grad data: {f_frozen} elems")
    assert f_warm >= biggest_leaf, (
        f"warm program should carry the fp32 gradient reduction "
        f"({f_warm} < {biggest_leaf})")


def test_module_only_restore_keeps_stacked_error_buffers(tmp_path):
    """Module-only restore must rebuild the engine-internal opt state
    (stacked [dp, n] per-worker error buffers) — a plain optimizer.init
    would produce a world=1 state the compiled shard_map step can't eat."""
    eng, cfg = _engine(freeze=2)
    batches = list(random_batches(cfg.train_batch_size, 16, num_batches=6,
                                  seed=3))
    for b in batches[:4]:
        eng.train_batch(b)
    eng.save_checkpoint(str(tmp_path), tag="t0")

    e2, _ = _engine(freeze=2)
    path, _ = e2.load_checkpoint(str(tmp_path), tag="t0",
                                 load_module_only=True)
    assert path is not None
    we_leaf = jax.tree.leaves(e2.state.opt_state.worker_error)[0]
    assert we_leaf.shape[0] == 8  # stacked per-worker
    # resumed engine is already past freeze -> next step runs the frozen
    # shard_map program against the restored state
    l = float(np.asarray(e2.train_batch(batches[4])))
    assert np.isfinite(l)


def test_warm_phase_matches_reference_adam_semantics():
    """Warm steps are plain (bias-correction-free) Adam on pmean'd grads —
    the trajectory must be deterministic across the program pair: running
    N<freeze steps gives identical params whether freeze is far or near."""
    eng_a, cfg = _engine(freeze=100)
    eng_b, _ = _engine(freeze=3)
    batches = list(random_batches(cfg.train_batch_size, 16, num_batches=3,
                                  seed=11))
    for b in batches:
        la = float(np.asarray(eng_a.train_batch(b)))
        lb = float(np.asarray(eng_b.train_batch(b)))
        assert la == pytest.approx(lb, abs=1e-6)
    pa = jax.tree.leaves(eng_a.state.master_params)
    pb = jax.tree.leaves(eng_b.state.master_params)
    for a, b in zip(pa, pb):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))


def test_onebit_terminal_loss_parity_with_adam():
    """Convergence parity past freeze_step — the reference's core 1-bit
    Adam claim ("same convergence as Adam",
    reference docs/_posts/2020-09-09-onebit-adam-blog-post.md:85): same
    model/seeds/data, OneBitAdam vs plain Adam, terminal losses must
    agree within a small multiple after the compressed stage has run
    3x the warm stage."""
    steps, freeze = 60, 15
    batches = list(random_batches(64, 16, num_batches=steps, seed=21))

    eng_1bit, _ = _engine(freeze=freeze, lr=5e-3)

    cfg_dict = base_config(micro_bs=8, grad_acc=1)
    cfg_dict["optimizer"] = {"type": "Adam", "params": {"lr": 5e-3}}
    cfg_adam = DeepSpeedConfig(cfg_dict, world_size=8)
    eng_adam = DeepSpeedEngine(
        SimpleModel(hidden_dim=16, nlayers=2), cfg_adam,
        mesh=build_mesh(dp=8, devices=jax.devices()))

    l1 = [float(np.asarray(eng_1bit.train_batch(b))) for b in batches]
    la = [float(np.asarray(eng_adam.train_batch(b))) for b in batches]

    # both converge...
    assert l1[-1] < l1[0] * 0.5, l1[:3] + l1[-3:]
    assert la[-1] < la[0] * 0.5, la[:3] + la[-3:]
    # ...and the compressed run tracks plain Adam at the end: terminal
    # loss within 1.5x (the curves are identical until freeze_step, so a
    # broken compressed stage shows up as a multiple-x gap or divergence)
    tail1 = float(np.mean(l1[-5:]))
    taila = float(np.mean(la[-5:]))
    assert tail1 <= 1.5 * taila + 1e-3, (tail1, taila)
    # warm stage runs the same Adam math pre-freeze; the first step is
    # bit-near (init + first forward identical — the 1-bit engine's
    # manual-collective program only reorders reductions), later warm
    # steps drift at bf16 noise scale and are covered by the tail check
    np.testing.assert_allclose(l1[0], la[0], rtol=2e-2, atol=2e-3)
