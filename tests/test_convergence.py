"""Real-corpus convergence regression — the repo's analogue of the
reference's Megatron-GPT2 convergence tier, which trains on real text and
diffs the loss curve against a checked-in baseline (reference:
tests/model/Megatron_GPT2/test_common.py:12+ and the checked-in
ds_config/baseline curves next to it).

The baseline artifact (tests/baselines/convergence_gpt2.json) is produced
by examples/convergence_gpt2.py through the full user path (``ds``
launcher -> initialize -> train_batch) on 600 steps of the vendored real
corpus.  Tests here:

  * the banked curve itself shows sustained convergence on real text
  * a re-run of the first steps reproduces the banked curve (numerics
    regression; same platform + seeds -> float round-off only)
"""
import json
import os
import subprocess
import sys

import numpy as np
import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
BASELINE = os.path.join(REPO, "tests", "baselines", "convergence_gpt2.json")

needs_baseline = pytest.mark.skipif(
    not os.path.exists(BASELINE),
    reason="baseline curve not banked yet (examples/convergence_gpt2.py)")


@needs_baseline
def test_banked_curve_shows_real_convergence():
    with open(BASELINE) as f:
        base = json.load(f)
    losses = np.array(base["losses"], dtype=np.float64)
    assert len(losses) >= 500, "convergence tier requires 500+ steps"
    first, last = losses[:20].mean(), losses[-50:].mean()
    # from ~ln(V)=8.3 the model must make sustained real progress
    assert first > 7.0, f"suspicious start {first}"
    assert last < first - 1.5, f"no convergence: {first} -> {last}"
    # sustained, not a lucky dip: every quarter improves on the previous
    q = len(losses) // 4
    means = [losses[i * q:(i + 1) * q].mean() for i in range(4)]
    assert all(b < a for a, b in zip(means, means[1:])), means


@needs_baseline
@pytest.mark.slow
def test_rerun_reproduces_banked_prefix(tmp_path):
    """80-step re-run through the same entry point must match the banked
    curve — catches any numerics drift in engine/optimizer/model/data."""
    out = str(tmp_path / "rerun.json")
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    # the baseline trained on ONE cpu device; pytest's conftest appends
    # an 8-virtual-device token to XLA_FLAGS that would hand the
    # subprocess a dp=8 mesh (8x the work on this 1-core host AND
    # different batch semantics than the banked curve).  Strip ONLY that
    # token — any other inherited XLA flags also applied when the
    # baseline was banked outside pytest.
    flags = " ".join(
        tok for tok in env.get("XLA_FLAGS", "").split()
        if not tok.startswith("--xla_force_host_platform_device_count"))
    if flags:
        env["XLA_FLAGS"] = flags
    else:
        env.pop("XLA_FLAGS", None)
    subprocess.run(
        [sys.executable, os.path.join(REPO, "examples", "convergence_gpt2.py"),
         "--cpu", "--steps", "80", "--out", out],
        check=True, cwd=str(tmp_path), env=env, timeout=1200)
    with open(out) as f:
        rerun = np.array(json.load(f)["losses"], dtype=np.float64)
    with open(BASELINE) as f:
        base = np.array(json.load(f)["losses"][:80], dtype=np.float64)
    np.testing.assert_allclose(rerun, base, rtol=2e-3, atol=2e-3)
