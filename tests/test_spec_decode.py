"""Speculative decoding (docs/serving.md): multi-query kernel
differentials, widened-verify parity against sequential decode, the
acceptance math (greedy + rejection sampling), the engine-level parity
bar (speculative stream == non-speculative stream, greedy, at every k,
both KV layouts, dp1 and dp2×tp2, zero recompiles), accepted-length-
variance scheduler semantics, telemetry flow, and the bench smoke.
"""
import json
import os

import numpy as np
import jax
import jax.numpy as jnp
import pytest

from deepspeed_tpu.config import DeepSpeedConfigError
from deepspeed_tpu.config.config import DeepSpeedServingConfig
from deepspeed_tpu.inference import ServeEngine
from deepspeed_tpu.inference.speculative import (greedy_accept,
                                                 rejection_sample_accept,
                                                 select_next_token)
from deepspeed_tpu.models.gpt2 import (GPT2Config, GPT2Model,
                                       gpt2_decode_step, gpt2_prefill,
                                       gpt2_verify_step)
from deepspeed_tpu.ops.pallas.decode_attention import (
    decode_attention_multi, decode_attention_multi_reference,
    decode_attention_paged_multi, decode_attention_reference,
    paged_gather)
from deepspeed_tpu.parallel import build_mesh
from deepspeed_tpu.runtime.stages import reset_fault_injection

TINY = GPT2Config(vocab_size=128, n_positions=64, d_model=32, n_layer=2,
                  n_head=4, remat=None, attn_impl="dense")
DRAFT_BLOCK = {"d_model": 32, "n_layer": 2, "n_head": 4}

_CHAOS_ENVS = ("DS_STAGE_FAULT", "DS_STAGE_DELAY_S")


@pytest.fixture(autouse=True)
def _clean_faults(monkeypatch):
    for env in _CHAOS_ENVS:
        monkeypatch.delenv(env, raising=False)
    reset_fault_injection()
    yield
    reset_fault_injection()


def _tokens(n, vocab=128, seed=0):
    return np.random.default_rng(seed).integers(
        0, vocab, (n,)).astype(np.int32)


# ---------------------------------------------------------------------------
# multi-query kernel differentials
# ---------------------------------------------------------------------------


def _multi_case(S=3, H=2, T=128, Dh=32, W=5, seed=0):
    rng = np.random.RandomState(seed)
    q = jnp.asarray(rng.randn(S, H, W, Dh), jnp.float32)
    k = jnp.asarray(rng.randn(S, H, T, Dh), jnp.float32)
    v = jnp.asarray(rng.randn(S, H, T, Dh), jnp.float32)
    base = np.array([0, 17, T - W - 2][:S], np.int32)
    lens = np.where(base[:, None] > 0,
                    base[:, None] + np.arange(1, W + 1)[None, :],
                    0).astype(np.int32)
    return q, k, v, jnp.asarray(np.minimum(lens, T))


def test_multi_dense_is_stacked_single_queries_bitwise():
    """The dense multi arm is DEFINED as W stacked single-query
    references — the fp32-bitwise anchor the widened program rests
    on."""
    q, k, v, lens = _multi_case()
    out = decode_attention_multi(q, k, v, lens, impl="dense")
    for i in range(q.shape[2]):
        ref = decode_attention_reference(q[:, :, i], k, v, lens[:, i])
        np.testing.assert_array_equal(np.asarray(out[:, :, i]),
                                      np.asarray(ref))
    # slot with all-zero row lengths -> exact zeros
    assert (np.asarray(out[0]) == 0).all()


@pytest.mark.parametrize("block_k", [32, 64, 256])
def test_multi_pallas_matches_dense(block_k):
    q, k, v, lens = _multi_case()
    out_p = decode_attention_multi(q, k, v, lens, impl="pallas",
                                   block_k=block_k)
    out_d = decode_attention_multi(q, k, v, lens, impl="dense")
    np.testing.assert_allclose(out_p, out_d, atol=2e-6, rtol=2e-6)
    assert (np.asarray(out_p[0]) == 0).all()


def test_multi_pallas_w9_sublane_padding():
    """W=9 (k=8) crosses the 8-row sublane tile: the padded rows must
    stay exact-zero and the live rows correct."""
    q, k, v, _ = _multi_case(W=9)
    lens = jnp.asarray(
        np.minimum(np.array([[5], [17], [100]], np.int32)
                   + np.arange(1, 10)[None, :], 128))
    out_p = decode_attention_multi(q, k, v, lens, impl="pallas",
                                   block_k=64)
    out_d = decode_attention_multi(q, k, v, lens, impl="dense")
    np.testing.assert_allclose(out_p, out_d, atol=2e-6, rtol=2e-6)


def test_multi_masks_garbage_tail():
    """Keys at/beyond each ROW's length are garbage (rejected
    speculation, evicted requests) and must never be attended."""
    q, k, v, lens = _multi_case(T=64)
    limit = int(np.asarray(lens).max())
    bad_k = k.at[:, :, limit:].set(1e4)
    bad_v = v.at[:, :, limit:].set(1e4)
    for impl in ("pallas", "dense"):
        clean = decode_attention_multi(q, k, v, lens, impl=impl)
        dirty = decode_attention_multi(q, bad_k, bad_v, lens, impl=impl)
        np.testing.assert_array_equal(np.asarray(clean),
                                      np.asarray(dirty))


def _paged_case(S=3, H=2, W=5, page_len=16, pages=17, max_pages=8,
                seed=1):
    rng = np.random.RandomState(seed)
    Dh = 32
    q = jnp.asarray(rng.randn(S, H, W, Dh), jnp.float32)
    kp = jnp.asarray(rng.randn(pages, H, page_len, Dh), jnp.float32)
    vp = jnp.asarray(rng.randn(pages, H, page_len, Dh), jnp.float32)
    pt = np.zeros((S, max_pages), np.int32)
    ids = list(range(1, pages))
    for s in range(S):
        for m in range(max_pages):
            pt[s, m] = ids.pop(0) if ids else 0
    base = np.array([0, 9, 100][:S], np.int32)
    lens = np.where(base[:, None] > 0,
                    base[:, None] + np.arange(1, W + 1)[None, :],
                    0).astype(np.int32)
    lens = np.minimum(lens, max_pages * page_len)
    return q, kp, vp, jnp.asarray(pt), jnp.asarray(lens)


def test_paged_multi_dense_matches_gathered_reference():
    q, kp, vp, pt, lens = _paged_case()
    out = decode_attention_paged_multi(q, kp, vp, pt, lens,
                                       impl="dense")
    kg, vg = paged_gather(kp, pt), paged_gather(vp, pt)
    ref = decode_attention_multi_reference(q, kg, vg, lens)
    np.testing.assert_array_equal(np.asarray(out), np.asarray(ref))


def test_paged_multi_pallas_matches_dense():
    q, kp, vp, pt, lens = _paged_case()
    out_p = decode_attention_paged_multi(q, kp, vp, pt, lens,
                                         impl="pallas")
    out_d = decode_attention_paged_multi(q, kp, vp, pt, lens,
                                         impl="dense")
    np.testing.assert_allclose(out_p, out_d, atol=2e-6, rtol=2e-6)
    assert (np.asarray(out_p[0]) == 0).all()


def test_multi_single_compile_across_length_mixes():
    """Traced per-row lengths: one jit cache entry for any accepted-
    length mix."""
    q, k, v, _ = _multi_case(T=64)
    f = jax.jit(lambda q, k, v, l: decode_attention_multi(
        q, k, v, l, impl="pallas"))
    S, _, W, _ = q.shape
    for lens in (np.zeros((S, W)), np.full((S, W), 7),
                 np.arange(S * W).reshape(S, W) % 60):
        f(q, k, v, jnp.asarray(lens, jnp.int32)).block_until_ready()
    assert f._cache_size() == 1


# ---------------------------------------------------------------------------
# widened verify vs sequential decode ticks
# ---------------------------------------------------------------------------


def test_verify_step_matches_sequential_decode():
    """One verify pass over W tokens == W sequential decode ticks:
    same logits (ulp-tier — the qkv einsum widens), same argmaxes,
    same K/V rows written."""
    cfg = TINY
    model = GPT2Model(cfg)
    params = model.init(jax.random.PRNGKey(0))
    S, T = 2, 32
    prompt = _tokens(6)[None, :].repeat(S, axis=0)
    logits, ks, vs = gpt2_prefill(cfg, params, jnp.asarray(prompt))
    k_cache = jnp.zeros((cfg.n_layer, S, cfg.n_head, T, cfg.d_head))
    v_cache = jnp.zeros_like(k_cache)
    k_cache = k_cache.at[:, :, :, :6].set(ks.transpose(0, 1, 2, 3, 4))
    v_cache = v_cache.at[:, :, :, :6].set(vs)
    lengths = jnp.full((S,), 6, jnp.int32)
    active = jnp.ones((S,), bool)
    toks = np.stack([_tokens(5, seed=3), _tokens(5, seed=4)])
    # sequential reference
    seq_logits = []
    kc, vc, ln = k_cache, v_cache, lengths
    for i in range(5):
        lg, kc, vc, ln = gpt2_decode_step(
            cfg, params, jnp.asarray(toks[:, i]), kc, vc, ln, active)
        seq_logits.append(lg)
    # one widened pass
    w_logits, kw, vw = gpt2_verify_step(
        cfg, params, jnp.asarray(toks), k_cache, v_cache, lengths,
        active)
    for i in range(5):
        np.testing.assert_allclose(np.asarray(w_logits[:, i]),
                                   np.asarray(seq_logits[i]),
                                   atol=1e-5, rtol=1e-5)
        assert (np.argmax(np.asarray(w_logits[:, i]), -1)
                == np.argmax(np.asarray(seq_logits[i]), -1)).all()
    np.testing.assert_allclose(np.asarray(kw), np.asarray(kc),
                               atol=1e-6, rtol=1e-6)


# ---------------------------------------------------------------------------
# acceptance math (inference/speculative.py)
# ---------------------------------------------------------------------------


def test_select_next_token_greedy_is_argmax_bitwise():
    """The satellite regression: the shared helper at temperature 0 is
    bitwise the argmax the engine used to inline at its four
    prefill/decode emission sites."""
    rng = np.random.RandomState(0)
    for shape in ((7,), (3, 9), (2, 4, 11)):
        logits = jnp.asarray(rng.randn(*shape), jnp.float32)
        out = select_next_token(logits)
        ref = jnp.argmax(logits, axis=-1).astype(jnp.int32)
        np.testing.assert_array_equal(np.asarray(out), np.asarray(ref))
        assert out.dtype == jnp.int32


def test_select_next_token_temperature_needs_rng():
    logits = jnp.zeros((4, 8))
    with pytest.raises(ValueError, match="rng"):
        select_next_token(logits, 0.7)
    a = select_next_token(logits, 0.7, jax.random.PRNGKey(0))
    b = select_next_token(logits, 0.7, jax.random.PRNGKey(0))
    np.testing.assert_array_equal(np.asarray(a), np.asarray(b))


def test_greedy_accept_prefix_semantics():
    """Hand-built case: acceptance is the longest PREFIX of proposals
    matching the target argmaxes; out tokens are the argmaxes."""
    V = 8
    g = np.array([[3, 5, 2, 7], [1, 1, 1, 1]])       # [S, W=4], k=3
    logits = np.full((2, 4, V), -10.0, np.float32)
    for s in range(2):
        for i in range(4):
            logits[s, i, g[s, i]] = 1.0
    drafts = np.array([[3, 5, 0], [2, 1, 1]])        # [S, k]
    out, acc = greedy_accept(jnp.asarray(logits), jnp.asarray(drafts))
    np.testing.assert_array_equal(np.asarray(out), g)
    # slot 0: d1=3==g0, d2=5==g1, d3=0!=g2 -> m=2 (emit g0,g1,g2)
    # slot 1: d1=2!=g0 -> m=0 (emit g0 only)
    np.testing.assert_array_equal(np.asarray(acc), [2, 0])


def test_rejection_sampling_recovers_target_distribution():
    """The Chen et al. guarantee: draft-proposed + accept/resample ==
    sampling the target, empirically at S=1, k=1 over a tiny vocab."""
    p_log = jnp.log(jnp.asarray(
        [[0.45, 0.30, 0.15, 0.10], [0.25, 0.25, 0.25, 0.25]],
        jnp.float32))                                   # [W=2, V]
    q = jnp.asarray([[0.10, 0.40, 0.30, 0.20]], jnp.float32)

    def one(key):
        kd, ka = jax.random.split(key)
        d = jax.random.categorical(kd, jnp.log(q[0]))[None, None]
        out, _ = rejection_sample_accept(p_log[None], d, q[None], 1.0,
                                         ka)
        return out[0, 0]

    toks = jax.vmap(one)(jax.random.split(jax.random.PRNGKey(0), 30000))
    freq = np.bincount(np.asarray(toks), minlength=4) / 30000
    target = np.asarray(jax.nn.softmax(p_log[0]))
    assert np.abs(freq - target).max() < 0.02, (freq, target)


def test_rejection_residual_excludes_overproposed_token():
    """Where q >= p the residual max(p-q, 0) is zero: a rejected
    proposal can never be resampled as itself."""
    p_log = jnp.log(jnp.asarray([[0.05, 0.90, 0.05],
                                 [1 / 3, 1 / 3, 1 / 3]], jnp.float32))
    q = jnp.asarray([[0.90, 0.05, 0.05]], jnp.float32)  # over-proposes 0

    def one(key):
        out, acc = rejection_sample_accept(
            p_log[None], jnp.asarray([[0]]), q[None], 1.0, key)
        return out[0, 0], acc[0]

    toks, accs = jax.vmap(one)(
        jax.random.split(jax.random.PRNGKey(1), 2000))
    toks, accs = np.asarray(toks), np.asarray(accs)
    rejected = toks[accs == 0]
    assert len(rejected) > 100            # p(0)/q(0) is tiny
    assert (rejected != 0).all()          # residual excludes token 0


# ---------------------------------------------------------------------------
# engine parity bar: spec stream == non-spec stream, greedy, every k
# ---------------------------------------------------------------------------

_PROMPTS = [_tokens(3, seed=10), _tokens(7, seed=11), _tokens(5, seed=12)]
_GEN = 10
_model = GPT2Model(TINY)
_params = None
_noisy_draft = None
_ref_cache = {}


def _target_params():
    global _params
    if _params is None:
        _params = _model.init(jax.random.PRNGKey(0))
    return _params


def _noisy_draft_params():
    """Target params with small noise on the embedding: the draft
    mostly agrees with the target but rejects often enough to exercise
    every rollback path at mid accept ratios."""
    global _noisy_draft
    if _noisy_draft is None:
        p = jax.tree.map(lambda a: a, _target_params())
        noise = jax.random.normal(jax.random.PRNGKey(9),
                                  p["wte"].shape) * 0.02
        p = dict(p)
        p["wte"] = p["wte"] + noise
        _noisy_draft = p
    return _noisy_draft


def _serve(serving, draft_params=None, mesh=None, prompts=None,
           gen=_GEN, telemetry=None, return_engine=False):
    cfgd = {"serving": {"slots": 2, "max_seq_len": 64,
                        "prefill_len": 16, **serving}}
    if telemetry:
        cfgd["telemetry"] = telemetry
    eng = ServeEngine(_model, cfgd, params=_target_params(),
                      draft_params=draft_params, mesh=mesh)
    reqs = [eng.submit(p, max_new_tokens=gen)
            for p in (prompts or _PROMPTS)]
    eng.run_until_idle()
    out = [r.result() for r in reqs]
    if return_engine:
        return out, reqs, eng
    eng.close()
    return out


def _ref_stream(arm, mesh_key=None, mesh=None):
    key = (arm, mesh_key)
    if key not in _ref_cache:
        serving = {"page_len": 8} if arm == "paged" else {}
        _ref_cache[key] = _serve(serving, mesh=mesh)
    return _ref_cache[key]


@pytest.mark.parametrize("arm", ["unpaged", "paged"])
@pytest.mark.parametrize("k", [1, 2, 4, 8])
def test_spec_stream_parity(arm, k):
    """THE parity bar: the speculative greedy stream equals the
    non-speculative stream at every k, on both KV layouts, with a
    rejection-heavy (noisy) draft."""
    serving = {"speculate_k": k, "draft": DRAFT_BLOCK}
    if arm == "paged":
        serving["page_len"] = 8
    spec = _serve(serving, draft_params=_noisy_draft_params())
    assert spec == _ref_stream(arm)


@pytest.mark.parametrize("arm", ["unpaged", "paged"])
def test_spec_stream_parity_full_accept(arm):
    """draft == target: every proposal accepts (the m=k bonus-token
    edge, incl. the draft's k+1-th KV write) — stream still equal."""
    serving = {"speculate_k": 4, "draft": DRAFT_BLOCK}
    if arm == "paged":
        serving["page_len"] = 8
    out, reqs, eng = _serve(serving, draft_params=_target_params(),
                            return_engine=True)
    assert out == _ref_stream(arm)
    # accounting counts tokens DELIVERED: every decode token beyond
    # each pass's first came from an accepted draft, so the counters
    # reconcile exactly with the emitted streams even though the
    # budget truncates the final block
    decode_tokens = sum(len(t) - 1 for t in out)
    assert eng._spec_accepted_n == decode_tokens - eng._spec_passes
    # and acceptance really was total up to that truncation: every
    # pass emitted its whole surviving block
    assert all(m >= 0 for r in reqs for m in r.spec_accepted)
    assert eng._spec_accepted_n > eng._spec_passes  # blocks, not 1/tick
    eng.close()


@pytest.mark.parametrize("arm", ["unpaged", "paged"])
@pytest.mark.parametrize("k", [1, 2, 4, 8])
def test_spec_stream_parity_dp2_tp2(arm, k):
    """Same bar on a sharded (data=2, model=2) mesh: TP-sharded heads
    + DP-sharded slots/pages through the ordinary mesh plumbing."""
    mesh = build_mesh(dp=2, tp=2, devices=jax.devices()[:4])
    serving = {"speculate_k": k, "draft": DRAFT_BLOCK}
    if arm == "paged":
        serving["page_len"] = 8
    spec = _serve(serving, draft_params=_noisy_draft_params(),
                  mesh=mesh)
    assert spec == _ref_stream(arm, "dp2tp2", mesh)


def test_spec_zero_recompiles_and_telemetry(tmp_path):
    """Mixed accepted lengths across ticks never recompile the verify/
    propose programs; speculation counters + flush scalars land in the
    summarize 'speculation' row; the flight-recorder depth dict carries
    the live accept ratio."""
    tel = {"enabled": True, "output_path": str(tmp_path),
           "memory": False}
    serving = {"speculate_k": 4, "draft": DRAFT_BLOCK,
               "flush_interval_ticks": 2}
    out, reqs, eng = _serve(serving,
                            draft_params=_noisy_draft_params(),
                            telemetry=tel, return_engine=True)
    assert out == _ref_stream("unpaged")
    reg = eng.telemetry.registry
    assert reg.counter("recompiles_total").value(
        program="verify_step") == 0
    assert reg.counter("recompiles_total").value(
        program="draft_propose") == 0
    assert eng._verify_fn._cache_size() == 1
    assert eng._propose_fn._cache_size() == 1
    proposed = reg.counter("serve_spec_proposed_total").value()
    accepted = reg.counter("serve_spec_accepted_total").value()
    assert proposed == eng._spec_passes * 4
    assert 0 <= accepted <= proposed
    # uneven per-slot progress: the noisy draft's accepted lengths
    # vary across passes (the scheduler-variance scenario)
    all_acc = [m for r in reqs for m in r.spec_accepted]
    assert len(set(all_acc)) > 1, all_acc
    depth = eng._stage_depth()
    assert depth["spec_accept_ratio"] == round(
        accepted / max(proposed, 1), 4)
    # the flight-recorder ring stamps the ratio as a FLOAT (an int cast
    # would truncate every live ratio to 0)
    eng.stage.record_event("probe")
    ev = eng.stage.flight_snapshot()["events"][-1]
    assert ev["kind"] == "probe"
    assert isinstance(ev["spec_accept_ratio"], float)
    assert ev["spec_accept_ratio"] == depth["spec_accept_ratio"]
    assert ev["depth"] == 0
    eng._flush()
    eng.close()
    from deepspeed_tpu.telemetry.cli import summarize
    with open(os.devnull, "w") as devnull:
        report = summarize(str(tmp_path / "events.jsonl"), out=devnull)
    # ONE ratio formula everywhere: the flush scalar equals the depth
    # dict's rounded value, not a differently-computed cousin
    assert report["serve_spec_accept_ratio"] == depth["spec_accept_ratio"]
    assert report["serve_spec_mean_accepted_len"] == pytest.approx(
        (accepted + eng._spec_passes) / eng._spec_passes)


# ---------------------------------------------------------------------------
# accepted-length-variance scheduler semantics (the satellite matrix)
# ---------------------------------------------------------------------------


def test_uneven_progress_staggered_admissions():
    """A request admitted mid-stream decodes next to one several
    speculative blocks ahead — the masked machinery absorbs the skew
    and both streams stay parity-exact."""
    eng = ServeEngine(_model, {"serving": {
        "slots": 2, "max_seq_len": 64, "prefill_len": 16,
        "speculate_k": 4, "draft": DRAFT_BLOCK}},
        params=_target_params(),
        draft_params=_noisy_draft_params())
    r0 = eng.submit(_PROMPTS[0], max_new_tokens=_GEN)
    eng.step()
    eng.step()
    r1 = eng.submit(_PROMPTS[1], max_new_tokens=_GEN)
    eng.run_until_idle()
    ref = _ref_stream("unpaged")
    assert r0.result() == ref[0]
    assert r1.result() == ref[1]
    # the two slots really did progress unevenly
    assert len(r0.spec_accepted) != len(r1.spec_accepted) or \
        r0.spec_accepted != r1.spec_accepted
    eng.close()


def test_eos_inside_accepted_block():
    """EOS landing mid-block truncates the emission AT the EOS token
    and finishes the request — stream identical to the non-spec arm
    with the same eos_id."""
    ref = _ref_stream("unpaged")
    eos = ref[1][4]                       # a token mid-stream
    base = _serve({"eos_id": int(eos)})
    spec = _serve({"eos_id": int(eos), "speculate_k": 4,
                   "draft": DRAFT_BLOCK},
                  draft_params=_target_params())
    assert spec == base
    assert any(len(s) < _GEN for s in spec)  # EOS actually fired


def test_kv_capacity_inside_accepted_block():
    """The generation hitting the slot's KV capacity mid-block
    truncates exactly where the non-spec arm stops."""
    serving = {"max_seq_len": 12, "prefill_len": 8}
    prompts = [_tokens(5, seed=20), _tokens(3, seed=21)]
    base = _serve(serving, prompts=prompts, gen=16)
    out, reqs, eng = _serve(
        {**serving, "speculate_k": 4, "draft": DRAFT_BLOCK},
        draft_params=_target_params(), prompts=prompts, gen=16,
        return_engine=True)
    assert out == base
    assert any(r.finish_reason == "kv_capacity" for r in reqs)
    eng.close()


def test_paged_pool_exhaustion_during_block_append_no_leaks():
    """A k-token append draining the page pool finishes that request
    kv_capacity (the pool-aware reason), the other slot keeps serving,
    and when everything drains the pool holds ZERO refs — speculated
    pages were freed, not leaked."""
    eng = ServeEngine(_model, {"serving": {
        "slots": 2, "max_seq_len": 64, "prefill_len": 16,
        "page_len": 4, "pages": 9, "prefix_cache": False,
        "speculate_k": 4, "draft": DRAFT_BLOCK}},
        params=_target_params(), draft_params=_target_params())
    # two requests: 8 usable pages = 32 token-rows; both want to grow
    # past that, so one hits pool exhaustion mid-append
    r0 = eng.submit(_tokens(8, seed=30), max_new_tokens=24)
    r1 = eng.submit(_tokens(8, seed=31), max_new_tokens=24)
    eng.run_until_idle()
    assert r0.error is None and r1.error is None
    reasons = {r0.finish_reason, r1.finish_reason}
    assert "kv_capacity" in reasons
    # the survivor kept decoding after the other's exhaustion finish
    assert max(len(r0.tokens), len(r1.tokens)) > \
        min(len(r0.tokens), len(r1.tokens))
    assert eng.pool.refs == {}
    assert eng.pool.free_count == 8
    eng.close()


def test_eviction_mid_speculation_frees_speculated_pages():
    """EOS inside an accepted block on the paged arm: the finish frees
    EVERY page the request held, including the block's speculative
    pre-allocation — no refcount leaks."""
    ref = _ref_stream("paged")
    eos = ref[0][3]
    eng = ServeEngine(_model, {"serving": {
        "slots": 2, "max_seq_len": 64, "prefill_len": 16,
        "page_len": 8, "prefix_cache": False,
        "speculate_k": 4, "draft": DRAFT_BLOCK,
        "eos_id": int(eos)}},
        params=_target_params(), draft_params=_target_params())
    reqs = [eng.submit(p, max_new_tokens=_GEN) for p in _PROMPTS]
    eng.run_until_idle()
    assert all(r.error is None for r in reqs)
    assert eng.pool.refs == {}
    assert eng.pool.free_count == eng.cache_spec.pages - 1
    eng.close()


def test_spec_tick_chaos_transient_absorbed(monkeypatch):
    """The serve stage's chaos semantics hold in spec mode: a
    transient injected fault at the step boundary is retried by the
    stage budget and the emitted stream is unchanged."""
    monkeypatch.setenv("DS_STAGE_FAULT", "serve:step:2")
    reset_fault_injection()
    spec = _serve({"speculate_k": 4, "draft": DRAFT_BLOCK},
                  draft_params=_target_params())
    assert spec == _ref_stream("unpaged")


def test_spec_poison_fails_inflight_typed():
    """A fatal mid-verify failure poisons the pool: every in-flight
    request fails with the ORIGINAL exception (the cache was donated),
    submitters release, and close() stays clean."""
    eng = ServeEngine(_model, {"serving": {
        "slots": 2, "max_seq_len": 64, "prefill_len": 16,
        "speculate_k": 2, "draft": DRAFT_BLOCK}},
        params=_target_params(), draft_params=_target_params())
    reqs = [eng.submit(p, max_new_tokens=_GEN) for p in _PROMPTS[:2]]
    eng.step()
    boom = RuntimeError("verify exploded")

    def bad_tick():
        raise boom
    eng._spec_tick = bad_tick
    with pytest.raises(RuntimeError, match="verify exploded"):
        eng.step()
    for r in reqs:
        assert r.done.is_set()
        with pytest.raises(RuntimeError, match="verify exploded"):
            r.result(timeout=1)
    eng.close()


# ---------------------------------------------------------------------------
# temperature plane
# ---------------------------------------------------------------------------


def test_temperature_sampling_deterministic_under_seed():
    a = _serve({"temperature": 0.8}, gen=6)
    b = _serve({"temperature": 0.8}, gen=6)
    assert a == b
    assert a != _ref_stream("unpaged")  # it really sampled


def test_temperature_spec_serves_end_to_end():
    """T>0 speculation (rejection-sampling acceptance) serves the full
    workload; the stream is a sample, not the greedy stream, so the
    bar is completion + budget-exact lengths."""
    out = _serve({"temperature": 0.8, "speculate_k": 3,
                  "draft": DRAFT_BLOCK},
                 draft_params=_target_params(), gen=6)
    assert [len(t) for t in out] == [6, 6, 6]


# ---------------------------------------------------------------------------
# config + mesh validation
# ---------------------------------------------------------------------------


def test_config_rejects_bad_spec_blocks():
    for bad in ({"speculate_k": -1}, {"speculate_k": True},
                {"temperature": -0.5}, {"temperature": "hot"},
                {"draft": {"bogus": 1}}, {"draft": 3},
                {"draft": {"d_model": 65, "n_head": 4}},
                {"draft": {"n_layer": 0}},
                {"draft": {"attn_impl": "ring"}}):
        with pytest.raises(DeepSpeedConfigError):
            DeepSpeedServingConfig({"serving": bad})


def test_config_draft_defaults_filled():
    c = DeepSpeedServingConfig({"serving": {"speculate_k": 2}})
    assert c.draft == {"d_model": 256, "n_layer": 2, "n_head": 4,
                       "attn_impl": ""}
    assert c.temperature == 0.0


def test_draft_heads_must_divide_tp():
    mesh = build_mesh(dp=1, tp=2, devices=jax.devices()[:2])
    with pytest.raises(ValueError, match="divisible"):
        ServeEngine(_model, {"serving": {
            "slots": 2, "max_seq_len": 64, "prefill_len": 16,
            "speculate_k": 2,
            "draft": {"d_model": 30, "n_layer": 1, "n_head": 3}}},
            params=_target_params(), mesh=mesh)


def test_benchgate_pins_spec_metric_lower_better():
    from tools.benchgate import is_lower_better
    assert is_lower_better("serve_spec_wall_per_token_ratio") is True


# ---------------------------------------------------------------------------
# bench smoke
# ---------------------------------------------------------------------------


def test_bench_spec_smoke(tmp_path):
    """CPU A/B: spec wall/token beats non-spec under injected per-pass
    delay and the artifact carries the 1/MAL expectation."""
    import bench_serve
    rec = bench_serve.run_spec_ab(k=2, slots=3, n_requests=3,
                                  prompt_len=6, gen_tokens=7,
                                  pass_delay_s=0.05,
                                  out_dir=str(tmp_path))
    assert rec["metric"] == "serve_spec_wall_per_token_ratio"
    assert rec["value"] < 0.8, rec
    assert rec["expected_ratio_1_over_mal"] == pytest.approx(
        1.0 / rec["spec"]["mean_accepted_len"])
    assert os.path.exists(tmp_path / "BENCH_serve_spec.json")
    with open(tmp_path / "BENCH_serve_spec.json") as f:
        assert json.load(f)["value"] == rec["value"]
