"""Paged KV cache with prefix reuse (docs/serving.md):

* kernel parity matrix at page-boundary-covering lengths — fp32
  BITWISE dense-paged vs the pre-page dense reference (the ``jnp.take``
  anchor) and pallas-paged vs the pre-page pallas kernel; pallas vs
  dense at the established kernel tolerance,
* token-stream identity of the paged engine vs the pre-page engine,
* the zero-recompile contract across mixed page-count request waves,
* prefix cache: shared-template reuse, copy-on-write of the last
  partial page, leaf-LRU eviction, pool accounting,
* pool-exhaustion backpressure + the pool-aware ``kv_capacity`` finish,
* the batched-``device_put`` satellite, deque free lists, config
  validation, telemetry flow, flight-recorder depth fields, benchgate
  direction pin, and the ``bench_serve.py --paged`` smoke.
"""
import json
import os

import numpy as np
import jax
import jax.numpy as jnp
import pytest

from deepspeed_tpu.config import DeepSpeedConfigError
from deepspeed_tpu.inference import (PagedKVCacheSpec, ServeEngine,
                                     init_paged_cache, shard_cache)
from deepspeed_tpu.inference.kv_cache import (KVCacheSpec, init_cache,
                                              paged_cache_shardings,
                                              validate_paged_cache_mesh)
from deepspeed_tpu.inference.scheduler import (PagePool, PrefixCache,
                                               SlotScheduler)
from deepspeed_tpu.models.gpt2 import (GPT2Config, GPT2Model,
                                       gpt2_prefill, gpt2_prefill_paged)
from deepspeed_tpu.ops.pallas.decode_attention import (
    decode_attention, decode_attention_paged, paged_gather)
from deepspeed_tpu.parallel import build_mesh
from deepspeed_tpu.runtime.stages import reset_fault_injection

TINY = GPT2Config(vocab_size=128, n_positions=64, d_model=32, n_layer=2,
                  n_head=4, remat=None, attn_impl="dense")
TINY_FLASH = GPT2Config(**{**TINY.__dict__, "attn_impl": "flash"})

_CHAOS_ENVS = ("DS_STAGE_FAULT", "DS_STAGE_DELAY_S")


@pytest.fixture(autouse=True)
def _clean_faults(monkeypatch):
    for env in _CHAOS_ENVS:
        monkeypatch.delenv(env, raising=False)
    reset_fault_injection()
    yield
    reset_fault_injection()


def _tokens(n, vocab=128, seed=0):
    return np.random.default_rng(seed).integers(
        0, vocab, (n,)).astype(np.int32)


def _pool_and_table(S, H, page_len, max_pages, Dh, seed=0):
    """A filled pool + disjoint per-slot tables (page 0 = scratch)."""
    rng = np.random.RandomState(seed)
    P = 1 + S * max_pages
    kp = jnp.asarray(rng.randn(P, H, page_len, Dh), jnp.float32)
    vp = jnp.asarray(rng.randn(P, H, page_len, Dh), jnp.float32)
    pt = np.arange(1, P).reshape(S, max_pages).astype(np.int32)
    return kp, vp, jnp.asarray(pt)


# ---------------------------------------------------------------------------
# kernel parity matrix at page-boundary-covering lengths
# ---------------------------------------------------------------------------

#: len < page_len, == page_len, spanning 3 pages, plus the free slot
PAGE_BOUNDARY_LENGTHS = [0, 7, 16, 2 * 16 + 5]


def test_paged_kernel_parity_matrix():
    """fp32 parity at page-boundary lengths: dense-paged is BITWISE
    against the pre-page dense reference on the gathered layout (the
    jnp.take anchor), pallas-paged is BITWISE against the pre-page
    pallas kernel at the same block size, and pallas-vs-dense holds the
    established kernel tolerance."""
    S, H, page_len, max_pages, Dh = 4, 3, 16, 3, 32
    kp, vp, pt = _pool_and_table(S, H, page_len, max_pages, Dh)
    q = jnp.asarray(np.random.RandomState(1).randn(S, H, Dh), jnp.float32)
    lengths = jnp.asarray(PAGE_BOUNDARY_LENGTHS, jnp.int32)
    out_d = decode_attention_paged(q, kp, vp, pt, lengths, impl="dense")
    out_p = decode_attention_paged(q, kp, vp, pt, lengths, impl="pallas",
                                   interpret=True)
    # the pre-page reference arms over the SAME values, gathered dense
    kg, vg = paged_gather(kp, pt), paged_gather(vp, pt)
    ref_d = decode_attention(q, kg, vg, lengths, impl="dense")
    ref_p = decode_attention(q, kg, vg, lengths, impl="pallas",
                             interpret=True, block_k=page_len)
    np.testing.assert_array_equal(np.asarray(out_d), np.asarray(ref_d))
    np.testing.assert_array_equal(np.asarray(out_p), np.asarray(ref_p))
    np.testing.assert_allclose(out_p, out_d, atol=2e-6, rtol=2e-6)
    # free slot (length 0) outputs exact zeros on both paged arms
    assert (np.asarray(out_d[0]) == 0).all()
    assert (np.asarray(out_p[0]) == 0).all()


def test_paged_kernel_masks_dead_pages():
    """Garbage in pages beyond a slot's live length — and in the dead
    table entries pointing at the scratch page — must never leak."""
    S, H, page_len, max_pages, Dh = 2, 2, 8, 3, 16
    kp, vp, pt = _pool_and_table(S, H, page_len, max_pages, Dh, seed=2)
    q = jnp.asarray(np.random.RandomState(3).randn(S, H, Dh), jnp.float32)
    lengths = jnp.asarray([5, 8], jnp.int32)  # only page 0 of each live
    ptn = np.asarray(pt).copy()
    poisoned_pt = ptn.copy()
    poisoned_pt[:, 1:] = 0                    # dead entries -> scratch
    kp_bad = kp.at[ptn[0, 1]].set(1e4).at[0].set(-1e4)
    vp_bad = vp.at[ptn[0, 1]].set(1e4).at[0].set(-1e4)
    for impl in ("dense", "pallas"):
        clean = decode_attention_paged(q, kp, vp, pt, lengths, impl=impl)
        dirty = decode_attention_paged(q, kp_bad, vp_bad,
                                       jnp.asarray(poisoned_pt),
                                       lengths, impl=impl)
        np.testing.assert_array_equal(np.asarray(clean),
                                      np.asarray(dirty))


def test_paged_kernel_single_compile_across_tables():
    """Page table AND lengths are traced: one jit cache entry no
    matter the mix."""
    S, H, page_len, max_pages, Dh = 3, 2, 8, 2, 16
    kp, vp, pt = _pool_and_table(S, H, page_len, max_pages, Dh)
    q = jnp.asarray(np.random.RandomState(4).randn(S, H, Dh), jnp.float32)
    f = jax.jit(lambda q, k, v, t, l: decode_attention_paged(
        q, k, v, t, l, impl="pallas"))
    for tab, lens in ((pt, [0, 3, 16]),
                      (jnp.zeros_like(pt), [0, 0, 0]),
                      (pt[::-1], [8, 8, 1])):
        f(q, kp, vp, tab, jnp.asarray(lens, jnp.int32)).block_until_ready()
    assert f._cache_size() == 1


def test_paged_kernel_rejects_unknown_impl():
    S, H, page_len, max_pages, Dh = 2, 2, 8, 2, 16
    kp, vp, pt = _pool_and_table(S, H, page_len, max_pages, Dh)
    q = jnp.asarray(np.zeros((S, H, Dh)), jnp.float32)
    with pytest.raises(ValueError, match="impl"):
        decode_attention_paged(q, kp, vp, pt,
                               jnp.zeros((S,), jnp.int32), impl="cuda")


# ---------------------------------------------------------------------------
# paged prefill: bitwise against the pre-page prefill when no prefix
# ---------------------------------------------------------------------------


@pytest.mark.parametrize("cfg", [TINY, TINY_FLASH],
                         ids=["dense", "flash"])
def test_paged_prefill_no_prefix_bitwise(cfg):
    """The ``prefix_len == 0`` arm of the paged prefill runs the
    model's OWN attention (dense or flash) — logits AND the written
    K/V pages are BITWISE identical to ``gpt2_prefill``."""
    model = GPT2Model(cfg)
    params = model.init(jax.random.PRNGKey(0))
    page_len, max_pages = 8, 3
    t_prompt = 13                                  # spans 2 pages
    toks = _tokens(t_prompt, seed=5)[None]
    logits_ref, ks, vs = gpt2_prefill(cfg, params, jnp.asarray(toks))
    L, H, Dh = cfg.n_layer, cfg.n_head, cfg.d_head
    P = 1 + max_pages
    kp = jnp.zeros((L, P, H, page_len, Dh), jnp.float32)
    vp = jnp.zeros((L, P, H, page_len, Dh), jnp.float32)
    row = np.zeros((max_pages,), np.int32)
    npg = -(-t_prompt // page_len)
    row[:npg] = np.arange(1, 1 + npg)
    pad = np.zeros((1, 16), np.int32)
    pad[0, :t_prompt] = toks[0]
    logits, kp, vp = gpt2_prefill_paged(
        cfg, params, jnp.asarray(pad), np.int32(t_prompt), np.int32(0),
        jnp.asarray(row), kp, vp)
    np.testing.assert_array_equal(np.asarray(logits[0, :t_prompt]),
                                  np.asarray(logits_ref[0]))
    for layer in range(L):
        got_k = paged_gather(kp[layer], jnp.asarray(row)[None])[0]
        got_v = paged_gather(vp[layer], jnp.asarray(row)[None])[0]
        np.testing.assert_array_equal(
            np.asarray(got_k[:, :t_prompt]), np.asarray(ks[layer, 0]))
        np.testing.assert_array_equal(
            np.asarray(got_v[:, :t_prompt]), np.asarray(vs[layer, 0]))


# ---------------------------------------------------------------------------
# engine: token streams identical to the pre-page engine
# ---------------------------------------------------------------------------


def _serve_cfg(slots=4, max_seq=32, prefill=24, telemetry_path=None,
               **serving_extra):
    cfg = {"serving": {"slots": slots, "max_seq_len": max_seq,
                       "prefill_len": prefill, **serving_extra}}
    if telemetry_path is not None:
        cfg["telemetry"] = {"enabled": True,
                            "output_path": str(telemetry_path)}
    return cfg


#: prompt lengths covering every page boundary of page_len=8: inside
#: the first page, == page_len, and spanning 3 pages
BOUNDARY_PROMPTS = [1, 3, 8, 17, 20]


@pytest.mark.parametrize("cfg", [TINY, TINY_FLASH],
                         ids=["dense", "flash"])
def test_paged_engine_token_streams_match_prepage(cfg):
    """THE engine-level acceptance bar: the paged engine emits
    token-for-token the same streams as the pre-page engine — for
    single-page-sufficient requests AND page-spanning ones, on both
    kernel arms."""
    model = GPT2Model(cfg)
    params = model.init(jax.random.PRNGKey(0))
    prompts = [list(_tokens(n, seed=10 + i))
               for i, n in enumerate(BOUNDARY_PROMPTS)]

    def run(extra):
        eng = ServeEngine(model, _serve_cfg(**extra), params=params)
        rs = [eng.submit(p, max_new_tokens=10) for p in prompts]
        eng.run_until_idle()
        toks = [r.tokens for r in rs]
        assert all(r.error is None for r in rs)
        assert all(r.finish_reason == "length" for r in rs)
        eng.close()
        return toks

    assert run({}) == run({"page_len": 8})


def test_paged_engine_dense_decode_is_bitwise_vs_prepage():
    """On the dense arm the whole paged chain (prefill + every decode
    tick) is bitwise, so even argmax TIES can't diverge: compare full
    greedy streams at an adversarially long generation."""
    model = GPT2Model(TINY)
    params = model.init(jax.random.PRNGKey(0))
    p = list(_tokens(9, seed=33))

    def run(extra):
        eng = ServeEngine(model, _serve_cfg(slots=1, **extra),
                          params=params)
        r = eng.submit(p, max_new_tokens=23)   # to the kv_capacity edge
        eng.run_until_idle()
        out = (r.tokens, r.finish_reason)
        eng.close()
        return out

    assert run({}) == run({"page_len": 8})


def test_paged_zero_recompiles_mixed_page_count_waves(tmp_path):
    """Acceptance bar: one compiled decode program (and one prefill,
    one COW copy) survives waves of requests with VARYING page counts —
    zero recompiles, cache size 1."""
    eng = ServeEngine(GPT2Model(TINY), _serve_cfg(
        slots=3, page_len=8, telemetry_path=tmp_path))
    rng = np.random.default_rng(7)
    reqs = []
    for wave in range(3):
        for i in range(5):
            n = int(rng.integers(1, 24))       # 1..3 pages per prompt
            reqs.append(eng.submit(
                list(_tokens(n, seed=100 * wave + i)),
                max_new_tokens=int(rng.integers(1, 9))))
        eng.run_until_idle()
    assert all(r.error is None for r in reqs)
    eng.telemetry.compile_monitor.sample()
    reg = eng.telemetry.registry
    for prog in ("decode_step", "prefill", "copy_page"):
        assert reg.counter("recompiles_total").value(program=prog) == 0
    assert eng._decode_fn._cache_size() == 1
    assert eng._prefill_fn._cache_size() == 1
    eng.close()


def test_paged_submit_validation():
    eng = ServeEngine(GPT2Model(TINY), _serve_cfg(
        slots=2, page_len=8, pages=3, prefill=24))
    # 2 usable pages: a 3-page prompt can never be admitted
    with pytest.raises(ValueError, match="KV pages"):
        eng.submit(list(_tokens(17, seed=1)))
    eng.close()


# ---------------------------------------------------------------------------
# chunked prefill: parity, co-scheduling, zero recompiles, KV migration
# ---------------------------------------------------------------------------


#: prompt lengths covering the chunk/page boundary matrix for
#: prefill_chunk_len=4 on page_len=8: sub-chunk, == chunk, chunk
#: boundary inside a page, == page, and final chunks landing inside,
#: at, and across page boundaries
CHUNK_PROMPTS = [1, 3, 4, 8, 11, 17, 20]


def test_chunked_prefill_stream_parity_across_boundaries():
    """Acceptance bar: splitting prefill into fixed-size chunks changes
    WHEN the prompt's KV is computed, never WHAT — token streams are
    bitwise the unchunked paged streams at every chunk/page-boundary
    class."""
    model = GPT2Model(TINY)
    params = model.init(jax.random.PRNGKey(0))
    prompts = [list(_tokens(n, seed=40 + i))
               for i, n in enumerate(CHUNK_PROMPTS)]

    def run(extra):
        eng = ServeEngine(model, _serve_cfg(page_len=8, **extra),
                          params=params)
        rs = [eng.submit(p, max_new_tokens=8) for p in prompts]
        eng.run_until_idle()
        assert all(r.error is None for r in rs)
        toks = [list(r.tokens) for r in rs]
        eng.close()
        return toks

    assert run({}) == run({"prefill_chunk_len": 4})


def test_chunked_prefill_coschedules_decode_ticks():
    """While a long prompt is mid-chunks, decode-phase slots keep
    producing a token EVERY tick — chunked prefill bounds the decode
    stall to one chunk per step instead of a whole-prompt prefill
    (Sarathi-Serve co-scheduling, docs/serving.md)."""
    eng = ServeEngine(GPT2Model(TINY), _serve_cfg(
        slots=2, page_len=8, prefill_chunk_len=4))
    short = eng.submit(list(_tokens(2, seed=1)), max_new_tokens=24)
    eng.step()
    assert len(short.tokens) >= 1          # short is decoding
    long = eng.submit(list(_tokens(20, seed=2)), max_new_tokens=4)
    eng.step()                             # admits long + chunk 1
    assert long.prefilling                 # 20 tokens = 5 chunks
    stalls = 0
    while long.prefilling:
        before = len(short.tokens)
        eng.step()
        stalls += (len(short.tokens) == before)
    assert stalls == 0                     # decode never starved
    assert long.tokens                     # final chunk stamped TTFT
    eng.run_until_idle()
    assert short.error is None and long.error is None
    assert long.finish_reason == "length" and len(long.tokens) == 4
    eng.close()


def test_chunked_prefill_zero_recompiles_mixed_lengths(tmp_path):
    """One compiled prefill program serves EVERY chunk: varying prompt
    lengths, chunk counts, and final-chunk widths cost zero recompiles
    — the chunk position rides the traced prefix_len, not a shape."""
    eng = ServeEngine(GPT2Model(TINY), _serve_cfg(
        slots=3, page_len=8, prefill_chunk_len=4,
        telemetry_path=tmp_path))
    rng = np.random.default_rng(11)
    reqs = []
    for wave in range(3):
        for i in range(5):
            n = int(rng.integers(1, 24))   # 1..6 chunks per prompt
            reqs.append(eng.submit(
                list(_tokens(n, seed=200 * wave + i)),
                max_new_tokens=int(rng.integers(1, 9))))
        eng.run_until_idle()
    assert all(r.error is None for r in reqs)
    eng.telemetry.compile_monitor.sample()
    reg = eng.telemetry.registry
    for prog in ("decode_step", "prefill", "copy_page"):
        assert reg.counter("recompiles_total").value(program=prog) == 0
    assert eng._prefill_fn._cache_size() == 1
    assert eng._decode_fn._cache_size() == 1
    eng.close()


def test_kv_migration_export_adopt_stream_parity():
    """Engine-level disaggregation parity: prefill on engine A with
    ``detach_kv`` (1 token), ship the exported page payloads into
    engine B via ``adopt_request``, and the combined stream is bitwise
    what a single engine produces — at page-boundary-covering prompt
    lengths."""
    model = GPT2Model(TINY)
    params = model.init(jax.random.PRNGKey(0))
    prompts = [list(_tokens(n, seed=60 + i))
               for i, n in enumerate([3, 8, 11])]
    budget = 10

    def single():
        eng = ServeEngine(model, _serve_cfg(page_len=8),
                          params=params)
        rs = [eng.submit(p, max_new_tokens=budget) for p in prompts]
        eng.run_until_idle()
        assert all(r.error is None for r in rs)
        toks = [list(r.tokens) for r in rs]
        eng.close()
        return toks

    def migrated():
        a = ServeEngine(model, _serve_cfg(page_len=8), params=params)
        b = ServeEngine(model, _serve_cfg(page_len=8), params=params)
        assert a.page_leaf_nbytes() == b.page_leaf_nbytes()
        out = []
        for p in prompts:
            r = a.submit(p, max_new_tokens=1, detach_kv=True)
            a.run_until_idle()
            assert r.error is None and r.pages is not None
            payloads = a.export_pages(r)
            a.release_detached(r)
            assert r.pages is None         # capacity returned
            rb = b.adopt_request(p, r.tokens[0], budget, None,
                                 payloads)
            assert rb is not None
            b.run_until_idle()
            assert rb.error is None
            out.append(list(rb.tokens))
        a.close()
        b.close()
        return out

    assert single() == migrated()


def test_kv_adoption_backpressure_returns_none():
    """adopt_request under slot/page pressure parks instead of raising
    — the router's retry contract."""
    model = GPT2Model(TINY)
    params = model.init(jax.random.PRNGKey(0))
    a = ServeEngine(model, _serve_cfg(page_len=8), params=params)
    p = list(_tokens(9, seed=5))
    r = a.submit(p, max_new_tokens=1, detach_kv=True)
    a.run_until_idle()
    payloads = a.export_pages(r)
    a.release_detached(r)
    # slot pressure: a 1-slot engine mid-request has no free slot
    b = ServeEngine(model, _serve_cfg(slots=1, page_len=8),
                    params=params)
    held = b.submit(list(_tokens(2, seed=6)), max_new_tokens=30)
    b.step()
    assert b.adopt_request(p, r.tokens[0], 4, None, payloads) is None
    b.run_until_idle()
    assert held.error is None
    # page-count mismatch is a config error, not backpressure
    with pytest.raises(ValueError, match="pages"):
        b.adopt_request(p, r.tokens[0], 4, None, payloads[:-1])
    a.close()
    b.close()


def test_chunked_prefill_config_needs_paged_layout():
    with pytest.raises(DeepSpeedConfigError, match="page_len"):
        ServeEngine(GPT2Model(TINY), _serve_cfg(prefill_chunk_len=4))


# ---------------------------------------------------------------------------
# prefix cache: shared templates, COW, eviction, accounting
# ---------------------------------------------------------------------------


def test_prefix_cache_shared_template_prefills_delta_only(tmp_path):
    """K requests sharing a template: the prefill computes the full
    prompt once and only the delta afterwards; token streams stay
    identical to prefix-cache-off."""
    model = GPT2Model(TINY)
    params = model.init(jax.random.PRNGKey(0))
    template = list(_tokens(16, seed=40))          # exactly 2 pages
    prompts = [template + list(_tokens(3, seed=41 + i))
               for i in range(4)]

    def run(prefix_cache, tel=None):
        eng = ServeEngine(model, _serve_cfg(
            page_len=8, prefix_cache=prefix_cache, telemetry_path=tel),
            params=params)
        rs = [eng.submit(p, max_new_tokens=5) for p in prompts]
        eng.run_until_idle()
        out = [r.tokens for r in rs]
        computed = [r.computed_len for r in rs]
        shared = [r.shared_len for r in rs]
        stats = (eng.prefix.hits, eng.prefix.misses,
                 eng.prefix.hit_tokens) if eng.prefix else None
        reg = (eng.telemetry.registry if eng.telemetry else None)
        hits_counter = (reg.counter("serve_prefix_hits_total").value()
                        if reg else None)
        eng.close()
        assert all(r.error is None for r in rs)
        return out, computed, shared, stats, hits_counter

    on = run(True, tel=tmp_path)
    off = run(False)
    assert on[0] == off[0], "prefix cache changed the token streams"
    # first request misses and computes everything; later ones compute
    # only the 3-token suffix + the uncacheable last-page remainder
    assert on[1][0] == 19 and all(c == 3 for c in on[1][1:])
    assert on[2][0] == 0 and all(s == 16 for s in on[2][1:])
    assert on[3] == (3, 1, 48)
    assert on[4] == 3
    # prefix-cache-off never shares
    assert all(c == 19 for c in off[1])


def test_prefix_cache_cow_on_divergent_append():
    """Identical prompts share down INTO the last partial page; the
    divergent append triggers copy-on-write, and the streams match a
    no-prefix-cache run bit for bit."""
    model = GPT2Model(TINY)
    params = model.init(jax.random.PRNGKey(0))
    prompt = list(_tokens(13, seed=50))            # 1 full + 5-token tail

    def run(prefix_cache):
        eng = ServeEngine(model, _serve_cfg(
            page_len=8, prefix_cache=prefix_cache), params=params)
        rs = [eng.submit(list(prompt), max_new_tokens=6)
              for _ in range(3)]
        eng.run_until_idle()
        out = [r.tokens for r in rs]
        cow = eng.prefix.cow if eng.prefix else None
        eng.close()
        assert all(r.error is None for r in rs)
        return out, cow

    on, cow = run(True)
    off, _ = run(False)
    assert on == off
    # requests 2 and 3 hit the partial tail (4 cacheable tokens of it)
    # and each must COW before appending
    assert cow == 2


def test_prefix_cache_last_token_never_cached():
    """The vLLM rule: a full-prompt hit still computes >= 1 token so
    prefill has logits to emit the first generated token from."""
    pool = PagePool(8)
    pc = PrefixCache(4, pool)
    prompt = list(range(8))                        # exactly 2 pages
    pages = pool.alloc(2)
    pc.insert(prompt, pages)
    # an identical prompt may share at most len-1 = 7 tokens -> only
    # the first full page (4) + 3 tokens of the second
    shared, spages, cow = pc.match(prompt)
    assert shared == 7 and len(spages) == 2 and cow
    pc.release(spages)


def test_prefix_cache_leaf_lru_eviction_keeps_chains_reachable():
    pool = PagePool(16)
    pc = PrefixCache(4, pool)
    # two chains sharing nothing: A (2 full pages + tail), B (1 full)
    a = [1] * 4 + [2] * 4 + [3, 3]
    b = [9] * 4 + [8, 8]
    pa = pool.alloc(3)
    pc.insert(a, pa)
    pb = pool.alloc(2)
    pc.insert(b, pb)
    held = pc.entries
    assert held == 5
    # evict until 12 pages free: leaf-first order means a chain's inner
    # page is never dropped while a deeper entry still chains through it
    pc.evict(12)
    for d, fe in pc.full.items():
        parent = fe.parent
        while parent:
            assert parent in pc.full, "evicted an inner chain page"
            parent = pc.full[parent].parent
    for parent in pc.partials:
        assert parent == "" or parent in pc.full


def test_page_pool_contracts():
    pool = PagePool(5)
    assert pool.free_count == 4 and pool.used_count == 0
    got = pool.alloc(2)
    assert len(got) == 2 and 0 not in got
    assert pool.alloc(3) is None                   # no side effects
    assert pool.free_count == 2
    pool.ref(got[0])
    pool.deref(got[0])
    assert pool.free_count == 2                    # still held once
    pool.deref(got[0])
    assert pool.free_count == 3                    # freed
    pool.deref(got[1])
    with pytest.raises(AssertionError, match="double free"):
        pool.deref(got[1])
    with pytest.raises(ValueError, match="scratch"):
        pool.ref(0)
    with pytest.raises(ValueError, match="2 pages"):
        PagePool(1)


def test_slot_scheduler_free_list_is_deque():
    from collections import deque
    s = SlotScheduler(4)
    assert isinstance(s.free, deque)
    eng = ServeEngine(GPT2Model(TINY), _serve_cfg(page_len=8))
    assert isinstance(eng.pool.free, deque)
    eng.close()


def test_paged_pool_accounting_after_drain():
    """Every page returns to the free list once its holders are gone:
    slots release on finish, the prefix cache holds only its entries."""
    model = GPT2Model(TINY)
    eng = ServeEngine(model, _serve_cfg(page_len=8, prefix_cache=True))
    usable = eng.cache_spec.pages - 1
    rs = [eng.submit(list(_tokens(n, seed=60 + n)), max_new_tokens=4)
          for n in (3, 9, 17)]
    eng.run_until_idle()
    assert all(r.error is None for r in rs)
    # only the prefix cache still holds pages — one per entry
    assert eng.pool.used_count == eng.prefix.entries
    assert sum(eng.pool.refs.values()) == eng.prefix.entries
    eng.prefix.clear()
    assert eng.pool.free_count == usable
    eng.close()


# ---------------------------------------------------------------------------
# pool exhaustion: backpressure + pool-aware kv_capacity
# ---------------------------------------------------------------------------


def test_pool_exhaustion_admission_backpressure():
    """More demand than pages: admission parks requests (order
    preserved) until releases free pages — every request completes."""
    model = GPT2Model(TINY)
    eng = ServeEngine(model, _serve_cfg(
        slots=4, page_len=8, pages=5, prefix_cache=False))
    # each request needs 2 pages (prompt 9) but only 4 are usable
    rs = [eng.submit(list(_tokens(9, seed=70 + i)), max_new_tokens=3)
          for i in range(4)]
    saw_pending = False
    ticks = 0
    while eng.scheduler.active or eng._pending or eng.queue.qsize():
        eng.step()
        saw_pending = saw_pending or bool(eng._pending)
        ticks += 1
        assert ticks < 1000
    assert saw_pending, "pool never backpressured"
    for r in rs:
        assert r.error is None and r.finish_reason == "length"
    eng.close()


def test_pool_exhaustion_decode_append_finishes_kv_capacity():
    """A request that can't grow into a new page finishes with the
    pool-exhaustion-aware kv_capacity reason instead of wedging."""
    model = GPT2Model(TINY)
    eng = ServeEngine(model, _serve_cfg(
        slots=2, page_len=8, pages=2, prefix_cache=False))
    r = eng.submit(list(_tokens(8, seed=80)), max_new_tokens=50)
    eng.run_until_idle()
    # prompt fills the single usable page; the first append needs a
    # second page that doesn't exist
    assert r.finish_reason == "kv_capacity"
    assert len(r.tokens) == 1                      # the prefill token
    assert r.error is None
    eng.close()


def test_paged_close_fails_parked_requests():
    model = GPT2Model(TINY)
    eng = ServeEngine(model, _serve_cfg(
        slots=4, page_len=8, pages=3, prefix_cache=False))
    rs = [eng.submit(list(_tokens(9, seed=90 + i)), max_new_tokens=4)
          for i in range(3)]
    eng.step()              # admits the first, parks/queues the rest
    eng.close()
    # every request the pool backpressured (parked OR still queued)
    # fails typed at close instead of hanging its waiter
    failed = [r for r in rs if r.error is not None]
    assert len(failed) == 2
    for r in failed:
        assert r.done.is_set()
        with pytest.raises(RuntimeError, match="closed"):
            r.result(timeout=0)


# ---------------------------------------------------------------------------
# sharding: batched placement + TP/DP paged serving
# ---------------------------------------------------------------------------


def test_shard_cache_issues_one_batched_device_put(monkeypatch):
    """The PR 3/4 idiom: ONE list-form jax.device_put for every cache
    leaf, both layouts — a put per leaf is a dispatch per leaf."""
    calls = []
    real = jax.device_put

    def spy(x, device=None, **kw):
        calls.append(x)
        return real(x, device, **kw)

    mesh = build_mesh(dp=2, tp=2, devices=jax.devices()[:4])
    spec = PagedKVCacheSpec(layers=2, slots=4, heads=4, pages=8,
                            page_len=4, head_dim=8, max_pages=2)
    monkeypatch.setattr(jax, "device_put", spy)
    cache = shard_cache(init_paged_cache(spec), mesh,
                        paged_cache_shardings(mesh))
    assert len(calls) == 1 and isinstance(calls[0], list)
    assert cache["k"].shape == (2, 8, 4, 4, 8)
    calls.clear()
    legacy = KVCacheSpec(layers=2, slots=8, heads=4, max_len=8,
                         head_dim=4)
    shard_cache(init_cache(legacy), mesh)
    assert len(calls) == 1 and isinstance(calls[0], list)


def test_paged_cache_mesh_validation():
    spec = PagedKVCacheSpec(layers=2, slots=4, heads=4, pages=7,
                            page_len=4, head_dim=8, max_pages=2)
    with pytest.raises(ValueError, match="pages"):
        validate_paged_cache_mesh(
            build_mesh(dp=2, devices=jax.devices()[:2]), spec)
    spec2 = PagedKVCacheSpec(layers=2, slots=4, heads=3, pages=8,
                             page_len=4, head_dim=8, max_pages=2)
    with pytest.raises(ValueError, match="model axis"):
        validate_paged_cache_mesh(
            build_mesh(dp=1, tp=2, devices=jax.devices()[:2]), spec2)
    assert spec.page_bytes == 2 * 2 * 4 * 4 * 8 * 4
    assert spec.bytes == spec.page_bytes * spec.pages


def test_paged_tp_dp_sharded_matches_single_device():
    model = GPT2Model(TINY_FLASH)
    params = model.init(jax.random.PRNGKey(0))
    prompts = [list(_tokens(5, seed=i)) for i in range(4)]

    def run(mesh):
        eng = ServeEngine(model, _serve_cfg(page_len=8), mesh=mesh,
                          params=params)
        rs = [eng.submit(p, max_new_tokens=6) for p in prompts]
        eng.run_until_idle()
        toks = [r.tokens for r in rs]
        eng.close()
        return toks

    base = run(None)
    sharded = run(build_mesh(dp=2, tp=2, devices=jax.devices()[:4]))
    assert base == sharded


# ---------------------------------------------------------------------------
# config block
# ---------------------------------------------------------------------------


def test_paged_serving_config_validation():
    from deepspeed_tpu.config.config import DeepSpeedServingConfig
    ok = DeepSpeedServingConfig({"serving": {"page_len": 16,
                                             "pages": 64}})
    assert ok.page_len == 16 and ok.pages == 64 and ok.prefix_cache
    off = DeepSpeedServingConfig({"serving": {}})
    assert off.page_len == 0 and off.pages == 0
    with pytest.raises(DeepSpeedConfigError, match="page_len"):
        DeepSpeedServingConfig({"serving": {"page_len": -1}})
    with pytest.raises(DeepSpeedConfigError, match="page_len"):
        DeepSpeedServingConfig({"serving": {"pages": 8}})
    with pytest.raises(DeepSpeedConfigError, match="scratch"):
        DeepSpeedServingConfig({"serving": {"page_len": 8, "pages": 1}})
    with pytest.raises(DeepSpeedConfigError, match="prefix_cache"):
        DeepSpeedServingConfig({"serving": {"prefix_cache": "false"}})


# ---------------------------------------------------------------------------
# telemetry: gauges -> sync scalars -> summarize rows; flight recorder
# ---------------------------------------------------------------------------


def test_paged_telemetry_flows_to_summarize(tmp_path, capsys):
    from deepspeed_tpu.telemetry.cli import summarize
    model = GPT2Model(TINY)
    eng = ServeEngine(model, _serve_cfg(
        page_len=8, telemetry_path=tmp_path, flush_interval_ticks=2),
        params=model.init(jax.random.PRNGKey(0)))
    template = list(_tokens(16, seed=95))
    for i in range(3):
        eng.submit(template + list(_tokens(2, seed=96 + i)),
                   max_new_tokens=4)
    eng.run_until_idle()
    reg = eng.telemetry.registry
    assert reg.gauge("serve_pages_total").value() == \
        eng.cache_spec.pages - 1
    assert reg.counter("serve_prefix_hits_total").value() == 2
    eng.close()
    events = os.path.join(str(tmp_path), "events.jsonl")
    report = summarize(events)
    out = capsys.readouterr().out
    assert report["serve_page_utilization"] is not None
    assert report["serve_free_pages"] is not None
    assert report["serve_prefix_hit_ratio"] == pytest.approx(2 / 3)
    assert report["serve_prefix_hit_tokens"] == 32
    assert "kv page pool" in out and "prefix cache" in out


def test_serve_stage_depth_snapshots_include_free_pages():
    """The flight-recorder satellite: every serve stage ring event now
    carries the pool's free-page count next to the queue depth."""
    model = GPT2Model(TINY)
    eng = ServeEngine(model, _serve_cfg(page_len=8))
    eng.submit(list(_tokens(5, seed=97)), max_new_tokens=3)
    eng.run_until_idle()
    snap = eng.stage.flight_snapshot()
    assert snap["events"], "no stage events recorded"
    for ev in snap["events"]:
        assert "free_pages" in ev and "depth" in ev
        assert 0 <= ev["free_pages"] <= eng.cache_spec.pages - 1
    eng.close()
    # the pre-page engine keeps its plain int depth
    eng2 = ServeEngine(model, _serve_cfg())
    eng2.submit(list(_tokens(3, seed=98)), max_new_tokens=2)
    eng2.run_until_idle()
    evs = eng2.stage.flight_snapshot()["events"]
    assert evs and all("depth" in e and "free_pages" not in e
                       for e in evs)
    eng2.close()


# ---------------------------------------------------------------------------
# injected prefill device time ∝ computed pages (the bench's cost model)
# ---------------------------------------------------------------------------


def test_prefix_hit_prefill_pays_delta_chunks_only(monkeypatch):
    monkeypatch.setenv("DS_STAGE_DELAY_S", "serve:0.05")
    reset_fault_injection()
    model = GPT2Model(TINY)
    params = model.init(jax.random.PRNGKey(0))
    eng = ServeEngine(model, _serve_cfg(page_len=8), params=params)
    template = list(_tokens(16, seed=99))
    r1 = eng.submit(template + [1, 2], max_new_tokens=1)
    eng.run_until_idle()
    r2 = eng.submit(template + [3, 4], max_new_tokens=1)
    eng.run_until_idle()
    eng.close()
    # r1 computed 18 tokens = 3 chunks -> 2 extra delay units inside
    # the prefill window; r2 computed 2 tokens -> 0 extra
    assert r1.prefill_s >= 0.10
    assert r2.prefill_s < 0.05


# ---------------------------------------------------------------------------
# benchgate: explicit direction pin for the new headline
# ---------------------------------------------------------------------------


def test_benchgate_paged_ratio_is_higher_better():
    from tools.benchgate import compare, is_lower_better
    assert not is_lower_better("serve_paged_admitted_ratio")
    fresh = {"metric": "serve_paged_admitted_ratio", "value": 1.2}
    base = {"metric": "serve_paged_admitted_ratio", "value": 4.0}
    assert compare(fresh, base)["regressed"]
    assert not compare(base, fresh)["regressed"]


# ---------------------------------------------------------------------------
# bench smoke: >= 2x admitted slots at fixed KV bytes, prefix ∝ deltas
# ---------------------------------------------------------------------------


def test_bench_serve_paged_smoke(tmp_path):
    import importlib.util
    path = os.path.join(os.path.dirname(__file__), "..",
                        "bench_serve.py")
    spec = importlib.util.spec_from_file_location(
        "bench_serve_for_paged_test", path)
    bench_serve = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(bench_serve)
    rec = bench_serve.run_paged_ab(
        kv_budget_slots=2, max_seq_len=32, page_len=8, n_requests=8,
        template_len=16, prefix_k=3, tick_delay_s=0.02,
        out_dir=str(tmp_path))
    assert rec["metric"] == "serve_paged_admitted_ratio"
    # the CPU-provable acceptance bar: >= 2x admitted concurrency at a
    # fixed KV-byte budget under the short/long mix
    assert rec["value"] >= 2.0
    assert rec["paged"]["max_concurrent"] >= \
        2 * rec["legacy"]["max_concurrent"]
    # prefix caching: total prefill ∝ 1 template + K deltas
    assert rec["prefix"]["prefill_ratio"] < 0.75
    assert rec["prefix"]["on"]["prefix_hits"] == 2
    art = json.load(open(os.path.join(str(tmp_path),
                                      "BENCH_serve_paged.json")))
    assert art["value"] == rec["value"]
