"""Asynchronous input pipeline (DevicePrefetcher + engine wiring).

The step loop used to pay ``next(data_iter)`` → collate →
``_shard_batch`` serially before every dispatch; the prefetcher moves
that chain onto a daemon worker behind a bounded queue.  Contracts
these tests pin:

  - bitwise equivalence with the inline path (``DS_PREFETCH=0``):
    identical losses AND state trees over a seeded loader, standard and
    host-offload engine tiers, and with PLD (whose theta is overwritten
    at consumption time so prefetched batches stay valid across
    ``global_steps`` changes);
  - real concurrency, proven from tracer timestamps with an injected
    worker delay (``DS_PREFETCH_DELAY_S``): batch i+1's collate+put
    overlaps batch i's consumption window, and ``prefetch_wait`` ≈ 0 in
    steady state;
  - StopIteration propagates cleanly at epoch boundaries, worker
    failures poison the iterator with the ORIGINAL exception, shutdown
    is idempotent and ``engine.close()`` drains the worker;
  - ``_shard_batch`` issues ONE batched list-form ``jax.device_put``
    for all numpy leaves, and the multi-process arm raises the
    descriptive ValueError on mismatched jax.Array shardings.
"""
import importlib.util
import os
import sys
import threading
import time

import jax
import numpy as np
import pytest

sys.path.insert(0, "tests")

import deepspeed_tpu.runtime.engine as engine_mod
from deepspeed_tpu.config import DeepSpeedConfig, DeepSpeedConfigError
from deepspeed_tpu.parallel import build_mesh
from deepspeed_tpu.runtime.dataloader import (DeepSpeedDataLoader,
                                              RepeatingLoader)
from deepspeed_tpu.runtime.engine import DeepSpeedEngine
from deepspeed_tpu.runtime.prefetch import (DevicePlacedBatch,
                                            DevicePrefetcher)
from deepspeed_tpu.telemetry.tracing import TraceRecorder

from simple_model import SimpleModel, base_config

HIDDEN = 16


def _dataset(n, seed=0):
    rng = np.random.default_rng(seed)
    xs = rng.standard_normal((n, HIDDEN)).astype(np.float32)
    return [(xs[i], 0.5 * xs[i]) for i in range(n)]


def _engine(monkeypatch=None, prefetch_on=True, n_batches=4, seed=3,
            cfg_over=None, model=None, dataset=None, world_size=8,
            mesh=None):
    cfg = base_config(micro_bs=2, grad_acc=1)
    cfg.update(cfg_over or {})
    dscfg = DeepSpeedConfig(cfg, world_size=world_size)
    if mesh is None:
        mesh = build_mesh() if world_size == 8 else build_mesh(
            dp=1, devices=jax.devices()[:1])
    bs = dscfg.train_batch_size
    if monkeypatch is not None:
        if prefetch_on:
            monkeypatch.delenv("DS_PREFETCH", raising=False)
        else:
            monkeypatch.setenv("DS_PREFETCH", "0")
    eng = DeepSpeedEngine(
        model or SimpleModel(hidden_dim=HIDDEN), dscfg, mesh=mesh,
        seed=seed,
        training_data=(dataset if dataset is not None
                       else _dataset(bs * n_batches)))
    assert eng._prefetch_enabled == prefetch_on
    return eng


def _train(engine, steps):
    return [float(np.asarray(engine.train_batch())) for _ in range(steps)]


def _assert_state_bitwise(e_a, e_b):
    la = jax.tree.leaves(e_a.state)
    lb = jax.tree.leaves(e_b.state)
    assert len(la) == len(lb)
    for i, (x, y) in enumerate(zip(la, lb)):
        np.testing.assert_array_equal(np.asarray(x), np.asarray(y),
                                      err_msg=f"state leaf {i}")


# ---------------------------------------------------------------------
# bitwise equivalence: prefetched vs inline (DS_PREFETCH=0)
# ---------------------------------------------------------------------
def test_prefetch_bitwise_equals_inline(monkeypatch):
    """The acceptance contract (standard tier): N steps over the same
    seeded loader produce identical losses and state trees — the env
    escape hatch IS the inline reference, so it is exercised too."""
    e_on = _engine(monkeypatch, prefetch_on=True)
    e_off = _engine(monkeypatch, prefetch_on=False)
    assert isinstance(e_on._training_iter(), DevicePrefetcher)
    assert not isinstance(e_off._training_iter(), DevicePrefetcher)
    l_on = _train(e_on, 4)
    l_off = _train(e_off, 4)
    assert l_on == l_off
    _assert_state_bitwise(e_on, e_off)
    e_on.close()
    e_off.close()


def test_prefetch_bitwise_offload_tier(monkeypatch):
    """Same contract on the host-offload engine tier (its step path
    composes the input pipeline with the optimizer pipeline)."""
    over = {"zero_optimization": {"stage": 2, "cpu_offload": True,
                                  "offload_impl": "host"},
            "train_micro_batch_size_per_gpu": 4}
    e_on = _engine(monkeypatch, prefetch_on=True, cfg_over=over,
                   world_size=1)
    e_off = _engine(monkeypatch, prefetch_on=False, cfg_over=over,
                    world_size=1)
    l_on = _train(e_on, 3)
    l_off = _train(e_off, 3)
    assert l_on == l_off
    _assert_state_bitwise(e_on, e_off)
    e_on.close()
    e_off.close()


class _PLDModel(SimpleModel):
    """Consumes the engine-injected pld_theta leaf so the theta VALUE
    affects the loss — a stale (placement-time) theta would break the
    bitwise contract below."""

    def loss_fn(self, params, batch, rng, train=True):
        import jax.numpy as jnp
        x, y = batch["x"], batch["y"]
        theta = batch.get("pld_theta")
        base = super().loss_fn(params, (x, y), rng, train)
        if theta is not None:
            return base * jnp.mean(theta.astype(jnp.float32))
        return base


def test_prefetch_pld_theta_overwritten_at_consumption(monkeypatch):
    """PLD + prefetch: batches are placed AHEAD of the step that
    consumes them, so the theta leaf is a placeholder until
    consumption-time overwrite — losses/state must still match the
    inline path exactly (which injects theta fresh each step)."""
    bs = 2 * 8
    ds = [{"x": x, "y": y} for x, y in _dataset(bs * 4)]
    over = {"progressive_layer_drop": {"enabled": True, "theta": 0.5,
                                       "gamma": 0.05}}
    e_on = _engine(monkeypatch, prefetch_on=True, cfg_over=over,
                   model=_PLDModel(hidden_dim=HIDDEN), dataset=ds)
    e_off = _engine(monkeypatch, prefetch_on=False, cfg_over=over,
                    model=_PLDModel(hidden_dim=HIDDEN), dataset=ds)
    # depth-2 queue: batch for step t is placed while global_steps is
    # still t-1 (or t-2) — exactly the staleness the overwrite fixes
    l_on = _train(e_on, 4)
    l_off = _train(e_off, 4)
    assert l_on == l_off
    _assert_state_bitwise(e_on, e_off)
    # theta actually moved over the run (the schedule was live)
    e_on.progressive_layer_drop.update_state(e_on.global_steps)
    assert e_on.progressive_layer_drop.get_theta() < 1.0
    e_on.close()
    e_off.close()


# ---------------------------------------------------------------------
# the concurrency proof: tracer timestamps + injected worker delay
# ---------------------------------------------------------------------
def test_prefetch_overlap_proven_by_tracer(monkeypatch):
    """With a 30ms injected worker delay (DS_PREFETCH_DELAY_S) and a
    50ms consumer, steady-state ``prefetch_wait`` ≈ 0 — batch i+1's
    collate+put ran during batch i's consumption window, read straight
    off tracer timestamps."""
    monkeypatch.setenv("DS_PREFETCH_DELAY_S", "0.03")
    tracer = TraceRecorder()

    def span_fn(name, cat="runtime", **args):
        return tracer.span(name, cat, **args)

    src = iter([np.full((4,), float(i), np.float32) for i in range(6)])
    pf = DevicePrefetcher(src, place_fn=lambda b: jax.device_put(b),
                          depth=2, span_fn=span_fn)
    waits = []
    try:
        for _ in range(6):
            t0 = time.perf_counter()
            batch = next(pf)
            waits.append(time.perf_counter() - t0)
            assert isinstance(batch, jax.Array)
            with tracer.span("consume", "test"):
                time.sleep(0.05)
    finally:
        pf.close()
    # the first pull pays the pipeline fill; steady state is hidden
    assert waits[0] >= 0.02, waits
    assert max(waits[2:]) < 0.02, waits

    def intervals(name):
        return [(e["ts"], e["ts"] + e["dur"]) for e in tracer.events()
                if e.get("name") == name and e.get("ph") == "X"]

    place = intervals("data/prefetch_place")
    consume = intervals("consume")
    assert len(place) == 6 and len(consume) == 6
    overlaps = [min(p1, c1) - max(p0, c0)
                for p0, p1 in place for c0, c1 in consume]
    assert max(overlaps) > 0.02 * 1e6, (
        "no place × consume overlap observed in the trace")
    s = pf.stats()
    assert s["consumed"] == 6
    assert s["hits"] >= 4  # steady state: batch already resident


def test_prefetch_wait_span_emitted(monkeypatch):
    tracer = TraceRecorder()
    pf = DevicePrefetcher(iter([np.zeros(2)]),
                          span_fn=lambda n, cat="x", **a:
                          tracer.span(n, cat, **a))
    next(pf)
    pf.close()
    names = {e["name"] for e in tracer.events()}
    assert "data/prefetch_wait" in names
    assert "data/prefetch_place" in names


# ---------------------------------------------------------------------
# lifecycle: epoch boundary, poison, close, depth bound
# ---------------------------------------------------------------------
def test_stop_iteration_propagates_after_draining():
    pf = DevicePrefetcher(iter([np.zeros(2), np.ones(2)]), depth=4)
    assert np.asarray(next(pf)).sum() == 0
    assert np.asarray(next(pf)).sum() == 2
    with pytest.raises(StopIteration):
        next(pf)
    with pytest.raises(StopIteration):  # stays exhausted
        next(pf)


def test_engine_epoch_boundary_stop_iteration(monkeypatch):
    """A finite (non-repeating) training loader: the engine's wrapped
    iterator raises StopIteration at the epoch boundary, same as the
    inline path."""
    e = _engine(monkeypatch, prefetch_on=True, n_batches=2)
    _train(e, 2)
    with pytest.raises(StopIteration):
        e.train_batch()
    e.close()


def test_worker_source_failure_poisons_with_original_error():
    def gen():
        yield np.zeros(2)
        raise ValueError("collate died")

    pf = DevicePrefetcher(gen(), depth=2)
    next(pf)  # the batch produced before the failure drains first
    with pytest.raises(ValueError, match="collate died"):
        next(pf)
    with pytest.raises(ValueError, match="collate died"):  # poisoned
        next(pf)


def test_worker_place_failure_poisons():
    seen = {"n": 0}

    def place(b):
        seen["n"] += 1
        if seen["n"] > 1:
            raise RuntimeError("h2d link died")
        return b

    pf = DevicePrefetcher(iter([np.zeros(2)] * 4), place_fn=place,
                          depth=2)
    next(pf)
    with pytest.raises(RuntimeError, match="h2d link died"):
        next(pf)


def test_close_idempotent_and_releases_worker():
    before = set(threading.enumerate())
    pf = DevicePrefetcher(iter([np.zeros(2)] * 8), depth=2)
    workers = set(threading.enumerate()) - before
    next(pf)
    pf.close()
    pf.close()  # idempotent
    with pytest.raises(RuntimeError, match="closed"):
        next(pf)
    deadline = time.perf_counter() + 5.0
    while any(t.is_alive() for t in workers) and \
            time.perf_counter() < deadline:
        time.sleep(0.01)
    assert not any(t.is_alive() for t in workers), "worker leaked"


def test_engine_close_drains_prefetcher(monkeypatch):
    e = _engine(monkeypatch, prefetch_on=True)
    _train(e, 1)
    pf = e._train_prefetcher
    assert pf is not None and not pf.closed
    e.close()
    assert pf.closed


def test_depth_bounds_lookahead():
    class Counting:
        def __init__(self):
            self.count = 0

        def __next__(self):
            self.count += 1
            return np.zeros(2)

    src = Counting()
    pf = DevicePrefetcher(src, depth=2)
    deadline = time.perf_counter() + 5.0
    while src.count < 2 and time.perf_counter() < deadline:
        time.sleep(0.01)
    time.sleep(0.1)  # worker must now be parked at the bound
    assert src.count == 2, src.count
    next(pf)
    deadline = time.perf_counter() + 5.0
    while src.count < 3 and time.perf_counter() < deadline:
        time.sleep(0.01)
    time.sleep(0.1)
    assert src.count == 3, src.count
    pf.close()


def test_depth_validation():
    with pytest.raises(ValueError, match="depth"):
        DevicePrefetcher(iter([]), depth=0)
    with pytest.raises(DeepSpeedConfigError, match="depth"):
        DeepSpeedConfig(base_config(data_prefetch={"depth": 0}),
                        world_size=8)
    with pytest.raises(DeepSpeedConfigError, match="depth"):
        DeepSpeedConfig(base_config(data_prefetch={"depth": True}),
                        world_size=8)
    cfg = DeepSpeedConfig(base_config(), world_size=8)
    assert cfg.data_prefetch_config.enabled is True  # default ON
    assert cfg.data_prefetch_config.depth == 2


# ---------------------------------------------------------------------
# engine adoption: external prefetcher, eval, placed-batch tag
# ---------------------------------------------------------------------
def test_train_batch_adopts_external_prefetcher(monkeypatch):
    bs = 2 * 8
    ds = _dataset(bs * 3)
    e_pf = _engine(monkeypatch, prefetch_on=False, dataset=ds)
    e_ref = _engine(monkeypatch, prefetch_on=False, dataset=ds)
    loader = DeepSpeedDataLoader(ds, batch_size=bs)
    pf = e_pf.prefetch(iter(loader))
    l_pf = [float(np.asarray(e_pf.train_batch(data_iter=pf)))
            for _ in range(3)]
    l_ref = _train(e_ref, 3)
    assert l_pf == l_ref
    # adopted: stats tracked and close() owns it
    assert e_pf._train_prefetcher is pf
    e_pf.close()
    assert pf.closed
    e_ref.close()


def test_eval_batch_adopts_prefetched(monkeypatch):
    e = _engine(monkeypatch, prefetch_on=False)
    batch = _dataset(16, seed=9)
    xs = np.stack([b[0] for b in batch])
    ys = np.stack([b[1] for b in batch])
    direct = float(np.asarray(e.eval_batch(batch=(xs, ys))))
    pf = e.prefetch(iter([(xs, ys)]), for_eval=True)
    via_pf = float(np.asarray(e.eval_batch(data_iter=pf)))
    assert direct == via_pf
    pf.close()
    e.close()


def test_dropped_engine_stays_collectable(monkeypatch):
    """The worker thread is a GC root: it must hold the engine WEAKLY,
    so an engine dropped without close() is still collected (its flush
    finalizer fires) and the finalizer drains the parked worker."""
    import gc
    import weakref

    e = _engine(monkeypatch, prefetch_on=True)
    _train(e, 1)
    pf = e._train_prefetcher
    assert pf is not None
    ref = weakref.ref(e)
    del e
    gc.collect()
    assert ref() is None, "engine pinned by the prefetch worker"
    deadline = time.perf_counter() + 5.0
    while not pf.closed and time.perf_counter() < deadline:
        gc.collect()
        time.sleep(0.02)
    assert pf.closed, "finalizer did not drain the worker"


def test_engine_close_drains_eval_prefetchers(monkeypatch):
    """An engine-built eval prefetcher abandoned mid-consumption must be
    drained by engine.close() — otherwise its parked worker pins
    ``depth`` device-resident batches forever."""
    e = _engine(monkeypatch, prefetch_on=False)
    batch = _dataset(16, seed=9)
    xs = np.stack([b[0] for b in batch])
    ys = np.stack([b[1] for b in batch])
    pf = e.prefetch(iter([(xs, ys)] * 6), for_eval=True)
    e.eval_batch(data_iter=pf)  # consume one, abandon the rest
    assert not pf.closed
    e.close()
    assert pf.closed


def test_placed_batch_kind_mismatch_is_descriptive(monkeypatch):
    """A prefetcher built with the wrong for_eval flag must fail with a
    descriptive error at the consumption site, not a deep shape error
    (or a silently wrong loss) inside the compiled step."""
    e = _engine(monkeypatch, prefetch_on=False)
    batch = _dataset(16, seed=9)
    xs = np.stack([b[0] for b in batch])
    ys = np.stack([b[1] for b in batch])
    pf_train = e.prefetch(iter([(xs, ys)]))
    with pytest.raises(ValueError, match="for_eval=True"):
        e.eval_batch(data_iter=pf_train)
    pf_eval = e.prefetch(iter([(xs, ys)]), for_eval=True)
    with pytest.raises(ValueError, match="train placement"):
        e.train_batch(data_iter=pf_eval)
    e.close()


def test_adopted_prefetcher_replaced_still_drains(monkeypatch):
    """Mixed usage: a caller-built training prefetcher adopted via
    data_iter=, then a no-arg train_batch() that builds the engine's
    own — the replaced one must still be closed by engine.close(), and
    the stats baseline must reset (no negative interval deltas)."""
    bs = 2 * 8
    ds = _dataset(bs * 4)
    e = _engine(monkeypatch, prefetch_on=True, dataset=ds)
    external = e.prefetch(iter(DeepSpeedDataLoader(ds, batch_size=bs)))
    e.train_batch(data_iter=external)
    assert e._train_prefetcher is external
    e.train_batch()  # no-arg: engine builds + binds its own
    assert e._train_prefetcher is not external
    assert e._prefetch_prev_stats is None  # baseline reset on rebind
    e.close()
    assert external.closed
    assert e._train_prefetcher.closed


def test_prefetcher_list_pruned(monkeypatch):
    """Per-eval prefetchers must not accumulate forever: closed entries
    are pruned from the engine's list on the next prefetch()."""
    e = _engine(monkeypatch, prefetch_on=False)
    for _ in range(5):
        pf = e.prefetch(iter([]), for_eval=True)
        pf.close()
    assert len(e._prefetchers) <= 2
    e.close()


def test_placed_batch_is_explicit_tag(monkeypatch):
    """A user batch containing jax Arrays must still go through the
    engine's reshape/validation — only the DevicePlacedBatch TAG skips
    it."""
    e = _engine(monkeypatch, prefetch_on=False)
    placed = e._place_train_batch(next(iter(
        DeepSpeedDataLoader(_dataset(16), batch_size=16))))
    assert isinstance(placed, DevicePlacedBatch)
    loss = float(np.asarray(e.train_batch(placed)))
    assert np.isfinite(loss)
    e.close()


# ---------------------------------------------------------------------
# _shard_batch satellites: batched put + multi-process error arm
# ---------------------------------------------------------------------
def test_shard_batch_issues_one_batched_put(monkeypatch):
    e = _engine(monkeypatch, prefetch_on=False)
    bs = e.train_batch_size
    x = np.zeros((bs, HIDDEN), np.float32)
    y = np.ones((bs, HIDDEN), np.float32)
    calls = []
    real_put = jax.device_put

    def spy(v, device=None, **kw):
        calls.append(v)
        return real_put(v, device, **kw)

    monkeypatch.setattr(engine_mod.jax, "device_put", spy)
    sharded = e._shard_batch((x, y))
    monkeypatch.undo()
    assert len(calls) == 1, f"{len(calls)} device_put calls (want 1)"
    assert isinstance(calls[0], list) and len(calls[0]) == 2
    for leaf in jax.tree.leaves(sharded):
        assert leaf.shape[:2] == (1, bs)
    e.close()


def test_shard_batch_device_leaf_passthrough(monkeypatch):
    """jax.Array leaves keep the pay-zero-transfer contract (a repeating
    batch device_put ONCE costs nothing per step)."""
    e = _engine(monkeypatch, prefetch_on=False)
    bs = e.train_batch_size
    x = jax.device_put(np.zeros((bs, HIDDEN), np.float32))
    sharded = e._shard_batch((x, np.ones((bs, HIDDEN), np.float32)))
    assert jax.tree.leaves(sharded)[0].shape == (1, bs, HIDDEN)
    e.close()


def test_shard_batch_multiprocess_error_arm(monkeypatch):
    """nproc > 1 with a mismatched-sharding jax.Array must raise the
    descriptive ValueError, not a deep XLA error."""
    e = _engine(monkeypatch, prefetch_on=False)
    rows = e.train_batch_size // 2  # per-process slice at nproc=2
    x = jax.device_put(np.zeros((rows, HIDDEN), np.float32))
    monkeypatch.setattr(engine_mod.jax, "process_count", lambda: 2)
    with pytest.raises(ValueError,
                       match="multi-process _shard_batch needs "
                             "process-local"):
        e._shard_batch({"x": x})
    monkeypatch.undo()
    e.close()


# ---------------------------------------------------------------------
# telemetry: wait span + hit-ratio scalar + gauge + summarize row
# ---------------------------------------------------------------------
def test_prefetch_telemetry_artifacts(monkeypatch, tmp_path):
    import json as _json
    from deepspeed_tpu.telemetry.cli import summarize

    e = _engine(monkeypatch, prefetch_on=True,
                cfg_over={"steps_per_print": 1,
                          "telemetry": {"enabled": True,
                                        "output_path": str(tmp_path)}})
    _train(e, 3)
    depth_gauge = e.telemetry.registry.gauge("data_prefetch_queue_depth")
    assert depth_gauge.value() is not None
    e.close()

    prom = (tmp_path / "metrics.prom").read_text()
    assert "data_prefetch_queue_depth" in prom
    syncs = [_json.loads(l) for l in
             (tmp_path / "events.jsonl").read_text().splitlines()
             if _json.loads(l).get("kind") == "sync"]
    assert any("prefetch_hit_ratio" in (s.get("scalars") or {})
               for s in syncs)
    rep = summarize(str(tmp_path / "events.jsonl"))
    assert rep["prefetch_hit_ratio"] is not None


def test_summarize_prefetch_row(tmp_path, capsys):
    import json as _json
    from deepspeed_tpu.telemetry.cli import summarize
    p = tmp_path / "events.jsonl"
    lines = [{"kind": "sync", "step": 10 * (i + 1), "interval_s": 1.0,
              "steps": 10, "step_avg_s": 0.1,
              "scalars": {"prefetch_hit_ratio": r,
                          "prefetch_wait_s": 0.001}}
             for i, r in enumerate((0.8, 1.0))]
    p.write_text("\n".join(_json.dumps(l) for l in lines) + "\n")
    rep = summarize(str(p))
    assert rep["prefetch_hit_ratio"] == pytest.approx(0.9)
    assert rep["prefetch_wait_s"] == pytest.approx(0.001)
    assert "input prefetch" in capsys.readouterr().out


# ---------------------------------------------------------------------
# bench CPU smoke (tier-1): the A/B leg with an injected slow collate
# ---------------------------------------------------------------------
def _load_bench():
    path = os.path.join(os.path.dirname(__file__), "..", "bench.py")
    spec = importlib.util.spec_from_file_location("bench_for_test", path)
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


def test_bench_prefetch_smoke(monkeypatch):
    """The --prefetch A/B legs on CPU with a 50ms injected collate: the
    off leg pays it inline every step, the on leg's exposed input stall
    (prefetch_wait) is strictly smaller — the worker hid the step's
    compute window worth of it."""
    bench = _load_bench()
    monkeypatch.setenv("BENCH_PREFETCH_COLLATE_S", "0.05")
    on = bench.bench_prefetch(jax, prefetch_on=True, steps=2)
    off = bench.bench_prefetch(jax, prefetch_on=False, steps=2)
    assert on["prefetch"] == "on" and off["prefetch"] == "off"
    assert "prefetch_wait_s" in on and "prefetch_wait_s" not in off
    # off pays the collate on the hot path every step
    assert off["step_s"] >= 0.05, off
    # on: the worker hid the collate — the step's exposed input stall is
    # a fraction of the injected delay, and batches were already
    # resident when asked.  (No raw step_s comparison: wall-clock A/B
    # on a loaded CI container is noise; the wait/hit numbers are the
    # same evidence without the flake.)
    assert on["prefetch_wait_s"] < 0.05, on
    assert on["hit_ratio"] > 0.0, on
