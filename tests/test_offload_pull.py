"""Wedge-proofing of the host-tier bulk pulls.

Round-3 root cause (BENCH_NOTES.md): one monolithic ``jax.device_get``
of a multi-GB leaf is a single native call that a sick tunnel stalls
*forever* — un-interruptible by signals, holding the device. The fix is
piece-wise pulls with a per-piece daemon-thread watchdog
(``runtime/offload.py: chunked_device_get``), mirroring how the
reference staggers its pinned-buffer copies tile by tile (reference:
csrc/adam/cpu_adam.cpp:64-113). These tests simulate the stall and
assert the failure is a clean RuntimeError that leaves the process
healthy — the bench chain can then fall through to the next tier.
"""
import threading
import time

import jax
import jax.numpy as jnp
import numpy as np
import pytest

import deepspeed_tpu.runtime.offload as offload
from deepspeed_tpu.runtime.offload import (HostOffloadOptimizer,
                                           chunked_device_get)


# ---------------------------------------------------------------------
# correctness: chunked pull == plain pull
# ---------------------------------------------------------------------
@pytest.mark.parametrize("shape,dtype", [
    ((), jnp.float32),
    ((7,), jnp.float32),
    ((100, 50), jnp.float32),
    ((33, 16), jnp.bfloat16),
    ((64, 3), jnp.int32),
])
def test_chunked_get_matches_plain(shape, dtype):
    x = jnp.arange(int(np.prod(shape)) or 1, dtype=jnp.float32)
    x = x.reshape(shape).astype(dtype)
    # chunk_mb tiny enough to force many pieces on the 2-D cases
    got = chunked_device_get(x, chunk_mb=0.002, piece_timeout=30)
    want = np.asarray(jax.device_get(x))
    assert got.dtype == want.dtype
    np.testing.assert_array_equal(got, want)


def test_chunked_get_numpy_passthrough():
    x = np.arange(12.0, dtype=np.float32).reshape(3, 4)
    got = chunked_device_get(x, chunk_mb=0.001, piece_timeout=5)
    np.testing.assert_array_equal(got, x)


def test_chunked_get_watchdog_disabled():
    x = jnp.ones((8, 8))
    got = chunked_device_get(x, chunk_mb=0.001, piece_timeout=0)
    np.testing.assert_array_equal(got, np.ones((8, 8), np.float32))


def test_chunked_get_actually_chunks(monkeypatch):
    """The piece loop must issue multiple bounded native calls — that
    bound IS the wedge protection."""
    calls = []
    real_get = jax.device_get

    def spy(x):
        calls.append(tuple(x.shape))
        return real_get(x)

    monkeypatch.setattr(offload.jax, "device_get", spy)
    x = jnp.ones((100, 128))  # 51.2 KB fp32
    chunked_device_get(x, chunk_mb=0.01, piece_timeout=30)  # ~10 KB pieces
    assert len(calls) >= 4
    assert all(int(np.prod(s)) * 4 <= 16 << 10 for s in calls)


def test_chunked_get_bounds_pieces_for_wide_leaves(monkeypatch):
    """Flat element-range chunking: a (2, huge) leaf must NOT produce
    half-leaf pieces — every piece stays <= the chunk size, so the
    per-piece timeout measures PROGRESS even on leaves with few rows
    (the slow-vs-stalled distinction)."""
    calls = []
    real_get = jax.device_get

    def spy(x):
        calls.append(int(np.prod(x.shape)))
        return real_get(x)

    monkeypatch.setattr(offload.jax, "device_get", spy)
    x = jnp.ones((2, 16384))  # 128 KB fp32, only 2 rows
    got = chunked_device_get(x, chunk_mb=0.01, piece_timeout=30)
    assert all(n * 4 <= 16 << 10 for n in calls)
    assert len(calls) >= 8
    np.testing.assert_array_equal(got, np.ones((2, 16384), np.float32))


# ---------------------------------------------------------------------
# the stall: a piece that never completes raises cleanly and quickly
# ---------------------------------------------------------------------
def test_stalled_piece_raises_cleanly(monkeypatch):
    release = threading.Event()
    real_get = jax.device_get

    def stalled(x):
        release.wait()  # simulate the un-interruptible native stall
        return real_get(x)

    monkeypatch.setattr(offload.jax, "device_get", stalled)
    x = jnp.ones((100, 128))
    t0 = time.perf_counter()
    try:
        with pytest.raises(RuntimeError, match="did not complete"):
            chunked_device_get(x, chunk_mb=0.01, piece_timeout=0.3)
        elapsed = time.perf_counter() - t0
        # one piece-timeout, not shape[0] of them, and nowhere near a hang
        assert elapsed < 5.0
    finally:
        release.set()  # let the abandoned daemon thread exit
    monkeypatch.undo()
    # process stays healthy: a subsequent pull works (the "next probe")
    got = chunked_device_get(jnp.ones((4, 4)), piece_timeout=10)
    np.testing.assert_array_equal(got, np.ones((4, 4), np.float32))


def test_stalled_master_pull_fails_construction(monkeypatch):
    """End-to-end: HostOffloadOptimizer construction on a stalled link is
    a RuntimeError (the engine attempt chain catches it and falls through
    to the xla tier), not a hang."""
    release = threading.Event()
    real_get = jax.device_get

    def stalled(x):
        release.wait()
        return real_get(x)

    master = {"w": jnp.ones((600, 1024)),  # 2.4 MB: big enough to probe
              "b": jnp.zeros((1024,))}
    monkeypatch.setattr(offload.jax, "device_get", stalled)
    try:
        with pytest.raises(RuntimeError):
            HostOffloadOptimizer(
                master, lr=1e-3, betas=(0.9, 0.999), eps=1e-8,
                weight_decay=0.0)
    finally:
        release.set()


# ---------------------------------------------------------------------
# slow-but-completing probe: warn by default, hard error on request
# ---------------------------------------------------------------------
def _slow_link(monkeypatch, delay=0.05):
    real_get = jax.device_get

    def slow(x):
        time.sleep(delay)
        return real_get(x)

    monkeypatch.setattr(offload.jax, "device_get", slow)


def test_slow_probe_warns_by_default(monkeypatch):
    import logging

    from deepspeed_tpu.utils.logging import logger as ds_logger

    _slow_link(monkeypatch)
    monkeypatch.delenv("DS_OFFLOAD_SLOW_LINK", raising=False)
    master = {"w": jnp.ones((600, 1024))}
    records = []

    class Rec(logging.Handler):
        def emit(self, record):
            records.append(record)

    h = Rec(level=logging.WARNING)
    ds_logger.addHandler(h)
    try:
        # must NOT raise; must log the loud warning
        HostOffloadOptimizer._probe_transfer_path(
            master, min_mbps=1e9, probe_timeout=30)
    finally:
        ds_logger.removeHandler(h)
    assert any("MB/s" in r.getMessage() for r in records)


def test_slow_probe_errors_when_strict(monkeypatch):
    _slow_link(monkeypatch)
    monkeypatch.setenv("DS_OFFLOAD_SLOW_LINK", "error")
    master = {"w": jnp.ones((600, 1024))}
    with pytest.raises(RuntimeError, match="measured"):
        HostOffloadOptimizer._probe_transfer_path(
            master, min_mbps=1e9, probe_timeout=30)


def test_probe_propagates_pull_errors(monkeypatch):
    """A dead tunnel raising from device_get must FAIL the probe, not be
    swallowed into a fast-looking measurement."""
    def broken(x):
        raise ValueError("tunnel is dead")

    monkeypatch.setattr(offload.jax, "device_get", broken)
    master = {"w": jnp.ones((600, 1024))}
    with pytest.raises(ValueError, match="tunnel is dead"):
        HostOffloadOptimizer._probe_transfer_path(
            master, min_mbps=1, probe_timeout=30)


def test_steady_state_grad_pull_stall_raises(monkeypatch):
    """Steady-state guard: the per-step grad pull is watchdogged too —
    the probe certifies the link once, this holds for every step after."""
    release = threading.Event()
    real_get = jax.device_get

    def stalled(x):
        release.wait()
        return real_get(x)

    monkeypatch.setattr(offload.jax, "device_get", stalled)
    monkeypatch.setenv("DS_OFFLOAD_PULL_TIMEOUT", "0.3")
    try:
        with pytest.raises(RuntimeError, match="grad pull"):
            offload.guarded_tree_pull({"g": jnp.ones((32, 32))})
    finally:
        release.set()
    monkeypatch.undo()
    got = offload.guarded_tree_pull(
        {"g": jnp.ones((4, 4), jnp.bfloat16), "n": np.int32(3)})
    # dtype-preserving: the DPU stash must stay at 1x the grads' bytes
    assert got["g"].dtype == jnp.bfloat16
    np.testing.assert_array_equal(
        np.asarray(got["g"], np.float32), np.ones((4, 4), np.float32))


def test_prefetch_puller_order_and_errors(monkeypatch):
    """One worker, flatten-order prefetch: values match, device errors
    propagate to the consuming call, duplicate leaf objects are handled."""
    x = jnp.arange(16.0).reshape(4, 4)
    tree = {"a": x, "b": jnp.ones((2,)), "dup": x}
    puller = offload._PrefetchPuller(tree)
    out = jax.tree.map(puller, tree)
    np.testing.assert_array_equal(out["a"], np.asarray(x))
    np.testing.assert_array_equal(out["dup"], np.asarray(x))

    def broken(x):
        raise ValueError("tunnel is dead")

    monkeypatch.setattr(offload.jax, "device_get", broken)
    g = jnp.ones((3,))
    h = jnp.ones((5,))
    puller = offload._PrefetchPuller({"g": g, "h": h})
    with pytest.raises(ValueError, match="tunnel is dead"):
        puller(g)
    # later slots are poisoned with the SAME error, immediately (no
    # per-leaf piece-timeout burn)
    with pytest.raises(ValueError, match="tunnel is dead"):
        puller(h)


def test_prefetch_puller_close_releases_skipped_leaves():
    """The consumer may legitimately skip trailing leaves (the Adam loop
    never requests non-fp32 ones).  close() must release the parked
    worker — otherwise each step leaks a daemon thread holding a
    reference to the whole grad tree — and fail any un-pulled slot a
    late (buggy) request touches instead of hanging."""
    leaves = [jnp.full((4,), float(i)) for i in range(8)]
    before = set(threading.enumerate())
    puller = offload._PrefetchPuller(leaves)
    workers = set(threading.enumerate()) - before  # THIS puller's thread
    assert workers, "no worker thread observed"
    out0 = puller(leaves[0])  # consume ONE leaf; skip the rest
    np.testing.assert_array_equal(out0, np.zeros((4,), np.float32))
    puller.close()
    deadline = time.perf_counter() + 5.0
    while any(t.is_alive() for t in workers) and \
            time.perf_counter() < deadline:
        time.sleep(0.02)
    assert not any(t.is_alive() for t in workers), "worker thread leaked"
    # a late request for a never-pulled leaf fails, not hangs
    with pytest.raises(RuntimeError, match="closed"):
        puller(leaves[-1])


def test_prefetch_puller_bounded_lookahead(monkeypatch):
    """The worker must stay <= LOOKAHEAD leaves past the consumer's need
    — the prefetch buffer is a few leaves, not a full grad tree."""
    pulled = []
    real_get = jax.device_get

    def spy(x):
        pulled.append(x.shape)
        return real_get(x)

    monkeypatch.setattr(offload.jax, "device_get", spy)
    leaves = [jnp.full((4,), float(i)) for i in range(8)]
    puller = offload._PrefetchPuller(leaves)
    time.sleep(0.4)  # give the worker time to run ahead if it could
    assert len(pulled) <= offload._PrefetchPuller.LOOKAHEAD + 1
    out = [puller(g) for g in leaves]
    for i, o in enumerate(out):
        np.testing.assert_array_equal(o, np.full((4,), float(i), np.float32))
    assert len(pulled) == 8


def test_poisoned_optimizer_refuses(monkeypatch):
    """A mid-step pull failure leaves master/moments partially updated:
    the optimizer must refuse further steps AND refuse to serialize that
    state; a checkpoint restore clears the poison."""
    opt = HostOffloadOptimizer(
        {"w": jnp.ones((8, 4)), "b": jnp.zeros((4,))},
        lr=1e-3, betas=(0.9, 0.999), eps=1e-8, weight_decay=0.0)
    healthy_state = opt.state_tree()
    healthy_master = jax.tree.map(np.copy, opt.master)

    def broken(x):
        raise ValueError("tunnel is dead")

    monkeypatch.setattr(offload.jax, "device_get", broken)
    with pytest.raises(ValueError, match="tunnel is dead"):
        opt.step({"w": jnp.ones((8, 4)), "b": jnp.ones((4,))})
    monkeypatch.undo()
    with pytest.raises(RuntimeError, match="poisoned"):
        opt.step({"w": np.ones((8, 4), np.float32),
                  "b": np.ones((4,), np.float32)})
    with pytest.raises(RuntimeError, match="refusing to serialize"):
        opt.state_tree()
    opt.load_state_tree(healthy_master, healthy_state)
    opt.step({"w": np.ones((8, 4), np.float32),
              "b": np.ones((4,), np.float32)})  # healthy again
    assert opt.state_tree()["step"] >= 1


def _pull_threads():
    return [t for t in threading.enumerate()
            if t.name.startswith("ds-offload-pull")]


def test_watchdog_reuses_one_persistent_worker():
    """No thread spawn per pulled piece (was ~100 spawns/step for a 6 GB
    master at 64 MB chunks): many chunked pulls ride ONE daemon worker.
    Counted by the worker's thread name, not process-wide active_count()
    — unrelated pools must not flake this."""
    # warm: create the worker
    chunked_device_get(jnp.ones((64, 64)), chunk_mb=0.001,
                       piece_timeout=30)
    worker = offload._PULL_POOL.worker
    assert worker is not None
    before = set(_pull_threads())
    assert before, "no pull worker thread observed"
    for _ in range(3):
        chunked_device_get(jnp.ones((100, 128)), chunk_mb=0.01,
                           piece_timeout=30)  # ~13 pieces each
    assert offload._PULL_POOL.worker is worker, "worker was replaced"
    # no NEW pull threads across ~40 pieces (an abandoned predecessor
    # from an earlier stall test may still be draining out of `before`,
    # which is why this is a no-new-threads check, not a count of 1)
    assert not (set(_pull_threads()) - before), (
        "watchdogged pulls must not spawn threads")


def test_watchdog_timeout_abandons_worker(monkeypatch):
    """A timed-out pull abandons the wedged worker (later pulls must not
    queue behind its stalled native call) and the next pull lazily gets
    a fresh one — the per-spawn semantics, paid only on failure."""
    chunked_device_get(jnp.ones((4, 4)), piece_timeout=10)  # ensure one
    wedged = offload._PULL_POOL.worker
    release = threading.Event()
    real_get = jax.device_get

    def stalled(x):
        release.wait()
        return real_get(x)

    monkeypatch.setattr(offload.jax, "device_get", stalled)
    try:
        with pytest.raises(RuntimeError, match="did not complete"):
            chunked_device_get(jnp.ones((32, 32)), chunk_mb=0.001,
                               piece_timeout=0.3)
    finally:
        release.set()  # let the abandoned worker drain and exit
    monkeypatch.undo()
    assert offload._PULL_POOL.worker is not wedged  # abandoned
    got = chunked_device_get(jnp.ones((4, 4)), piece_timeout=10)
    np.testing.assert_array_equal(got, np.ones((4, 4), np.float32))
    assert offload._PULL_POOL.worker is not None
    assert offload._PULL_POOL.worker is not wedged


def test_watchdog_retries_after_abandoned_worker():
    """The sentinel race: a pull landing on a worker that a concurrent
    timeout just stopped must retry transparently on a fresh worker —
    never surface a spurious 'stalled' error on a healthy link."""
    chunked_device_get(jnp.ones((4, 4)), piece_timeout=10)  # ensure one
    worker = offload._PULL_POOL.worker
    worker.stop()  # simulate the concurrent-timeout abandonment
    got = chunked_device_get(jnp.ones((4, 4)), piece_timeout=10)
    np.testing.assert_array_equal(got, np.ones((4, 4), np.float32))
    assert offload._PULL_POOL.worker is not None
    assert offload._PULL_POOL.worker is not worker


def test_fast_probe_passes(monkeypatch):
    monkeypatch.setenv("DS_OFFLOAD_SLOW_LINK", "error")
    master = {"w": jnp.ones((600, 1024))}
    HostOffloadOptimizer._probe_transfer_path(
        master, min_mbps=0.001, probe_timeout=30)


def test_sharded_tier_preserves_passthrough_dtypes():
    """Int/bool buffers must ride the sharded tier UNCAST (the single-
    controller to_host rule): blocks keep their dtype, Adam skips them,
    assemble/canonical/load round-trip them exactly — including wide
    int64 values an fp32 hop would corrupt."""
    from jax.sharding import Mesh, NamedSharding, PartitionSpec as P
    from deepspeed_tpu.runtime.offload import ShardedHostOffloadOptimizer

    devs = jax.devices()
    mesh = Mesh(np.array(devs), ("data",))
    wide = np.int32(2**24 + 1)  # exact in int32, corrupts via fp32
    master = {
        "w": jax.device_put(np.arange(32, dtype=np.float32).reshape(8, 4),
                            NamedSharding(mesh, P("data", None))),
        "counter": jax.device_put(np.array([wide, 7], np.int32),
                                  NamedSharding(mesh, P())),
        "flag": jax.device_put(np.array([True, False]),
                               NamedSharding(mesh, P())),
    }
    opt = ShardedHostOffloadOptimizer(
        master, lr=1e-2, betas=(0.9, 0.999), eps=1e-8, weight_decay=0.0,
        compute_dtype=jnp.bfloat16)

    # blocks keep their own dtype (leaf order: sorted dict keys)
    blocks = {k: [g["block"] for g in leaf]
              for k, leaf in zip(sorted(master), opt._local)}
    assert all(b.dtype == np.float32 for b in blocks["w"])
    assert all(b.dtype == np.int32 for b in blocks["counter"])
    assert all(b.dtype == np.bool_ for b in blocks["flag"])
    assert blocks["counter"][0][0] == wide

    # compute params: floats → bf16, passthrough buffers uncast
    cp = opt.compute_params()
    assert cp["w"].dtype == jnp.bfloat16
    assert cp["counter"].dtype == jnp.int32
    assert cp["flag"].dtype == jnp.bool_
    assert int(cp["counter"][0]) == wide

    # a step leaves passthrough buffers bit-identical
    grads = {
        "w": jax.device_put(np.ones((8, 4), np.float32),
                            NamedSharding(mesh, P("data", None))),
        "counter": jax.device_put(np.zeros(2, np.int32),
                                  NamedSharding(mesh, P())),
        "flag": jax.device_put(np.zeros(2, np.bool_),
                               NamedSharding(mesh, P())),
    }
    out = opt.step(grads)
    assert out["counter"].dtype == jnp.int32
    assert int(out["counter"][0]) == wide
    assert out["w"].dtype == jnp.bfloat16
    # Adam actually ran on the float leaf ("w" is leaf 2 in sorted order)
    w_blocks = [g["block"] for g in opt._local[2]]
    assert not np.allclose(np.concatenate([b.ravel() for b in w_blocks]),
                           np.arange(32, dtype=np.float32))

    # canonical save form + load round-trip keep the exact wide int
    m, st = opt.canonical_state()
    assert m["counter"].dtype == jnp.int32
    assert int(m["counter"][0]) == wide
    opt.load_state_tree(m, st)
    assert opt._local[0][0]["block"][0] == wide  # "counter" is leaf 0

    tmpl_m, _ = opt.canonical_templates()
    assert tmpl_m["counter"].dtype == jnp.int32
    assert tmpl_m["flag"].dtype == jnp.bool_
