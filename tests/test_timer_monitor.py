"""utils/timer.py + utils/monitor.py satellites: the _synchronize
per-device drain fix, ThroughputTimer semantics, and SummaryWriter
lifecycle hardening."""
import json
import os
import sys

import numpy as np
import pytest

import jax.numpy as jnp

from deepspeed_tpu.utils import timer as timer_mod
from deepspeed_tpu.utils.timer import ThroughputTimer


# ---------------------------------------------------------------------------
# _synchronize: continue past devices lacking the PJRT sync hook
# ---------------------------------------------------------------------------

class _HookDevice:
    def __init__(self, log):
        self._log = log

    def synchronize_all_activity(self):
        self._log.append(self)


class _NoHookDevice:
    pass  # no synchronize_all_activity attribute


def test_synchronize_drains_every_device(monkeypatch):
    """A device without synchronize_all_activity must not short-circuit
    the loop (the old ``break`` left later devices undrained), and gets
    the dispatched-token block_until_ready fallback instead."""
    drained = []
    no_hook = _NoHookDevice()
    hooked = _HookDevice(drained)
    monkeypatch.setattr(timer_mod.jax, "local_devices",
                        lambda: [no_hook, hooked])
    fallback_devices = []
    monkeypatch.setattr(
        timer_mod.jax, "device_put",
        lambda x, d: (fallback_devices.append(d), jnp.asarray(x))[1])
    blocked = []
    monkeypatch.setattr(timer_mod.jax, "block_until_ready",
                        lambda x: (blocked.append(x), x)[1])
    timer_mod._synchronize()
    assert drained == [hooked], \
        "device after the hook-less one was not drained"
    assert fallback_devices == [no_hook]
    assert len(blocked) == 1


def test_synchronize_fallback_failure_is_swallowed(monkeypatch):
    monkeypatch.setattr(timer_mod.jax, "local_devices",
                        lambda: [_NoHookDevice()])

    def boom(x, d):
        raise RuntimeError("no transfers to fake devices")
    monkeypatch.setattr(timer_mod.jax, "device_put", boom)
    timer_mod._synchronize()  # must not raise


# ---------------------------------------------------------------------------
# ThroughputTimer (previously untested)
# ---------------------------------------------------------------------------

@pytest.fixture
def fake_clock(monkeypatch):
    state = {"t": 100.0}

    def now():
        state["t"] += 0.25
        return state["t"]
    monkeypatch.setattr(timer_mod.time, "time", now)
    # keep _synchronize out of the fake-clock path entirely
    monkeypatch.setattr(timer_mod, "_synchronize", lambda: None)
    return state


def test_throughput_timer_warmup_skip(fake_clock):
    logs = []
    tt = ThroughputTimer(batch_size=8, start_step=2,
                         logging_fn=logs.append)
    tt.start()
    tt.stop()  # local step 1 < start_step: warmup, not counted
    assert tt.counted_steps == 0
    assert tt.total_step_count == 1
    assert tt.avg_samples_per_sec() == 0.0
    tt.start()
    tt.stop()  # step 2: counted
    assert tt.counted_steps == 1
    assert tt.avg_samples_per_sec() > 0.0


def test_throughput_timer_counted_steps_survive_epochs(fake_clock):
    tt = ThroughputTimer(batch_size=4, start_step=1,
                         logging_fn=lambda m: None)
    for _ in range(3):
        tt.start()
        tt.stop()
    assert tt.counted_steps == 3
    elapsed_before = tt.total_elapsed_time
    tt.update_epoch_count()
    assert tt.local_step_count == 0       # per-epoch counter resets
    assert tt.counted_steps == 3          # cumulative stats survive
    assert tt.total_elapsed_time == elapsed_before
    tt.start()
    tt.stop()
    assert tt.counted_steps == 4
    # rate uses CUMULATIVE elapsed / CUMULATIVE counted steps
    expect = 4 / (tt.total_elapsed_time / tt.counted_steps)
    assert tt.avg_samples_per_sec() == pytest.approx(expect)


def test_throughput_timer_zero_division_guards(fake_clock):
    tt = ThroughputTimer(batch_size=4, logging_fn=lambda m: None)
    assert tt.avg_samples_per_sec() == 0.0     # no steps at all
    tt.stop()                                  # stop without start: no-op
    assert tt.counted_steps == 0
    # counted steps but zero elapsed (frozen clock) must not divide
    tt2 = ThroughputTimer(batch_size=4, start_step=1,
                          logging_fn=lambda m: None)
    tt2.counted_steps = 1
    tt2.total_elapsed_time = 0.0
    assert tt2.avg_samples_per_sec() == 0.0


def test_throughput_timer_periodic_report(fake_clock):
    logs = []
    tt = ThroughputTimer(batch_size=4, start_step=1, steps_per_output=2,
                         logging_fn=logs.append)
    for _ in range(4):
        tt.start()
        tt.stop()
    assert len(logs) == 2
    assert "samples/sec" in logs[0]


# ---------------------------------------------------------------------------
# SummaryWriter lifecycle (forced JSONL fallback: torch import blocked)
# ---------------------------------------------------------------------------

@pytest.fixture
def jsonl_writer_cls(monkeypatch):
    monkeypatch.setitem(sys.modules, "torch", None)  # force the fallback
    from deepspeed_tpu.utils.monitor import SummaryWriter
    return SummaryWriter


def test_summary_writer_lifecycle(tmp_path, jsonl_writer_cls):
    w = jsonl_writer_cls(output_path=str(tmp_path), job_name="job")
    w.add_scalar("Train/loss", 1.5, 1)
    w.flush()
    w.flush()            # idempotent
    w.close()
    w.close()            # second close: previously died on closed handle
    assert w.closed
    w.add_scalar("Train/loss", 2.5, 2)   # post-close: dropped, no raise
    w.flush()                            # post-close flush: no-op
    lines = [json.loads(l) for l in
             open(os.path.join(str(tmp_path), "job", "events.jsonl"))]
    assert [l["step"] for l in lines] == [1]


def test_summary_writer_context_manager(tmp_path, jsonl_writer_cls):
    with jsonl_writer_cls(output_path=str(tmp_path), job_name="cm") as w:
        w.add_scalar("t", 1.0, 1)
    assert w.closed
    lines = open(os.path.join(str(tmp_path), "cm", "events.jsonl")).read()
    assert '"t"' in lines


def test_engine_close_flushes_buffered_scalars(tmp_path, monkeypatch):
    """Buffered _tb_pending scalars (steps_per_print never reached) land
    in the writer on engine.close() instead of being lost."""
    monkeypatch.setitem(sys.modules, "torch", None)
    sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))
    from simple_model import SimpleModel, base_config
    import deepspeed_tpu
    cfg = base_config(micro_bs=2, grad_acc=1, stage=0)
    cfg["steps_per_print"] = 10 ** 9
    cfg["tensorboard"] = {"enabled": True, "output_path": str(tmp_path),
                          "job_name": "close_test"}
    eng, *_ = deepspeed_tpu.initialize(model=SimpleModel(hidden_dim=16),
                                       config=cfg)
    rng = np.random.default_rng(0)
    x = rng.standard_normal((int(eng.train_batch_size), 16)) \
        .astype(np.float32)
    eng.train_batch((x, 0.5 * x))
    eng.train_batch((x, 0.5 * x))
    assert eng._tb_pending, "scalars should be buffered pre-sync"
    eng.close()
    eng.close()  # idempotent
    path = os.path.join(str(tmp_path), "close_test", "events.jsonl")
    steps = sorted({json.loads(l)["step"] for l in open(path)})
    assert steps == [1, 2]
    tags = {json.loads(l)["tag"] for l in open(path)}
    assert "Train/loss" in tags