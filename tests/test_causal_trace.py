"""Causal tracing, per-request serving traces, and the fault flight
recorder (ISSUE 10; docs/observability.md).

Covers the acceptance contract:
  - CPU-provable causal chain: under ``DS_STAGE_DELAY_S`` injected
    delay, trace.json contains flow events linking a prefetch place
    span to the consuming step span, and a serve request's admission to
    its decode ticks — asserted from the PARSED trace JSON (flow ids +
    span enclosure), not timestamps alone;
  - an injected sticky fault produces a ``flightrec_*.json`` whose
    ``diagnose`` output names the degraded stage and the original
    exception;
  - per-request serving records reconstruct TTFT / queue-wait p50/p99
    matching the registry histograms;
  - trace-context lifecycle at the fault boundaries: poison ends a
    request's trace with an error span (no leaked flows), degradation
    to inline keeps emitting the same span names, and export flushes
    in-flight flows.
"""
import glob
import json
import os

import numpy as np
import pytest

import jax

import deepspeed_tpu
from deepspeed_tpu.telemetry.cli import _percentile, diagnose, summarize
from deepspeed_tpu.telemetry.hub import write_flight_record
from deepspeed_tpu.telemetry.tracing import TraceContext, TraceRecorder
from deepspeed_tpu.runtime.stages import Stage, reset_fault_injection

from simple_model import SimpleModel, base_config

HIDDEN = 16


@pytest.fixture(autouse=True)
def _clean_faults():
    reset_fault_injection()
    yield
    reset_fault_injection()


def _load_trace(tel_dir):
    doc = json.loads(open(os.path.join(str(tel_dir), "trace.json")).read())
    return doc["traceEvents"]


def _enclosing_spans(evs, flow_ev):
    """Names of the complete spans (ph X) whose [ts, ts+dur] on the
    flow event's thread contain the flow event — the slice a Chrome
    flow arrow binds to."""
    return {e["name"] for e in evs
            if e["ph"] == "X" and e["tid"] == flow_ev["tid"]
            and e["ts"] <= flow_ev["ts"] <= e["ts"] + e["dur"]}


# ---------------------------------------------------------------------------
# TraceContext + flow-event primitives
# ---------------------------------------------------------------------------

def test_trace_context_ids_unique_and_child_lineage():
    a, b = TraceContext.new(), TraceContext.new()
    assert a.trace_id != b.trace_id
    c = a.child()
    assert c.trace_id == a.trace_id          # same flow
    assert c.parent_id == a.span_id
    assert c.span_id not in (a.span_id, b.trace_id)


def test_flow_events_emitted_with_shared_identity(tmp_path):
    tr = TraceRecorder()
    ctx = TraceContext.new()
    with tr.span("producer", cat="data"):
        tr.flow_start("link", ctx, cat="data")
    with tr.span("middle"):
        tr.flow_step("link", ctx, cat="data")
    with tr.span("consumer", cat="train"):
        tr.flow_end("link", ctx, cat="data")
    tr.export(str(tmp_path / "trace.json"))
    evs = json.loads(open(tmp_path / "trace.json").read())["traceEvents"]
    flows = [e for e in evs if e["ph"] in ("s", "t", "f")]
    assert [e["ph"] for e in flows] == ["s", "t", "f"]
    # Chrome binds a flow by (cat, id, name): all three must agree
    assert len({(e["name"], e["cat"], e["id"]) for e in flows}) == 1
    assert flows[0]["id"] == ctx.trace_id
    end = flows[-1]
    assert end["bp"] == "e"
    for e in flows:
        assert "ph" in e and "ts" in e and "name" in e  # trace contract


def test_export_flushes_in_flight_flows(tmp_path):
    """A flow open at shutdown (work in flight when the run died) is
    terminated by export — no dangling arrows, and the terminator is
    marked as a flush, not a real consumption."""
    tr = TraceRecorder()
    ctx = TraceContext.new()
    tr.flow_start("inflight", ctx)
    tr.export(str(tmp_path / "trace.json"))
    evs = json.loads(open(tmp_path / "trace.json").read())["traceEvents"]
    ends = [e for e in evs if e["ph"] == "f" and e["id"] == ctx.trace_id]
    assert len(ends) == 1
    assert ends[0]["args"]["flushed"] is True
    # flushing is once: a second export must not duplicate terminators
    tr.export(str(tmp_path / "trace2.json"))
    evs2 = json.loads(open(tmp_path / "trace2.json").read())["traceEvents"]
    assert len([e for e in evs2 if e["ph"] == "f"
                and e["id"] == ctx.trace_id]) == 1


def test_flow_terminators_survive_buffer_cap(tmp_path):
    """Regression: once the event buffer caps, a flow whose 's' was
    admitted must still get its 'f' (terminators force past the cap,
    bounded by admitted starts) — otherwise diagnose reports phantom
    in-flight work on a healthy run."""
    tr = TraceRecorder(max_events=4)
    ctx = TraceContext.new()
    tr.flow_start("link", ctx)
    for i in range(10):
        tr.instant(f"filler{i}")       # fill the buffer past the cap
    tr.flow_end("link", ctx)           # must not be dropped
    evs = tr.events()
    assert any(e["ph"] == "f" and e["id"] == ctx.trace_id for e in evs)
    assert tr.dropped > 0
    tr.export(str(tmp_path / "trace.json"))
    doc = json.loads(open(tmp_path / "trace.json").read())
    starts = {e["id"] for e in doc["traceEvents"] if e.get("ph") == "s"}
    ends = {e["id"] for e in doc["traceEvents"] if e.get("ph") == "f"}
    assert starts <= ends              # no dangling starts


def test_async_span_pairs_for_overlapping_intervals():
    tr = TraceRecorder()
    a = tr.async_begin("req", 1, cat="serve", rid=1)
    b = tr.async_begin("req", 2, cat="serve", rid=2)  # overlaps a
    a.end(reason="length")
    a.end()                            # idempotent
    b.end()
    evs = tr.events()
    assert [(e["ph"], e["id"]) for e in evs] == [
        ("b", 1), ("b", 2), ("e", 1), ("e", 2)]
    assert evs[2]["args"]["reason"] == "length"


# ---------------------------------------------------------------------------
# engine: prefetch place span -> consuming step span (acceptance)
# ---------------------------------------------------------------------------

def _make_engine(tel_dir, steps_per_print=10 ** 9, **tel_extra):
    cfg = base_config(micro_bs=2, grad_acc=1, stage=0)
    cfg["steps_per_print"] = steps_per_print
    cfg["telemetry"] = {"enabled": True, "output_path": str(tel_dir),
                        **tel_extra}
    eng, *_ = deepspeed_tpu.initialize(
        model=SimpleModel(hidden_dim=HIDDEN), config=cfg)
    return eng


def _batches(eng, n, seed=0):
    rng = np.random.default_rng(seed)
    for _ in range(n):
        x = rng.standard_normal((int(eng.train_batch_size),
                                 HIDDEN)).astype(np.float32)
        yield (x, 0.5 * x)


def test_prefetch_flow_links_place_span_to_step_span(tmp_path,
                                                     monkeypatch):
    """THE train-side causal chain, CPU-provable: with injected
    placement delay the worker's place spans and the consumer's
    dispatch spans are far apart in time and on different threads, and
    the flow events still link them pairwise by id."""
    monkeypatch.setenv("DS_STAGE_DELAY_S", "prefetch:0.02")
    eng = _make_engine(tmp_path)
    it = eng.prefetch(_batches(eng, 5))
    for _ in range(5):
        eng.train_batch(data_iter=it)
    eng.close()
    evs = _load_trace(tmp_path)
    starts = [e for e in evs if e["ph"] == "s"
              and e["name"] == "data/batch"]
    ends = [e for e in evs if e["ph"] == "f"
            and e["name"] == "data/batch"]
    assert len(starts) == 5 and len(ends) == 5
    # ids pair the producer side to the consumer side (the causal
    # assertion — parsed structure, not timestamps)
    assert {e["id"] for e in starts} == {e["id"] for e in ends}
    for s in starts:
        assert "data/prefetch_place" in _enclosing_spans(evs, s)
    for f in ends:
        assert "train/dispatch" in _enclosing_spans(evs, f)
    # produced on the worker thread, consumed on the caller's
    assert {e["tid"] for e in starts} != {e["tid"] for e in ends}


def test_closed_prefetcher_releases_stage_depth_sampler(tmp_path):
    """Regression: a closed prefetcher must not stay pinned by the
    engine-lifetime shared Stage record through its bound qsize — later
    stage events would sample a dead channel's depth and the source
    iterator would be retained for the rest of the run."""
    eng = _make_engine(tmp_path)
    stage = eng._stage_records["prefetch"]
    pf_eval = eng.prefetch(_batches(eng, 1), for_eval=True)
    assert stage.depth_fn is not None
    pf_eval.close()
    assert stage.depth_fn is None      # released with its owner
    pf_train = eng.prefetch(_batches(eng, 1))
    assert stage.depth_fn is not None  # next owner reinstalls
    pf_train.close()
    eng.close()


def test_eval_prefetched_batches_close_their_flows(tmp_path):
    """Regression: eval-placed batches must terminate their flows too —
    an eval loop must not grow the recorder's open-flow set by one
    entry per batch (each would flush as a synthetic terminator at
    export, eating the event budget)."""
    eng = _make_engine(tmp_path)
    it = eng.prefetch(_batches(eng, 3), for_eval=True)
    for _ in range(3):
        eng.eval_batch(data_iter=it)
    assert not eng.telemetry.tracer._open_flows, (
        "eval batches leaked open flows")
    eng.close()
    evs = _load_trace(tmp_path)
    ends = [e for e in evs if e["ph"] == "f"
            and e["name"] == "data/batch"]
    assert len(ends) == 3
    assert not any((e.get("args") or {}).get("flushed") for e in ends)
    for f in ends:
        assert "eval/dispatch" in _enclosing_spans(evs, f)


def test_ckpt_flow_links_save_to_async_write(tmp_path):
    eng = _make_engine(tmp_path / "tel")
    for b in _batches(eng, 1):
        eng.train_batch(b)
    eng.save_checkpoint(str(tmp_path / "ckpt"), tag="t1",
                        async_write=True)
    eng.close()
    evs = _load_trace(tmp_path / "tel")
    starts = [e for e in evs if e["ph"] == "s"
              and e["name"] == "checkpoint/job"]
    ends = [e for e in evs if e["ph"] == "f"
            and e["name"] == "checkpoint/job"]
    assert len(starts) == 1 and len(ends) == 1
    assert starts[0]["id"] == ends[0]["id"]
    assert "checkpoint/snapshot" in _enclosing_spans(evs, starts[0])
    assert "checkpoint/async_write" in _enclosing_spans(evs, ends[0])


def test_degraded_prefetch_keeps_span_names_and_closes_flows(
        tmp_path, monkeypatch):
    """Satellite: degradation-to-inline keeps emitting the SAME span
    names (a degraded run's trace answers the same queries) and every
    batch flow still closes — no leaks across the fault boundary."""
    monkeypatch.setenv("DS_STAGE_FAULT", "prefetch:place:1+")
    eng = _make_engine(tmp_path)
    it = eng.prefetch(_batches(eng, 4))
    for _ in range(4):
        eng.train_batch(data_iter=it)
    assert eng._stage_records["prefetch"].degraded
    eng.close()
    evs = _load_trace(tmp_path)
    places = [e for e in evs if e["ph"] == "X"
              and e["name"] == "data/prefetch_place"]
    inline = [e for e in places if (e.get("args") or {}).get("inline")]
    assert inline, "degraded path emitted no inline place spans"
    starts = {e["id"] for e in evs if e["ph"] == "s"
              and e["name"] == "data/batch"}
    ends = {e["id"] for e in evs if e["ph"] == "f"
            and e["name"] == "data/batch"}
    assert len(starts) == 4 and starts == ends
    # the degradation itself dumped a flight record
    assert glob.glob(os.path.join(str(tmp_path), "flightrec_*.json"))


def test_flow_end_adds_zero_device_syncs(tmp_path, monkeypatch):
    """The causal-linking overhead contract, on the CONSUMER path: a
    train_batch consuming a prefetched batch (which terminates the
    batch's flow inside its dispatch span) performs zero device syncs —
    flow events are host-side appends riding existing span points.
    (The producer-side flow rides the worker thread, whose in-span
    drain was always there; here the worker is drained first so the
    counter sees only the consumer.)"""
    import time as _time
    eng = _make_engine(tmp_path)
    eng.train_batch(next(_batches(eng, 1)))     # compile outside window
    it = eng.prefetch(_batches(eng, 2), depth=2)
    deadline = _time.monotonic() + 30
    while it.qsize() < 2 and _time.monotonic() < deadline:
        _time.sleep(0.01)
    assert it.qsize() == 2                      # worker fully drained

    class Counter:
        count = 0
    real_bur = jax.block_until_ready
    real_dg = jax.device_get
    real_asarray = np.asarray

    def wrap(real):
        def inner(*a, **k):
            Counter.count += 1
            return real(*a, **k)
        return inner

    def asarray(obj, *a, **k):
        if isinstance(obj, jax.Array):
            Counter.count += 1
        return real_asarray(obj, *a, **k)
    monkeypatch.setattr(jax, "block_until_ready", wrap(real_bur))
    monkeypatch.setattr(jax, "device_get", wrap(real_dg))
    monkeypatch.setattr(np, "asarray", asarray)
    for _ in range(2):
        eng.train_batch(data_iter=it)
    assert Counter.count == 0, (
        "flow-event emission added device syncs to the consume path")
    monkeypatch.undo()
    eng.close()
    evs = _load_trace(tmp_path)
    assert len([e for e in evs if e["ph"] == "f"
                and e["name"] == "data/batch"]) == 2


# ---------------------------------------------------------------------------
# flight recorder + diagnose (acceptance)
# ---------------------------------------------------------------------------

def test_sticky_fault_flightrec_diagnose_names_stage_and_error(
        tmp_path, monkeypatch, capsys):
    monkeypatch.setenv("DS_STAGE_FAULT", "prefetch:place:1+")
    eng = _make_engine(tmp_path)
    it = eng.prefetch(_batches(eng, 3))
    for _ in range(3):
        eng.train_batch(data_iter=it)
    eng.close()
    recs = glob.glob(os.path.join(str(tmp_path), "flightrec_*.json"))
    assert recs
    fr = json.loads(open(recs[0]).read())
    assert fr["version"] == 1
    st = fr["stages"]["prefetch"]
    assert st["degraded"] is True
    kinds = [e["kind"] for e in st["events"]]
    assert "failure" in kinds and "degraded" in kinds
    rep = diagnose(str(tmp_path))
    out = capsys.readouterr().out
    assert rep["degraded_stages"] == ["prefetch"]
    assert rep["first_failure_stage"] == "prefetch"
    assert "InjectedStageFault" in rep["error"]
    assert "prefetch" in out and "InjectedStageFault" in out


def test_dump_flight_record_on_demand_and_step_failure(tmp_path):
    eng = _make_engine(tmp_path)
    for b in _batches(eng, 1):
        eng.train_batch(b)
    path = eng.dump_flight_record(reason="operator request")
    assert path and os.path.isfile(path)
    doc = json.loads(open(path).read())
    assert doc["reason"] == "operator request"
    assert set(doc["stages"]) == {"prefetch", "offload_h2d",
                                  "disk_read", "disk_write",
                                  "ckpt_writer"}
    # a failing train_batch dumps once (and only once)
    with pytest.raises((ValueError, IndexError, TypeError)):
        eng.train_batch(np.float32(0.0))  # bogus batch: placement fails
    assert eng._flightrec_poison_dumped
    eng.close()


def test_stage_ring_is_bounded_and_samples_depth():
    st = Stage("s")
    st.depth_fn = lambda: 7
    for i in range(600):
        st.record_event("ok", point="p", i=i)
    assert len(st.events) == 256        # FLIGHT_RING_SIZE bound
    ev = list(st.events)[-1]
    assert ev["depth"] == 7 and ev["i"] == 599
    snap = st.flight_snapshot()
    assert snap["degraded"] is False
    assert len(snap["events"]) == 256


def test_write_flight_record_torn_safe(tmp_path):
    st = Stage("x")
    st.record_event("failure", error="boom")
    p = write_flight_record(str(tmp_path), {"x": st}, 3, "unit",
                            error=RuntimeError("orig"))
    doc = json.loads(open(p).read())
    assert doc["error"] == "RuntimeError('orig')"
    assert not glob.glob(os.path.join(str(tmp_path), "*.tmp"))


def test_supervisor_give_up_dumps_flight_record(tmp_path):
    from deepspeed_tpu.launcher.elastic import (ElasticGiveUpError,
                                                ElasticSupervisor,
                                                RestartPolicy)

    class P:
        def poll(self):
            return 1

    sup = ElasticSupervisor(
        {"localhost": [0]},
        launch_fn=lambda active, attempt: [("localhost", P())],
        policy=RestartPolicy(max_restarts=0),
        heartbeat_dir=str(tmp_path))
    with pytest.raises(ElasticGiveUpError):
        sup.run()
    p = os.path.join(str(tmp_path), "flightrec_supervisor.json")
    assert os.path.isfile(p)
    doc = json.loads(open(p).read())
    assert "ElasticGiveUpError" in doc["reason"]
    kinds = [e["kind"] for e in doc["stages"]["supervisor"]["events"]]
    assert kinds.count("launch") == 1 and "give_up" in kinds


# ---------------------------------------------------------------------------
# serving: request flow + per-request records (acceptance)
# ---------------------------------------------------------------------------

def _serve_engine(tmp_path, slots=2, **serving_extra):
    from deepspeed_tpu.inference import ServeEngine
    from deepspeed_tpu.models.gpt2 import GPT2Config, GPT2Model
    tiny = GPT2Config(vocab_size=128, n_positions=64, d_model=32,
                      n_layer=2, n_head=4)
    cfg = {"serving": {"slots": slots, "max_seq_len": 32,
                       "prefill_len": 8, **serving_extra},
           "telemetry": {"enabled": True, "output_path": str(tmp_path)}}
    return ServeEngine(GPT2Model(tiny), cfg)


def test_serve_flow_links_admit_to_decode_ticks(tmp_path, monkeypatch):
    """THE serve-side causal chain: each request's flow starts inside
    its prefill (admission) span and steps through every decode tick it
    rides — under injected per-tick delay, asserted structurally."""
    monkeypatch.setenv("DS_STAGE_DELAY_S", "serve:0.005")
    eng = _serve_engine(tmp_path)
    reqs = [eng.submit([1, 2, 3], max_new_tokens=4) for _ in range(3)]
    eng.run_until_idle()
    for r in reqs:
        assert r.result(timeout=30)
    eng.close()
    evs = _load_trace(tmp_path)
    starts = [e for e in evs if e["ph"] == "s"
              and e["name"] == "serve/request"]
    steps = [e for e in evs if e["ph"] == "t"
             and e["name"] == "serve/request"]
    ends = [e for e in evs if e["ph"] == "f"
            and e["name"] == "serve/request"]
    assert len(starts) == 3 and len(ends) == 3
    assert {e["id"] for e in starts} == {e["id"] for e in ends}
    # every decode-tick step belongs to an admitted request's flow
    assert steps and {e["id"] for e in steps} <= {e["id"]
                                                 for e in starts}
    for s in starts:
        assert "serve/prefill" in _enclosing_spans(evs, s)
    for t in steps:
        assert "serve/decode_step" in _enclosing_spans(evs, t)
    for f in ends:
        assert "serve/finish" in _enclosing_spans(evs, f)
    # root lifetimes are ASYNC (b/e) pairs — concurrent requests
    # overlap, which complete (X) slices would mis-render; pairs match
    # by (cat, id, name) and carry the rid
    roots_b = [e for e in evs if e["ph"] == "b"
               and e["name"] == "serve/request"]
    roots_e = [e for e in evs if e["ph"] == "e"
               and e["name"] == "serve/request"]
    assert {e["args"]["rid"] for e in roots_b} == {r.rid for r in reqs}
    assert {e["id"] for e in roots_b} == {e["id"] for e in roots_e}
    waits_b = [e for e in evs if e["ph"] == "b"
               and e["name"] == "serve/queue_wait"]
    waits_e = [e for e in evs if e["ph"] == "e"
               and e["name"] == "serve/queue_wait"]
    assert len(waits_b) == 3
    assert {e["id"] for e in waits_b} == {e["id"] for e in waits_e}


def test_serve_records_reconstruct_registry_histograms(tmp_path):
    """Acceptance: the per-request completion records in events.jsonl
    reconstruct TTFT and queue-wait p50/p99 matching the registry
    histograms (same raw observations, same interpolation)."""
    eng = _serve_engine(tmp_path, slots=2)
    reqs = [eng.submit([1 + i, 2, 3], max_new_tokens=3)
            for i in range(6)]
    eng.run_until_idle()
    for r in reqs:
        r.result(timeout=30)
    reg = eng.telemetry.registry
    eng.close()
    recs = [json.loads(l) for l in
            open(os.path.join(str(tmp_path), "events.jsonl"))]
    srs = [r for r in recs if r["kind"] == "serve_request"]
    assert len(srs) == 6
    for r in srs:
        assert r["error"] is None and r["finish_reason"] == "length"
        assert r["queue_wait_s"] >= 0 and r["ttft_s"] > 0
        assert r["decode_tokens"] == 2
        assert "trace_id" in r
    for name, field in (("serve_ttft_seconds", "ttft_s"),
                        ("serve_queue_wait_seconds", "queue_wait_s")):
        res = reg.histogram(name).reservoir()
        assert res is not None and res.count == 6
        vals = sorted(float(r[field]) for r in srs)
        for q in (0.50, 0.99):
            assert res.percentile(q) == pytest.approx(
                _percentile(vals, q), rel=1e-9)
    # summarize's split row reports the same reconstruction
    rep = summarize(os.path.join(str(tmp_path), "events.jsonl"))
    assert rep["serve_requests"] == 6
    assert rep["serve_ttft_p50_s"] == pytest.approx(
        reg.histogram("serve_ttft_seconds").reservoir().percentile(0.5),
        rel=1e-9)
    assert rep["serve_queue_wait_p99_s"] == pytest.approx(
        reg.histogram("serve_queue_wait_seconds").reservoir()
        .percentile(0.99), rel=1e-9)
    assert rep["serve_decode_p50_s"] is not None


def test_serve_poison_ends_traces_with_error_span_no_leaks(tmp_path):
    """Satellite: trace context survives Channel poison — every
    in-flight request's trace ends with an error span and a terminated
    flow, and the flight recorder captures the pool's last moments."""
    eng = _serve_engine(tmp_path, slots=2)
    r_ok = eng.submit([1, 2], max_new_tokens=2)
    eng.run_until_idle()
    r_ok.result(timeout=30)

    boom = RuntimeError("decode exploded")

    def bad_decode(*a, **k):
        raise boom
    reqs = [eng.submit([3, 4], max_new_tokens=4) for _ in range(2)]
    eng._decode_fn = bad_decode
    with pytest.raises(RuntimeError, match="decode exploded"):
        eng.run_until_idle()
    for r in reqs:
        with pytest.raises(RuntimeError, match="decode exploded"):
            r.result(timeout=30)
    recs = glob.glob(os.path.join(str(tmp_path), "flightrec_*.json"))
    assert recs, "poison did not dump a flight record"
    fr = json.loads(open(max(recs)).read())
    assert fr["reason"] == "serve poison"
    assert "decode exploded" in fr["error"]
    assert "poison" in [e["kind"] for e in fr["stages"]["serve"]["events"]]
    eng.close()
    evs = _load_trace(tmp_path)
    errors = [e for e in evs if e["ph"] == "X"
              and e["name"] == "serve/error"]
    assert {e["args"]["rid"] for e in errors} == {r.rid for r in reqs}
    starts = {e["id"] for e in evs if e["ph"] == "s"
              and e["name"] == "serve/request"}
    ends = {e["id"] for e in evs if e["ph"] == "f"
            and e["name"] == "serve/request"}
    assert starts == ends, "poisoned requests leaked open flows"
    # the failed requests' completion records carry the original error
    jrecs = [json.loads(l) for l in
             open(os.path.join(str(tmp_path), "events.jsonl"))]
    failed = [r for r in jrecs if r["kind"] == "serve_request"
              and r.get("error")]
    assert len(failed) == 2
    assert all("decode exploded" in r["error"] for r in failed)
    rep = summarize(os.path.join(str(tmp_path), "events.jsonl"))
    assert rep["serve_requests_failed"] == 2


# ---------------------------------------------------------------------------
# anomaly trigger (opt-in, one-shot, bounded)
# ---------------------------------------------------------------------------

def test_anomaly_ratio_config_validation():
    from deepspeed_tpu.config import DeepSpeedConfig, DeepSpeedConfigError
    cfg = DeepSpeedConfig({"train_micro_batch_size_per_gpu": 1}, 1)
    assert cfg.telemetry_config.anomaly_ratio == 0.0   # default off
    for bad in (1.0, -2, True, "3"):
        with pytest.raises(DeepSpeedConfigError):
            DeepSpeedConfig({"train_micro_batch_size_per_gpu": 1,
                             "telemetry": {"anomaly_ratio": bad}}, 1)
    ok = DeepSpeedConfig({"train_micro_batch_size_per_gpu": 1,
                          "telemetry": {"anomaly_ratio": 3.0}}, 1)
    assert ok.telemetry_config.anomaly_ratio == 3.0


def test_anomaly_trigger_one_shot_capture_and_dump(tmp_path,
                                                   monkeypatch):
    calls = []
    monkeypatch.setattr(jax.profiler, "start_trace",
                        lambda path, **k: calls.append(("start", path)))
    monkeypatch.setattr(jax.profiler, "stop_trace",
                        lambda: calls.append(("stop", None)))
    eng = _make_engine(tmp_path, anomaly_ratio=2.0)
    for avg in [0.1] * 6:
        eng._anomaly_check(avg)           # healthy baseline
    assert not eng._anomaly_fired and not calls
    eng._anomaly_check(0.5)               # 5x the trailing median
    assert eng._anomaly_fired
    assert [c[0] for c in calls] == ["start"]
    assert "anomaly_profile" in calls[0][1]
    recs = glob.glob(os.path.join(str(tmp_path), "flightrec_*.json"))
    assert recs
    assert "anomaly" in json.loads(open(recs[0]).read())["reason"]
    # bounded: the capture closes at the NEXT sync ...
    eng._anomaly_check(0.5)
    assert [c[0] for c in calls] == ["start", "stop"]
    # ... and one-shot: a later anomalous interval must not re-fire
    eng._anomaly_check(5.0)
    assert [c[0] for c in calls] == ["start", "stop"]
    eng.close()
    assert [c[0] for c in calls] == ["start", "stop"]


def test_anomaly_straggler_arm_capture_survives_its_own_sync(
        tmp_path, monkeypatch):
    """Regression: the straggler arm fires AFTER the sync's anomaly
    check (which is also where a previous capture closes) — its capture
    must stay open until the NEXT sync, not be stopped microseconds
    after it starts by the same sync."""
    calls = []
    monkeypatch.setattr(jax.profiler, "start_trace",
                        lambda path, **k: calls.append("start"))
    monkeypatch.setattr(jax.profiler, "stop_trace",
                        lambda: calls.append("stop"))
    eng = _make_engine(tmp_path, anomaly_ratio=2.0)
    # one telemetry sync: check runs first, then the straggler arm
    # fires (the ordering _telemetry_sync now guarantees)
    eng._anomaly_check(0.1)
    eng._fire_anomaly("this host flagged as straggler (hostX/0)")
    assert calls == ["start"]          # still capturing after the sync
    eng._anomaly_check(0.1)            # next sync closes the window
    assert calls == ["start", "stop"]
    eng.close()
    assert calls == ["start", "stop"]


def test_anomaly_capture_defers_to_pending_profiler_window(
        tmp_path, monkeypatch):
    """Regression: with a user-configured profiler window still PENDING
    (start_step not reached), the anomaly trigger must not open its own
    capture — the window's later start_trace would raise 'Profile has
    already been started' and kill train_batch.  The flight dump still
    happens."""
    calls = []
    monkeypatch.setattr(jax.profiler, "start_trace",
                        lambda path, **k: calls.append(path))
    cfg = base_config(micro_bs=2, grad_acc=1, stage=0)
    cfg["telemetry"] = {"enabled": True, "output_path": str(tmp_path),
                        "anomaly_ratio": 2.0}
    cfg["profiler"] = {"enabled": True, "start_step": 100,
                       "num_steps": 3,
                       "output_path": str(tmp_path / "xplane")}
    eng, *_ = deepspeed_tpu.initialize(
        model=SimpleModel(hidden_dim=HIDDEN), config=cfg)
    for avg in [0.1] * 6:
        eng._anomaly_check(avg)
    eng._anomaly_check(0.9)            # anomalous: fires the one-shot
    assert eng._anomaly_fired
    assert not eng._anomaly_profiling and not calls
    assert glob.glob(os.path.join(str(tmp_path), "flightrec_*.json"))
    eng.close()


def test_serve_close_failed_records_match_counter(tmp_path):
    """Regression: requests still queued at close() get failed records
    AND the serve_requests_failed_total counter — summarize's
    record-derived count and the scraped counter must agree."""
    eng = _serve_engine(tmp_path, slots=2)
    reqs = [eng.submit([1, 2], max_new_tokens=2) for _ in range(3)]
    reg = eng.telemetry.registry
    eng.close()                        # never stepped: all still queued
    for r in reqs:
        with pytest.raises(RuntimeError, match="ServeEngine closed"):
            r.result(timeout=5)
    assert reg.counter("serve_requests_failed_total").value() == 3
    recs = [json.loads(l) for l in
            open(os.path.join(str(tmp_path), "events.jsonl"))]
    failed = [r for r in recs if r["kind"] == "serve_request"
              and r.get("error")]
    assert len(failed) == 3


def test_anomaly_trigger_off_by_default(tmp_path, monkeypatch):
    calls = []
    monkeypatch.setattr(jax.profiler, "start_trace",
                        lambda path, **k: calls.append(path))
    eng = _make_engine(tmp_path)           # anomaly_ratio defaults 0
    for avg in [0.1] * 6 + [9.9]:
        eng._anomaly_check(avg)
    assert not eng._anomaly_fired and not calls
    eng.close()
