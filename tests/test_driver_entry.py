"""Guard the driver-facing artifacts: bench.py must print one JSON line,
__graft_entry__.entry() must jit, dryrun_multichip must run on a small
virtual mesh.  A regression in any of these costs a whole round."""
import json
import os
import subprocess
import sys

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _run(args, timeout, extra_env=None):
    env = dict(os.environ)
    env["PYTHONPATH"] = REPO + os.pathsep + env.get("PYTHONPATH", "")
    # force the CPU backend in the child (the pinned platform of THIS
    # process does not inherit)
    env["JAX_PLATFORMS"] = "cpu"
    if extra_env:
        env.update(extra_env)
    return subprocess.run(args, cwd=REPO, env=env, timeout=timeout,
                          capture_output=True, text=True)


@pytest.mark.slow
def test_bench_cpu_smoke_emits_one_json_line():
    # the env var alone cannot pin the platform (sitecustomize forces the
    # TPU backend); pin via jax.config before running the script
    runner = ("import jax; jax.config.update('jax_platforms','cpu'); "
              "import runpy, sys; sys.argv=['bench.py']; "
              "runpy.run_path('bench.py', run_name='__main__')")
    proc = _run([sys.executable, "-c", runner], timeout=300)
    assert proc.returncode == 0, proc.stderr[-2000:]
    lines = [l for l in proc.stdout.splitlines() if l.startswith("{")]
    assert len(lines) == 1, proc.stdout
    rec = json.loads(lines[0])
    assert {"metric", "value", "unit", "vs_baseline"} <= set(rec)
    assert rec["value"] > 0


def test_graft_entry_fn_jits():
    sys.path.insert(0, REPO)
    import __graft_entry__ as ge
    import jax

    fn, args = ge.entry()
    loss = jax.jit(fn)(*args)
    import numpy as np
    assert np.isfinite(float(np.asarray(loss)))


@pytest.mark.slow
def test_dryrun_multichip_four_devices():
    proc = _run([sys.executable, "__graft_entry__.py", "4"], timeout=480,
                extra_env={"XLA_FLAGS":
                           "--xla_force_host_platform_device_count=4"})
    assert proc.returncode == 0, proc.stderr[-2000:]
    oks = [l for l in proc.stdout.splitlines() if l.endswith("OK")]
    assert len(oks) >= 3, proc.stdout  # zero3+tp, pp, pp+zero3, offload
