"""Balanced-partition algorithms (mirrors reference tests/unit/test_partition.py
coverage of partition_uniform / partition_balanced)."""
import numpy as np
import pytest

from deepspeed_tpu.parallel import partition_uniform, partition_balanced


def _max_part(weights, parts):
    return max(sum(weights[parts[p]:parts[p + 1]])
               for p in range(len(parts) - 1))


def test_uniform_exact():
    assert partition_uniform(8, 4) == [0, 2, 4, 6, 8]


def test_uniform_remainder_front_loaded():
    parts = partition_uniform(10, 4)
    sizes = [parts[i + 1] - parts[i] for i in range(4)]
    assert sum(sizes) == 10
    assert max(sizes) - min(sizes) <= 1


def test_balanced_uniform_weights():
    parts = partition_balanced([1.0] * 8, 4)
    sizes = [parts[i + 1] - parts[i] for i in range(4)]
    assert sizes == [2, 2, 2, 2]


def test_balanced_skewed():
    w = [10.0, 1.0, 1.0, 1.0, 1.0, 1.0, 1.0, 1.0]
    parts = partition_balanced(w, 2)
    # optimal: [10] | rest (max=10) — anything placing 10 with others is worse
    assert _max_part(w, parts) == 10.0


def test_balanced_monotone_boundaries():
    rng = np.random.default_rng(0)
    for _ in range(20):
        n = int(rng.integers(1, 40))
        k = int(rng.integers(1, 10))
        w = rng.random(n).tolist()
        parts = partition_balanced(w, k)
        assert len(parts) == k + 1
        assert parts[0] == 0 and parts[-1] == n
        assert all(parts[i] <= parts[i + 1] for i in range(k))


def test_balanced_near_optimal():
    rng = np.random.default_rng(1)
    w = rng.random(32).tolist()
    parts = partition_balanced(w, 4)
    # bottleneck within 1.05x of the trivial lower bound would be too strict;
    # require within max(weight) + mean (greedy bound)
    lower = max(max(w), sum(w) / 4)
    assert _max_part(w, parts) <= lower + max(w)


def test_more_parts_than_items():
    parts = partition_balanced([1.0, 1.0], 4)
    assert parts[0] == 0 and parts[-1] == 2
    assert len(parts) == 5
