"""Multi-tenant LoRA serving (docs/serving.md "multi-tenant serving"):

* adapter pool unit tests — refcount/LRU-eviction/double-free, the
  park-on-dry (None, side-effect-free) contract, registry capacity and
  shape validation, deterministic synthesis,
* `DS_STAGE_FAULT=adapter_fetch:fetch:...` chaos — transient fetch
  faults retry invisibly, a sticky fault degrades the stage to the
  synchronous copy and the run completes BITWISE-identical,
* engine parity bars — heterogeneous single-tenant streams == the
  dense-merged (`W + scale·BA`) engine, the zero-tenant arm ==
  lora-off token for token, int8-base + fp16-adapter composition,
  dp2×tp2 == single device,
* the zero-recompile contract over waves mixing >= 8 tenants
  (`recompiles_total{program=decode_step}` == 0, one cache entry),
* park-on-adapter-dry admission ordering,
* cross-tenant prefix-cache isolation — tenant A never hits tenant
  B's pages; the no-lora namespace stays the pre-change digest chain,
* fleet tenant affinity (bounded by ADAPTER_AFFINITY_SLACK, never
  starving JSQ) + the real-subprocess replica-death reroute e2e,
* config validation and the serve_adapter_* telemetry -> summarize
  "adapters" row.
"""
import os
import time

import numpy as np
import jax
import pytest

from deepspeed_tpu.config import DeepSpeedConfigError
from deepspeed_tpu.config.config import DeepSpeedServingConfig
from deepspeed_tpu.inference import ServeEngine
from deepspeed_tpu.inference.adapters import (AdapterPool,
                                              AdapterRegistry,
                                              adapter_param_shapes,
                                              merge_adapter,
                                              synth_adapter,
                                              zero_adapter)
from deepspeed_tpu.inference.scheduler import PagePool, PrefixCache
from deepspeed_tpu.models.gpt2 import GPT2Config, GPT2Model
from deepspeed_tpu.runtime.stages import Stage, reset_fault_injection

TINY = GPT2Config(vocab_size=128, n_positions=64, d_model=32, n_layer=2,
                  n_head=4, remat=None, attn_impl="dense")
TINY_FLASH = GPT2Config(**{**TINY.__dict__, "attn_impl": "flash"})

_CHAOS_ENVS = ("DS_STAGE_FAULT", "DS_STAGE_DELAY_S")


@pytest.fixture(autouse=True)
def _clean_faults(monkeypatch):
    for env in _CHAOS_ENVS:
        monkeypatch.delenv(env, raising=False)
    reset_fault_injection()
    yield
    reset_fault_injection()


def _tokens(n, vocab=128, seed=0):
    return np.random.default_rng(seed).integers(
        0, vocab, (n,)).astype(np.int32)


def _lora_cfg(slots=4, hbm_slots=3, rank=4, alpha=8.0,
              targets=("qkv_w", "out_w", "fc_w", "proj_w"),
              telemetry_path=None, **serving_extra):
    cfg = {"serving": {"slots": slots, "max_seq_len": 32,
                       "prefill_len": 24, "page_len": 8, "pages": 40,
                       "lora": {"rank": rank, "alpha": alpha,
                                "hbm_adapter_slots": hbm_slots,
                                "max_adapters": 32,
                                "targets": list(targets)},
                       **serving_extra}}
    if telemetry_path is not None:
        cfg["telemetry"] = {"enabled": True,
                            "output_path": str(telemetry_path)}
    return cfg


def _base_cfg(slots=4, **serving_extra):
    return {"serving": {"slots": slots, "max_seq_len": 32,
                        "prefill_len": 24, "page_len": 8, "pages": 40,
                        **serving_extra}}


_MODEL = None


def _model_params():
    """One shared tiny model across the engine tests (init is the
    slow part; params are read-only)."""
    global _MODEL
    if _MODEL is None:
        model = GPT2Model(TINY)
        _MODEL = (model, model.init(jax.random.PRNGKey(0)))
    return _MODEL


# ---------------------------------------------------------------------------
# adapter pool: refcount / LRU / park-on-dry / double-free
# ---------------------------------------------------------------------------


def _small_pool(slots=2, max_adapters=16):
    shapes = adapter_param_shapes(2, 8, 2, ("qkv_w",))
    reg = AdapterRegistry(max_adapters, shapes)
    uploads = []
    pool = AdapterPool(slots, reg,
                       lambda slot, w: uploads.append(slot))
    return pool, reg, uploads


def test_pool_refcount_hit_fault_eviction_lru():
    pool, _, uploads = _small_pool(slots=2)
    # cold acquire: fault + device upload into slot 1
    assert pool.acquire(7) == 1
    assert (pool.faults, pool.hits, uploads) == (1, 0, [1])
    # second acquire of a resident adapter: hit, refcount 2, no upload
    assert pool.acquire(7) == 1
    assert (pool.faults, pool.hits, len(uploads)) == (1, 1, 1)
    assert pool.refs(7) == 2
    # releases drop to 0: adapter stays RESIDENT (cold, evictable)
    pool.release(7)
    pool.release(7)
    assert pool.refs(7) == 0 and pool.resident() == 1
    # the next acquire is a free hit
    assert pool.acquire(7) == 1 and pool.hits == 2
    pool.release(7)
    # fill the other slot, then a third tenant must LRU-evict the
    # OLDEST cold resident (7 went cold before 8)
    assert pool.acquire(8) == 2
    pool.release(8)
    assert pool.acquire(9) == 1           # evicted 7, reused its slot
    assert pool.evictions == 1
    assert pool.slot_of(7) is None and pool.slot_of(8) == 2
    assert pool.hot_ids() == [8, 9]


def test_pool_slot0_zero_adapter_never_refcounted():
    pool, _, uploads = _small_pool()
    assert pool.acquire(0) == 0
    pool.release(0)
    assert (pool.resident(), pool.hits, pool.faults) == (0, 0, 0)
    assert not uploads


def test_pool_park_on_dry_is_side_effect_free():
    pool, _, uploads = _small_pool(slots=2)
    assert pool.acquire(1) == 1 and pool.acquire(2) == 2
    before = (list(pool.free), dict(pool._slot_of), pool.hits,
              pool.faults, pool.evictions, len(uploads))
    # every slot pinned: acquire returns None and changes NOTHING
    assert pool.acquire(3) is None
    after = (list(pool.free), dict(pool._slot_of), pool.hits,
             pool.faults, pool.evictions, len(uploads))
    assert before == after
    # a release turns the dry pool back into an evictable one
    pool.release(1)
    assert pool.acquire(3) is not None
    assert pool.evictions == 1


def test_pool_double_free_asserts():
    pool, _, _ = _small_pool()
    pool.acquire(5)
    pool.release(5)
    with pytest.raises(AssertionError, match="below zero"):
        pool.release(5)
    with pytest.raises(AssertionError, match="not resident"):
        pool.release(6)


def test_registry_capacity_shapes_and_synthesis():
    shapes = adapter_param_shapes(2, 8, 2, ("qkv_w", "fc_w"))
    assert shapes["qkv_w"] == ((2, 8, 2), (2, 2, 3, 8))
    assert shapes["fc_w"] == ((2, 8, 2), (2, 2, 32))
    with pytest.raises(ValueError, match="unknown lora target"):
        adapter_param_shapes(2, 8, 2, ("qkv_w", "nope"))
    reg = AdapterRegistry(2, shapes)
    reg.get(1)
    reg.get(2)
    with pytest.raises(RuntimeError, match="registry full"):
        reg.get(3)
    # re-touching a known adapter is fine at capacity
    assert 1 in reg and len(reg) == 2
    with pytest.raises(ValueError, match="shapes"):
        reg.register(1, {"qkv_w": (np.zeros((1, 8, 2), np.float32),
                                   np.zeros((2, 2, 3, 8), np.float32))})
    with pytest.raises(ValueError, match="positive"):
        synth_adapter(0, shapes)
    # deterministic synthesis: same id -> byte-identical weights
    w1, w2 = synth_adapter(9, shapes), synth_adapter(9, shapes)
    for t in shapes:
        assert np.array_equal(w1[t][0], w2[t][0])
        assert np.array_equal(w1[t][1], w2[t][1])
    z = zero_adapter(shapes)
    assert all(not z[t][0].any() and not z[t][1].any() for t in shapes)


def test_pool_transient_fetch_fault_retries(monkeypatch):
    """One injected fetch fault is absorbed by the stage budget: the
    acquire succeeds, nothing degrades, the pool bookkeeping is the
    no-fault bookkeeping."""
    monkeypatch.setenv("DS_STAGE_FAULT", "adapter_fetch:fetch:1")
    reset_fault_injection()
    pool, _, uploads = _small_pool()
    assert pool.acquire(4) == 1
    assert not pool.stage.degraded
    assert pool.stage.failures == 1
    assert pool.resident() == 1 and pool.faults == 1


def test_pool_sticky_fetch_fault_degrades_and_recovers(monkeypatch):
    """A sticky fetch fault exhausts the budget: the stage degrades to
    the synchronous copy (ONE loud fallback) and every subsequent cold
    fetch still lands — latency-only, the adapter bytes are
    identical."""
    monkeypatch.setenv("DS_STAGE_FAULT", "adapter_fetch:fetch:1+")
    reset_fault_injection()
    shapes = adapter_param_shapes(2, 8, 2, ("qkv_w",))
    reg = AdapterRegistry(16, shapes)
    uploads = []
    pool = AdapterPool(2, reg, lambda slot, w: uploads.append((slot, w)),
                       stage=Stage("adapter_fetch", max_failures=2))
    assert pool.acquire(4) == 1
    assert pool.stage.degraded
    # degraded = injection plane bypassed: the next cold tenant works
    assert pool.acquire(5) == 2
    assert [s for s, _ in uploads] == [1, 2]
    # the degraded copy carried the REAL registry weights
    want = reg.get(4)["qkv_w"][0]
    assert np.array_equal(uploads[0][1]["qkv_w"][0], want)


def test_pool_nontransient_fetch_error_releases_slot():
    """A non-transient fetch failure (poison class) must not leak the
    slot it grabbed."""
    shapes = adapter_param_shapes(2, 8, 2, ("qkv_w",))
    reg = AdapterRegistry(16, shapes)

    def boom(slot, w):
        raise RuntimeError("device copy failed")

    pool = AdapterPool(2, reg, boom)
    with pytest.raises(RuntimeError, match="device copy failed"):
        pool.acquire(3)
    assert sorted(pool.free) == [1, 2]
    assert pool.resident() == 0 and pool.slot_of(3) is None


# ---------------------------------------------------------------------------
# engine parity bars
# ---------------------------------------------------------------------------


def _run_streams(cfg, prompts, tenants, gen=6, params=None, model=None):
    if model is None:
        model, shared = _model_params()
        params = shared if params is None else params
    eng = ServeEngine(model, cfg, params=params)
    rs = [eng.submit(p, max_new_tokens=gen, adapter_id=t)
          for p, t in zip(prompts, tenants)]
    eng.run_until_idle()
    assert all(r.error is None for r in rs), \
        [repr(r.error) for r in rs if r.error]
    toks = [list(r.tokens) for r in rs]
    stats = {"decode_programs": eng._decode_fn._cache_size(),
             "prefill_programs": eng._prefill_fn._cache_size(),
             "pool": eng.adapters if eng.lora else None,
             "engine": eng}
    eng.close()
    return toks, stats


def test_heterogeneous_tenants_match_dense_merged():
    """THE parity bar: each tenant's stream out of one heterogeneous
    batch (tenants resolved per-slot through the traced adapter table)
    equals a dense-merged ``W + scale·BA`` engine serving that tenant
    alone — and the whole mix rode ONE compiled decode program."""
    model, params = _model_params()
    prompts = [list(_tokens(n, seed=10 + i))
               for i, n in enumerate([5, 9, 13, 7, 11, 6])]
    tenants = [0, 1, 2, 3, 1, 4]
    toks, stats = _run_streams(_lora_cfg(), prompts, tenants)
    assert stats["decode_programs"] == 1
    assert stats["prefill_programs"] == 1
    eng_scale = 8.0 / 4  # alpha / rank of _lora_cfg
    shapes = adapter_param_shapes(
        TINY.n_layer, TINY.d_model, 4,
        ("qkv_w", "out_w", "fc_w", "proj_w"))
    for tid in (0, 1, 4):
        mparams = params if tid == 0 else merge_adapter(
            params, synth_adapter(tid, shapes), eng_scale)
        meng = ServeEngine(model, _base_cfg(), params=mparams)
        refs = [meng.submit(p, max_new_tokens=6)
                for p, t in zip(prompts, tenants) if t == tid]
        meng.run_until_idle()
        got = [s for s, t in zip(toks, tenants) if t == tid]
        assert [list(r.tokens) for r in refs] == got, tid
        meng.close()


def test_zero_tenant_arm_matches_lora_off():
    """lora ON + every request tenant-0 (the all-zero slot-0 adapter)
    emits the SAME streams as the lora-off engine — the no-tenant arm
    computes a mathematically-zero delta through the shared program."""
    prompts = [list(_tokens(n, seed=20 + i))
               for i, n in enumerate([5, 9, 7])]
    base, _ = _run_streams(_base_cfg(), prompts, [0, 0, 0])
    zero, _ = _run_streams(_lora_cfg(), prompts, [0, 0, 0])
    assert zero == base


def test_lora_off_rejects_adapter_ids():
    model, params = _model_params()
    eng = ServeEngine(model, _base_cfg(), params=params)
    with pytest.raises(ValueError, match="lora"):
        eng.submit(list(_tokens(5)), max_new_tokens=2, adapter_id=3)
    with pytest.raises(ValueError, match="adapter"):
        eng.submit(list(_tokens(5)), max_new_tokens=2, adapter_id=-1)
    eng.close()
    leng = ServeEngine(model, _lora_cfg(), params=params)
    with pytest.raises(ValueError, match="adapter"):
        leng.submit(list(_tokens(5)), max_new_tokens=2, adapter_id=-2)
    leng.close()


def test_int8_base_fp16_adapter_composition():
    """Quantized base weights + fp adapters compose: the tenant-0 arm
    stays bitwise the int8-no-lora engine, a real tenant's delta
    lands, and the mix still rides one decode program."""
    quant = {"weights": "int8", "kv": "int8"}
    prompts = [list(_tokens(n, seed=30 + i))
               for i, n in enumerate([5, 9, 7, 6])]
    base, _ = _run_streams(_base_cfg(quantization=quant), prompts,
                           [0] * 4)
    mixed, stats = _run_streams(_lora_cfg(quantization=quant), prompts,
                                [0, 3, 0, 3])
    assert stats["decode_programs"] == 1
    assert [mixed[0], mixed[2]] == [base[0], base[2]]
    # the adapter really applied: at a large alpha the delta is big
    # enough to flip greedy argmaxes on the tiny model
    solo, _ = _run_streams(_lora_cfg(quantization=quant, alpha=512.0),
                           prompts, [3, 3, 3, 3])
    assert solo != base


def test_lora_dp2_tp2_matches_single_device():
    """The sharding story: adapter pools ride the same Megatron splits
    as their base matmuls — dp2×tp2 tenant streams == single device."""
    from deepspeed_tpu.parallel import build_mesh
    model = GPT2Model(TINY_FLASH)
    params = model.init(jax.random.PRNGKey(0))
    prompts = [list(_tokens(5, seed=40 + i)) for i in range(4)]
    tenants = [0, 1, 2, 1]

    def run(mesh):
        eng = ServeEngine(model, _lora_cfg(), mesh=mesh, params=params)
        rs = [eng.submit(p, max_new_tokens=6, adapter_id=t)
              for p, t in zip(prompts, tenants)]
        eng.run_until_idle()
        assert all(r.error is None for r in rs)
        toks = [r.tokens for r in rs]
        eng.close()
        return toks

    base = run(None)
    sharded = run(build_mesh(dp=2, tp=2, devices=jax.devices()[:4]))
    assert base == sharded


# ---------------------------------------------------------------------------
# zero-recompile + park-on-dry + chaos through the engine
# ---------------------------------------------------------------------------


def test_zero_recompiles_across_eight_tenant_waves(tmp_path):
    """Waves mixing >= 8 distinct tenants (cold faults, hits, and
    evictions included) never grow the compiled-program caches:
    ``recompiles_total{program=decode_step}`` stays 0."""
    model, params = _model_params()
    eng = ServeEngine(model, _lora_cfg(
        hbm_slots=3, telemetry_path=tmp_path), params=params)
    tenants = [1, 2, 3, 4, 5, 6, 7, 8, 3, 1, 0, 5]
    for wave in range(3):
        rs = [eng.submit(list(_tokens(5 + (i % 3), seed=50 + i)),
                         max_new_tokens=4, adapter_id=t)
              for i, t in enumerate(tenants)]
        eng.run_until_idle()
        assert all(r.error is None for r in rs)
    assert eng._decode_fn._cache_size() == 1
    assert eng._prefill_fn._cache_size() == 1
    reg = eng.telemetry.registry
    assert reg.counter("recompiles_total").value(
        program="decode_step") == 0
    assert reg.counter("recompiles_total").value(program="prefill") == 0
    assert eng.adapters.evictions > 0     # the waves churned the pool
    eng.close()


def test_park_on_adapter_dry_admits_in_order():
    """Every HBM slot pinned by long generations: later requests PARK
    (no error, no slot held) and admit oldest-first as pins release —
    the page-pool backpressure contract applied to adapters."""
    model, params = _model_params()
    eng = ServeEngine(model, _lora_cfg(hbm_slots=2, slots=6),
                      params=params)
    hold = [eng.submit(list(_tokens(5, seed=60 + i)),
                       max_new_tokens=16, adapter_id=i + 1)
            for i in range(2)]
    parked = [eng.submit(list(_tokens(5, seed=70 + i)),
                         max_new_tokens=3, adapter_id=8 + i)
              for i in range(2)]
    eng.run_until_idle()
    for r in hold + parked:
        assert r.error is None and len(r.tokens) > 0
    # FIFO under backpressure: the first parked tenant started first
    assert parked[0].token_times[0] <= parked[1].token_times[0]
    assert eng.adapters.evictions >= 1
    eng.close()


def test_engine_adapter_fetch_chaos_streams_bitwise(monkeypatch):
    """Injected adapter-fetch faults (transient AND sticky-degraded)
    change latency, never tokens: the chaos streams equal the
    no-chaos streams token for token."""
    prompts = [list(_tokens(n, seed=80 + i))
               for i, n in enumerate([5, 9, 7, 6])]
    tenants = [1, 2, 1, 3]
    clean, _ = _run_streams(_lora_cfg(), prompts, tenants)

    monkeypatch.setenv("DS_STAGE_FAULT", "adapter_fetch:fetch:2")
    reset_fault_injection()
    transient, tstats = _run_streams(_lora_cfg(), prompts, tenants)
    assert transient == clean

    monkeypatch.setenv("DS_STAGE_FAULT", "adapter_fetch:fetch:1+")
    reset_fault_injection()
    model, params = _model_params()
    eng = ServeEngine(model, _lora_cfg(), params=params)
    rs = [eng.submit(p, max_new_tokens=6, adapter_id=t)
          for p, t in zip(prompts, tenants)]
    eng.run_until_idle()
    assert all(r.error is None for r in rs)
    assert [list(r.tokens) for r in rs] == clean
    assert eng.adapter_stage.degraded   # budget burned, copy degraded
    eng.close()


def test_adapter_telemetry_flows_to_summarize(tmp_path, capsys):
    from deepspeed_tpu.telemetry.cli import summarize
    model, params = _model_params()
    eng = ServeEngine(model, _lora_cfg(
        hbm_slots=2, telemetry_path=tmp_path,
        flush_interval_ticks=2), params=params)
    for i, t in enumerate([1, 2, 3, 1]):
        eng.submit(list(_tokens(6, seed=90 + i)), max_new_tokens=4,
                   adapter_id=t)
    eng.run_until_idle()
    pool = eng.adapters
    want = (pool.resident(), pool.hits, pool.faults, pool.evictions)
    eng.close()
    rep = summarize(os.path.join(str(tmp_path), "events.jsonl"))
    assert rep["serve_adapters_resident"] == want[0]
    assert rep["serve_adapter_hits_total"] == want[1]
    assert rep["serve_adapter_faults_total"] == want[2]
    assert rep["serve_adapter_evictions_total"] == want[3]
    assert rep["serve_adapter_bytes"] > 0
    out = capsys.readouterr().out
    assert "adapters" in out and "faults" in out


# ---------------------------------------------------------------------------
# cross-tenant prefix-cache isolation
# ---------------------------------------------------------------------------


def test_prefix_cache_namespaces_isolate_tenants():
    """The leakage regression, at the cache: the same prompt inserted
    under tenant A's namespace never matches under tenant B's — and
    the default namespace is the pre-change digest chain (a no-lora
    engine's hits are bitwise what they were)."""
    pool = PagePool(pages=32)
    cache = PrefixCache(4, pool)
    prompt = list(range(12))           # 2 full pages + a 3-token tail
    pages = pool.alloc(3)
    cache.insert(prompt, pages, "adapter:1")
    shared, got, _cow = cache.match(prompt, "adapter:2")
    assert (shared, got) == (0, [])
    shared, got, _cow = cache.match(prompt)     # no-lora namespace
    assert (shared, got) == (0, [])
    shared, got, cow = cache.match(prompt, "adapter:1")
    assert (shared, got, cow) == (11, pages, True)
    cache.release(got)
    # default-namespace insert/match round-trips exactly as before
    pages2 = pool.alloc(3)
    cache.insert(prompt, pages2)
    shared, got, _cow = cache.match(prompt)
    assert (shared, got) == (11, pages2)
    cache.release(got)
    # and the explicit "" spelling is the same namespace
    shared2, got2, _cow = cache.match(prompt, "")
    assert (shared2, got2) == (shared, pages2)
    cache.release(got2)


def test_engine_prefix_never_crosses_tenants():
    """Engine-level: tenant B submitting tenant A's exact prompt gets
    ZERO shared prefix pages; tenant A's own repeat still hits."""
    model, params = _model_params()
    eng = ServeEngine(model, _lora_cfg(slots=2), params=params)
    prompt = list(_tokens(16, seed=7))
    a1 = eng.submit(prompt, max_new_tokens=2, adapter_id=1)
    eng.run_until_idle()
    b = eng.submit(prompt, max_new_tokens=2, adapter_id=2)
    eng.run_until_idle()
    a2 = eng.submit(prompt, max_new_tokens=2, adapter_id=1)
    eng.run_until_idle()
    assert a1.shared_len == 0
    assert b.shared_len == 0              # the leakage bar
    assert a2.shared_len > 0              # same tenant still reuses
    # base-tenant reuse is its own namespace too
    z1 = eng.submit(prompt, max_new_tokens=2)
    eng.run_until_idle()
    z2 = eng.submit(prompt, max_new_tokens=2)
    eng.run_until_idle()
    assert z1.shared_len == 0 and z2.shared_len > 0
    eng.close()


# ---------------------------------------------------------------------------
# fleet: tenant affinity + replica-death reroute
# ---------------------------------------------------------------------------


def test_config_validation():
    cfg = DeepSpeedServingConfig({"serving": {}})
    assert cfg.lora["rank"] == 0
    on = DeepSpeedServingConfig({"serving": {
        "page_len": 8, "lora": {"rank": 4}}})
    assert on.lora["alpha"] == 16.0
    assert on.lora["hbm_adapter_slots"] == 8
    assert on.lora["targets"] == ("qkv_w", "out_w")
    with pytest.raises(DeepSpeedConfigError, match="page_len"):
        DeepSpeedServingConfig({"serving": {"lora": {"rank": 4}}})
    with pytest.raises(DeepSpeedConfigError):
        DeepSpeedServingConfig({"serving": {
            "page_len": 8, "lora": {"rank": -1}}})
    with pytest.raises(DeepSpeedConfigError):
        DeepSpeedServingConfig({"serving": {
            "page_len": 8, "lora": {"rank": 4, "targets": ["nope"]}}})
    with pytest.raises(DeepSpeedConfigError):
        DeepSpeedServingConfig({"serving": {
            "page_len": 8, "lora": {"rank": 4, "bogus": 1}}})


def test_fleet_affinity_bounded_by_slack(tmp_path):
    """Tenant affinity picks the replica advertising the adapter hot —
    but only within ADAPTER_AFFINITY_SLACK of the JSQ minimum, so a
    hot tenant can never starve the queue balance."""
    from deepspeed_tpu.inference.fleet import FleetRouter
    from deepspeed_tpu.telemetry.heartbeat import HeartbeatWriter
    from test_fleet import Fleet

    fl = Fleet(tmp_path, {"replicas": 2, "max_replicas": 2}).start()
    try:
        router = fl.router
        assert FleetRouter.ADAPTER_AFFINITY_SLACK == 2
        # replica 1 advertises adapter 7 resident: affinity overrides
        # the lowest-id JSQ tie-break
        w1 = HeartbeatWriter(router.fleet_dir, process_index=1)
        w1.beat(1, extra={"adapters_hot": [7]})
        router._last_beats_read = 0.0
        router.poll(0.01)
        assert router._pick_replica(adapter_id=7).id == 1
        assert router._pick_replica().id == 0          # plain JSQ tie
        assert router._pick_replica(adapter_id=9).id == 0  # nobody hot
        # pile load beyond the slack onto the hot replica: JSQ wins
        w1.beat(2, extra={"adapters_hot": [7],
                          "serve_queue_depth": 3,
                          "serve_active_slots": 0})
        router._last_beats_read = 0.0
        router.poll(0.01)
        assert router._pick_replica(adapter_id=7).id == 0
        # ...and within the slack, affinity still wins
        w1.beat(3, extra={"adapters_hot": [7],
                          "serve_queue_depth": 2,
                          "serve_active_slots": 0})
        router._last_beats_read = 0.0
        router.poll(0.01)
        assert router._pick_replica(adapter_id=7).id == 1
    finally:
        fl.router.close()


def _lora_fleet_config(replicas, **fleet_over):
    return {
        "serving": {"slots": 4, "max_seq_len": 64, "prefill_len": 8,
                    "queue_capacity": 256, "flush_interval_ticks": 5,
                    "page_len": 8, "pages": 64,
                    "lora": {"rank": 4, "alpha": 8.0,
                             "hbm_adapter_slots": 4,
                             "max_adapters": 32}},
        "fleet": {"replicas": replicas, "min_replicas": 1,
                  "max_replicas": max(replicas, 2),
                  "slo_p99_s": 30.0, "scale_up_window_s": 5.0,
                  "scale_down_window_s": 600.0,
                  "spawn_timeout_s": 120.0, "backoff_base_s": 0.2,
                  "heartbeat_timeout_s": 60.0, **fleet_over},
        "fleet_model": {"vocab_size": 128, "n_positions": 64,
                        "d_model": 32, "n_layer": 2, "n_head": 4,
                        "attn_impl": "dense", "seed": 0},
    }


def test_e2e_lora_fleet_replica_death_reroutes(tmp_path, monkeypatch):
    """Real subprocess fleet, tenants spread across replicas: killing
    one replica re-routes its queued tenant requests to a survivor
    that synthesizes the SAME adapter weights locally (no adapter
    bytes on the wire) — zero queued-but-unstarted requests lost,
    survivors' streams intact, and the survivor's heartbeat ends up
    advertising the re-routed tenants hot."""
    from deepspeed_tpu.inference.fleet import (FleetRouter,
                                               ReplicaFailure)
    monkeypatch.setenv("DS_STAGE_DELAY_S", "serve:0.05")
    reset_fault_injection()
    cfg = _lora_fleet_config(2, slo_p99_s=1e9)
    d = str(tmp_path / "fleet")
    router = FleetRouter(cfg, fleet_dir=d)
    rng = np.random.default_rng(3)
    try:
        router.start()
        initial = sorted(router.replicas)
        reqs = [router.submit(
            [int(t) for t in rng.integers(0, 128, (5,))],
            max_new_tokens=8, adapter_id=1 + (i % 3))
            for i in range(16)]
        deadline = time.monotonic() + 60
        while time.monotonic() < deadline:
            router.poll(0.02)
            started_by = {rid: any(r.started and r.replica == rid
                                   for r in reqs)
                          for rid in initial}
            if all(started_by.values()):
                break
        assert all(started_by.values()), "replicas never streamed"
        victim = max(router.replicas.values(),
                     key=lambda r: len(r.outstanding)).id
        router.kill_replica(victim)
        router.run_until_idle(max_s=120)
        failed = [r for r in reqs if r.error is not None]
        assert all(r.started for r in failed)   # zero unstarted lost
        assert all(isinstance(r.error, ReplicaFailure) for r in failed)
        survivors = [r for r in reqs if r.error is None]
        assert survivors and all(len(r.tokens) == 8 for r in survivors)
        assert sum(r.failovers for r in reqs) > 0
        # the surviving replica advertises the tenants it now serves
        router._last_beats_read = 0.0
        router.poll(0.05)
        hot = [set(b.get("adapters_hot") or [])
               for b in router._beats.values()]
        assert any(h & {1, 2, 3} for h in hot), router._beats
    finally:
        router.close()
