"""Pallas block-sparse attention kernel — differential tests vs dense
masked attention (the reference's kernel-vs-reference pattern,
tests/unit/test_sparse_attention.py there; our kernel replaces the Triton
sdd/softmax/dsd trio, reference trsrc/matmul.tr:1, softmax_fwd.tr:1)."""
import numpy as np
import jax
import jax.numpy as jnp
import pytest

from deepspeed_tpu.ops.pallas.block_sparse_attention import (
    block_sparse_attention, build_kernel_luts)
from deepspeed_tpu.ops.sparse_attention import (
    BigBirdSparsityConfig, BSLongformerSparsityConfig, FixedSparsityConfig,
    SparseSelfAttention)

B, H, T, D = 2, 4, 256, 64


def _qkv(seed=0, dtype=jnp.float32):
    rng = np.random.default_rng(seed)
    mk = lambda: jnp.asarray(rng.normal(size=(B, H, T, D)), dtype)
    return mk(), mk(), mk()


def _dense_ref(q, k, v, layout, block):
    mask = jnp.asarray(np.kron(np.asarray(layout),
                               np.ones((block, block))))[None]
    s = jnp.einsum("bhqd,bhkd->bhqk", q.astype(jnp.float32),
                   k.astype(jnp.float32)) / np.sqrt(D)
    s = jnp.where(mask > 0, s, -1e30)
    p = jax.nn.softmax(s, axis=-1)
    # fully-masked rows -> zeros (kernel semantics)
    alive = (mask > 0).any(-1, keepdims=True)
    p = jnp.where(alive, p, 0.0)
    return jnp.einsum("bhqk,bhkd->bhqd", p, v.astype(jnp.float32))


CONFIGS = [
    ("bigbird", lambda: BigBirdSparsityConfig(num_heads=H, block=16)),
    ("longformer", lambda: BSLongformerSparsityConfig(num_heads=H,
                                                      block=16)),
    ("fixed", lambda: FixedSparsityConfig(num_heads=H, block=16,
                                          attention="bidirectional")),
]


@pytest.mark.parametrize("name,mk", CONFIGS, ids=[c[0] for c in CONFIGS])
def test_forward_matches_dense(name, mk):
    cfg = mk()
    layout = np.asarray(cfg.make_layout(T))
    q, k, v = _qkv()
    out = block_sparse_attention(q, k, v, layout, cfg.block)
    ref = _dense_ref(q, k, v, layout, cfg.block)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               atol=2e-5, rtol=2e-5)


def test_backward_matches_dense():
    cfg = BigBirdSparsityConfig(num_heads=H, block=16)
    layout = np.asarray(cfg.make_layout(T))
    q, k, v = _qkv(3)

    gk = jax.grad(lambda *a: jnp.sum(
        block_sparse_attention(*a, layout, cfg.block) ** 2),
        argnums=(0, 1, 2))(q, k, v)
    gd = jax.grad(lambda *a: jnp.sum(
        _dense_ref(*a, layout, cfg.block) ** 2),
        argnums=(0, 1, 2))(q, k, v)
    for name, a, b in zip("qkv", gk, gd):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   atol=1e-2, rtol=1e-3,
                                   err_msg=f"d{name}")


def test_bf16_inputs():
    cfg = BigBirdSparsityConfig(num_heads=H, block=16)
    layout = np.asarray(cfg.make_layout(T))
    q, k, v = _qkv(1, jnp.bfloat16)
    out = block_sparse_attention(q, k, v, layout, cfg.block)
    assert out.dtype == jnp.bfloat16
    ref = _dense_ref(q, k, v, layout, cfg.block)
    np.testing.assert_allclose(np.asarray(out, np.float32),
                               np.asarray(ref), atol=0.05, rtol=0.05)


def test_kernel_luts_repeat_padding():
    layout = np.zeros((1, 4, 4), np.int64)
    layout[0, 0, [1, 3]] = 1
    layout[0, 2, 2] = 1
    cols, nvalid, rows_t, nvalid_t = build_kernel_luts(layout)
    assert nvalid[0].tolist() == [2, 0, 1, 0]
    assert cols[0, 0].tolist()[:2] == [1, 3]
    assert all(c == 3 for c in cols[0, 0, 2:])   # repeat-padded
    assert nvalid_t[0].tolist() == [0, 1, 1, 1]
    assert rows_t[0, 1, 0] == 0 and rows_t[0, 3, 0] == 0


def test_module_dispatches_to_kernel():
    """No masks/rpe -> the Pallas kernel path; outputs must agree with the
    gathered-block XLA path (which masks force)."""
    cfg = BigBirdSparsityConfig(num_heads=H, block=16)
    attn = SparseSelfAttention(sparsity_config=cfg)
    q, k, v = _qkv(7)
    out_kernel = attn(q, k, v)
    # an all-ones additive key-padding mask forces the gather path without
    # changing the math
    out_gather = attn(q, k, v,
                      key_padding_mask=jnp.zeros((B, T), jnp.float32))
    np.testing.assert_allclose(np.asarray(out_kernel),
                               np.asarray(out_gather), atol=2e-5,
                               rtol=2e-5)


def test_luts_dedup_head_uniform_planes():
    """Head-uniform layouts collapse to one SMEM plane (the 2 MB bigbird
    seq-16k LUT that overflowed the ~1 MB v5e SMEM budget on hardware)."""
    layout = BigBirdSparsityConfig(num_heads=H, block=16).make_layout(T)
    assert layout.shape[0] == H  # broadcast form going in
    cols, nvalid, rows_t, nvalid_t = build_kernel_luts(np.asarray(layout))
    assert cols.shape[0] == 1 and nvalid.shape[0] == 1
    assert rows_t.shape[0] == 1 and nvalid_t.shape[0] == 1
    # per-head layouts must NOT dedup
    rng = np.random.default_rng(0)
    perhead = (rng.random((H, 4, 4)) < 0.5).astype(np.int64)
    perhead[:, np.arange(4), np.arange(4)] = 1  # keep rows alive
    cols2, _, _, _ = build_kernel_luts(perhead)
    assert cols2.shape[0] == H


def test_deduped_luts_match_dense():
    """Numerics through the deduped plane stay exact vs dense reference."""
    q, k, v = _qkv(3)
    cfg = BigBirdSparsityConfig(num_heads=H, block=16)
    layout = cfg.make_layout(T)
    out = block_sparse_attention(q, k, v, np.asarray(layout), 16)
    ref = _dense_ref(q, k, v, layout, 16)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               atol=2e-5, rtol=2e-5)


def test_oversized_per_head_lut_raises():
    """A per-head LUT past the SMEM budget must fail loudly at trace time
    (hardware fails with an opaque AOT allocator error otherwise)."""
    Hh, nb = 16, 128
    rng = np.random.default_rng(1)
    layout = (rng.random((Hh, nb, nb)) < 0.9).astype(np.int64)
    tiny = 16
    Tt = nb * tiny
    q = jnp.zeros((1, Hh, Tt, 8), jnp.float32)
    with pytest.raises(ValueError, match="SMEM"):
        block_sparse_attention(q, q, q, layout, tiny, interpret=False)
