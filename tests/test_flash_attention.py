"""Differential tests: Pallas flash attention vs dense XLA reference.

Mirrors the reference's kernel-vs-reference-implementation strategy
(reference: tests/unit/test_cuda_forward.py / test_cuda_backward.py —
DeepSpeedTransformerLayer vs a vendored HuggingFace BertEncoder over a
grid of shapes/dtypes).
"""
import jax
import jax.numpy as jnp
import numpy as np
import pytest

from deepspeed_tpu.ops.attention import causal_attention
from deepspeed_tpu.ops.pallas.flash_attention import flash_attention, mha


def _rand_qkv(b, h, t, d, dtype=jnp.float32, seed=0):
    rng = np.random.RandomState(seed)
    mk = lambda: jnp.asarray(rng.randn(b, h, t, d), dtype)
    return mk(), mk(), mk()


def _dense(q, k, v, causal):
    if causal:
        return causal_attention(q, k, v)
    scale = 1.0 / np.sqrt(q.shape[-1])
    s = jnp.einsum("bhqd,bhkd->bhqk", q, k,
                   preferred_element_type=jnp.float32) * scale
    p = jax.nn.softmax(s, axis=-1).astype(q.dtype)
    return jnp.einsum("bhqk,bhkd->bhqd", p, v)


@pytest.mark.parametrize("t", [64, 128, 200, 384])
@pytest.mark.parametrize("causal", [True, False])
def test_forward_matches_dense(t, causal):
    q, k, v = _rand_qkv(2, 2, t, 64)
    out = flash_attention(q, k, v, causal=causal)
    ref = _dense(q, k, v, causal)
    np.testing.assert_allclose(out, ref, atol=2e-5, rtol=2e-5)


def test_forward_bf16():
    q, k, v = _rand_qkv(1, 2, 128, 64, dtype=jnp.bfloat16)
    out = flash_attention(q, k, v, causal=True)
    ref = causal_attention(q, k, v)
    assert out.dtype == jnp.bfloat16
    np.testing.assert_allclose(out.astype(np.float32),
                               ref.astype(np.float32), atol=3e-2)


@pytest.mark.parametrize("t", [128, 200])
@pytest.mark.parametrize("causal", [True, False])
def test_backward_matches_dense(t, causal):
    q, k, v = _rand_qkv(1, 2, t, 32, seed=1)

    def loss_flash(q, k, v):
        return jnp.sum(flash_attention(q, k, v, causal=causal) ** 2)

    def loss_dense(q, k, v):
        return jnp.sum(_dense(q, k, v, causal) ** 2)

    gf = jax.grad(loss_flash, argnums=(0, 1, 2))(q, k, v)
    gr = jax.grad(loss_dense, argnums=(0, 1, 2))(q, k, v)
    for a, b in zip(gf, gr):
        np.testing.assert_allclose(a, b, atol=5e-4, rtol=1e-3)


def test_small_block_sizes_exercise_multiblock_path():
    q, k, v = _rand_qkv(1, 1, 64, 32, seed=2)
    out = flash_attention(q, k, v, causal=True, block_q=16, block_k=16)
    ref = causal_attention(q, k, v)
    np.testing.assert_allclose(out, ref, atol=2e-5, rtol=2e-5)

    def loss(q, k, v):
        return jnp.sum(flash_attention(q, k, v, causal=True,
                                       block_q=16, block_k=16) ** 2)

    def loss_ref(q, k, v):
        return jnp.sum(causal_attention(q, k, v) ** 2)

    gf = jax.grad(loss, argnums=(0, 1, 2))(q, k, v)
    gr = jax.grad(loss_ref, argnums=(0, 1, 2))(q, k, v)
    for a, b in zip(gf, gr):
        np.testing.assert_allclose(a, b, atol=5e-4, rtol=1e-3)


def test_cross_attention_lengths():
    rng = np.random.RandomState(3)
    q = jnp.asarray(rng.randn(1, 2, 96, 32), jnp.float32)
    k = jnp.asarray(rng.randn(1, 2, 160, 32), jnp.float32)
    v = jnp.asarray(rng.randn(1, 2, 160, 32), jnp.float32)
    out = flash_attention(q, k, v, causal=False)
    ref = _dense(q, k, v, causal=False)
    np.testing.assert_allclose(out, ref, atol=2e-5, rtol=2e-5)


def _dense_dropout_oracle(q, k, v, rate, rng, causal=True):
    """Dense attention applying the kernel's EXACT keep mask (same hash,
    same seed derivation) — fwd and grads must match the kernel bitwise
    up to fp32 reduction noise."""
    from attention_oracles import dense_dropout_oracle
    seed = jax.random.bits(rng, (), jnp.uint32)
    return dense_dropout_oracle(q, k, v, rate, seed, causal=causal)


def test_dropout_zero_rate_is_identity():
    q, k, v = _rand_qkv(1, 2, 96, 32)
    base = flash_attention(q, k, v)
    out = flash_attention(q, k, v, dropout_rate=0.0,
                          dropout_rng=jax.random.PRNGKey(0))
    np.testing.assert_array_equal(np.asarray(out), np.asarray(base))


@pytest.mark.parametrize("causal", [True, False])
def test_dropout_forward_matches_masked_oracle(causal):
    q, k, v = _rand_qkv(2, 2, 128, 32, seed=3)
    rng = jax.random.PRNGKey(7)
    out = flash_attention(q, k, v, causal=causal, dropout_rate=0.2,
                          dropout_rng=rng)
    ref = _dense_dropout_oracle(q, k, v, 0.2, rng, causal=causal)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               atol=2e-5, rtol=2e-5)


def test_dropout_multiblock_mask_offsets():
    """Small blocks: the in-kernel mask must hash GLOBAL positions, so a
    multi-block run agrees with the one-block oracle."""
    q, k, v = _rand_qkv(1, 2, 200, 32, seed=4)
    rng = jax.random.PRNGKey(11)
    out = flash_attention(q, k, v, dropout_rate=0.3, dropout_rng=rng,
                          block_q=64, block_k=64)
    ref = _dense_dropout_oracle(q, k, v, 0.3, rng)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               atol=2e-5, rtol=2e-5)


def test_dropout_backward_matches_masked_oracle():
    q, k, v = _rand_qkv(1, 2, 128, 32, seed=5)
    rng = jax.random.PRNGKey(13)
    wt = jnp.asarray(np.random.RandomState(9).randn(*q.shape), q.dtype)

    def loss_kernel(q, k, v):
        return jnp.sum(flash_attention(q, k, v, dropout_rate=0.25,
                                       dropout_rng=rng) * wt)

    def loss_oracle(q, k, v):
        return jnp.sum(_dense_dropout_oracle(q, k, v, 0.25, rng) * wt)

    gk = jax.grad(loss_kernel, argnums=(0, 1, 2))(q, k, v)
    go = jax.grad(loss_oracle, argnums=(0, 1, 2))(q, k, v)
    for a, b, name in zip(gk, go, "qkv"):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   atol=5e-5, rtol=5e-5,
                                   err_msg=f"d{name} mismatch")


def test_dropout_seed_sensitivity_and_determinism():
    q, k, v = _rand_qkv(1, 1, 96, 32)
    r1, r2 = jax.random.PRNGKey(0), jax.random.PRNGKey(1)
    a1 = flash_attention(q, k, v, dropout_rate=0.5, dropout_rng=r1)
    a1b = flash_attention(q, k, v, dropout_rate=0.5, dropout_rng=r1)
    a2 = flash_attention(q, k, v, dropout_rate=0.5, dropout_rng=r2)
    np.testing.assert_array_equal(np.asarray(a1), np.asarray(a1b))
    assert np.abs(np.asarray(a1) - np.asarray(a2)).max() > 0


def test_dropout_is_unbiased():
    """Averaged over many seeds, dropped attention approaches the
    undropped output (inverted-dropout scaling)."""
    q, k, v = _rand_qkv(1, 1, 64, 32)
    base = np.asarray(flash_attention(q, k, v))
    acc = np.zeros_like(base)
    n = 48
    for s in range(n):
        acc += np.asarray(flash_attention(
            q, k, v, dropout_rate=0.3, dropout_rng=jax.random.PRNGKey(s)))
    err = np.abs(acc / n - base).mean() / np.abs(base).mean()
    assert err < 0.15, f"dropout mean deviates {err:.3f} from base"


def test_mha_routes_dropout_into_kernel():
    q, k, v = _rand_qkv(1, 1, 64, 32)
    rng = jax.random.PRNGKey(0)
    out = mha(q, k, v, dropout_rate=0.1, dropout_rng=rng)
    ref = flash_attention(q, k, v, dropout_rate=0.1, dropout_rng=rng)
    np.testing.assert_array_equal(np.asarray(out), np.asarray(ref))


def test_jit_compiles_once():
    q, k, v = _rand_qkv(1, 1, 128, 32)
    f = jax.jit(lambda q, k, v: flash_attention(q, k, v))
    np.testing.assert_allclose(f(q, k, v),
                               causal_attention(q, k, v),
                               atol=2e-5, rtol=2e-5)


# ---------------------------------------------------------------------------
# key (padding) masks — the BERT/HF case
# ---------------------------------------------------------------------------
def _dense_masked(q, k, v, add_mask):
    """Dense oracle with an additive [B, Tk] key mask."""
    scale = 1.0 / np.sqrt(q.shape[-1])
    s = jnp.einsum("bhqd,bhkd->bhqk", q, k,
                   preferred_element_type=jnp.float32) * scale
    s = s + add_mask[:, None, None, :]
    p = jax.nn.softmax(s, axis=-1).astype(q.dtype)
    return jnp.einsum("bhqk,bhkd->bhqd", p, v)


@pytest.mark.parametrize("t,lens", [(128, (128, 70)), (200, (200, 33))])
def test_key_mask_forward_matches_dense(t, lens):
    q, k, v = _rand_qkv(2, 2, t, 32, seed=4)
    valid = jnp.asarray(
        np.arange(t)[None, :] < np.asarray(lens)[:, None])   # [B, T] bool
    add = jnp.where(valid, 0.0, -1e9).astype(jnp.float32)
    out_bool = flash_attention(q, k, v, causal=False, key_mask=valid)
    out_add = flash_attention(q, k, v, causal=False, key_mask=add)
    ref = _dense_masked(q, k, v, add)
    np.testing.assert_allclose(out_bool, ref, atol=2e-5, rtol=2e-5)
    np.testing.assert_allclose(out_add, ref, atol=2e-5, rtol=2e-5)


def test_key_mask_backward_matches_dense():
    t = 200  # multi-block with block_q=block_k=64
    q, k, v = _rand_qkv(1, 2, t, 32, seed=5)
    valid = jnp.asarray(np.arange(t)[None, :] < 131)
    add = jnp.where(valid, 0.0, -1e9).astype(jnp.float32)

    def loss_flash(q, k, v):
        return jnp.sum(flash_attention(
            q, k, v, causal=False, key_mask=valid,
            block_q=64, block_k=64) ** 2)

    def loss_dense(q, k, v):
        return jnp.sum(_dense_masked(q, k, v, add) ** 2)

    gf = jax.grad(loss_flash, argnums=(0, 1, 2))(q, k, v)
    gr = jax.grad(loss_dense, argnums=(0, 1, 2))(q, k, v)
    for a, b in zip(gf, gr):
        np.testing.assert_allclose(a, b, atol=5e-4, rtol=1e-3)
    # masked keys receive zero dK/dV
    np.testing.assert_allclose(np.asarray(gf[1])[:, :, 131:], 0.0,
                               atol=1e-6)
    np.testing.assert_allclose(np.asarray(gf[2])[:, :, 131:], 0.0,
                               atol=1e-6)


def test_key_mask_composes_with_causal_and_dropout():
    """Mask × causal × in-kernel dropout: against the dense oracle that
    applies the kernel's exact keep mask plus the key mask."""
    from attention_oracles import dense_dropout_oracle
    t = 128
    q, k, v = _rand_qkv(1, 2, t, 32, seed=6)
    valid = jnp.asarray(np.arange(t)[None, :] < 99)
    seed = jnp.uint32(42)
    out = flash_attention(q, k, v, causal=True, dropout_rate=0.3,
                          dropout_seed=seed, key_mask=valid)
    ref = dense_dropout_oracle(q, k, v, 0.3, seed, causal=True,
                               key_mask=valid)
    np.testing.assert_allclose(out, ref, atol=3e-5, rtol=3e-5)


def test_key_mask_per_head_shape():
    """[B*H, Tk] masks (per-head) are accepted verbatim."""
    b, h, t = 2, 2, 64
    q, k, v = _rand_qkv(b, h, t, 32, seed=7)
    lens = np.array([50, 64, 20, 40])                      # one per b*h row
    valid = jnp.asarray(np.arange(t)[None, :] < lens[:, None])
    out = flash_attention(q, k, v, causal=False, key_mask=valid)
    add = jnp.where(valid, 0.0, -1e9).astype(jnp.float32).reshape(b, h, t)
    scale = 1.0 / np.sqrt(32)
    s = jnp.einsum("bhqd,bhkd->bhqk", q, k,
                   preferred_element_type=jnp.float32) * scale
    s = s + add[:, :, None, :]
    ref = jnp.einsum("bhqk,bhkd->bhqd",
                     jax.nn.softmax(s, -1).astype(q.dtype), v)
    np.testing.assert_allclose(out, ref, atol=2e-5, rtol=2e-5)


# ---------------------------------------------------------------------
# mis-masking hazard regressions (the KV-cache decode class): kv_length
# hard-masks out-of-range keys, all-masked rows hard-zero with zero
# gradients — never silently attend
# ---------------------------------------------------------------------
def test_kv_length_masks_garbage_tail():
    """A KV buffer whose tail is garbage (the decode-cache shape) must
    match the dense reference truncated to the live length — forward
    AND gradients."""
    q, k, v = _rand_qkv(2, 2, 96, 32, seed=8)
    live = 60
    k = k.at[:, :, live:].set(1e4)
    v = v.at[:, :, live:].set(1e4)

    def loss_flash(q, k, v):
        return jnp.sum(flash_attention(q, k, v, causal=False,
                                       kv_length=live) ** 2)

    def loss_dense(q, k, v):
        return jnp.sum(_dense(q, k[:, :, :live], v[:, :, :live],
                              causal=False) ** 2)

    out = flash_attention(q, k, v, causal=False, kv_length=live)
    ref = _dense(q, k[:, :, :live], v[:, :, :live], causal=False)
    np.testing.assert_allclose(out, ref, atol=2e-5, rtol=2e-5)
    gf = jax.grad(loss_flash, argnums=(0, 1, 2))(q, k, v)
    gd = jax.grad(loss_dense, argnums=(0, 1, 2))(q, k, v)
    np.testing.assert_allclose(gf[0], gd[0], atol=3e-4, rtol=3e-4)
    np.testing.assert_allclose(gf[1][:, :, :live], gd[1][:, :, :live],
                               atol=3e-4, rtol=3e-4)
    # masked tail keys take ZERO gradient (they were never attended)
    assert (np.asarray(gf[1][:, :, live:]) == 0).all()
    assert (np.asarray(gf[2][:, :, live:]) == 0).all()


def test_kv_length_out_of_range_raises():
    q, k, v = _rand_qkv(1, 1, 32, 16, seed=9)
    with pytest.raises(ValueError, match="out of range"):
        flash_attention(q, k, v, causal=False, kv_length=33)
    with pytest.raises(ValueError, match="out of range"):
        flash_attention(q, k, v, causal=False, kv_length=-1)


def test_kv_length_zero_hard_zeros():
    """kv_length=0 (no live key at all) outputs exact zeros instead of
    the mean of V (the silent-attend failure this satellite closes)."""
    q, k, v = _rand_qkv(1, 2, 32, 16, seed=10)
    out = flash_attention(q, k, v, causal=False, kv_length=0)
    assert (np.asarray(out) == 0).all()


def test_all_masked_key_rows_zero_output_and_grads():
    """A key_mask dropping EVERY key of a batch row previously
    renormalized over the masked keys (silently attending to the
    max-scoring masked key); now: exact zeros, zero gradients, other
    rows untouched."""
    b, h, t = 2, 2, 64
    q, k, v = _rand_qkv(b, h, t, 32, seed=11)
    km = np.ones((b, t), bool)
    km[0, :] = False

    def loss(q, k, v):
        return jnp.sum(flash_attention(q, k, v, causal=False,
                                       key_mask=jnp.asarray(km)) ** 2)

    out = flash_attention(q, k, v, causal=False, key_mask=jnp.asarray(km))
    assert (np.asarray(out[0]) == 0).all()
    ref = _dense(q[1:], k[1:], v[1:], causal=False)
    np.testing.assert_allclose(out[1:], ref, atol=2e-5, rtol=2e-5)
    gq, gk, gv = jax.grad(loss, argnums=(0, 1, 2))(q, k, v)
    assert (np.asarray(gq[0]) == 0).all()
    assert (np.asarray(gk[0]) == 0).all()
    assert (np.asarray(gv[0]) == 0).all()
    assert np.abs(np.asarray(gq[1])).max() > 0
