"""Differential tests: Pallas flash attention vs dense XLA reference.

Mirrors the reference's kernel-vs-reference-implementation strategy
(reference: tests/unit/test_cuda_forward.py / test_cuda_backward.py —
DeepSpeedTransformerLayer vs a vendored HuggingFace BertEncoder over a
grid of shapes/dtypes).
"""
import jax
import jax.numpy as jnp
import numpy as np
import pytest

from deepspeed_tpu.ops.attention import causal_attention
from deepspeed_tpu.ops.pallas.flash_attention import flash_attention, mha


def _rand_qkv(b, h, t, d, dtype=jnp.float32, seed=0):
    rng = np.random.RandomState(seed)
    mk = lambda: jnp.asarray(rng.randn(b, h, t, d), dtype)
    return mk(), mk(), mk()


def _dense(q, k, v, causal):
    if causal:
        return causal_attention(q, k, v)
    scale = 1.0 / np.sqrt(q.shape[-1])
    s = jnp.einsum("bhqd,bhkd->bhqk", q, k,
                   preferred_element_type=jnp.float32) * scale
    p = jax.nn.softmax(s, axis=-1).astype(q.dtype)
    return jnp.einsum("bhqk,bhkd->bhqd", p, v)


@pytest.mark.parametrize("t", [64, 128, 200, 384])
@pytest.mark.parametrize("causal", [True, False])
def test_forward_matches_dense(t, causal):
    q, k, v = _rand_qkv(2, 2, t, 64)
    out = flash_attention(q, k, v, causal=causal)
    ref = _dense(q, k, v, causal)
    np.testing.assert_allclose(out, ref, atol=2e-5, rtol=2e-5)


def test_forward_bf16():
    q, k, v = _rand_qkv(1, 2, 128, 64, dtype=jnp.bfloat16)
    out = flash_attention(q, k, v, causal=True)
    ref = causal_attention(q, k, v)
    assert out.dtype == jnp.bfloat16
    np.testing.assert_allclose(out.astype(np.float32),
                               ref.astype(np.float32), atol=3e-2)


@pytest.mark.parametrize("t", [128, 200])
@pytest.mark.parametrize("causal", [True, False])
def test_backward_matches_dense(t, causal):
    q, k, v = _rand_qkv(1, 2, t, 32, seed=1)

    def loss_flash(q, k, v):
        return jnp.sum(flash_attention(q, k, v, causal=causal) ** 2)

    def loss_dense(q, k, v):
        return jnp.sum(_dense(q, k, v, causal) ** 2)

    gf = jax.grad(loss_flash, argnums=(0, 1, 2))(q, k, v)
    gr = jax.grad(loss_dense, argnums=(0, 1, 2))(q, k, v)
    for a, b in zip(gf, gr):
        np.testing.assert_allclose(a, b, atol=5e-4, rtol=1e-3)


def test_small_block_sizes_exercise_multiblock_path():
    q, k, v = _rand_qkv(1, 1, 64, 32, seed=2)
    out = flash_attention(q, k, v, causal=True, block_q=16, block_k=16)
    ref = causal_attention(q, k, v)
    np.testing.assert_allclose(out, ref, atol=2e-5, rtol=2e-5)

    def loss(q, k, v):
        return jnp.sum(flash_attention(q, k, v, causal=True,
                                       block_q=16, block_k=16) ** 2)

    def loss_ref(q, k, v):
        return jnp.sum(causal_attention(q, k, v) ** 2)

    gf = jax.grad(loss, argnums=(0, 1, 2))(q, k, v)
    gr = jax.grad(loss_ref, argnums=(0, 1, 2))(q, k, v)
    for a, b in zip(gf, gr):
        np.testing.assert_allclose(a, b, atol=5e-4, rtol=1e-3)


def test_cross_attention_lengths():
    rng = np.random.RandomState(3)
    q = jnp.asarray(rng.randn(1, 2, 96, 32), jnp.float32)
    k = jnp.asarray(rng.randn(1, 2, 160, 32), jnp.float32)
    v = jnp.asarray(rng.randn(1, 2, 160, 32), jnp.float32)
    out = flash_attention(q, k, v, causal=False)
    ref = _dense(q, k, v, causal=False)
    np.testing.assert_allclose(out, ref, atol=2e-5, rtol=2e-5)


def test_mha_dropout_falls_back_to_dense():
    q, k, v = _rand_qkv(1, 1, 64, 32)
    rng = jax.random.PRNGKey(0)
    out = mha(q, k, v, dropout_rate=0.1, dropout_rng=rng)
    ref = causal_attention(q, k, v, dropout_rate=0.1, dropout_rng=rng)
    np.testing.assert_allclose(out, ref, atol=1e-6)


def test_jit_compiles_once():
    q, k, v = _rand_qkv(1, 1, 128, 32)
    f = jax.jit(lambda q, k, v: flash_attention(q, k, v))
    np.testing.assert_allclose(f(q, k, v),
                               causal_attention(q, k, v),
                               atol=2e-5, rtol=2e-5)
