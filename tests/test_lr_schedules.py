"""LR schedule semantics (mirrors reference tests/unit/test_lr_schedulers.py)."""
import jax.numpy as jnp
import numpy as np
import pytest

from deepspeed_tpu.runtime.lr_schedules import (
    get_lr_schedule, lr_range_test, one_cycle, warmup_lr, warmup_decay_lr,
    VALID_LR_SCHEDULES,
)


def _at(sched, step):
    return float(sched(jnp.asarray(step)))


def test_warmup_linear():
    s = warmup_lr(warmup_min_lr=0.0, warmup_max_lr=1.0, warmup_num_steps=10,
                  warmup_type="linear")
    assert _at(s, 0) == 0.0
    assert abs(_at(s, 5) - 0.5) < 1e-6
    assert _at(s, 10) == 1.0
    assert _at(s, 100) == 1.0


def test_warmup_log_above_linear():
    s_log = warmup_lr(0.0, 1.0, 10, "log")
    s_lin = warmup_lr(0.0, 1.0, 10, "linear")
    assert _at(s_log, 5) > _at(s_lin, 5)
    assert abs(_at(s_log, 10) - 1.0) < 1e-6


def test_warmup_decay_hits_zero():
    s = warmup_decay_lr(total_num_steps=20, warmup_max_lr=1.0,
                        warmup_num_steps=10, warmup_type="linear")
    assert abs(_at(s, 10) - 1.0) < 1e-6
    assert abs(_at(s, 15) - 0.5) < 1e-6
    assert _at(s, 20) == 0.0
    assert _at(s, 30) == 0.0


def test_lr_range_test_continuous():
    s = lr_range_test(lr_range_test_min_lr=0.1,
                      lr_range_test_step_size=10,
                      lr_range_test_step_rate=1.0)
    assert abs(_at(s, 0) - 0.1) < 1e-7
    assert _at(s, 10) > _at(s, 5) > _at(s, 0)


def test_lr_range_test_staircase():
    s = lr_range_test(lr_range_test_min_lr=0.1,
                      lr_range_test_step_size=10,
                      lr_range_test_step_rate=1.0,
                      lr_range_test_staircase=True)
    assert _at(s, 3) == _at(s, 9)
    assert _at(s, 10) > _at(s, 9)


def test_one_cycle_shape():
    s = one_cycle(cycle_min_lr=0.0, cycle_max_lr=1.0,
                  cycle_first_step_size=10, cycle_second_step_size=10)
    assert _at(s, 0) == 0.0
    assert abs(_at(s, 10) - 1.0) < 1e-6   # peak
    assert _at(s, 15) < _at(s, 10)
    assert abs(_at(s, 20)) < 1e-6          # back to min


def test_registry():
    for name in VALID_LR_SCHEDULES:
        params = {}
        if name == "WarmupDecayLR":
            params = {"total_num_steps": 100}
        sched = get_lr_schedule(name, params)
        assert np.isfinite(_at(sched, 5))
    with pytest.raises(ValueError):
        get_lr_schedule("Nope", {})


def test_add_tuning_arguments_roundtrip():
    """CLI tuning flags -> scheduler config block -> live schedule
    (reference: lr_schedules.py:54-160)."""
    import argparse
    from deepspeed_tpu.runtime.lr_schedules import (
        add_tuning_arguments, parse_arguments, schedule_params_from_args)

    parser = add_tuning_arguments(argparse.ArgumentParser())
    args = parser.parse_args(
        ["--lr_schedule", "OneCycle", "--cycle_min_lr", "0.0",
         "--cycle_max_lr", "1.0", "--cycle_first_step_size", "10",
         "--cycle_second_step_size", "10"])
    blk = schedule_params_from_args(args)
    assert blk["type"] == "OneCycle"
    sched = get_lr_schedule(blk["type"], blk["params"])
    assert abs(_at(sched, 10) - 1.0) < 1e-6

    # unset --lr_schedule -> no block (engine falls back to config json)
    assert schedule_params_from_args(parser.parse_args([])) is None

    # parse_arguments tolerates unknown flags (reference parse_known_args)
    import sys
    argv = sys.argv
    sys.argv = ["prog", "--lr_schedule", "WarmupLR", "--not_a_ds_flag", "1"]
    try:
        parsed, unknown = parse_arguments()
        assert parsed.lr_schedule == "WarmupLR"
        assert "--not_a_ds_flag" in unknown
    finally:
        sys.argv = argv


def test_top_level_export_parity():
    """Reference deepspeed/__init__.py re-exports (SURVEY L6)."""
    import deepspeed_tpu as ds
    for name in ["initialize", "add_config_arguments", "add_tuning_arguments",
                 "DeepSpeedEngine", "PipelineEngine", "DeepSpeedConfig",
                 "PipelineModule", "DeepSpeedTransformerLayer",
                 "DeepSpeedTransformerConfig", "log_dist", "checkpointing",
                 "ADAM_OPTIMIZER", "LAMB_OPTIMIZER", "__version__"]:
        assert hasattr(ds, name), name
