"""LR schedule semantics (mirrors reference tests/unit/test_lr_schedulers.py)."""
import jax.numpy as jnp
import numpy as np
import pytest

from deepspeed_tpu.runtime.lr_schedules import (
    get_lr_schedule, lr_range_test, one_cycle, warmup_lr, warmup_decay_lr,
    VALID_LR_SCHEDULES,
)


def _at(sched, step):
    return float(sched(jnp.asarray(step)))


def test_warmup_linear():
    s = warmup_lr(warmup_min_lr=0.0, warmup_max_lr=1.0, warmup_num_steps=10,
                  warmup_type="linear")
    assert _at(s, 0) == 0.0
    assert abs(_at(s, 5) - 0.5) < 1e-6
    assert _at(s, 10) == 1.0
    assert _at(s, 100) == 1.0


def test_warmup_log_above_linear():
    s_log = warmup_lr(0.0, 1.0, 10, "log")
    s_lin = warmup_lr(0.0, 1.0, 10, "linear")
    assert _at(s_log, 5) > _at(s_lin, 5)
    assert abs(_at(s_log, 10) - 1.0) < 1e-6


def test_warmup_decay_hits_zero():
    s = warmup_decay_lr(total_num_steps=20, warmup_max_lr=1.0,
                        warmup_num_steps=10, warmup_type="linear")
    assert abs(_at(s, 10) - 1.0) < 1e-6
    assert abs(_at(s, 15) - 0.5) < 1e-6
    assert _at(s, 20) == 0.0
    assert _at(s, 30) == 0.0


def test_lr_range_test_continuous():
    s = lr_range_test(lr_range_test_min_lr=0.1,
                      lr_range_test_step_size=10,
                      lr_range_test_step_rate=1.0)
    assert abs(_at(s, 0) - 0.1) < 1e-7
    assert _at(s, 10) > _at(s, 5) > _at(s, 0)


def test_lr_range_test_staircase():
    s = lr_range_test(lr_range_test_min_lr=0.1,
                      lr_range_test_step_size=10,
                      lr_range_test_step_rate=1.0,
                      lr_range_test_staircase=True)
    assert _at(s, 3) == _at(s, 9)
    assert _at(s, 10) > _at(s, 9)


def test_one_cycle_shape():
    s = one_cycle(cycle_min_lr=0.0, cycle_max_lr=1.0,
                  cycle_first_step_size=10, cycle_second_step_size=10)
    assert _at(s, 0) == 0.0
    assert abs(_at(s, 10) - 1.0) < 1e-6   # peak
    assert _at(s, 15) < _at(s, 10)
    assert abs(_at(s, 20)) < 1e-6          # back to min


def test_registry():
    for name in VALID_LR_SCHEDULES:
        params = {}
        if name == "WarmupDecayLR":
            params = {"total_num_steps": 100}
        sched = get_lr_schedule(name, params)
        assert np.isfinite(_at(sched, 5))
    with pytest.raises(ValueError):
        get_lr_schedule("Nope", {})
