"""Fused transformer layer + BERT differential tests.

Mirrors the reference's kernel-vs-HuggingFace differential pattern
(reference: tests/unit/test_cuda_forward.py:10-25 /
test_cuda_backward.py): the layer is checked against an independent
straight-line jnp BERT encoder implementation over a grid of shapes, in
forward and backward, fp32 and bf16.
"""
import jax
import jax.numpy as jnp
import numpy as np
import pytest

from deepspeed_tpu.models import BERT_BASE, BertConfig, BertModel
from deepspeed_tpu.ops.transformer import (DeepSpeedTransformerConfig,
                                           DeepSpeedTransformerLayer)


# ---------------------------------------------------------------------------
# independent reference encoder layer (straight-line, no shared helpers)
# ---------------------------------------------------------------------------
def ref_layer_norm(x, g, b, eps=1e-12):
    x = x.astype(jnp.float32)
    mu = x.mean(-1, keepdims=True)
    var = ((x - mu) ** 2).mean(-1, keepdims=True)
    return (x - mu) / jnp.sqrt(var + eps) * g + b


def ref_bert_layer(p, x, mask, heads, pre_ln=False):
    """Classic BERT encoder layer, everything in fp32."""
    x = x.astype(jnp.float32)
    B, T, D = x.shape
    Dh = D // heads

    def attn(h):
        # layer stores [d, 3, d]; the fused-[3d] view is its reshape
        qkv = (h @ p["attn_qkvw"].reshape(D, 3 * D)
               + p["attn_qkvb"].reshape(3 * D))
        q, k, v = jnp.split(qkv, 3, axis=-1)
        sh = lambda t: t.reshape(B, T, heads, Dh).transpose(0, 2, 1, 3)
        q, k, v = sh(q), sh(k), sh(v)
        s = (q @ k.transpose(0, 1, 3, 2)) * (Dh ** -0.5)
        if mask is not None:
            s = s + mask
        a = jax.nn.softmax(s, -1) @ v
        a = a.transpose(0, 2, 1, 3).reshape(B, T, D)
        return a @ p["attn_ow"] + p["attn_ob"]

    def ffn(h):
        y = jax.nn.gelu(h @ p["inter_w"] + p["inter_b"], approximate=False)
        return y @ p["output_w"] + p["output_b"]

    if pre_ln:
        x = x + attn(ref_layer_norm(x, p["attn_nw"], p["attn_nb"]))
        return x + ffn(ref_layer_norm(x, p["norm_w"], p["norm_b"]))
    x = ref_layer_norm(x + attn(x), p["attn_nw"], p["attn_nb"])
    return ref_layer_norm(x + ffn(x), p["norm_w"], p["norm_b"])


def make_layer(hidden, heads, pre_ln=False, **kw):
    cfg = DeepSpeedTransformerConfig(
        hidden_size=hidden, heads=heads, num_hidden_layers=2,
        attn_dropout_ratio=0.0, hidden_dropout_ratio=0.0,
        pre_layer_norm=pre_ln, **kw)
    layer = DeepSpeedTransformerLayer(cfg)
    params = layer.init(jax.random.PRNGKey(0))
    return layer, params


GRID = [  # (batch, seq, hidden, heads) — subset of the reference grid
    (2, 32, 64, 4),
    (1, 128, 128, 8),
    (3, 51, 96, 3),   # odd seq/batch like the reference's 1122/27/54 cases
]


@pytest.mark.parametrize("B,T,D,H", GRID)
@pytest.mark.parametrize("pre_ln", [False, True])
def test_forward_matches_reference(B, T, D, H, pre_ln):
    layer, params = make_layer(D, H, pre_ln)
    x = jnp.asarray(np.random.default_rng(1).standard_normal((B, T, D)),
                    jnp.float32)
    mask = None
    out = layer(params, x, mask, train=False)
    ref = ref_bert_layer(params, x, mask, H, pre_ln)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=1e-4, atol=1e-4)


def test_forward_with_attention_mask():
    B, T, D, H = 2, 64, 64, 4
    layer, params = make_layer(D, H)
    x = jnp.asarray(np.random.default_rng(2).standard_normal((B, T, D)),
                    jnp.float32)
    # HF additive mask: drop second half of keys for batch 0
    m = np.zeros((B, 1, 1, T), np.float32)
    m[0, :, :, T // 2:] = -10000.0
    out = layer(params, x, jnp.asarray(m), train=False)
    ref = ref_bert_layer(params, x, jnp.asarray(m), H)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=1e-4, atol=1e-4)


@pytest.mark.parametrize("pre_ln", [False, True])
def test_backward_matches_reference(pre_ln):
    B, T, D, H = 2, 32, 64, 4
    layer, params = make_layer(D, H, pre_ln)
    x = jnp.asarray(np.random.default_rng(3).standard_normal((B, T, D)),
                    jnp.float32)

    g1 = jax.grad(lambda p: jnp.sum(layer(p, x, train=False) ** 2))(params)
    g2 = jax.grad(
        lambda p: jnp.sum(ref_bert_layer(p, x, None, H, pre_ln) ** 2)
    )(params)
    for k in g1:
        np.testing.assert_allclose(np.asarray(g1[k]), np.asarray(g2[k]),
                                   rtol=2e-3, atol=2e-3, err_msg=k)


def test_bf16_close_to_fp32():
    B, T, D, H = 2, 64, 128, 8
    layer, params = make_layer(D, H)
    x32 = jnp.asarray(np.random.default_rng(4).standard_normal((B, T, D)),
                      jnp.float32)
    out32 = layer(params, x32, train=False)
    out16 = layer(params, x32.astype(jnp.bfloat16), train=False)
    assert out16.dtype == jnp.bfloat16
    np.testing.assert_allclose(np.asarray(out16, dtype=np.float32),
                               np.asarray(out32), rtol=0.1, atol=0.15)


@pytest.mark.parametrize("flag", ["normalize_invertible", "gelu_checkpoint",
                                  "attn_dropout_checkpoint"])
def test_memory_knobs_preserve_numerics(flag):
    """The remat flags must not change forward or backward values."""
    B, T, D, H = 2, 32, 64, 4
    layer0, params = make_layer(D, H)
    layer1, _ = make_layer(D, H, **{flag: True})
    x = jnp.asarray(np.random.default_rng(5).standard_normal((B, T, D)),
                    jnp.float32)
    np.testing.assert_allclose(
        np.asarray(layer0(params, x, train=False)),
        np.asarray(layer1(params, x, train=False)), rtol=1e-6, atol=1e-6)
    g0 = jax.grad(lambda p: jnp.sum(layer0(p, x, train=False) ** 2))(params)
    g1 = jax.grad(lambda p: jnp.sum(layer1(p, x, train=False) ** 2))(params)
    for k in g0:
        np.testing.assert_allclose(np.asarray(g0[k]), np.asarray(g1[k]),
                                   rtol=1e-5, atol=1e-5, err_msg=k)


def test_dropout_train_vs_eval():
    B, T, D, H = 2, 32, 64, 4
    cfg = DeepSpeedTransformerConfig(
        hidden_size=D, heads=H, attn_dropout_ratio=0.3,
        hidden_dropout_ratio=0.3, pre_layer_norm=False)
    layer = DeepSpeedTransformerLayer(cfg)
    params = layer.init(jax.random.PRNGKey(0))
    x = jnp.asarray(np.random.default_rng(6).standard_normal((B, T, D)),
                    jnp.float32)
    rng = jax.random.PRNGKey(7)
    t1 = layer(params, x, rng=rng, train=True)
    t2 = layer(params, x, rng=rng, train=True)
    np.testing.assert_array_equal(np.asarray(t1), np.asarray(t2))  # same key
    t3 = layer(params, x, rng=jax.random.PRNGKey(8), train=True)
    assert not np.allclose(np.asarray(t1), np.asarray(t3))
    e1 = layer(params, x, train=False)
    e2 = layer(params, x, train=False)
    np.testing.assert_array_equal(np.asarray(e1), np.asarray(e2))


def test_config_from_dict_roundtrip():
    cfg = DeepSpeedTransformerConfig.from_dict(
        {"hidden_size": 64, "heads": 4, "pre_layer_norm": False,
         "intermediate_size": 128})
    assert cfg.intermediate_size == 128 and not cfg.pre_layer_norm
    cfg2 = DeepSpeedTransformerConfig(hidden_size=64, heads=4)
    assert cfg2.intermediate_size == 256  # 4x default


# ---------------------------------------------------------------------------
# BERT model
# ---------------------------------------------------------------------------
def tiny_bert(**over):
    base = dict(vocab_size=128, hidden_size=64, num_hidden_layers=2,
                num_attention_heads=4, intermediate_size=128,
                max_position_embeddings=64,
                hidden_dropout_prob=0.0, attention_probs_dropout_prob=0.0)
    base.update(over)
    return BertConfig(**base)


def bert_batch(B=4, T=32, V=128, seed=0):
    rng = np.random.default_rng(seed)
    ids = rng.integers(0, V, (B, T)).astype(np.int32)
    labels = np.where(rng.random((B, T)) < 0.15, ids, -100).astype(np.int32)
    return {
        "input_ids": jnp.asarray(ids),
        "token_type_ids": jnp.asarray(
            (np.arange(T)[None] >= T // 2).astype(np.int32).repeat(B, 0)),
        "attention_mask": jnp.asarray(np.ones((B, T), np.float32)),
        "masked_lm_labels": jnp.asarray(labels),
        "next_sentence_label": jnp.asarray(
            rng.integers(0, 2, (B,)).astype(np.int32)),
    }


def test_bert_loss_finite_and_shapes():
    model = BertModel(tiny_bert())
    params = model.init(jax.random.PRNGKey(0))
    batch = bert_batch()
    mlm, nsp = model.apply(params, batch, jax.random.PRNGKey(1),
                           train=False)
    assert mlm.shape == (4, 32, 128) and nsp.shape == (4, 2)
    loss = model.loss_fn(params, batch, jax.random.PRNGKey(1), train=False)
    assert np.isfinite(float(loss))


@pytest.mark.slow
def test_bert_trains_via_engine():
    import sys
    sys.path.insert(0, "tests")
    from simple_model import base_config
    from deepspeed_tpu.config import DeepSpeedConfig
    from deepspeed_tpu.runtime.engine import DeepSpeedEngine

    cfg = DeepSpeedConfig(base_config(micro_bs=2, grad_acc=1),
                          world_size=8)
    model = BertModel(tiny_bert())
    engine = DeepSpeedEngine(model, cfg)
    losses = [float(engine.train_batch(bert_batch(B=16, T=32, seed=s)))
              for s in range(8)]
    assert all(np.isfinite(l) for l in losses)
    assert losses[-1] < losses[0], losses


def test_bert_remat_matches_no_remat():
    cfg0, cfg1 = tiny_bert(remat=None), tiny_bert(remat="block")
    m0, m1 = BertModel(cfg0), BertModel(cfg1)
    params = m0.init(jax.random.PRNGKey(0))
    batch = bert_batch(seed=2)
    r = jax.random.PRNGKey(3)
    l0 = m0.loss_fn(params, batch, r, train=False)
    l1 = m1.loss_fn(params, batch, r, train=False)
    np.testing.assert_allclose(float(l0), float(l1), rtol=1e-6)


def test_bert_large_param_count():
    """BERT-large ≈ 335M encoder+embedding params (sanity vs the published
    number the reference benchmarks against)."""
    model = BertModel(BERT_BASE)
    # count analytically from shapes without materializing
    shapes = jax.eval_shape(model.init, jax.random.PRNGKey(0))
    n = sum(int(np.prod(l.shape)) for l in jax.tree.leaves(shapes))
    assert 105e6 < n < 115e6  # BERT-base ≈ 110M


def _hf_bert_layer_and_params(D, H, I, seed, attn_impl="flash"):
    """Build an HF BertLayer and map its weights into our param dict
    (shared by the forward and backward differential tests)."""
    import torch
    import transformers
    from transformers.models.bert.modeling_bert import BertLayer

    hf_cfg = transformers.BertConfig(
        hidden_size=D, num_attention_heads=H, intermediate_size=I,
        hidden_dropout_prob=0.0, attention_probs_dropout_prob=0.0,
        attn_implementation="eager")
    torch.manual_seed(seed)
    hf_layer = BertLayer(hf_cfg).eval()

    def t2j(t):
        return jnp.asarray(t.detach().numpy())

    sd = dict(hf_layer.named_parameters())
    params = {
        "attn_qkvw": jnp.stack(
            [t2j(sd[f"attention.self.{n}.weight"]).T
             for n in ("query", "key", "value")], axis=1),
        "attn_qkvb": jnp.stack(
            [t2j(sd[f"attention.self.{n}.bias"])
             for n in ("query", "key", "value")], axis=0),
        "attn_ow": t2j(sd["attention.output.dense.weight"]).T,
        "attn_ob": t2j(sd["attention.output.dense.bias"]),
        "attn_nw": t2j(sd["attention.output.LayerNorm.weight"]),
        "attn_nb": t2j(sd["attention.output.LayerNorm.bias"]),
        "inter_w": t2j(sd["intermediate.dense.weight"]).T,
        "inter_b": t2j(sd["intermediate.dense.bias"]),
        "output_w": t2j(sd["output.dense.weight"]).T,
        "output_b": t2j(sd["output.dense.bias"]),
        "norm_w": t2j(sd["output.LayerNorm.weight"]),
        "norm_b": t2j(sd["output.LayerNorm.bias"]),
    }
    layer = DeepSpeedTransformerLayer(DeepSpeedTransformerConfig(
        hidden_size=D, heads=H, intermediate_size=I,
        attn_dropout_ratio=0.0, hidden_dropout_ratio=0.0,
        pre_layer_norm=False,   # classic BERT is post-LN, like HF
        attn_impl=attn_impl))
    return hf_layer, layer, params


@pytest.mark.parametrize("impl", ["flash", "dense"])
def test_forward_matches_huggingface_bert_layer(impl):
    """The reference's exact differential pattern: weights copied from a
    HuggingFace BertLayer, outputs compared (reference
    tests/unit/test_cuda_forward.py:10-25 copies from the vendored HF
    BertEncoder in tests/unit/modeling.py) — both attention impls."""
    torch = pytest.importorskip("torch")
    pytest.importorskip("transformers")

    B, T, D, H, I = 2, 33, 64, 4, 256
    hf_layer, layer, params = _hf_bert_layer_and_params(
        D, H, I, seed=0, attn_impl=impl)

    x = np.random.default_rng(0).standard_normal((B, T, D)).astype(
        np.float32)
    with torch.no_grad():
        want = hf_layer(torch.from_numpy(x))[0].numpy()
    got = np.asarray(layer(params, jnp.asarray(x), attention_mask=None,
                           rng=jax.random.PRNGKey(0), train=False))
    np.testing.assert_allclose(got, want, rtol=2e-4, atol=2e-4)


@pytest.mark.parametrize("impl", ["flash", "dense"])
def test_forward_matches_huggingface_with_padding_mask(impl):
    """HF differential WITH a padding mask — the flash path routes it
    through the kernel's per-key mask operand."""
    torch = pytest.importorskip("torch")
    pytest.importorskip("transformers")

    B, T, D, H, I = 2, 40, 64, 4, 256
    hf_layer, layer, params = _hf_bert_layer_and_params(
        D, H, I, seed=2, attn_impl=impl)

    x = np.random.default_rng(2).standard_normal((B, T, D)).astype(
        np.float32)
    add = np.zeros((B, 1, 1, T), np.float32)
    add[0, :, :, 29:] = -10000.0     # batch 0 pads the last 11 keys
    add[1, :, :, 7:] = -10000.0      # batch 1 keeps only 7
    with torch.no_grad():
        want = hf_layer(torch.from_numpy(x),
                        attention_mask=torch.from_numpy(add))[0].numpy()
    got = np.asarray(layer(params, jnp.asarray(x),
                           attention_mask=jnp.asarray(add),
                           rng=jax.random.PRNGKey(0), train=False))
    np.testing.assert_allclose(got, want, rtol=2e-4, atol=2e-4)


def test_flash_matches_dense_layer_with_mask():
    """Impl-vs-impl equivalence on the same params, padding mask on."""
    B, T, D, H = 2, 70, 64, 4
    layer_f, params = make_layer(D, H, attn_impl="flash")
    layer_d, _ = make_layer(D, H, attn_impl="dense")
    x = jnp.asarray(np.random.default_rng(5).standard_normal((B, T, D)),
                    jnp.float32)
    add = np.zeros((B, 1, 1, T), np.float32)
    add[0, :, :, 50:] = -10000.0
    out_f = layer_f(params, x, jnp.asarray(add), train=False)
    out_d = layer_d(params, x, jnp.asarray(add), train=False)
    np.testing.assert_allclose(np.asarray(out_f), np.asarray(out_d),
                               rtol=1e-4, atol=1e-4)
    # and gradients agree
    gf = jax.grad(lambda p: jnp.sum(
        layer_f(p, x, jnp.asarray(add), train=False) ** 2))(params)
    gd = jax.grad(lambda p: jnp.sum(
        layer_d(p, x, jnp.asarray(add), train=False) ** 2))(params)
    for k in gf:
        np.testing.assert_allclose(np.asarray(gf[k]), np.asarray(gd[k]),
                                   rtol=2e-3, atol=2e-3, err_msg=k)


def test_flash_rejects_full_2d_masks():
    layer, params = make_layer(64, 4, attn_impl="flash")
    x = jnp.zeros((1, 16, 64), jnp.float32)
    full = jnp.zeros((1, 1, 16, 16), jnp.float32)  # q-position dim
    with pytest.raises(ValueError, match="key-padding"):
        layer(params, x, full, train=False)


def test_backward_matches_huggingface_bert_layer():
    """Gradient differential against torch autograd through the HF layer
    (reference tests/unit/test_cuda_backward.py)."""
    torch = pytest.importorskip("torch")
    pytest.importorskip("transformers")

    B, T, D, H, I = 2, 17, 64, 4, 256
    hf_layer, layer, params = _hf_bert_layer_and_params(D, H, I, seed=1)

    x = np.random.default_rng(1).standard_normal((B, T, D)).astype(
        np.float32)

    # torch side: sum-of-squares loss, grads wrt input and all params
    tx = torch.from_numpy(x).requires_grad_(True)
    tloss = (hf_layer(tx)[0] ** 2).sum()
    tloss.backward()
    want_dx = tx.grad.numpy()
    want_qkvw = torch.stack(
        [hf_layer.attention.self.query.weight.grad.T,
         hf_layer.attention.self.key.weight.grad.T,
         hf_layer.attention.self.value.weight.grad.T], dim=1).numpy()
    want_ow = hf_layer.attention.output.dense.weight.grad.T.numpy()
    want_norm_b = hf_layer.output.LayerNorm.bias.grad.numpy()

    def loss_fn(p, xin):
        out = layer(p, xin, attention_mask=None,
                    rng=jax.random.PRNGKey(0), train=False)
        return jnp.sum(out.astype(jnp.float32) ** 2)

    gp, gx = jax.grad(loss_fn, argnums=(0, 1))(params, jnp.asarray(x))
    np.testing.assert_allclose(np.asarray(gx), want_dx,
                               rtol=2e-3, atol=2e-3)
    np.testing.assert_allclose(np.asarray(gp["attn_qkvw"]), want_qkvw,
                               rtol=2e-3, atol=2e-3)
    np.testing.assert_allclose(np.asarray(gp["attn_ow"]), want_ow,
                               rtol=2e-3, atol=2e-3)
    np.testing.assert_allclose(np.asarray(gp["norm_b"]), want_norm_b,
                               rtol=2e-3, atol=2e-3)



def test_flash_per_head_mask_matches_dense():
    """[B, H, 1, T] per-head masks route through the kernel's [B*H, T]
    path instead of collapsing to head 0."""
    B, T, D, H = 2, 48, 64, 4
    layer_f, params = make_layer(D, H, attn_impl="flash")
    layer_d, _ = make_layer(D, H, attn_impl="dense")
    x = jnp.asarray(np.random.default_rng(8).standard_normal((B, T, D)),
                    jnp.float32)
    rng = np.random.default_rng(9)
    add = np.where(rng.random((B, H, 1, T)) < 0.3, -10000.0, 0.0
                   ).astype(np.float32)
    out_f = layer_f(params, x, jnp.asarray(add), train=False)
    out_d = layer_d(params, x, jnp.asarray(add), train=False)
    np.testing.assert_allclose(np.asarray(out_f), np.asarray(out_d),
                               rtol=1e-4, atol=1e-4)
