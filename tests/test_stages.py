"""One fault plane (ISSUE 7): the shared async-stage runtime.

``runtime/stages.py`` is the abstraction the four hand-rolled async
subsystems (input prefetch, streamed offload uploads, the offload pull
watchdog, the async checkpoint writer) were ported onto.  Contracts
these tests pin:

  - the unified chaos spec: ``DS_STAGE_FAULT=stage:point:n[+]`` /
    ``DS_STAGE_DELAY_S=stage:sec`` arm every stage boundary, and the
    legacy per-subsystem env vars (``DS_CKPT_FAULT``,
    ``DS_PREFETCH_DELAY_S``, ``DS_OFFLOAD_H2D_DELAY_S``,
    ``DS_CKPT_DELAY_S``) keep working as aliases;
  - the chaos matrix: a TRANSIENT fault (n) at any stage is retried
    and training stays BITWISE identical to the fault-free run; a
    STICKY fault (n+) exhausts the stage's failure budget and the
    stage DEGRADES to its inline/serial equivalent — training runs to
    completion bitwise-equal to the serial leg, with exactly ONE loud
    warning and one ``stage_degraded_total`` tick per degraded stage;
  - THE drain order: ``engine.close()`` drains prefetch -> offload
    uploads -> disk write-back -> ckpt writer -> telemetry flush,
    idempotently, with everything mid-flight at once (satellite 1);
  - a StreamingUploader failure after ``close()``/``abort()`` began is
    surfaced through the stage record into ``engine.last_stage_error``
    instead of vanishing with the daemon thread (satellite 2);
  - primitives: Channel poison carries the ORIGINAL exception and
    queued items drain first; StageWorker restarts a crashed loop;
    WatchdogPool abandons a wedged worker and replaces it lazily;
    StageGraph never aborts mid-order and never raises.

Every potentially-blocking wait in this file is bounded by an explicit
watchdog (``_wait_until`` / timeouts), never by pytest's clock.
"""
import logging
import sys
import threading
import time

import numpy as np
import jax
import pytest

sys.path.insert(0, "tests")

from deepspeed_tpu.config import DeepSpeedConfig, DeepSpeedConfigError
from deepspeed_tpu.parallel import build_mesh
from deepspeed_tpu.runtime import offload as offload_mod
from deepspeed_tpu.runtime import resilience
from deepspeed_tpu.runtime.engine import DeepSpeedEngine
from deepspeed_tpu.runtime.offload import StreamingUploader
from deepspeed_tpu.runtime.prefetch import DevicePrefetcher
from deepspeed_tpu.runtime.stages import (
    Channel, InjectedStageFault, Stage, StageGraph, WatchdogPool,
    fault_point, injected_delay, reset_fault_injection, spawn)
from deepspeed_tpu.utils.logging import logger as ds_logger

from simple_model import SimpleModel, base_config, random_batches

HIDDEN = 16
#: bound for every blocking wait in this file (generous; CI is slow)
WATCHDOG_S = 30.0

_CHAOS_ENVS = ("DS_STAGE_FAULT", "DS_STAGE_DELAY_S", "DS_CKPT_FAULT",
               "DS_CKPT_DELAY_S", "DS_PREFETCH_DELAY_S",
               "DS_OFFLOAD_H2D_DELAY_S")


@pytest.fixture(autouse=True)
def _clean_faults(monkeypatch):
    for env in _CHAOS_ENVS:
        monkeypatch.delenv(env, raising=False)
    reset_fault_injection()
    yield
    reset_fault_injection()


@pytest.fixture
def ds_caplog(caplog, monkeypatch):
    """The project logger does not propagate; flip it so caplog sees
    stage warnings (same idiom as tests/test_offload_xla.py)."""
    monkeypatch.setattr(ds_logger, "propagate", True)
    with caplog.at_level(logging.WARNING, logger="DeepSpeedTPU"):
        yield caplog


def _wait_until(pred, timeout=WATCHDOG_S, msg="condition"):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if pred():
            return
        time.sleep(0.01)
    raise AssertionError(f"watchdog: {msg} not satisfied in {timeout}s")


def _degrade_warnings(caplog, stage_name):
    return [r for r in caplog.records
            if r.levelno == logging.WARNING
            and "DEGRADING" in r.getMessage()
            and f"stage '{stage_name}'" in r.getMessage()]


# ---------------------------------------------------------------------
# engine helpers (mirrors test_prefetch.py / test_offload_pipeline.py)
# ---------------------------------------------------------------------
def _dataset(n, seed=0):
    rng = np.random.default_rng(seed)
    xs = rng.standard_normal((n, HIDDEN)).astype(np.float32)
    return [(xs[i], 0.5 * xs[i]) for i in range(n)]


def _plain_engine(cfg_over=None, n_batches=4, seed=3):
    cfg = base_config(micro_bs=2, grad_acc=1)
    cfg.update(cfg_over or {})
    dscfg = DeepSpeedConfig(cfg, world_size=8)
    mesh = build_mesh()
    return DeepSpeedEngine(
        SimpleModel(hidden_dim=HIDDEN), dscfg, mesh=mesh, seed=seed,
        training_data=_dataset(dscfg.train_batch_size * n_batches))


def _offload_engine(cfg_over=None, pipeline=None, seed=0):
    cfg = base_config(micro_bs=4, grad_acc=1, stage=2)
    cfg["zero_optimization"].update({"cpu_offload": True,
                                     "offload_impl": "host"})
    if pipeline is not None:
        cfg["zero_optimization"]["offload_pipeline"] = pipeline
    cfg["steps_per_print"] = 10 ** 9
    cfg.update(cfg_over or {})
    dscfg = DeepSpeedConfig(cfg, world_size=1)
    mesh = build_mesh(dp=1, devices=jax.devices()[:1])
    return DeepSpeedEngine(SimpleModel(hidden_dim=HIDDEN), dscfg,
                           mesh=mesh, seed=seed)


def _train_loader(engine, steps):
    return [float(np.asarray(engine.train_batch())) for _ in range(steps)]


def _train_batches(engine, steps=4, seed=11):
    losses = []
    for b in random_batches(engine.train_batch_size, HIDDEN,
                            num_batches=steps, seed=seed):
        losses.append(float(np.asarray(engine.train_batch(b))))
    return losses


def _assert_state_bitwise(e_a, e_b):
    la, lb = jax.tree.leaves(e_a.state), jax.tree.leaves(e_b.state)
    assert len(la) == len(lb)
    for i, (x, y) in enumerate(zip(la, lb)):
        np.testing.assert_array_equal(np.asarray(x), np.asarray(y),
                                      err_msg=f"state leaf {i}")


def _assert_offload_state_bitwise(e_a, e_b):
    for name, (la, lb) in (
            ("master", (jax.tree.leaves(e_a.state.master_params),
                        jax.tree.leaves(e_b.state.master_params))),
            ("mu", (jax.tree.leaves(e_a.state.opt_state["mu"]),
                    jax.tree.leaves(e_b.state.opt_state["mu"]))),
            ("nu", (jax.tree.leaves(e_a.state.opt_state["nu"]),
                    jax.tree.leaves(e_b.state.opt_state["nu"]))),
            ("compute", (jax.tree.leaves(e_a._compute_params),
                         jax.tree.leaves(e_b._compute_params)))):
        assert len(la) == len(lb)
        for i, (x, y) in enumerate(zip(la, lb)):
            np.testing.assert_array_equal(
                np.asarray(x), np.asarray(y), err_msg=f"{name}[{i}]")


# ---------------------------------------------------------------------
# unified fault spec + back-compat aliases
# ---------------------------------------------------------------------
def test_fault_spec_nth_hit_transient(monkeypatch):
    monkeypatch.setenv("DS_STAGE_FAULT", "prefetch:place:2")
    fault_point("prefetch", "place")  # hit 1: armed for 2
    with pytest.raises(InjectedStageFault):
        fault_point("prefetch", "place")  # hit 2
    fault_point("prefetch", "place")  # hit 3: transient, re-armed never


def test_fault_spec_sticky(monkeypatch):
    monkeypatch.setenv("DS_STAGE_FAULT", "s:p:2+")
    fault_point("s", "p")
    for _ in range(3):
        with pytest.raises(InjectedStageFault):
            fault_point("s", "p")


def test_fault_spec_multi_and_malformed(monkeypatch):
    monkeypatch.setenv("DS_STAGE_FAULT", "a:b:1, garbage, c:d:1+,x:y")
    with pytest.raises(InjectedStageFault):
        fault_point("a", "b")
    with pytest.raises(InjectedStageFault):
        fault_point("c", "d")
    fault_point("x", "y")  # malformed entry ignored, never armed


def test_fault_injection_is_transient_class(monkeypatch):
    """The injected fault IS an OSError — the class every retry plane
    (io_retry, the stage budget) already treats as transient."""
    monkeypatch.setenv("DS_STAGE_FAULT", "s:p:1")
    with pytest.raises(OSError):
        fault_point("s", "p")


def test_reset_fault_injection(monkeypatch):
    monkeypatch.setenv("DS_STAGE_FAULT", "s:p:2")
    fault_point("s", "p")
    reset_fault_injection()
    fault_point("s", "p")  # counting restarted: this is hit 1 again
    with pytest.raises(InjectedStageFault):
        fault_point("s", "p")


def test_ckpt_fault_alias(monkeypatch):
    """DS_CKPT_FAULT=<point>:<n>[+] == stage ``ckpt`` in the unified
    spec, through BOTH the stages API and resilience's historical
    ``fault_point(point)`` wrapper."""
    monkeypatch.setenv("DS_CKPT_FAULT", "meta:1+")
    with pytest.raises(InjectedStageFault):
        fault_point("ckpt", "meta")
    with pytest.raises(OSError):
        resilience.fault_point("meta")


def test_unified_spec_wins_over_ckpt_alias(monkeypatch):
    monkeypatch.setenv("DS_STAGE_FAULT", "ckpt:meta:3")
    monkeypatch.setenv("DS_CKPT_FAULT", "meta:1+")
    fault_point("ckpt", "meta")  # unified n=3 wins: hits 1-2 pass
    fault_point("ckpt", "meta")
    with pytest.raises(InjectedStageFault):
        fault_point("ckpt", "meta")


def test_delay_aliases(monkeypatch):
    monkeypatch.setenv("DS_PREFETCH_DELAY_S", "0.25")
    monkeypatch.setenv("DS_OFFLOAD_H2D_DELAY_S", "0.5")
    monkeypatch.setenv("DS_CKPT_DELAY_S", "0.75")
    assert injected_delay("prefetch") == 0.25
    assert injected_delay("offload_h2d") == 0.5
    assert injected_delay("ckpt") == 0.75
    assert injected_delay("other") == 0.0
    # the unified spec wins over a legacy alias for the same stage
    monkeypatch.setenv("DS_STAGE_DELAY_S", "prefetch:0.1,offload_pull:1.5")
    assert injected_delay("prefetch") == 0.1
    assert injected_delay("offload_pull") == 1.5
    assert injected_delay("offload_h2d") == 0.5


# ---------------------------------------------------------------------
# Channel
# ---------------------------------------------------------------------
def test_channel_fifo_and_bound():
    ch = Channel(2)
    assert ch.put(1) and ch.put(2)
    third_in = threading.Event()

    def producer():
        ch.put(3)
        third_in.set()

    t = threading.Thread(target=producer, daemon=True)
    t.start()
    time.sleep(0.05)
    assert not third_in.is_set()  # bounded: parked at capacity
    assert ch.get(timeout=WATCHDOG_S) == 1
    _wait_until(third_in.is_set, msg="bounded put released by a get")
    assert ch.get(timeout=WATCHDOG_S) == 2
    assert ch.get(timeout=WATCHDOG_S) == 3
    t.join(WATCHDOG_S)


def test_channel_poison_drains_queued_first():
    ch = Channel(4)
    ch.put("before")
    err = ValueError("original")
    ch.poison(err)
    assert ch.get(timeout=WATCHDOG_S) == "before"
    for _ in range(2):  # re-raises the ORIGINAL object, repeatedly
        with pytest.raises(ValueError) as ei:
            ch.get(timeout=WATCHDOG_S)
        assert ei.value is err


def test_channel_close_drops_and_releases():
    ch = Channel(2)
    ch.put(1)
    ch.close()
    assert ch.qsize() == 0  # queued items dropped
    assert ch.put(2) is False  # producer told to stop
    with pytest.raises(RuntimeError):
        ch.get(timeout=WATCHDOG_S)
    with pytest.raises(TimeoutError):
        Channel(1).get(timeout=0.05)


def test_channel_poison_releases_parked_producer():
    """The producer side of the documented poison contract: a consumer-
    side poison must release a producer parked on a full channel (nobody
    will ever drain it again) and put() must report stop."""
    ch = Channel(1)
    assert ch.put(1)
    stopped, result = threading.Event(), {}

    def producer():
        result["ok"] = ch.put(2)
        stopped.set()

    t = threading.Thread(target=producer, daemon=True)
    t.start()
    time.sleep(0.05)
    assert not stopped.is_set()  # parked at capacity
    ch.poison(ValueError("downstream died"))
    _wait_until(stopped.is_set, msg="poison released the parked producer")
    assert result["ok"] is False
    assert ch.wait_space() is False  # and wait_space agrees
    t.join(WATCHDOG_S)


# ---------------------------------------------------------------------
# StageWorker: restart-on-crash
# ---------------------------------------------------------------------
def test_stage_worker_restarts_crashed_loop():
    done = threading.Event()
    attempts = []

    def loop():
        attempts.append(1)
        if len(attempts) == 1:
            raise RuntimeError("boom")
        done.set()

    spawn(loop, name="t-restart", restarts=1)
    _wait_until(done.is_set, msg="restarted loop ran")
    assert len(attempts) == 2


def test_stage_worker_dies_after_budget():
    def loop():
        raise RuntimeError("always")

    w = spawn(loop, name="t-dies", restarts=1)
    _wait_until(lambda: not w.is_alive(), msg="worker died")


# ---------------------------------------------------------------------
# Stage: budget, degradation, surfaced errors
# ---------------------------------------------------------------------
def test_stage_call_retries_transient_then_succeeds():
    st = Stage("s", max_failures=3)
    calls = []

    def fn():
        calls.append(1)
        if len(calls) < 3:
            raise OSError("transient blip")
        return 7

    assert st.call("pt", fn) == 7
    assert len(calls) == 3
    assert st.failures == 2 and not st.degraded
    # the budget is CONSECUTIVE: the success above reset it
    calls.clear()
    assert st.call("pt", fn) == 7
    assert not st.degraded


def test_stage_retry_is_backed_off():
    """Transient retries are SPACED (doubling from
    RETRY_BACKOFF_BASE_S): a real blip microseconds long must not burn
    the whole budget inside its own window and permanently degrade the
    stage."""
    from deepspeed_tpu.runtime.stages import RETRY_BACKOFF_BASE_S
    st = Stage("s", max_failures=3)
    calls = []

    def fn():
        calls.append(time.monotonic())
        if len(calls) < 3:
            raise OSError("blip")
        return "ok"

    t0 = time.monotonic()
    assert st.call("pt", fn) == "ok"
    # two retries -> base + 2*base of sleep between the three attempts
    assert time.monotonic() - t0 >= 3 * RETRY_BACKOFF_BASE_S - 0.01
    assert calls[1] - calls[0] >= RETRY_BACKOFF_BASE_S - 0.01
    assert calls[2] - calls[1] >= 2 * RETRY_BACKOFF_BASE_S - 0.01


def test_shared_stage_sibling_success_cannot_starve_budget():
    """Two workers share one Stage record (the engine threads ONE
    'prefetch' Stage through the train AND eval prefetchers): a
    sibling's interleaved successes reset the shared consecutive
    counter, but a persistently failing call-site must still exhaust
    the budget from its OWN attempt count — never retry unbounded
    (an unbounded watchdog-free wait for its consumer)."""
    st = Stage("prefetch", max_failures=3)
    calls = {"n": 0}

    def failing():
        calls["n"] += 1
        st.note_ok()  # the sibling worker's interleaved success
        raise OSError("persistent")

    with pytest.raises(OSError):
        # 3 in-budget attempts, then the degraded-inline run fails too
        # and the real error propagates (poison contract)
        st.call("place", failing)
    assert st.degraded
    assert calls["n"] == 4  # bounded: budget + one inline run


def test_stage_degrades_after_budget_one_warning(monkeypatch, ds_caplog):
    monkeypatch.setenv("DS_STAGE_FAULT", "s:pt:1+")
    counts = {}
    st = Stage("s", max_failures=3, fallback="the inline path")
    st.counter_fn = lambda name, help, n: counts.__setitem__(
        name, counts.get(name, 0) + n)
    # sticky injection: 3 transient hits exhaust the budget, then the
    # work runs OUTSIDE the injection plane and succeeds
    assert st.call("pt", lambda: "ok") == "ok"
    assert st.degraded
    assert counts["stage_failures_total"] == 3
    assert counts["stage_degraded_total"] == 1
    assert len(_degrade_warnings(ds_caplog, "s")) == 1
    # degraded: later calls bypass injection entirely, no new warnings
    assert st.call("pt", lambda: "again") == "again"
    assert counts["stage_degraded_total"] == 1
    assert len(_degrade_warnings(ds_caplog, "s")) == 1


def test_stage_degraded_still_surfaces_real_errors(monkeypatch):
    """A genuinely broken resource must not be masked by degradation:
    the fallback call runs outside the injection plane but its REAL
    exception propagates."""
    monkeypatch.setenv("DS_STAGE_FAULT", "s:pt:1+")
    st = Stage("s", max_failures=1)

    def broken():
        raise OSError("the disk is really gone")

    with pytest.raises(OSError, match="really gone"):
        st.call("pt", broken)
    assert st.degraded


def test_stage_non_transient_propagates_untouched():
    st = Stage("s", max_failures=3)
    err = ValueError("subsystem poison path")
    calls = []

    def fn():
        calls.append(1)
        raise err

    with pytest.raises(ValueError) as ei:
        st.call("pt", fn)
    assert ei.value is err
    assert len(calls) == 1  # no retry: not the runtime's to absorb
    assert st.failures == 0 and not st.degraded


def test_stage_degradation_disabled_raises(monkeypatch):
    monkeypatch.setenv("DS_STAGE_FAULT", "s:pt:1+")
    st = Stage("s", max_failures=2, allow_degraded=False)
    with pytest.raises(InjectedStageFault):
        st.call("pt", lambda: "never")
    assert not st.degraded and st.failures == 2


def test_stage_surface_and_pop():
    counts = {}
    st = Stage("s")
    st.counter_fn = lambda name, help, n: counts.__setitem__(
        name, counts.get(name, 0) + n)
    err = OSError("post-close failure")
    st.surface(err)
    assert st.pop_error() is err
    assert st.pop_error() is None
    assert counts["stage_errors_total"] == 1


def test_stage_broken_counter_hook_never_breaks_stage():
    st = Stage("s")
    st.counter_fn = lambda *a: (_ for _ in ()).throw(RuntimeError("hook"))
    st.surface(OSError("x"))  # must not raise
    assert isinstance(st.pop_error(), OSError)


# ---------------------------------------------------------------------
# WatchdogPool: abandon-and-replace
# ---------------------------------------------------------------------
def test_watchdog_pool_roundtrip_and_persistence():
    pool = WatchdogPool("t-pool")
    assert pool.call(lambda: 42, timeout_s=WATCHDOG_S, what="job") == 42
    first = pool.worker
    assert pool.call(lambda: 43, timeout_s=WATCHDOG_S, what="job") == 43
    assert pool.worker is first  # ONE persistent worker across calls
    pool.stop()


def test_watchdog_pool_timeout_abandons_and_replaces():
    pool = WatchdogPool("t-pool")
    wedge = threading.Event()
    with pytest.raises(RuntimeError, match="wedged"):
        pool.call(lambda: wedge.wait(WATCHDOG_S), timeout_s=0.1,
                  what="stalled pull")
    wedged_worker = pool.worker
    assert wedged_worker is None  # abandoned: next call starts fresh
    assert pool.call(lambda: 1, timeout_s=WATCHDOG_S, what="job") == 1
    wedge.set()  # let the abandoned worker's thread exit
    pool.stop()


def test_watchdog_pool_custom_timeout_message():
    pool = WatchdogPool("t-pool")
    ev = threading.Event()
    with pytest.raises(RuntimeError, match="custom diagnosis"):
        pool.call(lambda: ev.wait(WATCHDOG_S), timeout_s=0.1, what="x",
                  timeout_msg="custom diagnosis")
    ev.set()
    pool.stop()


def test_watchdog_pool_error_propagates():
    pool = WatchdogPool("t-pool")
    with pytest.raises(ValueError, match="inner"):
        pool.call(lambda: (_ for _ in ()).throw(ValueError("inner")),
                  timeout_s=WATCHDOG_S, what="job")
    pool.stop()


def test_offload_pull_chaos_boundary(monkeypatch):
    """The D2H pull watchdog rides the unified spec: an injected fault
    surfaces as the transient OSError class; an injected delay trips the
    real watchdog timeout (abandon-and-replace), not a hang."""
    x = jax.device_put(np.arange(8, dtype=np.float32))
    monkeypatch.setenv("DS_STAGE_FAULT", "offload_pull:pull:1")
    with pytest.raises(InjectedStageFault):
        offload_mod._watchdog_get(x, timeout_s=WATCHDOG_S)
    out = offload_mod._watchdog_get(x, timeout_s=WATCHDOG_S)  # recovered
    np.testing.assert_array_equal(out, np.arange(8, dtype=np.float32))
    monkeypatch.delenv("DS_STAGE_FAULT")
    monkeypatch.setenv("DS_STAGE_DELAY_S", "offload_pull:5")
    with pytest.raises(RuntimeError, match="did not complete"):
        offload_mod._watchdog_get(x, timeout_s=0.2)


# ---------------------------------------------------------------------
# StageGraph
# ---------------------------------------------------------------------
def test_stage_graph_order_and_error_collection():
    g = StageGraph()
    ran = []
    g.register("a", close=lambda: ran.append("a"))
    g.register("b", close=lambda: (_ for _ in ()).throw(OSError("mid")))
    g.register("c", close=lambda: ran.append("c"))
    errors = g.close_all()
    assert ran == ["a", "c"]  # never aborts mid-order
    assert [(n, type(e)) for n, e in errors] == [("b", OSError)]
    assert g.order == ["a", "b", "c"]


def test_stage_graph_drain_prefers_drain_fn():
    g = StageGraph()
    ran = []
    g.register("a", close=lambda: ran.append("a-close"),
               drain=lambda: ran.append("a-drain"))
    g.register("b", close=lambda: ran.append("b-close"))
    assert g.drain_all() == []
    assert ran == ["a-drain", "b-close"]  # drain falls back to close


def test_engine_graph_registers_the_documented_order():
    eng = _plain_engine()
    try:
        assert eng._stage_graph.order == [
            "prefetch", "offload_uploads", "disk_writeback",
            "ckpt_writer", "telemetry"]
    finally:
        eng.close()


# ---------------------------------------------------------------------
# chaos matrix: prefetch
# ---------------------------------------------------------------------
def test_prefetch_transient_fault_bitwise(monkeypatch):
    """A transient placement fault is retried against the SAME drawn
    batch: losses and state stay bitwise-identical to the fault-free
    run, and the stage never degrades."""
    e_ref = _plain_engine()
    l_ref = _train_loader(e_ref, 4)
    reset_fault_injection()
    monkeypatch.setenv("DS_STAGE_FAULT", "prefetch:place:2")
    e_chaos = _plain_engine()
    l_chaos = _train_loader(e_chaos, 4)
    assert l_chaos == l_ref
    _assert_state_bitwise(e_chaos, e_ref)
    st = e_chaos._stage_records["prefetch"]
    assert st.failures == 1 and not st.degraded
    e_ref.close()
    e_chaos.close()


def test_prefetch_sticky_fault_degrades_bitwise(monkeypatch, ds_caplog,
                                                tmp_path):
    """The degradation proof (acceptance): a sticky placement fault
    exhausts the budget and prefetch falls back to inline iteration —
    training completes bitwise-equal to the DS_PREFETCH=0 leg, with
    exactly one warning and one stage_degraded_total tick."""
    monkeypatch.setenv("DS_PREFETCH", "0")
    e_ref = _plain_engine()
    l_ref = _train_loader(e_ref, 4)
    monkeypatch.delenv("DS_PREFETCH")
    reset_fault_injection()
    monkeypatch.setenv("DS_STAGE_FAULT", "prefetch:place:1+")
    e_chaos = _plain_engine(
        cfg_over={"telemetry": {"enabled": True,
                                "output_path": str(tmp_path)}})
    l_chaos = _train_loader(e_chaos, 4)
    assert l_chaos == l_ref
    _assert_state_bitwise(e_chaos, e_ref)
    st = e_chaos._stage_records["prefetch"]
    assert st.degraded and st.failures == 3
    assert len(_degrade_warnings(ds_caplog, "prefetch")) == 1
    assert e_chaos.telemetry.registry.counter(
        "stage_degraded_total").value() == 1
    assert e_chaos.telemetry.registry.counter(
        "stage_failures_total").value() == 3
    e_ref.close()
    e_chaos.close()


def test_degraded_inline_failure_keeps_poison_contract():
    """Inline (degraded) iteration honors the SAME poison contract as
    the async path: a placement failure re-raises on every later next —
    a retrying caller must not silently skip the batch the failure
    consumed (sample-exactness)."""
    st = Stage("prefetch")
    st.degraded = True  # pre-degraded: hand-off happens immediately

    def place(b):
        if b == 1:
            raise ValueError("inline place died")
        return b

    pf = DevicePrefetcher(iter(range(3)), place_fn=place, stage=st)
    assert next(pf) == 0
    with pytest.raises(ValueError) as ei:
        next(pf)
    with pytest.raises(ValueError) as ei2:
        next(pf)  # the ORIGINAL error again — batch 2 is never served
    assert ei2.value is ei.value
    pf.close()


def test_worker_escape_poisons_instead_of_stranding(monkeypatch):
    """An exception ESCAPING the produce loop (outside the draw/place
    try blocks) poisons the channel: with restarts=0 a silently dead
    worker would otherwise strand the consumer forever."""
    monkeypatch.setattr(
        DevicePrefetcher, "_produce",
        lambda self: (_ for _ in ()).throw(MemoryError("worker oom")))
    pf = DevicePrefetcher(iter(range(2)), place_fn=lambda b: b)
    with pytest.raises(MemoryError, match="worker oom"):
        next(pf)
    pf.close()


def test_prefetch_custom_budget(monkeypatch):
    """stages.max_stage_failures=1 degrades on the FIRST transient
    failure — the config knob reaches the engine's stage records."""
    monkeypatch.setenv("DS_STAGE_FAULT", "prefetch:place:1+")
    eng = _plain_engine(cfg_over={"stages": {"max_stage_failures": 1}})
    assert eng._stage_records["prefetch"].max_failures == 1
    _train_loader(eng, 2)
    st = eng._stage_records["prefetch"]
    assert st.degraded and st.failures == 1
    eng.close()


# ---------------------------------------------------------------------
# chaos matrix: streamed offload uploads
# ---------------------------------------------------------------------
def test_offload_transient_fault_bitwise(monkeypatch):
    e_ref = _offload_engine(pipeline=True)
    l_ref = _train_batches(e_ref, 4)
    reset_fault_injection()
    monkeypatch.setenv("DS_STAGE_FAULT", "offload_h2d:put:2")
    e_chaos = _offload_engine(pipeline=True)
    l_chaos = _train_batches(e_chaos, 4)
    assert l_chaos == l_ref
    _assert_offload_state_bitwise(e_chaos, e_ref)
    st = e_chaos._stage_records["offload_h2d"]
    assert st.failures == 1 and not st.degraded
    e_ref.close()
    e_chaos.close()


def test_offload_sticky_fault_degrades_bitwise(monkeypatch, ds_caplog,
                                               tmp_path):
    """Sticky upload faults degrade the offload_h2d stage: the step in
    flight completes inline (no half-swapped tree), and every later
    step takes the serial update path — bitwise-equal to the
    offload_pipeline=False leg, one warning, one counter tick."""
    e_ref = _offload_engine(pipeline=False)
    l_ref = _train_batches(e_ref, 4)
    reset_fault_injection()
    monkeypatch.setenv("DS_STAGE_FAULT", "offload_h2d:put:1+")
    e_chaos = _offload_engine(
        pipeline=True,
        cfg_over={"telemetry": {"enabled": True,
                                "output_path": str(tmp_path)}})
    l_chaos = _train_batches(e_chaos, 4)
    assert l_chaos == l_ref
    _assert_offload_state_bitwise(e_chaos, e_ref)
    st = e_chaos._stage_records["offload_h2d"]
    assert st.degraded and st.failures == 3
    assert len(_degrade_warnings(ds_caplog, "offload_h2d")) == 1
    assert e_chaos.telemetry.registry.counter(
        "stage_degraded_total").value() == 1
    e_ref.close()
    e_chaos.close()


# ---------------------------------------------------------------------
# chaos matrix: async checkpoint writer
# ---------------------------------------------------------------------
def test_ckpt_writer_sticky_fault_degrades_to_sync(
        monkeypatch, tmp_path, ds_caplog):
    """Sticky writer faults fail each async save (surfaced, training
    continues); exhausting the budget degrades the stage and a save
    requested async runs SYNC — and succeeds, because the fallback is
    the path that never had the async machinery."""
    monkeypatch.setenv("DS_STAGE_FAULT", "ckpt_writer:job:1+")
    eng = _offload_engine(
        cfg_over={"telemetry": {"enabled": True,
                                "output_path": str(tmp_path / "tel")}})
    _train_batches(eng, 1)
    for i in range(3):
        eng.save_checkpoint(str(tmp_path), tag=f"doomed{i}",
                            async_write=True)
        err = eng._ckpt_writer.drain(timeout=WATCHDOG_S)
        assert isinstance(err, InjectedStageFault)
    st = eng._stage_records["ckpt_writer"]
    assert st.degraded and st.failures == 3
    assert len(_degrade_warnings(ds_caplog, "ckpt_writer")) == 1
    assert eng.telemetry.registry.counter(
        "stage_degraded_total").value() == 1
    # degraded: async_write=True is honored as a sync save, which lands
    eng.save_checkpoint(str(tmp_path), tag="ok", async_write=True)
    assert eng._ckpt_writer.pop_error() is None
    eng2 = _offload_engine()
    path, _ = eng2.load_checkpoint(str(tmp_path), tag="ok")
    assert path is not None
    eng.close()
    eng2.close()


def test_ckpt_write_point_via_unified_spec(monkeypatch, tmp_path):
    """The checkpoint write points answer to the unified spec
    (DS_STAGE_FAULT=ckpt:<point>:<n>), and a transient hit rides the
    existing io_retry plane — the save still lands."""
    monkeypatch.setenv("DS_STAGE_FAULT", "ckpt:meta:1")
    eng = _offload_engine()
    _train_batches(eng, 1)
    eng.save_checkpoint(str(tmp_path), tag="t", async_write=False)
    eng2 = _offload_engine()
    path, _ = eng2.load_checkpoint(str(tmp_path), tag="t")
    assert path is not None
    eng.close()
    eng2.close()


# ---------------------------------------------------------------------
# satellite 1: THE drain order, mid-flight on everything at once
# ---------------------------------------------------------------------
def test_engine_close_drain_order_mid_flight(monkeypatch, tmp_path):
    """One close() call drains all four subsystems in THE documented
    order with everything in flight at once: a running prefetcher, a
    submitted async save still writing (injected latency), live
    telemetry.  The order is observed by wrapping the stage-graph
    entries; the save must LAND (not be dropped), the prefetcher must
    be closed, telemetry must flush, and a second close() is a no-op."""
    from deepspeed_tpu.runtime import engine_stages

    order = []
    for fn_name in ("close_prefetch_stage", "close_upload_stage",
                    "close_ckpt_stage", "close_telemetry_stage"):
        real = getattr(engine_stages, fn_name)

        def wrapped(engine, _real=real, _name=fn_name):
            order.append(_name)
            return _real(engine)

        monkeypatch.setattr(engine_stages, fn_name, wrapped)

    eng = _plain_engine(cfg_over={
        "telemetry": {"enabled": True, "output_path": str(tmp_path)}})
    it = eng._training_iter()
    assert isinstance(it, DevicePrefetcher)
    next(it)  # worker live, queue filling
    monkeypatch.setenv("DS_CKPT_DELAY_S", "0.3")
    eng.save_checkpoint(str(tmp_path / "ck"), tag="mid",
                        async_write=True)  # in flight at close time
    t0 = time.monotonic()
    eng.close()
    assert time.monotonic() - t0 < WATCHDOG_S  # drained, not hung
    assert order == ["close_prefetch_stage", "close_upload_stage",
                     "close_ckpt_stage", "close_telemetry_stage"]
    assert it.closed
    assert eng.last_ckpt_error is None
    # the in-flight save landed before telemetry flushed
    eng2 = _plain_engine()
    path, _ = eng2.load_checkpoint(str(tmp_path / "ck"), tag="mid")
    assert path is not None
    eng2.close()
    assert (tmp_path / "metrics.prom").exists()
    order.clear()
    eng.close()  # idempotent: runs the same order, nothing raises
    assert order == ["close_prefetch_stage", "close_upload_stage",
                     "close_ckpt_stage", "close_telemetry_stage"]


def test_close_pops_errors_surfaced_during_drain(monkeypatch):
    """A stage failure surfaced DURING the close drain (after the ckpt
    tick already ran) still lands on the engine: finish_close pops the
    records — there is no later pre-step tick to do it."""
    eng = _plain_engine()
    monkeypatch.setattr(eng, "_ckpt_writer_tick", lambda: None)
    err = OSError("upload died while close was draining")
    eng._stage_records["offload_h2d"].surface(err)
    eng.close()
    assert eng.last_stage_error is err
    assert err in eng.stage_errors


def test_close_failure_surfaces_and_still_drains(monkeypatch):
    """A close-time failure (telemetry flush dying) never aborts the
    drain mid-order: earlier stages still close, the error lands in
    stage_errors/last_stage_error, and close() re-raises it so an
    explicit caller sees the shutdown was not clean."""
    eng = _plain_engine()
    it = eng._training_iter()
    next(it)
    boom = OSError("disk full during trace export")
    monkeypatch.setattr(eng, "_flush_tensorboard",
                        lambda: (_ for _ in ()).throw(boom))
    with pytest.raises(OSError) as ei:
        eng.close()
    assert ei.value is boom
    assert it.closed  # the prefetch stage, earlier in THE order, drained
    assert eng.last_stage_error is boom
    assert boom in eng.stage_errors


def test_drain_stages_is_a_barrier_not_a_teardown(monkeypatch, tmp_path):
    """engine.drain_stages() waits out in-flight work (the sync-save /
    elastic-restart barrier) WITHOUT closing anything: the writer takes
    another save afterwards and the prefetcher keeps producing."""
    eng = _plain_engine()
    it = eng._training_iter()
    next(it)
    monkeypatch.setenv("DS_CKPT_DELAY_S", "0.2")
    eng.save_checkpoint(str(tmp_path), tag="a", async_write=True)
    assert eng.drain_stages() == []
    assert not eng._ckpt_writer.in_flight()
    monkeypatch.delenv("DS_CKPT_DELAY_S")
    eng.save_checkpoint(str(tmp_path), tag="b", async_write=True)
    assert eng._ckpt_writer.drain(timeout=WATCHDOG_S) is None
    next(it)  # prefetcher survived the drain
    eng.close()


# ---------------------------------------------------------------------
# satellite 2: upload failure after close()/abort() is surfaced
# ---------------------------------------------------------------------
def test_upload_failure_after_abort_surfaces():
    """offload.py used to drop an upload failure on the floor when it
    landed after abort() (nobody calls finish() then): now it routes
    through the stage record like last_ckpt_error does."""
    st = Stage("offload_h2d")
    started, release = threading.Event(), threading.Event()

    def put(idx, arr):
        started.set()
        release.wait(WATCHDOG_S)
        raise ValueError("in-flight transfer died")  # non-transient

    up = StreamingUploader(put, stage=st)
    up.submit(0, np.zeros(4))
    _wait_until(started.is_set, msg="worker entered the put")
    up.abort()  # close began; finish() will never run
    release.set()
    _wait_until(lambda: st.pop_error() is not None,
                msg="post-abort failure surfaced through the stage")
    time.sleep(0.1)  # a racing abort-side surface() would re-arm it
    assert st.pop_error() is None  # surfaced exactly once


def test_upload_failure_recorded_before_abort_surfaces():
    """The other arm: the worker already recorded the failure when
    abort() arrives — abort surfaces it instead of clearing it, and the
    worker/abort pair surfaces it exactly ONCE (the counter is the
    surfaced-error metric; a race must not double it)."""
    counts = {}
    st = Stage("offload_h2d")
    st.counter_fn = lambda name, help, n: counts.__setitem__(
        name, counts.get(name, 0) + n)
    failed = threading.Event()

    def put(idx, arr):
        failed.set()
        raise ValueError("upload died before abort")

    up = StreamingUploader(put, stage=st)
    up.submit(0, np.zeros(4))
    _wait_until(failed.is_set, msg="worker failed")
    _wait_until(lambda: up._err is not None, msg="failure recorded")
    up.abort()
    err = st.pop_error()
    assert isinstance(err, ValueError)
    time.sleep(0.1)  # give a racing second surface() the chance to run
    assert st.pop_error() is None
    assert counts["stage_errors_total"] == 1


def test_finish_claims_error_abort_does_not_double_report():
    """finish() re-raising a recorded failure claims it under the
    exactly-once flag: an abort() racing in afterwards (the engine's
    close path following the step failure) must NOT also surface it
    through the stage record — one failure, one report."""
    st = Stage("offload_h2d")
    failed = threading.Event()

    def put(idx, arr):
        failed.set()
        raise ValueError("upload died")

    up = StreamingUploader(put, stage=st)
    up.submit(0, np.zeros(4))
    _wait_until(failed.is_set, msg="worker failed")
    with pytest.raises(ValueError):
        up.finish()
    up.abort()
    time.sleep(0.1)  # a racing abort-side surface() would re-arm it
    assert st.pop_error() is None  # finish()'s re-raise WAS the report


def test_finish_after_concurrent_abort_raises_not_partial():
    """finish() racing a concurrent abort() (engine.close() from another
    thread/signal handler mid-step) must raise UploadAborted — NOT
    return a partial results dict, which would escape the engine's
    poison path through a bare assert and publish a half-uploaded
    step."""
    from deepspeed_tpu.runtime.offload import UploadAborted
    st = Stage("offload_h2d")
    started, release = threading.Event(), threading.Event()

    def put(idx, arr):
        started.set()
        release.wait(WATCHDOG_S)
        return arr

    up = StreamingUploader(put, stage=st)
    up.submit(0, np.zeros(4))
    up.submit(1, np.zeros(4))  # queued behind the blocked put: dropped
    _wait_until(started.is_set, msg="worker entered the put")
    up.abort()  # the close landed mid-step
    release.set()
    with pytest.raises(UploadAborted):
        up.finish()
    assert st.pop_error() is None  # no failure — just an abort


def test_surfaced_stage_error_lands_on_engine_tick():
    """pop_stage_errors: the pre-step tick moves a surfaced stage
    failure into engine.last_stage_error — the training thread's
    advertised surface, like last_ckpt_error."""
    eng = _plain_engine()
    assert eng.last_stage_error is None
    err = OSError("post-close upload failure")
    eng._stage_records["offload_h2d"].surface(err)
    eng._ckpt_writer_tick()
    assert eng.last_stage_error is err
    # several stages surfacing between two ticks must ALL be retained
    # (last_stage_error carries the newest; stage_errors keeps every one)
    err_a = OSError("prefetch post-close failure")
    err_b = OSError("upload post-close failure")
    eng._stage_records["prefetch"].surface(err_a)
    eng._stage_records["offload_h2d"].surface(err_b)
    eng._ckpt_writer_tick()
    assert set(eng.stage_errors) >= {err, err_a, err_b}
    assert eng.last_stage_error in (err_a, err_b)
    eng.close()


# ---------------------------------------------------------------------
# config block
# ---------------------------------------------------------------------
def test_stages_config_default_and_custom():
    cfg = DeepSpeedConfig(base_config(), world_size=8)
    assert cfg.stages_config.max_stage_failures == 3
    cfg = DeepSpeedConfig(
        base_config(stages={"max_stage_failures": 5}), world_size=8)
    assert cfg.stages_config.max_stage_failures == 5


@pytest.mark.parametrize("bad", [0, -1, "3", True, 2.5, None])
def test_stages_config_rejects_bad_budget(bad):
    with pytest.raises(DeepSpeedConfigError, match="max_stage_failures"):
        DeepSpeedConfig(base_config(stages={"max_stage_failures": bad}),
                        world_size=8)
