"""Streaming offload update pipeline (ZeRO-Offload overlap, H2D half).

The serial host tier only overlapped the D2H direction: grads prefetch
under the C++ Adam, but every updated leaf's re-upload waited for the
WHOLE CPU step.  The pipeline streams each leaf's H2D the moment its
block is written (``on_leaf`` → ``StreamingUploader``), so while Adam
updates leaf i, leaf i+1's grad pull and leaf i-1's upload are both in
flight.  Contracts these tests pin:

  - bitwise equivalence with the serial path (DS_OFFLOAD_PIPELINE=0),
    master + moments + uploaded compute params, both tiers, with and
    without DPU;
  - a mid-pipeline upload failure poisons the optimizer and leaves
    ``_compute_params`` fully intact (never half-swapped);
  - real concurrency, proven from tracer timestamps with injected
    transfer delays: the H2D span for leaf i-1 overlaps the CPU-Adam
    span for leaf i.
"""
import importlib.util
import os
import sys
import threading
import time

import jax
import jax.numpy as jnp
import numpy as np
import pytest

sys.path.insert(0, "tests")

import deepspeed_tpu.runtime.offload as offload
from deepspeed_tpu.config import DeepSpeedConfig, DeepSpeedConfigError
from deepspeed_tpu.runtime.engine import DeepSpeedEngine
from deepspeed_tpu.runtime.offload import (ShardedHostOffloadOptimizer,
                                           StreamingUploader)
from deepspeed_tpu.telemetry.tracing import TraceRecorder

from simple_model import SimpleModel, base_config, random_batches


def _dp1_mesh():
    from deepspeed_tpu.parallel import build_mesh
    return build_mesh(dp=1, devices=jax.devices()[:1])


def _cfg(pipeline=None, dpu=False, micro_bs=4, grad_acc=1, world_size=1):
    cfg = base_config(micro_bs=micro_bs, grad_acc=grad_acc, stage=2)
    cfg["zero_optimization"].update({"cpu_offload": True,
                                     "offload_impl": "host",
                                     "delayed_param_update": dpu})
    if pipeline is not None:
        cfg["zero_optimization"]["offload_pipeline"] = pipeline
    cfg["steps_per_print"] = 10 ** 9
    return DeepSpeedConfig(cfg, world_size=world_size)


def _train(engine, steps=4, hidden=16, seed=11):
    losses = []
    for b in random_batches(engine.train_batch_size, hidden,
                            num_batches=steps, seed=seed):
        losses.append(float(np.asarray(engine.train_batch(b))))
    return losses


def _assert_state_bitwise(e_a, e_b):
    for name, (la, lb) in (
            ("master", (jax.tree.leaves(e_a.state.master_params),
                        jax.tree.leaves(e_b.state.master_params))),
            ("mu", (jax.tree.leaves(e_a.state.opt_state["mu"]),
                    jax.tree.leaves(e_b.state.opt_state["mu"]))),
            ("nu", (jax.tree.leaves(e_a.state.opt_state["nu"]),
                    jax.tree.leaves(e_b.state.opt_state["nu"])))):
        assert len(la) == len(lb)
        for i, (x, y) in enumerate(zip(la, lb)):
            np.testing.assert_array_equal(
                np.asarray(x), np.asarray(y), err_msg=f"{name}[{i}]")
    ca = jax.tree.leaves(e_a._compute_params)
    cb = jax.tree.leaves(e_b._compute_params)
    assert len(ca) == len(cb)
    for i, (x, y) in enumerate(zip(ca, cb)):
        assert x.dtype == y.dtype, f"compute[{i}] dtype"
        np.testing.assert_array_equal(
            np.asarray(x), np.asarray(y), err_msg=f"compute_params[{i}]")


# ---------------------------------------------------------------------
# bitwise equivalence: pipelined vs serial (DS_OFFLOAD_PIPELINE=0)
# ---------------------------------------------------------------------
@pytest.mark.parametrize("dpu", [False, True])
def test_pipelined_bitwise_equals_serial(dpu, monkeypatch):
    """The acceptance contract: identical master, moments, AND uploaded
    compute params after N steps — the env escape hatch IS the serial
    reference (so it is exercised too).  DPU composes: the flush during
    step t+1's dispatch window streams the same bytes."""
    mesh_devs = jax.devices()[:1]
    from deepspeed_tpu.parallel import build_mesh
    monkeypatch.delenv("DS_OFFLOAD_PIPELINE", raising=False)
    e_pipe = DeepSpeedEngine(SimpleModel(hidden_dim=16), _cfg(dpu=dpu),
                             mesh=build_mesh(dp=1, devices=mesh_devs),
                             seed=3)
    assert e_pipe._offload_pipeline
    monkeypatch.setenv("DS_OFFLOAD_PIPELINE", "0")
    e_ser = DeepSpeedEngine(SimpleModel(hidden_dim=16), _cfg(dpu=dpu),
                            mesh=build_mesh(dp=1, devices=mesh_devs),
                            seed=3)
    assert not e_ser._offload_pipeline
    monkeypatch.delenv("DS_OFFLOAD_PIPELINE")
    l_pipe = _train(e_pipe)
    l_ser = _train(e_ser)
    assert l_pipe == l_ser
    if dpu:  # compare the fully-applied state
        e_pipe._dpu_flush()
        e_ser._dpu_flush()
    _assert_state_bitwise(e_pipe, e_ser)


def test_pipelined_bitwise_dp8():
    """dp=8 single-process (replicated-compute host tier): the per-leaf
    uploads target the real compute shardings."""
    e_pipe = DeepSpeedEngine(SimpleModel(hidden_dim=16),
                             _cfg(pipeline=True, world_size=8), seed=5)
    e_ser = DeepSpeedEngine(SimpleModel(hidden_dim=16),
                            _cfg(pipeline=False, world_size=8), seed=5)
    assert e_pipe._offload_pipeline and not e_ser._offload_pipeline
    l_pipe = _train(e_pipe, steps=3)
    l_ser = _train(e_ser, steps=3)
    assert l_pipe == l_ser
    _assert_state_bitwise(e_pipe, e_ser)


def _sharded_fixture():
    from jax.sharding import Mesh, NamedSharding, PartitionSpec as P
    devs = jax.devices()
    mesh = Mesh(np.array(devs), ("data",))
    master = {
        "w": jax.device_put(
            np.arange(64, dtype=np.float32).reshape(16, 4) * 0.1,
            NamedSharding(mesh, P("data", None))),
        "b": jax.device_put(np.linspace(-1, 1, 4).astype(np.float32),
                            NamedSharding(mesh, P())),
    }
    grads = {
        "w": jax.device_put(np.ones((16, 4), np.float32),
                            NamedSharding(mesh, P("data", None))),
        "b": jax.device_put(np.full((4,), 0.5, np.float32),
                            NamedSharding(mesh, P())),
    }
    kw = dict(lr=1e-2, betas=(0.9, 0.999), eps=1e-8, weight_decay=0.0,
              compute_dtype=jnp.bfloat16)
    return master, grads, kw


def test_sharded_tier_streamed_bitwise():
    """ShardedHostOffloadOptimizer: on_leaf + upload_block +
    assemble_uploaded produce the SAME global arrays as the serial
    step's _assemble — including replicated (multi-device group)
    leaves — and identical blocks/moments.  Covers step_local (the DPU
    stash half) too."""
    master, grads, kw = _sharded_fixture()
    opt_a = ShardedHostOffloadOptimizer(master, **kw)
    opt_b = ShardedHostOffloadOptimizer(master, **kw)

    serial = opt_a.step(grads)
    uploaded = {}
    ret = opt_b.step(grads, on_leaf=lambda i, blk: uploaded.__setitem__(
        i, opt_b.upload_block(i, blk)))
    assert ret is None  # streamed mode: engine assembles
    pipe = opt_b.assemble_uploaded(
        [uploaded[i] for i in range(len(uploaded))])
    for k in serial:
        assert serial[k].dtype == pipe[k].dtype
        np.testing.assert_array_equal(np.asarray(serial[k]),
                                      np.asarray(pipe[k]), err_msg=k)
    for (_, _, ga), (_, _, gb) in zip(opt_a._flat_groups,
                                      opt_b._flat_groups):
        np.testing.assert_array_equal(ga["block"], gb["block"])
    for i in range(len(opt_a._flat_groups)):
        ma, va = opt_a.opt._moments(i, opt_a._flat_groups[i][2]["block"])
        mb, vb = opt_b.opt._moments(i, opt_b._flat_groups[i][2]["block"])
        np.testing.assert_array_equal(ma, mb)
        np.testing.assert_array_equal(va, vb)

    # the DPU half: pull_local + step_local, streamed vs serial
    blocks_a = opt_a.pull_local(grads)
    blocks_b = opt_b.pull_local(grads)
    serial2 = opt_a.step_local(blocks_a)
    uploaded2 = {}
    opt_b.step_local(blocks_b, on_leaf=lambda i, blk: uploaded2.__setitem__(
        i, opt_b.upload_block(i, blk)))
    pipe2 = opt_b.assemble_uploaded(
        [uploaded2[i] for i in range(len(uploaded2))])
    for k in serial2:
        np.testing.assert_array_equal(np.asarray(serial2[k]),
                                      np.asarray(pipe2[k]), err_msg=k)


def test_assemble_batches_device_puts(monkeypatch):
    """Satellite: _assemble must issue ONE batched jax.device_put call
    for all groups x replica devices, not one blocking-ish put per
    device in a serial python loop."""
    master, grads, kw = _sharded_fixture()
    opt = ShardedHostOffloadOptimizer(master, **kw)
    calls = []
    real_put = jax.device_put

    def spy(x, device=None, **kwargs):
        calls.append(x)
        return real_put(x, device, **kwargs)

    monkeypatch.setattr(offload.jax, "device_put", spy)
    cp = opt.compute_params()
    assert len(calls) == 1, f"{len(calls)} device_put calls (want 1)"
    # the replicated leaf fanned out under that one call: 8 devices for
    # "b" + one per "w" shard group
    assert len(calls[0]) == len(jax.devices()) + len(opt._local[1])
    assert cp["w"].dtype == jnp.bfloat16


class _ShardedShim:
    """Drives the REAL engine pipelined-update method against the
    sharded tier in one process (the engine only picks that tier under
    process_count > 1, which this container cannot run — the two-process
    e2e lives in test_multiprocess.py's slow tier)."""

    _offload_sharded = True
    _offload_pipeline = True
    telemetry = None

    def __init__(self, master, kw):
        import contextlib
        self._span = contextlib.nullcontext
        self._host_opt = ShardedHostOffloadOptimizer(master, **kw)
        shardings = jax.tree.map(lambda l: l.sharding, master)
        self._sharded_gather = jax.jit(lambda t: t,
                                       out_shardings=shardings)
        self._reshard_to_master = jax.jit(lambda t: t,
                                          out_shardings=shardings)
        self._compute_params = object()  # sentinel: must be REPLACED

    def _tel_span(self, *a, **k):
        return self._span()

    def _record_offload_overlap(self, *a):
        DeepSpeedEngine._record_offload_overlap(self, *a)


def test_engine_sharded_pipelined_update_bitwise():
    """The engine's sharded pipelined arm (upload_block → uploader →
    assemble_uploaded → _sharded_gather) against the serial sharded
    step, including the DPU-stash (_HostBlockStash) routing."""
    from deepspeed_tpu.runtime.engine import _HostBlockStash

    master, grads, kw = _sharded_fixture()
    shim = _ShardedShim(master, kw)
    ref_opt = ShardedHostOffloadOptimizer(master, **kw)

    DeepSpeedEngine._apply_host_update_pipelined(shim, grads)
    serial = ref_opt.step(grads)
    for k in serial:
        assert shim._compute_params[k].dtype == serial[k].dtype
        np.testing.assert_array_equal(
            np.asarray(shim._compute_params[k]), np.asarray(serial[k]),
            err_msg=k)
    assert shim.last_offload_breakdown["pipelined"]

    # DPU composition: stash → step_local through the same arm
    stash = _HostBlockStash(shim._host_opt.pull_local(grads))
    ref_blocks = ref_opt.pull_local(grads)
    DeepSpeedEngine._apply_host_update_pipelined(shim, stash)
    serial2 = ref_opt.step_local(ref_blocks)
    for k in serial2:
        np.testing.assert_array_equal(
            np.asarray(shim._compute_params[k]), np.asarray(serial2[k]),
            err_msg=k)


def test_engine_sharded_pipelined_upload_failure_poisons(monkeypatch):
    """Sharded arm of the poison contract: a failing batched put must
    poison the optimizer and leave the compute-param object untouched."""
    master, grads, kw = _sharded_fixture()
    shim = _ShardedShim(master, kw)
    sentinel = shim._compute_params

    def boom(blk, devices):
        raise ValueError("h2d link died")

    monkeypatch.setattr(offload, "_batched_device_put", boom)
    with pytest.raises(ValueError, match="h2d link died"):
        DeepSpeedEngine._apply_host_update_pipelined(shim, grads)
    assert shim._compute_params is sentinel
    assert shim._host_opt._poisoned is not None
    with pytest.raises(RuntimeError, match="poisoned"):
        shim._host_opt.step(grads)


# ---------------------------------------------------------------------
# failure injection: poison + intact _compute_params
# ---------------------------------------------------------------------
def test_upload_failure_poisons_and_preserves_compute_params(monkeypatch):
    """Adam completes, an H2D upload dies mid-pipeline: the optimizer
    must poison (master carries step t, device would keep t-1) and the
    engine must NOT have half-swapped _compute_params."""
    engine = DeepSpeedEngine(SimpleModel(hidden_dim=16),
                             _cfg(pipeline=True), mesh=_dp1_mesh(),
                             seed=7)
    batches = list(random_batches(engine.train_batch_size, 16,
                                  num_batches=3, seed=2))
    engine.train_batch(batches[0])  # healthy step
    old_params = engine._compute_params
    old_leaves = [np.asarray(x).copy()
                  for x in jax.tree.leaves(old_params)]

    fail_after = {"n": 0}

    def boom(arr, sharding):
        # let a couple of leaves through so the failure is genuinely
        # mid-pipeline, not at the first put
        fail_after["n"] += 1
        if fail_after["n"] > 2:
            raise ValueError("h2d link died")
        return jax.device_put(arr, sharding)

    monkeypatch.setattr(offload, "device_put_leaf", boom)
    with pytest.raises(ValueError, match="h2d link died"):
        engine.train_batch(batches[1])
    monkeypatch.undo()

    # old tree object untouched, values untouched
    assert engine._compute_params is old_params
    for x, ref in zip(jax.tree.leaves(engine._compute_params), old_leaves):
        np.testing.assert_array_equal(np.asarray(x), ref)
    # poisoned: no further training, no serialization
    assert engine._host_opt._poisoned is not None
    with pytest.raises(RuntimeError, match="poisoned"):
        engine.train_batch(batches[2])
    with pytest.raises(RuntimeError, match="refusing to serialize"):
        engine._host_opt.state_tree()


def test_adam_failure_with_pipeline_keeps_compute_params(monkeypatch):
    """The OTHER failure side: a grad-pull death mid-Adam (existing
    poison contract) must also leave _compute_params intact under the
    pipeline, and must not wedge on the upload worker."""
    engine = DeepSpeedEngine(SimpleModel(hidden_dim=16),
                             _cfg(pipeline=True), mesh=_dp1_mesh(),
                             seed=8)
    batch = next(random_batches(engine.train_batch_size, 16,
                                num_batches=1, seed=4))
    engine.train_batch(batch)
    old_params = engine._compute_params

    def broken(x):
        raise ValueError("tunnel is dead")

    monkeypatch.setattr(offload.jax, "device_get", broken)
    with pytest.raises(ValueError, match="tunnel is dead"):
        engine.train_batch(batch)
    monkeypatch.undo()
    assert engine._compute_params is old_params
    assert engine._host_opt._poisoned is not None


def test_streaming_uploader_blocks_until_transfer_done(monkeypatch):
    """The per-leaf timing window must contain the TRANSFER, not just
    the dispatch (device_put is async — the JL006 bug class), and an
    async transfer failure must surface inside the worker so the poison
    contract holds: the worker calls block_until_ready on every put."""
    drained = []
    real_block = jax.block_until_ready

    def spy(x):
        drained.append(x)
        return real_block(x)

    monkeypatch.setattr(offload.jax, "block_until_ready", spy)
    up = StreamingUploader(lambda i, a: jax.device_put(a))
    for i in range(3):
        up.submit(i, np.full((2,), float(i), np.float32))
    results, timings = up.finish()
    assert len(drained) == 3
    assert len(results) == 3 and len(timings) == 3

    # an error raised by the drain (async transfer failure) is caught
    # and re-raised from finish(), not leaked past it
    def boom(x):
        raise ValueError("async transfer died")

    monkeypatch.setattr(offload.jax, "block_until_ready", boom)
    up2 = StreamingUploader(lambda i, a: jax.device_put(a))
    up2.submit(0, np.zeros(2, np.float32))
    with pytest.raises(ValueError, match="async transfer died"):
        up2.finish()


def test_streaming_uploader_drains_after_failure():
    """A failed put poisons the uploader: later submissions are drained
    without touching the device, finish() raises the FIRST error, and
    the worker thread exits."""
    calls = []

    def put(idx, arr):
        calls.append(idx)
        if idx == 1:
            raise ValueError("boom")
        return arr

    before = set(threading.enumerate())
    up = StreamingUploader(put)
    workers = set(threading.enumerate()) - before
    for i in range(5):
        up.submit(i, np.zeros(2))
    with pytest.raises(ValueError, match="boom"):
        up.finish()
    assert calls == [0, 1], calls  # 2..4 drained, device untouched
    deadline = time.perf_counter() + 5.0
    while any(t.is_alive() for t in workers) and \
            time.perf_counter() < deadline:
        time.sleep(0.01)
    assert not any(t.is_alive() for t in workers), "worker leaked"


# ---------------------------------------------------------------------
# the concurrency proof: tracer timestamps with injected delays
# ---------------------------------------------------------------------
def _span_intervals(events, name):
    out = {}
    for e in events:
        if e.get("name") == name and e.get("ph") == "X":
            out[e["args"]["leaf"]] = (e["ts"], e["ts"] + e["dur"])
    return out


def test_pipeline_overlap_proven_by_tracer(monkeypatch):
    """With slow grad pulls (20ms) and slow uploads (30ms), the H2D span
    for leaf i-1 MUST overlap the CPU-Adam span for leaf i — the
    acceptance criterion, read straight off tracer timestamps — and the
    engine's measured overlap must be positive."""
    monkeypatch.setenv("DS_OFFLOAD_H2D_DELAY_S", "0.03")
    real_get = jax.device_get

    def slow_get(x):
        time.sleep(0.02)
        return real_get(x)

    tracer = TraceRecorder()
    offload.set_transfer_tracer(tracer)
    try:
        engine = DeepSpeedEngine(SimpleModel(hidden_dim=16, nlayers=3),
                                 _cfg(pipeline=True), mesh=_dp1_mesh(),
                                 seed=9)
        batch = next(random_batches(engine.train_batch_size, 16,
                                    num_batches=1, seed=5))
        monkeypatch.setattr(offload.jax, "device_get", slow_get)
        engine.train_batch(batch)
        monkeypatch.undo()
    finally:
        offload.set_transfer_tracer(None)

    evs = tracer.events()
    adam = _span_intervals(evs, "offload/adam_leaf")
    h2d = _span_intervals(evs, "offload/h2d_params")
    assert len(adam) >= 2 and len(h2d) >= 2, (len(adam), len(h2d))
    overlaps = []
    for i in sorted(adam):
        if i - 1 in h2d:
            a0, a1 = adam[i]
            u0, u1 = h2d[i - 1]
            overlaps.append(min(a1, u1) - max(a0, u0))
    assert overlaps and max(overlaps) > 0, (
        f"no H2D(i-1) x Adam(i) overlap observed: {overlaps}")

    bd = engine.last_offload_breakdown
    assert bd["pipelined"]
    assert bd["h2d_hidden_s"] > 0, bd
    assert 0 < bd["overlap_ratio"] <= 1, bd


def test_serial_path_reports_zero_overlap(monkeypatch):
    monkeypatch.setenv("DS_OFFLOAD_PIPELINE", "0")
    engine = DeepSpeedEngine(SimpleModel(hidden_dim=16), _cfg(),
                             mesh=_dp1_mesh(), seed=10)
    batch = next(random_batches(engine.train_batch_size, 16,
                                num_batches=1, seed=6))
    engine.train_batch(batch)
    bd = engine.last_offload_breakdown
    assert not bd["pipelined"]
    assert bd["h2d_hidden_s"] == 0.0
    assert bd["overlap_ratio"] == 0.0
    assert bd["cpu_adam_s"] > 0


# ---------------------------------------------------------------------
# bench CPU smoke (tier-1): measured overlap > 0 under a fake slow link
# ---------------------------------------------------------------------
def _load_bench():
    path = os.path.join(os.path.dirname(__file__), "..", "bench.py")
    spec = importlib.util.spec_from_file_location("bench_for_test", path)
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


def test_bench_offload_pipeline_smoke(monkeypatch):
    """The --offload-pipeline A/B leg on CPU with a fake slow-transfer
    delay: the 'on' leg must measure hidden transfer time > 0, the 'off'
    leg reports all-tail."""
    bench = _load_bench()
    monkeypatch.setenv("DS_OFFLOAD_H2D_DELAY_S", "0.02")
    on = bench.bench_offload_pipeline(jax, pipeline_on=True, steps=2)
    assert on["pipeline"] == "on"
    assert on["h2d_hidden_s"] > 0, on
    assert on["overlap_ratio"] > 0, on
    monkeypatch.delenv("DS_OFFLOAD_H2D_DELAY_S")
    off = bench.bench_offload_pipeline(jax, pipeline_on=False, steps=1)
    assert off["pipeline"] == "off"
    assert off["h2d_hidden_s"] == 0.0
    assert off["overlap_ratio"] == 0.0


# ---------------------------------------------------------------------
# telemetry: gauge + sync scalar + summarize row
# ---------------------------------------------------------------------
def test_overlap_ratio_reaches_telemetry_artifacts(tmp_path):
    """offload_overlap_ratio must flow end-to-end: registry gauge →
    metrics.prom, sync scalar → events.jsonl → summarize report/row."""
    import json as _json
    from deepspeed_tpu.telemetry.cli import summarize

    cfg = base_config(micro_bs=4, grad_acc=1, stage=2)
    cfg["zero_optimization"].update({"cpu_offload": True,
                                     "offload_impl": "host"})
    cfg["steps_per_print"] = 1
    cfg["telemetry"] = {"enabled": True, "output_path": str(tmp_path)}
    engine = DeepSpeedEngine(SimpleModel(hidden_dim=16),
                             DeepSpeedConfig(cfg, world_size=1),
                             mesh=_dp1_mesh(), seed=12)
    for b in random_batches(engine.train_batch_size, 16, num_batches=2,
                            seed=7):
        engine.train_batch(b)
    gauge = engine.telemetry.registry.gauge("offload_overlap_ratio")
    assert gauge.value() is not None
    engine.close()

    prom = (tmp_path / "metrics.prom").read_text()
    assert "offload_overlap_ratio" in prom
    syncs = [_json.loads(l) for l in
             (tmp_path / "events.jsonl").read_text().splitlines()
             if _json.loads(l).get("kind") == "sync"]
    assert any("offload_overlap_ratio" in (s.get("scalars") or {})
               for s in syncs)
    rep = summarize(str(tmp_path / "events.jsonl"))
    assert rep["offload_overlap_ratio"] is not None


def test_summarize_overlap_row(tmp_path, capsys):
    import json as _json
    from deepspeed_tpu.telemetry.cli import summarize
    p = tmp_path / "events.jsonl"
    lines = [{"kind": "sync", "step": 10 * (i + 1), "interval_s": 1.0,
              "steps": 10, "step_avg_s": 0.1,
              "scalars": {"offload_overlap_ratio": r}}
             for i, r in enumerate((0.5, 0.7))]
    p.write_text("\n".join(_json.dumps(l) for l in lines) + "\n")
    rep = summarize(str(p))
    assert rep["offload_overlap_ratio"] == pytest.approx(0.6)
    assert "offload H2D overlap" in capsys.readouterr().out


# ---------------------------------------------------------------------
# config knob
# ---------------------------------------------------------------------
def test_offload_pipeline_config_validation():
    cfg = base_config(stage=2)
    cfg["zero_optimization"]["offload_pipeline"] = True
    with pytest.raises(DeepSpeedConfigError, match="requires cpu_offload"):
        DeepSpeedConfig(cfg, world_size=1)
    # explicit false is benign anywhere; the default never validates
    cfg["zero_optimization"]["offload_pipeline"] = False
    DeepSpeedConfig(cfg, world_size=1)
    DeepSpeedConfig(base_config(stage=2), world_size=1)


def test_explicit_pipeline_on_xla_tier_warns():
    """Explicit offload_pipeline:true on the xla tier must warn, not be
    silently ignored (the DS_OFFLOAD_SPLIT_UPDATE precedent)."""
    import logging

    from deepspeed_tpu.utils.logging import logger as ds_logger

    cfg = base_config(micro_bs=4, grad_acc=1, stage=2)
    cfg["zero_optimization"].update({"cpu_offload": True,
                                     "offload_impl": "xla",
                                     "offload_pipeline": True})
    cfg["steps_per_print"] = 10 ** 9
    records = []

    class Rec(logging.Handler):
        def emit(self, record):
            records.append(record)

    h = Rec(level=logging.WARNING)
    ds_logger.addHandler(h)
    try:
        DeepSpeedEngine(SimpleModel(hidden_dim=16),
                        DeepSpeedConfig(cfg, world_size=1),
                        mesh=_dp1_mesh(), seed=13)
    finally:
        ds_logger.removeHandler(h)
    assert any("offload_pipeline is a host-tier knob" in r.getMessage()
               for r in records)


def test_offload_pipeline_default_on():
    cfg = _cfg()
    assert cfg.zero_config.offload_pipeline is True
    assert cfg.zero_config.offload_pipeline_explicit is False
