"""1-bit Adam tests.

Differential strategy mirrors the reference's manual MPI scripts
(reference: tests/onebitadam/test_com_reduce_host.py:27-35 compares
Compressed_Allreduce against a numpy simulation of sign compression +
error feedback) — here the collective runs for real on the 8-device
virtual CPU mesh via shard_map, no cluster needed.
"""
import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest
from functools import partial

from jax.sharding import Mesh, PartitionSpec as P

# check_vma=False: the collective's output is replicated by construction
# (it is computed from all_gathered buffers), but JAX's varying-manual-axes
# inference cannot prove that through the bit-unpack arithmetic.
shard_map = partial(jax.shard_map, check_vma=False)

from deepspeed_tpu.compress import (compressed_allreduce, init_onebit_state,
                                    onebit_adam, pack_signs, padded_size,
                                    simulated_compressed_allreduce,
                                    unpack_signs)

WORLD = 8


def _mesh():
    return Mesh(np.array(jax.devices()[:WORLD]), ("data",))


# ---------------------------------------------------------------------------
# numpy reference of the two-phase algorithm (independent implementation)
# ---------------------------------------------------------------------------
def np_sign_compress(buf, error):
    buf = buf + error
    scale = np.linalg.norm(buf) / np.sqrt(buf.size)
    sign = np.where(buf >= 0, 1.0, -1.0)
    return sign, scale, buf - scale * sign


def np_compressed_allreduce(locals_, worker_errors, server_errors):
    """locals_: [world, n].  Returns (out [world, n], new_we, new_se)."""
    world, n = locals_.shape
    Pn = padded_size(n, world)
    chunk = Pn // world
    signs = np.zeros((world, Pn))
    scales = np.zeros(world)
    new_we = np.zeros_like(worker_errors)
    for w in range(world):
        buf = np.pad(locals_[w], (0, Pn - n))
        s, sc, err = np_sign_compress(buf, worker_errors[w])
        signs[w], scales[w], new_we[w] = s, sc, err
    # server r averages chunk r of every worker's compressed buffer
    out = np.zeros(Pn)
    new_se = np.zeros_like(server_errors)
    sscales = np.zeros(world)
    ssigns = np.zeros((world, chunk))
    for r in range(world):
        comp = np.mean(
            signs[:, r * chunk:(r + 1) * chunk] * scales[:, None], axis=0)
        s, sc, err = np_sign_compress(comp, server_errors[r])
        ssigns[r], sscales[r], new_se[r] = s, sc, err
    for r in range(world):
        out[r * chunk:(r + 1) * chunk] = sscales[r] * ssigns[r]
    return np.tile(out[:n], (world, 1)), new_we, new_se


def test_pack_unpack_roundtrip():
    rng = np.random.default_rng(0)
    x = rng.standard_normal(256).astype(np.float32)
    sign = jnp.where(jnp.asarray(x) >= 0, 1.0, -1.0)
    packed = pack_signs(sign > 0)
    assert packed.dtype == jnp.uint8 and packed.size == 32  # 1/32 of fp32
    np.testing.assert_array_equal(np.asarray(unpack_signs(packed)),
                                  np.asarray(sign))


@pytest.mark.parametrize("n", [64, 100, 1000])
def test_compressed_allreduce_vs_numpy(n):
    rng = np.random.default_rng(1)
    locals_ = rng.standard_normal((WORLD, n)).astype(np.float32)
    Pn = padded_size(n, WORLD)
    we = rng.standard_normal((WORLD, Pn)).astype(np.float32) * 0.1
    se = rng.standard_normal((WORLD, Pn // WORLD)).astype(np.float32) * 0.1

    mesh = _mesh()
    fn = shard_map(
        lambda x, w, s: compressed_allreduce(x[0], w[0], s[0], "data"),
        mesh=mesh,
        in_specs=(P("data"), P("data"), P("data")),
        out_specs=(P(), P("data"), P("data")))
    out, new_we, new_se = jax.jit(fn)(locals_, we, se)

    ref_out, ref_we, ref_se = np_compressed_allreduce(locals_, we, se)
    np.testing.assert_allclose(np.asarray(out), ref_out[0], rtol=2e-5,
                               atol=2e-5)
    np.testing.assert_allclose(np.asarray(new_we), ref_we.reshape(-1),
                               rtol=2e-5, atol=2e-5)
    np.testing.assert_allclose(np.asarray(new_se), ref_se.reshape(-1),
                               rtol=2e-5, atol=2e-5)


def test_simulated_matches_collective_on_identical_buffers():
    """When all workers hold the same buffer, the real collective equals
    the no-communication simulation (the engine's pre-averaged path)."""
    n = 200
    rng = np.random.default_rng(2)
    x = rng.standard_normal(n).astype(np.float32)
    locals_ = np.tile(x, (WORLD, 1))
    Pw = padded_size(n, WORLD)
    we = np.zeros((WORLD, Pw), np.float32)
    se = np.zeros((WORLD, Pw // WORLD), np.float32)

    mesh = _mesh()
    fn = shard_map(
        lambda xs, w, s: compressed_allreduce(xs[0], w[0], s[0], "data")[0],
        mesh=mesh, in_specs=(P("data"), P("data"), P("data")), out_specs=P())
    out_real = np.asarray(jax.jit(fn)(locals_, we, se))

    # the simulation must pad like the collective does: the sign scale is
    # ||buf||_2/sqrt(padded_n), so equality holds when paddings match
    out_sim, _, _ = simulated_compressed_allreduce(
        jnp.asarray(x), jnp.zeros(Pw), jnp.zeros(Pw))
    np.testing.assert_allclose(out_real, np.asarray(out_sim), rtol=1e-5,
                               atol=1e-5)


def test_error_feedback_accumulates_compression_residual():
    """After one round, error buffers hold exactly buf - scale*sign."""
    n = 64
    x = jnp.asarray(np.random.default_rng(3).standard_normal(n),
                    jnp.float32)
    out, we, se = simulated_compressed_allreduce(
        x, jnp.zeros(n), jnp.zeros(n))
    scale = float(jnp.linalg.norm(x) / jnp.sqrt(n))
    sign = np.where(np.asarray(x) >= 0, 1.0, -1.0)
    np.testing.assert_allclose(np.asarray(we), np.asarray(x) - scale * sign,
                               rtol=1e-5, atol=1e-6)


def test_onebit_adam_warmup_matches_plain_adam():
    """Steps <= freeze_step must be exactly un-bias-corrected Adam."""
    params = {"w": jnp.ones((4, 3)), "b": jnp.zeros((3,))}
    tx = onebit_adam(lr=0.1, freeze_step=100)
    state = tx.init(params)
    rngs = np.random.default_rng(4)
    mu = jax.tree.map(jnp.zeros_like, params)
    nu = jax.tree.map(jnp.zeros_like, params)
    p_ref = params
    for step in range(1, 6):
        grads = jax.tree.map(
            lambda p: jnp.asarray(
                rngs.standard_normal(p.shape), jnp.float32), params)
        updates, state = tx.update(grads, state, params)
        params = optax.apply_updates(params, updates)
        # manual un-bias-corrected Adam
        mu = jax.tree.map(lambda m, g: 0.9 * m + 0.1 * g, mu, grads)
        nu = jax.tree.map(lambda v, g: 0.999 * v + 0.001 * g * g, nu, grads)
        p_ref = jax.tree.map(
            lambda p, m, v: p - 0.1 * m / (jnp.sqrt(v) + 1e-8),
            p_ref, mu, nu)
    for k in params:
        np.testing.assert_allclose(np.asarray(params[k]),
                                   np.asarray(p_ref[k]), rtol=1e-5,
                                   atol=1e-6)


def test_onebit_adam_frozen_phase_converges():
    """After freeze, compressed momentum with error feedback still drives a
    quadratic to its optimum."""
    target = jnp.asarray(np.random.default_rng(5).standard_normal(32),
                         jnp.float32)
    params = {"x": jnp.zeros(32)}
    # decaying lr: sign-compressed updates have an lr-proportional noise
    # floor, so a fixed lr plateaus at ~lr-scale error
    tx = onebit_adam(lr=lambda c: 0.05 / jnp.sqrt(c.astype(jnp.float32)),
                     freeze_step=10)
    state = tx.init(params)

    @jax.jit
    def step(params, state):
        grads = jax.grad(
            lambda p: jnp.sum((p["x"] - target) ** 2))(params)
        updates, state = tx.update(grads, state, params)
        return optax.apply_updates(params, updates), state

    for _ in range(400):
        params, state = step(params, state)
    assert int(state.count) == 400
    err = float(jnp.max(jnp.abs(params["x"] - target)))
    assert err < 0.05, f"did not converge: max err {err}"


def test_onebit_adam_variance_frozen_after_freeze_step():
    params = {"x": jnp.zeros(8)}
    tx = onebit_adam(lr=0.01, freeze_step=3)
    state = tx.init(params)
    # non-uniform grads: a constant buffer sign-compresses exactly (zero
    # residual), which would make the error-feedback assertion vacuous
    g = {"x": jnp.linspace(0.5, 2.0, 8)}
    for _ in range(3):
        _, state = tx.update(g, state, params)
    nu_at_freeze = np.asarray(state.nu["x"]).copy()
    for _ in range(4):
        _, state = tx.update(g, state, params)
    np.testing.assert_array_equal(np.asarray(state.nu["x"]), nu_at_freeze)
    # error feedback is live: worker error must be nonzero after compression
    assert float(jnp.max(jnp.abs(state.worker_error["x"]))) > 0


def test_onebit_adam_collective_in_shard_map():
    """Full optimizer step inside shard_map with per-shard local grads:
    post-freeze updates must be identical on every shard (momentum is
    exchanged through the compressed collective)."""
    n = 64
    mesh = _mesh()
    params = {"x": jnp.zeros(n)}
    tx = onebit_adam(lr=0.05, freeze_step=2, data_axis="data")
    state = init_onebit_state(params, WORLD)
    # broadcast state leaves that are per-worker (errors) across shards
    rng = np.random.default_rng(6)
    local_targets = rng.standard_normal((WORLD, n)).astype(np.float32)

    def one_step(params, state, targets):
        # per-shard local gradient (different data per worker); the
        # transform itself pmeans during warmup and compresses after
        g = {"x": 2 * (params["x"] - targets[0])}
        # sharded error buffers arrive with a leading local dim of 1
        squeeze = lambda t: jax.tree.map(lambda a: a[0], t)
        unsq = lambda t: jax.tree.map(lambda a: a[None], t)
        local = state._replace(
            worker_error=squeeze(state.worker_error),
            server_error=squeeze(state.server_error))
        updates, state2 = tx.update(g, local, params)
        new_params = optax.apply_updates(params, updates)
        # expose every shard's momentum so the test can assert they agree
        mu_all = jax.lax.all_gather(state2.mu["x"], "data")
        state2 = state2._replace(
            worker_error=unsq(state2.worker_error),
            server_error=unsq(state2.server_error))
        return new_params, state2, mu_all

    from deepspeed_tpu.compress import OnebitAdamState
    state_spec = OnebitAdamState(
        count=P(), mu=P(), nu=P(),
        worker_error=P("data"), server_error=P("data"))
    fn = shard_map(
        one_step, mesh=mesh,
        in_specs=(P(), state_spec, P("data")),
        out_specs=(P(), state_spec, P()))
    fn = jax.jit(fn)

    we = jnp.tile(state.worker_error["x"], (WORLD, 1))
    se = jnp.tile(state.server_error["x"], (WORLD, 1))
    st = state._replace(worker_error={"x": we}, server_error={"x": se})
    for step in range(6):
        params, st, mu_all = fn(params, st, local_targets)
        # momentum must be identical on every shard: during warmup because
        # grads are pmean'd, after freeze because the compressed collective
        # returns one all-gathered buffer
        mu_all = np.asarray(mu_all)
        for w in range(1, WORLD):
            np.testing.assert_allclose(mu_all[w], mu_all[0], rtol=1e-6,
                                       atol=1e-7,
                                       err_msg=f"step {step} shard {w}")
    assert params["x"].shape == (n,)
    assert np.isfinite(np.asarray(params["x"])).all()
    assert int(st.count) == 6


def test_onebit_adam_via_engine():
    """Engine dispatch: optimizer type 'onebitadam' trains end-to-end and
    the loss decreases (engine path = pre-averaged grads → simulated
    compression)."""
    import sys
    sys.path.insert(0, "tests")
    from simple_model import SimpleModel, base_config, random_batches
    from deepspeed_tpu.config import DeepSpeedConfig
    from deepspeed_tpu.runtime.engine import DeepSpeedEngine

    cfg_dict = base_config(micro_bs=8, grad_acc=1)
    cfg_dict["optimizer"] = {
        "type": "OneBitAdam",
        "params": {"lr": 5e-3, "freeze_step": 10}}
    cfg = DeepSpeedConfig(cfg_dict, world_size=8)
    engine = DeepSpeedEngine(SimpleModel(hidden_dim=16), cfg)
    losses = [float(engine.train_batch(b)) for b in
              random_batches(cfg.train_batch_size, 16, num_batches=30,
                             seed=7)]
    assert losses[-1] < losses[0] * 0.5, losses
    assert engine.get_skipped_steps() == 0
