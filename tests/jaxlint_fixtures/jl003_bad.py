"""JL003 positive fixture: in_shardings without out_shardings, and a
bare jit site among pinned siblings."""
import jax

in_spec = out_spec = None


def build_step(fn):
    # JL003: in_shardings given, out_shardings omitted
    return jax.jit(fn, in_shardings=(in_spec,))


def build_split(stats_fn, tail_fn):
    stats = jax.jit(stats_fn, out_shardings=(out_spec,))
    tail = jax.jit(tail_fn)            # JL003: bare among pinned siblings
    return stats, tail
