"""Stage plane of the bad mini-project: writer:flush is live in code
but undocumented (JL103), and the docs fence names a ghost stage."""

ENGINE_STAGES = (
    ("loader", "input"),
    ("writer", "output"),
)


def fault_point(stage, point):
    return (stage, point)


def wire(graph, loader, writer):
    graph.register("loader", close=loader.close, drain=loader.drain)
    graph.register("writer", close=writer.close, drain=writer.drain)


def tick():
    fault_point("loader", "read")
    fault_point("writer", "flush")
