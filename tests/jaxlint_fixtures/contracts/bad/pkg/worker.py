"""A Stage construction whose literal name is in NO registry —
no ENGINE_STAGES entry, no docs row, no fault-point constant (JL008)."""
from .runtime import Stage


def make():
    return Stage("mystery")
