"""Metric plane violations: a counter with no HELP text that nothing
consumes, and a sync scalar nothing reads (JL102)."""


class Recorder:
    def __init__(self, reg):
        self.ticks = reg.counter("fixture_orphan_total")

    def on_sync(self, scalars):
        scalars["fixture_dead_s"] = 1.0
