"""Reads a sync scalar no engine ever emits (JL102)."""


def summarize(scalars):
    return scalars.get("fixture_ghost_s")
