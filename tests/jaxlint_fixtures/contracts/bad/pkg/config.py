from . import constants as C


def keys():
    # references the key but not its schema default (JL104: the key
    # is read somewhere without the default constant)
    return [C.TIMEOUT]
