LOWER_BETTER_HINTS = ("_seconds",)

METRIC_DIRECTIONS = {
    "fixture_missing_speedup": False,
}
