"""A Stage construction whose literal name IS in the registry
(ENGINE_STAGES), plus a wrapper-resolved fault point."""
from .runtime import Stage
from .stages import fault_point


class Loader:
    def __init__(self):
        self.stage = Stage("loader")

    def step(self):
        fault_point("loader", "read")
        self.stage.check("read")
