import os

from . import constants as C


def load(d):
    return d.get(C.QUEUE_CAPACITY, C.QUEUE_CAPACITY_DEFAULT)


def pipeline_enabled():
    return os.getenv("DS_FIXTURE_PIPELINE", "1") == "1"
