"""Metric plane: a registry counter with HELP text and a sync scalar,
both consumed elsewhere (docs bullet / summarize row)."""


class Recorder:
    def __init__(self, reg):
        self.ticks = reg.counter("fixture_ticks_total",
                                 "ticks observed by the loop")

    def on_sync(self, scalars, wait):
        scalars["fixture_wait_s"] = wait
