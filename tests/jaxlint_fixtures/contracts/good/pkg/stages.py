"""Stage plane of the good mini-project: every name is registered,
every fault point documented, drain order matches the docs fence."""

ENGINE_STAGES = (
    ("loader", "input"),
    ("writer", "output"),
)


def fault_point(stage, point):
    return (stage, point)


def wire(graph, loader, writer):
    graph.register("loader", close=loader.close, drain=loader.drain)
    graph.register("writer", close=writer.close, drain=writer.drain)


def tick():
    fault_point("loader", "read")
    fault_point("writer", "flush")
