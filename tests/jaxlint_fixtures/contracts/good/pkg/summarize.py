"""Consumer side: reads the emitted sync scalar fixture_wait_s."""


def summarize(scalars):
    return scalars.get("fixture_wait_s")
