LOWER_BETTER_HINTS = ("_seconds",)

METRIC_DIRECTIONS = {
    "fixture_speedup": False,
}
