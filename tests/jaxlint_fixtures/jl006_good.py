"""JL006 fixture (good): timed sections bounded by a sync (or with no
device work inside at all)."""
import time

import jax
import numpy as np


@jax.jit
def compiled(x):
    return x * 2


def timed_synced(x):
    t0 = time.time()
    y = compiled(x)
    jax.block_until_ready(y)     # drains the dispatch queue
    return y, time.time() - t0


def timed_materialized(x):
    t0 = time.time()
    y = np.asarray(compiled(x))  # materialization is the sync
    return y, time.time() - t0


def timed_pure_python(values):
    t0 = time.time()
    total = sum(values)          # no device work timed
    return total, time.time() - t0


def prefetcher_queue_wait(q, cond):
    """DevicePrefetcher-shaped (runtime/prefetch.py): the timed window
    brackets a REAL block — a condition wait on the bounded queue — not
    an async jax dispatch.  JL006 must stay silent."""
    t0 = time.perf_counter()
    with cond:
        cond.wait_for(lambda: q)
        batch = q.pop(0)
    return batch, time.perf_counter() - t0


def prefetcher_place_window(x):
    """Worker-side placement window: the device_put dispatch is drained
    by block_until_ready INSIDE the timed window (transfer-real)."""
    t0 = time.perf_counter()
    y = jax.device_put(x)
    jax.block_until_ready(y)
    return y, time.perf_counter() - t0
