"""JL006 fixture (good): timed sections bounded by a sync (or with no
device work inside at all)."""
import time

import jax
import numpy as np


@jax.jit
def compiled(x):
    return x * 2


def timed_synced(x):
    t0 = time.time()
    y = compiled(x)
    jax.block_until_ready(y)     # drains the dispatch queue
    return y, time.time() - t0


def timed_materialized(x):
    t0 = time.time()
    y = np.asarray(compiled(x))  # materialization is the sync
    return y, time.time() - t0


def timed_pure_python(values):
    t0 = time.time()
    total = sum(values)          # no device work timed
    return total, time.time() - t0
