"""JL007 positive fixture: raw daemon-thread construction — the
hand-rolled async-worker shape the stage runtime replaced."""
import threading
import threading as _renamed
from threading import Thread


def hand_rolled_worker(q):
    def work():
        while True:
            q.get()()

    threading.Thread(target=work, daemon=True).start()          # flagged
    t = Thread(target=work, daemon=True, name="ds-rogue")       # flagged
    t.start()
    _renamed.Thread(target=work, daemon=True).start()           # flagged
