"""JL002 negative fixture: donation with the name rebound before any
read — the train-loop idiom."""
import jax


def rebind(state, step_fn):
    step = jax.jit(step_fn, donate_argnums=(0,))
    state = step(state)                # rebound from the result
    return state.loss_scale            # fine: reads the NEW buffers


class Engine:
    def train(self, batch):
        step = jax.jit(lambda s, b: s, donate_argnums=(0,))
        self.state = step(self.state, batch)   # rebound in place
        return self.state.scaler


def non_donated_args_are_free(state, aux, step_fn):
    step = jax.jit(step_fn, donate_argnums=(0,))
    state = step(state, aux)
    return aux                         # arg 1 was not donated
