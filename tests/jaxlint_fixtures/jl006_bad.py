"""JL006 fixture (bad): wall-clock deltas that bracket async jax
dispatch with no intervening sync — they time the ENQUEUE, not the
device."""
import time

import jax


@jax.jit
def compiled(x):
    return x * 2


def timed_enqueue(x):
    t0 = time.time()
    y = compiled(x)              # async dispatch: returns immediately
    return y, time.time() - t0   # JL006: enqueue latency only


def timed_step_driver(step_fn, state, batch):
    start = time.perf_counter()
    state = step_fn(state, batch)    # compiled-step naming convention
    now = time.perf_counter()
    return state, now - start        # JL006: same bug, two stored reads
