"""JL101 fixture constants: the checkable schema."""
TRAIN_BATCH = "train_batch"
TRAIN_BATCH_DEFAULT = None

STEPS = "steps"
STEPS_DEFAULT = 10

OPTIMIZER = "optimizer"          # block key: no _DEFAULT on purpose
