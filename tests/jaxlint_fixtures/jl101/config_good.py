"""JL101 negative fixture: every read routed through matching constants."""
from . import constants as C


def get_scalar_param(d, key, default):
    return d.get(key, default) if d is not None else default


class Config:
    def __init__(self, pd):
        self.train_batch = get_scalar_param(pd, C.TRAIN_BATCH,
                                            C.TRAIN_BATCH_DEFAULT)
        self.steps = get_scalar_param(pd, C.STEPS, C.STEPS_DEFAULT)
        # block key with no schema default: a bare read is legitimate
        self.optimizer = pd.get(C.OPTIMIZER)
        # explicit literal default is a local decision, not a schema gap
        self.zero = pd.get(C.TRAIN_BATCH, None)
