"""JL101 positive fixture: unknown, bypassed, defaultless, cross-wired."""
from . import constants as C


def get_scalar_param(d, key, default):
    return d.get(key, default) if d is not None else default


class Config:
    def __init__(self, pd):
        self.ok = get_scalar_param(pd, C.TRAIN_BATCH, C.TRAIN_BATCH_DEFAULT)
        self.unknown = get_scalar_param(pd, C.MISSING_KEY, None)
        self.bypassed = get_scalar_param(pd, "raw_key", 3)
        self.defaultless = pd.get(C.STEPS)
        self.crossed = pd.get(C.TRAIN_BATCH, C.STEPS_DEFAULT)
