"""JL010 good: the schedule scalar is passed as an argument (static, so
each value retraces) instead of being closed over — rebinding it between
calls reaches the compiled code."""
from functools import partial

import jax


def warmup_schedule(steps):
    scale = 0.1

    @partial(jax.jit, static_argnums=(1,))
    def scaled_loss(x, s):
        return x * s

    losses = []
    for step in range(steps):
        losses.append(scaled_loss(step, scale))
        scale = scale + 0.01
    return losses
