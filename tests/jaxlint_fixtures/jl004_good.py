"""JL004 negative fixture: local mutation inside the trace (fine — it
builds the program), side effects in eager driver code."""
import jax


class Engine:
    def build(self):
        def step(state, batch):
            pieces = []                   # local list: fine
            for leaf in state:
                pieces.append(leaf * 2)
            jax.debug.print("loss {}", pieces[0])   # trace-safe print
            return tuple(pieces)
        return jax.jit(step)

    def train(self, batch):
        self.count = getattr(self, "count", 0) + 1   # eager: fine
        print("step", self.count)                    # eager: fine
        return self._step(batch)
