"""JL001 negative fixture: the same call names OUTSIDE traced code, and
trace-safe jnp equivalents inside it."""
import jax
import jax.numpy as jnp
import numpy as np


@jax.jit
def traced(x):
    # jnp.asarray is trace-safe; astype is not a sync
    return jnp.asarray(x).astype(jnp.float32)


def eager_driver(batch, step):
    micro = np.asarray(batch)         # eager host code: fine
    loss = step(micro)
    return float(loss), loss.item()   # after the step returns: fine


def helper_not_called_from_jit(x):
    return np.asarray(x)              # never reachable from a jit body
