"""JL001 positive fixture: host syncs reachable from jitted code."""
import jax
import numpy as np


@jax.jit
def direct_sync(x):
    return np.asarray(x) + 1          # JL001: np.asarray under trace


def helper(x):
    return x.item()                   # JL001: reachable from jitted f


@jax.jit
def via_helper(x):
    return helper(x)


def concretize(x):
    return float(x)                   # JL001: float() on a tracer


step = jax.jit(concretize)


class Engine:
    def _wait(self, x):
        return x.block_until_ready()  # JL001: via self-method call

    def build(self):
        def step(x):
            return self._wait(x)
        return jax.jit(step)
