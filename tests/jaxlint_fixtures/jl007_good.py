"""JL007 negative fixture: workers built from the stage runtime, plus
the non-daemon shapes the rule leaves alone."""
import threading

from deepspeed_tpu.runtime.stages import spawn


def sanctioned_worker(q):
    def work():
        while True:
            q.get()()

    spawn(work, name="ds-sanctioned")  # the stage runtime's constructor


def foreground_thread(fn):
    # non-daemon: a deliberate blocking join-at-exit thread is not the
    # hand-rolled-async-subsystem shape JL007 polices
    t = threading.Thread(target=fn)
    t.start()
    return t
