"""JL008 good: puts live inside the worker-body closure (directly or
transitively), callers use the non-blocking force=True overflow policy,
and the Thread alias never builds daemon threads."""
import threading

from deepspeed_tpu.runtime.stages import Channel, spawn


class Producer:
    def __init__(self, capacity):
        self.ch = Channel(capacity=capacity)
        spawn("producer", self._loop)

    def _loop(self):
        while True:
            self._push()

    def _push(self):
        # transitively inside the worker body via _loop's call closure
        self.ch.put(object())

    def submit(self, item):
        # caller-side path: explicit drop/overflow policy, never blocks
        return self.ch.put(item, force=True)


T = threading.Thread
helper = T(target=print)  # non-daemon: not a stage-runtime bypass
