"""JL009 bad: step() donates self.params into the jitted update and
never rebinds it; snapshot() later reads the deleted buffer — an error
only on real TPU (CPU jit ignores donation), invisible in CI."""
import jax


def _adam_update(params, grads):
    return jax.tree_util.tree_map(lambda p, g: p - 0.1 * g, params, grads)


class Engine:
    def __init__(self, params):
        self.params = params
        self._update = jax.jit(_adam_update, donate_argnums=(0,))

    def step(self, grads):
        new_params = self._update(self.params, grads)
        return new_params

    def snapshot(self):
        return dict(self.params)
