"""JL003 negative fixture: both shardings pinned, or neither given
(single-device code has no placement to pin)."""
import jax

in_spec = out_spec = None


def build_step(fn):
    return jax.jit(fn, in_shardings=(in_spec,),
                   out_shardings=(out_spec,))


def build_plain(fn):
    return jax.jit(fn)                 # no shardings at all: fine


def build_split(stats_fn, tail_fn):
    stats = jax.jit(stats_fn, out_shardings=(out_spec,))
    tail = jax.jit(tail_fn, out_shardings=(out_spec,))
    return stats, tail
