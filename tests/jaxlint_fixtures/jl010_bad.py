"""JL010 bad: `scale` is a Python scalar closed over by a jitted
callable; jit bakes it in as a constant at trace time, so the later
rebinding silently never reaches the compiled code — the stale constant
runs forever, no recompile, no error."""
import jax


def warmup_schedule(steps):
    scale = 0.1

    @jax.jit
    def scaled_loss(x):
        return x * scale

    losses = []
    for step in range(steps):
        losses.append(scaled_loss(step))
        scale = scale + 0.01
    return losses
