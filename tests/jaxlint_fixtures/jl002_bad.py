"""JL002 positive fixture: reads after donation."""
import jax


def plain_read_after_donate(state, step_fn):
    step = jax.jit(step_fn, donate_argnums=(0,))
    new_state, metrics = step(state)
    return state.loss_scale            # JL002: state was donated


class Engine:
    def train(self, batch):
        step = jax.jit(lambda s, b: s, donate_argnums=(0,))
        st = self.state                # alias of self.state
        out = step(st, batch)
        return self.state.scaler       # JL002: donated via the alias


def donate_by_name(state, step_fn):
    step = jax.jit(step_fn, donate_argnames=("state",))
    out = step(state=state)
    return state                       # JL002: donated by argname
