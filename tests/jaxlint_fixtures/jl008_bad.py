"""JL008 bad: blocking Channel.put outside any worker body (wedges the
caller when the stage degrades) + a raw daemon thread hidden behind an
assignment alias that JL007's import-alias tracking cannot see."""
import threading

from deepspeed_tpu.runtime.stages import Channel


class Producer:
    def __init__(self, capacity):
        self.ch = Channel(capacity=capacity)

    def submit(self, item):
        # no worker drains self.ch when the stage is degraded: this
        # blocks the submitting thread forever
        return self.ch.put(item)


T = threading.Thread
worker = T(target=print, daemon=True)
