"""JL004 positive fixture: Python side effects inside traced bodies."""
import jax

metrics_log = []
STEP_COUNT = 0


class Engine:
    def build(self):
        def step(state, batch):
            self.last_state = state     # JL004: self.* assignment
            print(state)                # JL004: print under trace
            metrics_log.append(batch)   # JL004: closed-over list mutation
            global STEP_COUNT           # JL004: global under trace
            STEP_COUNT += 1
            return state
        return jax.jit(step)
