"""JL009 good: the donated self.params is rebound to the jitted call's
result before anyone can read it — snapshot() sees the fresh buffer."""
import jax


def _adam_update(params, grads):
    return jax.tree_util.tree_map(lambda p, g: p - 0.1 * g, params, grads)


class Engine:
    def __init__(self, params):
        self.params = params
        self._update = jax.jit(_adam_update, donate_argnums=(0,))

    def step(self, grads):
        self.params = self._update(self.params, grads)
        return self.params

    def snapshot(self):
        return dict(self.params)
