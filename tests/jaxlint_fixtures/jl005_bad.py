"""JL005 positive fixture: unhashable static args and trace-time clocks."""
import time

import jax


@jax.jit(static_argnums=(1,))
def step(x, cfg):
    return x


def run(x):
    return step(x, {"lr": 0.1})        # JL005: dict in a static slot


@jax.jit(static_argnames=("tag",))
def tagged(x, tag):
    return x


def run_tagged(x, i):
    return tagged(x, tag=f"step{i}")   # JL005: f-string static arg


@jax.jit
def stamped(x):
    return x * time.time()             # JL005: clock baked at trace time
