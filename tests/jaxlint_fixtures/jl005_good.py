"""JL005 negative fixture: hashable statics, clocks outside the trace."""
import time

import jax


@jax.jit(static_argnums=(1,))
def step(x, n):
    return x * n


def run(x):
    return step(x, 4)                  # int static: hashable, stable


def timed_driver(x):
    t0 = time.time()                   # eager timing: fine
    y = step(x, 2)
    return y, time.time() - t0
