"""Model-level convergence regression suite — the analogue of the
reference's Megatron_GPT2 sanity tests (reference:
tests/model/Megatron_GPT2/test_common.py:12+ — run a config matrix, log
the loss curve, compare against checked-in baselines).

Instead of shelling out to launcher scripts, each case trains GPT-2-tiny
on a FIXED synthetic corpus for 20 steps on the 8-device mesh and compares
the loss trajectory against the baseline recorded in
``model_baselines.json``.  Tolerances are loose enough for cross-platform
float drift but tight enough that a numerics regression (wrong grad
scaling, broken ZeRO reduction, remat RNG skew) shows up.

Regenerate baselines after an INTENTIONAL numerics change:
    python tests/test_model_regression.py --regen
"""
import json
import os

import numpy as np
import pytest

CASES = {
    # name -> config overrides (the matrix mirrors the reference's
    # ds_config_func_* files: fp16/bf16 x zero stage x grad-acc)
    "bf16_zero0": dict(precision="bf16", stage=0, grad_acc=1),
    "bf16_zero1_ga2": dict(precision="bf16", stage=1, grad_acc=2),
    "bf16_zero2": dict(precision="bf16", stage=2, grad_acc=1),
    "bf16_zero3": dict(precision="bf16", stage=3, grad_acc=1),
    "fp16_zero2": dict(precision="fp16", stage=2, grad_acc=1),
}
BASELINE_PATH = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                             "model_baselines.json")
STEPS = 20
MICRO = 2


def _train_curve(precision: str, stage: int, grad_acc: int):
    import jax
    from deepspeed_tpu.config import DeepSpeedConfig
    from deepspeed_tpu.models import GPT2Config, GPT2Model
    from deepspeed_tpu.parallel import build_mesh
    from deepspeed_tpu.runtime.engine import DeepSpeedEngine

    cfg_model = GPT2Config(vocab_size=257, n_positions=64, d_model=64,
                           n_layer=2, n_head=4, remat=None)
    cfg = {
        "train_micro_batch_size_per_gpu": MICRO,
        "gradient_accumulation_steps": grad_acc,
        "steps_per_print": 10 ** 9,
        "optimizer": {"type": "Adam", "params": {"lr": 3e-3}},
        "zero_optimization": {"stage": stage},
    }
    if precision == "bf16":
        cfg["bf16"] = {"enabled": True}
    else:
        cfg["fp16"] = {"enabled": True, "initial_scale_power": 8}
    ds_cfg = DeepSpeedConfig(cfg, world_size=8)
    engine = DeepSpeedEngine(GPT2Model(cfg_model), ds_cfg,
                             mesh=build_mesh(), seed=0)

    # fixed synthetic corpus: token sequences with a learnable bigram
    # structure so the loss actually moves
    rng = np.random.default_rng(1234)
    base = rng.integers(0, 256, size=(4096,), dtype=np.int32)
    batch_tokens = ds_cfg.train_batch_size
    curve = []
    for step in range(STEPS):
        idx = rng.integers(0, len(base) - 34, size=(batch_tokens,))
        batch = np.stack([base[i:i + 34] for i in idx])
        loss = engine.train_batch(batch)
        curve.append(round(float(np.asarray(loss)), 4))
    return curve


@pytest.mark.slow
@pytest.mark.parametrize("name", sorted(CASES))
def test_loss_curve_matches_baseline(name):
    if not os.path.exists(BASELINE_PATH):
        pytest.skip("no baselines recorded; run --regen")
    baselines = json.load(open(BASELINE_PATH))
    if name not in baselines:
        pytest.skip(f"no baseline for {name}; run --regen")
    expect = baselines[name]
    got = _train_curve(**CASES[name])
    # end-of-training convergence level must match
    assert abs(got[-1] - expect[-1]) < 0.15, (name, got[-1], expect[-1])
    # the whole trajectory must track the recorded curve
    diffs = [abs(a - b) for a, b in zip(got, expect)]
    assert max(diffs) < 0.25, (name, max(diffs))
    # and training must actually have learned something (margin well under
    # the drift tolerance above so platform drift can't flip it)
    assert got[-1] < got[0] - 0.05, (name, got[0], got[-1])


def _regen():
    out = {}
    for name, kw in sorted(CASES.items()):
        out[name] = _train_curve(**kw)
        print(f"{name}: {out[name][0]} -> {out[name][-1]}")
    json.dump(out, open(BASELINE_PATH, "w"), indent=1)
    print(f"wrote {BASELINE_PATH}")


if __name__ == "__main__":
    import sys
    if "--regen" in sys.argv:
        # standalone run: set up the same 8-device virtual CPU mesh that
        # conftest.py provides under pytest (and pin away from the
        # force-registered TPU platform) BEFORE jax backend init
        flags = os.environ.get("XLA_FLAGS", "")
        if "host_platform_device_count" not in flags:
            os.environ["XLA_FLAGS"] = (
                flags + " --xla_force_host_platform_device_count=8").strip()
        import jax
        jax.config.update("jax_platforms", "cpu")
        sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))
        sys.path.insert(0, os.path.dirname(
            os.path.dirname(os.path.abspath(__file__))))
        _regen()
    else:
        print(__doc__)
