"""jaxlint: fixture-driven rule tests + the tier-1 regression gate.

The gate (test_tree_is_clean) runs the full pass over ``deepspeed_tpu/``
and fails on any non-baselined finding — the linter IS a permanent
regression gate, not an advisory tool.  Pure-stdlib: no jax import
needed, so these tests run even where jax is broken.
"""
import json
import os
import subprocess
import sys
import time

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
FIXTURES = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                        "jaxlint_fixtures")
sys.path.insert(0, REPO)

from tools.jaxlint import lint_paths, load_baseline          # noqa: E402
from tools.jaxlint.core import (default_baseline_path,       # noqa: E402
                                lint_file, lint_source, write_baseline)


def _rules(path):
    return sorted({f.rule for f in lint_file(path)})


def _fixture(name):
    return os.path.join(FIXTURES, name)


# ---------------------------------------------------------------------------
# per-rule positive/negative fixtures
# ---------------------------------------------------------------------------

@pytest.mark.parametrize("rule,bad,good", [
    ("JL001", "jl001_bad.py", "jl001_good.py"),
    ("JL002", "jl002_bad.py", "jl002_good.py"),
    ("JL003", "jl003_bad.py", "jl003_good.py"),
    ("JL004", "jl004_bad.py", "jl004_good.py"),
    ("JL005", "jl005_bad.py", "jl005_good.py"),
    ("JL006", "jl006_bad.py", "jl006_good.py"),
    ("JL007", "jl007_bad.py", "jl007_good.py"),
    ("JL008", "jl008_bad.py", "jl008_good.py"),
    ("JL009", "jl009_bad.py", "jl009_good.py"),
    ("JL010", "jl010_bad.py", "jl010_good.py"),
    ("JL101", os.path.join("jl101", "config_bad.py"),
     os.path.join("jl101", "config_good.py")),
])
def test_rule_fires_on_bad_and_not_on_good(rule, bad, good):
    assert rule in _rules(_fixture(bad)), \
        f"{rule} must fire on {bad}"
    assert rule not in _rules(_fixture(good)), \
        f"{rule} must stay silent on {good}"


def test_jl001_flags_every_sync_shape():
    lines = {f.line for f in lint_file(_fixture("jl001_bad.py"))
             if f.rule == "JL001"}
    # np.asarray, .item via helper, float via wrap-assign, self-method
    assert len(lines) == 4, lines


def test_jl002_alias_and_argname_forms():
    msgs = [f.message for f in lint_file(_fixture("jl002_bad.py"))
            if f.rule == "JL002"]
    assert len(msgs) == 3
    assert any("self.state" in m for m in msgs)   # attribute alias caught


def test_jl003_sibling_pinning_heuristic():
    findings = [f for f in lint_file(_fixture("jl003_bad.py"))
                if f.rule == "JL003"]
    assert len(findings) == 2
    assert any("in_shardings" in f.message for f in findings)
    assert any("sibling" in f.message for f in findings)


def test_jl004_all_side_effect_shapes():
    cats = [f.message for f in lint_file(_fixture("jl004_bad.py"))
            if f.rule == "JL004"]
    assert len(cats) == 4
    joined = "\n".join(cats)
    for needle in ("assignment to 'self.last_state'", "'print'",
                   "'.append'", "'global'"):
        assert needle in joined, (needle, joined)


def test_jl006_both_delta_shapes_and_sync_kinds():
    """Direct-call delta AND two-stored-reads delta fire; every sync
    shape in the good fixture (block_until_ready, np.asarray
    materialization, no-device-work) stays silent (covered by the
    parametrized good-file check; here: exactly the two bad lines)."""
    findings = [f for f in lint_file(_fixture("jl006_bad.py"))
                if f.rule == "JL006"]
    assert len(findings) == 2, [(f.line, f.message) for f in findings]
    msgs = "\n".join(f.message for f in findings)
    assert "ENQUEUE latency" in msgs
    assert "'compiled'" in msgs      # known-jitted callable detected
    assert "'step_fn'" in msgs       # compiled-step naming heuristic


def test_jl006_ignores_traced_bodies():
    """Clocks inside jit-traced code are JL005's finding, not JL006's."""
    src = (
        "import jax, time\n"
        "@jax.jit\n"
        "def f(x):\n"
        "    t0 = time.time()\n"
        "    y = jax.numpy.sin(x)\n"
        "    return y, time.time() - t0\n")
    rules = {f.rule for f in lint_source(src, path="t.py")}
    assert "JL006" not in rules
    assert "JL005" in rules


def test_jl101_finding_kinds():
    msgs = "\n".join(f.message for f in
                     lint_file(_fixture(os.path.join("jl101",
                                                     "config_bad.py")))
                     if f.rule == "JL101")
    assert "unknown config key constant C.MISSING_KEY" in msgs
    assert "'raw_key' bypasses constants.py" in msgs
    assert "defaultless read of C.STEPS" in msgs
    assert "cross-wired" in msgs


# ---------------------------------------------------------------------------
# suppression + baseline machinery
# ---------------------------------------------------------------------------

def test_suppression_same_line_and_line_above():
    src = (
        "import jax, numpy as np\n"
        "@jax.jit\n"
        "def f(x):\n"
        "    return np.asarray(x)  # jaxlint: disable=JL001\n"
        "@jax.jit\n"
        "def g(x):\n"
        "    # jaxlint: disable\n"
        "    return np.asarray(x)\n"
        "@jax.jit\n"
        "def h(x):\n"
        "    return np.asarray(x)  # jaxlint: disable=JL999\n"
    )
    findings = lint_source(src, path="t.py")
    # only h's survives: its comment disables a different rule
    assert [(f.rule, f.line) for f in findings] == [("JL001", 11)]


def test_baseline_roundtrip(tmp_path):
    src = "import jax, numpy as np\n@jax.jit\ndef f(x):\n    return np.asarray(x)\n"
    bad = tmp_path / "mod.py"
    bad.write_text(src)
    findings = lint_file(str(bad))
    assert findings
    bl_path = tmp_path / "baseline.json"
    write_baseline(findings, str(bl_path))
    baseline = load_baseline(str(bl_path))
    assert all(f.key() in baseline for f in findings)
    # baseline keys are line-number independent: shifting the file down
    # must not un-baseline the finding
    bad.write_text("# a new comment line\n" + src)
    shifted = lint_file(str(bad))
    assert shifted and all(f.key() in baseline for f in shifted)


def test_syntax_error_is_a_finding():
    findings = lint_source("def broken(:\n", path="b.py")
    assert [f.rule for f in findings] == ["JL000"]


def test_decorator_jit_call_registers_once():
    """@jax.jit(...) must not be double-registered by the plain-call walk
    (duplicate findings + a phantom non-decorator site that defeats
    JL003's sibling heuristic)."""
    src = ("import jax\n"
           "@jax.jit(in_shardings=(None,))\n"
           "def f(x):\n"
           "    return x\n")
    findings = lint_source(src, path="t.py")
    assert [(f.rule, f.line) for f in findings] == [("JL003", 2)]


def test_write_baseline_preserves_justifications(tmp_path):
    src = "import jax, numpy as np\n@jax.jit\ndef f(x):\n    return np.asarray(x)\n"
    bad = tmp_path / "mod.py"
    bad.write_text(src)
    findings = lint_file(str(bad))
    bl = tmp_path / "baseline.json"
    write_baseline(findings, str(bl))
    data = json.loads(bl.read_text())
    data["findings"][0]["why"] = "accepted: legacy module"
    bl.write_text(json.dumps(data))
    write_baseline(findings, str(bl))          # regenerate
    again = json.loads(bl.read_text())
    assert again["findings"][0]["why"] == "accepted: legacy module"


def test_nonexistent_path_is_an_error(tmp_path):
    with pytest.raises(FileNotFoundError):
        lint_paths([str(tmp_path / "no_such_dir")])
    proc = subprocess.run(
        [sys.executable, "-m", "tools.jaxlint", "deepspeed_tpuu"],
        cwd=REPO, capture_output=True, text=True, timeout=120)
    assert proc.returncode == 2
    assert "no such file" in proc.stderr


# ---------------------------------------------------------------------------
# the tier-1 gate + CLI contract
# ---------------------------------------------------------------------------

def test_tree_is_clean():
    """The permanent regression gate: zero non-baselined findings over
    the whole package.  Fix new findings (or suppress inline with a
    justification; baseline only with a 'why' — docs/jaxlint.md)."""
    findings = lint_paths([os.path.join(REPO, "deepspeed_tpu")])
    baseline = load_baseline()
    rel = []
    for f in findings:
        key = f.key().replace(REPO + os.sep, "")
        if key not in baseline and f.key() not in baseline:
            rel.append(f.render())
    assert not rel, "new jaxlint findings:\n" + "\n".join(rel)


def test_baseline_entries_are_justified():
    """Every baselined finding must carry a non-empty 'why'."""
    path = default_baseline_path()
    with open(path) as fh:
        data = json.load(fh)
    for entry in data.get("findings", []):
        assert isinstance(entry, dict) and entry.get("why"), \
            f"baseline entry without justification: {entry}"


def test_cli_runs_clean_from_repo_root():
    """``python -m tools.jaxlint deepspeed_tpu/ --format=github`` is the
    CI entry point and must exit 0 on the current tree with no deps
    beyond the stdlib."""
    proc = subprocess.run(
        [sys.executable, "-m", "tools.jaxlint", "deepspeed_tpu",
         "--format=github"],
        cwd=REPO, capture_output=True, text=True, timeout=300)
    assert proc.returncode == 0, proc.stdout + proc.stderr


def test_cli_reports_findings_in_github_format(tmp_path):
    bad = tmp_path / "mod.py"
    bad.write_text(
        "import jax, numpy as np\n@jax.jit\ndef f(x):\n"
        "    return np.asarray(x)\n")
    proc = subprocess.run(
        [sys.executable, "-m", "tools.jaxlint", str(bad),
         "--format=github", "--no-baseline"],
        cwd=REPO, capture_output=True, text=True, timeout=300)
    assert proc.returncode == 1
    assert "::error file=" in proc.stdout
    assert "JL001" in proc.stdout


def test_jl007_exemption_is_runtime_stages_only():
    """The JL007 exemption matches the FULL package path suffix
    deepspeed_tpu/runtime/stages.py — a future serving/stages.py, a
    nested .../runtime/stages.py, or any other stages.py basename does
    NOT inherit the right to construct raw daemon threads."""
    src = ("import threading\n"
           "threading.Thread(target=print, daemon=True).start()\n")
    exempt = os.path.join("deepspeed_tpu", "runtime", "stages.py")
    assert not [f for f in lint_source(src, path=exempt)
                if f.rule == "JL007"]
    for path in (os.path.join("deepspeed_tpu", "serving", "stages.py"),
                 "stages.py",
                 os.path.join("deepspeed_tpu", "runtime", "other.py"),
                 os.path.join("deepspeed_tpu", "serving", "runtime",
                              "stages.py")):
        assert [f for f in lint_source(src, path=path)
                if f.rule == "JL007"], path


def test_cli_list_rules_covers_all_ids():
    proc = subprocess.run(
        [sys.executable, "-m", "tools.jaxlint", "--list-rules"],
        cwd=REPO, capture_output=True, text=True, timeout=120)
    assert proc.returncode == 0
    for rule_id in ("JL001", "JL002", "JL003", "JL004", "JL005", "JL006",
                    "JL007", "JL101"):
        assert rule_id in proc.stdout


def test_disk_offload_is_clean_with_empty_baseline():
    """The disk offload tier (runtime/disk_offload.py) is JL001-JL007
    clean WITHOUT any baseline entries — its bitwise-vs-host contract
    depends on the stage runtime's thread discipline (JL007) and on
    never timing a dispatch as a transfer (JL006), so no finding there
    may ever be baselined (the serving-subsystem rule, applied to the
    new module)."""
    findings = lint_paths([os.path.join(REPO, "deepspeed_tpu", "runtime",
                                        "disk_offload.py")])
    assert not findings, "\n".join(f.render() for f in findings)
    baseline = load_baseline()
    prefix = os.path.join("deepspeed_tpu", "runtime", "disk_offload.py")
    assert not [k for k in baseline if prefix in k]


def test_serving_subsystem_is_clean_with_empty_baseline():
    """The serving engine (deepspeed_tpu/inference/) is JL001-JL007
    clean WITHOUT any baseline entries — the one-compiled-decode-
    program contract (docs/serving.md) depends on staying JL005/JL006
    clean by construction, so no finding there may ever be baselined."""
    findings = lint_paths([os.path.join(REPO, "deepspeed_tpu",
                                        "inference")])
    assert not findings, "\n".join(f.render() for f in findings)
    baseline = load_baseline()
    inference_prefix = os.path.join("deepspeed_tpu", "inference")
    assert not [k for k in baseline if inference_prefix in k]


def test_kv_tier_is_clean_with_empty_baseline():
    """The KV tiering plane (inference/kv_tier.py) is JL001-JL007
    clean WITHOUT any baseline entries — its bitwise-resume contract
    (docs/serving.md "KV tiering") depends on the page export/import
    seams staying on the stage runtime's thread plane (JL007) and on
    the serving subsystem's JL005/JL006 discipline, so no finding
    there may ever be baselined."""
    findings = lint_paths([os.path.join(REPO, "deepspeed_tpu",
                                        "inference", "kv_tier.py")])
    assert not findings, "\n".join(f.render() for f in findings)
    baseline = load_baseline()
    prefix = os.path.join("deepspeed_tpu", "inference", "kv_tier.py")
    assert not [k for k in baseline if prefix in k]


def test_adapter_plane_is_clean_with_empty_baseline():
    """The multi-tenant adapter plane (inference/adapters.py) is
    JL001-JL007 clean WITHOUT any baseline entries — its zero-recompile
    contract (traced adapter-table indirection, docs/serving.md
    "multi-tenant serving") depends on the same JL005/JL006 discipline
    as the rest of the serving subsystem, and its host->HBM fetch must
    stay on the stage runtime's thread plane (JL007), so no finding
    there may ever be baselined."""
    findings = lint_paths([os.path.join(REPO, "deepspeed_tpu",
                                        "inference", "adapters.py")])
    assert not findings, "\n".join(f.render() for f in findings)
    baseline = load_baseline()
    prefix = os.path.join("deepspeed_tpu", "inference", "adapters.py")
    assert not [k for k in baseline if prefix in k]


# ---------------------------------------------------------------------------
# v2: interprocedural rules + the cross-artifact contract registry
# ---------------------------------------------------------------------------

CONTRACTS = os.path.join(FIXTURES, "contracts")


def test_jl008_flags_both_per_file_shapes():
    """Blocking put outside the worker closure AND the Thread
    assignment alias, in one fixture."""
    findings = [f for f in lint_file(_fixture("jl008_bad.py"))
                if f.rule == "JL008"]
    assert len(findings) == 2, [(f.line, f.message) for f in findings]
    msgs = "\n".join(f.message for f in findings)
    assert "blocking Channel.put" in msgs
    assert "assignment alias" in msgs


def test_jl009_names_the_reader_method():
    [f] = [f for f in lint_file(_fixture("jl009_bad.py"))
           if f.rule == "JL009"]
    assert "self.params" in f.message
    assert "snapshot()" in f.message


def test_jl010_anchors_at_the_dead_rebinding():
    [f] = [f for f in lint_file(_fixture("jl010_bad.py"))
           if f.rule == "JL010"]
    assert "scaled_loss" in f.message
    assert "scale = scale + 0.01" in f.line_text.strip()


def test_contracts_good_project_is_clean():
    """The good mini-project satisfies every cross-artifact contract:
    full v2 lint (per-file + project rules) reports nothing."""
    findings = lint_paths([os.path.join(CONTRACTS, "good")])
    assert findings == [], "\n".join(f.render() for f in findings)


def test_contracts_bad_project_catches_every_violation_class():
    findings = lint_paths([os.path.join(CONTRACTS, "bad")])
    msgs = [f"{f.rule} {f.message}" for f in findings]
    expected = [
        ("JL008", "Stage('mystery') is not in the stage registry"),
        ("JL102", "metric 'fixture_orphan_total' is emitted without HELP"),
        ("JL102", "'fixture_orphan_total' is emitted here but consumed"),
        ("JL102", "sync scalar 'fixture_dead_s' is emitted here but"),
        ("JL102", "'fixture_ghost_s' is read here but no engine"),
        ("JL102", "pins 'fixture_missing_speedup' but no committed"),
        ("JL102", "documented metric 'fixture_phantom_total' does not"),
        ("JL103", "`loader`:`vanished` does not exist in code"),
        ("JL103", "('writer', 'flush') is live here but missing"),
        ("JL103", "fence token 'ghost' is not a StageGraph.register"),
        ("JL104", "'ORPHAN_DEFAULT' has no matching key constant"),
        ("JL104", "'TIMEOUT_DEFAULT' is never referenced outside"),
        ("JL104", "config key constant 'DEAD_KEY'"),
    ]
    for rule, needle in expected:
        assert any(m.startswith(rule) and needle in m for m in msgs), \
            f"missing: {rule} ...{needle}...\ngot:\n" + "\n".join(msgs)
    assert len(findings) == len(expected), "\n".join(msgs)


def test_contract_findings_are_suppressible_inline(tmp_path):
    """Inline '# jaxlint: disable=JL10x' works for project-level
    findings exactly like per-file ones (same definition)."""
    import shutil
    proj = tmp_path / "proj"
    shutil.copytree(os.path.join(CONTRACTS, "bad"), proj)
    tel = proj / "pkg" / "telemetry.py"
    src = tel.read_text()
    src = src.replace(
        '        self.ticks = reg.counter("fixture_orphan_total")',
        '        # jaxlint: disable=JL102\n'
        '        self.ticks = reg.counter("fixture_orphan_total")')
    tel.write_text(src)
    findings = lint_paths([str(proj)])
    assert not [f for f in findings
                if "fixture_orphan_total" in f.message], \
        "\n".join(f.render() for f in findings)


def test_registry_dump_matches_golden():
    proc = subprocess.run(
        [sys.executable, "-m", "tools.jaxlint", "--registry-dump",
         os.path.join(CONTRACTS, "good")],
        cwd=REPO, capture_output=True, text=True, timeout=120)
    assert proc.returncode == 0, proc.stderr
    dump = json.loads(proc.stdout)
    assert dump.pop("root").endswith(os.path.join("contracts", "good"))
    with open(os.path.join(CONTRACTS, "good_registry.json")) as f:
        golden = json.load(f)
    assert dump == golden


def test_registry_dump_without_root_is_usage_error(tmp_path):
    (tmp_path / "m.py").write_text("x = 1\n")
    proc = subprocess.run(
        [sys.executable, "-m", "tools.jaxlint", "--registry-dump",
         str(tmp_path)],
        cwd=REPO, capture_output=True, text=True, timeout=120)
    assert proc.returncode == 2
    assert "no project root" in proc.stderr


def test_missing_baseline_is_typed_error(tmp_path):
    from tools.jaxlint.core import BaselineError
    missing = tmp_path / "nope.json"
    with pytest.raises(BaselineError) as ei:
        load_baseline(str(missing))
    assert str(missing) in str(ei.value)
    proc = subprocess.run(
        [sys.executable, "-m", "tools.jaxlint",
         os.path.join("deepspeed_tpu", "telemetry"),
         "--baseline", str(missing)],
        cwd=REPO, capture_output=True, text=True, timeout=300)
    assert proc.returncode == 2
    assert str(missing) in proc.stderr


def test_corrupt_baseline_is_typed_error(tmp_path):
    from tools.jaxlint.core import BaselineError
    bad = tmp_path / "corrupt.json"
    bad.write_text("{not json")
    with pytest.raises(BaselineError) as ei:
        load_baseline(str(bad))
    assert str(bad) in str(ei.value)
    bad.write_text(json.dumps({"findings": "wrong-shape"}))
    with pytest.raises(BaselineError):
        load_baseline(str(bad))
    proc = subprocess.run(
        [sys.executable, "-m", "tools.jaxlint",
         os.path.join("deepspeed_tpu", "telemetry"),
         "--baseline", str(bad)],
        cwd=REPO, capture_output=True, text=True, timeout=300)
    assert proc.returncode == 2
    assert str(bad) in proc.stderr


def test_github_format_paths_are_root_relative_regardless_of_cwd(tmp_path):
    """CI annotations must name repo-relative files no matter where the
    runner invoked the linter from."""
    bad_proj = os.path.join(CONTRACTS, "bad")
    env = dict(os.environ, PYTHONPATH=REPO)
    runs = []
    for cwd in (REPO, str(tmp_path)):
        proc = subprocess.run(
            [sys.executable, "-m", "tools.jaxlint", bad_proj,
             "--format=github", "--no-baseline"],
            cwd=cwd, capture_output=True, text=True, timeout=300, env=env)
        assert proc.returncode == 1, proc.stdout + proc.stderr
        runs.append(sorted(l for l in proc.stdout.splitlines()
                           if l.startswith("::error")))
    assert runs[0] == runs[1]
    assert any("file=pkg/worker.py" in l for l in runs[0]), runs[0]


def test_inference_telemetry_tools_clean_under_full_v2_rules():
    """The v2 gate: the serving plane, the telemetry plane and the
    tools themselves are clean under the FULL rule set (JL001-JL010 +
    JL101-JL104) with the baseline EMPTY."""
    findings = lint_paths([
        os.path.join(REPO, "deepspeed_tpu", "inference"),
        os.path.join(REPO, "deepspeed_tpu", "telemetry"),
        os.path.join(REPO, "tools")])
    assert findings == [], "\n".join(f.render() for f in findings)


def test_baseline_is_empty():
    """v2 acceptance: all real drift is FIXED, not baselined.  The only
    accepted exceptions are inline suppressions with justification
    comments at the site."""
    assert load_baseline() == {}


def test_contracts_only_preflight_budget():
    t0 = time.time()
    proc = subprocess.run(
        [sys.executable, "-m", "tools.jaxlint", "--contracts-only",
         "deepspeed_tpu", "tools"],
        cwd=REPO, capture_output=True, text=True, timeout=60)
    dt = time.time() - t0
    assert proc.returncode == 0, proc.stdout + proc.stderr
    assert dt < 10.0, f"--contracts-only took {dt:.1f}s (budget: 10s)"


def test_full_tree_run_budget():
    t0 = time.time()
    proc = subprocess.run(
        [sys.executable, "-m", "tools.jaxlint", "deepspeed_tpu", "tools"],
        cwd=REPO, capture_output=True, text=True, timeout=120)
    dt = time.time() - t0
    assert proc.returncode == 0, proc.stdout + proc.stderr
    assert dt < 30.0, f"full tree-wide run took {dt:.1f}s (budget: 30s)"


# ---------------------------------------------------------------------------
# pins for the drift the v2 contract passes surfaced (fixed in-tree)
# ---------------------------------------------------------------------------

def test_jl008_suppressions_carry_justifications():
    """The two deliberate blocking puts (serve admission, disk-tier
    bounded-RAM streaming) are suppressed INLINE with a reason — not
    baselined, not silently exempted."""
    for rel in (os.path.join("deepspeed_tpu", "inference", "engine.py"),
                os.path.join("deepspeed_tpu", "runtime",
                             "disk_offload.py")):
        with open(os.path.join(REPO, rel)) as f:
            src = f.read()
        assert "# jaxlint: disable=JL008" in src, rel
        before = src.split("# jaxlint: disable=JL008")[0]
        assert "backpressure" in before.rsplit("\n\n", 1)[-1].lower() \
            or "backpressure" in "\n".join(
                before.splitlines()[-8:]).lower(), \
            f"{rel}: JL008 suppression without a justification comment"


def test_jl006_dispatch_delta_is_inline_suppressed_not_baselined():
    with open(os.path.join(REPO, "deepspeed_tpu", "runtime",
                           "engine.py")) as f:
        src = f.read()
    assert "# jaxlint: disable=JL006" in src
    assert "dispatch-only delta by design" in src


def test_real_tree_registry_pins_the_fixed_drift():
    """docs fence tokens name real StageGraph entries, the serving
    prefix-miss counter is documented, and the offload attribution
    scalars have summarize consumers."""
    from tools.jaxlint.registry import ProjectRegistry
    reg = ProjectRegistry.build(REPO)
    drain_names = {n for entries in reg.drain_orders.values()
                   for n, _l in entries}
    for tok, _f, _l in reg.docs_drain:
        assert tok in drain_names, \
            f"docs drain fence token {tok!r} not registered"
    assert "serve_prefix_misses_total" in {n for n, _f, _l
                                           in reg.docs_metrics}
    for name in ("offload_h2d_s", "offload_cpu_adam_s"):
        assert name in reg.scalars, name
        assert name in reg.scalar_reads, \
            f"{name} emitted but summarize never reads it"
