"""jaxlint: fixture-driven rule tests + the tier-1 regression gate.

The gate (test_tree_is_clean) runs the full pass over ``deepspeed_tpu/``
and fails on any non-baselined finding — the linter IS a permanent
regression gate, not an advisory tool.  Pure-stdlib: no jax import
needed, so these tests run even where jax is broken.
"""
import json
import os
import subprocess
import sys

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
FIXTURES = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                        "jaxlint_fixtures")
sys.path.insert(0, REPO)

from tools.jaxlint import lint_paths, load_baseline          # noqa: E402
from tools.jaxlint.core import (default_baseline_path,       # noqa: E402
                                lint_file, lint_source, write_baseline)


def _rules(path):
    return sorted({f.rule for f in lint_file(path)})


def _fixture(name):
    return os.path.join(FIXTURES, name)


# ---------------------------------------------------------------------------
# per-rule positive/negative fixtures
# ---------------------------------------------------------------------------

@pytest.mark.parametrize("rule,bad,good", [
    ("JL001", "jl001_bad.py", "jl001_good.py"),
    ("JL002", "jl002_bad.py", "jl002_good.py"),
    ("JL003", "jl003_bad.py", "jl003_good.py"),
    ("JL004", "jl004_bad.py", "jl004_good.py"),
    ("JL005", "jl005_bad.py", "jl005_good.py"),
    ("JL006", "jl006_bad.py", "jl006_good.py"),
    ("JL007", "jl007_bad.py", "jl007_good.py"),
    ("JL101", os.path.join("jl101", "config_bad.py"),
     os.path.join("jl101", "config_good.py")),
])
def test_rule_fires_on_bad_and_not_on_good(rule, bad, good):
    assert rule in _rules(_fixture(bad)), \
        f"{rule} must fire on {bad}"
    assert rule not in _rules(_fixture(good)), \
        f"{rule} must stay silent on {good}"


def test_jl001_flags_every_sync_shape():
    lines = {f.line for f in lint_file(_fixture("jl001_bad.py"))
             if f.rule == "JL001"}
    # np.asarray, .item via helper, float via wrap-assign, self-method
    assert len(lines) == 4, lines


def test_jl002_alias_and_argname_forms():
    msgs = [f.message for f in lint_file(_fixture("jl002_bad.py"))
            if f.rule == "JL002"]
    assert len(msgs) == 3
    assert any("self.state" in m for m in msgs)   # attribute alias caught


def test_jl003_sibling_pinning_heuristic():
    findings = [f for f in lint_file(_fixture("jl003_bad.py"))
                if f.rule == "JL003"]
    assert len(findings) == 2
    assert any("in_shardings" in f.message for f in findings)
    assert any("sibling" in f.message for f in findings)


def test_jl004_all_side_effect_shapes():
    cats = [f.message for f in lint_file(_fixture("jl004_bad.py"))
            if f.rule == "JL004"]
    assert len(cats) == 4
    joined = "\n".join(cats)
    for needle in ("assignment to 'self.last_state'", "'print'",
                   "'.append'", "'global'"):
        assert needle in joined, (needle, joined)


def test_jl006_both_delta_shapes_and_sync_kinds():
    """Direct-call delta AND two-stored-reads delta fire; every sync
    shape in the good fixture (block_until_ready, np.asarray
    materialization, no-device-work) stays silent (covered by the
    parametrized good-file check; here: exactly the two bad lines)."""
    findings = [f for f in lint_file(_fixture("jl006_bad.py"))
                if f.rule == "JL006"]
    assert len(findings) == 2, [(f.line, f.message) for f in findings]
    msgs = "\n".join(f.message for f in findings)
    assert "ENQUEUE latency" in msgs
    assert "'compiled'" in msgs      # known-jitted callable detected
    assert "'step_fn'" in msgs       # compiled-step naming heuristic


def test_jl006_ignores_traced_bodies():
    """Clocks inside jit-traced code are JL005's finding, not JL006's."""
    src = (
        "import jax, time\n"
        "@jax.jit\n"
        "def f(x):\n"
        "    t0 = time.time()\n"
        "    y = jax.numpy.sin(x)\n"
        "    return y, time.time() - t0\n")
    rules = {f.rule for f in lint_source(src, path="t.py")}
    assert "JL006" not in rules
    assert "JL005" in rules


def test_jl101_finding_kinds():
    msgs = "\n".join(f.message for f in
                     lint_file(_fixture(os.path.join("jl101",
                                                     "config_bad.py")))
                     if f.rule == "JL101")
    assert "unknown config key constant C.MISSING_KEY" in msgs
    assert "'raw_key' bypasses constants.py" in msgs
    assert "defaultless read of C.STEPS" in msgs
    assert "cross-wired" in msgs


# ---------------------------------------------------------------------------
# suppression + baseline machinery
# ---------------------------------------------------------------------------

def test_suppression_same_line_and_line_above():
    src = (
        "import jax, numpy as np\n"
        "@jax.jit\n"
        "def f(x):\n"
        "    return np.asarray(x)  # jaxlint: disable=JL001\n"
        "@jax.jit\n"
        "def g(x):\n"
        "    # jaxlint: disable\n"
        "    return np.asarray(x)\n"
        "@jax.jit\n"
        "def h(x):\n"
        "    return np.asarray(x)  # jaxlint: disable=JL999\n"
    )
    findings = lint_source(src, path="t.py")
    # only h's survives: its comment disables a different rule
    assert [(f.rule, f.line) for f in findings] == [("JL001", 11)]


def test_baseline_roundtrip(tmp_path):
    src = "import jax, numpy as np\n@jax.jit\ndef f(x):\n    return np.asarray(x)\n"
    bad = tmp_path / "mod.py"
    bad.write_text(src)
    findings = lint_file(str(bad))
    assert findings
    bl_path = tmp_path / "baseline.json"
    write_baseline(findings, str(bl_path))
    baseline = load_baseline(str(bl_path))
    assert all(f.key() in baseline for f in findings)
    # baseline keys are line-number independent: shifting the file down
    # must not un-baseline the finding
    bad.write_text("# a new comment line\n" + src)
    shifted = lint_file(str(bad))
    assert shifted and all(f.key() in baseline for f in shifted)


def test_syntax_error_is_a_finding():
    findings = lint_source("def broken(:\n", path="b.py")
    assert [f.rule for f in findings] == ["JL000"]


def test_decorator_jit_call_registers_once():
    """@jax.jit(...) must not be double-registered by the plain-call walk
    (duplicate findings + a phantom non-decorator site that defeats
    JL003's sibling heuristic)."""
    src = ("import jax\n"
           "@jax.jit(in_shardings=(None,))\n"
           "def f(x):\n"
           "    return x\n")
    findings = lint_source(src, path="t.py")
    assert [(f.rule, f.line) for f in findings] == [("JL003", 2)]


def test_write_baseline_preserves_justifications(tmp_path):
    src = "import jax, numpy as np\n@jax.jit\ndef f(x):\n    return np.asarray(x)\n"
    bad = tmp_path / "mod.py"
    bad.write_text(src)
    findings = lint_file(str(bad))
    bl = tmp_path / "baseline.json"
    write_baseline(findings, str(bl))
    data = json.loads(bl.read_text())
    data["findings"][0]["why"] = "accepted: legacy module"
    bl.write_text(json.dumps(data))
    write_baseline(findings, str(bl))          # regenerate
    again = json.loads(bl.read_text())
    assert again["findings"][0]["why"] == "accepted: legacy module"


def test_nonexistent_path_is_an_error(tmp_path):
    with pytest.raises(FileNotFoundError):
        lint_paths([str(tmp_path / "no_such_dir")])
    proc = subprocess.run(
        [sys.executable, "-m", "tools.jaxlint", "deepspeed_tpuu"],
        cwd=REPO, capture_output=True, text=True, timeout=120)
    assert proc.returncode == 2
    assert "no such file" in proc.stderr


# ---------------------------------------------------------------------------
# the tier-1 gate + CLI contract
# ---------------------------------------------------------------------------

def test_tree_is_clean():
    """The permanent regression gate: zero non-baselined findings over
    the whole package.  Fix new findings (or suppress inline with a
    justification; baseline only with a 'why' — docs/jaxlint.md)."""
    findings = lint_paths([os.path.join(REPO, "deepspeed_tpu")])
    baseline = load_baseline()
    rel = []
    for f in findings:
        key = f.key().replace(REPO + os.sep, "")
        if key not in baseline and f.key() not in baseline:
            rel.append(f.render())
    assert not rel, "new jaxlint findings:\n" + "\n".join(rel)


def test_baseline_entries_are_justified():
    """Every baselined finding must carry a non-empty 'why'."""
    path = default_baseline_path()
    with open(path) as fh:
        data = json.load(fh)
    for entry in data.get("findings", []):
        assert isinstance(entry, dict) and entry.get("why"), \
            f"baseline entry without justification: {entry}"


def test_cli_runs_clean_from_repo_root():
    """``python -m tools.jaxlint deepspeed_tpu/ --format=github`` is the
    CI entry point and must exit 0 on the current tree with no deps
    beyond the stdlib."""
    proc = subprocess.run(
        [sys.executable, "-m", "tools.jaxlint", "deepspeed_tpu",
         "--format=github"],
        cwd=REPO, capture_output=True, text=True, timeout=300)
    assert proc.returncode == 0, proc.stdout + proc.stderr


def test_cli_reports_findings_in_github_format(tmp_path):
    bad = tmp_path / "mod.py"
    bad.write_text(
        "import jax, numpy as np\n@jax.jit\ndef f(x):\n"
        "    return np.asarray(x)\n")
    proc = subprocess.run(
        [sys.executable, "-m", "tools.jaxlint", str(bad),
         "--format=github", "--baseline", str(tmp_path / "none.json")],
        cwd=REPO, capture_output=True, text=True, timeout=300)
    assert proc.returncode == 1
    assert "::error file=" in proc.stdout
    assert "JL001" in proc.stdout


def test_jl007_exemption_is_runtime_stages_only():
    """The JL007 exemption matches the FULL package path suffix
    deepspeed_tpu/runtime/stages.py — a future serving/stages.py, a
    nested .../runtime/stages.py, or any other stages.py basename does
    NOT inherit the right to construct raw daemon threads."""
    src = ("import threading\n"
           "threading.Thread(target=print, daemon=True).start()\n")
    exempt = os.path.join("deepspeed_tpu", "runtime", "stages.py")
    assert not [f for f in lint_source(src, path=exempt)
                if f.rule == "JL007"]
    for path in (os.path.join("deepspeed_tpu", "serving", "stages.py"),
                 "stages.py",
                 os.path.join("deepspeed_tpu", "runtime", "other.py"),
                 os.path.join("deepspeed_tpu", "serving", "runtime",
                              "stages.py")):
        assert [f for f in lint_source(src, path=path)
                if f.rule == "JL007"], path


def test_cli_list_rules_covers_all_ids():
    proc = subprocess.run(
        [sys.executable, "-m", "tools.jaxlint", "--list-rules"],
        cwd=REPO, capture_output=True, text=True, timeout=120)
    assert proc.returncode == 0
    for rule_id in ("JL001", "JL002", "JL003", "JL004", "JL005", "JL006",
                    "JL007", "JL101"):
        assert rule_id in proc.stdout


def test_disk_offload_is_clean_with_empty_baseline():
    """The disk offload tier (runtime/disk_offload.py) is JL001-JL007
    clean WITHOUT any baseline entries — its bitwise-vs-host contract
    depends on the stage runtime's thread discipline (JL007) and on
    never timing a dispatch as a transfer (JL006), so no finding there
    may ever be baselined (the serving-subsystem rule, applied to the
    new module)."""
    findings = lint_paths([os.path.join(REPO, "deepspeed_tpu", "runtime",
                                        "disk_offload.py")])
    assert not findings, "\n".join(f.render() for f in findings)
    baseline = load_baseline()
    prefix = os.path.join("deepspeed_tpu", "runtime", "disk_offload.py")
    assert not [k for k in baseline if prefix in k]


def test_serving_subsystem_is_clean_with_empty_baseline():
    """The serving engine (deepspeed_tpu/inference/) is JL001-JL007
    clean WITHOUT any baseline entries — the one-compiled-decode-
    program contract (docs/serving.md) depends on staying JL005/JL006
    clean by construction, so no finding there may ever be baselined."""
    findings = lint_paths([os.path.join(REPO, "deepspeed_tpu",
                                        "inference")])
    assert not findings, "\n".join(f.render() for f in findings)
    baseline = load_baseline()
    inference_prefix = os.path.join("deepspeed_tpu", "inference")
    assert not [k for k in baseline if inference_prefix in k]
