"""Schedule generators as pure data — no devices needed
(mirrors reference tests/unit/test_pipe_schedule.py)."""
import pytest

from deepspeed_tpu.pipe.schedule import (
    TrainSchedule, InferenceSchedule, DataParallelSchedule,
    ForwardPass, BackwardPass, SendActivation, RecvActivation,
    SendGrad, RecvGrad, LoadMicroBatch, OptimizerStep, ReduceGrads,
    ReduceTiedGrads,
)


def _flat(sched):
    return [c for step in sched for c in step]


def _count(sched, cls):
    return sum(1 for c in _flat(sched) if isinstance(c, cls))


@pytest.mark.parametrize("micros,stages", [(1, 1), (4, 2), (8, 4), (3, 4)])
def test_train_schedule_full_coverage(micros, stages):
    """Every stage forwards and backwards every micro-batch exactly once."""
    for stage in range(stages):
        s = TrainSchedule(micro_batches=micros, stages=stages, stage_id=stage)
        cmds = _flat(s)
        assert sum(isinstance(c, ForwardPass) for c in cmds) == micros
        assert sum(isinstance(c, BackwardPass) for c in cmds) == micros
        assert sum(isinstance(c, OptimizerStep) for c in cmds) == 1
        assert sum(isinstance(c, ReduceGrads) for c in cmds) == 1
        assert sum(isinstance(c, ReduceTiedGrads) for c in cmds) == 1


def test_train_schedule_step_count():
    s = TrainSchedule(micro_batches=4, stages=2, stage_id=0)
    assert len(list(s.steps())) == 2 * (4 + 2 - 1)


def test_send_recv_pairing():
    """Stage s sends exactly as many activations as stage s+1 receives —
    AND every Send lands on the same tick as the matching neighbor Recv
    (rendezvous-p2p pairing, reference schedule.py:200-232)."""
    micros, stages = 4, 3
    steps = [list(TrainSchedule(micros, stages, s).steps())
             for s in range(stages)]
    for s in range(stages - 1):
        sends = sum(isinstance(c, SendActivation)
                    for step in steps[s] for c in step)
        recvs = sum(isinstance(c, RecvActivation)
                    for step in steps[s + 1] for c in step)
        assert sends == recvs == micros
        gsends = sum(isinstance(c, SendGrad)
                     for step in steps[s + 1] for c in step)
        grecvs = sum(isinstance(c, RecvGrad)
                     for step in steps[s] for c in step)
        assert gsends == grecvs == micros
        # same-tick pairing
        for t in range(len(steps[s])):
            n_send = sum(isinstance(c, SendActivation) for c in steps[s][t])
            n_recv = sum(isinstance(c, RecvActivation)
                         for c in steps[s + 1][t])
            assert n_send == n_recv, (s, t)
            n_gsend = sum(isinstance(c, SendGrad) for c in steps[s + 1][t])
            n_grecv = sum(isinstance(c, RecvGrad) for c in steps[s][t])
            assert n_gsend == n_grecv, (s, t)


def test_first_last_stage_no_external_comm():
    micros, stages = 4, 3
    first = TrainSchedule(micros, stages, 0)
    last = TrainSchedule(micros, stages, stages - 1)
    assert _count(first, RecvActivation) == 0
    assert _count(first, SendGrad) == 0
    assert _count(last, SendActivation) == 0
    assert _count(last, RecvGrad) == 0
    # only first/last load data (reference pipe/engine.py:612-651)
    assert _count(first, LoadMicroBatch) == micros
    assert _count(last, LoadMicroBatch) == micros
    mid = TrainSchedule(micros, stages, 1)
    assert _count(mid, LoadMicroBatch) == 0


def test_forward_before_backward_per_micro():
    s = TrainSchedule(micro_batches=4, stages=2, stage_id=1)
    seen_fwd = set()
    for c in _flat(s):
        if isinstance(c, ForwardPass):
            seen_fwd.add(c.buffer_id)
        if isinstance(c, BackwardPass):
            assert c.buffer_id in seen_fwd


def test_buffer_count():
    assert TrainSchedule(8, 4, 0).num_pipe_buffers() == 5
    assert TrainSchedule(8, 4, 3).num_pipe_buffers() == 2
    assert TrainSchedule(1, 4, 0).num_pipe_buffers() == 2


def test_inference_schedule():
    micros, stages = 4, 2
    for stage in range(stages):
        s = InferenceSchedule(micros, stages, stage)
        cmds = _flat(s)
        assert sum(isinstance(c, ForwardPass) for c in cmds) == micros
        assert sum(isinstance(c, BackwardPass) for c in cmds) == 0


def test_data_parallel_schedule():
    s = DataParallelSchedule(micro_batches=3, stages=1, stage_id=0)
    cmds = _flat(s)
    assert sum(isinstance(c, ForwardPass) for c in cmds) == 3
    assert sum(isinstance(c, OptimizerStep) for c in cmds) == 1


def test_invalid_stage_raises():
    with pytest.raises(ValueError):
        TrainSchedule(4, 2, 5)
