"""2-process × 4-device multi-host integration test (reference
@distributed_test analogue, tests/unit/common.py:14-100).

Spawns two real OS processes, each owning 4 virtual CPU devices, joined
into one 8-device jax.distributed runtime via the launcher env contract.
Exercises init_distributed, per-process batch feeding, cross-process
collectives (ZeRO-2 grad sharding), and per-process checkpoint shards
with merge-on-load.
"""
import os
import socket
import subprocess
import sys

import pytest


def _free_port():
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    return port


@pytest.mark.slow
def test_two_process_training(tmp_path):
    port = _free_port()
    workers = []
    for pid in range(2):
        env = dict(os.environ)
        env.pop("JAX_PLATFORMS", None)
        env["XLA_FLAGS"] = "--xla_force_host_platform_device_count=4"
        env["JAX_PLATFORMS"] = "cpu"
        env["JAX_COORDINATOR_ADDRESS"] = f"127.0.0.1:{port}"
        env["JAX_NUM_PROCESSES"] = "2"
        env["JAX_PROCESS_ID"] = str(pid)
        workers.append(subprocess.Popen(
            [sys.executable,
             os.path.join(os.path.dirname(__file__),
                          "multiproc_worker.py"),
             str(tmp_path)],
            env=env, stdout=subprocess.PIPE, stderr=subprocess.STDOUT,
            text=True))
    outs = []
    for pid, p in enumerate(workers):
        try:
            out, _ = p.communicate(timeout=240)
        except subprocess.TimeoutExpired:
            for w in workers:
                w.kill()
            pytest.fail(f"worker {pid} hung (reference common.py:70-84 "
                        "kills hung ranks the same way)")
        outs.append(out)
    for pid, (p, out) in enumerate(zip(workers, outs)):
        assert p.returncode == 0, f"worker {pid} failed:\n{out[-3000:]}"
        assert f"WORKER_{pid}_OK" in out, out[-3000:]
