"""2-process × 4-device multi-host integration test (reference
@distributed_test analogue, tests/unit/common.py:14-100).

Spawns two real OS processes, each owning 4 virtual CPU devices, joined
into one 8-device jax.distributed runtime via the launcher env contract.
Exercises init_distributed, per-process batch feeding, cross-process
collectives (ZeRO-2 grad sharding), and per-process checkpoint shards
with merge-on-load.
"""
import os
import socket
import subprocess
import sys

import pytest


def _free_port():
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    return port


def _run_workers(script: str, tmp_path, timeout: int = 240):
    port = _free_port()
    workers = []
    for pid in range(2):
        env = dict(os.environ)
        env.pop("JAX_PLATFORMS", None)
        env["XLA_FLAGS"] = "--xla_force_host_platform_device_count=4"
        env["JAX_PLATFORMS"] = "cpu"
        env["JAX_COORDINATOR_ADDRESS"] = f"127.0.0.1:{port}"
        env["JAX_NUM_PROCESSES"] = "2"
        env["JAX_PROCESS_ID"] = str(pid)
        workers.append(subprocess.Popen(
            [sys.executable,
             os.path.join(os.path.dirname(__file__), script),
             str(tmp_path)],
            env=env, stdout=subprocess.PIPE, stderr=subprocess.STDOUT,
            text=True))
    outs = []
    for pid, p in enumerate(workers):
        try:
            out, _ = p.communicate(timeout=timeout)
        except subprocess.TimeoutExpired:
            for w in workers:
                w.kill()
            pytest.fail(f"worker {pid} hung (reference common.py:70-84 "
                        "kills hung ranks the same way)")
        outs.append(out)
    for pid, (p, out) in enumerate(zip(workers, outs)):
        assert p.returncode == 0, f"worker {pid} failed:\n{out[-3000:]}"
        assert f"WORKER_{pid}_OK" in out, out[-3000:]
    return outs


@pytest.mark.slow
def test_two_process_training(tmp_path):
    _run_workers("multiproc_worker.py", tmp_path)


@pytest.mark.slow
def test_two_process_host_offload(tmp_path):
    """Multi-host ZeRO-Offload host tier: each process stages only its
    dp-shard of master/grads (reference stage2.py:743-900 per-DP-rank
    partitions) and the trajectory matches the single-controller tier.
    The reference trajectory is computed HERE, in this single process,
    over the same 8-device mesh and global batch."""
    import json

    import numpy as np
    import jax

    import deepspeed_tpu
    from deepspeed_tpu.parallel import build_mesh
    from simple_model import SimpleModel

    HIDDEN = 32
    mesh = build_mesh(dp=8, devices=jax.devices())
    cfg = {
        "train_micro_batch_size_per_gpu": 2,
        "gradient_accumulation_steps": 2,
        "steps_per_print": 10 ** 9,
        "bf16": {"enabled": True},
        "optimizer": {"type": "Adam", "params": {"lr": 1e-2}},
        "zero_optimization": {"stage": 2, "cpu_offload": True,
                              "offload_impl": "host"},
    }
    engine, _, _, _ = deepspeed_tpu.initialize(
        model=SimpleModel(hidden_dim=HIDDEN), config=cfg, mesh=mesh)
    assert not getattr(engine, "_offload_sharded", False)
    rng = np.random.default_rng(0)
    gx = rng.normal(size=(32, HIDDEN)).astype(np.float32)
    gy = (0.5 * gx).astype(np.float32)
    ref = [float(np.asarray(engine.train_batch((gx, gy))))
           for _ in range(5)]
    with open(os.path.join(tmp_path, "ref_losses.json"), "w") as f:
        json.dump(ref, f)

    outs = _run_workers("multiproc_offload_worker.py", tmp_path,
                        timeout=360)
    # staged bytes printed by each worker prove the per-host partition
    for out in outs:
        assert "staged=" in out

    # --- pod-shrink elasticity: the 2-process sharded save loads into
    # THIS single process's single-controller host tier (per-process
    # shard files merge on load; canonical FusedAdamState optimizer
    # plane crosses the topology change) and reproduces the workers'
    # post-restore step on the same global batch
    import re
    resume = {float(m.group(1)) for out in outs
              for m in [re.search(r"resume=([0-9.]+)", out)] if m}
    assert len(resume) == 1, resume  # global loss: both workers agree
    eng1, _, _, _ = deepspeed_tpu.initialize(
        model=SimpleModel(hidden_dim=HIDDEN), config=cfg, mesh=mesh,
        seed=4)
    assert not getattr(eng1, "_offload_sharded", False)
    path, _ = eng1.load_checkpoint(str(tmp_path), tag="mpoff")
    assert path is not None
    got = float(np.asarray(eng1.train_batch((gx, gy))))
    assert abs(got - resume.pop()) < 1e-4, got
