"""KV tiering (docs/serving.md "KV tiering"): park idle sessions'
prefix-cache pages on host RAM and disk, stream them back on resume,
and survive every failure on the way down:

* the acceptance bar — resume streams BITWISE equal to a never-spilled
  engine across {host, disk} x {fp16, int8} KV x {plain, speculative},
* the torture matrix — an injected fault at EVERY ``kv_spill``/
  ``kv_fetch`` point, one-shot (absorbed by the retry budget) and
  sticky (ONE degradation warning, engine keeps serving, zero lost
  requests),
* the corruption matrix — CRC flip / truncation / deletion of a parked
  disk page and a poisoned host copy all land the typed
  :class:`KVTierCorruptError` path and fall back to recompute-from-
  prompt, never a poisoned stream,
* pool hygiene — ``pool.refs == {}`` after close in every scenario,
* the disk-store dialect (PR 15's magic/header/CRC format, tmp+rename),
  the close-time drain barrier, config validation, telemetry rows.
"""
import logging
import os

import numpy as np
import jax
import pytest

from deepspeed_tpu.config import DeepSpeedConfigError
from deepspeed_tpu.inference import ServeEngine
from deepspeed_tpu.inference.kv_tier import (KVTierCorruptError,
                                             KVTierDiskStore)
from deepspeed_tpu.models.gpt2 import GPT2Config, GPT2Model
from deepspeed_tpu.runtime.resilience import CheckpointCorruptError
from deepspeed_tpu.runtime.stages import reset_fault_injection
from deepspeed_tpu.utils.logging import logger as ds_logger

TINY = GPT2Config(vocab_size=128, n_positions=64, d_model=32, n_layer=2,
                  n_head=4, remat=None, attn_impl="dense")
DRAFT_BLOCK = {"d_model": 32, "n_layer": 2, "n_head": 4}

_CHAOS_ENVS = ("DS_STAGE_FAULT", "DS_STAGE_DELAY_S")

#: idle_park_ticks used by every engine-level test; the idle-step loop
#: runs IDLE + 3 ticks (one tick snapshots last_hit, IDLE more cross
#: the threshold, the rest are slack)
IDLE = 3


@pytest.fixture(autouse=True)
def _clean_faults(monkeypatch):
    for env in _CHAOS_ENVS:
        monkeypatch.delenv(env, raising=False)
    reset_fault_injection()
    yield
    reset_fault_injection()


@pytest.fixture
def ds_caplog(caplog, monkeypatch):
    """The project logger does not propagate; flip it so caplog sees
    the degradation warning (same idiom as tests/test_stages.py)."""
    monkeypatch.setattr(ds_logger, "propagate", True)
    with caplog.at_level(logging.WARNING, logger="DeepSpeedTPU"):
        yield caplog


def _tokens(n, vocab=128, seed=0):
    return np.random.default_rng(seed).integers(
        0, vocab, (n,)).astype(np.int32)


def _p1():
    # 17 tokens: two full pages (page_len=8) + a 1-token partial tail
    return list(_tokens(17, seed=11))


def _p2():
    # turn 2 of the same conversation: turn 1's prompt + new tokens
    return _p1() + list(_tokens(8, seed=12))


_model_cache = {}


def _model_params():
    if not _model_cache:
        model = GPT2Model(TINY)
        _model_cache["mp"] = (model, model.init(jax.random.PRNGKey(0)))
    return _model_cache["mp"]


def _serve_cfg(slots=4, max_seq=64, prefill=32, telemetry_path=None,
               **serving_extra):
    cfg = {"serving": {"slots": slots, "max_seq_len": max_seq,
                       "prefill_len": prefill, "page_len": 8,
                       "pages": 16, **serving_extra}}
    if telemetry_path is not None:
        cfg["telemetry"] = {"enabled": True,
                            "output_path": str(telemetry_path)}
    return cfg


def _tier(disk_dir=None, ticks=IDLE, budget=256, **kw):
    kv = {"idle_park_ticks": ticks, "host_budget_pages": budget}
    if disk_dir is not None:
        kv["disk_dir"] = str(disk_dir)
    kv.update(kw)
    return {"kv_tier": kv}


def _mode_serving(mode):
    s = {}
    if "int8" in mode:
        s["quantization"] = {"kv": "int8"}
    if "spec" in mode:
        s["speculate_k"] = 2
        s["draft"] = dict(DRAFT_BLOCK)
    return s


def _two_turns(serving_extra, mode="plain", idle=0, between=None,
               collect=None, telemetry_path=None):
    """The canonical session: turn 1, a think-time gap of idle engine
    ticks (what parks the session), an optional mid-gap mutation hook,
    then turn 2 extending the same prompt.  Returns the two token
    streams, turn 2's shared prefix length, and the collect() snapshot;
    asserts zero request errors and a leak-free pool."""
    model, params = _model_params()
    eng = ServeEngine(
        model,
        _serve_cfg(telemetry_path=telemetry_path,
                   **_mode_serving(mode), **serving_extra),
        params=params,
        draft_params=params if "spec" in mode else None)
    r1 = eng.submit(_p1(), max_new_tokens=4)
    eng.run_until_idle()
    for _ in range(idle):
        eng.step()
    if between is not None:
        between(eng)
    r2 = eng.submit(_p2(), max_new_tokens=4)
    eng.run_until_idle()
    assert r1.error is None and r2.error is None
    stats = collect(eng) if collect is not None else None
    streams = (list(r1.tokens), list(r2.tokens))
    shared = r2.shared_len
    eng.close()
    assert eng.pool.refs == {}
    return streams, shared, stats


_base_cache = {}


def _baseline(mode):
    """The never-spilled reference streams for one mode (the tier-off
    engine still gets a live prefix-cache hit on turn 2)."""
    if mode not in _base_cache:
        _base_cache[mode] = _two_turns({}, mode=mode)[0]
    return _base_cache[mode]


def _tier_stats(eng):
    t = eng.kv_tier
    return {"parked": t.parked_pages_total, "spill": t.spill_bytes,
            "fetched": t.fetch_bytes, "resumed": t.resumed_sessions_total,
            "corrupt": t.corrupt_total,
            "spill_deg": t.spill_stage.degraded,
            "fetch_deg": t.fetch_stage.degraded,
            "fails": t.spill_stage.failures + t.fetch_stage.failures}


# ---------------------------------------------------------------------------
# THE acceptance bar: resume is bitwise a never-spilled engine
# ---------------------------------------------------------------------------


@pytest.mark.parametrize("mode", ["plain", "int8", "spec", "int8_spec"])
@pytest.mark.parametrize("arm", ["host", "disk"])
def test_park_resume_stream_bitwise_vs_never_spilled(arm, mode, tmp_path):
    """{host, disk} x {fp16, int8} KV x {plain, speculative}: the
    session parks during think time (host-resident, or written back to
    the disk tier under a zero host budget), turn 2 resumes it, and
    both turns' streams are bitwise the never-spilled engine's."""
    extra = _tier(tmp_path if arm == "disk" else None,
                  budget=0 if arm == "disk" else 256)
    # parking cascades root-ward one leaf per idle window (a parent
    # becomes a leaf only once its child parks), so give the gap a few
    # windows — enough for the whole 2-3 page chain on every mode
    streams, shared, stats = _two_turns(
        extra, mode=mode, idle=4 * (IDLE + 3), collect=_tier_stats)
    assert streams == _baseline(mode)
    assert stats["parked"] >= 2 and stats["spill"] > 0
    assert stats["fetched"] > 0 and stats["resumed"] >= 1
    assert stats["corrupt"] == 0
    assert shared >= 16       # both full pages came back from the tier


def test_parked_pages_leave_the_pool_during_the_gap(tmp_path):
    """Parking is the point: mid-gap, the session's prefix-cache pages
    are OUT of the pool (free for new traffic) and the tier holds the
    only copy; resume brings them back."""
    model, params = _model_params()
    eng = ServeEngine(model, _serve_cfg(**_tier(tmp_path, budget=1)),
                      params=params)
    r1 = eng.submit(_p1(), max_new_tokens=4)
    eng.run_until_idle()
    held_mid_gap = None
    for _ in range(IDLE + 3):
        eng.step()
    held_mid_gap = (eng.pool.used_count, eng.kv_tier.parked_pages)
    # over the 1-page host budget, the overflow lives in the disk tier
    on_disk = [f for f in os.listdir(tmp_path) if f.endswith(".page")]
    r2 = eng.submit(_p2(), max_new_tokens=4)
    eng.run_until_idle()
    assert r1.error is None and r2.error is None
    assert held_mid_gap[0] == 0 and held_mid_gap[1] >= 2
    assert len(on_disk) >= 1
    assert eng.kv_tier.parked_sessions == 0   # consumed by the resume
    eng.close()
    assert eng.pool.refs == {}


# ---------------------------------------------------------------------------
# torture matrix: a fault at EVERY spill/fetch point
# ---------------------------------------------------------------------------

POINTS = [("kv_spill", "pageout"), ("kv_spill", "write"),
          ("kv_fetch", "read"), ("kv_fetch", "pagein")]


@pytest.mark.parametrize("stage,point", POINTS)
def test_one_shot_fault_is_absorbed_by_the_retry_budget(
        stage, point, tmp_path, monkeypatch):
    """A single injected fault at each point is retried inside the
    stage budget: nothing degrades, the session still parks to disk and
    resumes, streams stay bitwise."""
    monkeypatch.setenv("DS_STAGE_FAULT", f"{stage}:{point}:1")
    reset_fault_injection()
    streams, shared, stats = _two_turns(
        _tier(tmp_path, budget=0), idle=IDLE + 3, collect=_tier_stats)
    assert streams == _baseline("plain")
    assert stats["fails"] == 1
    assert not stats["spill_deg"] and not stats["fetch_deg"]
    assert stats["fetched"] > 0 and stats["corrupt"] == 0
    assert shared >= 16


@pytest.mark.parametrize("stage,point", POINTS)
def test_sticky_fault_degrades_once_and_keeps_serving(
        stage, point, tmp_path, monkeypatch, ds_caplog):
    """A sticky fault at each point exhausts the budget: the stage
    degrades with ONE loud warning (spill -> sessions stay
    HBM-resident, fetch -> recompute-from-prompt), every request of
    every turn still completes, and the streams are bitwise the
    never-spilled engine's — zero lost requests."""
    monkeypatch.setenv("DS_STAGE_FAULT", f"{stage}:{point}:1+")
    reset_fault_injection()
    model, params = _model_params()
    eng = ServeEngine(model, _serve_cfg(**_tier(tmp_path, budget=0)),
                      params=params)
    r1 = eng.submit(_p1(), max_new_tokens=4)
    eng.run_until_idle()
    for _ in range(IDLE + 3):
        eng.step()
    r2 = eng.submit(_p2(), max_new_tokens=4)
    eng.run_until_idle()
    # zero lost requests: a brand-new session still serves afterwards
    r3 = eng.submit(list(_tokens(9, seed=44)), max_new_tokens=3)
    eng.run_until_idle()
    tier = eng.kv_tier
    degraded = tier.spill_stage.degraded or tier.fetch_stage.degraded
    corrupt = tier.corrupt_total
    eng.close()
    assert [r.error for r in (r1, r2, r3)] == [None, None, None]
    assert (list(r1.tokens), list(r2.tokens)) == _baseline("plain")
    assert degraded and corrupt == 0
    warns = [r for r in ds_caplog.records
             if "failure budget" in r.getMessage()]
    assert len(warns) == 1, "degradation must warn exactly ONCE"
    assert eng.pool.refs == {}


def test_degraded_spill_goes_dormant(tmp_path, monkeypatch):
    """After kv_spill degrades, parking stops for the rest of the run:
    later idle sessions stay HBM-resident (the prefix cache keeps
    their pages) instead of half-parking through a failing tier."""
    monkeypatch.setenv("DS_STAGE_FAULT", "kv_spill:pageout:1+")
    reset_fault_injection()
    model, params = _model_params()
    eng = ServeEngine(model, _serve_cfg(**_tier(tmp_path, budget=0)),
                      params=params)
    r1 = eng.submit(_p1(), max_new_tokens=4)
    eng.run_until_idle()
    for _ in range(IDLE + 3):
        eng.step()
    assert eng.kv_tier.spill_stage.degraded
    parked_at_degrade = eng.kv_tier.parked_pages_total
    # a second session goes idle — with the tier dormant it must stay
    # in the prefix cache, not the tier
    r2 = eng.submit(list(_tokens(17, seed=55)), max_new_tokens=4)
    eng.run_until_idle()
    for _ in range(IDLE + 3):
        eng.step()
    assert eng.kv_tier.parked_pages_total == parked_at_degrade
    assert eng.prefix.entries > 0
    assert r1.error is None and r2.error is None
    eng.close()
    assert eng.pool.refs == {}


# ---------------------------------------------------------------------------
# corruption matrix: typed error + recompute fallback, never a poison
# ---------------------------------------------------------------------------


def _flip(path):
    with open(path, "r+b") as f:
        f.seek(-1, os.SEEK_END)
        b = f.read(1)
        f.seek(-1, os.SEEK_END)
        f.write(bytes([b[0] ^ 0xFF]))


def _truncate(path):
    with open(path, "r+b") as f:
        f.truncate(10)


@pytest.mark.parametrize("damage", [_flip, _truncate, os.unlink],
                         ids=["crc_flip", "truncate", "unlink"])
def test_disk_damage_falls_back_to_recompute(damage, tmp_path):
    """Every parked disk page damaged mid-gap (CRC flip, truncation,
    deletion): resume hits the typed ``KVTierCorruptError`` BEFORE any
    byte re-enters the pool, drops the record, and recomputes from the
    prompt — turn 2 is still bitwise correct, nothing is lost."""
    def corrupt(eng):
        files = [f for f in os.listdir(tmp_path) if f.endswith(".page")]
        assert files, "nothing parked to disk — the test lost its prey"
        for fn in files:
            damage(os.path.join(str(tmp_path), fn))

    streams, shared, stats = _two_turns(
        _tier(tmp_path, budget=0), idle=IDLE + 3, between=corrupt,
        collect=_tier_stats)
    assert streams == _baseline("plain")
    assert stats["corrupt"] >= 1
    assert stats["fetched"] == 0      # no damaged byte reached the pool
    assert not stats["fetch_deg"]     # typed, not transient: no budget


def test_poisoned_host_copy_reverifies_at_pagein(tmp_path):
    """The host tier re-verifies too: a corrupted host-resident payload
    fails its CRC stamp at page-in and resume recomputes — the stamp
    taken at park time gates EVERY re-entry, not just the disk path."""
    def poison(eng):
        recs = list(eng.kv_tier._full.values())
        assert recs
        for rec in recs:
            rec.payload = bytes(len(rec.payload))

    streams, _, stats = _two_turns(
        _tier(None, budget=256), idle=IDLE + 3, between=poison,
        collect=_tier_stats)
    assert streams == _baseline("plain")
    assert stats["corrupt"] >= 1 and stats["fetched"] == 0


def test_corrupt_error_is_typed_not_transient():
    """``KVTierCorruptError`` is the checkpoint family's corrupt error
    and NOT an ``OSError`` — ``Stage.call`` propagates it on the first
    hit instead of burning the retry budget on a deterministic CRC
    mismatch."""
    assert issubclass(KVTierCorruptError, CheckpointCorruptError)
    assert not issubclass(KVTierCorruptError, OSError)


# ---------------------------------------------------------------------------
# the disk-store dialect (PR 15's leaf-state format, verbatim)
# ---------------------------------------------------------------------------


def test_disk_store_roundtrip(tmp_path):
    st = KVTierDiskStore(str(tmp_path), fsync=False)
    payload = bytes(np.random.default_rng(3).integers(
        0, 256, 4096).astype(np.uint8))
    assert st.write("abc", payload) == 4096
    assert st.read("abc") == payload
    assert os.path.basename(st.path("abc")) == "kv_abc.page"
    # tmp+rename: no .tmp survivors under the real names
    assert not [f for f in os.listdir(tmp_path) if f.endswith(".tmp")]
    st.remove("abc")
    with pytest.raises(KVTierCorruptError, match="missing"):
        st.read("abc")
    st.remove("abc")                     # best-effort: no raise


@pytest.mark.parametrize("mutate,msg", [
    (lambda p: open(p, "r+b").write(b"XXXXXXXX"), "bad magic"),
    (lambda p: _truncate(p), "truncated in its header"),
    (lambda p: _flip(p), "CRC"),
], ids=["magic", "header", "crc"])
def test_disk_store_detects_corruption(tmp_path, mutate, msg):
    st = KVTierDiskStore(str(tmp_path), fsync=False)
    st.write("x", b"\x01\x02\x03\x04" * 64)
    mutate(st.path("x"))
    with pytest.raises(KVTierCorruptError, match=msg):
        st.read("x")


def test_disk_store_shares_the_checkpoint_magic(tmp_path):
    """One on-disk dialect: a parked page file opens with the SAME
    magic as PR 15's leaf-state files."""
    from deepspeed_tpu.inference.kv_tier import _MAGIC
    from deepspeed_tpu.runtime.disk_offload import _MAGIC as CKPT_MAGIC
    assert _MAGIC == CKPT_MAGIC


# ---------------------------------------------------------------------------
# close plane: drain barrier, idempotence, defaults
# ---------------------------------------------------------------------------


def test_drain_writes_every_host_copy_to_disk(tmp_path):
    """The ``kv_spill`` graph drain: every host-resident parked page is
    written back before close, and the ``kv_fetch`` close then drops
    the records and their files — nothing leaks on either tier."""
    model, params = _model_params()
    eng = ServeEngine(model, _serve_cfg(**_tier(tmp_path, budget=256)),
                      params=params)
    eng.submit(_p1(), max_new_tokens=4)
    eng.run_until_idle()
    for _ in range(IDLE + 3):
        eng.step()
    tier = eng.kv_tier
    assert tier.parked_pages >= 2 and tier._host_pages > 0
    n = tier.drain()
    assert n >= 2 and tier._host_pages == 0
    files = [f for f in os.listdir(tmp_path) if f.endswith(".page")]
    assert len(files) == tier.parked_pages
    eng.close()
    assert not [f for f in os.listdir(tmp_path) if f.endswith(".page")]
    assert eng.pool.refs == {}


def test_close_is_idempotent_with_parked_sessions(tmp_path):
    model, params = _model_params()
    eng = ServeEngine(model, _serve_cfg(**_tier(tmp_path, budget=1)),
                      params=params)
    eng.submit(_p1(), max_new_tokens=4)
    eng.run_until_idle()
    for _ in range(IDLE + 3):
        eng.step()
    assert eng.kv_tier.parked_pages >= 2
    eng.close()
    eng.close()
    assert eng.kv_tier.parked_pages == 0
    assert eng.pool.refs == {}


def test_tier_off_by_default_builds_no_tier():
    """idle_park_ticks=0 (the default) means NO tier object — the
    paged engine is bitwise the pre-tier engine."""
    model, params = _model_params()
    eng = ServeEngine(model, _serve_cfg(), params=params)
    assert eng.kv_tier is None
    eng.close()


# ---------------------------------------------------------------------------
# config validation + telemetry
# ---------------------------------------------------------------------------


@pytest.mark.parametrize("kv,msg", [
    ("nope", "must be a dict"),
    ({"bogus": 1}, "unknown key"),
    ({"idle_park_ticks": -1}, "int >= 0"),
    ({"idle_park_ticks": True}, "int >= 0"),
    ({"host_budget_pages": -2}, "int >= 0"),
    ({"disk_dir": 7}, "string"),
    ({"fsync": "yes"}, "bool"),
], ids=["dict", "unknown", "neg_ticks", "bool_ticks", "neg_budget",
        "dir_type", "fsync_type"])
def test_kv_tier_config_validation(kv, msg):
    with pytest.raises(DeepSpeedConfigError, match=msg):
        ServeEngine(GPT2Model(TINY), _serve_cfg(kv_tier=kv))


def test_kv_tier_requires_the_paged_plane():
    cfg = {"serving": {"slots": 2, "max_seq_len": 32, "prefill_len": 16,
                       "kv_tier": {"idle_park_ticks": 2}}}
    with pytest.raises(DeepSpeedConfigError, match="page_len"):
        ServeEngine(GPT2Model(TINY), cfg)


def test_kv_tier_telemetry_flows_to_summarize(tmp_path, capsys):
    from deepspeed_tpu.telemetry.cli import summarize
    tel = tmp_path / "tel"
    disk = tmp_path / "disk"
    _, _, stats = _two_turns(_tier(disk, budget=0), idle=IDLE + 3,
                             telemetry_path=tel, collect=_tier_stats)
    rep = summarize(os.path.join(str(tel), "events.jsonl"))
    assert rep["serve_kv_spill_bytes_total"] == stats["spill"]
    assert rep["serve_kv_fetch_bytes_total"] == stats["fetched"]
    assert rep["serve_kv_parked_sessions"] is not None
    assert rep["serve_kv_resume_p99_s"] is not None
    out = capsys.readouterr().out
    assert "kv tier" in out
