"""End-to-end engine training on the 8-device virtual mesh — the analogue of
the reference's tests/unit/test_fp16.py training loops over
@distributed_test(world_size=[1,2]) (common.py:14-100)."""
import numpy as np
import jax
import jax.numpy as jnp
import pytest

import deepspeed_tpu
from deepspeed_tpu.config import DeepSpeedConfig
from deepspeed_tpu.parallel import build_mesh
from deepspeed_tpu.runtime.engine import DeepSpeedEngine

from simple_model import SimpleModel, base_config, random_batches

HIDDEN = 16


def _train(stage, precision="bf16", grad_acc=1, micro=2, steps=10,
           mesh=None, **over):
    mesh = mesh or build_mesh()
    dp = mesh.shape["data"]
    cfg = DeepSpeedConfig(
        base_config(micro_bs=micro, grad_acc=grad_acc, stage=stage,
                    precision=precision, **over),
        world_size=dp)
    eng = DeepSpeedEngine(SimpleModel(hidden_dim=HIDDEN), cfg, mesh=mesh)
    batch_size = cfg.train_batch_size
    losses = []
    for batch in random_batches(batch_size, HIDDEN, num_batches=steps):
        losses.append(float(eng.train_batch(batch)))
    return losses, eng


@pytest.mark.parametrize("stage", [0, 1, 2, 3])
def test_zero_stages_loss_decreases(stage):
    losses, eng = _train(stage=stage)
    assert losses[-1] < losses[0] * 0.7, losses
    assert eng.global_steps == 10
    assert eng.get_skipped_steps() == 0


@pytest.mark.parametrize("stage", [0, 2])
def test_grad_accumulation(stage):
    losses, eng = _train(stage=stage, grad_acc=4, micro=1, steps=8)
    assert losses[-1] < losses[0] * 0.8
    assert eng.micro_steps == 8 * 4


def test_fp16_training():
    losses, eng = _train(stage=0, precision="fp16", steps=10,
                         **{"fp16": {"enabled": True,
                                     "initial_scale_power": 8}})
    assert losses[-1] < losses[0] * 0.7


def test_fp32_training():
    losses, _ = _train(stage=0, precision="fp32")
    assert losses[-1] < losses[0] * 0.7


@pytest.mark.slow
def test_zero_stages_agree():
    """Stages 0/1/2/3 must produce (nearly) identical training curves —
    ZeRO is a memory layout, not an algorithm change (the TPU analogue of
    the reference's pg_correctness_test, stage2.py:23-25)."""
    ref, _ = _train(stage=0, steps=5)
    for stage in (1, 2, 3):
        got, _ = _train(stage=stage, steps=5)
        np.testing.assert_allclose(got, ref, rtol=2e-2)


def test_zero_sharding_actually_shards():
    mesh = build_mesh()
    cfg = DeepSpeedConfig(base_config(micro_bs=2, stage=2), world_size=8)
    eng = DeepSpeedEngine(SimpleModel(hidden_dim=HIDDEN), cfg, mesh=mesh)
    w0 = eng.state.master_params["w0"]
    # hidden=16 divisible by dp=8 → dim 0 sharded over data axis
    shard_shape = w0.sharding.shard_shape(w0.shape)
    assert shard_shape[0] == HIDDEN // 8
    # optimizer moments shard identically
    mu0 = eng.state.opt_state.mu["w0"]
    assert mu0.sharding.shard_shape(mu0.shape)[0] == HIDDEN // 8


def test_stage0_replicated():
    mesh = build_mesh()
    cfg = DeepSpeedConfig(base_config(micro_bs=2, stage=0), world_size=8)
    eng = DeepSpeedEngine(SimpleModel(hidden_dim=HIDDEN), cfg, mesh=mesh)
    w0 = eng.state.master_params["w0"]
    assert w0.sharding.shard_shape(w0.shape) == w0.shape


def test_initialize_api():
    mesh = build_mesh()
    engine, optimizer, dataloader, sched = deepspeed_tpu.initialize(
        model=SimpleModel(hidden_dim=HIDDEN),
        config=base_config(micro_bs=2, stage=1),
        mesh=mesh)
    batch = next(random_batches(engine.train_batch_size, HIDDEN))
    loss0 = float(engine.train_batch(batch))
    loss1 = float(engine.train_batch(batch))
    assert loss1 < loss0


def test_forward_backward_step_facade():
    mesh = build_mesh()
    cfg = DeepSpeedConfig(base_config(micro_bs=2, grad_acc=2),
                          world_size=8)
    eng = DeepSpeedEngine(SimpleModel(hidden_dim=HIDDEN), cfg, mesh=mesh)
    micro_global = 2 * 8
    batches = list(random_batches(micro_global, HIDDEN, num_batches=4))
    import logging

    from deepspeed_tpu.utils.logging import logger as ds_logger

    records = []

    class Rec(logging.Handler):
        def emit(self, record):
            if "facade" in record.getMessage():
                records.append(record)

    h = Rec(level=logging.INFO)
    ds_logger.addHandler(h)
    try:
        for i, b in enumerate(batches):
            loss = eng.forward(b)
            eng.backward(loss)
            eng.step()
    finally:
        ds_logger.removeHandler(h)
    assert eng.global_steps == 2  # 4 micros / grad_acc 2
    # the extra-forward cost warning fires exactly ONCE (VERDICT r3 #8:
    # users porting reference-idiom loops must not silently pay it)
    assert len(records) == 1, [r.getMessage() for r in records]


def test_wrong_batch_size_raises():
    mesh = build_mesh()
    cfg = DeepSpeedConfig(base_config(micro_bs=2), world_size=8)
    eng = DeepSpeedEngine(SimpleModel(hidden_dim=HIDDEN), cfg, mesh=mesh)
    with pytest.raises(ValueError):
        eng.train_batch(next(random_batches(7, HIDDEN)))


def test_gradient_clipping_runs():
    losses, eng = _train(stage=1, gradient_clipping=0.1)
    assert losses[-1] < losses[0]


def test_lamb_optimizer():
    losses, _ = _train(
        stage=0,
        optimizer={"type": "Lamb", "params": {"lr": 1e-2}})
    assert losses[-1] < losses[0] * 0.9


def test_scheduler_from_config():
    losses, eng = _train(
        stage=0,
        scheduler={"type": "WarmupLR",
                   "params": {"warmup_min_lr": 0.0,
                              "warmup_max_lr": 1e-2,
                              "warmup_num_steps": 5}})
    assert losses[-1] < losses[0]
    assert eng.get_lr() > 0


def test_pg_correctness_sweep_zero2():
    """Partitioned vs replicated gradient diff (the reference's
    pg_correctness_test, stage2.py:23-25,1008-1022)."""
    cfg = DeepSpeedConfig(base_config(micro_bs=4, grad_acc=2, stage=2),
                          world_size=8)
    eng = DeepSpeedEngine(SimpleModel(hidden_dim=8), cfg, mesh=build_mesh())
    batch = next(random_batches(64, 8))
    report = eng.verify_gradient_partitioning(batch)
    assert report["max_abs_diff"] < 2e-5

    # stage 3 (param sharding) must agree too
    cfg3 = DeepSpeedConfig(base_config(micro_bs=4, grad_acc=2, stage=3),
                           world_size=8)
    eng3 = DeepSpeedEngine(SimpleModel(hidden_dim=8), cfg3,
                           mesh=build_mesh())
    report3 = eng3.verify_gradient_partitioning(batch)
    assert report3["max_abs_diff"] < 2e-5


def test_pg_correctness_config_flag_runs_on_first_step():
    cfg = DeepSpeedConfig(
        base_config(micro_bs=4, stage=2,
                    **{"zero_optimization": {"stage": 2,
                                             "pg_correctness_test": True}}),
        world_size=8)
    eng = DeepSpeedEngine(SimpleModel(hidden_dim=8), cfg, mesh=build_mesh())
    assert eng._pg_check_pending
    loss = eng.train_batch(next(random_batches(32, 8)))
    assert np.isfinite(float(np.asarray(loss)))
    assert not eng._pg_check_pending  # consumed on step 1


def test_reference_accessor_surface():
    """Config facts exposed as zero-arg methods (reference engine.py:241-392)
    plus the dual attribute/method batch accessors."""
    cfg = DeepSpeedConfig(
        base_config(micro_bs=4, grad_acc=2, stage=2,
                    **{"gradient_clipping": 1.0,
                       "scheduler": {"type": "WarmupLR",
                                     "params": {"warmup_num_steps": 5}}}),
        world_size=8)
    eng = DeepSpeedEngine(SimpleModel(hidden_dim=8), cfg, mesh=build_mesh())

    # dual style: attribute (this codebase) AND call (reference)
    assert eng.train_batch_size == 64 and eng.train_batch_size() == 64
    assert eng.gradient_accumulation_steps() == 2
    assert eng.train_micro_batch_size_per_gpu() == 4
    assert eng.gradient_clipping == 1.0 and eng.gradient_clipping() == 1.0

    assert eng.zero_optimization() is True
    assert eng.zero_optimization_stage() == 2
    assert eng.zero_cpu_offload() is False
    assert eng.optimizer_name() == "adam"
    assert eng.scheduler_name() == "WarmupLR"
    assert eng.scheduler_params() == {"warmup_num_steps": 5}
    assert eng.pld_enabled() is False and eng.pld_params() is False
    assert eng.tensorboard_enabled() is False
    assert eng.dynamic_loss_scale() is False  # bf16: no loss scaling
    assert eng.loss_scale() == 1.0
    assert eng.steps_per_print() == 1000
    assert eng.wall_clock_breakdown() is False
    assert eng.sparse_gradients_enabled() is False
    assert eng.train() is eng and eng._train_mode
    assert eng.eval() is eng and not eng._train_mode


def test_unused_parameter_trains_under_zero2():
    """Models with parameters not touched by the loss must still train
    (reference test_fp16.py exercises unused-parameter edge cases — eager
    autograd leaves .grad=None there; under jax.grad unused leaves get
    zeros, and the ZeRO sharding plan must handle them)."""
    class PartiallyUsedModel(SimpleModel):
        def init(self, rng):
            params = super().init(rng)
            params["never_used"] = jnp.ones((4, 4), jnp.float32)
            return params

    cfg = DeepSpeedConfig(base_config(micro_bs=4, stage=2), world_size=8)
    eng = DeepSpeedEngine(PartiallyUsedModel(hidden_dim=8), cfg,
                          mesh=build_mesh())
    before = np.asarray(eng.state.master_params["never_used"])
    losses = [float(np.asarray(eng.train_batch(b)))
              for b in random_batches(32, 8, num_batches=4)]
    assert losses[-1] < losses[0]
    # zero grad + zero Adam moments -> the unused leaf must not move
    np.testing.assert_array_equal(
        before, np.asarray(eng.state.master_params["never_used"]))


def test_flax_module_adapter_trains():
    """A flax linen model through FlaxModule + initialize — the adapter
    path for the broader jax ecosystem."""
    flax = pytest.importorskip("flax")
    import flax.linen as nn
    from deepspeed_tpu.runtime.module import FlaxModule

    class MLP(nn.Module):
        @nn.compact
        def __call__(self, x):
            x = nn.Dense(16)(x)
            x = nn.relu(x)
            return nn.Dense(8)(x)

    def loss(apply_fn, variables, batch, rng, train):
        x, y = batch
        pred = apply_fn(variables, x)
        return jnp.mean((pred.astype(jnp.float32)
                         - y.astype(jnp.float32)) ** 2)

    example = next(random_batches(32, 8))
    module = FlaxModule(MLP(), loss, example_batch=example[0])
    engine, _, _, _ = deepspeed_tpu.initialize(
        model=module, config=DeepSpeedConfig(
            base_config(micro_bs=4, stage=1), world_size=8),
        mesh=build_mesh())
    losses = [float(np.asarray(engine.train_batch(b)))
              for b in random_batches(32, 8, num_batches=6, seed=5)]
    assert losses[-1] < losses[0]


def test_jitted_init_matches_eager_init():
    """Engine construction compiles model.init as ONE program (a remote-
    compile platform turns per-leaf eager init into ~15 sequential compile
    round-trips — the round-2 1.5B 'constructing engine' stall).  The
    compiled init must match the eager init it replaced (1-ulp fusion
    reassociation aside — XLA may fma the `normal * scale`)."""
    mesh = build_mesh()
    model = SimpleModel(hidden_dim=HIDDEN)
    cfg = DeepSpeedConfig(base_config(micro_bs=2, stage=0), world_size=8)
    eng = DeepSpeedEngine(model, cfg, mesh=mesh)
    seed = 0  # engine default; init_rng = split(PRNGKey(seed))[0]
    init_rng, _ = jax.random.split(jax.random.PRNGKey(seed))
    eager = model.init(init_rng)
    got = jax.tree.leaves(eng.state.master_params)
    want = jax.tree.leaves(eager)
    assert len(got) == len(want)
    for g, w in zip(got, want):
        np.testing.assert_allclose(np.asarray(g),
                                   np.asarray(w, dtype=np.float32),
                                   rtol=3e-7, atol=0)
