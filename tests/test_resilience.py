"""Fault-tolerant checkpointing (ISSUE 5): async saves bitwise-identical
to sync, CRC integrity + typed corruption errors, the corrupt-latest
fallback chain, retention GC + orphaned-tmp sweep, transient-I/O retry,
the kill-during-save torture matrix, and the SIGTERM preemption hook."""
import json
import os
import signal
import threading
import time

import numpy as np
import jax
import pytest

from deepspeed_tpu.config import DeepSpeedConfig
from deepspeed_tpu.parallel import build_mesh
from deepspeed_tpu.runtime.engine import DeepSpeedEngine
from deepspeed_tpu.runtime import resilience
from deepspeed_tpu.runtime.resilience import (
    AsyncCheckpointWriter, CheckpointCorruptError, CheckpointJob,
    RetryPolicy, io_retry, reset_fault_injection)

from simple_model import SimpleModel, base_config, random_batches

HIDDEN = 16


@pytest.fixture(autouse=True)
def _clean_faults(monkeypatch):
    monkeypatch.delenv("DS_CKPT_FAULT", raising=False)
    monkeypatch.delenv("DS_CKPT_DELAY_S", raising=False)
    reset_fault_injection()
    yield
    reset_fault_injection()


def _engine(stage=0, precision="bf16", dp=1, seed=0, **over):
    # dp=1 default: the resilience plane (integrity, retention, retry,
    # writer semantics) is sharding-agnostic, and 1-device programs
    # compile several times faster — the multi-device save/load paths are
    # covered by tests/test_checkpointing.py's dp=8 matrix
    devices = jax.devices()
    if dp is not None:
        devices = devices[:dp]
    mesh = build_mesh(devices=devices)
    cfg = DeepSpeedConfig(
        base_config(micro_bs=2, grad_acc=1, stage=stage, precision=precision,
                    **over),
        world_size=mesh.shape["data"])
    return DeepSpeedEngine(SimpleModel(hidden_dim=HIDDEN), cfg, mesh=mesh,
                           seed=seed)


def _train(eng, steps=2, seed=0):
    losses = []
    for batch in random_batches(eng.train_batch_size, HIDDEN,
                                num_batches=steps, seed=seed):
        losses.append(float(eng.train_batch(batch)))
    return losses


def _state_equal(a, b):
    fa, fb = jax.tree.leaves(a), jax.tree.leaves(b)
    assert len(fa) == len(fb)
    for x, y in zip(fa, fb):
        np.testing.assert_array_equal(
            np.asarray(jax.device_get(x)), np.asarray(jax.device_get(y)))


def _dir_bytes(root):
    """relpath -> file bytes for a checkpoint dir (the bitwise contract)."""
    out = {}
    for dirpath, _, files in os.walk(root):
        for f in files:
            p = os.path.join(dirpath, f)
            with open(p, "rb") as fh:
                out[os.path.relpath(p, root)] = fh.read()
    return out


HOST_OFFLOAD = {"zero_optimization": {"stage": 2, "cpu_offload": True,
                                      "offload_impl": "host"}}


# ---------------------------------------------------------------------------
# tentpole: async == sync, bitwise
# ---------------------------------------------------------------------------
@pytest.mark.parametrize("over", [{}, HOST_OFFLOAD],
                         ids=["plain", "host_offload"])
def test_async_save_bitwise_equals_sync(over, tmp_path):
    """Async and sync saves share ONE serialization path; the artifact
    bytes must be identical file for file (manifests, CRCs, meta, leaf
    data) — on the plain engine and across the offload boundary."""
    kw = dict(dp=1) if over else {}
    eng = _engine(stage=over and 2 or 0, **kw, **over)
    _train(eng, steps=2)
    eng.save_checkpoint(str(tmp_path / "sync"), tag="t", async_write=False)
    eng.save_checkpoint(str(tmp_path / "async"), tag="t", async_write=True)
    err = eng._ckpt_writer.drain()
    assert err is None
    a = _dir_bytes(str(tmp_path / "sync"))
    b = _dir_bytes(str(tmp_path / "async"))
    assert a.keys() == b.keys()
    for rel in a:
        assert a[rel] == b[rel], f"{rel} differs between sync and async"


def test_async_snapshot_immune_to_later_steps(tmp_path):
    """The snapshot COPIES host-tier numpy leaves: training steps taken
    while the writer is still serializing must not bleed into the saved
    bytes (the offload staging buffers are mutated in place by the C++
    Adam).  Sync ground truth is taken at the same step."""
    eng = _engine(stage=2, dp=1, **HOST_OFFLOAD)
    _train(eng, steps=2)
    eng.save_checkpoint(str(tmp_path / "truth"), tag="t", async_write=False)
    # slow the async write so the next steps overlap it
    os.environ["DS_CKPT_DELAY_S"] = "0.3"
    try:
        eng.save_checkpoint(str(tmp_path / "live"), tag="t",
                            async_write=True)
        _train(eng, steps=2, seed=7)  # mutates staging while writing
        err = eng._ckpt_writer.drain()
    finally:
        os.environ.pop("DS_CKPT_DELAY_S", None)
    assert err is None
    a = _dir_bytes(str(tmp_path / "truth"))
    b = _dir_bytes(str(tmp_path / "live"))
    assert a.keys() == b.keys()
    for rel in a:
        assert a[rel] == b[rel], f"{rel} corrupted by post-snapshot steps"


def test_async_roundtrip_restores(tmp_path):
    eng = _engine()
    _train(eng, steps=3)
    eng.save_checkpoint(str(tmp_path), tag="t", async_write=True)
    assert eng._ckpt_writer.drain() is None
    eng2 = _engine(seed=9)
    path, _ = eng2.load_checkpoint(str(tmp_path), tag="t")
    assert path is not None
    _state_equal(eng.state.master_params, eng2.state.master_params)
    assert eng2.global_steps == 3


def test_pipeline_engine_async_bitwise(tmp_path):
    """The pipe engine inherits the checkpoint machinery; async==sync
    must hold for its stage-stacked state too (pp=2 stays in the core
    tier; no train step — the save plane alone is under test)."""
    from deepspeed_tpu.pipe.engine import PipelineEngine
    from deepspeed_tpu.models import GPT2Config
    from deepspeed_tpu.models.gpt2_pipe import build_gpt2_pipe

    mesh = build_mesh(pp=2)
    cfg_model = GPT2Config(vocab_size=64, n_positions=16, d_model=16,
                           n_layer=2, n_head=2, remat=None)
    cfg = DeepSpeedConfig({
        "train_micro_batch_size_per_gpu": 1,
        "gradient_accumulation_steps": 2,
        "steps_per_print": 10 ** 9,
        "bf16": {"enabled": True},
        "zero_optimization": {"stage": 1},
        "optimizer": {"type": "Adam", "params": {"lr": 1e-3}},
    }, world_size=mesh.shape["data"])
    eng = PipelineEngine(build_gpt2_pipe(cfg_model, num_stages=2), cfg, mesh)
    eng.save_checkpoint(str(tmp_path / "sync"), tag="t", async_write=False)
    eng.save_checkpoint(str(tmp_path / "async"), tag="t", async_write=True)
    assert eng._ckpt_writer.drain() is None
    a = _dir_bytes(str(tmp_path / "sync"))
    b = _dir_bytes(str(tmp_path / "async"))
    assert a.keys() == b.keys()
    for rel in a:
        assert a[rel] == b[rel], f"{rel} differs between sync and async"


# ---------------------------------------------------------------------------
# writer semantics
# ---------------------------------------------------------------------------
def test_writer_coalesces_latest_wins(tmp_path):
    ran = []
    gate = threading.Event()

    def slow_job(tag):
        def run():
            if tag == "a":
                gate.wait(5.0)
            ran.append(tag)
        return CheckpointJob(tag=tag, tmp_dir=str(tmp_path / f"{tag}.tmp"),
                             final_dir=str(tmp_path / tag), run=run)

    w = AsyncCheckpointWriter()
    w.submit(slow_job("a"))
    deadline = time.time() + 5.0
    while w._busy is None and time.time() < deadline:
        time.sleep(0.002)     # wait until the worker holds "a" (gated)
    assert w._busy is not None
    w.submit(slow_job("b"))   # pending
    w.submit(slow_job("c"))   # replaces "b" — latest wins
    assert w.active_tmp() >= {str(tmp_path / "a.tmp"),
                              str(tmp_path / "c.tmp")}
    gate.set()
    assert w.drain() is None
    assert ran == ["a", "c"]  # "b" was coalesced away
    assert w.coalesced == 1
    w.close()
    w.close()  # idempotent


def test_writer_failure_poisons_only_pending():
    w = AsyncCheckpointWriter()

    def boom():
        raise OSError("disk gone")
    w.submit(CheckpointJob("bad", "/tmp/x.tmp", "/tmp/x", boom))
    err = w.drain()
    assert isinstance(err, OSError)
    assert w.pop_error() is None  # drain cleared it
    ok = []
    w.submit(CheckpointJob("good", "/tmp/y.tmp", "/tmp/y",
                           lambda: ok.append(1)))
    assert w.drain() is None  # writer survived; next save succeeded
    assert ok == [1]
    assert w.failed == 1 and w.completed == 1
    w.close()


def test_engine_survives_async_save_failure(tmp_path):
    """A writer failure poisons only the pending save: training continues,
    the error surfaces on the next train_batch (last_ckpt_error), and the
    next save — fault cleared — succeeds and is loadable."""
    eng = _engine()
    _train(eng, steps=1)
    os.environ["DS_CKPT_FAULT"] = "meta:1+"
    try:
        eng.save_checkpoint(str(tmp_path), tag="doomed", async_write=True)
        eng._ckpt_writer.drain()
    finally:
        os.environ.pop("DS_CKPT_FAULT", None)
    # drain() cleared the writer-side error; the tick path is exercised
    # by a fresh failure left un-drained:
    reset_fault_injection()
    os.environ["DS_CKPT_FAULT"] = "meta:1+"
    try:
        eng.save_checkpoint(str(tmp_path), tag="doomed2", async_write=True)
        eng._ckpt_writer.drain(timeout=10.0)
        eng._ckpt_writer._last_error = OSError("kept for tick")  # rearm
        _train(eng, steps=1, seed=5)  # pre-step tick surfaces it
    finally:
        os.environ.pop("DS_CKPT_FAULT", None)
    assert isinstance(eng.last_ckpt_error, OSError)
    reset_fault_injection()
    eng.save_checkpoint(str(tmp_path), tag="ok", async_write=True)
    assert eng._ckpt_writer.drain() is None
    eng2 = _engine(seed=3)
    path, _ = eng2.load_checkpoint(str(tmp_path), tag="ok")
    assert path is not None


# ---------------------------------------------------------------------------
# integrity plane
# ---------------------------------------------------------------------------
def _corrupt_one_leaf(ckpt_dir, plane="model"):
    """Flip bytes inside the first leaf's .npy payload (header intact)."""
    mpath = os.path.join(ckpt_dir, plane, "manifest.json")
    manifest = json.load(open(mpath))
    key, entry = next((k, e) for k, e in manifest.items()
                      if e.get("nbytes", 0) > 4)
    fpath = os.path.join(ckpt_dir, plane, entry["file"])
    data = bytearray(open(fpath, "rb").read())
    data[-4] ^= 0xFF  # inside the array payload, not the npy header
    open(fpath, "wb").write(bytes(data))
    return key, entry["file"]


def test_crc_detects_flipped_bit(tmp_path):
    eng = _engine()
    _train(eng, steps=2)
    eng.save_checkpoint(str(tmp_path), tag="t")
    key, fname = _corrupt_one_leaf(str(tmp_path / "t"), "optim")
    eng2 = _engine(seed=9)
    with pytest.raises(CheckpointCorruptError) as ei:
        eng2.load_checkpoint(str(tmp_path), tag="t")
    # the typed error names the leaf and the file
    assert fname in str(ei.value) and "CRC32" in str(ei.value)
    # and no half-restored state: the engine still trains
    assert np.isfinite(_train(eng2, steps=1)).all()


def test_manifest_digest_detects_tamper(tmp_path):
    eng = _engine()
    _train(eng, steps=1)
    eng.save_checkpoint(str(tmp_path), tag="t")
    mpath = tmp_path / "t" / "optim" / "manifest.json"
    m = json.load(open(mpath))
    json.dump(m, open(mpath, "w"), indent=4)  # re-serialized != digest
    eng2 = _engine(seed=1)
    with pytest.raises(CheckpointCorruptError, match="digest"):
        eng2.load_checkpoint(str(tmp_path), tag="t")


def test_truncated_leaf_detected(tmp_path):
    eng = _engine()
    _train(eng, steps=1)
    eng.save_checkpoint(str(tmp_path), tag="t")
    mpath = tmp_path / "t" / "optim" / "manifest.json"
    manifest = json.load(open(mpath))
    key, entry = next((k, e) for k, e in manifest.items()
                      if e.get("nbytes", 0) > 16)
    fpath = tmp_path / "t" / "optim" / entry["file"]
    data = open(fpath, "rb").read()
    open(fpath, "wb").write(data[:-8])  # truncate mid-payload
    eng2 = _engine(seed=1)
    with pytest.raises(CheckpointCorruptError):
        eng2.load_checkpoint(str(tmp_path), tag="t")
    # the model plane arm too (module-only restore)
    mpath = tmp_path / "t" / "model" / "manifest.json"
    manifest = json.load(open(mpath))
    key, entry = next((k, e) for k, e in manifest.items()
                      if e.get("nbytes", 0) > 16)
    fpath = tmp_path / "t" / "model" / entry["file"]
    data = open(fpath, "rb").read()
    open(fpath, "wb").write(data[:-8])
    with pytest.raises(CheckpointCorruptError):
        eng2.load_checkpoint(str(tmp_path), tag="t",
                             load_module_only=True)


# ---------------------------------------------------------------------------
# fallback chain
# ---------------------------------------------------------------------------
def test_corrupt_latest_falls_back_to_older_tag(tmp_path):
    eng = _engine()
    _train(eng, steps=2)
    eng.save_checkpoint(str(tmp_path), tag="t1")
    good_master = jax.tree.map(
        lambda x: np.array(jax.device_get(x)), eng.state.master_params)
    _train(eng, steps=1, seed=3)
    eng.save_checkpoint(str(tmp_path), tag="t2")  # latest -> t2
    _corrupt_one_leaf(str(tmp_path / "t2"), "optim")

    eng2 = _engine(seed=9)
    path, _ = eng2.load_checkpoint(str(tmp_path))  # tag=None
    assert path is not None and path.endswith("t1")
    _state_equal(good_master, eng2.state.master_params)
    assert eng2.global_steps == 2


def test_latest_points_to_deleted_tag(tmp_path):
    """Manual cleanup / partial rsync: `latest` names a tag whose dir is
    gone — fall back to the newest on-disk tag that verifies instead of
    reporting "nothing to load" (ISSUE 5 satellite)."""
    import shutil
    eng = _engine()
    _train(eng, steps=2)
    eng.save_checkpoint(str(tmp_path), tag="a")
    _train(eng, steps=1, seed=3)
    eng.save_checkpoint(str(tmp_path), tag="b")  # latest -> b
    shutil.rmtree(tmp_path / "b")

    eng2 = _engine(seed=9)
    path, _ = eng2.load_checkpoint(str(tmp_path))
    assert path is not None and path.endswith("a")
    assert eng2.global_steps == 2


def test_fallback_bounded_by_config(tmp_path):
    """load_fallback=0 disables walking back: a corrupt latest raises
    instead of silently resuming from an older tag."""
    over = {"checkpoint": {"load_fallback": 0}}
    eng = _engine(**over)
    _train(eng, steps=1)
    eng.save_checkpoint(str(tmp_path), tag="t1")
    _train(eng, steps=1, seed=3)
    eng.save_checkpoint(str(tmp_path), tag="t2")
    _corrupt_one_leaf(str(tmp_path / "t2"), "optim")
    eng2 = _engine(seed=9, **over)
    with pytest.raises(CheckpointCorruptError, match="load_fallback"):
        eng2.load_checkpoint(str(tmp_path))


def test_all_candidates_corrupt_raises(tmp_path):
    eng = _engine()
    _train(eng, steps=1)
    eng.save_checkpoint(str(tmp_path), tag="t1")
    _train(eng, steps=1, seed=3)
    eng.save_checkpoint(str(tmp_path), tag="t2")
    _corrupt_one_leaf(str(tmp_path / "t1"), "optim")
    _corrupt_one_leaf(str(tmp_path / "t2"), "optim")
    eng2 = _engine(seed=9)
    with pytest.raises(CheckpointCorruptError, match="no loadable"):
        eng2.load_checkpoint(str(tmp_path))


# ---------------------------------------------------------------------------
# retention GC + orphan sweep
# ---------------------------------------------------------------------------
def test_retention_keep_last_n(tmp_path):
    over = {"checkpoint": {"keep_last_n": 2}}
    eng = _engine(**over)
    for i in range(4):
        _train(eng, steps=1, seed=i)
        eng.save_checkpoint(str(tmp_path), tag=f"t{i}")
        time.sleep(0.02)  # distinct mtimes for newest-first ordering
    tags = {d for d in os.listdir(tmp_path)
            if os.path.isdir(tmp_path / d)}
    assert tags == {"t2", "t3"}
    assert (tmp_path / "latest").read_text().strip() == "t3"
    # the survivors load fine
    eng2 = _engine(seed=9, **over)
    path, _ = eng2.load_checkpoint(str(tmp_path))
    assert path.endswith("t3")


def test_stale_tmp_sweep(tmp_path):
    """A crash mid-save leaves <tag>.tmp forever unless the SAME tag is
    re-saved (the old behavior); any save now sweeps every orphaned
    *.tmp under save_dir (ISSUE 5 satellite)."""
    orphan = tmp_path / "dead_tag.tmp"
    orphan.mkdir()
    (orphan / "leaf_00000.npy").write_bytes(b"partial")
    eng = _engine()
    _train(eng, steps=1)
    eng.save_checkpoint(str(tmp_path), tag="fresh")
    assert not orphan.exists()
    assert (tmp_path / "fresh").is_dir()


def test_gc_never_removes_before_save_verifies(tmp_path):
    """A save that dies mid-write must not trigger retention: the old
    tags — the fallback chain's substance — survive."""
    over = {"checkpoint": {"keep_last_n": 1, "io_retry_attempts": 1}}
    eng = _engine(**over)
    for i in range(2):
        _train(eng, steps=1, seed=i)
        eng.save_checkpoint(str(tmp_path), tag=f"t{i}")
        time.sleep(0.02)
    assert {d for d in os.listdir(tmp_path)
            if os.path.isdir(tmp_path / d)} == {"t1"}
    _train(eng, steps=1, seed=9)
    os.environ["DS_CKPT_FAULT"] = "meta:1+"
    try:
        with pytest.raises(Exception):
            eng.save_checkpoint(str(tmp_path), tag="t2")
    finally:
        os.environ.pop("DS_CKPT_FAULT", None)
    # t1 survived the failed save; nothing was GC'd
    assert (tmp_path / "t1" / "meta.json").is_file()
    eng2 = _engine(seed=5, **over)
    path, _ = eng2.load_checkpoint(str(tmp_path))
    assert path.endswith("t1")


# ---------------------------------------------------------------------------
# transient-I/O retry
# ---------------------------------------------------------------------------
def test_io_retry_transient_blip():
    calls = []

    def flaky():
        calls.append(1)
        if len(calls) < 3:
            raise OSError("blip")
        return "ok"
    assert io_retry(flaky, "flaky", RetryPolicy(3, 0.001)) == "ok"
    assert len(calls) == 3
    with pytest.raises(OSError):
        io_retry(lambda: (_ for _ in ()).throw(OSError("dead")),
                 "dead", RetryPolicy(2, 0.001))


def test_save_retries_injected_fault(tmp_path):
    """A single-shot injected fault (leaf write #2 fails once) is
    absorbed by the retry plane; the save completes, loads back, and the
    ckpt_retries_total counter records the blip."""
    over = {"checkpoint": {"io_retry_base_s": 0.001},
            "telemetry": {"enabled": True,
                          "output_path": str(tmp_path / "tel"),
                          "compile_events": False, "memory": False}}
    eng = _engine(**over)
    _train(eng, steps=1)
    os.environ["DS_CKPT_FAULT"] = "leaf:2"
    try:
        eng.save_checkpoint(str(tmp_path), tag="t")
    finally:
        os.environ.pop("DS_CKPT_FAULT", None)
    assert eng.telemetry.registry.counter(
        "ckpt_retries_total", "").value() >= 1
    eng2 = _engine(seed=5)
    path, _ = eng2.load_checkpoint(str(tmp_path), tag="t")
    assert path is not None
    _state_equal(eng.state.master_params, eng2.state.master_params)
    eng.close()


# ---------------------------------------------------------------------------
# kill-during-save torture matrix
# ---------------------------------------------------------------------------
@pytest.mark.parametrize("point", ["leaf:1+", "leaf:4+", "manifest:1+",
                                   "manifest:2+", "meta:1+", "rename:1+",
                                   "latest:1+"])
def test_torture_kill_at_every_write_point(point, tmp_path):
    """Sustained failure (≈ SIGKILL mid-save) at EVERY write point —
    each leaf file, the manifests, meta.json, the rename, the latest
    update: a subsequent load must always restore the last GOOD
    checkpoint bitwise, never a partial one."""
    over = {"checkpoint": {"io_retry_attempts": 2,
                           "io_retry_base_s": 0.001}}
    eng = _engine(**over)
    _train(eng, steps=2)
    eng.save_checkpoint(str(tmp_path), tag="good")
    good_bytes = _dir_bytes(str(tmp_path / "good"))
    good_master = jax.tree.map(
        lambda x: np.array(jax.device_get(x)), eng.state.master_params)
    good_opt = jax.tree.map(
        lambda x: np.array(jax.device_get(x)), eng.state.opt_state)

    _train(eng, steps=1, seed=7)
    os.environ["DS_CKPT_FAULT"] = point
    try:
        if point.startswith("latest"):
            # everything else landed; only the pointer update died —
            # the save fails loudly but `latest` still names "good"
            with pytest.raises(Exception):
                eng.save_checkpoint(str(tmp_path), tag="doomed")
        else:
            with pytest.raises(Exception):
                eng.save_checkpoint(str(tmp_path), tag="doomed")
            # the kill left no loadable-looking doomed checkpoint
            assert not os.path.isfile(
                tmp_path / "doomed" / "meta.json") or point == "latest:1+"
    finally:
        os.environ.pop("DS_CKPT_FAULT", None)
    reset_fault_injection()

    # the good checkpoint's bytes are untouched
    assert _dir_bytes(str(tmp_path / "good")) == good_bytes
    eng2 = _engine(seed=11, **over)
    path, _ = eng2.load_checkpoint(str(tmp_path))  # via latest
    assert path is not None and path.endswith("good")
    _state_equal(good_master, eng2.state.master_params)
    _state_equal(good_opt, eng2.state.opt_state)
    assert eng2.global_steps == 2


def test_torture_kill_during_async_save(tmp_path):
    """The async arm of the same guarantee: a writer killed mid-save
    leaves the previous checkpoint as the loadable truth."""
    over = {"checkpoint": {"io_retry_attempts": 1}}
    eng = _engine(**over)
    _train(eng, steps=2)
    eng.save_checkpoint(str(tmp_path), tag="good")
    good_master = jax.tree.map(
        lambda x: np.array(jax.device_get(x)), eng.state.master_params)
    _train(eng, steps=1, seed=7)
    os.environ["DS_CKPT_FAULT"] = "manifest:1+"
    try:
        eng.save_checkpoint(str(tmp_path), tag="doomed", async_write=True)
        err = eng._ckpt_writer.drain()
    finally:
        os.environ.pop("DS_CKPT_FAULT", None)
    assert err is not None  # poisoned THAT save only
    eng2 = _engine(seed=11)
    path, _ = eng2.load_checkpoint(str(tmp_path))
    assert path.endswith("good")
    _state_equal(good_master, eng2.state.master_params)


# ---------------------------------------------------------------------------
# SIGTERM preemption
# ---------------------------------------------------------------------------
def test_preemption_sigterm_resume_identical(tmp_path):
    """End-to-end: SIGTERM mid-run → final sync save + close → restart →
    loss trajectory identical to an uninterrupted run."""
    ref = _engine(seed=0)
    batches = list(random_batches(ref.train_batch_size, HIDDEN,
                                  num_batches=5, seed=0))
    ref_losses = [float(ref.train_batch(b)) for b in batches]

    eng = _engine(seed=0)
    handler = resilience.install_preemption_handler(
        eng, str(tmp_path), exit_after=False)
    for b in batches[:3]:
        eng.train_batch(b)
    os.kill(os.getpid(), signal.SIGTERM)  # delivered between bytecodes
    assert handler.fired
    handler.uninstall()
    # the hook saved at the PREEMPTED step (3), not an interval boundary
    eng2 = _engine(seed=42)
    path, _ = eng2.load_checkpoint(str(tmp_path))
    assert path is not None and eng2.global_steps == 3
    resumed = [float(eng2.train_batch(b)) for b in batches[3:]]
    assert resumed == ref_losses[3:]


def test_sigterm_config_installs_handler(tmp_path):
    over = {"checkpoint": {"sigterm_save": True,
                           "save_dir": str(tmp_path)}}
    eng = _engine(**over)
    h = eng._preemption_handler
    assert h is not None and h.installed
    assert signal.getsignal(signal.SIGTERM) == h._handle
    eng.close()  # uninstalls
    assert signal.getsignal(signal.SIGTERM) != h._handle


# ---------------------------------------------------------------------------
# telemetry + bench evidence
# ---------------------------------------------------------------------------
def test_async_overlap_visible_in_tracer(tmp_path):
    """With injected write latency, the checkpoint/async_write span must
    extend past its checkpoint/save span (the write ran in the
    background) and a subsequent train/dispatch span must start inside
    the write window — overlap proven from tracer timestamps."""
    over = {"telemetry": {"enabled": True,
                          "output_path": str(tmp_path / "tel"),
                          "compile_events": False, "memory": False}}
    eng = _engine(**over)
    _train(eng, steps=1)
    os.environ["DS_CKPT_DELAY_S"] = "0.2"
    try:
        eng.save_checkpoint(str(tmp_path / "ck"), async_write=True)
        _train(eng, steps=2, seed=5)
        assert eng._ckpt_writer.drain() is None
    finally:
        os.environ.pop("DS_CKPT_DELAY_S", None)
    ev = [e for e in eng.telemetry.tracer.events() if e.get("ph") == "X"]

    def spans(name):
        return [(e["ts"], e["ts"] + e["dur"]) for e in ev
                if e["name"] == name]
    (s0, s1), = spans("checkpoint/save")
    (w0, w1), = spans("checkpoint/async_write")
    assert w1 > s1 + 0.1e6, "write did not run past the save call"
    dispatch = [t for t in spans("train/dispatch") if t[0] > s1]
    assert dispatch and dispatch[0][0] < w1, \
        "no training step overlapped the background write"
    eng.close()


def test_ckpt_scalars_flow_to_summarize(tmp_path, capsys):
    """ckpt_save_s / ckpt_async_overlap_s ride the periodic sync into
    events.jsonl and surface as the summarize checkpoint row."""
    from deepspeed_tpu.telemetry.cli import summarize
    over = {"steps_per_print": 2,
            "telemetry": {"enabled": True,
                          "output_path": str(tmp_path / "tel"),
                          "compile_events": False, "memory": False}}
    eng = _engine(**over)
    _train(eng, steps=1)
    eng.save_checkpoint(str(tmp_path / "ck"), async_write=True)
    assert eng._ckpt_writer.drain() is None
    _train(eng, steps=3, seed=5)  # crosses the steps_per_print sync
    eng.close()
    report = summarize(str(tmp_path / "tel" / "events.jsonl"))
    capsys.readouterr()
    assert report["ckpt_save_s"] is not None
    assert report["ckpt_async_overlap_s"] is not None
    assert report["ckpt_async_overlap_s"] > 0


@pytest.mark.slow
def test_bench_ckpt_cpu_smoke(tmp_path, monkeypatch):
    """bench.py --ckpt legs run on CPU with injected write latency: the
    async leg's exposed per-save stall collapses vs sync, and hidden
    (tracer-proven) time is > 0.  Slow tier: the two GPT-2 engine builds
    dominate (~19s); the core tier proves the same overlap from tracer
    timestamps in test_async_overlap_visible_in_tracer."""
    import importlib.util
    path = os.path.join(os.path.dirname(__file__), "..", "bench.py")
    spec = importlib.util.spec_from_file_location("bench_for_test", path)
    bench = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(bench)
    monkeypatch.setenv("DS_CKPT_DELAY_S", "0.1")
    monkeypatch.chdir(tmp_path)
    a = bench.bench_ckpt(jax, True, steps=4, interval=2)
    s = bench.bench_ckpt(jax, False, steps=4, interval=2)
    assert a["saves"] == s["saves"] == 2
    assert a["save_exposed_s"] < s["save_exposed_s"]
    assert a["ckpt_hidden_s"] > 0
    assert s["ckpt_hidden_s"] == 0


# ---------------------------------------------------------------------------
# misc semantics
# ---------------------------------------------------------------------------
def test_sync_save_drains_pending_async(tmp_path):
    """Ordering: a sync save issued while an async one is in flight must
    land AFTER it — `latest` ends on the sync tag, never a stale one."""
    eng = _engine()
    _train(eng, steps=1)
    os.environ["DS_CKPT_DELAY_S"] = "0.2"
    try:
        eng.save_checkpoint(str(tmp_path), tag="a", async_write=True)
    finally:
        os.environ.pop("DS_CKPT_DELAY_S", None)
    eng.save_checkpoint(str(tmp_path), tag="b", async_write=False)
    assert not eng._ckpt_writer.in_flight()
    assert (tmp_path / "a" / "meta.json").is_file()
    assert (tmp_path / "b" / "meta.json").is_file()
    assert (tmp_path / "latest").read_text().strip() == "b"


def test_close_drains_async_save(tmp_path):
    eng = _engine()
    _train(eng, steps=1)
    os.environ["DS_CKPT_DELAY_S"] = "0.2"
    try:
        eng.save_checkpoint(str(tmp_path), tag="t", async_write=True)
    finally:
        os.environ.pop("DS_CKPT_DELAY_S", None)
    eng.close()
    assert (tmp_path / "t" / "meta.json").is_file()


def test_fsync_on_by_default(tmp_path, monkeypatch):
    """Production saves fsync every file + the dir (power-loss
    durability); DS_CKPT_FSYNC=0 (the conftest's test-speed knob on this
    image's slow 9p filesystem) suppresses it.  Pin both arms so the
    default can't silently rot."""
    import deepspeed_tpu.runtime.checkpointing as ckpt_mod
    calls = []
    real_fsync = os.fsync
    monkeypatch.setattr(os, "fsync", lambda fd: calls.append(fd)
                        or real_fsync(fd))
    eng = _engine()
    _train(eng, steps=1)
    monkeypatch.setenv("DS_CKPT_FSYNC", "0")
    eng.save_checkpoint(str(tmp_path), tag="nosync")
    assert not calls
    monkeypatch.delenv("DS_CKPT_FSYNC")  # production default: ON
    assert ckpt_mod._fsync_enabled()
    eng.save_checkpoint(str(tmp_path), tag="sync")
    assert len(calls) > 5  # every leaf + manifests + meta + latest + dir


def test_legacy_checkpoint_without_crc_still_loads(tmp_path):
    """Pre-integrity checkpoints (no crc32/nbytes/digests) load on
    trust — format evolution must not orphan old runs."""
    eng = _engine()
    _train(eng, steps=2)
    eng.save_checkpoint(str(tmp_path), tag="t")
    ck = tmp_path / "t"
    meta = json.load(open(ck / "meta.json"))
    meta.pop("manifest_digests", None)
    meta.pop("format_version", None)
    json.dump(meta, open(ck / "meta.json", "w"))
    for plane in ("model", "optim"):
        mp = ck / plane / "manifest.json"
        m = json.load(open(mp))
        for e in m.values():
            e.pop("crc32", None)
            e.pop("nbytes", None)
        json.dump(m, open(mp, "w"))
    eng2 = _engine(seed=9)
    path, _ = eng2.load_checkpoint(str(tmp_path), tag="t")
    assert path is not None
    _state_equal(eng.state.master_params, eng2.state.master_params)


def test_stacked_handler_uninstall_does_not_clobber(tmp_path):
    """Two engines with SIGTERM hooks: closing/uninstalling the FIRST
    must not clobber the second's active handler (blind restore would
    silently revert SIGTERM to the default kill — found by the verify
    drive)."""
    e1 = _engine(seed=1)
    e2 = _engine(seed=2)
    h1 = resilience.install_preemption_handler(
        e1, str(tmp_path / "a"), exit_after=False)
    h2 = resilience.install_preemption_handler(
        e2, str(tmp_path / "b"), exit_after=False)
    _train(e1, 1)
    _train(e2, 1)
    h1.uninstall()  # sandwiched: must go inert, not restore its prev
    assert signal.getsignal(signal.SIGTERM) == h2._handle
    os.kill(os.getpid(), signal.SIGTERM)
    assert h2.fired and not h1.fired
    assert (tmp_path / "b" / "latest").is_file()   # e2's hook saved
    assert not (tmp_path / "a").exists()           # e1's did not
    h2.uninstall()
    # h2 restored ITS prev (the inert h1, which chains through); a
    # further SIGTERM fires neither hook and saves nothing new
    before = os.listdir(tmp_path)
    os.kill(os.getpid(), signal.SIGTERM)
    assert not h1.fired and os.listdir(tmp_path) == before


def test_sigterm_mid_step_defers_to_boundary(tmp_path):
    """A SIGTERM that interrupts train_batch mid-update must NOT save
    immediately (it could checkpoint a torn, half-applied optimizer
    state with valid CRCs — code-review finding): the handler parks and
    the save runs at the step boundary."""
    eng = _engine(seed=0)
    handler = resilience.install_preemption_handler(
        eng, str(tmp_path), exit_after=False)
    _train(eng, steps=1)
    eng._in_step = True  # simulate the signal landing inside train_batch
    handler._handle(signal.SIGTERM, None)
    assert not handler.fired
    assert eng._deferred_preempt is handler
    assert not (tmp_path / "latest").exists()  # nothing saved mid-step
    eng._in_step = False
    _train(eng, steps=1, seed=3)  # finally-block completes the save
    assert handler.fired
    assert (tmp_path / "latest").is_file()
    eng2 = _engine(seed=9)
    path, _ = eng2.load_checkpoint(str(tmp_path))
    # saved at the boundary AFTER the interrupted step finished
    assert eng2.global_steps == 2
    handler.uninstall()


def test_same_tag_resave_survives_failed_publish(tmp_path):
    """Re-saving an EXISTING tag must never destroy the only copy: the
    old checkpoint is parked aside (swap) and restored when the publish
    rename fails — previously it was rmtree'd before the rename
    (code-review finding)."""
    over = {"checkpoint": {"io_retry_attempts": 1}}
    eng = _engine(**over)
    _train(eng, steps=2)
    eng.save_checkpoint(str(tmp_path), tag="best")
    good = _dir_bytes(str(tmp_path / "best"))
    _train(eng, steps=1, seed=7)
    os.environ["DS_CKPT_FAULT"] = "rename:1+"
    try:
        with pytest.raises(Exception):
            eng.save_checkpoint(str(tmp_path), tag="best")
    finally:
        os.environ.pop("DS_CKPT_FAULT", None)
    reset_fault_injection()
    # the OLD 'best' was restored bitwise and still loads
    assert _dir_bytes(str(tmp_path / "best")) == good
    eng2 = _engine(seed=9, **over)
    path, _ = eng2.load_checkpoint(str(tmp_path), tag="best")
    assert path is not None and eng2.global_steps == 2
    # the parked copy was named *.tmp, so the next save sweeps any debris
    eng.save_checkpoint(str(tmp_path), tag="best")
    assert not any(d.endswith(".tmp") for d in os.listdir(tmp_path))


def test_close_surfaces_lost_async_save(tmp_path):
    """A save that fails while close() drains must still land in
    last_ckpt_error — not vanish with the daemon thread (code-review
    finding: drain() inside close() used to clear the error before the
    tick could pop it)."""
    eng = _engine()
    _train(eng, steps=1)
    os.environ["DS_CKPT_FAULT"] = "meta:1+"
    try:
        eng.save_checkpoint(str(tmp_path), tag="t", async_write=True)
        eng.close()
    finally:
        os.environ.pop("DS_CKPT_FAULT", None)
    assert eng.last_ckpt_error is not None


def test_sweep_restores_stranded_park_dir(tmp_path):
    """A crash between the park and publish renames of a same-tag
    re-save leaves ONLY <tag>.replaced.tmp (the old good copy) and
    <tag>.tmp on disk; the next save's sweep must RESTORE the park dir,
    not delete it (code-review finding: it was treated as an orphan)."""
    import shutil
    eng = _engine()
    _train(eng, steps=2)
    eng.save_checkpoint(str(tmp_path), tag="best")
    good = _dir_bytes(str(tmp_path / "best"))
    # simulate the crash window: tag parked, publish never happened
    shutil.move(str(tmp_path / "best"), str(tmp_path / "best.replaced.tmp"))
    (tmp_path / "best.tmp").mkdir()
    (tmp_path / "best.tmp" / "junk.npy").write_bytes(b"partial")
    _train(eng, steps=1, seed=5)
    eng.save_checkpoint(str(tmp_path), tag="other")
    assert _dir_bytes(str(tmp_path / "best")) == good  # restored bitwise
    assert not (tmp_path / "best.replaced.tmp").exists()
    assert not (tmp_path / "best.tmp").exists()        # debris swept
    eng2 = _engine(seed=9)
    path, _ = eng2.load_checkpoint(str(tmp_path), tag="best")
    assert path is not None and eng2.global_steps == 2


def test_sync_save_surfaces_drained_async_failure(tmp_path):
    """An async save failing WHILE a subsequent sync save drains the
    writer must land in last_ckpt_error, not vanish with the drain
    (code-review finding: drain() cleared the error before the pre-step
    tick could pop it)."""
    eng = _engine()
    _train(eng, steps=1)
    gate = threading.Event()

    def boom():
        gate.wait(5.0)
        raise OSError("lost async save")
    eng._ckpt_writer.submit(CheckpointJob(
        "doomed", str(tmp_path / "doomed.tmp"),
        str(tmp_path / "doomed"), boom))
    threading.Timer(0.2, gate.set).start()
    # the sync save finds the writer in flight, drains it, and must
    # surface the drained failure on the engine
    eng.save_checkpoint(str(tmp_path), tag="ok", async_write=False)
    assert isinstance(eng.last_ckpt_error, OSError)
    assert (tmp_path / "latest").read_text().strip() == "ok"


# ---------------------------------------------------------------------------
# elastic-supervisor interplay (ISSUE 6 satellites)
# ---------------------------------------------------------------------------
def test_sigterm_during_elastic_restart_window_no_double_save(tmp_path):
    """The elastic supervisor's kill discipline is SIGTERM (the
    preemption save fires) then an escalated second SIGTERM when the
    worker is slow to die.  The escalation landing in the restart
    window must chain to the previous handler cleanly — exactly ONE
    save on disk, no second save mutating the just-written tag, no
    torn handler chain."""
    chained = []
    prev = signal.signal(signal.SIGTERM, lambda s, f: chained.append(s))
    try:
        eng = _engine(seed=0)
        handler = resilience.install_preemption_handler(
            eng, str(tmp_path), exit_after=False)
        _train(eng, steps=2)
        os.kill(os.getpid(), signal.SIGTERM)   # supervisor's TERM
        assert handler.fired
        assert chained == [signal.SIGTERM]     # saved, THEN chained prev
        latest = (tmp_path / "latest").read_text().strip()
        saved = sorted(os.listdir(tmp_path))
        meta = tmp_path / latest / "meta.json"
        mtime = os.stat(meta).st_mtime_ns
        os.kill(os.getpid(), signal.SIGTERM)   # escalation in the window
        assert chained == [signal.SIGTERM] * 2  # chained, never swallowed
        assert sorted(os.listdir(tmp_path)) == saved  # no new tag/tmp
        assert os.stat(meta).st_mtime_ns == mtime     # no re-save either
        handler.uninstall()
    finally:
        signal.signal(signal.SIGTERM, prev)


def test_sigterm_escalation_mid_step_defers_one_save(tmp_path):
    """Both the supervisor's TERM and its escalation landing while
    train_batch is mid-update (``_in_step``): the handler parks twice,
    saves NOTHING mid-step (a torn half-applied state would have valid
    CRCs), and the step boundary completes exactly one save."""
    eng = _engine(seed=0)
    handler = resilience.install_preemption_handler(
        eng, str(tmp_path), exit_after=False)
    _train(eng, steps=1)
    eng._in_step = True
    handler._handle(signal.SIGTERM, None)
    handler._handle(signal.SIGTERM, None)  # escalation, still mid-step
    assert not handler.fired
    assert not (tmp_path / "latest").exists()  # nothing saved mid-step
    eng._in_step = False
    _train(eng, steps=1, seed=3)  # finally-block completes ONE save
    assert handler.fired
    eng2 = _engine(seed=9)
    path, _ = eng2.load_checkpoint(str(tmp_path))
    assert path is not None and eng2.global_steps == 2
    handler.uninstall()


def test_legacy_checkpoint_without_data_plane_loads_fresh_iter(tmp_path):
    """Checkpoints from before the data-iterator plane existed (ISSUE 6)
    still load: model/optimizer restore exactly, the iterator starts
    FRESH with one loud warning — pinned alongside the no-CRC legacy
    test above (format evolution must not orphan old runs)."""
    import logging

    from deepspeed_tpu.runtime.dataloader import (DeepSpeedDataLoader,
                                                  RepeatingLoader)
    from deepspeed_tpu.utils.logging import logger as ds_logger

    def mk(seed):
        eng = _engine(seed=seed)
        xs = np.random.default_rng(0).standard_normal(
            (32, HIDDEN)).astype(np.float32)
        eng.training_dataloader = RepeatingLoader(DeepSpeedDataLoader(
            [(xs[i], 0.5 * xs[i]) for i in range(32)],
            batch_size=eng.train_batch_size, shuffle=True, seed=5))
        return eng

    eng = mk(0)
    losses = [float(eng.train_batch()) for _ in range(2)]
    eng.save_checkpoint(str(tmp_path), tag="t")
    eng.close()
    # strip the data plane + its digest: the pre-ISSUE-6 on-disk layout
    import shutil
    shutil.rmtree(tmp_path / "t" / "data")
    meta = json.load(open(tmp_path / "t" / "meta.json"))
    del meta["manifest_digests"]["data"]
    json.dump(meta, open(tmp_path / "t" / "meta.json", "w"))

    eng2 = mk(9)
    records = []

    class Rec(logging.Handler):
        def emit(self, record):
            records.append(record)

    h = Rec(level=logging.WARNING)
    ds_logger.addHandler(h)
    try:
        path, _ = eng2.load_checkpoint(str(tmp_path), tag="t")
    finally:
        ds_logger.removeHandler(h)
    assert path is not None and eng2.global_steps == 2
    assert any("predates the data-iterator plane" in r.getMessage()
               for r in records)
    _state_equal(eng.state.master_params, eng2.state.master_params)
    # fresh iterator: draws epoch 0's first batch (a replay, loudly
    # warned about — NOT a crash)
    float(eng2.train_batch())
    assert losses  # reference leg really trained
    eng2.close()
