"""DeepSpeedDataLoader + RepeatingLoader + the initialize(training_data=…)
leg (reference: deepspeed/runtime/dataloader.py and the deepspeed_io wiring
in engine.__init__ there)."""
import numpy as np
import pytest

import deepspeed_tpu
from deepspeed_tpu import DeepSpeedDataLoader, RepeatingLoader
from deepspeed_tpu.config import DeepSpeedConfig
from deepspeed_tpu.parallel import build_mesh

from simple_model import SimpleModel, base_config

HIDDEN = 8


def _dataset(n=32):
    rng = np.random.default_rng(0)
    xs = rng.standard_normal((n, HIDDEN)).astype(np.float32)
    return [(xs[i], 0.5 * xs[i]) for i in range(n)]


def test_dataloader_batches_and_len():
    dl = DeepSpeedDataLoader(_dataset(32), batch_size=8)
    assert len(dl) == 4
    batches = list(dl)
    assert len(batches) == 4
    x, y = batches[0]
    assert x.shape == (8, HIDDEN) and y.shape == (8, HIDDEN)
    np.testing.assert_allclose(y, 0.5 * x)


def test_dataloader_drop_last_and_shuffle():
    dl = DeepSpeedDataLoader(_dataset(30), batch_size=8)  # 30 % 8 != 0
    assert len(dl) == 3  # drop_last default

    dl_keep = DeepSpeedDataLoader(_dataset(30), batch_size=8,
                                  drop_last=False)
    assert len(dl_keep) == 4

    d1 = DeepSpeedDataLoader(_dataset(32), batch_size=8, shuffle=True,
                             seed=1)
    d2 = DeepSpeedDataLoader(_dataset(32), batch_size=8, shuffle=False)
    x_shuf = next(iter(d1))[0]
    x_seq = next(iter(d2))[0]
    assert not np.allclose(x_shuf, x_seq)  # order actually changed


def test_dataloader_dict_samples():
    ds = [{"a": np.ones((2,)) * i, "b": np.asarray(i)} for i in range(8)]
    batch = next(iter(DeepSpeedDataLoader(ds, batch_size=4)))
    assert set(batch) == {"a", "b"}
    assert batch["a"].shape == (4, 2) and batch["b"].shape == (4,)


def test_repeating_loader_restarts():
    dl = DeepSpeedDataLoader(_dataset(16), batch_size=8)
    rep = RepeatingLoader(dl)
    got = [next(rep) for _ in range(5)]  # 2 per epoch -> wraps twice
    np.testing.assert_allclose(got[0][0], got[2][0])
    np.testing.assert_allclose(got[1][0], got[3][0])


def test_repeating_loader_reshuffle_deterministic():
    """Epoch-boundary reshuffle under a fixed seed: two identically
    seeded loaders produce the SAME batch sequence across epoch
    restarts (the loader's rng is persistent state, not re-seeded per
    epoch), and consecutive epochs actually differ (the reshuffle
    happened)."""
    def seq(seed):
        rep = RepeatingLoader(DeepSpeedDataLoader(
            _dataset(16), batch_size=8, shuffle=True, seed=seed))
        return [next(rep)[0] for _ in range(6)]  # 3 epochs x 2 batches

    a, b = seq(seed=3), seq(seed=3)
    for i, (x, y) in enumerate(zip(a, b)):
        np.testing.assert_array_equal(x, y, err_msg=f"batch {i}")
    # epoch 0 vs epoch 1: order changed (epoch-boundary reshuffle)
    assert not all(np.array_equal(a[i], a[i + 2]) for i in range(2))


def test_drop_last_false_tail_warns_and_recompiles(tmp_path):
    """drop_last=False with a non-divisible dataset yields a short tail
    batch whose differing leading shape silently recompiles the step it
    feeds once per epoch (the JL005 hazard class): the loader must warn
    LOUDLY at construction, and the recompile must be visible as a
    recompiles_total{program=...} bump."""
    import logging

    from deepspeed_tpu.utils.logging import logger as ds_logger

    records = []

    class Rec(logging.Handler):
        def emit(self, record):
            records.append(record)

    h = Rec(level=logging.WARNING)
    ds_logger.addHandler(h)
    try:
        dl = DeepSpeedDataLoader(_dataset(30), batch_size=8,
                                 drop_last=False)
    finally:
        ds_logger.removeHandler(h)
    msgs = [r.getMessage() for r in records]
    assert any("drop_last=False" in m and "JL005" in m for m in msgs), msgs
    assert len(dl) == 4

    # the runtime shadow: feed the full batches then the 6-row tail to
    # eval_batch and watch the tracked program retrace
    cfg_dict = base_config(micro_bs=2, grad_acc=1)
    cfg_dict["telemetry"] = {"enabled": True,
                             "output_path": str(tmp_path)}
    engine, _, _, _ = deepspeed_tpu.initialize(
        model=SimpleModel(hidden_dim=HIDDEN),
        config=DeepSpeedConfig(cfg_dict, world_size=8),
        mesh=build_mesh())
    batches = list(dl)
    assert batches[-1][0].shape[0] == 30 % 8  # the tail
    engine.eval_batch(batch=batches[0])
    engine.telemetry.compile_monitor.sample()
    before = engine.telemetry.registry.counter(
        "recompiles_total").value(program="eval_step")
    engine.eval_batch(batch=batches[-1])  # tail shape -> retrace
    engine.telemetry.compile_monitor.sample()
    after = engine.telemetry.registry.counter(
        "recompiles_total").value(program="eval_step")
    assert after >= before + 1, (before, after)
    engine.close()


def test_initialize_with_training_data_trains():
    """The 4-tuple's dataloader leg: initialize(training_data=…) must
    return a loader sized to the global batch, and train_batch(data_iter=…)
    must consume it (reference __init__.py:47-136 + engine deepspeed_io)."""
    mesh = build_mesh()
    cfg = DeepSpeedConfig(base_config(micro_bs=2, grad_acc=2, stage=1),
                          world_size=8)
    engine, opt, dl, sched = deepspeed_tpu.initialize(
        model=SimpleModel(hidden_dim=HIDDEN), config=cfg, mesh=mesh,
        training_data=_dataset(engine_bs := cfg.train_batch_size * 2))
    assert dl is not None and len(dl) == 2  # 2 global batches
    it = iter(RepeatingLoader(dl))
    losses = [float(np.asarray(engine.train_batch(data_iter=it)))
              for _ in range(4)]
    assert all(np.isfinite(l) for l in losses)
    # eval_batch shares the data_iter signature (reference
    # pipe/engine.py:305 there)
    ev = float(np.asarray(engine.eval_batch(data_iter=it)))
    assert np.isfinite(ev)
    # a no-arg eval_batch must NOT silently consume the training iterator
    with pytest.raises(ValueError, match="does not fall back"):
        engine.eval_batch()
