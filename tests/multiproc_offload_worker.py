"""Worker body for the multi-host ZeRO-Offload host-tier test.

Each of the two processes must stage ONLY its dp-shard of the fp32
master and gradients (the reference's per-DP-rank fp32 partitions,
reference: deepspeed/runtime/zero/stage2.py:743-900) — asserted from
the optimizer's actual host bytes — and the loss trajectory must match
the single-controller tier run by the parent test process on the same
global batch (same global semantics, different staging topology).
"""
import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))

import jax

jax.config.update("jax_platforms", "cpu")

import numpy as np  # noqa: E402

import deepspeed_tpu  # noqa: E402
from deepspeed_tpu.parallel import build_mesh  # noqa: E402
from simple_model import SimpleModel  # noqa: E402

HIDDEN = 32


def main():
    out_dir = sys.argv[1]
    deepspeed_tpu.init_distributed()
    assert jax.process_count() == 2, jax.process_count()
    pid = jax.process_index()

    mesh = build_mesh(dp=8, devices=jax.devices())
    cfg = {
        "train_micro_batch_size_per_gpu": 2,
        "gradient_accumulation_steps": 2,
        "steps_per_print": 10 ** 9,
        "bf16": {"enabled": True},
        "optimizer": {"type": "Adam", "params": {"lr": 1e-2}},
        "zero_optimization": {"stage": 2, "cpu_offload": True,
                              "offload_impl": "host"},
    }
    engine, _, _, _ = deepspeed_tpu.initialize(
        model=SimpleModel(hidden_dim=HIDDEN), config=cfg, mesh=mesh)
    assert getattr(engine, "_offload_sharded", False), \
        "multi-process host tier must use the sharded optimizer"

    # --- per-host staged bytes ~ total/nproc -------------------------
    params = SimpleModel(hidden_dim=HIDDEN).init(jax.random.PRNGKey(0))
    total_fp32 = sum(int(np.prod(l.shape)) * 4
                     for l in jax.tree.leaves(params))
    staged = engine._host_opt.staged_bytes()
    # each process addresses 4 of the 8 dp shards; leaves that don't
    # shard stay replicated but deduplicate to ONE block per process
    assert staged <= total_fp32 * 0.75, (staged, total_fp32)
    assert staged >= total_fp32 * 0.25, (staged, total_fp32)

    # --- step parity with the single-controller tier -----------------
    rng = np.random.default_rng(0)
    gx = rng.normal(size=(32, HIDDEN)).astype(np.float32)
    gy = (0.5 * gx).astype(np.float32)
    lo, hi = (0, 16) if pid == 0 else (16, 32)
    losses = []
    for _ in range(5):
        loss = engine.train_batch((gx[lo:hi], gy[lo:hi]))
        losses.append(float(np.asarray(loss)))
    ref = json.load(open(os.path.join(out_dir, "ref_losses.json")))
    np.testing.assert_allclose(losses, ref, rtol=2e-3, atol=2e-3)

    # --- checkpoint roundtrip (per-process shard files) ---------------
    engine.save_checkpoint(out_dir, tag="mpoff")
    cont = float(np.asarray(engine.train_batch((gx[lo:hi], gy[lo:hi]))))

    engine2, _, _, _ = deepspeed_tpu.initialize(
        model=SimpleModel(hidden_dim=HIDDEN), config=cfg, mesh=mesh,
        seed=9)
    path, _ = engine2.load_checkpoint(out_dir, tag="mpoff")
    assert path is not None
    got = float(np.asarray(engine2.train_batch((gx[lo:hi], gy[lo:hi]))))
    assert abs(got - cont) < 1e-5, (got, cont)

    # --- delayed parameter update × sharded tier ----------------------
    cfg_dpu = dict(cfg)
    cfg_dpu["zero_optimization"] = dict(
        cfg["zero_optimization"], delayed_param_update=True)
    eng3, _, _, _ = deepspeed_tpu.initialize(
        model=SimpleModel(hidden_dim=HIDDEN), config=cfg_dpu, mesh=mesh)
    dl = [float(np.asarray(eng3.train_batch((gx[lo:hi], gy[lo:hi]))))
          for _ in range(5)]
    assert all(np.isfinite(v) for v in dl), dl
    assert dl[-1] < dl[0], dl

    # --- ZeRO-3 × offload-xla × param streaming, 2 processes ----------
    # (dryrun leg 10 runs this single-process; here the pieces and the
    # host-resident streamed leaves span two REAL processes)
    from deepspeed_tpu.models import GPT2Config, GPT2Model
    cfg_m = GPT2Config(d_model=32, n_layer=2, n_head=4, vocab_size=128,
                       n_positions=32, remat="block", scan_layers=True,
                       stream_scan=True, attn_impl="dense")
    cfg_s = {
        "train_micro_batch_size_per_gpu": 1,
        "gradient_accumulation_steps": 1,
        "steps_per_print": 10 ** 9,
        "bf16": {"enabled": True},
        "optimizer": {"type": "Adam", "params": {"lr": 1e-3}},
        "zero_optimization": {"stage": 3, "cpu_offload": True,
                              "offload_impl": "xla",
                              "param_streaming": True},
    }
    eng5, _, _, _ = deepspeed_tpu.initialize(
        model=GPT2Model(cfg_m), config=cfg_s, mesh=mesh)
    toks = np.random.default_rng(2).integers(0, 128, (8, 17),
                                             dtype=np.int32)
    sl = [float(np.asarray(eng5.train_batch(toks[4 * pid:4 * pid + 4])))
          for _ in range(3)]
    assert all(np.isfinite(v) for v in sl), sl

    print(f"WORKER_{pid}_OK staged={staged} total={total_fp32} "
          f"loss={losses[-1]:.6f} resume={got:.6f} dpu={dl[-1]:.6f} "
          f"stream={sl[-1]:.6f}")


if __name__ == "__main__":
    main()
