"""Mixed precision + dynamic loss scaling semantics
(mirrors reference tests/unit/test_dynamic_loss_scale.py and parts of
test_fp16.py)."""
import jax
import jax.numpy as jnp
import numpy as np
import pytest

from deepspeed_tpu.runtime import precision
from deepspeed_tpu.runtime.engine import DeepSpeedEngine
from deepspeed_tpu.config import DeepSpeedConfig
from deepspeed_tpu.parallel import build_mesh

from simple_model import SimpleModel, base_config, random_batches


def _scaler(**kw):
    defaults = dict(enabled=True, static_scale=0, initial_scale_power=4,
                    scale_window=3, hysteresis=2, min_scale=1.0)
    defaults.update(kw)
    return precision.make_loss_scaler(**defaults)


def test_initial_scale():
    s, _ = _scaler()
    assert float(s.loss_scale) == 2 ** 4


def test_static_scale():
    s, c = _scaler(static_scale=128)
    assert float(s.loss_scale) == 128
    s2 = precision.update_scale(s, jnp.asarray(False), c)
    assert float(s2.loss_scale) == 128  # static never moves


def test_overflow_hysteresis_then_halve():
    s, c = _scaler(hysteresis=2)
    overflow = jnp.asarray(False)
    # first overflow: hysteresis absorbs it
    s1 = precision.update_scale(s, overflow, c)
    assert float(s1.loss_scale) == 16.0
    # second overflow: scale halves
    s2 = precision.update_scale(s1, overflow, c)
    assert float(s2.loss_scale) == 8.0


def test_growth_after_window():
    s, c = _scaler(scale_window=3, hysteresis=1)
    good = jnp.asarray(True)
    for _ in range(2):
        s = precision.update_scale(s, good, c)
        assert float(s.loss_scale) == 16.0
    s = precision.update_scale(s, good, c)
    assert float(s.loss_scale) == 32.0


def test_overflow_resets_good_steps():
    s, c = _scaler(scale_window=3, hysteresis=1)
    s = precision.update_scale(s, jnp.asarray(True), c)
    s = precision.update_scale(s, jnp.asarray(False), c)  # halve + reset
    assert float(s.loss_scale) == 8.0
    for _ in range(2):
        s = precision.update_scale(s, jnp.asarray(True), c)
    assert float(s.loss_scale) == 8.0  # window restarted, not grown yet
    s = precision.update_scale(s, jnp.asarray(True), c)
    assert float(s.loss_scale) == 16.0


def test_min_scale_floor():
    s, c = _scaler(initial_scale_power=1, hysteresis=1, min_scale=1.0)
    for _ in range(5):
        s = precision.update_scale(s, jnp.asarray(False), c)
    assert float(s.loss_scale) == 1.0


def test_grads_finite():
    good = {"a": jnp.ones((3,)), "b": jnp.zeros((2, 2))}
    assert bool(precision.grads_finite(good))
    bad = {"a": jnp.ones((3,)), "b": jnp.array([jnp.inf, 1.0])}
    assert not bool(precision.grads_finite(bad))
    nan = {"a": jnp.array([jnp.nan])}
    assert not bool(precision.grads_finite(nan))


def test_cast_to_compute_skips_ints():
    tree = {"w": jnp.ones((2,), jnp.float32), "i": jnp.ones((2,), jnp.int32)}
    out = precision.cast_to_compute(tree, jnp.bfloat16)
    assert out["w"].dtype == jnp.bfloat16
    assert out["i"].dtype == jnp.int32


def test_engine_overflow_skips_step():
    """An inf loss must skip the update, bump skipped_steps, halve scale."""
    model = SimpleModel(hidden_dim=8)

    class ExplodingModel(SimpleModel):
        def loss_fn(self, params, batch, rng, train=True):
            loss = super().loss_fn(params, batch, rng, train)
            # overflow on the very first step only (step counter via params
            # is not available; instead scale loss hugely so fp16 grads inf)
            return loss * 1e38

    cfg = DeepSpeedConfig(
        base_config(micro_bs=4, stage=0, precision="fp16",
                    **{"fp16": {"enabled": True, "initial_scale_power": 8,
                                "hysteresis": 1}}),
        world_size=8)
    mesh = build_mesh()
    eng = DeepSpeedEngine(ExplodingModel(hidden_dim=8), cfg, mesh=mesh)
    batch = next(random_batches(32, 8))
    before = jax.tree.leaves(eng.state.master_params)[0].copy()
    eng.train_batch(batch)
    after = jax.tree.leaves(eng.state.master_params)[0]
    assert eng.get_skipped_steps() == 1
    assert float(eng.state.scaler.loss_scale) == 2 ** 7
    np.testing.assert_array_equal(np.asarray(before), np.asarray(after))


def test_lamb_fp16_unfused_contract():
    """LAMB under fp16 — the reference routes this through the *unfused*
    wrapper (runtime/fp16/unfused_optimizer.py:42-63: per-tensor fp32
    masters, no flattening, because LAMB needs per-tensor norms).  Here the
    master is a per-tensor fp32 pytree by construction; this test pins that
    contract (mirrors reference test_fp16.py:54 test_lamb_fp16_basic)."""
    cfg = DeepSpeedConfig(
        base_config(micro_bs=4, stage=0, precision="fp16",
                    optimizer={"type": "lamb",
                               "params": {"lr": 1e-2}},
                    **{"fp16": {"enabled": True,
                                "initial_scale_power": 8}}),
        world_size=8)
    eng = DeepSpeedEngine(SimpleModel(hidden_dim=8), cfg, mesh=build_mesh())

    # per-tensor fp32 master: every leaf keeps its own shape and dtype
    masters = jax.tree.leaves(eng.state.master_params)
    assert all(m.dtype == jnp.float32 for m in masters)
    assert len(masters) == len(jax.tree.leaves(eng.module.init(
        jax.random.PRNGKey(0))))

    losses = [float(np.asarray(eng.train_batch(b)))
              for b in random_batches(32, 8, num_batches=6, seed=3)]
    assert losses[-1] < losses[0]
    assert eng.get_skipped_steps() == 0


def test_lamb_fp16_overflow_skip():
    """Overflow-skip must work on the LAMB path too (reference:
    unfused_optimizer.py step/overflow handling + step_fused_lamb :118)."""
    class ExplodingModel(SimpleModel):
        def loss_fn(self, params, batch, rng, train=True):
            return super().loss_fn(params, batch, rng, train) * 1e38

    cfg = DeepSpeedConfig(
        base_config(micro_bs=4, stage=0, precision="fp16",
                    optimizer={"type": "lamb", "params": {"lr": 1e-2}},
                    **{"fp16": {"enabled": True, "initial_scale_power": 8,
                                "hysteresis": 1}}),
        world_size=8)
    eng = DeepSpeedEngine(ExplodingModel(hidden_dim=8), cfg,
                          mesh=build_mesh())
    before = jax.tree.leaves(eng.state.master_params)[0].copy()
    eng.train_batch(next(random_batches(32, 8)))
    assert eng.get_skipped_steps() == 1
    assert float(eng.state.scaler.loss_scale) == 2 ** 7
    np.testing.assert_array_equal(
        np.asarray(before), np.asarray(jax.tree.leaves(
            eng.state.master_params)[0]))
