"""Byte-BPE tokenizer tests (deepspeed_tpu/utils/bpe.py) — the data plane
of the real-corpus convergence tier (reference trains its convergence
models on pre-tokenized real text, tests/model/Megatron_GPT2/test_common.py
there)."""
import gzip
import json
import os

import numpy as np
import pytest

from deepspeed_tpu.utils.bpe import ByteBPE, _pretokenize

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
DATA = os.path.join(REPO, "data")

SAMPLE = (
    "The quick brown fox jumps over the lazy dog. "
    "Training the tokenizer on repeated text: the the the fox fox. "
    "Unicode survives byte-level round trips: naive café — δx ≈ 0.1!\n\n"
    "Indented code-ish lines\n    stay intact too.\n"
) * 50


def test_pretokenize_partitions_exactly():
    words = _pretokenize(SAMPLE)
    assert b"".join(words).decode() == SAMPLE


def test_train_and_roundtrip():
    bpe = ByteBPE.train(SAMPLE, vocab_size=300)
    assert 256 < bpe.vocab_size <= 300
    ids = bpe.encode(SAMPLE)
    assert bpe.decode(ids) == SAMPLE
    # merges must actually compress repeated text
    assert len(ids) < len(SAMPLE.encode()) * 0.6


def test_byte_fallback_handles_unseen_text():
    bpe = ByteBPE.train("aaaa bbbb " * 100, vocab_size=260)
    weird = "完全 unseen ← ☃ text\x00\x07"
    assert bpe.decode(bpe.encode(weird)) == weird


def test_training_is_deterministic():
    a = ByteBPE.train(SAMPLE, vocab_size=300)
    b = ByteBPE.train(SAMPLE, vocab_size=300)
    assert a.merges == b.merges


def test_save_load(tmp_path):
    bpe = ByteBPE.train(SAMPLE, vocab_size=300)
    p = str(tmp_path / "tok.json")
    bpe.save(p)
    loaded = ByteBPE.load(p)
    assert loaded.merges == bpe.merges
    assert loaded.encode("fox jumps") == bpe.encode("fox jumps")


def test_load_rejects_foreign_json(tmp_path):
    p = str(tmp_path / "bad.json")
    with open(p, "w") as f:
        json.dump({"merges": []}, f)
    with pytest.raises(ValueError):
        ByteBPE.load(p)


@pytest.mark.skipif(not os.path.exists(os.path.join(DATA, "tokens.npz")),
                    reason="vendored corpus not built")
def test_vendored_corpus_artifacts_consistent():
    """The committed tokens must be exactly what the committed tokenizer
    produces from the committed corpus (prefix check keeps it fast)."""
    bpe = ByteBPE.load(os.path.join(DATA, "tokenizer.json"))
    assert bpe.vocab_size == 4096
    tokens = np.load(os.path.join(DATA, "tokens.npz"))["tokens"]
    assert tokens.dtype == np.uint16
    assert int(tokens.max()) < 4096
    assert len(tokens) > 1_000_000          # enough for 500+ distinct steps
    with gzip.open(os.path.join(DATA, "corpus.txt.gz"), "rt",
                   encoding="utf-8") as f:
        text = f.read(200_000)
    enc = bpe.encode(text)
    n = min(len(enc), 20_000) - 64  # stay clear of the read-boundary word
    assert enc[:n] == tokens[:n].tolist()
    # the corpus is real prose: natural-language word statistics
    assert "the" in text.lower()
