"""End-to-end pipeline-parallel training on the virtual mesh — the analogue
of the reference's pipeline-vs-sequential equivalence test
(reference: tests/unit/test_pipe.py trains AlexNet pipelined vs sequential
and compares losses)."""
import numpy as np
import jax
import jax.numpy as jnp
import pytest

import deepspeed_tpu
from deepspeed_tpu.config import DeepSpeedConfig
from deepspeed_tpu.parallel import build_mesh
from deepspeed_tpu.pipe import LayerSpec, TiedLayerSpec, PipelineModule
from deepspeed_tpu.pipe.engine import PipelineEngine
from deepspeed_tpu.runtime.engine import DeepSpeedEngine
from deepspeed_tpu.runtime.module import FunctionalModule

from simple_model import base_config

DIM = 16


class Linear:
    """Minimal pipeline layer: init/apply contract."""

    def __init__(self, din, dout, act="relu"):
        self.din, self.dout, self.act = din, dout, act

    def init(self, rng):
        k1, _ = jax.random.split(rng)
        return {"w": jax.random.normal(k1, (self.din, self.dout),
                                       jnp.float32) * 0.2,
                "b": jnp.zeros((self.dout,), jnp.float32)}

    def apply(self, params, x, rng, train=True):
        y = x @ params["w"].astype(x.dtype) + params["b"].astype(x.dtype)
        if self.act == "relu":
            y = jax.nn.relu(y)
        return y


def mse_loss(out, labels):
    return jnp.mean((out.astype(jnp.float32) -
                     labels.astype(jnp.float32)) ** 2)


def _specs(nlayers=4, dim=DIM):
    return [LayerSpec(Linear, dim, dim) for _ in range(nlayers)]


def _batch(n, dim=DIM, seed=0):
    rng = np.random.default_rng(seed)
    x = rng.standard_normal((n, dim)).astype(np.float32)
    return (x, (0.5 * np.abs(x)).astype(np.float32))


def _pipe_cfg(micro=1, grad_acc=4, dp=4, **over):
    return base_config(micro_bs=micro, grad_acc=grad_acc, stage=0,
                       precision="bf16",
                       optimizer={"type": "Adam", "params": {"lr": 1e-2}},
                       **over)


def test_pipeline_trains_pp2():
    mesh = build_mesh(pp=2, dp=4, tp=1)
    pm = PipelineModule(_specs(4), num_stages=2, loss_fn=mse_loss,
                        partition_method="uniform")
    cfg = DeepSpeedConfig(_pipe_cfg(), world_size=4)
    eng = PipelineEngine(pm, cfg, mesh)
    batch = _batch(cfg.train_batch_size)
    losses = [float(eng.train_batch(batch)) for _ in range(10)]
    assert losses[-1] < losses[0] * 0.8, losses


def test_pipeline_matches_sequential():
    """pp=2 pipelined loss trajectory == sequential execution of the same
    layers (same init, same data)."""
    pm = PipelineModule(_specs(4), num_stages=2, loss_fn=mse_loss,
                        partition_method="uniform")

    seq_model = FunctionalModule(
        init_fn=pm.init,
        loss_fn=lambda p, b, rng, train: mse_loss(
            pm.forward(p, b[0], rng, train), b[1]))

    batch = _batch(16)

    mesh_p = build_mesh(pp=2, dp=4, tp=1)
    cfg_p = DeepSpeedConfig(_pipe_cfg(micro=1, grad_acc=4, dp=4),
                            world_size=4)
    eng_p = PipelineEngine(pm, cfg_p, mesh_p, seed=3)
    pipe_losses = [float(eng_p.train_batch(batch)) for _ in range(5)]

    mesh_s = build_mesh(pp=1, dp=4, tp=1, devices=jax.devices()[:4])
    cfg_s = DeepSpeedConfig(_pipe_cfg(micro=1, grad_acc=4, dp=4),
                            world_size=4)
    eng_s = DeepSpeedEngine(seq_model, cfg_s, mesh=mesh_s, seed=3)
    seq_losses = [float(eng_s.train_batch(batch)) for _ in range(5)]

    np.testing.assert_allclose(pipe_losses, seq_losses, rtol=2e-2)


def test_pipeline_eval_batch():
    """Forward-only pipelined eval (reference PipelineEngine.eval_batch /
    InferenceSchedule, pipe/engine.py:305-363): the pipelined eval loss
    equals a sequential evaluation of the same layers on the same batch."""
    pm = PipelineModule(_specs(4), num_stages=2, loss_fn=mse_loss,
                        partition_method="uniform")
    mesh = build_mesh(pp=2, dp=4, tp=1)
    cfg = DeepSpeedConfig(_pipe_cfg(), world_size=4)
    eng = PipelineEngine(pm, cfg, mesh, seed=3)
    batch = _batch(cfg.train_batch_size)

    ev = float(np.asarray(eng.eval_batch(batch)))
    # sequential reference on the identical params (pm.forward indexes the
    # packed/stacked tree directly outside shard_map)
    full = eng.state.master_params
    seq = float(mse_loss(
        pm.forward(jax.tree.map(lambda x: x.astype(jnp.bfloat16)
                                if jnp.issubdtype(x.dtype, jnp.floating)
                                else x, full),
                   jnp.asarray(batch[0], jnp.bfloat16),
                   jax.random.PRNGKey(0), train=False), batch[1]))
    assert abs(ev - seq) / max(abs(seq), 1e-6) < 2e-2, (ev, seq)
    # training still works after eval (separate compiled programs)
    l0 = float(eng.train_batch(batch))
    assert np.isfinite(l0)
    # divisibility error path
    with pytest.raises(ValueError, match="divisible"):
        eng.eval_batch((batch[0][:3], batch[1][:3]))


@pytest.mark.slow
def test_pipeline_pp4():
    mesh = build_mesh(pp=4, dp=2, tp=1)
    pm = PipelineModule(_specs(8), num_stages=4, loss_fn=mse_loss,
                        partition_method="uniform")
    cfg = DeepSpeedConfig(_pipe_cfg(micro=2, grad_acc=4, dp=2),
                          world_size=2)
    eng = PipelineEngine(pm, cfg, mesh)
    batch = _batch(cfg.train_batch_size)
    losses = [float(eng.train_batch(batch)) for _ in range(8)]
    assert losses[-1] < losses[0] * 0.8, losses


@pytest.mark.slow
def test_pipeline_heterogeneous_stages():
    """Different layer widths inside stages; only boundaries must match."""
    specs = [LayerSpec(Linear, DIM, 32), LayerSpec(Linear, 32, DIM),
             LayerSpec(Linear, DIM, 24), LayerSpec(Linear, 24, DIM)]
    mesh = build_mesh(pp=2, dp=4, tp=1)
    pm = PipelineModule(specs, num_stages=2, loss_fn=mse_loss,
                        partition_method="uniform")
    cfg = DeepSpeedConfig(_pipe_cfg(), world_size=4)
    eng = PipelineEngine(pm, cfg, mesh)
    batch = _batch(cfg.train_batch_size)
    losses = [float(eng.train_batch(batch)) for _ in range(8)]
    assert losses[-1] < losses[0] * 0.9


def test_pipeline_boundary_mismatch_raises():
    # stage boundary at layer 2: [.,32] vs final [.,16] — mismatched
    specs = [LayerSpec(Linear, DIM, 32), LayerSpec(Linear, 32, 32),
             LayerSpec(Linear, 32, 32), LayerSpec(Linear, 32, DIM)]
    mesh = build_mesh(pp=2, dp=4, tp=1)
    pm = PipelineModule(specs, num_stages=2, loss_fn=mse_loss,
                        partition_method="uniform")
    cfg = DeepSpeedConfig(_pipe_cfg(), world_size=4)
    eng_err = None
    try:
        eng = PipelineEngine(pm, cfg, mesh)
        eng.train_batch(_batch(cfg.train_batch_size))
    except ValueError as e:
        eng_err = str(e)
    assert eng_err is not None and "boundar" in eng_err


@pytest.mark.slow
def test_pipeline_tied_layers():
    """TiedLayerSpec shares params across stages; grads flow from both uses
    (replaces the reference's tied-weight allreduce, pipe/module.py:405-474)."""
    tied = [
        TiedLayerSpec("emb", Linear, DIM, DIM),
        LayerSpec(Linear, DIM, DIM),
        LayerSpec(Linear, DIM, DIM),
        TiedLayerSpec("emb", Linear, DIM, DIM),
    ]
    mesh = build_mesh(pp=2, dp=4, tp=1)
    pm = PipelineModule(tied, num_stages=2, loss_fn=mse_loss,
                        partition_method="uniform")
    cfg = DeepSpeedConfig(_pipe_cfg(), world_size=4)
    eng = PipelineEngine(pm, cfg, mesh)
    params = eng.state.master_params
    assert "tied" in params and "emb" in params["tied"]
    # exactly one copy of the tied weights exists
    assert "layer_0" not in params and "layer_3" not in params
    batch = _batch(cfg.train_batch_size)
    before = np.asarray(params["tied"]["emb"]["w"]).copy()
    losses = [float(eng.train_batch(batch)) for _ in range(6)]
    after = np.asarray(eng.state.master_params["tied"]["emb"]["w"])
    assert not np.array_equal(before, after)  # tied grads applied
    assert losses[-1] < losses[0]


def test_initialize_dispatches_pipeline():
    mesh = build_mesh(pp=2, dp=4, tp=1)
    pm = PipelineModule(_specs(4), num_stages=2, loss_fn=mse_loss,
                        partition_method="uniform")
    engine, *_ = deepspeed_tpu.initialize(
        model=pm, config=_pipe_cfg(), mesh=mesh)
    assert isinstance(engine, PipelineEngine)
    assert engine.schedule == "1f1b"  # pipeline.schedule default
    loss = engine.train_batch(_batch(engine.train_batch_size))
    assert np.isfinite(float(loss))


def test_initialize_respects_pipeline_schedule_config():
    """pipeline.schedule in the ds_config reaches the engine through the
    initialize() entry point (the fallback knob for the gpipe path)."""
    mesh = build_mesh(pp=2, dp=4, tp=1)
    pm = PipelineModule(_specs(4), num_stages=2, loss_fn=mse_loss,
                        partition_method="uniform")
    engine, *_ = deepspeed_tpu.initialize(
        model=pm, config=_pipe_cfg(pipeline={"schedule": "gpipe"}),
        mesh=mesh)
    assert engine.schedule == "gpipe"
    with pytest.raises(ValueError, match="schedule"):
        PipelineEngine(pm, DeepSpeedConfig(_pipe_cfg(), world_size=4),
                       mesh, schedule="bogus")


def test_pipeline_stage_mismatch_raises():
    mesh = build_mesh(pp=2, dp=4, tp=1)
    pm = PipelineModule(_specs(4), num_stages=4, loss_fn=mse_loss,
                        partition_method="uniform")
    cfg = DeepSpeedConfig(_pipe_cfg(), world_size=4)
    with pytest.raises(ValueError):
        PipelineEngine(pm, cfg, mesh)


@pytest.mark.slow
def test_pipeline_with_zero1():
    mesh = build_mesh(pp=2, dp=4, tp=1)
    pm = PipelineModule(_specs(4), num_stages=2, loss_fn=mse_loss,
                        partition_method="uniform")
    cfg = DeepSpeedConfig(_pipe_cfg(
        zero_optimization={"stage": 1}), world_size=4)
    eng = PipelineEngine(pm, cfg, mesh)
    batch = _batch(cfg.train_batch_size)
    losses = [float(eng.train_batch(batch)) for _ in range(6)]
    assert losses[-1] < losses[0]


@pytest.mark.slow
def test_gpt2_pipeline_trains():
    """GPT-2 as a pipeline module: tied embedding/head + block stages."""
    from deepspeed_tpu.models import GPT2Config
    from deepspeed_tpu.models.gpt2_pipe import build_gpt2_pipe, split_gpt2_batch

    cfg_model = GPT2Config(vocab_size=128, n_positions=32, d_model=32,
                           n_layer=4, n_head=4, remat=None)
    mesh = build_mesh(pp=2, dp=4, tp=1)
    pm = build_gpt2_pipe(cfg_model, num_stages=2)
    cfg = DeepSpeedConfig(_pipe_cfg(micro=1, grad_acc=2, dp=4), world_size=4)
    eng = PipelineEngine(pm, cfg, mesh)
    toks = np.random.default_rng(0).integers(
        0, 128, (cfg.train_batch_size, 33), dtype=np.int32)
    batch = split_gpt2_batch(toks)
    losses = [float(eng.train_batch(batch)) for _ in range(8)]
    assert losses[-1] < losses[0], losses
    # tied embedding exists once and moved (head grads + embed grads)
    p = eng.state.master_params
    before_absent = [k for k in p if k.startswith("layer_0")]
    assert before_absent == []


@pytest.mark.slow
def test_3d_parallel_pipeline_tp_dp():
    """Full 3D: pipeline x data x tensor on one mesh, TP specs from the
    pipe layers (the reference's PipeModelDataParallelTopology slot,
    topology.py:246-249)."""
    import numpy as np
    from deepspeed_tpu.config import DeepSpeedConfig
    from deepspeed_tpu.models.gpt2 import GPT2Config
    from deepspeed_tpu.models.gpt2_pipe import (build_gpt2_pipe,
                                                split_gpt2_batch)
    from deepspeed_tpu.parallel import build_mesh
    from deepspeed_tpu.pipe.engine import PipelineEngine

    mesh = build_mesh(pp=2, dp=2, tp=2)
    cfg_model = GPT2Config(vocab_size=128, n_positions=32, d_model=32,
                           n_layer=2, n_head=4, remat=None,
                           attn_impl="dense")
    cfg = DeepSpeedConfig({
        "train_micro_batch_size_per_gpu": 1,
        "gradient_accumulation_steps": 4,
        "steps_per_print": 10 ** 9,
        "bf16": {"enabled": True},
        "zero_optimization": {"stage": 2},
        "optimizer": {"type": "Adam", "params": {"lr": 1e-3}},
    }, world_size=2)
    pm = build_gpt2_pipe(cfg_model, num_stages=2)
    engine = PipelineEngine(pm, cfg, mesh)
    # TP placement really applied AND stage-local storage: the stacked
    # block params are [S, k, d, 3d] sharded over pipe (stage dim) and
    # model (tensor dim)
    qkv = engine.state.master_params["stack_0"]["qkv_w"]
    spec = qkv.sharding.spec
    assert "model" in str(spec), f"expected model-axis sharding, got {spec}"
    assert "pipe" in str(spec), f"expected pipe-axis sharding, got {spec}"
    rng = np.random.default_rng(0)
    losses = []
    for s in range(4):
        toks = rng.integers(0, 128, (cfg.train_batch_size, 17),
                            dtype=np.int32)
        losses.append(float(engine.train_batch(split_gpt2_batch(toks))))
    assert all(np.isfinite(l) for l in losses)
    assert losses[-1] < losses[0]


@pytest.mark.slow
def test_bert_pipeline_trains():
    """BERT as a PipelineModule (fused encoder LayerSpecs + tied MLM
    embedding) trains under pp2 x dp4 + ZeRO-1 — the second model family
    through the pipeline engine."""
    from deepspeed_tpu.models.bert import BertConfig
    from deepspeed_tpu.models.bert_pipe import (build_bert_pipe,
                                                split_bert_batch)

    cfg_model = BertConfig(
        vocab_size=256, hidden_size=64, num_hidden_layers=4,
        num_attention_heads=4, intermediate_size=128,
        max_position_embeddings=64, hidden_dropout_prob=0.0,
        attention_probs_dropout_prob=0.0, remat=None)
    mesh = build_mesh(pp=2, dp=4)
    cfg = DeepSpeedConfig({
        "train_micro_batch_size_per_gpu": 1,
        "gradient_accumulation_steps": 4,
        "steps_per_print": 10 ** 9,
        "bf16": {"enabled": True},
        "zero_optimization": {"stage": 1},
        "optimizer": {"type": "Adam", "params": {"lr": 2e-3}},
    }, world_size=4)
    eng = PipelineEngine(build_bert_pipe(cfg_model, num_stages=2),
                         cfg, mesh)
    rng = np.random.default_rng(0)
    losses = []
    for _ in range(6):
        ids = rng.integers(0, 256, (16, 33), dtype=np.int32)
        labels = np.where(rng.random((16, 33)) < 0.2, ids,
                          -100).astype(np.int32)
        losses.append(float(np.asarray(eng.train_batch(
            split_bert_batch({"input_ids": ids,
                              "masked_lm_labels": labels})))))
    assert losses[-1] < losses[0]
    # tied embedding is stage-shared: exactly one wte in the tree
    assert "wte" in eng.state.master_params["tied"]["embed"]


@pytest.mark.slow
def test_pipeline_sequence_parallel_ring():
    """PP × SP: ring attention over the 'seq' axis inside the pipeline's
    uniform-stage body (nested shard_map; VERDICT r2 weak #5 — the
    long-context × big-model combination).  Differential against the same
    model under dense attention on a pp×dp mesh."""
    import dataclasses
    from deepspeed_tpu.models import GPT2Config
    from deepspeed_tpu.models.gpt2_pipe import (build_gpt2_pipe,
                                                split_gpt2_batch)

    cfg_model = GPT2Config(vocab_size=128, n_positions=64, d_model=32,
                           n_layer=4, n_head=4, remat=None,
                           attn_impl="ring", dropout=0.0, embd_dropout=0.0)
    cfg = DeepSpeedConfig({
        "train_micro_batch_size_per_gpu": 1,
        "gradient_accumulation_steps": 4,
        "steps_per_print": 10 ** 9,
        "bf16": {"enabled": True},
        "zero_optimization": {"stage": 2},
        "optimizer": {"type": "Adam", "params": {"lr": 1e-3}},
    }, world_size=2)
    mesh = build_mesh(pp=2, dp=2, sp=2, tp=1)
    eng = PipelineEngine(build_gpt2_pipe(cfg_model, num_stages=2), cfg,
                         mesh)
    # 1f1b auto-upgrades to the uniform-tick variant under seq > 1 (the
    # cond-based schedule's divergent branches cannot carry seq
    # collectives; the uniform one runs F+B masked every tick)
    assert eng.schedule == "1f1b_uniform"
    toks = np.random.default_rng(0).integers(
        0, 128, (cfg.train_batch_size, 33), dtype=np.int32)
    losses = [float(np.asarray(eng.train_batch(split_gpt2_batch(toks))))
              for _ in range(4)]
    assert all(np.isfinite(l) for l in losses), losses
    assert losses[-1] < losses[0], losses

    # control: dense attention, pp2×dp2, same global batch
    mesh_d = build_mesh(pp=2, dp=2, tp=1, devices=jax.devices()[:4])
    cfg_d = dataclasses.replace(cfg_model, attn_impl="dense")
    e2 = PipelineEngine(build_gpt2_pipe(cfg_d, num_stages=2),
                        DeepSpeedConfig({
                            "train_micro_batch_size_per_gpu": 1,
                            "gradient_accumulation_steps": 4,
                            "steps_per_print": 10 ** 9,
                            "bf16": {"enabled": True},
                            "zero_optimization": {"stage": 2},
                            "optimizer": {"type": "Adam",
                                          "params": {"lr": 1e-3}},
                        }, world_size=2), mesh_d, schedule="gpipe")
    l2 = [float(np.asarray(e2.train_batch(split_gpt2_batch(toks))))
          for _ in range(4)]
    for a, b in zip(losses, l2):
        assert abs(a - b) < 5e-2, (losses, l2)


def test_pipeline_rejects_cpu_offload():
    """PP × cpu_offload must fail loudly at construction (the offload
    tiers' dp-sharded flat master layout does not fit pipe-sharded
    stacks; the reference never composed them either) — not crash deep
    inside the step builder."""
    from deepspeed_tpu.models import GPT2Config
    from deepspeed_tpu.models.gpt2_pipe import build_gpt2_pipe

    cfg_model = GPT2Config(vocab_size=128, n_positions=64, d_model=32,
                           n_layer=4, n_head=4, remat=None,
                           attn_impl="dense")
    mesh = build_mesh(pp=2, dp=2, tp=1, devices=jax.devices()[:4])
    cfg = DeepSpeedConfig({
        "train_micro_batch_size_per_gpu": 1,
        "gradient_accumulation_steps": 4,
        "steps_per_print": 10 ** 9,
        "bf16": {"enabled": True},
        "zero_optimization": {"stage": 2, "cpu_offload": True,
                              "offload_impl": "xla"},
        "optimizer": {"type": "Adam", "params": {"lr": 1e-3}},
    }, world_size=2)
    with pytest.raises(ValueError, match="pipeline"):
        PipelineEngine(build_gpt2_pipe(cfg_model, num_stages=2), cfg,
                       mesh)


@pytest.mark.slow
def test_uniform_1f1b_matches_cond_1f1b():
    """The uniform-tick 1F1B (F+B units masked every tick — the
    schedule-invariant collective footprint that composes with sequence
    parallelism) must train identically to the cond-based 1F1B and to
    gpipe on the same mesh/batch — it is a re-scheduling, not new math.
    (reference contract: runtime/pipe/schedule.py:189-247 — TrainSchedule
    is the default; this is its SPMD-expressible form.)"""
    from deepspeed_tpu.models import GPT2Config
    from deepspeed_tpu.models.gpt2_pipe import (build_gpt2_pipe,
                                                split_gpt2_batch)

    cfg_model = GPT2Config(vocab_size=128, n_positions=64, d_model=32,
                           n_layer=4, n_head=4, remat="block",
                           attn_impl="dense")
    toks = np.random.default_rng(3).integers(
        0, 128, (8, 33), dtype=np.int32)
    losses = {}
    for sched in ("1f1b", "1f1b_uniform", "gpipe"):
        mesh = build_mesh(pp=2, dp=4, tp=1)
        cfg = DeepSpeedConfig({
            "train_micro_batch_size_per_gpu": 1,
            "gradient_accumulation_steps": 2,
            "steps_per_print": 10 ** 9,
            "bf16": {"enabled": True},
            "zero_optimization": {"stage": 1},
            "optimizer": {"type": "Adam", "params": {"lr": 1e-3}},
        }, world_size=4)
        eng = PipelineEngine(build_gpt2_pipe(cfg_model, num_stages=2),
                             cfg, mesh, schedule=sched)
        assert eng.schedule == sched
        losses[sched] = [
            float(np.asarray(eng.train_batch(split_gpt2_batch(toks))))
            for _ in range(4)]
    for k in ("1f1b_uniform", "gpipe"):
        diffs = [abs(a - b)
                 for a, b in zip(losses["1f1b"], losses[k])]
        assert max(diffs) < 5e-3, (k, losses)
    assert losses["1f1b_uniform"][-1] < losses["1f1b_uniform"][0]


@pytest.mark.slow
def test_uniform_1f1b_sp_matches_gpipe_sp():
    """1F1B × sequence parallelism (the composition the old guard
    forbade): ring attention over 'seq' inside the uniform-tick 1F1B
    must match the gpipe×sp trajectory on the identical mesh/batch."""
    from deepspeed_tpu.models import GPT2Config
    from deepspeed_tpu.models.gpt2_pipe import (build_gpt2_pipe,
                                                split_gpt2_batch)

    cfg_model = GPT2Config(vocab_size=128, n_positions=64, d_model=32,
                           n_layer=4, n_head=4, remat=None,
                           attn_impl="ring", dropout=0.0,
                           embd_dropout=0.0)
    toks = np.random.default_rng(5).integers(
        0, 128, (8, 33), dtype=np.int32)
    losses = {}
    for sched in ("1f1b_uniform", "gpipe"):
        mesh = build_mesh(pp=2, dp=2, sp=2, tp=1)
        cfg = DeepSpeedConfig({
            "train_micro_batch_size_per_gpu": 1,
            "gradient_accumulation_steps": 4,
            "steps_per_print": 10 ** 9,
            "bf16": {"enabled": True},
            "zero_optimization": {"stage": 2},
            "optimizer": {"type": "Adam", "params": {"lr": 1e-3}},
        }, world_size=2)
        eng = PipelineEngine(build_gpt2_pipe(cfg_model, num_stages=2),
                             cfg, mesh, schedule=sched)
        losses[sched] = [
            float(np.asarray(eng.train_batch(split_gpt2_batch(toks))))
            for _ in range(4)]
    diffs = [abs(a - b) for a, b in
             zip(losses["1f1b_uniform"], losses["gpipe"])]
    assert max(diffs) < 5e-3, losses
    assert losses["1f1b_uniform"][-1] < losses["1f1b_uniform"][0]


@pytest.mark.slow
def test_uniform_1f1b_deep_pipeline_collision_micros():
    """S=4 with M=3 micro-batches: 2S-1-2s ≡ 0 (mod M) at s=2, the
    same-tick ring slot collision where the F unit's stash write lands
    on the slot B is about to read — the read-before-write ordering in
    the tick body is what keeps this correct."""
    import dataclasses
    from deepspeed_tpu.models import GPT2Config
    from deepspeed_tpu.models.gpt2_pipe import (build_gpt2_pipe,
                                                split_gpt2_batch)

    cfg_model = GPT2Config(vocab_size=128, n_positions=64, d_model=32,
                           n_layer=4, n_head=4, remat=None, dropout=0.0,
                           attn_impl="ring")
    mesh = build_mesh(pp=4, dp=1, sp=2, tp=1)
    cfg = DeepSpeedConfig({
        "train_micro_batch_size_per_gpu": 1,
        "gradient_accumulation_steps": 3,
        "steps_per_print": 10 ** 9,
        "bf16": {"enabled": True},
        "zero_optimization": {"stage": 1},
        "optimizer": {"type": "Adam", "params": {"lr": 1e-3}},
    }, world_size=1)
    eng = PipelineEngine(build_gpt2_pipe(cfg_model, num_stages=4), cfg,
                         mesh)
    assert eng.schedule == "1f1b_uniform"
    toks = np.random.default_rng(0).integers(0, 128, (3, 33),
                                             dtype=np.int32)
    ls = [float(np.asarray(eng.train_batch(split_gpt2_batch(toks))))
          for _ in range(4)]
    assert all(np.isfinite(v) for v in ls) and ls[-1] < ls[0], ls

    # numerics against gpipe on the same mesh/batch
    e2 = PipelineEngine(build_gpt2_pipe(cfg_model, num_stages=4), cfg,
                        mesh, schedule="gpipe")
    l2 = [float(np.asarray(e2.train_batch(split_gpt2_batch(toks))))
          for _ in range(4)]
    diffs = [abs(a - b) for a, b in zip(ls, l2)]
    assert max(diffs) < 5e-3, (ls, l2)


@pytest.mark.slow
def test_pipeline_sp_rejects_non_uniform_partition():
    """SP×PP demands the uniform-stage layout; a heterogeneous pipeline
    raises the real story instead of deadlocking in the partitioner."""
    from deepspeed_tpu.models import GPT2Config
    from deepspeed_tpu.models.gpt2_pipe import (build_gpt2_pipe,
                                                split_gpt2_batch)
    # 3 blocks over 2 stages: rows 2+1, non-uniform by construction
    cfg_model = GPT2Config(vocab_size=128, n_positions=64, d_model=32,
                           n_layer=3, n_head=4, remat=None,
                           attn_impl="ring", dropout=0.0, embd_dropout=0.0)
    mesh = build_mesh(pp=2, dp=2, sp=2, tp=1)
    cfg = DeepSpeedConfig({
        "train_micro_batch_size_per_gpu": 1,
        "gradient_accumulation_steps": 4,
        "steps_per_print": 10 ** 9,
        "bf16": {"enabled": True},
        "zero_optimization": {"stage": 1},
        "optimizer": {"type": "Adam", "params": {"lr": 1e-3}},
    }, world_size=2)
    pm = build_gpt2_pipe(cfg_model, num_stages=2)
    eng = PipelineEngine(pm, cfg, mesh)
    toks = np.random.default_rng(0).integers(
        0, 128, (cfg.train_batch_size, 33), dtype=np.int32)
    with pytest.raises(NotImplementedError, match="uniform"):
        eng.train_batch(split_gpt2_batch(toks))


@pytest.mark.slow
def test_pipeline_sequence_parallel_ulysses():
    """PP × SP with the Ulysses (all-to-all head-scatter) implementation
    inside the pipeline's uniform-stage body — same composition slot as
    ring, differential against ring on the identical mesh/batch."""
    from deepspeed_tpu.models import GPT2Config
    from deepspeed_tpu.models.gpt2_pipe import (build_gpt2_pipe,
                                                split_gpt2_batch)

    cfg = DeepSpeedConfig({
        "train_micro_batch_size_per_gpu": 1,
        "gradient_accumulation_steps": 4,
        "steps_per_print": 10 ** 9,
        "bf16": {"enabled": True},
        "zero_optimization": {"stage": 2},
        "optimizer": {"type": "Adam", "params": {"lr": 1e-3}},
    }, world_size=2)
    mesh = build_mesh(pp=2, dp=2, sp=2, tp=1)
    toks = np.random.default_rng(9).integers(
        0, 128, (cfg.train_batch_size, 33), dtype=np.int32)
    losses = {}
    for impl in ("ring", "ulysses"):
        cm = GPT2Config(vocab_size=128, n_positions=64, d_model=32,
                        n_layer=2, n_head=4, remat=None, attn_impl=impl,
                        dropout=0.0, embd_dropout=0.0)
        eng = PipelineEngine(build_gpt2_pipe(cm, num_stages=2), cfg, mesh)
        losses[impl] = [
            float(np.asarray(eng.train_batch(split_gpt2_batch(toks))))
            for _ in range(3)]
    diffs = [abs(a - b) for a, b in zip(losses["ring"], losses["ulysses"])]
    assert max(diffs) < 2e-3, (losses, diffs)
