"""Ring attention + Ulysses sequence parallelism tests: both schemes must
reproduce dense full-sequence attention (fwd + bwd) on the 8-device mesh."""
from functools import partial

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import Mesh, PartitionSpec as P

from deepspeed_tpu.parallel.sequence import ring_attention, ulysses_attention

shard_map = partial(jax.shard_map, check_vma=False)

N = 8


def _mesh():
    return Mesh(np.array(jax.devices()[:N]), ("seq",))


def dense_attention(q, k, v, causal):
    scale = q.shape[-1] ** -0.5
    s = jnp.einsum("bhqd,bhkd->bhqk", q.astype(jnp.float32),
                   k.astype(jnp.float32)) * scale
    if causal:
        T = s.shape[-1]
        s = jnp.where(jnp.tril(jnp.ones((T, T), bool))[None, None], s, -1e30)
    p = jax.nn.softmax(s, -1)
    return jnp.einsum("bhqk,bhkd->bhqd", p,
                      v.astype(jnp.float32)).astype(q.dtype)


def make_qkv(B=2, H=8, T=128, D=16, seed=0):
    rng = np.random.default_rng(seed)
    mk = lambda: jnp.asarray(rng.standard_normal((B, H, T, D)), jnp.float32)
    return mk(), mk(), mk()


def run_sharded(fn, q, k, v):
    """Shard the seq dim (axis 2) over the mesh and run fn in shard_map."""
    mesh = _mesh()
    spec = P(None, None, "seq", None)
    wrapped = shard_map(fn, mesh=mesh, in_specs=(spec, spec, spec),
                        out_specs=spec)
    return jax.jit(wrapped)(q, k, v)


@pytest.mark.parametrize("causal", [True, False])
def test_ring_attention_matches_dense(causal):
    q, k, v = make_qkv(seed=1)
    out = run_sharded(
        lambda a, b, c: ring_attention(a, b, c, "seq", causal=causal),
        q, k, v)
    ref = dense_attention(q, k, v, causal)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=2e-5, atol=2e-5)


@pytest.mark.parametrize("impl", ["ring", "ulysses", "ulysses-dense"])
def test_sp_dropout_matches_dense_oracle(impl):
    """Dropout masks hash GLOBAL positions, so the sharded schemes must
    reproduce the dense oracle exactly for the same seed — across
    different shardings of the same computation and both Ulysses local
    kernels (flash default, dense debug path)."""
    from attention_oracles import dense_dropout_oracle
    if impl == "ring":
        fn = ring_attention
    elif impl == "ulysses":
        fn = ulysses_attention
    else:
        fn = partial(ulysses_attention, local_impl="dense")
    q, k, v = make_qkv(seed=7)
    seed = jnp.uint32(42)
    out = run_sharded(
        lambda a, b, c: fn(a, b, c, "seq", causal=True,
                           dropout_rate=0.2, dropout_seed=seed),
        q, k, v)
    ref = dense_dropout_oracle(q, k, v, 0.2, seed, causal=True)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=2e-5, atol=2e-5)


def test_sp_dropout_grads_flow():
    q, k, v = make_qkv(seed=9)
    seed = jnp.uint32(3)

    def loss(q, k, v):
        out = run_sharded(
            lambda a, b, c: ring_attention(a, b, c, "seq", causal=True,
                                           dropout_rate=0.3,
                                           dropout_seed=seed),
            q, k, v)
        return jnp.sum(out ** 2)

    g = jax.grad(loss)(q, k, v)
    assert np.isfinite(np.asarray(g)).all()
    assert np.abs(np.asarray(g)).max() > 0


def test_ulysses_flash_dropout_grads_match_oracle():
    """The Ulysses-flash backward path threads bh_ids through both
    backward kernels; its gradients must equal the dense oracle's for
    the same seed (catches a wrong per-head mask in bwd that forward
    tests cannot see)."""
    from attention_oracles import dense_dropout_oracle
    q, k, v = make_qkv(seed=11)
    seed = jnp.uint32(17)
    wt = jnp.asarray(np.random.default_rng(2).standard_normal(q.shape),
                     jnp.float32)

    def loss_sp(q, k, v):
        out = run_sharded(
            lambda a, b, c: ulysses_attention(a, b, c, "seq", causal=True,
                                              dropout_rate=0.25,
                                              dropout_seed=seed),
            q, k, v)
        return jnp.sum(out * wt)

    def loss_oracle(q, k, v):
        return jnp.sum(dense_dropout_oracle(q, k, v, 0.25, seed) * wt)

    gs = jax.grad(loss_sp, argnums=(0, 1, 2))(q, k, v)
    go = jax.grad(loss_oracle, argnums=(0, 1, 2))(q, k, v)
    for a, b, name in zip(gs, go, "qkv"):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=5e-5, atol=5e-5,
                                   err_msg=f"d{name} mismatch")


@pytest.mark.parametrize("causal", [True, False])
def test_ulysses_attention_matches_dense(causal):
    q, k, v = make_qkv(seed=2)
    out = run_sharded(
        lambda a, b, c: ulysses_attention(a, b, c, "seq", causal=causal),
        q, k, v)
    ref = dense_attention(q, k, v, causal)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=2e-5, atol=2e-5)


@pytest.mark.parametrize("impl", [ring_attention, ulysses_attention],
                         ids=["ring", "ulysses"])
def test_gradients_match_dense(impl):
    # B=2 on purpose: the untiled all_to_all formulation mis-lowered the
    # Ulysses backward exactly (and only) at B > 1
    q, k, v = make_qkv(B=2, H=8, T=64, D=8, seed=3)
    mesh = _mesh()
    spec = P(None, None, "seq", None)

    def sp_loss(q, k, v):
        fn = shard_map(lambda a, b, c: impl(a, b, c, "seq", causal=True),
                       mesh=mesh, in_specs=(spec, spec, spec),
                       out_specs=spec)
        return jnp.sum(fn(q, k, v) ** 2)

    def dense_loss(q, k, v):
        return jnp.sum(dense_attention(q, k, v, True) ** 2)

    g_sp = jax.jit(jax.grad(sp_loss, argnums=(0, 1, 2)))(q, k, v)
    g_dn = jax.grad(dense_loss, argnums=(0, 1, 2))(q, k, v)
    for a, b, name in zip(g_sp, g_dn, "qkv"):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=5e-4, atol=5e-4, err_msg=name)


def test_ring_attention_bf16_io():
    q, k, v = make_qkv(seed=4)
    q, k, v = (x.astype(jnp.bfloat16) for x in (q, k, v))
    out = run_sharded(
        lambda a, b, c: ring_attention(a, b, c, "seq", causal=True),
        q, k, v)
    assert out.dtype == jnp.bfloat16
    ref = dense_attention(q, k, v, True)
    np.testing.assert_allclose(
        np.asarray(out, dtype=np.float32), np.asarray(ref, dtype=np.float32),
        rtol=0.05, atol=0.05)


def test_ulysses_head_divisibility_guard():
    q, k, v = make_qkv(H=4)  # 4 heads, 8 shards
    with pytest.raises(AssertionError, match="divisible"):
        run_sharded(lambda a, b, c: ulysses_attention(a, b, c, "seq"),
                    q, k, v)


def test_ring_attention_long_sequence_memory_shape():
    """T=1024 over 8 shards: each device's score block is 128x128 — the
    full 1024x1024 matrix is never materialized per device (shape-level
    check via the compiled HLO's largest intermediate)."""
    q, k, v = make_qkv(B=1, H=2, T=1024, D=16, seed=5)
    mesh = _mesh()
    spec = P(None, None, "seq", None)
    fn = shard_map(lambda a, b, c: ring_attention(a, b, c, "seq"),
                   mesh=mesh, in_specs=(spec, spec, spec), out_specs=spec)
    out = jax.jit(fn)(q, k, v)
    ref = dense_attention(q, k, v, True)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=3e-5, atol=3e-5)


@pytest.mark.slow
def test_engine_level_sp_training_matches_dense():
    """Full engine training with ring attention over the seq axis (the
    'modern slot' for the reference's long-sequence feature, SURVEY §5.7)
    must match the dense-attention engine on the same batch."""
    import numpy as np
    from deepspeed_tpu.config import DeepSpeedConfig
    from deepspeed_tpu.models import GPT2Config, GPT2Model
    from deepspeed_tpu.parallel import build_mesh
    from deepspeed_tpu.runtime.engine import DeepSpeedEngine

    kw = dict(vocab_size=256, n_positions=128, d_model=64, n_layer=2,
              n_head=4, remat=None, dropout=0.0)
    cfg = DeepSpeedConfig({
        "train_micro_batch_size_per_gpu": 1,
        "gradient_accumulation_steps": 2,
        "steps_per_print": 10 ** 9,
        "bf16": {"enabled": True},
        "zero_optimization": {"stage": 2},
        "optimizer": {"type": "Adam", "params": {"lr": 1e-3}},
    }, world_size=2)
    toks = np.random.default_rng(0).integers(0, 256, (4, 65),
                                             dtype=np.int32)

    eng_sp = DeepSpeedEngine(
        GPT2Model(GPT2Config(attn_impl="ring", **kw)), cfg,
        mesh=build_mesh(pp=1, dp=2, sp=2, tp=2))
    eng_dense = DeepSpeedEngine(
        GPT2Model(GPT2Config(attn_impl="dense", **kw)), cfg,
        mesh=build_mesh(pp=1, dp=2, tp=1, devices=jax.devices()[:2]))
    # The ring implementation must ACTUALLY engage inside the engine's
    # jitted step.  The model discovers the 'seq' axis from
    # jax.sharding.get_abstract_mesh() at trace time — empty inside jit
    # unless the engine establishes the ambient mesh (jax.set_mesh in
    # _pallas_scope), in which case ring would silently degrade to the
    # GSPMD dense fallback and this parity test would still pass
    # (regression guard for the round-4 ambient-mesh fix).
    import deepspeed_tpu.parallel.sequence as seq_mod
    calls = []
    real_ring = seq_mod.ring_attention

    def counting_ring(*a, **k):
        calls.append(1)
        return real_ring(*a, **k)

    seq_mod.ring_attention = counting_ring
    try:
        for _ in range(3):
            loss_sp = eng_sp.train_batch(toks)
            loss_dense = eng_dense.train_batch(toks)
    finally:
        seq_mod.ring_attention = real_ring
    assert calls, ("ring_attention never traced — the engine step saw "
                   "an empty abstract mesh (sp silently degraded)")
    assert abs(float(np.asarray(loss_sp))
               - float(np.asarray(loss_dense))) < 0.05
