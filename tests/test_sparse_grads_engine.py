"""Engine-integrated CSR sparse gradients.

With ``sparse_gradients`` enabled and a module declaring embedding-style
params (``sparse_grad_tokens``), the engine exchanges those grads across
the data axis as (indices, values) allgathers instead of a dense
[vocab, d] reduction — the reference's nn.Embedding CSR path (reference:
deepspeed/runtime/engine.py:177-183,1153-1209, csr_tensor.py).
"""
import re

import numpy as np
import jax
import jax.numpy as jnp
import pytest

from deepspeed_tpu.config import DeepSpeedConfig
from deepspeed_tpu.parallel import build_mesh
from deepspeed_tpu.runtime.engine import DeepSpeedEngine
from deepspeed_tpu.runtime.module import TrainModule

VOCAB, DIM, SEQ = 4096, 16, 8


class BigEmbeddingModel(TrainModule):
    """Embedding -> mean-pool -> linear head; the embedding grad touches
    only the batch's token rows (the nn.Embedding sparse case)."""

    def init(self, rng):
        k1, k2 = jax.random.split(rng)
        return {
            "emb": jax.random.normal(k1, (VOCAB, DIM), jnp.float32) * 0.1,
            "head_w": jax.random.normal(k2, (DIM, DIM), jnp.float32) * 0.2,
        }

    def loss_fn(self, params, batch, rng, train=True):
        tokens, target = batch
        h = params["emb"].astype(jnp.float32)[tokens].mean(axis=1)
        out = h @ params["head_w"].astype(jnp.float32)
        return jnp.mean((out - target.astype(jnp.float32)) ** 2)

    def sparse_grad_tokens(self, batch):
        tokens, _ = batch
        return {"['emb']": tokens}


def _cfg(sparse: bool):
    return DeepSpeedConfig({
        "train_micro_batch_size_per_gpu": 2,
        "gradient_accumulation_steps": 2,
        "steps_per_print": 10 ** 9,
        "bf16": {"enabled": True},
        "sparse_gradients": sparse,
        "optimizer": {"type": "Adam", "params": {"lr": 1e-2}},
        "zero_optimization": {"stage": 0},
    }, world_size=8)


def _batch(seed=0):
    rng = np.random.default_rng(seed)
    tokens = rng.integers(0, VOCAB, (32, SEQ), dtype=np.int32)
    target = rng.normal(size=(32, DIM)).astype(np.float32)
    return tokens, target


@pytest.fixture(scope="module")
def mesh():
    return build_mesh(dp=8, devices=jax.devices())


def test_sparse_matches_dense_path(mesh):
    es = DeepSpeedEngine(BigEmbeddingModel(), _cfg(True), mesh=mesh, seed=5)
    ed = DeepSpeedEngine(BigEmbeddingModel(), _cfg(False), mesh=mesh, seed=5)
    assert es._use_sparse_grads()
    for i in range(5):
        b = _batch(i)
        ls = float(np.asarray(es.train_batch(b)))
        ld = float(np.asarray(ed.train_batch(b)))
        assert ls == pytest.approx(ld, rel=2e-3), (i, ls, ld)
    assert ls < float(np.asarray(es.eval_batch(_batch(0)))) * 5  # sane


def test_wire_format_is_indices_values(mesh):
    """The compiled HLO must carry NO collective of dense-embedding size
    (VOCAB*DIM); the embedding exchange is the token-sized (indices,
    values) gather."""
    eng = DeepSpeedEngine(BigEmbeddingModel(), _cfg(True), mesh=mesh)
    sharded = eng._shard_batch(_batch(0))
    txt = eng._train_step.lower(eng.state, sharded).compile().as_text()
    dense_elems = VOCAB * DIM
    coll = []
    for line in txt.splitlines():
        if re.search(r"= .*(all-reduce|all-gather|all-to-all)\(", line):
            for dt, dims in re.findall(r"(\w+)\[([\d,]+)\]", line):
                coll.append(int(np.prod([int(d) for d in dims.split(",")])))
    assert coll, "no collectives found in HLO"
    assert max(coll) < dense_elems // 4, (
        f"a dense-embedding-sized collective survived: max={max(coll)} "
        f"vs dense={dense_elems}")


def test_dense_fallbacks(mesh):
    # no sparse hook -> dense path
    from simple_model import SimpleModel
    cfg = _cfg(True)
    eng = DeepSpeedEngine(SimpleModel(hidden_dim=16), cfg, mesh=mesh)
    assert not eng._use_sparse_grads()
    # zero >= 1 -> dense path (reference parity)
    cfg2 = DeepSpeedConfig({
        "train_micro_batch_size_per_gpu": 2,
        "gradient_accumulation_steps": 2,
        "steps_per_print": 10 ** 9,
        "bf16": {"enabled": True},
        "sparse_gradients": True,
        "optimizer": {"type": "Adam", "params": {"lr": 1e-2}},
        "zero_optimization": {"stage": 2},
    }, world_size=8)
    eng2 = DeepSpeedEngine(BigEmbeddingModel(), cfg2, mesh=mesh)
    assert not eng2._use_sparse_grads()
    assert np.isfinite(float(np.asarray(eng2.train_batch(_batch(0)))))
