"""Standalone block-sparse MatMul/Softmax ops — differential tests vs
dense masked math (reference exposes the same reusable surface:
deepspeed/ops/sparse_attention/matmul.py:16, softmax.py; its unit tests
diff against dense torch the same way, tests/unit/test_sparse_attention.py
there)."""
import numpy as np
import jax
import jax.numpy as jnp
import pytest

from deepspeed_tpu.ops.sparse_attention import (BigBirdSparsityConfig,
                                                MatMul, Softmax)

BLK = 16
NB = 8
M = N = K = BLK * NB


def _layout(seed=0, density=0.4):
    rng = np.random.default_rng(seed)
    lay = (rng.random((NB, NB)) < density)
    lay[np.arange(NB), np.arange(NB)] = True   # keep every row/col alive
    return lay.astype(np.int64)


def _dense_mask(lay):
    return np.kron(lay, np.ones((BLK, BLK))) > 0


def _to_blocks(dense, lay):
    """Dense [., M, N] -> block-COO values in MatMul's row-major order."""
    r, c = np.nonzero(lay)
    order = np.lexsort((c, r))
    r, c = r[order], c[order]
    return np.stack([dense[..., i * BLK:(i + 1) * BLK,
                           j * BLK:(j + 1) * BLK]
                     for i, j in zip(r, c)], axis=-3)


def test_sdd_matches_dense():
    rng = np.random.default_rng(1)
    lay = _layout()
    a = rng.normal(size=(2, M, K)).astype(np.float32)
    b = rng.normal(size=(2, K, N)).astype(np.float32)
    vals = MatMul(lay, BLK, "sdd")(jnp.asarray(a), jnp.asarray(b))
    ref = _to_blocks(np.moveaxis(
        np.einsum("bmk,bkn->bmn", a, b), 0, 0), lay)
    np.testing.assert_allclose(np.asarray(vals), ref, rtol=2e-5, atol=2e-4)


def test_sdd_transpose_flags():
    rng = np.random.default_rng(2)
    lay = _layout(2)
    a = rng.normal(size=(K, M)).astype(np.float32)   # pre-transposed
    b = rng.normal(size=(N, K)).astype(np.float32)
    vals = MatMul(lay, BLK, "sdd", trans_a=True, trans_b=True)(
        jnp.asarray(a), jnp.asarray(b))
    ref = _to_blocks(a.T @ b.T, lay)
    np.testing.assert_allclose(np.asarray(vals), ref, rtol=2e-5, atol=2e-4)


def test_dsd_matches_dense():
    rng = np.random.default_rng(3)
    lay = _layout(3)
    a_dense = rng.normal(size=(2, M, K)).astype(np.float32) * \
        _dense_mask(lay)
    b = rng.normal(size=(2, K, N)).astype(np.float32)
    vals = jnp.asarray(_to_blocks(a_dense, lay))
    out = MatMul(lay, BLK, "dsd")(vals, jnp.asarray(b))
    ref = np.einsum("bmk,bkn->bmn", a_dense, b)
    np.testing.assert_allclose(np.asarray(out), ref, rtol=2e-5, atol=2e-4)


def test_dds_matches_dense():
    rng = np.random.default_rng(4)
    lay = _layout(4)
    a = rng.normal(size=(2, M, K)).astype(np.float32)
    b_dense = rng.normal(size=(2, K, N)).astype(np.float32) * \
        _dense_mask(lay)
    vals = jnp.asarray(_to_blocks(b_dense, lay))
    out = MatMul(lay, BLK, "dds")(jnp.asarray(a), vals)
    ref = np.einsum("bmk,bkn->bmn", a, b_dense)
    np.testing.assert_allclose(np.asarray(out), ref, rtol=2e-5, atol=2e-4)


def test_softmax_matches_dense():
    rng = np.random.default_rng(5)
    lay = _layout(5)
    scores = rng.normal(size=(2, M, N)).astype(np.float32)
    mask = _dense_mask(lay)
    vals = jnp.asarray(_to_blocks(scores, lay))
    out = Softmax(lay, BLK)(vals, scale=0.5)
    dense = np.where(mask, scores * 0.5, -1e30)
    p = np.exp(dense - dense.max(-1, keepdims=True))
    p = p / p.sum(-1, keepdims=True)
    ref = _to_blocks(p, lay)
    np.testing.assert_allclose(np.asarray(out), ref, rtol=2e-5, atol=2e-5)


def test_softmax_key_padding_mask():
    rng = np.random.default_rng(6)
    lay = _layout(6)
    scores = rng.normal(size=(M, N)).astype(np.float32)
    kpm = np.where(rng.random(N) < 0.2, -1e30, 0.0).astype(np.float32)
    vals = jnp.asarray(_to_blocks(scores, lay))
    out = Softmax(lay, BLK)(vals, key_padding_mask=jnp.asarray(kpm))
    dense = np.where(_dense_mask(lay), scores + kpm[None, :], -1e30)
    p = np.exp(dense - dense.max(-1, keepdims=True))
    s = p.sum(-1, keepdims=True)
    p = p / np.where(s == 0, 1.0, s)
    np.testing.assert_allclose(np.asarray(out), _to_blocks(p, lay),
                               rtol=2e-5, atol=2e-5)


def test_attention_composition_matches_fused_kernel():
    """sdd -> softmax -> dsd composed from the standalone ops reproduces
    the fused Pallas attention (the reference composes its attention from
    exactly these three ops, sparse_self_attention.py there)."""
    from deepspeed_tpu.ops.pallas.block_sparse_attention import (
        block_sparse_attention)
    rng = np.random.default_rng(7)
    H, D = 2, 32
    cfg = BigBirdSparsityConfig(num_heads=H, block=BLK)
    layout3 = np.asarray(cfg.make_layout(M))
    lay = layout3[0]
    q, k, v = (jnp.asarray(rng.normal(size=(1, H, M, D)), jnp.float32)
               for _ in range(3))
    sm = 1.0 / np.sqrt(D)
    scores = MatMul(lay, BLK, "sdd", trans_b=True)(q, k)
    probs = Softmax(lay, BLK)(scores, scale=sm)
    out = MatMul(lay, BLK, "dsd")(probs, v)
    ref = block_sparse_attention(q, k, v, layout3, BLK)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=2e-4, atol=2e-4)


def test_matmul_is_differentiable():
    rng = np.random.default_rng(8)
    lay = _layout(8)
    a = jnp.asarray(rng.normal(size=(M, K)), jnp.float32)
    b = jnp.asarray(rng.normal(size=(K, N)), jnp.float32)
    mm = MatMul(lay, BLK, "sdd")

    def f(a, b):
        return jnp.sum(mm(a, b) ** 2)

    ga, gb = jax.grad(f, argnums=(0, 1))(a, b)
    # dense reference gradient of sum((A@B * mask)^2)
    mask = _dense_mask(lay)
    c = np.asarray(a) @ np.asarray(b) * mask
    ga_ref = 2 * c @ np.asarray(b).T
    gb_ref = 2 * np.asarray(a).T @ c
    np.testing.assert_allclose(np.asarray(ga), ga_ref, rtol=2e-4, atol=2e-3)
    np.testing.assert_allclose(np.asarray(gb), gb_ref, rtol=2e-4, atol=2e-3)


def test_rejects_per_head_layout_and_bad_mode():
    per_head = np.stack([_layout(1), _layout(9)])
    with pytest.raises(ValueError, match="vmap"):
        MatMul(per_head, BLK, "sdd")
    with pytest.raises(ValueError, match="mode"):
        MatMul(_layout(), BLK, "ssd")


def test_softmax_batched_multihead_key_padding_mask():
    """[B, N] masks must hit the batch axis, not the head axis (reviewed
    bug: right-aligned broadcasting silently lined B up with H)."""
    rng = np.random.default_rng(10)
    B, H = 2, 3
    lay = _layout(10)
    scores = rng.normal(size=(B, H, M, N)).astype(np.float32)
    kpm = np.where(rng.random((B, N)) < 0.2, -1e30, 0.0).astype(np.float32)
    vals = jnp.asarray(np.stack([
        np.stack([_to_blocks(scores[b, h], lay) for h in range(H)])
        for b in range(B)]))
    out = Softmax(lay, BLK)(vals, key_padding_mask=jnp.asarray(kpm))
    dense = np.where(_dense_mask(lay)[None, None], scores
                     + kpm[:, None, None, :], -1e30)
    p = np.exp(dense - dense.max(-1, keepdims=True))
    s = p.sum(-1, keepdims=True)
    p = p / np.where(s == 0, 1.0, s)
    ref = np.stack([np.stack([_to_blocks(p[b, h], lay) for h in range(H)])
                    for b in range(B)])
    np.testing.assert_allclose(np.asarray(out), ref, rtol=2e-5, atol=2e-5)


def test_softmax_fully_masked_rows_emit_zeros():
    """A sequence whose keys are ALL padded must produce zero attention
    rows, matching the fused kernel's zeros-for-dead-rows semantics —
    not NaN from x - row_max = -inf - -inf (ADVICE.md round 5,
    matmul.py:210)."""
    lay = _layout(8)
    rng = np.random.default_rng(8)
    scores = rng.normal(size=(M, N)).astype(np.float32)
    vals = jnp.asarray(_to_blocks(scores, lay))
    kpm = np.full(N, -np.inf, np.float32)          # every key padded
    out = np.asarray(Softmax(lay, BLK)(vals,
                                       key_padding_mask=jnp.asarray(kpm)))
    assert np.isfinite(out).all()
    np.testing.assert_array_equal(out, np.zeros_like(out))
    # and an unpadded call on the same scores still normalizes properly
    live = np.asarray(Softmax(lay, BLK)(vals))
    assert np.isfinite(live).all() and live.max() > 0


def test_softmax_fully_masked_rows_fp16():
    """fp16 is where the NaN actually bit: the -1e30 row-max fill itself
    overflows to -inf, so every fully-masked row subtracted -inf from
    -inf before the dead-row guard."""
    lay = _layout(9)
    rng = np.random.default_rng(9)
    scores = rng.normal(size=(M, N)).astype(np.float32)
    vals = jnp.asarray(_to_blocks(scores, lay), jnp.float16)
    kpm = np.full(N, -np.inf, np.float16)
    out = np.asarray(Softmax(lay, BLK)(vals,
                                       key_padding_mask=jnp.asarray(kpm)))
    assert np.isfinite(out).all()
    assert (out == 0).all()
