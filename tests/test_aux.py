"""Aux subsystem tests: launcher parsing (reference: tests/unit/test_run.py),
timers, CSR tensors (test_csr.py), progressive layer drop (test_pld.py),
activation checkpointing (test_activation_checkpointing.py), env report."""
import time
from functools import partial

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import Mesh, PartitionSpec as P

from deepspeed_tpu.launcher import (build_env, decode_world_info,
                                    encode_world_info, fetch_hostfile,
                                    parse_inclusion_exclusion,
                                    parse_resource_filter)
from deepspeed_tpu.runtime.csr_tensor import (CSRTensor, csr_allgather,
                                              sparse_embedding_grad)
from deepspeed_tpu.runtime.progressive_layer_drop import ProgressiveLayerDrop
from deepspeed_tpu.runtime.activation_checkpointing import checkpointing as ac
from deepspeed_tpu.utils.timer import (SynchronizedWallClockTimer,
                                       ThroughputTimer)

shard_map = partial(jax.shard_map, check_vma=False)


# ---------------------------------------------------------------------------
# launcher (mirrors tests/unit/test_run.py)
# ---------------------------------------------------------------------------
def _pool():
    return {"worker-0": [0, 1, 2, 3], "worker-1": [0, 1, 2, 3]}


def test_hostfile_parse(tmp_path):
    hf = tmp_path / "hostfile"
    hf.write_text("# chips per host\nworker-0 slots=4\nworker-1 slots=4\n")
    pool = fetch_hostfile(str(hf))
    assert pool == {"worker-0": 4, "worker-1": 4}


def test_hostfile_duplicate_raises(tmp_path):
    hf = tmp_path / "hostfile"
    hf.write_text("w0 slots=4\nw0 slots=2\n")
    with pytest.raises(ValueError, match="already defined"):
        fetch_hostfile(str(hf))


def test_hostfile_bad_format(tmp_path):
    hf = tmp_path / "hostfile"
    hf.write_text("w0 gpus=4\n")
    with pytest.raises(ValueError, match="slots=N"):
        fetch_hostfile(str(hf))


def test_hostfile_missing_returns_none(tmp_path):
    assert fetch_hostfile(str(tmp_path / "nope")) is None


def test_include_filter():
    out = parse_resource_filter(_pool(), include_str="worker-0@worker-1:0,2")
    assert out == {"worker-0": [0, 1, 2, 3], "worker-1": [0, 2]}


def test_exclude_filter():
    out = parse_resource_filter(_pool(), exclude_str="worker-1:0")
    assert out == {"worker-0": [0, 1, 2, 3], "worker-1": [1, 2, 3]}


def test_exclude_whole_node():
    out = parse_resource_filter(_pool(), exclude_str="worker-0")
    assert out == {"worker-1": [0, 1, 2, 3]}


def test_include_exclude_mutually_exclusive():
    with pytest.raises(ValueError, match="mutually exclusive"):
        parse_resource_filter(_pool(), "worker-0", "worker-1")


def test_filter_unknown_host():
    with pytest.raises(ValueError, match="not found"):
        parse_resource_filter(_pool(), include_str="worker-9")


def test_filter_unknown_slot():
    with pytest.raises(ValueError, match="No slot"):
        parse_resource_filter(_pool(), include_str="worker-0:7")


def test_filter_preserves_hostfile_order():
    out = parse_resource_filter(_pool(), include_str="worker-1@worker-0")
    assert list(out.keys()) == ["worker-0", "worker-1"]


def test_world_info_roundtrip_and_env():
    active = parse_inclusion_exclusion({"a": 4, "b": 4}, "", "b:1,3")
    enc = encode_world_info(active)
    dec = decode_world_info(enc)
    assert dec == {"a": [0, 1, 2, 3], "b": [0, 2]}
    env = build_env(dec, node_rank=1, master_addr="a", master_port=1234,
                    base_env={})
    assert env["JAX_COORDINATOR_ADDRESS"] == "a:1234"
    assert env["JAX_NUM_PROCESSES"] == "2"
    assert env["JAX_PROCESS_ID"] == "1"
    assert env["TPU_VISIBLE_CHIPS"] == "0,2"
    assert env["TPU_VISIBLE_DEVICES"] == "0,2"
    assert env["RANK"] == "1" and env["WORLD_SIZE"] == "2"


def test_build_env_bad_rank():
    with pytest.raises(ValueError, match="out of range"):
        build_env({"a": [0]}, node_rank=3, master_addr="a",
                  master_port=1, base_env={})


# ---------------------------------------------------------------------------
# timers
# ---------------------------------------------------------------------------
def test_wallclock_timer_accumulates():
    timers = SynchronizedWallClockTimer()
    t = timers("phase")
    t.start()
    time.sleep(0.02)
    t.stop()
    t.start()
    time.sleep(0.02)
    t.stop()
    elapsed = t.elapsed(reset=True)
    assert 0.03 < elapsed < 0.5
    assert t.elapsed(reset=False) == 0.0  # reset cleared it
    timers.log(["phase"])  # must not raise


def test_throughput_timer_warmup_skip():
    tt = ThroughputTimer(batch_size=32, start_step=2, steps_per_output=1000)
    for _ in range(5):
        tt.start()
        time.sleep(0.005)
        tt.stop()
    # first start_step-1 steps excluded from the average
    assert tt.total_step_count == 5
    sps = tt.avg_samples_per_sec()
    assert 0 < sps < 32 / 0.004


# ---------------------------------------------------------------------------
# CSR tensors (mirrors tests/unit/test_csr.py)
# ---------------------------------------------------------------------------
def test_csr_roundtrip():
    dense = np.zeros((10, 4), np.float32)
    dense[2] = 1.5
    dense[7] = -2.0
    csr = CSRTensor.from_dense(jnp.asarray(dense), max_nnz=4)
    np.testing.assert_allclose(np.asarray(csr.to_dense()), dense)
    assert csr.sparse_size() < dense.size + 10


def test_csr_duplicate_indices_sum():
    csr = CSRTensor(jnp.asarray([1, 1, 3]),
                    jnp.asarray([[1.0], [2.0], [4.0]]), (5, 1))
    dense = np.asarray(csr.to_dense())
    assert dense[1, 0] == 3.0 and dense[3, 0] == 4.0


def test_sparse_embedding_grad_matches_dense():
    V, D = 50, 8
    tokens = jnp.asarray([[1, 4, 4], [9, 1, 30]], jnp.int32)
    emb = jnp.asarray(np.random.default_rng(0).standard_normal((V, D)),
                      jnp.float32)

    def loss(table):
        return jnp.sum(table[tokens] ** 2)

    dense_grad = jax.grad(loss)(emb)
    csr = sparse_embedding_grad(dense_grad, tokens)
    assert csr.nnz == 6  # one entry per token
    # duplicated tokens (two 4s, two 1s) must NOT double on densify
    np.testing.assert_allclose(np.asarray(csr.to_dense()),
                               np.asarray(dense_grad), rtol=1e-6,
                               atol=1e-6)


def test_csr_allgather_over_mesh():
    mesh = Mesh(np.array(jax.devices()[:8]), ("data",))
    V, D = 16, 4
    rng = np.random.default_rng(1)
    idx = rng.integers(0, V, (8, 2)).astype(np.int32)
    vals = rng.standard_normal((8, 2, D)).astype(np.float32)

    def combine(i, v):
        local = CSRTensor(i[0], v[0], (V, D))
        return csr_allgather(local, "data").to_dense()

    fn = shard_map(combine, mesh=mesh, in_specs=(P("data"), P("data")),
                   out_specs=P())
    out = np.asarray(jax.jit(fn)(idx, vals))
    ref = np.zeros((V, D), np.float32)
    for s in range(8):
        for j in range(2):
            ref[idx[s, j]] += vals[s, j]
    np.testing.assert_allclose(out, ref, rtol=1e-6, atol=1e-6)


# ---------------------------------------------------------------------------
# progressive layer drop (mirrors tests/unit/test_pld.py)
# ---------------------------------------------------------------------------
def test_pld_schedule():
    pld = ProgressiveLayerDrop(theta=0.5, gamma=0.001)
    assert pld.get_theta() == 1.0
    expected = []
    for step in [0, 100, 1000, 10000]:
        pld.update_state(step)
        theta = pld.get_theta()
        expected.append(theta)
        assert 0.5 <= theta <= 1.0
        np.testing.assert_allclose(
            theta, 0.5 * np.exp(-0.001 * step) + 0.5, rtol=1e-9)
    assert expected == sorted(expected, reverse=True)  # monotone decay
    assert pld.get_state()["progressive_layer_drop"] is True


# ---------------------------------------------------------------------------
# activation checkpointing
# ---------------------------------------------------------------------------
def test_checkpoint_preserves_values_and_grads():
    ac.reset()
    ac.configure(deepspeed_config={"activation_checkpointing": {
        "partition_activations": True}})
    assert ac.is_configured()

    def block(x, w):
        return jnp.tanh(x @ w)

    x = jnp.asarray(np.random.default_rng(2).standard_normal((4, 8)),
                    jnp.float32)
    w = jnp.asarray(np.random.default_rng(3).standard_normal((8, 8)),
                    jnp.float32)
    out_ck = ac.checkpoint(block, x, w)
    np.testing.assert_allclose(np.asarray(out_ck),
                               np.asarray(block(x, w)), rtol=1e-6)
    g_ck = jax.grad(lambda w: jnp.sum(ac.checkpoint(block, x, w) ** 2))(w)
    g = jax.grad(lambda w: jnp.sum(block(x, w) ** 2))(w)
    np.testing.assert_allclose(np.asarray(g_ck), np.asarray(g), rtol=1e-6)
    ac.reset()
    assert not ac.is_configured()


def test_cpu_checkpointing_selects_offload_policy():
    """``cpu_checkpointing`` must wire the HOST-OFFLOAD remat policy on
    this jax (reference moves saved activations to CPU,
    checkpointing.py:382-408 there) — not silently fall back to full
    remat.  The policy is asserted behaviorally: for a no-batch-dim dot
    it must answer Offloadable(device -> pinned_host).  (The on-TPU HLO
    check — residuals annotated into host memory space — lives in
    diag_hostperf.py's remat_offload probe; CPU lowering erases memory
    kinds, so it cannot be asserted here.)"""
    ac.reset()
    ac.configure(deepspeed_config={"activation_checkpointing": {
        "cpu_checkpointing": True}})
    assert ac._policy is not None, (
        "cpu_checkpointing fell back to full remat on a jax that "
        "provides the offload policy")

    def f(w, x):
        return x @ w

    jaxpr = jax.make_jaxpr(f)(jnp.ones((8, 8)), jnp.ones((4, 8)))
    eqn = jaxpr.jaxpr.eqns[0]
    verdict = ac._policy(eqn.primitive,
                         *[v.aval for v in eqn.invars], **eqn.params)
    assert type(verdict).__name__ == "Offloadable", verdict
    assert verdict.src == "device" and verdict.dst == "pinned_host", verdict

    # and grads through the offload policy match the plain function
    def block(x, w):
        for _ in range(3):
            x = jnp.tanh(x @ w)
        return x

    x = jnp.asarray(np.random.default_rng(4).standard_normal((4, 8)),
                    jnp.float32)
    w = jnp.asarray(np.random.default_rng(5).standard_normal((8, 8)),
                    jnp.float32)
    g_off = jax.grad(lambda w: jnp.sum(ac.checkpoint(block, x, w) ** 2))(w)
    g = jax.grad(lambda w: jnp.sum(block(x, w) ** 2))(w)
    np.testing.assert_allclose(np.asarray(g_off), np.asarray(g),
                               rtol=1e-5, atol=1e-6)
    ac.reset()


def test_rng_tracker_fork_advances():
    tracker = ac.RNGStatesTracker()
    tracker.add("mp", 17)
    k1 = tracker.fork("mp")
    k2 = tracker.fork("mp")
    assert not np.array_equal(np.asarray(k1), np.asarray(k2))
    with pytest.raises(Exception, match="already exists"):
        tracker.add("mp", 1)
    with pytest.raises(Exception, match="not added"):
        tracker.fork("nope")


def test_model_parallel_seed_ranks_differ():
    s0 = ac.model_parallel_cuda_manual_seed(1234, tp_rank=0)
    s1 = ac.model_parallel_cuda_manual_seed(1234, tp_rank=1)
    assert s0 != s1


# ---------------------------------------------------------------------------
# env report
# ---------------------------------------------------------------------------
def test_env_report_collects():
    from deepspeed_tpu.env_report import collect_report
    lines = dict(collect_report())
    assert lines["jax"] != "NOT INSTALLED"
    assert "cpu_ops" in lines["native host ops"]
    assert "deepspeed_tpu" in lines


# ---------------------------------------------------------------------------
# engine integration of PLD / tensorboard / wall-clock breakdown
# ---------------------------------------------------------------------------
@pytest.mark.slow
def test_engine_pld_tensorboard_timers(tmp_path):
    import sys
    sys.path.insert(0, "tests")
    from simple_model import base_config, random_batches
    from deepspeed_tpu.config import DeepSpeedConfig
    from deepspeed_tpu.runtime.engine import DeepSpeedEngine
    from deepspeed_tpu.runtime.module import TrainModule

    class PLDModel(TrainModule):
        """Consumes the engine-injected pld_theta batch leaf (the analogue
        of the reference's PLD_SimpleModel, tests/unit/simple_model.py:104)."""

        def init(self, rng):
            return {"w": jax.random.normal(rng, (16, 16)) * 0.1}

        def loss_fn(self, params, batch, rng, train=True):
            x, y = batch["x"], batch["y"]
            theta = batch.get("pld_theta")
            h = x @ params["w"].astype(x.dtype)
            if theta is not None:
                h = h * theta[:, None].astype(h.dtype)
            return jnp.mean((h.astype(jnp.float32) - y) ** 2)

    cfg_dict = base_config(micro_bs=4, grad_acc=1)
    cfg_dict["progressive_layer_drop"] = {"enabled": True, "theta": 0.5,
                                          "gamma": 0.01}
    cfg_dict["tensorboard"] = {"enabled": True,
                               "output_path": str(tmp_path),
                               "job_name": "job"}
    cfg_dict["wall_clock_breakdown"] = True
    cfg = DeepSpeedConfig(cfg_dict, world_size=8)
    engine = DeepSpeedEngine(PLDModel(), cfg)
    assert engine.progressive_layer_drop is not None
    assert engine.timers is not None
    for b in random_batches(32, 16, num_batches=3):
        loss = engine.train_batch({"x": b[0], "y": b[1]})
    assert np.isfinite(float(loss))
    # theta decayed from 1.0
    assert engine.progressive_layer_drop.get_theta() < 1.0
    engine.summary_writer.flush()
    logdir = tmp_path / "job"
    assert any(logdir.iterdir()), "no tensorboard/jsonl events written"
    # breakdown timers recorded both phases
    assert "train_batch_step" in engine.timers.timers


@pytest.mark.slow
def test_bert_consumes_pld_theta():
    """The SHIPPED BERT model consumes the engine-injected pld_theta
    (round-1 verdict: only a test model did).  θ=1 keeps every layer
    (identical to no-PLD); θ<1 changes the traced output in train mode
    and leaves eval untouched."""
    from deepspeed_tpu.models import BertConfig, BertModel

    cfg = BertConfig(vocab_size=64, hidden_size=32, num_hidden_layers=4,
                     num_attention_heads=2, intermediate_size=64,
                     max_position_embeddings=32, hidden_dropout_prob=0.0,
                     attention_probs_dropout_prob=0.0, remat=None)
    model = BertModel(cfg)
    params = model.init(jax.random.PRNGKey(0))
    ids = np.arange(16, dtype=np.int32).reshape(2, 8) % 64
    rng = jax.random.PRNGKey(1)

    base = {"input_ids": ids,
            "masked_lm_labels": np.where(ids % 3 == 0, ids, -100)}
    l_plain = float(model.loss_fn(params, base, rng, train=True))
    l_theta1 = float(model.loss_fn(
        params, {**base, "pld_theta": np.ones((2,), np.float32)},
        rng, train=True))
    assert l_plain == pytest.approx(l_theta1, abs=1e-6)
    # θ=0 drops deep layers with high probability — output must differ
    diffs = []
    for s in range(8):
        l_drop = float(model.loss_fn(
            params, {**base, "pld_theta": np.zeros((2,), np.float32)},
            jax.random.PRNGKey(s), train=True))
        diffs.append(abs(l_drop - l_plain))
    assert max(diffs) > 1e-6, diffs
    # eval ignores theta entirely
    e_plain = float(model.loss_fn(params, base, rng, train=False))
    e_theta = float(model.loss_fn(
        params, {**base, "pld_theta": np.zeros((2,), np.float32)},
        rng, train=False))
    assert e_plain == pytest.approx(e_theta, abs=1e-7)


@pytest.mark.slow
def test_bert_pld_via_engine():
    """End-to-end: engine-driven PLD on the shipped BERT (the reference
    wires PLD through its BERT example the same way, engine.py:787-788)."""
    from deepspeed_tpu.models import BertConfig, BertModel
    from deepspeed_tpu.config import DeepSpeedConfig
    from deepspeed_tpu.runtime.engine import DeepSpeedEngine

    cfg_m = BertConfig(vocab_size=64, hidden_size=32, num_hidden_layers=2,
                       num_attention_heads=2, intermediate_size=64,
                       max_position_embeddings=32, hidden_dropout_prob=0.0,
                       attention_probs_dropout_prob=0.0, remat=None)
    cfg = DeepSpeedConfig({
        "train_micro_batch_size_per_gpu": 1,
        "gradient_accumulation_steps": 1,
        "steps_per_print": 10 ** 9,
        "bf16": {"enabled": True},
        "progressive_layer_drop": {"enabled": True, "theta": 0.5,
                                   "gamma": 0.1},
        "optimizer": {"type": "Adam", "params": {"lr": 1e-3}},
        "zero_optimization": {"stage": 0},
    }, world_size=8)
    engine = DeepSpeedEngine(BertModel(cfg_m), cfg)
    ids = np.arange(64, dtype=np.int32).reshape(8, 8) % 64
    batch = {"input_ids": ids,
             "masked_lm_labels": np.where(ids % 3 == 0, ids, -100)}
    for _ in range(3):
        loss = engine.train_batch(dict(batch))
    assert np.isfinite(float(np.asarray(loss)))
    assert engine.progressive_layer_drop.get_theta() < 1.0


def test_profiler_trace_window(tmp_path):
    """The profiler config block captures an xplane trace over the step
    window (TPU-native tracer slot, SURVEY §5.1)."""
    from simple_model import SimpleModel, base_config, random_batches
    from deepspeed_tpu.config import DeepSpeedConfig
    from deepspeed_tpu.parallel import build_mesh
    from deepspeed_tpu.runtime.engine import DeepSpeedEngine

    out = str(tmp_path / "trace")
    cfg = DeepSpeedConfig(
        base_config(micro_bs=4, stage=0,
                    profiler={"enabled": True, "start_step": 1,
                              "num_steps": 2, "output_path": out}),
        world_size=8)
    eng = DeepSpeedEngine(SimpleModel(hidden_dim=8), cfg, mesh=build_mesh())
    for b in random_batches(32, 8, num_batches=5):
        eng.train_batch(b)
    assert not eng._profiler_active  # window closed by step 3
    import glob
    traces = glob.glob(out + "/**/*.xplane.pb", recursive=True)
    assert traces, f"no xplane trace under {out}"


def test_profiler_stop_escape_hatch(tmp_path):
    from simple_model import SimpleModel, base_config, random_batches
    from deepspeed_tpu.config import DeepSpeedConfig
    from deepspeed_tpu.parallel import build_mesh
    from deepspeed_tpu.runtime.engine import DeepSpeedEngine

    out = str(tmp_path / "trace2")
    cfg = DeepSpeedConfig(
        base_config(micro_bs=4, stage=0,
                    profiler={"enabled": True, "start_step": 0,
                              "num_steps": 100, "output_path": out}),
        world_size=8)
    eng = DeepSpeedEngine(SimpleModel(hidden_dim=8), cfg, mesh=build_mesh())
    eng.train_batch(next(random_batches(32, 8)))
    assert eng._profiler_active
    eng.stop_profiler()
    assert not eng._profiler_active
    eng.stop_profiler()  # idempotent


def test_multinode_runner_command_construction(tmp_path, monkeypatch):
    """pdsh/ssh fan-out builds one per-host command with distinct
    node_rank and the env-export prefix (reference: runner.py:320-356,
    multinode_runner.py:35-75 — their CI also only checks construction)."""
    from deepspeed_tpu.launcher import runner as R

    hf = tmp_path / "hostfile"
    hf.write_text("hostA slots=4\nhostB slots=4\n")

    spawned = []

    class FakeProc:
        def __init__(self, argv):
            spawned.append(argv)

        def wait(self):
            return 0

    monkeypatch.setattr(R.subprocess, "Popen",
                        lambda argv: FakeProc(argv))
    # shutil.which lives in multinode_runner since the runner refactor;
    # ssh must look present (SSHRunner.backend_exists gates the launch)
    from deepspeed_tpu.launcher import multinode_runner as MR
    monkeypatch.setattr(
        MR.shutil, "which",
        lambda name: "/usr/bin/ssh" if name == "ssh" else None)
    monkeypatch.setenv("XLA_FLAGS", "--some_flag=1")
    rc = R.main(["--hostfile", str(hf), "--launcher", "ssh",
                 "--master_port", "29401", "train.py", "--foo", "1"])
    assert rc == 0
    assert len(spawned) == 2
    for rank, argv in enumerate(spawned):
        assert argv[0] == "ssh"
        host, remote = argv[1], argv[2]
        assert host == ("hostA", "hostB")[rank]
        assert f"--node_rank={rank}" in remote
        assert "--master_addr=hostA" in remote
        assert "--master_port=29401" in remote
        assert "deepspeed_tpu.launcher.launch" in remote
        assert "XLA_FLAGS=" in remote          # env export propagated
        assert remote.rstrip().endswith("train.py --foo 1")


def test_partitioned_tensor_roundtrip():
    """PartitionedTensor meta/slice/full over a mesh axis (reference
    runtime/utils.py:379-482 — pipe TP activation shipping)."""
    from deepspeed_tpu.runtime.utils import PartitionedTensor

    mesh = Mesh(np.array(jax.devices()[:8]), ("data",))
    x = np.arange(3 * 7, dtype=np.float32).reshape(3, 7)  # numel=21, odd

    def body(xin):
        pt = PartitionedTensor(xin, "data")
        meta = pt.to_meta()  # concrete numpy even under jit
        assert isinstance(meta, np.ndarray) and meta.dtype == np.int32
        assert meta[0] == 2 and tuple(meta[1:3]) == (3, 7)
        assert meta[3] == 8  # num_parts
        # reconstruct on the "receiver" from the shipped meta + slice
        rt = PartitionedTensor.from_meta(meta, pt.local_data, "data")
        return rt.full()

    fn = shard_map(body, mesh=mesh, in_specs=P(), out_specs=P())
    out = np.asarray(jax.jit(fn)(jnp.asarray(x)))
    np.testing.assert_allclose(out, x)

    # size-mismatch validation: meta from an 8-part layout must be
    # rejected on a different-width axis
    mesh4 = Mesh(np.array(jax.devices()[:4]), ("data",))

    def bad(xin):
        pt = PartitionedTensor(xin, "data")
        wrong = pt.to_meta().copy()
        wrong[3] = 8  # claim 8 parts on a 4-wide axis
        PartitionedTensor.from_meta(wrong, pt.local_data, "data")
        return pt.full()

    with pytest.raises(ValueError, match="8 parts"):
        jax.jit(shard_map(bad, mesh=mesh4, in_specs=P(),
                          out_specs=P()))(jnp.asarray(x))


def test_env_report_device_probe_deadline(monkeypatch):
    """A wedged remote runtime must yield an UNREACHABLE line within the
    deadline, not hang the report (observed: ds_report blocked forever
    on a wedged tunnel).  Deterministic: the probe's subprocess.run is
    stubbed to time out."""
    import subprocess

    def fake_run(*a, **kw):
        raise subprocess.TimeoutExpired(cmd=a[0], timeout=kw["timeout"])

    monkeypatch.setattr(subprocess, "run", fake_run)
    from deepspeed_tpu.env_report import _device_line
    key, val = _device_line()
    assert key == "devices"
    assert "UNREACHABLE" in val
    # a malformed deadline knob degrades instead of crashing the report
    monkeypatch.setenv("DS_REPORT_DEVICE_TIMEOUT", "45s")
    key, val = _device_line()
    assert "UNREACHABLE" in val
