"""Elastic training (ISSUE 6): sample-exact data-iterator resume (loader
/ RepeatingLoader / DevicePrefetcher state, the checkpoint data plane +
its CRC/torture coverage), the restart supervisor (bounded restarts,
exponential backoff, typed give-up, host re-probe/world shrink,
heartbeat liveness), straggler detection, the launcher filter
satellites, and the end-to-end ``ds --elastic`` kill/resume +
dp4→dp2 trajectory-equivalence runs."""
import collections
import json
import os
import subprocess
import sys
import time

import numpy as np
import jax
import pytest

from deepspeed_tpu.config import DeepSpeedConfig
from deepspeed_tpu.launcher.elastic import (ElasticGiveUpError,
                                            ElasticSupervisor,
                                            RestartPolicy)
from deepspeed_tpu.parallel import build_mesh
from deepspeed_tpu.runtime.dataloader import (DeepSpeedDataLoader,
                                              RepeatingLoader)
from deepspeed_tpu.runtime.engine import DeepSpeedEngine
from deepspeed_tpu.runtime.prefetch import DevicePrefetcher
from deepspeed_tpu.runtime.resilience import (CheckpointCorruptError,
                                              reset_fault_injection)
from deepspeed_tpu.telemetry.heartbeat import (HeartbeatWriter,
                                               StragglerMonitor,
                                               read_heartbeats)

from simple_model import SimpleModel, base_config

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
HIDDEN = 8


@pytest.fixture(autouse=True)
def _clean_faults(monkeypatch):
    monkeypatch.delenv("DS_CKPT_FAULT", raising=False)
    monkeypatch.delenv("DS_HEARTBEAT_DIR", raising=False)
    reset_fault_injection()
    yield
    reset_fault_injection()


# ---------------------------------------------------------------------------
# iterator state: loader / RepeatingLoader / prefetcher
# ---------------------------------------------------------------------------
def _indexed_dataset(n=16):
    """Sample i is [i, noise...]: feature 0 is the identity channel."""
    rng = np.random.default_rng(0)
    xs = rng.standard_normal((n, 4)).astype(np.float32)
    xs[:, 0] = np.arange(n)
    return [xs[i] for i in range(n)]


def _mk_rep(seed=3):
    return RepeatingLoader(DeepSpeedDataLoader(
        _indexed_dataset(), batch_size=4, shuffle=True, seed=seed))


def _ids(batch):
    return [int(v) for v in np.asarray(batch)[:, 0]]


def test_loader_state_resumes_exactly_at_any_point():
    """Interrupt after k batches for every k across 2.5 epochs: the
    restored loader continues with the identical remaining sequence
    (same epoch permutation re-derived from the epoch-start RNG state,
    consumed batches skipped, later epochs reshuffled identically)."""
    rep = _mk_rep()
    ref = [_ids(next(rep)) for _ in range(10)]
    for k in range(10):
        r1 = _mk_rep()
        got = [_ids(next(r1)) for _ in range(k)]
        # the plane round-trips through JSON — state must survive it
        state = json.loads(json.dumps(r1.state_dict()))
        r2 = _mk_rep()
        r2.load_state_dict(state)
        got += [_ids(next(r2)) for _ in range(10 - k)]
        assert got == ref, f"diverged when interrupted at batch {k}"


def test_loader_fresh_state_roundtrip():
    """A never-iterated loader's state restores to a fresh start."""
    l1 = DeepSpeedDataLoader(_indexed_dataset(), batch_size=4,
                             shuffle=True, seed=7)
    l2 = DeepSpeedDataLoader(_indexed_dataset(), batch_size=4,
                             shuffle=True, seed=7)
    l2.load_state_dict(l1.state_dict())
    assert [_ids(b) for b in l2] == [
        _ids(b) for b in DeepSpeedDataLoader(
            _indexed_dataset(), batch_size=4, shuffle=True, seed=7)]


def test_repeating_loader_state_requires_checkpointable_inner():
    rep = RepeatingLoader(iter([1, 2, 3]))
    with pytest.raises(TypeError, match="checkpointable"):
        rep.state_dict()


def test_prefetcher_accounts_inflight_batches_as_unconsumed():
    """Consume 3 with depth-2 prefetch (the worker has produced ahead);
    the captured state must resume at batch 3 — produced-but-unconsumed
    batches re-produce, no skip."""
    rep = _mk_rep()
    ref = [_ids(next(rep)) for _ in range(10)]

    pf = DevicePrefetcher(_mk_rep(), depth=2)
    got = [_ids(next(pf)) for _ in range(3)]
    deadline = time.time() + 5.0
    while pf.qsize() == 0 and time.time() < deadline:
        time.sleep(0.01)  # let the worker stage ahead
    assert pf.qsize() > 0, "worker never prefetched ahead"
    state = json.loads(json.dumps(pf.state_dict()))
    pf.close()

    l2 = _mk_rep()
    l2.load_state_dict(state)
    pf2 = DevicePrefetcher(l2, depth=2)
    got += [_ids(next(pf2)) for _ in range(7)]
    pf2.close()
    assert got == ref


def test_prefetcher_stateless_source_raises_typed():
    pf = DevicePrefetcher(iter([np.zeros((2, 2))]), depth=1)
    with pytest.raises(TypeError, match="checkpointable"):
        pf.state_dict()
    pf.close()


# ---------------------------------------------------------------------------
# the checkpoint data-iterator plane
# ---------------------------------------------------------------------------
def _data_engine(seed=0, **over):
    mesh = build_mesh(devices=jax.devices()[:1])
    cfg = DeepSpeedConfig(base_config(micro_bs=4, grad_acc=1, **over),
                          world_size=1)
    rng = np.random.default_rng(0)
    xs = rng.standard_normal((32, HIDDEN)).astype(np.float32)
    ds = [(xs[i], 0.5 * xs[i]) for i in range(32)]
    eng = DeepSpeedEngine(SimpleModel(hidden_dim=HIDDEN), cfg, mesh=mesh,
                          seed=seed, training_data=ds)
    eng.training_dataloader = RepeatingLoader(DeepSpeedDataLoader(
        ds, batch_size=eng.train_batch_size, shuffle=True, seed=5))
    return eng


def test_checkpoint_carries_data_plane_and_resume_is_sample_exact(tmp_path):
    """The checkpoint gains a CRC'd, digest-pinned ``data`` plane; a
    resumed engine continues at the exact next sample — losses match an
    uninterrupted run bitwise (prefetcher ON at depth 2 throughout)."""
    ref = _data_engine()
    ref_losses = [float(ref.train_batch()) for _ in range(8)]
    ref.close()

    e1 = _data_engine()
    l1 = [float(e1.train_batch()) for _ in range(3)]
    e1.save_checkpoint(str(tmp_path), tag="t")
    e1.close()
    meta = json.load(open(tmp_path / "t" / "meta.json"))
    assert "data" in meta["manifest_digests"]
    manifest = json.load(open(tmp_path / "t" / "data" / "manifest.json"))
    (entry,) = manifest.values()
    assert entry.get("crc32") is not None  # same integrity plane

    e2 = _data_engine(seed=9)
    path, _ = e2.load_checkpoint(str(tmp_path), tag="t")
    assert path is not None
    l2 = [float(e2.train_batch()) for _ in range(5)]
    e2.close()
    assert l1 + l2 == ref_losses


def test_data_plane_crc_and_digest_tamper_detected(tmp_path):
    eng = _data_engine()
    _ = [float(eng.train_batch()) for _ in range(2)]
    eng.save_checkpoint(str(tmp_path), tag="t")
    eng.close()
    # flip a payload byte in the data plane's leaf
    manifest = json.load(open(tmp_path / "t" / "data" / "manifest.json"))
    (entry,) = manifest.values()
    fpath = tmp_path / "t" / "data" / entry["file"]
    data = bytearray(open(fpath, "rb").read())
    data[-2] ^= 0xFF
    open(fpath, "wb").write(bytes(data))
    e2 = _data_engine(seed=9)
    with pytest.raises(CheckpointCorruptError):
        e2.load_checkpoint(str(tmp_path), tag="t")
    # restore the byte; tamper the manifest instead -> digest mismatch
    data[-2] ^= 0xFF
    open(fpath, "wb").write(bytes(data))
    mpath = tmp_path / "t" / "data" / "manifest.json"
    json.dump(manifest, open(mpath, "w"), indent=4)
    with pytest.raises(CheckpointCorruptError, match="digest"):
        e2.load_checkpoint(str(tmp_path), tag="t")
    e2.close()


def test_corrupt_data_plane_walks_fallback_chain_engine_intact(tmp_path):
    """A rotten data plane is corruption like any other: tag=None walks
    back to the previous verified tag (which restores its OWN iterator
    state) instead of crashing or half-restoring."""
    eng = _data_engine()
    ref_losses = [float(eng.train_batch()) for _ in range(2)]
    eng.save_checkpoint(str(tmp_path), tag="t1")
    ref_losses += [float(eng.train_batch())]
    eng.save_checkpoint(str(tmp_path), tag="t2")  # latest -> t2
    ref_losses += [float(eng.train_batch()) for _ in range(2)]
    eng.close()
    manifest = json.load(open(tmp_path / "t2" / "data" / "manifest.json"))
    (entry,) = manifest.values()
    fpath = tmp_path / "t2" / "data" / entry["file"]
    data = bytearray(open(fpath, "rb").read())
    data[-2] ^= 0xFF
    open(fpath, "wb").write(bytes(data))

    e2 = _data_engine(seed=9)
    path, _ = e2.load_checkpoint(str(tmp_path))
    assert path.endswith("t1")
    # resumed from t1's state: replays exactly from step 2's sample
    got = [float(e2.train_batch()) for _ in range(3)]
    e2.close()
    assert got == ref_losses[2:]


@pytest.mark.parametrize("point", ["leaf:1+", "manifest:3+", "meta:1+",
                                   "rename:1+"])
def test_data_plane_survives_torture_matrix(point, tmp_path):
    """Kill-during-save at the write points (manifest:3 is the DATA
    plane's manifest — model and optim wrote theirs first): the resumed
    run restores the last GOOD tag's iterator state and continues with
    the reference sample sequence — never a torn or half-new one."""
    over = {"checkpoint": {"io_retry_attempts": 2,
                           "io_retry_base_s": 0.001}}
    ref = _data_engine(**over)
    ref_losses = [float(ref.train_batch()) for _ in range(6)]
    ref.close()

    eng = _data_engine(**over)
    l1 = [float(eng.train_batch()) for _ in range(2)]
    eng.save_checkpoint(str(tmp_path), tag="good")
    _ = [float(eng.train_batch())]
    os.environ["DS_CKPT_FAULT"] = point
    try:
        with pytest.raises(Exception):
            eng.save_checkpoint(str(tmp_path), tag="doomed")
    finally:
        os.environ.pop("DS_CKPT_FAULT", None)
    reset_fault_injection()
    eng.close()

    e2 = _data_engine(seed=9, **over)
    path, _ = e2.load_checkpoint(str(tmp_path))
    assert path.endswith("good")
    got = l1 + [float(e2.train_batch()) for _ in range(4)]
    e2.close()
    assert got == ref_losses


def test_engine_without_checkpointable_loader_saves_no_data_plane(tmp_path):
    """Batch-fed engines (no training_data) keep the two-plane layout —
    nothing to resume, nothing saved, and their checkpoints load with
    no data-plane warning noise."""
    from simple_model import random_batches
    mesh = build_mesh(devices=jax.devices()[:1])
    cfg = DeepSpeedConfig(base_config(micro_bs=2, grad_acc=1),
                          world_size=1)
    eng = DeepSpeedEngine(SimpleModel(hidden_dim=HIDDEN), cfg, mesh=mesh)
    for b in random_batches(eng.train_batch_size, HIDDEN, num_batches=1):
        eng.train_batch(b)
    eng.save_checkpoint(str(tmp_path), tag="t")
    eng.close()
    meta = json.load(open(tmp_path / "t" / "meta.json"))
    assert "data" not in meta["manifest_digests"]
    assert not (tmp_path / "t" / "data").exists()


# ---------------------------------------------------------------------------
# straggler monitor + heartbeat policy
# ---------------------------------------------------------------------------
def test_straggler_monitor_flags_ratio_over_median():
    m = StragglerMonitor(ratio=2.0, stale_after_s=30.0)
    now = time.time()
    fleet = {f"h{i}/0": {"time": now, "step_s": 1.0} for i in range(4)}
    fleet["slow/0"] = {"time": now, "step_s": 2.5}
    rep = m.update(fleet, now=now)
    assert rep["stragglers"] == ["slow/0"]
    assert rep["median_step_s"] == 1.0
    assert m.flagged_total == 1
    # still slow next interval: the episode is counted ONCE
    m.update(fleet, now=now)
    assert m.flagged_total == 1
    # recovers, then relapses: a new episode counts again
    fleet["slow/0"]["step_s"] = 1.0
    m.update(fleet, now=now)
    fleet["slow/0"]["step_s"] = 9.0
    m.update(fleet, now=now)
    assert m.flagged_total == 2


def test_straggler_monitor_stale_and_small_fleet():
    m = StragglerMonitor(ratio=2.0, stale_after_s=10.0, min_fleet=2)
    now = time.time()
    rep = m.update({"h/0": {"time": now - 60, "step_s": 50.0}}, now=now)
    assert rep["stale"] == ["h/0"]
    assert rep["stragglers"] == []  # a median of one is noise
    with pytest.raises(ValueError, match="> 1.0"):
        StragglerMonitor(ratio=1.0)


def test_heartbeat_writer_and_reader_roundtrip(tmp_path):
    w = HeartbeatWriter(str(tmp_path), process_index=2, host="hostA")
    assert w.beat(5)
    time.sleep(0.01)
    assert w.beat(6)
    beats = read_heartbeats(str(tmp_path))
    assert list(beats) == ["hostA/2"]
    rec = beats["hostA/2"]
    assert rec["step"] == 6 and rec["step_s"] > 0


def test_engine_emits_heartbeats_via_env(tmp_path, monkeypatch):
    """DS_HEARTBEAT_DIR (the supervisor's export) turns on per-step
    beats with no config — the liveness channel the supervisor reads."""
    from simple_model import random_batches
    monkeypatch.setenv("DS_HEARTBEAT_DIR", str(tmp_path))
    mesh = build_mesh(devices=jax.devices()[:1])
    cfg = DeepSpeedConfig(base_config(micro_bs=2, grad_acc=1),
                          world_size=1)
    eng = DeepSpeedEngine(SimpleModel(hidden_dim=HIDDEN), cfg, mesh=mesh)
    for b in random_batches(eng.train_batch_size, HIDDEN, num_batches=3):
        eng.train_batch(b)
    eng.close()
    beats = read_heartbeats(str(tmp_path))
    assert len(beats) == 1
    (rec,) = beats.values()
    assert rec["step"] == 3


def test_straggler_counter_flows_to_summarize(tmp_path, monkeypatch):
    """A straggling host planted in the heartbeat dir surfaces as
    straggler_detected_total at the periodic sync and as the summarize
    stragglers row."""
    from deepspeed_tpu.telemetry.cli import summarize
    from simple_model import random_batches
    hb = tmp_path / "hb"
    hb.mkdir()
    monkeypatch.setenv("DS_HEARTBEAT_DIR", str(hb))
    over = {"steps_per_print": 2,
            "telemetry": {"enabled": True,
                          "output_path": str(tmp_path / "tel"),
                          "compile_events": False, "memory": False}}
    mesh = build_mesh(devices=jax.devices()[:1])
    cfg = DeepSpeedConfig(base_config(micro_bs=2, grad_acc=1, **over),
                          world_size=1)
    eng = DeepSpeedEngine(SimpleModel(hidden_dim=HIDDEN), cfg, mesh=mesh)
    batches = list(random_batches(eng.train_batch_size, HIDDEN,
                                  num_batches=4))
    eng.train_batch(batches[0])
    # plant a healthy twin and a limper (a 2-host fleet can never exceed
    # 2x its own median — the median IS the midpoint of the pair)
    json.dump({"host": "healthy", "process_index": 2, "step": 1,
               "time": time.time(), "step_s": 0.001},
              open(hb / "heartbeat_2.json", "w"))
    json.dump({"host": "limper", "process_index": 1, "step": 1,
               "time": time.time(), "step_s": 99.0},
              open(hb / "heartbeat_1.json", "w"))
    for b in batches[1:]:
        eng.train_batch(b)
    assert eng.telemetry.registry.counter(
        "straggler_detected_total", "").value() >= 1
    eng.close()
    report = summarize(str(tmp_path / "tel" / "events.jsonl"),
                       out=open(os.devnull, "w"))
    assert report["straggler_detected_total"] >= 1


# ---------------------------------------------------------------------------
# supervisor semantics (unit tier: stub workers)
# ---------------------------------------------------------------------------
def _proc(code="import sys; sys.exit(0)"):
    return subprocess.Popen([sys.executable, "-c", code])


def test_supervisor_restarts_and_shrinks_world():
    calls = []

    def launch(active, attempt):
        calls.append((dict(active), attempt))
        rc = 3 if attempt == 0 else 0
        return [("a", _proc(f"import sys; sys.exit({3 if attempt == 0 else 0})"))]

    slept = []
    sup = ElasticSupervisor(
        collections.OrderedDict([("a", [0, 1]), ("b", [0, 1])]),
        launch, probe_fn=lambda h: None if h == "b" else True,
        policy=RestartPolicy(max_restarts=2, backoff_base_s=0.5),
        sleep_fn=slept.append)
    assert sup.run() == 0
    assert calls[0][0] == {"a": [0, 1], "b": [0, 1]}
    assert calls[1][0] == {"a": [0, 1]}  # b dropped after its probe
    assert calls[1][1] == 1              # DS_ELASTIC_RESTART advances
    assert slept == [0.5]


def test_supervisor_gives_up_typed_after_budget():
    slept = []
    sup = ElasticSupervisor(
        {"a": [0]}, lambda active, attempt: [("a", _proc(
            "import sys; sys.exit(1)"))],
        policy=RestartPolicy(max_restarts=3, backoff_base_s=1.0,
                             backoff_max_s=3.0),
        sleep_fn=slept.append)
    with pytest.raises(ElasticGiveUpError) as ei:
        sup.run()
    assert ei.value.restarts == 3
    assert "rc=1" in ei.value.last_failure
    assert slept == [1.0, 2.0, 3.0]  # exponential, capped at backoff_max


def test_supervisor_gives_up_below_min_slots():
    sup = ElasticSupervisor(
        {"a": [0], "b": [0]},
        lambda active, attempt: [("a", _proc("import sys; sys.exit(1)"))],
        probe_fn=lambda h: None,  # everything dies
        policy=RestartPolicy(max_restarts=5, min_slots=1,
                             backoff_base_s=0.0),
        sleep_fn=lambda s: None)
    with pytest.raises(ElasticGiveUpError, match="min_slots"):
        sup.run()


def test_supervisor_probe_resize_changes_slots():
    worlds = []

    def launch(active, attempt):
        worlds.append({h: len(s) for h, s in active.items()})
        return [("a", _proc(f"import sys; sys.exit({1 if attempt == 0 else 0})"))]

    sup = ElasticSupervisor(
        {"a": [0, 1, 2, 3]}, launch,
        probe_fn=lambda h: [0, 1],  # host survives with half its chips
        policy=RestartPolicy(max_restarts=1, backoff_base_s=0.0),
        sleep_fn=lambda s: None)
    assert sup.run() == 0
    assert worlds == [{"a": 4}, {"a": 2}]


def test_supervisor_missed_heartbeats_kill_and_restart(tmp_path):
    """A worker that beats once then hangs: the supervisor declares the
    host hung after heartbeat_timeout, kills the attempt, and
    relaunches — a wedged collective must not stall the job forever."""
    hb = tmp_path / "hb"
    hb.mkdir()
    attempts = []

    def launch(active, attempt):
        attempts.append(attempt)
        if attempt == 0:
            code = (
                "import json, time\n"
                f"rec = dict(host='h', process_index=0, step=1, "
                "time=time.time(), step_s=0.1)\n"
                f"json.dump(rec, open(r'{hb}/heartbeat_0.json', 'w'))\n"
                "time.sleep(120)\n")
        else:
            code = "pass"
        return [("h", _proc(code))]

    sup = ElasticSupervisor(
        {"h": [0]}, launch,
        policy=RestartPolicy(max_restarts=1, backoff_base_s=0.0),
        heartbeat_dir=str(hb), heartbeat_timeout_s=0.5,
        poll_interval_s=0.05, term_grace_s=2.0, sleep_fn=lambda s: None)
    t0 = time.time()
    assert sup.run() == 0
    assert attempts == [0, 1]
    assert time.time() - t0 < 60  # killed on staleness, not sleep(120)
    # attempt 0's beat file was swept before attempt 1 launched
    assert read_heartbeats(str(hb)) == {}


# ---------------------------------------------------------------------------
# launcher filter satellites
# ---------------------------------------------------------------------------
def test_filters_unknown_host_and_slot_raise_descriptive():
    from deepspeed_tpu.launcher.runner import parse_resource_filter
    pool = {"nodeA": [0, 1], "nodeB": [0, 1]}
    with pytest.raises(ValueError, match="'ghost'.*hosts: nodeA, nodeB"):
        parse_resource_filter(pool, include_str="ghost")
    with pytest.raises(ValueError, match="--exclude.*'ghost'"):
        parse_resource_filter(pool, exclude_str="ghost")
    with pytest.raises(ValueError, match="'ghost'"):
        parse_resource_filter(pool, include_str="ghost:0")
    with pytest.raises(ValueError, match="slot 7 on host 'nodeA'"):
        parse_resource_filter(pool, include_str="nodeA:7")


def test_filters_malformed_node_spec_raises_descriptive():
    from deepspeed_tpu.launcher.runner import parse_resource_filter
    pool = {"nodeA": [0, 1]}
    with pytest.raises(ValueError, match="empty NODE_SPEC"):
        parse_resource_filter(pool, include_str="nodeA@")
    with pytest.raises(ValueError, match="one colon"):
        parse_resource_filter(pool, include_str="nodeA:0:1")
    with pytest.raises(ValueError, match="comma-separated integers"):
        parse_resource_filter(pool, include_str="nodeA:x")
    # well-formed filters still work, order preserved
    out = parse_resource_filter({"a": [0, 1], "b": [0, 1]},
                                exclude_str="b:1")
    assert out == {"a": [0, 1], "b": [0]}


def test_filters_without_hostfile_raise_instead_of_silently_ignoring(
        tmp_path):
    """--include/--exclude with a missing hostfile used to be silently
    dropped (the single-host exec path ignored them); now it is a
    descriptive error naming the hostfile path."""
    from deepspeed_tpu.launcher.runner import main
    with pytest.raises(ValueError, match="no hostfile exists"):
        main(["--hostfile", str(tmp_path / "nope"), "--include",
              "ghost", "train.py"])
    with pytest.raises(ValueError, match="no hostfile exists"):
        main(["--hostfile", str(tmp_path / "nope"), "--exclude",
              "ghost", "train.py"])


def test_elastic_rejects_mpi_launchers(tmp_path):
    from deepspeed_tpu.launcher.runner import main
    hf = tmp_path / "hostfile"
    hf.write_text("localhost slots=1\n")
    with pytest.raises(ValueError, match="mpirun owns"):
        main(["--hostfile", str(hf), "--launcher", "openmpi",
              "--elastic", "train.py"])


# ---------------------------------------------------------------------------
# end to end: ds --elastic on localhost (the tier-1 kill/resume bar)
# ---------------------------------------------------------------------------
def _worker_env(tmp_path):
    env = dict(os.environ)
    env["PYTHONPATH"] = (REPO + os.pathsep
                         + os.path.join(REPO, "tests") + os.pathsep
                         + env.get("PYTHONPATH", ""))
    env["JAX_PLATFORMS"] = "cpu"
    env["DS_CKPT_FSYNC"] = "0"
    for k in ("DS_ELASTIC_RESTART", "DS_ELASTIC_WORLD_SLOTS",
              "DS_HEARTBEAT_DIR"):
        env.pop(k, None)
    return env


def _worker_direct(tmp_path, out, ckpt, steps, crash_at, slots, env):
    """One un-supervised worker run (the uninterrupted reference legs)."""
    e = dict(env)
    e["DS_ELASTIC_WORLD_SLOTS"] = str(slots)
    r = subprocess.run(
        [sys.executable, os.path.join(REPO, "tests", "elastic_worker.py"),
         str(out), str(ckpt), str(steps), str(crash_at)],
        env=e, timeout=240, capture_output=True, text=True)
    assert r.returncode == 0, r.stderr[-2000:]


def _lines(path):
    return [json.loads(l) for l in open(path)]


def test_ds_elastic_kill_resume_sample_exact(tmp_path):
    """The CPU e2e bar: ``ds --elastic`` launches the worker, the worker
    hard-kills itself mid-run (prefetcher ON, in-flight batches
    abandoned), the supervisor relaunches, and the stitched run is
    sample-exact AND loss-bitwise-identical to an uninterrupted one."""
    env = _worker_env(tmp_path)
    hf = tmp_path / "hostfile"
    hf.write_text("localhost slots=4\n")
    out, ckpt = tmp_path / "out", tmp_path / "ckpt"
    out.mkdir(), ckpt.mkdir()
    r = subprocess.run(
        [sys.executable, os.path.join(REPO, "bin", "ds"),
         "--hostfile", str(hf), "--launcher", "local", "--elastic",
         "--max-restarts", "2", "--backoff-base", "0.1",
         os.path.join(REPO, "tests", "elastic_worker.py"),
         str(out), str(ckpt), "6", "3"],
        env=env, timeout=300, capture_output=True, text=True)
    assert r.returncode == 0, (r.stdout[-1500:], r.stderr[-1500:])

    ref_out, ref_ckpt = tmp_path / "ref", tmp_path / "refck"
    ref_out.mkdir(), ref_ckpt.mkdir()
    _worker_direct(tmp_path, ref_out, ref_ckpt, 6, 0, 4, env)

    # trajectory continuity: the resumed run picks up at step 3 and the
    # stitched loss curve is bitwise the uninterrupted one
    t = _lines(out / "traj_r0.jsonl") + _lines(out / "traj_r1.jsonl")
    ref_t = _lines(ref_out / "traj_r0.jsonl")
    assert [r["step"] for r in t] == list(range(6))
    assert [r["loss"] for r in t] == [r["loss"] for r in ref_t]

    # sample-exactness: 3 consumed before the kill (prefetched extras in
    # the production log are re-produced after resume, never skipped)
    s = (_lines(out / "samples_r0.jsonl")[:3]
         + _lines(out / "samples_r1.jsonl"))
    ref_s = _lines(ref_out / "samples_r0.jsonl")
    assert s[:6] == ref_s[:6]


def test_ds_elastic_resize_matches_dp2_from_start(tmp_path):
    """ROADMAP item 2's trajectory-equivalence bar: dp4 run → kill →
    the probe reports the host shrunk to 2 slots → ``ds --elastic``
    resumes at dp2, and the resumed curve matches a dp2-from-start run
    given the same sample order (fp32; only psum reduction-order noise
    differs)."""
    env = _worker_env(tmp_path)
    hf = tmp_path / "hostfile"
    hf.write_text("localhost slots=4\n")
    probe = tmp_path / "probe.sh"
    probe.write_text("#!/bin/sh\necho slots=2\n")
    probe.chmod(0o755)
    out, ckpt = tmp_path / "out", tmp_path / "ckpt"
    out.mkdir(), ckpt.mkdir()
    r = subprocess.run(
        [sys.executable, os.path.join(REPO, "bin", "ds"),
         "--hostfile", str(hf), "--launcher", "local", "--elastic",
         "--max-restarts", "2", "--backoff-base", "0.1",
         "--probe-cmd", f"{probe} {{host}}",
         os.path.join(REPO, "tests", "elastic_worker.py"),
         str(out), str(ckpt), "6", "3"],
        env=env, timeout=300, capture_output=True, text=True)
    assert r.returncode == 0, (r.stdout[-1500:], r.stderr[-1500:])

    dp2_out, dp2_ckpt = tmp_path / "dp2", tmp_path / "dp2ck"
    dp2_out.mkdir(), dp2_ckpt.mkdir()
    _worker_direct(tmp_path, dp2_out, dp2_ckpt, 6, 0, 2, env)

    t1 = _lines(out / "traj_r1.jsonl")
    assert [r["dp"] for r in t1] == [2, 2, 2]  # resumed at reduced width
    ref = _lines(dp2_out / "traj_r0.jsonl")
    np.testing.assert_allclose(
        [r["loss"] for r in t1], [r["loss"] for r in ref[3:]],
        rtol=1e-5)
    # identical sample order across the resize
    s = (_lines(out / "samples_r0.jsonl")[:3]
         + _lines(out / "samples_r1.jsonl"))
    assert s[:6] == _lines(dp2_out / "samples_r0.jsonl")[:6]


# ---------------------------------------------------------------------------
# review-round regressions
# ---------------------------------------------------------------------------
def test_prefetcher_over_uncheckpointable_repeating_loader_still_runs():
    """A RepeatingLoader over a raw iterable quacks the state protocol
    but can't honor it: the prefetcher must construct and serve batches
    (the pre-ISSUE-6 behavior), with only state_dict() raising typed."""
    batches = [np.full((2, 2), float(i)) for i in range(3)]
    pf = DevicePrefetcher(RepeatingLoader(batches), depth=2)
    got = [float(np.asarray(next(pf))[0, 0]) for _ in range(5)]
    assert got == [0.0, 1.0, 2.0, 0.0, 1.0]
    with pytest.raises(TypeError, match="checkpointable"):
        pf.state_dict()
    pf.close()


def test_save_skips_data_plane_for_uncheckpointable_loader(tmp_path):
    """Engine whose training_dataloader is a RepeatingLoader over a raw
    iterable (prefetch OFF, so the non-prefetch state probe runs):
    save_checkpoint must omit the data plane, not crash."""
    mesh = build_mesh(devices=jax.devices()[:1])
    cfg = DeepSpeedConfig(base_config(
        micro_bs=4, grad_acc=1,
        **{"data_prefetch": {"enabled": False}}), world_size=1)
    eng = DeepSpeedEngine(SimpleModel(hidden_dim=HIDDEN), cfg, mesh=mesh)
    rng = np.random.default_rng(0)
    xs = rng.standard_normal((8, HIDDEN)).astype(np.float32)
    eng.training_dataloader = RepeatingLoader(
        [(xs[:4], 0.5 * xs[:4]), (xs[4:], 0.5 * xs[4:])])
    float(eng.train_batch())
    eng.save_checkpoint(str(tmp_path), tag="t")
    eng.close()
    meta = json.load(open(tmp_path / "t" / "meta.json"))
    assert "data" not in meta["manifest_digests"]
    assert not (tmp_path / "t" / "data").exists()


def test_straggler_monitor_excludes_stale_hosts_from_median():
    """A dead host's frozen last step_s must not skew the fleet median
    or sit in the straggler set forever."""
    m = StragglerMonitor(ratio=2.0, stale_after_s=10.0, min_fleet=2)
    now = time.time()
    fleet = {f"h{i}/0": {"time": now, "step_s": 1.0} for i in range(3)}
    fleet["dead/0"] = {"time": now - 60, "step_s": 99.0}
    rep = m.update(fleet, now=now)
    assert rep["stale"] == ["dead/0"]
    assert rep["stragglers"] == []          # dead, not slow
    assert rep["median_step_s"] == 1.0      # median of the LIVE fleet


def test_supervisor_exit_skew_stale_beats_do_not_kill(tmp_path):
    """Shutdown skew: one worker exits 0 and stops beating while rank 0
    finishes its final checkpoint — the finished worker's stale beat
    must NOT be read as a hang (no kill, no burned restart)."""
    hb = tmp_path / "hb"
    hb.mkdir()
    attempts = []

    def launch(active, attempt):
        attempts.append(attempt)
        # "a" beats once, exits clean almost immediately, and its beat
        # then goes stale (past the 0.3s timeout) while "b" keeps
        # working until 1.2s — the stale-after-clean-exit window
        json.dump({"host": "a", "process_index": 0, "step": 5,
                   "time": time.time(), "step_s": 0.1},
                  open(hb / "heartbeat_0.json", "w"))
        return [("a", _proc("pass")),
                ("b", _proc("import time; time.sleep(1.2)"))]

    sup = ElasticSupervisor(
        collections.OrderedDict([("a", [0]), ("b", [0])]), launch,
        policy=RestartPolicy(max_restarts=2, backoff_base_s=0.0),
        heartbeat_dir=str(hb), heartbeat_timeout_s=0.3,
        poll_interval_s=0.05, sleep_fn=lambda s: None)
    assert sup.run() == 0
    assert attempts == [0]  # completed on the first attempt


def test_supervisor_remote_kill_fn_called_for_live_hosts(tmp_path):
    """ssh-transport remnant cleanup: _kill must invoke remote_kill_fn
    for hosts whose handle was still live (the local ssh client does
    not forward SIGTERM to the remote worker)."""
    cleaned = []

    def launch(active, attempt):
        if attempt == 0:
            return [("a", _proc("import sys; sys.exit(1)")),
                    ("b", _proc("import time; time.sleep(60)"))]
        return [("a", _proc("pass")), ("b", _proc("pass"))]

    sup = ElasticSupervisor(
        collections.OrderedDict([("a", [0]), ("b", [0])]), launch,
        policy=RestartPolicy(max_restarts=1, backoff_base_s=0.0),
        poll_interval_s=0.05, term_grace_s=2.0,
        sleep_fn=lambda s: None, remote_kill_fn=cleaned.append)
    assert sup.run() == 0
    assert cleaned == ["b"]  # only the live remnant, not the dead "a"
