"""ZeRO-Infinity disk tier (runtime/disk_offload.py, docs/stages.md).

Contracts these tests pin — the PR 3/7 discipline applied to the new
bottom tier:

  - BITWISE equivalence: disk-tier training loss, master, moments, and
    uploaded compute params equal the host tier's, which equal the
    serial read-update-write loop's (the degradation target);
  - the chaos/torture matrix: transient ``disk_read``/``disk_write``
    faults are absorbed bitwise, sticky faults degrade to the serial
    loop bitwise, a CRC flip raises TYPED before any engine state is
    touched, and a kill mid-write-back resumes from checkpoint bitwise;
  - the capacity claim: total master+moment state larger than a
    configured host-RAM budget trains to completion with the resident
    window under the budget (the accounting assert);
  - real concurrency, proven from tracer timestamps with injected disk
    latency: the disk_read span for leaf i+1 overlaps the Adam span
    for leaf i.
"""
import json as _json
import os
import sys
import time

import jax
import numpy as np
import pytest

sys.path.insert(0, "tests")

import deepspeed_tpu.runtime.offload as offload
from deepspeed_tpu.config import DeepSpeedConfig, DeepSpeedConfigError
from deepspeed_tpu.runtime.disk_offload import (DiskLeafStore,
                                                DiskOffloadOptimizer,
                                                DiskStateCorruptError,
                                                disk_fsync_enabled)
from deepspeed_tpu.runtime.engine import DeepSpeedEngine
from deepspeed_tpu.runtime.stages import reset_fault_injection
from deepspeed_tpu.telemetry.tracing import TraceRecorder

from simple_model import SimpleModel, base_config, random_batches


def _dp1_mesh():
    from deepspeed_tpu.parallel import build_mesh
    return build_mesh(dp=1, devices=jax.devices()[:1])


def _cfg(tier="disk", disk_dir=None, io_depth=2, dpu=False, micro_bs=4,
         telemetry_path=None, steps_per_print=10 ** 9):
    cfg = base_config(micro_bs=micro_bs, grad_acc=1, stage=2)
    cfg["zero_optimization"].update({"cpu_offload": True,
                                     "offload_impl": "host",
                                     "delayed_param_update": dpu})
    if tier == "disk":
        cfg["offload"] = {"tier": "disk", "disk_dir": str(disk_dir),
                          "io_depth": io_depth}
    cfg["steps_per_print"] = steps_per_print
    if telemetry_path is not None:
        cfg["telemetry"] = {"enabled": True,
                            "output_path": str(telemetry_path)}
    return DeepSpeedConfig(cfg, world_size=1)


def _engine(tmp_path, name="disk", seed=3, **kw):
    disk_dir = tmp_path / f"state_{name}"
    return DeepSpeedEngine(SimpleModel(hidden_dim=16),
                           _cfg(disk_dir=disk_dir, **kw),
                           mesh=_dp1_mesh(), seed=seed)


def _host_engine(seed=3, **kw):
    return DeepSpeedEngine(SimpleModel(hidden_dim=16),
                           _cfg(tier="host", **kw),
                           mesh=_dp1_mesh(), seed=seed)


def _train(engine, steps=4, hidden=16, seed=11):
    losses = []
    for b in random_batches(engine.train_batch_size, hidden,
                            num_batches=steps, seed=seed):
        losses.append(float(np.asarray(engine.train_batch(b))))
    return losses


def _assert_state_bitwise(e_a, e_b):
    for name, (ta, tb) in (
            ("master", (e_a.state.master_params, e_b.state.master_params)),
            ("mu", (e_a.state.opt_state["mu"], e_b.state.opt_state["mu"])),
            ("nu", (e_a.state.opt_state["nu"],
                    e_b.state.opt_state["nu"]))):
        la, lb = jax.tree.leaves(ta), jax.tree.leaves(tb)
        assert len(la) == len(lb)
        for i, (x, y) in enumerate(zip(la, lb)):
            np.testing.assert_array_equal(
                np.asarray(x), np.asarray(y), err_msg=f"{name}[{i}]")
    ca = jax.tree.leaves(e_a._compute_params)
    cb = jax.tree.leaves(e_b._compute_params)
    for i, (x, y) in enumerate(zip(ca, cb)):
        assert x.dtype == y.dtype, f"compute[{i}] dtype"
        np.testing.assert_array_equal(
            np.asarray(x), np.asarray(y), err_msg=f"compute_params[{i}]")


# ---------------------------------------------------------------------
# bitwise equivalence: disk == host == serial reference
# ---------------------------------------------------------------------
@pytest.mark.parametrize("dpu", [False, True])
def test_disk_bitwise_equals_host_tier(dpu, tmp_path):
    """The acceptance contract: identical losses, master, moments, AND
    uploaded compute params after N steps, disk tier vs host tier —
    with and without the delayed parameter update composed on top."""
    e_disk = _engine(tmp_path, dpu=dpu, seed=3)
    e_host = _host_engine(dpu=dpu, seed=3)
    l_disk = _train(e_disk)
    l_host = _train(e_host)
    assert l_disk == l_host
    if dpu:
        e_disk._dpu_flush()
        e_host._dpu_flush()
    _assert_state_bitwise(e_disk, e_host)


def test_disk_pipelined_bitwise_equals_serial(tmp_path, monkeypatch):
    """The serial read-update-write loop IS the degradation target, so
    the escape hatch (DS_DISK_OFFLOAD_PIPELINE=0) must be bitwise the
    pipelined path — and this exercises the serial loop itself."""
    monkeypatch.delenv("DS_DISK_OFFLOAD_PIPELINE", raising=False)
    e_pipe = _engine(tmp_path, name="pipe", seed=5)
    monkeypatch.setenv("DS_DISK_OFFLOAD_PIPELINE", "0")
    e_ser = _engine(tmp_path, name="ser", seed=5)
    l_ser = _train(e_ser)
    monkeypatch.delenv("DS_DISK_OFFLOAD_PIPELINE")
    l_pipe = _train(e_pipe)
    assert l_pipe == l_ser
    _assert_state_bitwise(e_pipe, e_ser)
    assert e_ser.last_offload_breakdown["disk_serial"]
    assert not e_pipe.last_offload_breakdown["disk_serial"]
    # serial loop: I/O sits between Adam calls — zero hidden by
    # construction (the same shape as the host tier's all-tail rule)
    assert e_ser.last_offload_breakdown["disk_hidden_s"] == 0.0


# ---------------------------------------------------------------------
# the chaos/torture matrix (DS_STAGE_FAULT, docs/stages.md)
# ---------------------------------------------------------------------
def test_transient_disk_faults_bitwise(tmp_path, monkeypatch):
    """Transient faults at BOTH disk I/O points: absorbed by the stage
    retry budget, training bitwise-equal to the fault-free run, and no
    degradation (the budget counts CONSECUTIVE failures)."""
    e_fault = _engine(tmp_path, name="fault", seed=7)
    e_ref = _engine(tmp_path, name="ref", seed=7)
    reset_fault_injection()
    monkeypatch.setenv("DS_STAGE_FAULT",
                       "disk_read:read:2,disk_write:write:3")
    l_fault = _train(e_fault)
    monkeypatch.delenv("DS_STAGE_FAULT")
    reset_fault_injection()
    l_ref = _train(e_ref)
    assert l_fault == l_ref
    _assert_state_bitwise(e_fault, e_ref)
    assert not e_fault._stage_records["disk_read"].degraded
    assert not e_fault._stage_records["disk_write"].degraded
    assert e_fault._stage_records["disk_read"].failures >= 1


@pytest.mark.parametrize("stage,spec", [
    ("disk_read", "disk_read:read:1+"),
    ("disk_write", "disk_write:write:1+"),
])
def test_sticky_fault_degrades_to_serial_bitwise(stage, spec, tmp_path,
                                                 monkeypatch):
    """A sticky fault at EITHER disk I/O point (dead disk, not a blip)
    exhausts the budget, DEGRADES the stage to the serial
    read-update-write loop with training still completing, and the
    result is bitwise the fault-free reference — degradation costs
    latency, never bytes."""
    e_fault = _engine(tmp_path, name=f"sticky_{stage}", seed=9)
    e_ref = _engine(tmp_path, name=f"sref_{stage}", seed=9)
    reset_fault_injection()
    monkeypatch.setenv("DS_STAGE_FAULT", spec)
    l_fault = _train(e_fault)
    monkeypatch.delenv("DS_STAGE_FAULT")
    reset_fault_injection()
    assert e_fault._stage_records[stage].degraded
    # post-degradation steps took the serial loop
    assert e_fault.last_offload_breakdown["disk_serial"]
    l_ref = _train(e_ref)
    assert l_fault == l_ref
    _assert_state_bitwise(e_fault, e_ref)


def test_crc_flip_raises_typed_before_state_touched(tmp_path):
    """Bit-rot on a state file: the read raises
    :class:`DiskStateCorruptError` (typed, non-transient — retries
    cannot heal it) BEFORE the corrupt bytes reach the Adam kernel;
    the engine's compute params stay the old tree and the optimizer
    poisons so the torn state can neither train nor serialize."""
    engine = _engine(tmp_path, name="crc", seed=11)
    batches = list(random_batches(engine.train_batch_size, 16,
                                  num_batches=3, seed=2))
    engine.train_batch(batches[0])
    old_params = engine._compute_params
    # flip one payload byte of leaf 0's state file
    path = engine._host_opt._store.path(0)
    with open(path, "r+b") as f:
        f.seek(-1, os.SEEK_END)
        b = f.read(1)
        f.seek(-1, os.SEEK_END)
        f.write(bytes([b[0] ^ 0xFF]))
    with pytest.raises(DiskStateCorruptError, match="CRC32 mismatch"):
        engine.train_batch(batches[1])
    assert engine._compute_params is old_params
    assert engine._host_opt._poisoned is not None
    with pytest.raises(RuntimeError, match="poisoned"):
        engine.train_batch(batches[2])
    with pytest.raises(RuntimeError, match="refusing to serialize"):
        engine._host_opt.state_tree()


def test_kill_during_writeback_resumes_from_checkpoint_bitwise(
        tmp_path, monkeypatch):
    """A write-back that dies mid-step (power cut / kill) leaves leaf
    files torn across steps t-1/t: the step raises, the optimizer
    poisons, and a checkpoint restore REWRITES every leaf file —
    training then continues bitwise-identical to an uninterrupted
    run."""
    batches = list(random_batches(4, 16, num_batches=4, seed=13))
    # uninterrupted reference
    e_ref = _engine(tmp_path, name="kref", seed=15)
    l_ref = [float(np.asarray(e_ref.train_batch(b))) for b in batches]
    # victim: save after step 2, die mid-write-back on step 3
    e_vic = _engine(tmp_path, name="kvic", seed=15)
    for b in batches[:2]:
        e_vic.train_batch(b)
    save_dir = tmp_path / "ckpt"
    e_vic.save_checkpoint(str(save_dir), tag="t2", async_write=False)

    real_write = DiskLeafStore.write
    state = {"writes": 0}

    def dying_write(self, idx, sections):
        state["writes"] += 1
        if state["writes"] > 1:
            raise RuntimeError("power cut mid write-back")
        return real_write(self, idx, sections)

    monkeypatch.setattr(DiskLeafStore, "write", dying_write)
    with pytest.raises(RuntimeError, match="power cut"):
        e_vic.train_batch(batches[2])
    monkeypatch.undo()
    assert e_vic._host_opt._poisoned is not None
    # restore heals the torn per-leaf state and clears the poison
    e_vic.load_checkpoint(str(tmp_path / "ckpt"), tag="t2")
    assert e_vic._host_opt._poisoned is None
    l_resumed = [float(np.asarray(e_vic.train_batch(b)))
                 for b in batches[2:]]
    assert l_resumed == l_ref[2:]
    _assert_state_bitwise(e_vic, e_ref)


def test_async_save_downgrades_to_sync(tmp_path):
    """An async save on the disk tier would _host_snapshot the FULL
    master+moments into RAM — the exact bytes the tier keeps on disk —
    so the engine downgrades it to the sync path (which streams the
    fp32 planes leaf-by-leaf through save_tree) and the checkpoint is
    still produced, verified, and loadable."""
    engine = _engine(tmp_path, name="async", seed=25)
    batches = list(random_batches(engine.train_batch_size, 16,
                                  num_batches=2, seed=8))
    engine.train_batch(batches[0])
    sd = tmp_path / "async_ckpt"
    engine.save_checkpoint(str(sd), tag="t1", async_write=True)
    # downgraded: the writer never got a job (no coalescing/pending)
    assert not engine._ckpt_writer.in_flight()
    e2 = _engine(tmp_path, name="async2", seed=99)
    e2.load_checkpoint(str(sd), tag="t1")
    l1 = float(np.asarray(engine.train_batch(batches[1])))
    l2 = float(np.asarray(e2.train_batch(batches[1])))
    assert l1 == l2


# ---------------------------------------------------------------------
# capacity: state > RAM budget trains; resident window stays under it
# ---------------------------------------------------------------------
def test_capacity_state_exceeds_ram_budget(tmp_path, monkeypatch):
    """The ZeRO-Infinity claim, CPU-scaled: total master+moment bytes
    on disk EXCEED the configured host-RAM budget, yet training
    completes (the io_depth window stays under it — enforced by the
    accounting assert inside the optimizer) with loss bitwise the
    unbudgeted host tier's.  The budget is the ANALYTIC window bound
    (``(2*io_depth + 3)`` leaf states: read-ahead queue + leaf being
    staged + leaf in update + write-back queue + leaf being written),
    not a measured peak — so the assert can never flake on worker
    timing."""
    def mk(name, seed=17):
        disk_dir = tmp_path / f"state_{name}"
        return DeepSpeedEngine(
            SimpleModel(hidden_dim=16, nlayers=12),
            _cfg(disk_dir=disk_dir, io_depth=1),
            mesh=_dp1_mesh(), seed=seed)

    probe = mk("probe")
    opt = probe._host_opt
    max_leaf_state = max(
        (3 if prom else 1)
        * int(np.prod(shape, dtype=np.int64)) * dt.itemsize
        for shape, dt, prom in opt._meta)
    budget = (2 * opt.io_depth + 3) * max_leaf_state
    total = opt.total_state_bytes
    assert total > budget, (total, budget)
    l_probe = _train(probe, steps=2)
    monkeypatch.setenv("DS_OFFLOAD_DISK_RAM_BUDGET_MB",
                       str(budget / (1 << 20)))
    e_cap = mk("cap")
    l_cap = _train(e_cap, steps=2)
    monkeypatch.delenv("DS_OFFLOAD_DISK_RAM_BUDGET_MB")
    assert e_cap._host_opt.ram_budget_bytes == budget
    assert e_cap._host_opt.total_state_bytes > budget
    assert 0 < e_cap._host_opt.peak_resident_bytes <= budget
    assert l_cap == l_probe
    e_host = DeepSpeedEngine(SimpleModel(hidden_dim=16, nlayers=12),
                             _cfg(tier="host"), mesh=_dp1_mesh(),
                             seed=17)
    l_host = _train(e_host, steps=2)
    assert l_cap == l_host


def test_budget_violation_raises(tmp_path):
    """A window that genuinely does not fit must raise the accounting
    assert (non-transient), not silently blow past the budget."""
    import jax.numpy as jnp
    master = {"w": np.ones((64, 64), np.float32)}
    opt = DiskOffloadOptimizer(
        master, lr=1e-2, betas=(0.9, 0.999), eps=1e-8, weight_decay=0.0,
        compute_dtype=jnp.bfloat16, disk_dir=str(tmp_path / "tiny"),
        io_depth=1, ram_budget_bytes=1024)
    with pytest.raises(RuntimeError, match="exceeds the configured"):
        opt.step({"w": np.ones((64, 64), np.float32)})


# ---------------------------------------------------------------------
# the concurrency proof: tracer timestamps with injected disk latency
# ---------------------------------------------------------------------
def _span_intervals(events, name):
    out = {}
    for e in events:
        if e.get("name") == name and e.get("ph") == "X":
            out[e["args"]["leaf"]] = (e["ts"], e["ts"] + e["dur"])
    return out


def test_disk_overlap_proven_by_tracer(tmp_path, monkeypatch):
    """With injected disk latency (20ms/read, 10ms/write) and slow
    grad pulls (15ms), the disk_read span for leaf i+1 MUST overlap
    the Adam span for leaf i — the acceptance criterion, read straight
    off tracer timestamps — and the engine's measured disk overlap
    must be positive."""
    real_get = jax.device_get

    def slow_get(x):
        time.sleep(0.015)
        return real_get(x)

    tracer = TraceRecorder()
    offload.set_transfer_tracer(tracer)
    try:
        engine = DeepSpeedEngine(
            SimpleModel(hidden_dim=16, nlayers=3),
            _cfg(disk_dir=tmp_path / "ovl"), mesh=_dp1_mesh(), seed=19)
        batch = next(random_batches(engine.train_batch_size, 16,
                                    num_batches=1, seed=5))
        monkeypatch.setenv("DS_STAGE_DELAY_S",
                           "disk_read:0.02,disk_write:0.01")
        monkeypatch.setattr(offload.jax, "device_get", slow_get)
        engine.train_batch(batch)
        monkeypatch.undo()  # also reverts DS_STAGE_DELAY_S
    finally:
        offload.set_transfer_tracer(None)

    evs = tracer.events()
    adam = _span_intervals(evs, "offload/adam_leaf")
    reads = _span_intervals(evs, "offload/disk_read")
    assert len(adam) >= 2 and len(reads) >= 2, (len(adam), len(reads))
    overlaps = []
    for i in sorted(adam):
        if i + 1 in reads:
            a0, a1 = adam[i]
            r0, r1 = reads[i + 1]
            overlaps.append(min(a1, r1) - max(a0, r0))
    assert overlaps and max(overlaps) > 0, (
        f"no disk_read(i+1) x Adam(i) overlap observed: {overlaps}")

    bd = engine.last_offload_breakdown
    assert bd["disk_hidden_s"] > 0, bd
    assert 0 < bd["disk_overlap_ratio"] <= 1, bd
    assert bd["disk_bytes_read"] > 0 and bd["disk_bytes_written"] > 0


# ---------------------------------------------------------------------
# fsync: default-on pin + config/env gating
# ---------------------------------------------------------------------
def test_fsync_on_by_default(monkeypatch):
    """The production default is fsync ON (power-loss durability); the
    conftest's DS_DISK_FSYNC=0 is a test-suite override of that
    default, not the default itself — and the config knob can force
    it off without touching the env."""
    monkeypatch.delenv("DS_DISK_FSYNC", raising=False)
    assert disk_fsync_enabled() is True
    assert disk_fsync_enabled(config_default=False) is False
    monkeypatch.setenv("DS_DISK_FSYNC", "0")
    assert disk_fsync_enabled() is False
    monkeypatch.setenv("DS_DISK_FSYNC", "1")
    assert disk_fsync_enabled() is True


# ---------------------------------------------------------------------
# config validation (eager) + drain order
# ---------------------------------------------------------------------
def test_offload_config_validation(tmp_path):
    def cfg(**offload):
        c = base_config(micro_bs=4, grad_acc=1, stage=2)
        c["zero_optimization"].update({"cpu_offload": True,
                                       "offload_impl": "host"})
        c["offload"] = offload
        return c

    with pytest.raises(DeepSpeedConfigError, match="'host' or 'disk'"):
        DeepSpeedConfig(cfg(tier="nvme"), world_size=1)
    with pytest.raises(DeepSpeedConfigError, match="io_depth"):
        DeepSpeedConfig(cfg(tier="disk", disk_dir=str(tmp_path),
                            io_depth=0), world_size=1)
    with pytest.raises(DeepSpeedConfigError, match="io_depth"):
        DeepSpeedConfig(cfg(tier="disk", disk_dir=str(tmp_path),
                            io_depth=True), world_size=1)
    with pytest.raises(DeepSpeedConfigError, match="fsync"):
        DeepSpeedConfig(cfg(tier="disk", disk_dir=str(tmp_path),
                            fsync="yes"), world_size=1)
    with pytest.raises(DeepSpeedConfigError, match="requires "
                                                   "offload.disk_dir"):
        DeepSpeedConfig(cfg(tier="disk"), world_size=1)
    # tier=disk without cpu_offload
    c = base_config(micro_bs=4, grad_acc=1, stage=2)
    c["offload"] = {"tier": "disk", "disk_dir": str(tmp_path)}
    with pytest.raises(DeepSpeedConfigError, match="requires\n?.*"
                                                   "cpu_offload"):
        DeepSpeedConfig(c, world_size=1)
    # tier=disk with an explicit xla impl
    c = base_config(micro_bs=4, grad_acc=1, stage=2)
    c["zero_optimization"].update({"cpu_offload": True,
                                   "offload_impl": "xla"})
    c["offload"] = {"tier": "disk", "disk_dir": str(tmp_path)}
    with pytest.raises(DeepSpeedConfigError, match="host-impl"):
        DeepSpeedConfig(c, world_size=1)
    # the default tier never validates anything
    DeepSpeedConfig(base_config(micro_bs=4, grad_acc=1, stage=2),
                    world_size=1)


def test_drain_order_includes_disk_writeback(tmp_path):
    """THE documented drain order gains the disk write-back entry
    between the offload uploads and the checkpoint writer
    (docs/stages.md)."""
    engine = _engine(tmp_path, name="drain", seed=21)
    order = engine._stage_graph.order
    assert order.index("offload_uploads") < order.index("disk_writeback")
    assert order.index("disk_writeback") < order.index("ckpt_writer")
    engine.close()  # the disk entry must be close-safe between steps


# ---------------------------------------------------------------------
# telemetry: gauge + counters + sync scalar + summarize row
# ---------------------------------------------------------------------
def test_disk_telemetry_reaches_artifacts(tmp_path):
    """offload_disk_overlap_ratio and the disk byte counters must flow
    end-to-end: registry -> metrics.prom, sync scalar -> events.jsonl
    -> summarize report + printed row."""
    from deepspeed_tpu.telemetry.cli import summarize

    tel = tmp_path / "tel"
    engine = _engine(tmp_path, name="tel", telemetry_path=tel,
                     steps_per_print=1, seed=23)
    _train(engine, steps=2)
    assert engine.telemetry.registry.gauge(
        "offload_disk_overlap_ratio").value() is not None
    engine.close()

    prom = (tel / "metrics.prom").read_text()
    assert "offload_disk_overlap_ratio" in prom
    assert "disk_bytes_read_total" in prom
    assert "disk_bytes_written_total" in prom
    syncs = [_json.loads(l) for l in
             (tel / "events.jsonl").read_text().splitlines()
             if _json.loads(l).get("kind") == "sync"]
    assert any("offload_disk_overlap_ratio" in (s.get("scalars") or {})
               for s in syncs)
    rep = summarize(str(tel / "events.jsonl"))
    assert rep["offload_disk_overlap_ratio"] is not None


def test_summarize_disk_row(tmp_path, capsys):
    from deepspeed_tpu.telemetry.cli import summarize
    p = tmp_path / "events.jsonl"
    lines = [{"kind": "sync", "step": 10 * (i + 1), "interval_s": 1.0,
              "steps": 10, "step_avg_s": 0.1,
              "scalars": {"offload_disk_overlap_ratio": r,
                          "disk_read_s": 0.02, "disk_write_s": 0.01}}
             for i, r in enumerate((0.4, 0.8))]
    p.write_text("\n".join(_json.dumps(l) for l in lines) + "\n")
    rep = summarize(str(p))
    assert rep["offload_disk_overlap_ratio"] == pytest.approx(0.6)
    assert rep["disk_read_s"] == pytest.approx(0.02)
    out = capsys.readouterr().out
    assert "disk tier" in out


# ---------------------------------------------------------------------
# bench CPU smoke (tier-1): the --offload-tier legs
# ---------------------------------------------------------------------
def _load_bench():
    import importlib.util
    path = os.path.join(os.path.dirname(__file__), "..", "bench.py")
    spec = importlib.util.spec_from_file_location("bench_for_test", path)
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


def test_bench_offload_tier_smoke():
    """Both bench legs on CPU: bitwise-equal loss across tiers, the
    disk leg measures overlap > 0 under its injected latency, and the
    capacity accounting (total on disk > resident peak) is recorded."""
    bench = _load_bench()
    disk = bench.bench_offload_tier(jax, "disk", steps=2)
    host = bench.bench_offload_tier(jax, "host", steps=2)
    assert disk["loss"] == host["loss"]
    assert disk["disk_overlap_ratio"] > 0, disk
    assert 0 < disk["peak_resident_bytes"] < disk["total_state_bytes"]
