"""Stage-local pipeline parameter placement — the memory point of PP.

The reference materializes only each stage's own layers per rank
(reference: deepspeed/runtime/pipe/module.py:197-249, partitioning at
:348-403).  Here the equivalent is stacked [S, k, ...] leaves sharded over
the ``pipe`` mesh axis: these tests assert per-chip param bytes really
drop ≈ 1/S for the stacked bulk, and that a pp mesh stores fewer param
bytes per chip than a dp-only mesh for the same model.
"""
import numpy as np
import jax
import pytest
import jax.numpy as jnp

from deepspeed_tpu.config import DeepSpeedConfig
from deepspeed_tpu.models.gpt2 import GPT2Config
from deepspeed_tpu.models.gpt2_pipe import build_gpt2_pipe, split_gpt2_batch
from deepspeed_tpu.parallel import build_mesh
from deepspeed_tpu.pipe.engine import PipelineEngine

from simple_model import base_config


def _model_cfg(n_layer=4):
    return GPT2Config(vocab_size=128, n_positions=32, d_model=64,
                      n_layer=n_layer, n_head=4, remat=None)


def _cfg(grad_acc=2, stage=0, world_size=4):
    return DeepSpeedConfig(
        base_config(micro_bs=1, grad_acc=grad_acc, stage=stage,
                    precision="bf16",
                    optimizer={"type": "Adam", "params": {"lr": 1e-3}}),
        world_size=world_size)


def _addressable_param_bytes(params):
    """Bytes of param storage on device 0 (one chip's share)."""
    total = 0
    dev0 = jax.devices()[0]
    for leaf in jax.tree.leaves(params):
        if not hasattr(leaf, "addressable_shards"):
            continue
        for shard in leaf.addressable_shards:
            if shard.device == dev0:
                total += int(np.prod(shard.data.shape)) * leaf.dtype.itemsize
    return total


def test_stacked_params_are_stage_local():
    """Each chip stores only its own stage's slice of the stacked blocks."""
    mesh = build_mesh(pp=2, dp=4, tp=1)
    pm = build_gpt2_pipe(_model_cfg(), num_stages=2)
    eng = PipelineEngine(pm, _cfg(), mesh)
    p = eng.state.master_params
    assert "stack_0" in p, f"expected stacked blocks, keys={list(p)}"
    leaf = p["stack_0"]["qkv_w"]
    assert "pipe" in str(leaf.sharding.spec), leaf.sharding.spec
    # per-device shard covers exactly one stage (dim0 = 1 of S=2)
    shard = leaf.addressable_shards[0]
    assert shard.data.shape[0] == 1 and leaf.shape[0] == 2


def test_pp_param_bytes_less_than_dp_only():
    """pp2 mesh holds ~half the block params per chip vs dp-only (zero
    stage 0 so ZeRO sharding doesn't mask the pipeline placement)."""
    cfg_model = _model_cfg(n_layer=4)

    mesh_pp = build_mesh(pp=2, dp=4, tp=1)
    pm = build_gpt2_pipe(cfg_model, num_stages=2)
    eng_pp = PipelineEngine(pm, _cfg(), mesh_pp)
    pp_bytes = _addressable_param_bytes(eng_pp.state.master_params)

    # dp-only: same packed tree, replicated everywhere (what the old
    # engine stored per chip at zero stage 0)
    full_bytes = sum(
        int(np.prod(l.shape)) * l.dtype.itemsize
        for l in jax.tree.leaves(eng_pp.state.master_params))

    # stacked blocks dominate this model; per-chip must be well below the
    # replicated total (embedding/tied stay replicated, so not exactly /2)
    assert pp_bytes < 0.8 * full_bytes, (pp_bytes, full_bytes)

    # the stacked subtree itself is exactly 1/2 per chip
    stacked_total = sum(
        int(np.prod(l.shape)) * l.dtype.itemsize
        for l in jax.tree.leaves(eng_pp.state.master_params["stack_0"]))
    stacked_local = _addressable_param_bytes(
        {"s": eng_pp.state.master_params["stack_0"]})
    assert abs(stacked_local - stacked_total // 2) <= 8, (
        stacked_local, stacked_total)


@pytest.mark.slow
def test_pp_zero3_composes():
    """ZeRO-3 + pipeline: stacked params shard over pipe AND data; training
    converges (the composition the reference cannot express — VERDICT
    round-1 item 5)."""
    mesh = build_mesh(pp=2, dp=4, tp=1)
    pm = build_gpt2_pipe(_model_cfg(), num_stages=2)
    eng = PipelineEngine(pm, _cfg(stage=3), mesh)
    p = eng.state.master_params
    spec = str(p["stack_0"]["qkv_w"].sharding.spec)
    assert "pipe" in spec, spec
    assert "data" in spec, spec
    toks = np.random.default_rng(0).integers(
        0, 128, (eng.train_batch_size, 17), dtype=np.int32)
    losses = [float(eng.train_batch(split_gpt2_batch(toks)))
              for _ in range(6)]
    assert all(np.isfinite(l) for l in losses), losses
    assert losses[-1] < losses[0], losses


@pytest.mark.slow
def test_pipeline_resize_restore(tmp_path):
    """Checkpoint saved at pp=2 loads onto a pp=4 engine: stacked leaves
    restack [2, 2, ...] -> [4, 1, ...] (stage ranges are contiguous, so the
    flat layer order is canonical) — the pipeline analogue of the
    reference's DP-resize ZeRO restore (stage2.py:1712-1778)."""
    cfg_model = _model_cfg(n_layer=4)
    toks = np.random.default_rng(0).integers(0, 128, (8, 17), dtype=np.int32)

    mesh2 = build_mesh(pp=2, dp=4, tp=1)
    pm2 = build_gpt2_pipe(cfg_model, num_stages=2)
    eng2 = PipelineEngine(pm2, _cfg(), mesh2)
    for _ in range(2):
        eng2.train_batch(split_gpt2_batch(toks))
    eng2.save_checkpoint(str(tmp_path), tag="pp2")
    loss2 = float(eng2.eval_batch if False else eng2.train_batch(
        split_gpt2_batch(toks)))

    mesh4 = build_mesh(pp=4, dp=2, tp=1)
    pm4 = build_gpt2_pipe(cfg_model, num_stages=4)
    eng4 = PipelineEngine(pm4, _cfg(grad_acc=4, world_size=2), mesh4)
    path, _ = eng4.load_checkpoint(str(tmp_path), tag="pp2")
    assert path is not None
    assert eng4.state.master_params["stack_0"]["qkv_w"].shape[0] == 4
    # same weights -> same next-step loss trajectory (rtol covers bf16)
    loss4 = float(eng4.train_batch(split_gpt2_batch(toks)))
    np.testing.assert_allclose(loss4, loss2, rtol=5e-2)


def test_1f1b_matches_gpipe():
    """The hand-scheduled 1F1B backward computes the same gradients as AD
    over the GPipe scan: identical training trajectories (bf16 noise from
    a different reduction order only)."""
    cfg_model = _model_cfg(n_layer=4)
    mesh = build_mesh(pp=2, dp=4, tp=1)
    e1 = PipelineEngine(build_gpt2_pipe(cfg_model, num_stages=2),
                        _cfg(grad_acc=4), mesh, schedule="1f1b")
    eg = PipelineEngine(build_gpt2_pipe(cfg_model, num_stages=2),
                        _cfg(grad_acc=4), mesh, schedule="gpipe")
    toks = np.random.default_rng(3).integers(
        0, 128, (e1.train_batch_size, 17), dtype=np.int32)
    for _ in range(4):
        l1 = float(np.asarray(e1.train_batch(split_gpt2_batch(toks))))
        lg = float(np.asarray(eg.train_batch(split_gpt2_batch(toks))))
        assert abs(l1 - lg) < 3e-2, (l1, lg)
    # parameters stay together step after step (not just the loss)
    p1 = jax.tree.leaves(e1.state.master_params)
    pg = jax.tree.leaves(eg.state.master_params)
    for a, b in zip(p1, pg):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=5e-2, atol=5e-3)


def test_1f1b_activation_memory_independent_of_micro_count():
    """The 1F1B ring bounds live boundary activations at min(S, M): the
    compiled step's temp bytes must stay ~flat as M grows, while the
    GPipe/AD schedule stores one boundary per tick (O(M)) — the reference
    TrainSchedule's buffer bound min(stages - stage_id + 1, micro_batches)
    (reference deepspeed/runtime/pipe/schedule.py:243-247).  M is scaled
    4x at fixed micro size; per-micro batch data scales with M and is an
    operand (donated input), not a temp."""
    cfg_model = _model_cfg(n_layer=2)
    mesh = build_mesh(pp=2, dp=2, tp=1, devices=jax.devices()[:4])

    def temp_bytes(schedule, grad_acc):
        eng = PipelineEngine(build_gpt2_pipe(cfg_model, num_stages=2),
                             _cfg(grad_acc=grad_acc, world_size=2), mesh,
                             schedule=schedule)
        toks = np.random.default_rng(0).integers(
            0, 128, (eng.train_batch_size, 17), dtype=np.int32)
        sharded = eng._shard_batch(split_gpt2_batch(toks))
        compiled = eng._train_step.lower(eng.state, sharded).compile()
        ma = compiled.memory_analysis()
        if ma is None:
            pytest.skip("backend exposes no memory analysis")
        return int(ma.temp_size_in_bytes)

    t4 = temp_bytes("1f1b", 4)
    t16 = temp_bytes("1f1b", 16)
    # 4x the micro-batches, ~flat activation temp (ring is min(S,M)=2
    # boundaries; allow slack for per-tick scan bookkeeping)
    assert t16 < 1.6 * t4, (t4, t16)
    # uniform-tick 1F1B keeps the same property (ring of min(2S-1, M))
    # — it must not regress to gpipe's O(M) while buying seq-collective
    # schedule-invariance
    u4 = temp_bytes("1f1b_uniform", 4)
    u16 = temp_bytes("1f1b_uniform", 16)
    assert u16 < 1.6 * u4, (u4, u16)
    # the metric is real: the AD/GPipe schedule DOES grow with M
    g4 = temp_bytes("gpipe", 4)
    g16 = temp_bytes("gpipe", 16)
    assert g16 > 1.8 * g4, (g4, g16)


@pytest.mark.slow
def test_heterogeneous_stages_fall_back_to_replicated():
    """Stages with non-matching layer fingerprints keep the general
    replicated path (no stacking) and still train."""
    from deepspeed_tpu.pipe import LayerSpec, PipelineModule

    class Lin:
        def __init__(self, din, dout):
            self.din, self.dout = din, dout

        def init(self, rng):
            return {"w": jax.random.normal(
                rng, (self.din, self.dout), jnp.float32) * 0.2}

        def apply(self, p, x, rng, train=True):
            return jnp.tanh(x @ p["w"].astype(x.dtype))

    specs = [LayerSpec(Lin, 16, 48), LayerSpec(Lin, 48, 16),
             LayerSpec(Lin, 16, 24), LayerSpec(Lin, 24, 16)]
    pm = PipelineModule(specs, num_stages=2,
                        loss_fn=lambda o, l: jnp.mean(
                            (o.astype(jnp.float32) - l) ** 2),
                        partition_method="uniform")
    assert pm.stack_plan() == {}
    mesh = build_mesh(pp=2, dp=4, tp=1)
    eng = PipelineEngine(pm, _cfg(grad_acc=4), mesh)
    rng = np.random.default_rng(0)
    x = rng.standard_normal((eng.train_batch_size, 16)).astype(np.float32)
    y = (0.5 * np.abs(x)).astype(np.float32)
    losses = [float(eng.train_batch((x, y))) for _ in range(6)]
    assert losses[-1] < losses[0], losses
