"""GPT-2 flagship model: shapes, loss, TP sharding, end-to-end ZeRO train."""
import numpy as np
import jax
import jax.numpy as jnp
import pytest

import deepspeed_tpu
from deepspeed_tpu.config import DeepSpeedConfig
from deepspeed_tpu.models import GPT2Config, GPT2Model
from deepspeed_tpu.parallel import build_mesh
from deepspeed_tpu.runtime.engine import DeepSpeedEngine

from simple_model import base_config

TINY = GPT2Config(vocab_size=128, n_positions=64, d_model=32, n_layer=2,
                  n_head=4, remat=None)


def _tokens(batch, seqlen, vocab, seed=0):
    rng = np.random.default_rng(seed)
    return rng.integers(0, vocab, (batch, seqlen), dtype=np.int32)


def test_forward_shapes():
    model = GPT2Model(TINY)
    params = model.init(jax.random.PRNGKey(0))
    toks = _tokens(2, 16, TINY.vocab_size)
    logits = model.apply(params, jnp.asarray(toks), jax.random.PRNGKey(1),
                         train=False)
    assert logits.shape == (2, 16, TINY.vocab_size)


def test_loss_near_uniform_at_init():
    model = GPT2Model(TINY)
    params = model.init(jax.random.PRNGKey(0))
    toks = _tokens(4, 32, TINY.vocab_size)
    loss = model.loss_fn(params, jnp.asarray(toks), jax.random.PRNGKey(1),
                         train=False)
    # random init → loss ≈ ln(vocab)
    assert abs(float(loss) - np.log(TINY.vocab_size)) < 1.0


def test_flash_dropout_trains_through_engine():
    """attn_impl='flash' with attention+residual dropout: the in-kernel
    hashed dropout path runs end-to-end inside the compiled train step
    (grads through the custom VJP, seed folded per step)."""
    import dataclasses
    cfg = dataclasses.replace(TINY, attn_impl="flash", dropout=0.1)
    mesh = build_mesh()
    ds = DeepSpeedConfig(base_config(micro_bs=1, grad_acc=1, stage=2),
                         world_size=8)
    eng = DeepSpeedEngine(GPT2Model(cfg), ds, mesh=mesh)
    toks = _tokens(8, 33, cfg.vocab_size)
    losses = [float(np.asarray(eng.train_batch(toks))) for _ in range(6)]
    assert all(np.isfinite(losses))
    assert losses[-1] < losses[0]


def test_remat_matches_no_remat():
    cfg_r = GPT2Config(**{**TINY.__dict__, "remat": "block"})
    m1, m2 = GPT2Model(TINY), GPT2Model(cfg_r)
    params = m1.init(jax.random.PRNGKey(0))
    toks = jnp.asarray(_tokens(2, 16, TINY.vocab_size))
    l1 = m1.loss_fn(params, toks, jax.random.PRNGKey(1), train=False)
    l2 = m2.loss_fn(params, toks, jax.random.PRNGKey(1), train=False)
    np.testing.assert_allclose(float(l1), float(l2), rtol=1e-6)


def test_param_count_formula():
    model = GPT2Model(TINY)
    params = model.init(jax.random.PRNGKey(0))
    actual = sum(int(np.prod(l.shape)) for l in jax.tree.leaves(params))
    assert actual == TINY.num_params


@pytest.mark.slow
def test_gpt2_trains_with_zero2():
    mesh = build_mesh()
    cfg = DeepSpeedConfig(
        base_config(micro_bs=1, stage=2,
                    optimizer={"type": "Adam", "params": {"lr": 1e-3}}),
        world_size=8)
    eng = DeepSpeedEngine(GPT2Model(TINY), cfg, mesh=mesh)
    toks = _tokens(8, 33, TINY.vocab_size)
    losses = [float(eng.train_batch(toks)) for _ in range(8)]
    assert losses[-1] < losses[0]  # memorizes the repeated batch


@pytest.mark.slow
def test_gpt2_tensor_parallel_mesh():
    """dp=4 × tp=2 mesh: TP specs shard qkv over 'model' axis and training
    still runs (the Megatron-slice integration slot, reference
    topology.py:344-364)."""
    mesh = build_mesh(pp=1, dp=4, tp=2)
    cfg = DeepSpeedConfig(
        base_config(micro_bs=2, stage=1,
                    optimizer={"type": "Adam", "params": {"lr": 1e-3}}),
        world_size=4)
    eng = DeepSpeedEngine(GPT2Model(TINY), cfg, mesh=mesh)
    qkv = eng.state.master_params["blocks"]["qkv_w"]
    # [L, d, 3d]: data axis (4) shards some dim, model axis (2) shards last
    shard = qkv.sharding.shard_shape(qkv.shape)
    assert shard[-1] == qkv.shape[-1] // 2  # model-axis split
    toks = _tokens(8, 33, TINY.vocab_size)
    losses = [float(eng.train_batch(toks)) for _ in range(5)]
    assert losses[-1] < losses[0]


@pytest.mark.slow
def test_gpt2_dp_tp_matches_pure_dp():
    """Same seed, same data: (dp=8) and (dp=4,tp=2) must match numerics."""
    toks = _tokens(8, 33, TINY.vocab_size)

    def run(mesh, ws):
        cfg = DeepSpeedConfig(
            base_config(micro_bs=8 // ws, stage=1,
                        optimizer={"type": "Adam", "params": {"lr": 1e-3}}),
            world_size=ws)
        eng = DeepSpeedEngine(GPT2Model(TINY), cfg, mesh=mesh, seed=7)
        return [float(eng.train_batch(toks)) for _ in range(3)]

    a = run(build_mesh(), 8)
    b = run(build_mesh(pp=1, dp=4, tp=2), 4)
    np.testing.assert_allclose(a, b, rtol=5e-3)


def test_logits_match_huggingface_gpt2():
    """Weights copied from a HuggingFace GPT2LMHeadModel; logits compared
    (the reference's kernel-vs-HF differential pattern applied to the
    causal-LM family)."""
    torch = pytest.importorskip("torch")
    transformers = pytest.importorskip("transformers")

    V, T, D, L, H = 97, 16, 48, 2, 4
    hf_cfg = transformers.GPT2Config(
        vocab_size=V, n_positions=32, n_embd=D, n_layer=L, n_head=H,
        resid_pdrop=0.0, embd_pdrop=0.0, attn_pdrop=0.0,
        attn_implementation="eager")
    torch.manual_seed(0)
    hf = transformers.GPT2LMHeadModel(hf_cfg).eval()

    def t2j(t):
        return jnp.asarray(t.detach().numpy())

    sd = dict(hf.named_parameters())

    def stack(fmt):
        return jnp.stack([t2j(sd[fmt.format(i)]) for i in range(L)])

    # HF Conv1D stores weights [in, out] — same layout as ours, no .T
    params = {
        "wte": t2j(sd["transformer.wte.weight"]),
        "wpe": t2j(sd["transformer.wpe.weight"]),
        "ln_f_scale": t2j(sd["transformer.ln_f.weight"]),
        "ln_f_bias": t2j(sd["transformer.ln_f.bias"]),
        "blocks": {
            "ln1_scale": stack("transformer.h.{}.ln_1.weight"),
            "ln1_bias": stack("transformer.h.{}.ln_1.bias"),
            # HF fuses qkv on one [d, 3d] dim; ours keeps q/k/v on a
            # dedicated dim [d, 3, d] (same values, TP-shard-aligned)
            "qkv_w": stack("transformer.h.{}.attn.c_attn.weight").reshape(
                L, D, 3, D),
            "qkv_b": stack("transformer.h.{}.attn.c_attn.bias").reshape(
                L, 3, D),
            "out_w": stack("transformer.h.{}.attn.c_proj.weight"),
            "out_b": stack("transformer.h.{}.attn.c_proj.bias"),
            "ln2_scale": stack("transformer.h.{}.ln_2.weight"),
            "ln2_bias": stack("transformer.h.{}.ln_2.bias"),
            "fc_w": stack("transformer.h.{}.mlp.c_fc.weight"),
            "fc_b": stack("transformer.h.{}.mlp.c_fc.bias"),
            "proj_w": stack("transformer.h.{}.mlp.c_proj.weight"),
            "proj_b": stack("transformer.h.{}.mlp.c_proj.bias"),
        },
    }

    model = GPT2Model(GPT2Config(
        vocab_size=V, n_positions=32, d_model=D, n_layer=L, n_head=H,
        dropout=0.0, embd_dropout=0.0, remat=None, attn_impl="dense"))
    tokens = np.random.default_rng(0).integers(0, V, (2, T),
                                               dtype=np.int32)
    with torch.no_grad():
        want = hf(torch.from_numpy(tokens).long()).logits.numpy()
    got = np.asarray(model.apply(params, jnp.asarray(tokens),
                                 jax.random.PRNGKey(0), train=False))
    np.testing.assert_allclose(got, want, rtol=2e-4, atol=2e-4)
