"""Sparse attention tests.

Differential pattern from the reference (reference:
tests/unit/test_sparse_attention.py — sparse ops vs dense masked
references): every layout family is checked against a dense attention with
the block mask expanded to token granularity.
"""
import jax
import jax.numpy as jnp
import numpy as np
import pytest

from deepspeed_tpu.ops.sparse_attention import (
    BertSelfAttentionConfig, BertSparseSelfAttention, BigBirdSparsityConfig,
    BSLongformerSparsityConfig, DenseSparsityConfig, FixedSparsityConfig,
    SparseAttentionUtils, SparseSelfAttention, VariableSparsityConfig,
    build_lut)

BLOCK = 16


def dense_reference(q, k, v, token_mask, rpe=None, key_padding_mask=None,
                    kp_mode="add", attn_mask=None, am_mode="mul"):
    """Dense attention with explicit token-level mask [H, T, T]."""
    q32, k32, v32 = (x.astype(jnp.float32) for x in (q, k, v))
    scale = q.shape[-1] ** -0.5
    scores = jnp.einsum("bhqd,bhkd->bhqk", q32, k32) * scale
    if rpe is not None:
        scores = scores + rpe[None, None]
    if attn_mask is not None:
        if am_mode == "add":
            scores = scores + attn_mask[None, None]
        else:
            scores = jnp.where(attn_mask[None, None] != 0, scores, -1e38)
    if key_padding_mask is not None:
        kp = key_padding_mask[:, None, None, :]
        scores = scores + kp if kp_mode == "add" else jnp.where(
            kp != 0, scores, -1e38)
    scores = jnp.where(token_mask[None] != 0, scores, -1e38)
    probs = jax.nn.softmax(scores, axis=-1)
    # rows with no active keys → zero output (sparse kernel convention)
    any_active = (token_mask[None] != 0).any(-1, keepdims=True)
    if key_padding_mask is not None and kp_mode == "mul":
        any_active = any_active & (key_padding_mask[:, None, None, :] != 0
                                   ).any(-1, keepdims=True)
    probs = jnp.where(any_active, probs, 0.0)
    return jnp.einsum("bhqk,bhkd->bhqd", probs, v32)


def expand_layout(layout):
    """[H, nb, nb] block layout → [H, T, T] token mask."""
    return np.kron(layout, np.ones((BLOCK, BLOCK), dtype=np.int64))


def make_qkv(B, H, T, D, seed=0):
    rng = np.random.default_rng(seed)
    mk = lambda: jnp.asarray(
        rng.standard_normal((B, H, T, D)), jnp.float32)
    return mk(), mk(), mk()


CONFIGS = [
    ("dense", lambda H: DenseSparsityConfig(H, block=BLOCK)),
    ("fixed_bi", lambda H: FixedSparsityConfig(
        H, block=BLOCK, num_local_blocks=2, num_global_blocks=1)),
    ("fixed_uni", lambda H: FixedSparsityConfig(
        H, block=BLOCK, num_local_blocks=2, attention="unidirectional")),
    ("fixed_horizontal", lambda H: FixedSparsityConfig(
        H, block=BLOCK, num_local_blocks=2,
        horizontal_global_attention=True)),
    ("variable", lambda H: VariableSparsityConfig(
        H, block=BLOCK, num_random_blocks=1, local_window_blocks=[1, 2],
        global_block_indices=[0, 3], seed=11)),
    ("variable_ranges", lambda H: VariableSparsityConfig(
        H, block=BLOCK, global_block_indices=[0],
        global_block_end_indices=[2])),
    ("bigbird", lambda H: BigBirdSparsityConfig(
        H, block=BLOCK, num_random_blocks=1, num_sliding_window_blocks=3,
        num_global_blocks=1, seed=5)),
    ("longformer", lambda H: BSLongformerSparsityConfig(
        H, block=BLOCK, num_sliding_window_blocks=3,
        global_block_indices=[0])),
]


@pytest.mark.parametrize("name,make_cfg", CONFIGS, ids=[c[0] for c in CONFIGS])
def test_sparse_matches_dense_masked(name, make_cfg):
    B, H, T, D = 2, 4, 6 * BLOCK, 32
    cfg = make_cfg(H)
    attn = SparseSelfAttention(cfg)
    q, k, v = make_qkv(B, H, T, D, seed=1)
    out = attn(q, k, v)
    layout = cfg.make_layout(T)
    ref = dense_reference(q, k, v, jnp.asarray(expand_layout(layout)))
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=2e-4, atol=2e-4)


def test_sparse_with_key_padding_mask_add():
    B, H, T, D = 2, 4, 4 * BLOCK, 16
    cfg = FixedSparsityConfig(H, block=BLOCK, num_local_blocks=2)
    attn = SparseSelfAttention(cfg, key_padding_mask_mode="add")
    q, k, v = make_qkv(B, H, T, D, seed=2)
    # additive HF-style mask: 0 keep, -10000 drop last quarter
    kp = np.zeros((B, T), np.float32)
    kp[:, -T // 4:] = -1e9
    out = attn(q, k, v, key_padding_mask=jnp.asarray(kp))
    layout = cfg.make_layout(T)
    ref = dense_reference(q, k, v, jnp.asarray(expand_layout(layout)),
                          key_padding_mask=jnp.asarray(kp), kp_mode="add")
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=2e-4, atol=2e-4)


def test_sparse_with_attn_mask_mul():
    B, H, T, D = 1, 2, 4 * BLOCK, 16
    cfg = BSLongformerSparsityConfig(H, block=BLOCK)
    attn = SparseSelfAttention(cfg, attn_mask_mode="mul")
    q, k, v = make_qkv(B, H, T, D, seed=3)
    causal = np.tril(np.ones((T, T), np.float32))
    out = attn(q, k, v, attn_mask=jnp.asarray(causal))
    layout = cfg.make_layout(T)
    ref = dense_reference(q, k, v, jnp.asarray(expand_layout(layout)),
                          attn_mask=jnp.asarray(causal), am_mode="mul")
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=2e-4, atol=2e-4)


def test_sparse_with_rpe():
    B, H, T, D = 1, 2, 3 * BLOCK, 16
    cfg = FixedSparsityConfig(H, block=BLOCK, num_local_blocks=3)
    attn = SparseSelfAttention(cfg)
    q, k, v = make_qkv(B, H, T, D, seed=4)
    rpe = jnp.asarray(
        np.random.default_rng(5).standard_normal((T, T)), jnp.float32)
    out = attn(q, k, v, rpe=rpe)
    layout = cfg.make_layout(T)
    ref = dense_reference(q, k, v, jnp.asarray(expand_layout(layout)),
                          rpe=rpe)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=2e-4, atol=2e-4)


def test_sparse_attention_differentiable():
    B, H, T, D = 1, 2, 4 * BLOCK, 16
    cfg = BigBirdSparsityConfig(H, block=BLOCK, seed=1)
    attn = SparseSelfAttention(cfg)
    q, k, v = make_qkv(B, H, T, D, seed=6)

    def loss(qkv):
        return jnp.sum(attn(*qkv) ** 2)

    grads = jax.grad(loss)((q, k, v))
    for g in grads:
        assert np.isfinite(np.asarray(g)).all()
        assert float(jnp.max(jnp.abs(g))) > 0


# ---------------------------------------------------------------------------
# layout-shape properties (mirror test_sparse_attention.py's layout checks)
# ---------------------------------------------------------------------------
def test_fixed_unidirectional_is_block_lower_triangular():
    cfg = FixedSparsityConfig(2, block=BLOCK, num_local_blocks=4,
                              attention="unidirectional")
    layout = cfg.make_layout(8 * BLOCK)
    assert (np.triu(layout[0], 1) == 0).all()
    # diagonal always attended
    assert (np.diagonal(layout[0]) == 1).all()


def test_fixed_global_patterns_differ_per_head():
    cfg = FixedSparsityConfig(4, block=BLOCK, num_local_blocks=4,
                              different_layout_per_head=True,
                              num_different_global_patterns=4)
    layout = cfg.make_layout(8 * BLOCK)
    # each head uses a different global column within each window
    firsts = [np.nonzero(layout[h, 0])[0] for h in range(4)]
    assert len({tuple(f.tolist()) for f in firsts}) == 4


def test_bigbird_global_rows_and_cols():
    cfg = BigBirdSparsityConfig(1, block=BLOCK, num_random_blocks=1,
                                num_sliding_window_blocks=3,
                                num_global_blocks=2)
    layout = cfg.make_layout(8 * BLOCK)
    assert (layout[0, :2, :] == 1).all() and (layout[0, :, :2] == 1).all()


def test_longformer_window_width():
    cfg = BSLongformerSparsityConfig(1, block=BLOCK,
                                     num_sliding_window_blocks=3,
                                     global_block_indices=[0])
    layout = cfg.make_layout(8 * BLOCK)
    # row 4 attends blocks {0 (global), 3, 4, 5}
    np.testing.assert_array_equal(np.nonzero(layout[0, 4])[0], [0, 3, 4, 5])


def test_layout_head_propagation():
    cfg = BigBirdSparsityConfig(4, block=BLOCK, seed=3)
    layout = cfg.make_layout(4 * BLOCK)
    for h in range(1, 4):
        np.testing.assert_array_equal(layout[h], layout[0])


def test_seq_len_not_divisible_raises():
    cfg = FixedSparsityConfig(2, block=BLOCK)
    with pytest.raises(ValueError, match="divisible"):
        cfg.make_layout(BLOCK + 1)


def test_build_lut_padding():
    layout = np.zeros((1, 4, 4), dtype=np.int64)
    layout[0, 0, [0, 2]] = 1
    layout[0, 1, 1] = 1
    layout[0, 2] = 1
    layout[0, 3, 3] = 1
    cols, valid = build_lut(layout)
    assert cols.shape == (1, 4, 4)  # width = max row count = 4
    np.testing.assert_array_equal(cols[0, 0], [0, 2, 0, 0])
    np.testing.assert_array_equal(valid[0, 0], [True, True, False, False])


# ---------------------------------------------------------------------------
# utils + BERT layer
# ---------------------------------------------------------------------------
def test_pad_to_block_size_and_unpad():
    ids = jnp.ones((2, 20), jnp.int32)
    mask = jnp.ones((2, 20), jnp.float32)
    pad_len, (ids2, mask2, _, _, _) = SparseAttentionUtils.pad_to_block_size(
        BLOCK, ids, attention_mask=mask, pad_token_id=7)
    assert pad_len == 12 and ids2.shape == (2, 32)
    assert (np.asarray(ids2[:, 20:]) == 7).all()
    assert (np.asarray(mask2[:, 20:]) == 0).all()
    seq_out = jnp.ones((2, 32, 8))
    unp = SparseAttentionUtils.unpad_sequence_output(pad_len, seq_out)
    assert unp.shape == (2, 20, 8)


def test_extend_position_embedding():
    pe = jnp.asarray(np.arange(8 * 4, dtype=np.float32).reshape(8, 4))
    ext = SparseAttentionUtils.extend_position_embedding(pe, 20)
    assert ext.shape == (20, 4)
    np.testing.assert_array_equal(np.asarray(ext[8:16]), np.asarray(pe))


def test_bert_sparse_self_attention_shapes_and_grad():
    cfg = BertSelfAttentionConfig(hidden_size=64, num_attention_heads=4)
    layer = BertSparseSelfAttention(
        cfg, FixedSparsityConfig(4, block=BLOCK, num_local_blocks=2))
    params = layer.init(jax.random.PRNGKey(0))
    x = jnp.asarray(np.random.default_rng(8).standard_normal(
        (2, 4 * BLOCK, 64)), jnp.float32)
    mask = jnp.zeros((2, 4 * BLOCK), jnp.float32)
    out = layer(params, x, attention_mask=mask)
    assert out.shape == (2, 4 * BLOCK, 64)
    g = jax.grad(lambda p: jnp.sum(layer(p, x, mask) ** 2))(params)
    assert all(np.isfinite(np.asarray(l)).all() for l in jax.tree.leaves(g))


def test_native_lut_matches_numpy():
    """csrc/sparse_lut.cpp vs the numpy fallback (the reference's
    segment_blocks is likewise C++, csrc/sparse_attention/utils.cpp:14)."""
    from deepspeed_tpu.ops.op_builder import cpu_ops_available
    from deepspeed_tpu.ops.sparse_attention.sparse_self_attention import (
        build_lut)

    if not cpu_ops_available():
        pytest.skip("native toolchain unavailable")
    rng = np.random.default_rng(0)
    layout = (rng.random((3, 16, 16)) < 0.3).astype(np.int64)
    layout[:, 0, :] = 0  # an empty row must not break the width calc
    c_nat, v_nat = build_lut(layout, use_native=True)
    c_np, v_np = build_lut(layout, use_native=False)
    np.testing.assert_array_equal(c_nat, c_np)
    np.testing.assert_array_equal(v_nat, v_np)
