"""CPU Adam + ZeRO-Offload tests.

Differential pattern from the reference (reference:
tests/unit/test_cpu_adam.py compares DeepSpeedCPUAdam vs torch.optim.Adam):
the native kernel is checked against the device fused_adam and the numpy
fallback, and the offload engine path is trained end-to-end.
"""
import sys

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest

sys.path.insert(0, "tests")

from deepspeed_tpu.config import DeepSpeedConfig
from deepspeed_tpu.ops.adam import fused_adam
from deepspeed_tpu.ops.cpu_adam import DeepSpeedCPUAdam
from deepspeed_tpu.ops.op_builder import cpu_ops_available, cpu_ops_status
from deepspeed_tpu.runtime.engine import DeepSpeedEngine

from simple_model import SimpleModel, base_config, random_batches

NATIVE = cpu_ops_available()


def test_native_ops_build():
    """The C++ toolchain is present in CI and on TPU-VMs; the native op
    must build there (the numpy fallback is for exotic hosts only)."""
    assert NATIVE, cpu_ops_status()


@pytest.mark.parametrize("adamw", [True, False])
@pytest.mark.parametrize("native", [True, False] if NATIVE else [False])
def test_cpu_adam_matches_fused_adam(adamw, native):
    rng = np.random.default_rng(0)
    p0 = {"w": rng.standard_normal((64, 32)).astype(np.float32),
          "b": rng.standard_normal(32).astype(np.float32)}
    host = DeepSpeedCPUAdam(lr=1e-2, weight_decay=0.01, adamw_mode=adamw,
                            use_native=native)
    p_host = jax.tree.map(np.copy, p0)
    tx = fused_adam(1e-2, weight_decay=0.01, adam_w_mode=adamw)
    p_dev = jax.tree.map(jnp.asarray, p0)
    st = tx.init(p_dev)
    for _ in range(10):
        g = {"w": rng.standard_normal((64, 32)).astype(np.float32),
             "b": rng.standard_normal(32).astype(np.float32)}
        host.step(p_host, g)
        u, st = tx.update(jax.tree.map(jnp.asarray, g), st, p_dev)
        p_dev = optax.apply_updates(p_dev, u)
    for k in p0:
        np.testing.assert_allclose(p_host[k], np.asarray(p_dev[k]),
                                   rtol=1e-5, atol=1e-6, err_msg=k)


@pytest.mark.skipif(not NATIVE, reason="no C++ toolchain")
def test_native_matches_numpy_fallback():
    rng = np.random.default_rng(1)
    p_n = {"x": rng.standard_normal(1000).astype(np.float32)}
    p_f = jax.tree.map(np.copy, p_n)
    on = DeepSpeedCPUAdam(lr=3e-3, weight_decay=0.1, use_native=True)
    of = DeepSpeedCPUAdam(lr=3e-3, weight_decay=0.1, use_native=False)
    for _ in range(5):
        g = {"x": rng.standard_normal(1000).astype(np.float32)}
        lo_n = on.step(p_n, g, out_dtype="bfloat16")
        lo_f = of.step(p_f, g, out_dtype="bfloat16")
    np.testing.assert_allclose(p_n["x"], p_f["x"], rtol=1e-6, atol=1e-7)
    np.testing.assert_array_equal(
        np.asarray(lo_n["x"]).view(np.uint16),
        np.asarray(lo_f["x"]).view(np.uint16))  # bitwise-equal bf16 rounding


@pytest.mark.skipif(not NATIVE, reason="no C++ toolchain")
def test_fused_bf16_copyback_matches_cast():
    rng = np.random.default_rng(2)
    p = {"x": rng.standard_normal(257).astype(np.float32)}  # odd size
    opt = DeepSpeedCPUAdam(lr=1e-2, use_native=True)
    lowp = opt.step(p, {"x": rng.standard_normal(257).astype(np.float32)},
                    out_dtype="bfloat16")
    import ml_dtypes
    np.testing.assert_array_equal(
        np.asarray(lowp["x"]).view(np.uint16),
        p["x"].astype(ml_dtypes.bfloat16).view(np.uint16))


def _offload_config(**over):
    cfg = base_config(micro_bs=4, grad_acc=2, stage=2)
    cfg["zero_optimization"]["cpu_offload"] = True
    cfg.update(over)
    return DeepSpeedConfig(cfg, world_size=8)


def test_offload_engine_trains():
    cfg = _offload_config()
    engine = DeepSpeedEngine(SimpleModel(hidden_dim=16), cfg)
    assert engine._offload and engine._host_opt.is_native == NATIVE
    losses = [float(engine.train_batch(b)) for b in
              random_batches(cfg.train_batch_size, 16, num_batches=20,
                             seed=9)]
    assert losses[-1] < losses[0] * 0.7, losses
    # master + moments really live on host numpy
    assert isinstance(jax.tree.leaves(engine.state.master_params)[0],
                      np.ndarray)
    assert isinstance(jax.tree.leaves(engine.state.opt_state["mu"])[0],
                      np.ndarray)


def test_offload_matches_device_path():
    """Same data, same seeds: offload and in-device ZeRO-2 must track each
    other closely (bf16 upload rounding is the only divergence source)."""
    torch_batches = list(random_batches(32, 16, num_batches=8, seed=13))
    cfg_dev = DeepSpeedConfig(base_config(micro_bs=4, grad_acc=1, stage=2),
                              world_size=8)
    cfg_off = _offload_config(gradient_accumulation_steps=1)
    e_dev = DeepSpeedEngine(SimpleModel(hidden_dim=16), cfg_dev, seed=3)
    e_off = DeepSpeedEngine(SimpleModel(hidden_dim=16), cfg_off, seed=3)
    l_dev = [float(e_dev.train_batch(b)) for b in torch_batches]
    l_off = [float(e_off.train_batch(b)) for b in torch_batches]
    np.testing.assert_allclose(l_off, l_dev, rtol=0.05, atol=0.02)


def test_offload_checkpoint_roundtrip(tmp_path):
    cfg = _offload_config()
    engine = DeepSpeedEngine(SimpleModel(hidden_dim=16), cfg, seed=5)
    for b in random_batches(cfg.train_batch_size, 16, num_batches=3,
                            seed=1):
        engine.train_batch(b)
    engine.save_checkpoint(str(tmp_path))
    master_before = jax.tree.map(np.copy, engine.state.master_params)
    mu_before = jax.tree.map(np.copy, engine.state.opt_state["mu"])

    engine2 = DeepSpeedEngine(SimpleModel(hidden_dim=16), cfg, seed=99)
    path, _ = engine2.load_checkpoint(str(tmp_path))
    assert path is not None
    for k in master_before:
        np.testing.assert_array_equal(engine2.state.master_params[k],
                                      master_before[k])
        np.testing.assert_array_equal(engine2.state.opt_state["mu"][k],
                                      mu_before[k])
    assert engine2._host_opt.opt.step_count == 3
    # and it keeps training from there
    loss = engine2.train_batch(next(random_batches(
        cfg.train_batch_size, 16, num_batches=1, seed=2)))
    assert np.isfinite(float(loss))


@pytest.mark.skipif(not NATIVE, reason="no C++ toolchain")
def test_native_fp16_conversion_bit_exact():
    """The fused fp16 copy-back must match numpy's conversion bit-for-bit,
    including subnormals, NaN (preserved, not laundered to Inf), Inf, and
    overflow."""
    import ctypes
    import warnings
    from deepspeed_tpu.ops.op_builder import load_cpu_ops
    lib = load_cpu_ops()
    rng = np.random.default_rng(0)
    x = np.concatenate([
        rng.standard_normal(20000) * rng.choice([1e-8, 1e-4, 1, 1e4], 20000),
        np.array([np.nan, np.inf, -np.inf, 0.0, -0.0, 65519.0, 65520.0,
                  1e-8, 5.96e-8, 6.1e-5])]).astype(np.float32)
    p = x.copy()
    zeros = np.zeros_like(x)
    out = np.empty(x.shape, np.uint16)
    fp = ctypes.POINTER(ctypes.c_float)
    u16 = ctypes.POINTER(ctypes.c_uint16)
    lib.ds_cpu_adam_step(
        x.size, p.ctypes.data_as(fp), zeros.ctypes.data_as(fp),
        zeros.copy().ctypes.data_as(fp), zeros.copy().ctypes.data_as(fp),
        0.0, 0.9, 0.999, 1e-8, 0.0, 1, 1, 1,
        out.ctypes.data_as(u16), 2)  # lr=0: pure conversion
    with warnings.catch_warnings():
        warnings.simplefilter("ignore")  # expected overflow-in-cast
        ref = x.astype(np.float16)
    got = out.view(np.float16)
    both_nan = np.isnan(got) & np.isnan(ref)
    np.testing.assert_array_equal(got.view(np.uint16)[~both_nan],
                                  ref.view(np.uint16)[~both_nan])


# ---------------------------------------------------------------------------
# Delayed parameter update (host tier): ZeRO-Offload paper's DPU
# ---------------------------------------------------------------------------
def _dpu_cfg(dpu: bool):
    from deepspeed_tpu.config import DeepSpeedConfig
    return DeepSpeedConfig({
        "train_micro_batch_size_per_gpu": 4,
        "gradient_accumulation_steps": 1,
        "steps_per_print": 10 ** 9,
        "bf16": {"enabled": True},
        "optimizer": {"type": "Adam", "params": {"lr": 1e-2}},
        "zero_optimization": {"stage": 2, "cpu_offload": True,
                              "offload_impl": "host",
                              "delayed_param_update": dpu},
    }, world_size=1)


def test_dpu_staleness_and_convergence():
    """Steps 0 and 1 both compute at the INITIAL params under DPU (the
    first update is applied during step 1's dispatch window), so with a
    fixed batch their losses are identical — and differ without DPU.
    Training still converges."""
    import jax
    from deepspeed_tpu.parallel import build_mesh
    from deepspeed_tpu.runtime.engine import DeepSpeedEngine
    from simple_model import SimpleModel

    mesh = build_mesh(dp=1, devices=jax.devices()[:1])
    rng = np.random.default_rng(0)
    x = rng.normal(size=(4, 16)).astype(np.float32)
    batch = (x, (0.5 * x).astype(np.float32))

    ed = DeepSpeedEngine(SimpleModel(hidden_dim=16), _dpu_cfg(True),
                         mesh=mesh, seed=3)
    l0 = float(np.asarray(ed.train_batch(batch)))
    l1 = float(np.asarray(ed.train_batch(batch)))
    assert l0 == pytest.approx(l1, abs=1e-7), "DPU steps 0/1 share params"

    en = DeepSpeedEngine(SimpleModel(hidden_dim=16), _dpu_cfg(False),
                         mesh=mesh, seed=3)
    n0 = float(np.asarray(en.train_batch(batch)))
    n1 = float(np.asarray(en.train_batch(batch)))
    assert n0 == pytest.approx(l0, abs=1e-7)  # step 0 identical
    assert abs(n1 - n0) > 1e-6, "non-DPU step 1 must use updated params"

    losses = [float(np.asarray(ed.train_batch(batch))) for _ in range(30)]
    assert losses[-1] < l0 * 0.9, (l0, losses[-5:])


def test_dpu_checkpoint_flushes_pending():
    """save_checkpoint applies the pending update; the loaded engine and
    the original continue identically from the flushed state."""
    import jax
    from deepspeed_tpu.parallel import build_mesh
    from deepspeed_tpu.runtime.engine import DeepSpeedEngine
    from simple_model import SimpleModel
    import tempfile

    mesh = build_mesh(dp=1, devices=jax.devices()[:1])
    rng = np.random.default_rng(1)
    x = rng.normal(size=(4, 16)).astype(np.float32)
    batch = (x, (0.5 * x).astype(np.float32))
    ed = DeepSpeedEngine(SimpleModel(hidden_dim=16), _dpu_cfg(True),
                         mesh=mesh, seed=3)
    for _ in range(3):
        ed.train_batch(batch)
    d = tempfile.mkdtemp()
    ed.save_checkpoint(d, tag="t")
    assert ed._dpu_pending is None  # flushed
    ref = float(np.asarray(ed.train_batch(batch)))

    e2 = DeepSpeedEngine(SimpleModel(hidden_dim=16), _dpu_cfg(True),
                         mesh=mesh, seed=9)
    path, _ = e2.load_checkpoint(d, tag="t")
    assert path is not None
    got = float(np.asarray(e2.train_batch(batch)))
    assert got == pytest.approx(ref, abs=1e-6)


def test_poisoned_host_tier_blocks_save(tmp_path):
    """The save path must honor the poison guard (advisor, round 4):
    after a mid-step pull failure the native Adam buffers are partially
    updated, so save_checkpoint must refuse — serializing them would
    turn a clean failure into silent divergence on restore."""
    cfg = _offload_config()
    engine = DeepSpeedEngine(SimpleModel(hidden_dim=16), cfg, seed=7)
    engine.train_batch(next(random_batches(
        cfg.train_batch_size, 16, num_batches=1, seed=1)))
    engine._host_opt._poisoned = ValueError("tunnel died mid-pull")
    with pytest.raises(RuntimeError, match="refusing to serialize"):
        engine.save_checkpoint(str(tmp_path))
