"""Multi-output model parity (reference: tests/unit/multi_output_model.py
and test_multi_output_model.py).

The reference returns a tuple of per-head losses from forward; the user
sums them and drives the backward/step trio.  In the fused-step design
the combination lives inside ``loss_fn`` (a pure function returning the
summed scalar) — these tests pin the same observable semantics: the
per-head cross-entropy values the reference asserts (uniform logits →
ln(num_classes)), training through both ``train_batch`` and the
forward/backward/step facade, and loss decrease on the combined
objective.
"""
import jax
import jax.numpy as jnp
import numpy as np

from deepspeed_tpu.config import DeepSpeedConfig
from deepspeed_tpu.parallel.mesh import single_device_mesh
from deepspeed_tpu.runtime.engine import DeepSpeedEngine
from deepspeed_tpu.runtime.module import TrainModule
from simple_model import base_config

HIDDEN = 16


class MultiOutputModel(TrainModule):
    """Two classification heads over one shared linear trunk; the batch is
    ((x1, y1), (x2, y2)) and the loss is the sum of both heads' CE —
    the reference's MultiOutputModel with the sum folded into loss_fn."""

    def __init__(self, weight_value: float = 0.1):
        self.weight_value = weight_value

    def init(self, rng):
        return {"w": jnp.full((HIDDEN, HIDDEN), self.weight_value,
                              jnp.float32)}

    def head_losses(self, params, batch):
        losses = []
        for x, y in batch:
            logits = (x @ params["w"].astype(x.dtype)).astype(jnp.float32)
            logp = jax.nn.log_softmax(logits, axis=-1)
            losses.append(jnp.mean(
                -jnp.take_along_axis(logp, y[:, None], axis=-1)))
        return tuple(losses)

    def loss_fn(self, params, batch, rng, train: bool = True):
        return sum(self.head_losses(params, batch))


def _batch(batch, fills=(1.0, 2.0), targets=(1, 2)):
    return tuple(
        (np.full((batch, HIDDEN), v, np.float32),
         np.full((batch,), t, np.int64))
        for v, t in zip(fills, targets))


def _engine(ga=2, micro=2):
    cfg = DeepSpeedConfig(base_config(micro_bs=micro, grad_acc=ga),
                          world_size=1)
    return DeepSpeedEngine(MultiOutputModel(), cfg,
                           mesh=single_device_mesh())


def test_per_head_losses_match_reference_value():
    """Constant weights → uniform logits → each head's CE is exactly
    ln(HIDDEN), the value the reference test asserts (2.3027 for 10
    classes; here ln(16))."""
    model = MultiOutputModel()
    params = model.init(jax.random.PRNGKey(0))
    losses = model.head_losses(params, _batch(4))
    assert len(losses) == 2
    for l in losses:
        np.testing.assert_allclose(float(l), np.log(HIDDEN), rtol=1e-5)


def test_multi_output_train_batch_decreases_sum():
    eng = _engine()
    batch = _batch(eng.train_batch_size)
    losses = [float(np.asarray(eng.train_batch(batch))) for _ in range(10)]
    np.testing.assert_allclose(losses[0], 2 * np.log(HIDDEN), rtol=1e-2)
    assert losses[-1] < losses[0]


def test_multi_output_facade_trio():
    """forward/backward/step with the tuple-structured batch: the fused
    step fires at the accumulation boundary, matching the reference's
    imperative trio contract (engine.py:779/820/956 there)."""
    eng = _engine(ga=2, micro=2)
    out = None
    for i in range(4):  # 2 accumulation windows
        loss = eng.forward(_batch(2))
        assert np.isfinite(float(np.asarray(loss)))
        eng.backward(loss)
        if eng.is_gradient_accumulation_boundary():
            out = eng.step()
    assert out is not None and np.isfinite(float(np.asarray(out)))
    assert eng.global_steps == 2
