"""Memory claims made by the ZeRO stages, asserted from real array shards.

Round-1 verdict (weak #5): the ZeRO-3 "params live sharded" claim had no
test demonstrating per-chip bytes actually drop.  Here we measure the
per-device footprint of the train state directly from each leaf's
addressable shard shapes — the ground truth GSPMD placement — across
stages 0/1/3 on the 8-device mesh, plus a seq-1024 remat+bf16 GPT-2
training step (weak #7: nothing exercised seq >= 1024 + remat + bf16 in
CI).
"""
import numpy as np
import jax
import pytest

from deepspeed_tpu.config import DeepSpeedConfig
from deepspeed_tpu.models import GPT2Config, GPT2Model
from deepspeed_tpu.parallel import build_mesh
from deepspeed_tpu.runtime.engine import DeepSpeedEngine

from simple_model import SimpleModel


def _per_device_bytes(tree) -> int:
    total = 0
    for leaf in jax.tree.leaves(tree):
        shard = leaf.addressable_shards[0]
        total += np.prod(shard.data.shape) * leaf.dtype.itemsize
    return int(total)


def _engine(stage, mesh):
    cfg = DeepSpeedConfig({
        "train_micro_batch_size_per_gpu": 2,
        "gradient_accumulation_steps": 1,
        "steps_per_print": 10 ** 9,
        "bf16": {"enabled": True},
        "optimizer": {"type": "Adam", "params": {"lr": 1e-3}},
        "zero_optimization": {"stage": stage},
    }, world_size=8)
    return DeepSpeedEngine(SimpleModel(hidden_dim=64, nlayers=4), cfg,
                           mesh=mesh)

def test_zero_stage_memory_ladder():
    mesh = build_mesh(dp=8, devices=jax.devices())
    e0 = _engine(0, mesh)
    e1 = _engine(1, mesh)
    e3 = _engine(3, mesh)

    # stage 1: master + moments sharded over data -> ~1/8 per chip
    m0 = _per_device_bytes(e0.state.master_params)
    m1 = _per_device_bytes(e1.state.master_params)
    assert m1 <= m0 // 4, (m0, m1)  # dominated by the /8-sharded matrices
    o0 = _per_device_bytes(e0.state.opt_state.mu)
    o1 = _per_device_bytes(e1.state.opt_state.mu)
    assert o1 <= o0 // 4, (o0, o1)
    # stage 3 keeps the same master sharding; the difference is the
    # COMPUTE param placement inside the step (asserted below via specs)
    specs3 = e3.zero_plan.compute_param_specs(e3.state.master_params)
    assert any("data" in str(s) for s in jax.tree.leaves(
        specs3, is_leaf=lambda x: x is not None and not isinstance(x, dict))
        if s is not None), specs3
    specs0 = e0.zero_plan.compute_param_specs(e0.state.master_params)
    assert not any("data" in str(s) for s in jax.tree.leaves(
        specs0, is_leaf=lambda x: x is not None and not isinstance(x, dict))
        if s is not None), specs0

    # all three still train
    x = np.random.default_rng(0).normal(size=(16, 64)).astype(np.float32)
    for e in (e0, e1, e3):
        loss = float(np.asarray(e.train_batch((x, (0.5 * x)))))
        assert np.isfinite(loss)


@pytest.mark.slow
def test_gpt2_seq1024_remat_bf16_trains():
    """The bench configuration's memory ingredients — seq 1024, block
    remat, bf16, scanned layers — exercised in CI (round-1 weak #7)."""
    cfg_model = GPT2Config(d_model=64, n_layer=2, n_head=4,
                           vocab_size=512, n_positions=1024,
                           remat="block", scan_layers=True)
    mesh = build_mesh(dp=8, devices=jax.devices())
    cfg = DeepSpeedConfig({
        "train_micro_batch_size_per_gpu": 1,
        "gradient_accumulation_steps": 1,
        "steps_per_print": 10 ** 9,
        "bf16": {"enabled": True},
        "optimizer": {"type": "Adam", "params": {"lr": 1e-3}},
        "zero_optimization": {"stage": 2},
    }, world_size=8)
    eng = DeepSpeedEngine(GPT2Model(cfg_model), cfg, mesh=mesh)
    toks = np.random.default_rng(0).integers(0, 512, (8, 1025),
                                             dtype=np.int32)
    l0 = float(np.asarray(eng.train_batch(toks)))
    l1 = float(np.asarray(eng.train_batch(toks)))
    assert np.isfinite(l1) and l1 < l0
