"""Unit tests for the ZeRO spec helpers (runtime/zero.py).

The sanitize rule is the no-padding contract: an axis assignment survives
only if the leaf dim is divisible by the mesh-axis size; tuple entries are
retained greedily major-to-minor (reference ZeRO likewise pads nothing and
falls back per-tensor, stage2.py partitioning)."""
import jax
import numpy as np
import pytest
from jax.sharding import PartitionSpec as P

from deepspeed_tpu.parallel import build_mesh
from deepspeed_tpu.runtime.zero import sanitize_base_spec, shard_spec_for_leaf


@pytest.fixture(scope="module")
def mesh():
    # dp=4 × tp=2 on the 8-device CPU mesh
    return build_mesh(dp=4, tp=2)


def test_divisible_entry_kept(mesh):
    assert sanitize_base_spec(P("data", None), (8, 3), mesh) == P("data",
                                                                  None)


def test_indivisible_entry_dropped(mesh):
    assert sanitize_base_spec(P("data", None), (6, 3), mesh) == P(None, None)


def test_tuple_entry_retains_divisible_major_axes(mesh):
    # dim 8 divides dp=4 but not dp*tp=8? 8 % 8 == 0 → full tuple kept
    assert sanitize_base_spec(P(("data", "model"),), (8,), mesh) == P(
        ("data", "model"))
    # dim 4 divides dp=4 but not dp*tp=8 → keep the major 'data' sub-axis
    # instead of replicating the whole dim
    assert sanitize_base_spec(P(("data", "model"),), (4,), mesh) == P(
        ("data",))
    # dim 2: 'data' (4) fails but 'model' (2) divides — the minor axis is
    # retained alone (any divisible sub-axis set is a valid placement;
    # the fallback shards as much as divisibility allows)
    assert sanitize_base_spec(P(("data", "model"),), (2,), mesh) == P(
        "model")
    # nothing divides a prime dim
    assert sanitize_base_spec(P(("data", "model"),), (3,), mesh) == P(None)


def test_rank_mismatch_raises(mesh):
    with pytest.raises(ValueError, match="more entries"):
        sanitize_base_spec(P("data", None, None), (4, 4), mesh)


def test_shard_spec_first_divisible_dim():
    assert shard_spec_for_leaf((3, 8), 4) == P(None, "data")
    assert shard_spec_for_leaf((3, 5), 4) == P(None, None)
    assert shard_spec_for_leaf((4,), 1) == P(None)


def test_shard_spec_respects_base():
    # base consumes 'data' (expert-parallel weights): nothing to add
    assert shard_spec_for_leaf((8, 16), 4, base_spec=P("data")) == P(
        "data", None)
    # base TP spec on dim 1; ZeRO takes dim 0
    assert shard_spec_for_leaf((8, 16), 4, base_spec=P(None, "model")) == P(
        "data", "model")


def test_spec_tree_structure_mismatch_raises(mesh):
    """A model whose param_partition_specs tree disagrees structurally
    with its param tree must ERROR, not silently replicate everything
    (the positional spec-to-leaf matching would mis-assign or drop all
    tensor-parallel placement)."""
    from deepspeed_tpu.runtime.zero import ZeroShardingPlan

    params = {"w": np.zeros((8, 4), np.float32),
              "b": np.zeros((4,), np.float32)}
    bad_specs = {"w": P(None, "model")}  # missing "b"
    with pytest.raises(ValueError, match="does not match"):
        ZeroShardingPlan(stage=2, mesh=mesh, base_param_specs=bad_specs,
                         params=params)
    # an extra key is just as structural a mismatch
    bad_specs2 = {"w": P(None, "model"), "b": P(None), "ghost": P()}
    with pytest.raises(ValueError, match="does not match"):
        ZeroShardingPlan(stage=2, mesh=mesh, base_param_specs=bad_specs2,
                         params=params)
    # the matching tree still works and keeps TP placement
    plan = ZeroShardingPlan(
        stage=2, mesh=mesh,
        base_param_specs={"w": P(None, "model"), "b": P(None)},
        params=params)
    assert plan.master_param_specs(params)["w"] == P("data", "model")


def test_spec_leaf_count_mismatch_raises_at_query(mesh):
    """Plans built WITHOUT params (no construction-time check) must still
    refuse positional matching against a tree with a different leaf
    count at query time."""
    from deepspeed_tpu.runtime.zero import ZeroShardingPlan

    plan = ZeroShardingPlan(
        stage=2, mesh=mesh,
        base_param_specs={"w": P(None, "model")})
    two_leaves = {"w": np.zeros((8, 4), np.float32),
                  "b": np.zeros((4,), np.float32)}
    with pytest.raises(ValueError, match="leaf count"):
        plan.master_param_specs(two_leaves)
