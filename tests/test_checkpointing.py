"""Checkpoint save/load round-trips — the analogue of the reference's
tests/unit/test_checkpointing.py (654 LoC): every ZeRO stage, fp16/bf16,
optimizer-state restore vs module-only restore, DP-resize (elastic) restore,
latest-tag resolution, client state."""
import numpy as np
import jax
import pytest

from deepspeed_tpu.config import DeepSpeedConfig
from deepspeed_tpu.parallel import build_mesh
from deepspeed_tpu.runtime.engine import DeepSpeedEngine

from simple_model import SimpleModel, base_config, random_batches

HIDDEN = 16


def _engine(stage=0, precision="bf16", dp=None, seed=0, **over):
    devices = jax.devices()
    if dp is not None:
        devices = devices[:dp]
    mesh = build_mesh(devices=devices)
    cfg = DeepSpeedConfig(
        base_config(micro_bs=2, grad_acc=1, stage=stage, precision=precision,
                    **over),
        world_size=mesh.shape["data"])
    return DeepSpeedEngine(SimpleModel(hidden_dim=HIDDEN), cfg, mesh=mesh,
                           seed=seed)


def _train(eng, steps=3, seed=0):
    losses = []
    for batch in random_batches(eng.train_batch_size, HIDDEN,
                                num_batches=steps, seed=seed):
        losses.append(float(eng.train_batch(batch)))
    return losses


def _state_allclose(a, b):
    fa, fb = jax.tree.leaves(a), jax.tree.leaves(b)
    assert len(fa) == len(fb)
    for x, y in zip(fa, fb):
        np.testing.assert_allclose(
            np.asarray(x, dtype=np.float32), np.asarray(y, dtype=np.float32),
            rtol=0, atol=0)


@pytest.mark.parametrize("stage", [0, 1, 2, 3])
def test_roundtrip_exact(stage, tmp_path):
    eng = _engine(stage=stage)
    _train(eng, steps=3)
    eng.save_checkpoint(str(tmp_path), tag="t3")

    # a fresh engine with different seed → different params until load
    eng2 = _engine(stage=stage, seed=123)
    path, _ = eng2.load_checkpoint(str(tmp_path), tag="t3")
    assert path is not None
    _state_allclose(eng.state.master_params, eng2.state.master_params)
    _state_allclose(eng.state.opt_state, eng2.state.opt_state)
    assert eng2.global_steps == 3
    # rng restored → dropout masks match an uninterrupted run even though
    # eng2 was constructed with a different seed
    np.testing.assert_array_equal(np.asarray(eng.state.rng),
                                  np.asarray(eng2.state.rng))

    # training must continue identically (bitwise same batches → same loss)
    l1 = _train(eng, steps=2, seed=7)
    l2 = _train(eng2, steps=2, seed=7)
    assert l1 == l2


def test_fp16_scaler_restored(tmp_path):
    over = {"fp16": {"enabled": True, "initial_scale_power": 8}}
    eng = _engine(stage=0, precision="fp16", **over)
    _train(eng, steps=2)
    scale_before = eng.get_loss_scale()
    eng.save_checkpoint(str(tmp_path))

    eng2 = _engine(stage=0, precision="fp16", seed=9, **over)
    eng2.load_checkpoint(str(tmp_path))
    assert eng2.get_loss_scale() == scale_before
    assert eng2.get_skipped_steps() == eng.get_skipped_steps()


@pytest.mark.parametrize("save_dp,load_dp", [(4, 2), (2, 4), (8, 1)])
def test_elastic_dp_resize(save_dp, load_dp, tmp_path):
    """ZeRO checkpoints load at a different DP world size (reference
    stage2.py:1712-1778 merge + repartition)."""
    eng = _engine(stage=2, dp=save_dp)
    _train(eng, steps=2)
    eng.save_checkpoint(str(tmp_path), tag="resize")

    eng2 = _engine(stage=2, dp=load_dp, seed=5)
    path, _ = eng2.load_checkpoint(str(tmp_path), tag="resize")
    assert path is not None
    _state_allclose(eng.state.master_params, eng2.state.master_params)
    # continues training fine at the new size
    losses = _train(eng2, steps=2, seed=11)
    assert np.isfinite(losses).all()


def test_zero_stage_change_on_load(tmp_path):
    """Stage-2 checkpoint restores into a stage-0 (replicated) engine and
    vice versa — sharding is load-time policy, not file layout."""
    eng = _engine(stage=2)
    _train(eng, steps=2)
    eng.save_checkpoint(str(tmp_path), tag="s2")

    eng0 = _engine(stage=0, seed=3)
    eng0.load_checkpoint(str(tmp_path), tag="s2")
    _state_allclose(eng.state.master_params, eng0.state.master_params)


def test_module_only_load(tmp_path):
    eng = _engine(stage=1)
    _train(eng, steps=3)
    eng.save_checkpoint(str(tmp_path), tag="m")

    eng2 = _engine(stage=1, seed=77)
    path, _ = eng2.load_checkpoint(str(tmp_path), tag="m",
                                   load_module_only=True)
    assert path is not None
    # weights match only to compute-dtype precision (fp16-cast restore)
    for a, b in zip(jax.tree.leaves(eng.state.master_params),
                    jax.tree.leaves(eng2.state.master_params)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   atol=1e-2, rtol=1e-2)
    # optimizer state was re-initialized, counters restored
    assert eng2.global_steps == 3


def test_latest_tag_and_client_state(tmp_path):
    eng = _engine()
    _train(eng, steps=1)
    eng.save_checkpoint(str(tmp_path), tag="a",
                        client_state={"epoch": 1})
    _train(eng, steps=1)
    eng.save_checkpoint(str(tmp_path), tag="b",
                        client_state={"epoch": 2})

    eng2 = _engine(seed=42)
    path, client = eng2.load_checkpoint(str(tmp_path))  # tag=None → latest
    assert path.endswith("b")
    assert client == {"epoch": 2}
    assert eng2.global_steps == 2


def test_missing_checkpoint_returns_none(tmp_path):
    """tag=None on an empty dir is a fresh run → (None, None).  An
    EXPLICIT tag that doesn't exist or doesn't verify must RAISE with the
    path, never masquerade as "nothing to load" (ISSUE 5 satellite)."""
    from deepspeed_tpu.runtime.resilience import (
        CheckpointCorruptError, CheckpointMissingError)
    eng = _engine()
    path, client = eng.load_checkpoint(str(tmp_path))
    assert path is None and client is None
    # arm 1: the tag directory does not exist at all
    with pytest.raises(CheckpointMissingError, match="nope"):
        eng.load_checkpoint(str(tmp_path), tag="nope")
    # arm 2: the tag directory exists but has no meta.json (a crashed or
    # partial save) — previously indistinguishable from "fresh run"
    (tmp_path / "half").mkdir()
    (tmp_path / "half" / "junk.npy").write_bytes(b"x")
    with pytest.raises(CheckpointCorruptError, match="half"):
        eng.load_checkpoint(str(tmp_path), tag="half")


@pytest.mark.slow
def test_pipeline_engine_roundtrip(tmp_path):
    from deepspeed_tpu.pipe.engine import PipelineEngine
    from deepspeed_tpu.models import GPT2Config
    from deepspeed_tpu.models.gpt2_pipe import (build_gpt2_pipe,
                                                split_gpt2_batch)

    mesh = build_mesh(pp=2)
    cfg_model = GPT2Config(vocab_size=128, n_positions=32, d_model=32,
                           n_layer=2, n_head=2, remat=None)
    cfg = DeepSpeedConfig({
        "train_micro_batch_size_per_gpu": 1,
        "gradient_accumulation_steps": 2,
        "steps_per_print": 10 ** 9,
        "bf16": {"enabled": True},
        "zero_optimization": {"stage": 1},
        "optimizer": {"type": "Adam", "params": {"lr": 1e-3}},
    }, world_size=mesh.shape["data"])

    def make():
        pm = build_gpt2_pipe(cfg_model, num_stages=2)
        return PipelineEngine(pm, cfg, mesh)

    eng = make()
    toks = np.random.default_rng(0).integers(
        0, 128, (cfg.train_batch_size, 17), dtype=np.int32)
    eng.train_batch(split_gpt2_batch(toks))
    eng.save_checkpoint(str(tmp_path), tag="pipe")

    eng2 = make()
    path, _ = eng2.load_checkpoint(str(tmp_path), tag="pipe")
    assert path is not None
    _state_allclose(eng.state.master_params, eng2.state.master_params)
    l1 = float(eng.train_batch(split_gpt2_batch(toks)))
    l2 = float(eng2.train_batch(split_gpt2_batch(toks)))
    assert l1 == l2


def test_lamb_optimizer_state_roundtrip(tmp_path):
    """LAMB (the reference's unfused-wrapper optimizer) must restore its
    moments exactly (reference test_checkpointing covers every optimizer
    wrapper)."""
    eng = _engine(stage=1,
                  optimizer={"type": "lamb", "params": {"lr": 1e-2}})
    _train(eng, steps=3)
    eng.save_checkpoint(str(tmp_path), tag="t")

    eng2 = _engine(stage=1,
                   optimizer={"type": "lamb", "params": {"lr": 1e-2}},
                   seed=99)
    eng2.load_checkpoint(str(tmp_path), tag="t")
    _state_allclose(eng.state.master_params, eng2.state.master_params)
    _state_allclose(eng.state.opt_state.mu, eng2.state.opt_state.mu)
    _state_allclose(eng.state.opt_state.nu, eng2.state.opt_state.nu)
    assert int(np.asarray(eng2.state.opt_state.count)) == 3


def test_lr_schedule_continuity_across_restore(tmp_path):
    """The scheduler is a pure function of the restored step count, so the
    post-restore lr must continue where the saved run left off (reference:
    scheduler checkpoint tests in test_checkpointing.py)."""
    sched = {"scheduler": {"type": "WarmupLR",
                           "params": {"warmup_min_lr": 0.0,
                                      "warmup_max_lr": 0.01,
                                      "warmup_num_steps": 10}}}
    eng = _engine(stage=0, **sched)
    _train(eng, steps=4)
    lr_before = float(eng.last_metrics.lr)
    eng.save_checkpoint(str(tmp_path), tag="t")

    eng2 = _engine(stage=0, seed=7, **sched)
    eng2.load_checkpoint(str(tmp_path), tag="t")
    _train(eng2, steps=1, seed=42)
    lr_after = float(eng2.last_metrics.lr)
    # warmup is monotonically increasing: step-5 lr must sit above the
    # step-4 lr and below max — i.e. it continued, not restarted
    assert lr_before < lr_after < 0.01


def test_size_preserving_layout_reshape_on_load(tmp_path):
    """A leaf whose dims were refactored but whose element count (and
    row-major value order) is unchanged loads via a logged reshape — the
    shim that keeps pre-relayout checkpoints (e.g. qkv [.., d, 3d] →
    [.., d, 3, d]) loading after a layout evolution."""
    import glob
    import json
    import os

    eng = _engine(stage=0, seed=3)
    _train(eng, 2)
    eng.save_checkpoint(str(tmp_path), tag="t0")
    ref = float(eng.train_batch(next(iter(random_batches(
        eng.train_batch_size, HIDDEN, num_batches=1, seed=9)))))

    # simulate an OLD checkpoint: flatten one 2-D weight's dims on disk
    meta_path = os.path.join(str(tmp_path), "t0", "model",
                             "manifest.json")
    meta = json.load(open(meta_path))
    key, victim = next((k, e) for k, e in meta.items()
                       if len(e.get("shape", [])) == 2
                       and np.prod(e["shape"]) > 1)
    old_shape = list(victim["shape"])
    base = os.path.dirname(meta_path)
    arr = np.load(os.path.join(base, victim["file"]), allow_pickle=False)
    np.save(os.path.join(base, victim["file"]),
            arr.reshape(-1))                      # [a, b] -> [a*b]
    victim["shape"] = [int(np.prod(old_shape))]
    json.dump(meta, open(meta_path, "w"))

    e2 = _engine(stage=0, seed=11)
    path, _ = e2.load_checkpoint(str(tmp_path), tag="t0")
    assert path is not None
    got = float(e2.train_batch(next(iter(random_batches(
        e2.train_batch_size, HIDDEN, num_batches=1, seed=9)))))
    assert got == pytest.approx(ref, abs=1e-5)


def test_dpu_dispatch_counter_restores_from_global_steps(tmp_path):
    """The xla-tier DPU rng stream is seeded from global_steps on
    restore, NOT opt_state.count: count excludes overflow-skipped steps,
    and seeding from it would replay dropout seeds already consumed
    before the save (advisor finding, round 3)."""
    def dpu_engine():
        return _engine(
            stage=2, precision="bf16",
            zero_optimization={"stage": 2, "cpu_offload": True,
                               "offload_impl": "xla",
                               "delayed_param_update": True})

    eng = dpu_engine()
    _train(eng, steps=3)
    assert eng._xla_dpu_dispatch == 3
    # simulate a run that overflow-skipped one step: global_steps counts
    # every dispatch, opt count only the applied ones
    eng.skipped_steps = 1
    eng.save_checkpoint(str(tmp_path), tag="t")
    applied = int(np.asarray(jax.device_get(eng.state.opt_state.count)))

    eng2 = dpu_engine()
    eng2.load_checkpoint(str(tmp_path), tag="t")
    assert eng2._xla_dpu_dispatch == 3  # == global_steps, NOT applied
    assert eng2._xla_dpu_dispatch >= applied
    # and the stream continues without error
    _train(eng2, steps=2, seed=7)
    assert eng2._xla_dpu_dispatch == 5


def test_cross_tier_offload_restore(tmp_path):
    """The optimizer plane is saved as ONE canonical FusedAdamState
    shape by every tier, so checkpoints cross freely between the xla
    offload tier, the host (C++ Adam) tier, and plain device engines —
    the reference's merge/re-partition elasticity extended across
    offload implementations."""
    def eng(impl, seed):
        zero = {"stage": 2}
        if impl:
            zero.update({"cpu_offload": True, "offload_impl": impl})
        return _engine(stage=2, seed=seed, dp=1, zero_optimization=zero)

    batch = next(random_batches(2, HIDDEN, num_batches=1, seed=0))
    for src, dst in (("xla", "host"), ("host", "xla"),
                     (None, "host"), ("host", None)):
        e1 = eng(src, seed=3)
        for _ in range(3):
            e1.train_batch(batch)
        d = str(tmp_path / f"{src}-{dst}")
        e1.save_checkpoint(d, tag="t")
        ref = float(np.asarray(e1.train_batch(batch)))
        e2 = eng(dst, seed=9)
        path, _ = e2.load_checkpoint(d, tag="t")
        assert path is not None, (src, dst)
        got = float(np.asarray(e2.train_batch(batch)))
        assert abs(got - ref) < 2e-4, (src, dst, got, ref)


def test_offload_elastic_dp_resize(tmp_path):
    """ZeRO-Offload (xla tier) checkpoints resize across DP world sizes
    like plain ZeRO ones — the flat host pieces are canonicalized to
    per-parameter trees at save, so the dp=4 staging loads into a dp=2
    engine's pieces (reference stage2.py:1712-1778 merge+repartition,
    across the offload boundary)."""
    zero = {"stage": 2, "cpu_offload": True, "offload_impl": "xla"}
    eng = _engine(stage=2, dp=4, zero_optimization=zero)
    _train(eng, steps=2)
    eng.save_checkpoint(str(tmp_path), tag="oresize")
    saved_master = eng._canonical_state()[0]

    eng2 = _engine(stage=2, dp=2, seed=5, zero_optimization=zero)
    path, _ = eng2.load_checkpoint(str(tmp_path), tag="oresize")
    assert path is not None
    _state_allclose(saved_master, eng2._canonical_state()[0])
    losses = _train(eng2, steps=2, seed=11)
    assert np.isfinite(losses).all()
