"""Serving fleet: wire framing, the router's JSQ/failover/autoscale
semantics (fake socket replicas — the elastic supervisor's test
idiom), heartbeat gauge payloads, fleet diagnose correlation, and the
subprocess e2e bars (single-replica parity vs a bare ServeEngine;
replica-kill failover) — docs/serving.md "serving fleet".
"""
import json
import os
import socket
import subprocess
import time

import numpy as np
import pytest

from deepspeed_tpu.config import DeepSpeedConfigError
from deepspeed_tpu.config.config import DeepSpeedFleetConfig
from deepspeed_tpu.inference.fleet import (FleetClosedError,
                                           FleetGiveUpError,
                                           FleetRouter, ReplicaFailure)
from deepspeed_tpu.inference.wire import (BinaryFrame, FrameReader,
                                          WireError, drain_socket,
                                          encode_binary_frame,
                                          encode_frame,
                                          send_binary_frame,
                                          send_frame)
from deepspeed_tpu.runtime.stages import reset_fault_injection
from deepspeed_tpu.telemetry.heartbeat import (HeartbeatWriter,
                                               StragglerMonitor,
                                               beat_ages,
                                               read_heartbeats)

_CHAOS_ENVS = ("DS_STAGE_FAULT", "DS_STAGE_DELAY_S")


@pytest.fixture(autouse=True)
def _clean_faults(monkeypatch):
    for env in _CHAOS_ENVS:
        monkeypatch.delenv(env, raising=False)
    reset_fault_injection()
    yield
    reset_fault_injection()


# ---------------------------------------------------------------------------
# wire framing
# ---------------------------------------------------------------------------


def test_wire_roundtrip_and_partial_feeds():
    frames = [{"kind": "submit", "rid": 1, "prompt": [1, 2, 3]},
              {"kind": "token", "rid": 1, "toks": [7]},
              {"kind": "done", "rid": 1, "reason": "length"}]
    blob = b"".join(encode_frame(f) for f in frames)
    # byte-by-byte feeding must reassemble every frame exactly
    r = FrameReader()
    out = []
    for i in range(len(blob)):
        out.extend(r.feed(blob[i:i + 1]))
    assert out == frames
    # one big feed yields them all at once
    r2 = FrameReader()
    assert r2.feed(blob) == frames


def test_wire_corrupt_stream_raises_typed():
    r = FrameReader()
    # oversized length prefix = corrupt stream, not a real frame
    with pytest.raises(WireError):
        r.feed(b"\xff\xff\xff\xff")
    # valid length, non-JSON payload
    import struct
    r2 = FrameReader()
    with pytest.raises(WireError):
        r2.feed(struct.pack(">I", 4) + b"\x00\x01\x02\x03")
    # valid JSON but not an object
    r3 = FrameReader()
    with pytest.raises(WireError):
        r3.feed(struct.pack(">I", 3) + b"[1]")


def test_wire_socket_pair_drain():
    a, b = socket.socketpair()
    try:
        send_frame(a, {"kind": "hello", "replica": 0})
        send_frame(a, {"kind": "token", "rid": 2, "toks": [1, 2]})
        reader = FrameReader()
        frames, closed = drain_socket(b, reader)
        assert [f["kind"] for f in frames] == ["hello", "token"]
        assert not closed
        a.close()
        frames, closed = drain_socket(b, reader)
        assert frames == [] and closed
    finally:
        b.close()


# ---------------------------------------------------------------------------
# wire binary page frames (KV migration transport)
# ---------------------------------------------------------------------------


def test_wire_binary_frame_torn_read_resumption():
    """A binary page frame torn ANYWHERE — including mid page
    payload — reassembles byte-identically, interleaved with JSON
    frames on the same stream."""
    payload = bytes(range(256)) * 16
    blob = (encode_frame({"kind": "migrate_out", "rid": 7, "pages": 1})
            + encode_binary_frame({"kind": "page", "rid": 7, "seq": 0},
                                  payload)
            + encode_frame({"kind": "done", "rid": 7}))
    r = FrameReader()
    out = []
    for i in range(len(blob)):       # worst-case torn reads
        out.extend(r.feed(blob[i:i + 1]))
    assert [f.get("kind") for f in out] == ["migrate_out", "page",
                                            "done"]
    bf = out[1]
    assert isinstance(bf, BinaryFrame)
    assert bf.payload == payload
    assert bf.get("seq") == 0 and bf.kind == "page"
    # and in one gulp
    out2 = FrameReader().feed(blob)
    assert isinstance(out2[1], BinaryFrame)
    assert out2[1].payload == payload


def test_wire_binary_frame_crc_mismatch_is_connection_fatal():
    """A flipped payload byte fails the CRC with a typed WireError —
    the connection dies, it never resyncs (a corrupt KV page must not
    be silently adopted)."""
    good = bytearray(encode_binary_frame(
        {"kind": "page", "rid": 1, "seq": 0}, b"\x55" * 128))
    good[-10] ^= 0x01                # flip one payload bit
    r = FrameReader()
    with pytest.raises(WireError, match="CRC"):
        r.feed(bytes(good))
    # corrupt header length inside a CRC-valid body is also typed
    import struct as _struct
    import zlib as _zlib
    body = _struct.pack(">I", 9999) + b"xx"
    body += _struct.pack(">I", _zlib.crc32(body) & 0xFFFFFFFF)
    with pytest.raises(WireError, match="overruns"):
        FrameReader().feed(
            _struct.pack(">I", 0x80000000 | len(body)) + body)


def test_wire_binary_and_json_interleave_on_one_socket():
    a, b = socket.socketpair()
    try:
        send_frame(a, {"kind": "migrate_out", "rid": 3, "pages": 2})
        send_binary_frame(a, {"kind": "page", "rid": 3, "seq": 0},
                          b"A" * 64)
        send_frame(a, {"kind": "token", "rid": 9, "toks": [1]})
        send_binary_frame(a, {"kind": "page", "rid": 3, "seq": 1},
                          b"B" * 64)
        reader = FrameReader()
        frames, closed = drain_socket(b, reader)
        assert not closed
        assert [f.get("kind") for f in frames] == [
            "migrate_out", "page", "token", "page"]
        assert frames[1].payload == b"A" * 64
        assert frames[3].payload == b"B" * 64
        assert frames[2] == {"kind": "token", "rid": 9, "toks": [1]}
    finally:
        a.close()
        b.close()


# ---------------------------------------------------------------------------
# heartbeat serving gauges (the fleet's JSQ payload)
# ---------------------------------------------------------------------------


def test_heartbeat_extra_gauges_roundtrip_and_core_keys_win(tmp_path):
    w = HeartbeatWriter(str(tmp_path), process_index=3)
    assert w.beat(7, step_s=0.5, extra={
        "serve_active_slots": 2, "serve_queue_depth": 5,
        "serve_free_pages": 11, "spec_accept_ratio": 0.75,
        # a hostile gauge must never mask liveness: core keys win
        "time": 1.0, "step": 999})
    beats = read_heartbeats(str(tmp_path))
    (rec,) = beats.values()
    assert rec["serve_active_slots"] == 2
    assert rec["serve_queue_depth"] == 5
    assert rec["serve_free_pages"] == 11
    assert rec["spec_accept_ratio"] == 0.75
    assert rec["step"] == 7          # core beat fields won
    assert rec["time"] > 1e9
    # richer schema tolerated by every existing reader
    ages = beat_ages(beats)
    assert list(ages) and all(a >= 0 for a in ages.values())
    rep = StragglerMonitor(ratio=2.0).update(beats)
    assert rep["hosts"] == 1


# ---------------------------------------------------------------------------
# fleet config block
# ---------------------------------------------------------------------------


def test_fleet_config_defaults_and_validation():
    cfg = DeepSpeedFleetConfig({})
    assert (cfg.replicas, cfg.min_replicas, cfg.max_replicas) == (1, 1, 4)
    assert cfg.slo_p99_s == 2.0
    cfg = DeepSpeedFleetConfig({"fleet": {"replicas": 2,
                                          "max_replicas": 8,
                                          "slo_p99_s": 0.5}})
    assert cfg.replicas == 2 and cfg.slo_p99_s == 0.5
    for bad in ({"replicas": 0}, {"min_replicas": 3, "max_replicas": 2},
                {"replicas": 9}, {"slo_p99_s": 0},
                {"scale_up_window_s": -1}, {"max_restarts": -1},
                {"heartbeat_timeout_s": -2}, {"replicas": True},
                {"backoff_base_s": "fast"}):
        with pytest.raises(DeepSpeedConfigError):
            DeepSpeedFleetConfig({"fleet": bad})


def test_fleet_roles_config_validation():
    cfg = DeepSpeedFleetConfig(
        {"fleet": {"roles": {"prefill": 1, "decode": 2},
                   "max_replicas": 4}})
    assert cfg.roles == {"prefill": 1, "decode": 2}
    assert cfg.replicas == 3          # roles size the fleet
    # an explicit matching replicas count is redundant but legal
    cfg = DeepSpeedFleetConfig(
        {"fleet": {"roles": {"prefill": 1, "mixed": 1},
                   "replicas": 2}})
    assert cfg.replicas == 2
    assert DeepSpeedFleetConfig({}).roles is None
    for bad in (
            # replicas contradicting the role sum
            {"roles": {"prefill": 1, "decode": 1}, "replicas": 3},
            # prefill with nowhere to migrate to
            {"roles": {"prefill": 2}},
            {"roles": {"prefill": 1, "frontend": 1}},  # unknown role
            {"roles": {}},                             # empty map
            {"roles": {"decode": 0}},                  # count < 1
            {"roles": "prefill"},                      # not a dict
            {"slo_ttft_s": -1},
            {"slo_tpot_s": "fast"}):
        with pytest.raises(DeepSpeedConfigError):
            DeepSpeedFleetConfig({"fleet": bad})


# ---------------------------------------------------------------------------
# router semantics over fake socket replicas (the launch_fn test seam)
# ---------------------------------------------------------------------------


class FakeProc:
    """Popen-shaped handle the router supervises."""

    def __init__(self):
        self.rc = None
        self.terminated = False

    def poll(self):
        return self.rc

    def terminate(self):
        self.terminated = True
        if self.rc is None:
            self.rc = -15

    def kill(self):
        self.rc = -9

    def wait(self, timeout=None):
        if self.rc is None:
            raise subprocess.TimeoutExpired("fake", timeout or 0)
        return self.rc


class FakeReplica:
    """A scripted replica: real socket to the router, test-driven
    frames."""

    def __init__(self, addr, replica_id):
        self.id = replica_id
        self.proc = FakeProc()
        self.sock = socket.create_connection(addr, timeout=5.0)
        self.sock.settimeout(5.0)
        self.reader = FrameReader()
        self.submits = []
        self.saw_shutdown = False
        send_frame(self.sock, {"kind": "hello", "replica": replica_id,
                               "pid": 0})

    def pump(self):
        frames, _ = drain_socket(self.sock, self.reader)
        self.submits.extend(f for f in frames
                            if f.get("kind") == "submit")
        if any(f.get("kind") == "shutdown" for f in frames):
            self.saw_shutdown = True
        return frames

    def admit(self, rid):
        send_frame(self.sock, {"kind": "admit", "rid": rid})

    def tokens(self, rid, toks):
        send_frame(self.sock, {"kind": "token", "rid": rid,
                               "toks": list(toks)})

    def done(self, rid, reason="length", total=None):
        send_frame(self.sock, {"kind": "done", "rid": rid,
                               "reason": reason,
                               "tokens_total": total})

    def error(self, rid, err="boom"):
        send_frame(self.sock, {"kind": "error", "rid": rid,
                               "error": err})

    def die(self, rc=13):
        self.proc.rc = rc
        self.sock.close()


class Fleet:
    """Router + fake-replica harness with a fake autoscale clock."""

    def __init__(self, tmp_path, fleet=None):
        self.clock = [1000.0]
        self.fakes = {}
        # term_grace_s small: fake procs never exit on their own, and
        # close()'s graceful-drain window would otherwise wait it out
        cfg = {"fleet": {"heartbeat_timeout_s": 0.0,
                         "backoff_base_s": 0.01,
                         "term_grace_s": 0.2,
                         "spawn_timeout_s": 1e9, **(fleet or {})}}
        self.router = FleetRouter(
            cfg, fleet_dir=str(tmp_path / "fleet"),
            spawn_fn=self._spawn, now_fn=lambda: self.clock[0])

    def _spawn(self, replica_id, attempt):
        fake = FakeReplica(self.router.addr, replica_id)
        self.fakes[replica_id] = fake
        return fake.proc

    def start(self):
        self.router.start()
        return self

    def pump(self, n=6):
        """A few router+fake iterations — localhost frames land fast,
        but never assume a single poll saw them."""
        for _ in range(n):
            self.router.poll(0.01)
            for f in self.fakes.values():
                if f.proc.rc is None:
                    f.pump()

    def advance(self, dt):
        self.clock[0] += dt


def test_jsq_tie_breaks_deterministically_lowest_id(tmp_path):
    fl = Fleet(tmp_path, {"replicas": 2, "max_replicas": 2}).start()
    try:
        reqs = [fl.router.submit([1, 2], max_new_tokens=4)
                for _ in range(4)]
        deadline = time.monotonic() + 5
        while (len(fl.fakes[0].submits) + len(fl.fakes[1].submits) < 4
               and time.monotonic() < deadline):
            fl.pump(1)
        # equal loads tie-break to the LOWEST replica id, alternating
        # as outstanding counts grow: r0 gets rids 1,3 — r1 gets 2,4
        assert [f["rid"] for f in fl.fakes[0].submits] == [1, 3]
        assert [f["rid"] for f in fl.fakes[1].submits] == [2, 4]
        assert [r.replica for r in reqs] == [0, 1, 0, 1]
    finally:
        fl.router.close()


def test_jsq_reads_heartbeat_queue_gauges(tmp_path):
    fl = Fleet(tmp_path, {"replicas": 2, "max_replicas": 2}).start()
    try:
        # replica 0 reports a deep engine-side queue via its beat: the
        # next admission must go to replica 1 despite the id tie
        w = HeartbeatWriter(fl.router.fleet_dir, process_index=0)
        w.beat(1, extra={"serve_queue_depth": 5,
                         "serve_active_slots": 2})
        fl.router._last_beats_read = 0.0  # bypass the read throttle
        fl.router.poll(0.01)
        assert fl.router._beats[0]["serve_queue_depth"] == 5
        fl.router.submit([1], max_new_tokens=2)
        deadline = time.monotonic() + 5
        while not fl.fakes[1].submits and time.monotonic() < deadline:
            fl.pump(1)
        assert [f["rid"] for f in fl.fakes[1].submits] == [1]
        assert not fl.fakes[0].submits
    finally:
        fl.router.close()


def test_failover_queued_vs_midstream(tmp_path):
    """THE failover contract: a dead replica's queued-but-unstarted
    requests re-dispatch (order preserved, completing normally); the
    one whose tokens already streamed fails typed ReplicaFailure."""
    fl = Fleet(tmp_path, {"replicas": 2, "max_replicas": 2}).start()
    try:
        r1 = fl.router.submit([1], max_new_tokens=4)
        r2 = fl.router.submit([2], max_new_tokens=4)
        r3 = fl.router.submit([3], max_new_tokens=4)
        deadline = time.monotonic() + 5
        while len(fl.fakes[0].submits) < 2 and \
                time.monotonic() < deadline:
            fl.pump(1)
        assert [f["rid"] for f in fl.fakes[0].submits] == [1, 3]
        # rid 1 starts streaming on replica 0; rid 3 stays queued there
        fl.fakes[0].admit(1)
        fl.fakes[0].tokens(1, [42, 43])
        fl.pump()
        assert r1.started and r1.tokens == [42, 43]
        assert not r3.started
        fl.fakes[0].die(13)
        deadline = time.monotonic() + 5
        while not r1.done.is_set() and time.monotonic() < deadline:
            fl.pump(1)
        # mid-stream: typed failure naming the replica
        assert isinstance(r1.error, ReplicaFailure)
        assert r1.error.replica == 0
        with pytest.raises(ReplicaFailure):
            r1.result(timeout=1)
        # queued-but-unstarted: failed over to replica 1, completes
        deadline = time.monotonic() + 5
        while len(fl.fakes[1].submits) < 2 and \
                time.monotonic() < deadline:
            fl.pump(1)
        assert [f["rid"] for f in fl.fakes[1].submits] == [2, 3]
        assert r3.failovers == 1 and r3.error is None
        fl.fakes[1].admit(2)
        fl.fakes[1].tokens(2, [7])
        fl.fakes[1].done(2, total=1)
        fl.fakes[1].admit(3)
        fl.fakes[1].tokens(3, [8, 9])
        fl.fakes[1].done(3, total=2)
        fl.pump()
        assert r2.result(timeout=5) == [7]
        assert r3.result(timeout=5) == [8, 9]
        # a completed request resets the give-up budget
        assert fl.router._consec_failures == 0
    finally:
        fl.router.close()


def test_replica_error_frame_fails_one_request_only(tmp_path):
    """Per-request isolation (the engine's Orca discipline, surfaced
    through the wire): an ``error`` frame fails exactly that request —
    the replica keeps its slot pool and the fleet keeps routing."""
    fl = Fleet(tmp_path, {"replicas": 1, "max_replicas": 1}).start()
    try:
        r1 = fl.router.submit([1], max_new_tokens=2)
        r2 = fl.router.submit([2], max_new_tokens=2)
        deadline = time.monotonic() + 5
        while len(fl.fakes[0].submits) < 2 and \
                time.monotonic() < deadline:
            fl.pump(1)
        fl.fakes[0].error(1, "ValueError('empty prompt')")
        fl.fakes[0].admit(2)
        fl.fakes[0].tokens(2, [5])
        fl.fakes[0].done(2, total=1)
        fl.pump()
        assert r1.error is not None and "empty prompt" in str(r1.error)
        assert r2.result(timeout=5) == [5]
        assert 0 in fl.router.replicas  # replica survived
    finally:
        fl.router.close()


def test_autoscale_up_on_sustained_breach_with_hysteresis_and_max(
        tmp_path):
    fl = Fleet(tmp_path, {"replicas": 1, "max_replicas": 3,
                          "slo_p99_s": 1.0, "scale_up_window_s": 10.0,
                          "scale_down_window_s": 1e6}).start()
    try:
        # a request nobody admits: its age IS the breach signal (a
        # wedged fleet produces no admission samples at all)
        fl.router.submit([1], max_new_tokens=2)
        fl.pump()
        fl.advance(2.0)          # older than the SLO -> breach begins
        fl.pump(1)
        assert len(fl.router.replicas) == 1  # breach not sustained yet
        fl.advance(5.0)
        fl.pump(1)
        assert len(fl.router.replicas) == 1  # still inside the window
        fl.advance(6.0)          # breach sustained > scale_up_window_s
        fl.pump(1)
        assert len(fl.router.replicas) == 2  # scaled up
        # hysteresis: the scale event reset the breach clock — no
        # second spawn until ANOTHER full window of sustained breach
        fl.advance(3.0)
        fl.pump(2)
        assert len(fl.router.replicas) == 2
        fl.advance(11.0)
        fl.pump(2)
        assert len(fl.router.replicas) == 3
        # max clamp: breach may rage on, the fleet stays at max
        fl.advance(30.0)
        fl.pump(3)
        assert len(fl.router.replicas) == 3
    finally:
        fl.router.close()


def test_autoscale_down_on_sustained_slack_with_min_clamp(tmp_path):
    fl = Fleet(tmp_path, {"replicas": 2, "min_replicas": 1,
                          "max_replicas": 2, "slo_p99_s": 1.0,
                          "scale_up_window_s": 10.0,
                          "scale_down_window_s": 20.0}).start()
    try:
        # serve one request quickly: a healthy, then idle, fleet
        r = fl.router.submit([1], max_new_tokens=2)
        deadline = time.monotonic() + 5
        while not fl.fakes[0].submits and time.monotonic() < deadline:
            fl.pump(1)
        fl.fakes[0].admit(1)
        fl.fakes[0].tokens(1, [3])
        fl.fakes[0].done(1, total=1)
        fl.pump()
        assert r.result(timeout=5) == [3]
        # slack begins; not sustained yet -> no retire
        fl.advance(25.0)   # ages the wait sample out of both windows
        fl.pump(1)
        assert len(fl.router.replicas) == 2
        fl.advance(21.0)   # slack sustained > scale_down_window_s
        fl.pump(1)
        draining = [rep for rep in fl.router.replicas.values()
                    if rep.state == "draining"]
        assert [rep.id for rep in draining] == [1]  # highest id drains
        # the drained retiree exits 0 and is reaped
        deadline = time.monotonic() + 5
        while 1 in fl.router.replicas and time.monotonic() < deadline:
            fl.fakes[1].pump()
            if fl.fakes[1].saw_shutdown:
                fl.fakes[1].proc.rc = 0
            fl.router.poll(0.01)
        assert sorted(fl.router.replicas) == [0]
        # min clamp: slack forever, but the floor holds
        fl.advance(50.0)
        fl.pump(2)
        fl.advance(50.0)
        fl.pump(2)
        assert sorted(fl.router.replicas) == [0]
    finally:
        fl.router.close()


def test_give_up_typed_after_consecutive_spawn_failures(tmp_path):
    calls = []

    def bad_spawn(replica_id, attempt):
        calls.append(replica_id)
        raise RuntimeError("no capacity")

    router = FleetRouter(
        {"fleet": {"replicas": 1, "max_restarts": 2,
                   "backoff_base_s": 0.01, "backoff_max_s": 0.02}},
        fleet_dir=str(tmp_path / "fleet"), spawn_fn=bad_spawn)
    queued = router.submit([1], max_new_tokens=2)
    with pytest.raises(FleetGiveUpError) as ei:
        router.start()
    assert ei.value.restarts == 3          # budget 2 -> third strike
    assert "no capacity" in ei.value.last_failure
    assert len(calls) == 3
    # the give-up failed every in-flight request typed and dumped the
    # supervisor flight record for the post-mortem
    assert isinstance(queued.error, FleetGiveUpError)
    rec_path = os.path.join(router.fleet_dir,
                            "flightrec_supervisor.json")
    with open(rec_path) as f:
        rec = json.load(f)
    assert rec["stages"]["fleet"]["events"]
    # closed: further submits are refused
    with pytest.raises(RuntimeError):
        router.submit([1])


def test_spawn_timeout_counts_as_failure(tmp_path):
    """A replica that never says hello is a failed spawn: killed,
    counted against the give-up budget."""
    fl = Fleet(tmp_path, {"replicas": 1, "max_restarts": 0})
    fl.router.cfg = DeepSpeedFleetConfig(
        {"fleet": {"replicas": 1, "max_restarts": 0,
                   "spawn_timeout_s": 5.0, "backoff_base_s": 0.01}})

    def mute_spawn(replica_id, attempt):
        proc = FakeProc()
        fl.fakes[replica_id] = type("F", (), {"proc": proc})()
        return proc

    fl.router.spawn_fn = mute_spawn
    fl.router._spawn("initial")
    fl.advance(6.0)  # past spawn_timeout_s
    with pytest.raises(FleetGiveUpError):
        fl.router.poll(0.01)


def test_garbage_connection_cannot_crash_router(tmp_path):
    """A port scanner (or corrupt framing) on the router's listen port
    fails ITSELF — poll keeps routing and real replicas keep serving."""
    fl = Fleet(tmp_path, {"replicas": 1, "max_replicas": 1}).start()
    try:
        scanner = socket.create_connection(fl.router.addr, timeout=5.0)
        scanner.sendall(b"\xff\xff\xff\xffGARBAGE")  # >16MiB length prefix
        fl.pump()  # must not raise
        r1 = fl.router.submit([1], max_new_tokens=2)
        deadline = time.monotonic() + 5
        while not fl.fakes[0].submits and time.monotonic() < deadline:
            fl.pump(1)
        fl.fakes[0].admit(1)
        fl.fakes[0].tokens(1, [9])
        fl.fakes[0].done(1, total=1)
        fl.pump()
        assert r1.result(timeout=5) == [9]
        scanner.close()
    finally:
        fl.router.close()


def test_close_fails_inflight_typed_and_is_idempotent(tmp_path):
    fl = Fleet(tmp_path, {"replicas": 1, "max_replicas": 1}).start()
    r1 = fl.router.submit([1], max_new_tokens=2)
    fl.pump()
    fl.router.close()
    assert isinstance(r1.error, FleetClosedError)
    with pytest.raises(FleetClosedError):
        r1.result(timeout=1)
    fl.router.close()  # idempotent
    assert fl.fakes[0].proc.rc is not None  # replica torn down


def test_fleet_events_ledger_and_heartbeat_age_metrics(tmp_path):
    """The router's events.jsonl is the fleet's request ledger +
    per-replica liveness export: every submit has a completion record,
    and metrics records carry heartbeat_age_s{replica=...}."""
    fl = Fleet(tmp_path, {"replicas": 1, "max_replicas": 1}).start()
    try:
        w = HeartbeatWriter(fl.router.fleet_dir, process_index=0)
        w.beat(1, extra={"serve_active_slots": 0})
        r1 = fl.router.submit([1], max_new_tokens=2)
        deadline = time.monotonic() + 5
        while not fl.fakes[0].submits and time.monotonic() < deadline:
            fl.pump(1)
        fl.fakes[0].admit(1)
        fl.fakes[0].tokens(1, [4])
        fl.fakes[0].done(1, total=1)
        fl.pump()
        assert r1.result(timeout=5) == [4]
        fl.router._last_beats_read = 0.0
        fl.router._last_metrics_write = 0.0
        fl.router.poll(0.01)
    finally:
        fl.router.close()
    recs = []
    with open(os.path.join(fl.router.fleet_dir, "events.jsonl")) as f:
        for line in f:
            recs.append(json.loads(line))
    kinds = [r["kind"] for r in recs]
    assert "fleet_submit" in kinds and "fleet_request" in kinds
    done = next(r for r in recs if r["kind"] == "fleet_request")
    assert done["rid"] == 1 and done["error"] is None
    assert done["queue_wait_s"] is not None
    # the LAST metrics record: the first may predate the beat file
    mrec = [r for r in recs if r["kind"] == "metrics"][-1]
    ages = [m for m in mrec["metrics"]
            if m["name"] == "heartbeat_age_s"]
    assert ages and ages[0]["labels"]["replica"] == "0"
    assert ages[0]["value"] is not None and ages[0]["value"] >= 0


# ---------------------------------------------------------------------------
# diagnose: the fleet-directory post-mortem
# ---------------------------------------------------------------------------


def test_diagnose_fleet_directory_correlation(tmp_path, capsys):
    from deepspeed_tpu.telemetry.cli import diagnose
    d = tmp_path / "fleet"
    (d / "replica_0").mkdir(parents=True)
    (d / "replica_1").mkdir()
    with open(d / "replica_0" / "flightrec_5.json", "w") as f:
        json.dump({"version": 1, "reason": "serve poison", "step": 5,
                   "error": "RuntimeError('boom')",
                   "stages": {"serve": {"events": [
                       {"t": 100.0, "kind": "poison",
                        "error": "RuntimeError('boom')"}]}}}, f)
    events = [
        {"kind": "fleet_submit", "t": 99.0, "rid": 1},
        {"kind": "fleet_submit", "t": 99.1, "rid": 2},
        {"kind": "fleet_submit", "t": 99.2, "rid": 3},
        {"kind": "replica_dead", "t": 100.5, "replica": 0,
         "reason": "replica 0 exited rc=13", "failed_over": 1},
        {"kind": "fleet_request", "t": 101.0, "rid": 1,
         "error": "ReplicaFailure('mid-stream')", "started": True,
         "failovers": 0},
        {"kind": "fleet_request", "t": 101.5, "rid": 2, "error": None,
         "started": True, "failovers": 1, "queue_wait_s": 0.3},
    ]
    with open(d / "events.jsonl", "w") as f:
        for e in events:
            f.write(json.dumps(e) + "\n")
    report = diagnose(str(d))
    out = capsys.readouterr().out
    assert report["fleet_replica_dirs"] == 2
    assert report["fleet_failover_count"] == 1
    assert report["fleet_dangling_requests"] == 1   # rid 3 never done
    assert report["fleet_failed_requests"] == 1
    assert report["fleet_first_dead_replica"] == 0
    assert report["fleet_first_failing_replica"] == "replica_0"
    assert "failed over" in out and "DANGLING" in out
    assert "replica_0" in out


def test_diagnose_non_fleet_dir_unchanged(tmp_path, capsys):
    """A plain telemetry dir must not grow fleet rows."""
    from deepspeed_tpu.telemetry.cli import diagnose
    with open(tmp_path / "events.jsonl", "w") as f:
        f.write(json.dumps({"kind": "step", "step": 1}) + "\n")
    report = diagnose(str(tmp_path))
    out = capsys.readouterr().out
    assert "failed over" not in out and "DANGLING" not in out
    assert "fleet_failover_count" not in report
    assert "fleet_replica_dirs" not in report


def test_diagnose_fleet_per_role_breakdown_and_custody(tmp_path,
                                                       capsys):
    """A disaggregated fleet dir: diagnose breaks replicas down per
    role (first dead replica per role) and summarizes the migration
    custody ledger — taken into router custody, handed to decode,
    re-dispatched after a decode-replica death."""
    from deepspeed_tpu.telemetry.cli import diagnose
    d = tmp_path / "fleet"
    d.mkdir()
    events = [
        {"kind": "spawn", "t": 1.0, "replica": 0, "role": "prefill"},
        {"kind": "spawn", "t": 1.1, "replica": 1, "role": "decode"},
        {"kind": "spawn", "t": 9.0, "replica": 2, "role": "decode"},
        {"kind": "fleet_submit", "t": 10.0, "rid": 1},
        {"kind": "migration", "t": 10.5, "rid": 1,
         "custody": "router", "src": 0, "pages": 2, "bytes": 128},
        {"kind": "migration", "t": 10.6, "rid": 1,
         "custody": "decode", "dst": 1, "pages": 2, "bytes": 128},
        {"kind": "replica_dead", "t": 11.0, "replica": 1,
         "reason": "replica 1 exited rc=-9", "failed_over": 0},
        {"kind": "migration", "t": 11.0, "rid": 1,
         "custody": "router", "requeued": True, "src": 1},
        {"kind": "migration", "t": 11.2, "rid": 1,
         "custody": "decode", "dst": 2, "pages": 2, "bytes": 128},
        {"kind": "fleet_request", "t": 12.0, "rid": 1, "error": None,
         "started": True, "migrated": True, "prefill_replica": 0,
         "decode_replica": 2},
    ]
    with open(d / "events.jsonl", "w") as f:
        for e in events:
            f.write(json.dumps(e) + "\n")
    report = diagnose(str(d))
    out = capsys.readouterr().out
    assert report["fleet_roles"] == {"prefill": 1, "decode": 2}
    assert report["fleet_role_first_dead"] == {"decode": 1}
    assert report["fleet_migrations"] == 2        # handed to decode
    assert report["fleet_migration_requeued"] == 1
    assert "role prefill" in out and "role decode" in out
    assert "first dead replica 1" in out
    assert "re-dispatched after a decode-replica death" in out
    # a homogeneous (all-mixed, no migrations) ledger grows no role rows
    d2 = tmp_path / "homog"
    d2.mkdir()
    with open(d2 / "events.jsonl", "w") as f:
        f.write(json.dumps({"kind": "spawn", "t": 1.0, "replica": 0,
                            "role": "mixed"}) + "\n")
    report2 = diagnose(str(d2))
    out2 = capsys.readouterr().out
    assert "fleet_roles" not in report2
    assert "role mixed" not in out2


# ---------------------------------------------------------------------------
# disaggregated roles: steering, migration custody, per-role autoscale
# (fake socket replicas — custody transitions are deterministic here)
# ---------------------------------------------------------------------------


def _wait_for(cond, pump, timeout=5.0):
    deadline = time.monotonic() + timeout
    while not cond() and time.monotonic() < deadline:
        pump()
    assert cond(), "condition never held"


def test_roles_admissions_steer_to_prefill_with_migrate_flag(tmp_path):
    fl = Fleet(tmp_path, {"roles": {"prefill": 1, "decode": 1},
                          "max_replicas": 2}).start()
    try:
        assert {r.id: r.role for r in fl.router.replicas.values()} \
            == {0: "prefill", 1: "decode"}
        fl.router.submit([1, 2], max_new_tokens=4)
        fl.router.submit([3], max_new_tokens=1)
        _wait_for(lambda: len(fl.fakes[0].submits) == 2,
                  lambda: fl.pump(1))
        # both admissions went to the prefill replica; the multi-token
        # one carries the migrate flag, the single-token one serves in
        # place (its generation IS its prefill)
        flags = {f["rid"]: f.get("migrate") for f in fl.fakes[0].submits}
        assert flags == {1: True, 2: None}
        assert not fl.fakes[1].submits
    finally:
        fl.router.close()


def test_migration_custody_handoff_and_completion(tmp_path):
    """The happy-path custody chain: prefill replica streams the first
    token + KV blob, the router takes custody, hands blob + request to
    the decode replica byte-intact, and the decode replica finishes the
    stream — ledger transitions agree."""
    fl = Fleet(tmp_path, {"roles": {"prefill": 1, "decode": 1},
                          "max_replicas": 2}).start()
    d = fl.router.fleet_dir
    try:
        r = fl.router.submit([1, 2, 3], max_new_tokens=4)
        _wait_for(lambda: fl.fakes[0].submits, lambda: fl.pump(1))
        p0 = fl.fakes[0]
        p0.admit(1)
        p0.tokens(1, [42])
        send_frame(p0.sock, {"kind": "migrate_out", "rid": 1,
                             "first_token": 42, "kv_len": 3,
                             "pages": 2, "page_bytes": 128})
        send_binary_frame(p0.sock, {"kind": "page", "rid": 1,
                                    "seq": 0}, b"A" * 64)
        send_binary_frame(p0.sock, {"kind": "page", "rid": 1,
                                    "seq": 1}, b"B" * 64)
        got = []

        def _pump_decode():
            fl.router.poll(0.01)
            got.extend(fl.fakes[1].pump())
        _wait_for(lambda: sum(1 for f in got
                              if f.get("kind") == "page") == 2,
                  _pump_decode)
        assert [f.get("kind") for f in got] == ["migrate_in", "page",
                                               "page"]
        mi = got[0]
        assert mi["prompt"] == [1, 2, 3]
        assert mi["first_token"] == 42
        assert mi["max_new_tokens"] == 4   # the ORIGINAL budget
        assert isinstance(got[1], BinaryFrame)
        assert got[1].payload == b"A" * 64
        assert got[2].payload == b"B" * 64
        # a PREFILL token never flips the failover boundary
        assert r.tokens == [42] and not r.started
        assert r.migrated and r.prefill_replica == 0 \
            and r.decode_replica == 1
        fl.fakes[1].tokens(1, [43, 44, 45])
        fl.fakes[1].done(1, total=4)
        assert r.result(timeout=5) == [42, 43, 44, 45]
        assert r.started and r.error is None
    finally:
        fl.router.close()
    recs = [json.loads(line) for line in open(
        os.path.join(d, "events.jsonl"))]
    mig = [x for x in recs if x["kind"] == "migration"]
    assert [m["custody"] for m in mig] == ["router", "decode"]
    assert mig[0]["src"] == 0 and mig[1]["dst"] == 1
    assert mig[1]["pages"] == 2 and mig[1]["bytes"] == 128
    req_recs = [x for x in recs if x["kind"] == "fleet_request"]
    assert req_recs[-1]["migrated"] is True
    assert req_recs[-1]["prefill_replica"] == 0
    assert req_recs[-1]["decode_replica"] == 1


def test_migration_prefill_death_mid_blob_requeues_from_scratch(
        tmp_path):
    """Kill the prefill replica while its KV blob is HALF received:
    the partial blob is discarded, the request requeues unstarted with
    its stream stamps cleared (the caller never saw the first token),
    and the role floor respawns a PREFILL replica that re-runs it."""
    fl = Fleet(tmp_path, {"roles": {"prefill": 1, "decode": 1},
                          "max_replicas": 3}).start()
    try:
        r = fl.router.submit([5, 6], max_new_tokens=4)
        _wait_for(lambda: fl.fakes[0].submits, lambda: fl.pump(1))
        p0 = fl.fakes[0]
        p0.admit(1)
        p0.tokens(1, [42])
        send_frame(p0.sock, {"kind": "migrate_out", "rid": 1,
                             "first_token": 42, "kv_len": 2,
                             "pages": 2, "page_bytes": 128})
        send_binary_frame(p0.sock, {"kind": "page", "rid": 1,
                                    "seq": 0}, b"A" * 64)
        fl.pump()
        assert r.tokens == [42] and not r.started
        p0.die(9)
        fl.advance(1.0)          # past the respawn backoff

        def _pump():
            fl.advance(0.05)
            fl.pump(1)
        _wait_for(lambda: any(i >= 2 and fl.fakes[i].submits
                              for i in fl.fakes), _pump, timeout=10)
        (new_id,) = [i for i in fl.fakes if i >= 2]
        assert fl.router.replicas[new_id].role == "prefill"
        resub = fl.fakes[new_id].submits[0]
        assert resub["rid"] == 1 and resub.get("migrate") is True
        # restarted from scratch: no leaked tokens/stamps, failover
        # counted, nothing lost
        assert r.tokens == [] and r.ttft_s is None
        assert r.failovers == 1 and not r.done.is_set()
        assert not fl.router._migrate_queue
    finally:
        fl.router.close()


def test_migration_decode_death_reships_blob_zero_lost(tmp_path):
    """Kill the decode replica AFTER the blob was handed over but
    before it streamed: custody snaps back to the router, which
    re-ships the SAME bytes to the replacement decode replica — the
    request completes with its prefill work intact (never re-run)."""
    fl = Fleet(tmp_path, {"roles": {"prefill": 1, "decode": 1},
                          "max_replicas": 3}).start()
    d = fl.router.fleet_dir
    try:
        r = fl.router.submit([7, 8, 9], max_new_tokens=3)
        _wait_for(lambda: fl.fakes[0].submits, lambda: fl.pump(1))
        p0 = fl.fakes[0]
        p0.admit(1)
        p0.tokens(1, [10])
        send_frame(p0.sock, {"kind": "migrate_out", "rid": 1,
                             "first_token": 10, "kv_len": 3,
                             "pages": 1, "page_bytes": 32})
        send_binary_frame(p0.sock, {"kind": "page", "rid": 1,
                                    "seq": 0}, b"K" * 32)
        got1 = []

        def _pump1():
            fl.router.poll(0.01)
            got1.extend(fl.fakes[1].pump())
        _wait_for(lambda: any(f.get("kind") == "page" for f in got1),
                  _pump1)
        fl.fakes[1].die(9)
        fl.advance(1.0)
        got2 = []

        def _pump2():
            fl.advance(0.05)
            fl.router.poll(0.01)
            for i, f in list(fl.fakes.items()):
                if f.proc.rc is not None:
                    continue
                frames = f.pump()
                if i >= 2:
                    got2.extend(frames)
        _wait_for(lambda: any(f.get("kind") == "page" for f in got2),
                  _pump2, timeout=10)
        (new_id,) = [i for i in fl.fakes if i >= 2]
        assert fl.router.replicas[new_id].role == "decode"
        pages = [f for f in got2 if f.get("kind") == "page"]
        assert pages[0].payload == b"K" * 32    # the SAME bytes
        assert r.failovers == 1 and r.tokens == [10]
        fl.fakes[new_id].tokens(1, [11, 12])
        fl.fakes[new_id].done(1, total=3)
        assert r.result(timeout=5) == [10, 11, 12]
    finally:
        fl.router.close()
    recs = [json.loads(line) for line in open(
        os.path.join(d, "events.jsonl"))]
    mig = [x for x in recs if x["kind"] == "migration"]
    assert [m["custody"] for m in mig] == ["router", "decode",
                                           "router", "decode"]
    assert mig[2].get("requeued") is True
    req_recs = [x for x in recs if x["kind"] == "fleet_request"]
    assert req_recs[-1]["error"] is None       # zero lost


def test_roles_autoscale_decode_tpot_breach_spawns_decode(tmp_path):
    """Decode replicas beating a TPOT p99 over fleet.slo_tpot_s for a
    sustained window scale the DECODE role up — prefill stays put."""
    fl = Fleet(tmp_path, {"roles": {"prefill": 1, "decode": 1},
                          "max_replicas": 4, "slo_tpot_s": 0.1,
                          "scale_up_window_s": 5.0,
                          "scale_down_window_s": 600.0}).start()
    try:
        w = HeartbeatWriter(fl.router.fleet_dir, process_index=1)
        w.beat(1, extra={"serve_tpot_p99_s": 0.5})
        fl.router._last_beats_read = 0.0
        fl.router.poll(0.01)           # breach clock starts
        fl.advance(6.0)
        w.beat(2, extra={"serve_tpot_p99_s": 0.5})
        fl.router._last_beats_read = 0.0
        fl.router.poll(0.01)           # sustained past the window
        new = [r for r in fl.router.replicas.values() if r.id >= 2]
        assert [r.role for r in new] == ["decode"]
        assert fl.router._role_target == {"prefill": 1, "decode": 2}
    finally:
        fl.router.close()


def test_roles_autoscale_prefill_breach_spawns_prefill(tmp_path):
    """Admission-wait p99 over the TTFT SLO scales the PREFILL role —
    the phase that admissions actually queue behind."""
    fl = Fleet(tmp_path, {"roles": {"prefill": 1, "decode": 1},
                          "max_replicas": 4, "slo_ttft_s": 1.0,
                          "scale_up_window_s": 5.0,
                          "scale_down_window_s": 600.0}).start()
    try:
        fl.router._wait_samples.append((fl.router._now(), 5.0))
        fl.router.poll(0.01)
        fl.advance(6.0)
        fl.router._wait_samples.append((fl.router._now(), 5.0))
        fl.router.poll(0.01)
        new = [r for r in fl.router.replicas.values() if r.id >= 2]
        assert [r.role for r in new] == ["prefill"]
        assert fl.router._role_target == {"prefill": 2, "decode": 1}
    finally:
        fl.router.close()


# ---------------------------------------------------------------------------
# subprocess e2e: real replicas behind the router
# ---------------------------------------------------------------------------


def _e2e_config(replicas, *, slots=4, telemetry=False, **fleet_over):
    return {
        "serving": {"slots": slots, "max_seq_len": 64,
                    "prefill_len": 8, "queue_capacity": 256,
                    "flush_interval_ticks": 5},
        "telemetry": {"enabled": telemetry},
        "fleet": {"replicas": replicas, "min_replicas": 1,
                  "max_replicas": max(replicas, 2),
                  "slo_p99_s": 30.0, "scale_up_window_s": 5.0,
                  "scale_down_window_s": 600.0,
                  "spawn_timeout_s": 120.0, "backoff_base_s": 0.2,
                  "heartbeat_timeout_s": 60.0, **fleet_over},
        "fleet_model": {"vocab_size": 128, "n_positions": 64,
                        "d_model": 32, "n_layer": 2, "n_head": 4,
                        "attn_impl": "dense", "seed": 0},
    }


def _prompts(n, seed=0):
    rng = np.random.default_rng(seed)
    return [[int(t) for t in rng.integers(0, 128, (5,))]
            for _ in range(n)]


def test_e2e_single_replica_fleet_matches_bare_engine(tmp_path):
    """The parity bar: a 1-replica fleet emits the SAME greedy stream
    as a bare ServeEngine for the same request trace (the replica
    builds identical params from the shared fleet_model seed), and the
    replica's zero-recompile property survives the wire."""
    from deepspeed_tpu.inference.replica import build_engine
    cfg = _e2e_config(1, telemetry=True)
    prompts = _prompts(6)

    eng = build_engine(cfg, str(tmp_path / "bare"), 99)
    bare = [eng.submit(p, max_new_tokens=6) for p in prompts]
    eng.run_until_idle()
    bare_toks = [r.tokens for r in bare]
    bare_reasons = [r.finish_reason for r in bare]
    eng.close()

    d = str(tmp_path / "fleet")
    router = FleetRouter(cfg, fleet_dir=d)
    try:
        router.start()
        reqs = [router.submit(p, max_new_tokens=6) for p in prompts]
        router.run_until_idle(max_s=120)
        assert [r.tokens for r in reqs] == bare_toks
        assert [r.finish_reason for r in reqs] == bare_reasons
        assert all(r.queue_wait_s is not None for r in reqs)
    finally:
        router.close()
    # the replica's telemetry landed in its own subdir; its compile
    # tracking pins the decode program at zero recompiles through the
    # whole mixed trace (the bare-engine contract, preserved per
    # replica)
    rep_dir = os.path.join(d, "replica_0")
    assert os.path.isdir(rep_dir)
    prom = os.path.join(rep_dir, "metrics.prom")
    if os.path.isfile(prom):
        with open(prom) as f:
            for line in f:
                if line.startswith("recompiles_total") \
                        and "decode_step" in line:
                    assert float(line.rsplit(None, 1)[1]) == 0.0


def test_e2e_burst_larger_than_engine_queue_capacity(tmp_path):
    """Overload regression: the router dispatches unbounded, but the
    replica's engine queue is a BLOCKING bounded channel — a burst
    beyond serving.queue_capacity must park in the replica's host-side
    backlog and drain as the engine steps, never deadlock the
    single-threaded replica loop."""
    cfg = _e2e_config(1, slots=2)
    cfg["serving"]["queue_capacity"] = 4
    router = FleetRouter(cfg, fleet_dir=str(tmp_path / "fleet"))
    try:
        router.start()
        reqs = [router.submit(p, max_new_tokens=4)
                for p in _prompts(12, seed=5)]   # 3x the queue bound
        router.run_until_idle(max_s=120)
        assert all(r.error is None for r in reqs), \
            [repr(r.error) for r in reqs if r.error]
        assert all(len(r.tokens) == 4 for r in reqs)
    finally:
        router.close()


def test_e2e_replica_kill_fails_over_unstarted(tmp_path,
                                               monkeypatch):
    """Kill one of two REAL replicas mid-stream: every queued-but-
    unstarted request completes via failover (zero lost), mid-stream
    casualties fail typed, and the ledger agrees."""
    # slow the serving ticks so the kill reliably lands mid-stream
    monkeypatch.setenv("DS_STAGE_DELAY_S", "serve:0.05")
    reset_fault_injection()
    cfg = _e2e_config(2, slots=2)
    d = str(tmp_path / "fleet")
    router = FleetRouter(cfg, fleet_dir=d)
    try:
        router.start()
        initial = sorted(router.replicas)
        reqs = [router.submit(p, max_new_tokens=8)
                for p in _prompts(16, seed=3)]
        # wait until both replicas are streaming (started requests on
        # each), so the kill hits a mix of started + queued work
        deadline = time.monotonic() + 60
        while time.monotonic() < deadline:
            router.poll(0.02)
            started_by = {rid: any(r.started and r.replica == rid
                                   for r in reqs)
                          for rid in initial}
            if all(started_by.values()):
                break
        assert all(started_by.values()), "replicas never streamed"
        victim = max(router.replicas.values(),
                     key=lambda r: len(r.outstanding)).id
        router.kill_replica(victim)
        router.run_until_idle(max_s=120)
        failed = [r for r in reqs if r.error is not None]
        # zero queued-but-unstarted requests lost
        assert all(r.started for r in failed)
        assert all(isinstance(r.error, ReplicaFailure) for r in failed)
        survivors = [r for r in reqs if r.error is None]
        assert survivors and all(len(r.tokens) == 8 for r in survivors)
        assert sum(r.failovers for r in reqs) > 0
    finally:
        router.close()
    # the ledger agrees: every submit completed, failures all started
    recs = []
    with open(os.path.join(d, "events.jsonl")) as f:
        for line in f:
            recs.append(json.loads(line))
    submits = [r for r in recs if r["kind"] == "fleet_submit"]
    dones = {r["rid"]: r for r in recs
             if r["kind"] == "fleet_request"}
    assert len(dones) == len(submits)
    assert all(r["started"] for r in dones.values() if r["error"])
    assert any(r["kind"] == "replica_dead" and r["failed_over"] > 0
               for r in recs)


# ---------------------------------------------------------------------------
# subprocess e2e: disaggregated prefill/decode fleet
# ---------------------------------------------------------------------------


def _disagg_config(*, telemetry=False, chunk=4, **fleet_over):
    """Paged + chunked serving over a prefill/decode role split —
    prompts longer than prefill_len/2 exercise multi-page blobs."""
    cfg = _e2e_config(2, telemetry=telemetry,
                      roles={"prefill": 1, "decode": 1}, **fleet_over)
    cfg["serving"].update({"prefill_len": 16, "page_len": 4,
                           "pages": 64, "prefill_chunk_len": chunk})
    return cfg


def _long_prompts(n, seed=0):
    rng = np.random.default_rng(seed)
    return [[int(t) for t in rng.integers(0, 128, (11,))]
            for _ in range(n)]


def test_e2e_disagg_stream_parity_and_custody_ledger(tmp_path):
    """THE disaggregation parity bar: a prefill/decode fleet with
    chunked prefill emits the SAME greedy stream as a bare ServeEngine
    — every request migrated over binary page frames, TTFT stamped at
    the prefill replica, and the custody ledger balanced."""
    from deepspeed_tpu.inference.replica import build_engine
    cfg = _disagg_config(telemetry=True)
    prompts = _long_prompts(8)

    eng = build_engine(cfg, str(tmp_path / "bare"), 99)
    bare = [eng.submit(p, max_new_tokens=6) for p in prompts]
    eng.run_until_idle()
    bare_toks = [r.tokens for r in bare]
    eng.close()

    d = str(tmp_path / "fleet")
    router = FleetRouter(cfg, fleet_dir=d)
    try:
        router.start()
        assert sorted(r.role for r in router.replicas.values()) \
            == ["decode", "prefill"]
        reqs = [router.submit(p, max_new_tokens=6) for p in prompts]
        router.run_until_idle(max_s=120)
        assert [r.tokens for r in reqs] == bare_toks
        assert all(r.error is None for r in reqs)
        assert all(r.migrated for r in reqs)
        assert all(r.ttft_s is not None for r in reqs)
        assert router.migrations == len(prompts)
    finally:
        router.close()
    recs = []
    with open(os.path.join(d, "events.jsonl")) as f:
        for line in f:
            recs.append(json.loads(line))
    mig = [r for r in recs if r["kind"] == "migration"]
    # every request: exactly one router-custody + one decode-custody
    assert sum(1 for m in mig if m["custody"] == "router") \
        == len(prompts)
    assert sum(1 for m in mig if m["custody"] == "decode") \
        == len(prompts)
    done = [r for r in recs if r["kind"] == "fleet_request"]
    assert all(r["migrated"] and r["error"] is None for r in done)
    assert {r["prefill_replica"] for r in done} == {0}
    assert {r["decode_replica"] for r in done} == {1}
    # zero recompiles survive the wire on BOTH phases: one compiled
    # prefill program across chunked admissions, one decode program
    # across adopted requests
    for rid in (0, 1):
        prom = os.path.join(d, f"replica_{rid}", "metrics.prom")
        if os.path.isfile(prom):
            with open(prom) as f:
                for line in f:
                    if line.startswith("recompiles_total") and (
                            "prefill" in line or "decode_step" in line):
                        assert float(line.rsplit(None, 1)[1]) == 0.0, \
                            line


def test_e2e_disagg_decode_kill_zero_lost(tmp_path, monkeypatch):
    """Chaos-kill the DECODE replica mid-run: router-custody blobs
    re-ship to the respawned decode replica, started casualties fail
    typed, and the ledger shows zero dangling requests."""
    monkeypatch.setenv("DS_STAGE_DELAY_S", "serve:0.05")
    reset_fault_injection()
    cfg = _disagg_config(max_replicas=3)
    d = str(tmp_path / "fleet")
    router = FleetRouter(cfg, fleet_dir=d)
    try:
        router.start()
        reqs = [router.submit(p, max_new_tokens=8)
                for p in _long_prompts(10, seed=3)]
        # wait for the decode phase to hold real work (custody handed
        # over), then kill it
        deadline = time.monotonic() + 60
        victim = None
        while time.monotonic() < deadline:
            router.poll(0.02)
            decode = [r for r in router.replicas.values()
                      if r.role == "decode" and r.state == "ready"]
            if decode and decode[0].outstanding:
                victim = decode[0].id
                break
        assert victim is not None, "decode replica never took work"
        router.kill_replica(victim)
        router.run_until_idle(max_s=120)
        failed = [r for r in reqs if r.error is not None]
        assert all(r.started for r in failed)       # zero lost
        assert all(isinstance(r.error, ReplicaFailure)
                   for r in failed)
        survivors = [r for r in reqs if r.error is None]
        assert survivors and all(len(r.tokens) == 8
                                 for r in survivors)
    finally:
        router.close()
    recs = []
    with open(os.path.join(d, "events.jsonl")) as f:
        for line in f:
            recs.append(json.loads(line))
    submits = {r["rid"] for r in recs if r["kind"] == "fleet_submit"}
    dones = {r["rid"] for r in recs if r["kind"] == "fleet_request"}
    assert submits == dones                         # nothing dangling
    assert any(r["kind"] == "replica_dead" for r in recs)
    # the respawn honored the role floor: a DECODE replica came back
    respawns = [r for r in recs if r["kind"] == "spawn"
                and r["reason"] != "initial"]
    assert any(r.get("role") == "decode" for r in respawns)
