"""Serving fleet: wire framing, the router's JSQ/failover/autoscale
semantics (fake socket replicas — the elastic supervisor's test
idiom), heartbeat gauge payloads, fleet diagnose correlation, and the
subprocess e2e bars (single-replica parity vs a bare ServeEngine;
replica-kill failover) — docs/serving.md "serving fleet".
"""
import json
import os
import socket
import subprocess
import time

import numpy as np
import pytest

from deepspeed_tpu.config import DeepSpeedConfigError
from deepspeed_tpu.config.config import DeepSpeedFleetConfig
from deepspeed_tpu.inference.fleet import (FleetClosedError,
                                           FleetGiveUpError,
                                           FleetRouter, ReplicaFailure)
from deepspeed_tpu.inference.wire import (FrameReader, WireError,
                                          drain_socket, encode_frame,
                                          send_frame)
from deepspeed_tpu.runtime.stages import reset_fault_injection
from deepspeed_tpu.telemetry.heartbeat import (HeartbeatWriter,
                                               StragglerMonitor,
                                               beat_ages,
                                               read_heartbeats)

_CHAOS_ENVS = ("DS_STAGE_FAULT", "DS_STAGE_DELAY_S")


@pytest.fixture(autouse=True)
def _clean_faults(monkeypatch):
    for env in _CHAOS_ENVS:
        monkeypatch.delenv(env, raising=False)
    reset_fault_injection()
    yield
    reset_fault_injection()


# ---------------------------------------------------------------------------
# wire framing
# ---------------------------------------------------------------------------


def test_wire_roundtrip_and_partial_feeds():
    frames = [{"kind": "submit", "rid": 1, "prompt": [1, 2, 3]},
              {"kind": "token", "rid": 1, "toks": [7]},
              {"kind": "done", "rid": 1, "reason": "length"}]
    blob = b"".join(encode_frame(f) for f in frames)
    # byte-by-byte feeding must reassemble every frame exactly
    r = FrameReader()
    out = []
    for i in range(len(blob)):
        out.extend(r.feed(blob[i:i + 1]))
    assert out == frames
    # one big feed yields them all at once
    r2 = FrameReader()
    assert r2.feed(blob) == frames


def test_wire_corrupt_stream_raises_typed():
    r = FrameReader()
    # oversized length prefix = corrupt stream, not a real frame
    with pytest.raises(WireError):
        r.feed(b"\xff\xff\xff\xff")
    # valid length, non-JSON payload
    import struct
    r2 = FrameReader()
    with pytest.raises(WireError):
        r2.feed(struct.pack(">I", 4) + b"\x00\x01\x02\x03")
    # valid JSON but not an object
    r3 = FrameReader()
    with pytest.raises(WireError):
        r3.feed(struct.pack(">I", 3) + b"[1]")


def test_wire_socket_pair_drain():
    a, b = socket.socketpair()
    try:
        send_frame(a, {"kind": "hello", "replica": 0})
        send_frame(a, {"kind": "token", "rid": 2, "toks": [1, 2]})
        reader = FrameReader()
        frames, closed = drain_socket(b, reader)
        assert [f["kind"] for f in frames] == ["hello", "token"]
        assert not closed
        a.close()
        frames, closed = drain_socket(b, reader)
        assert frames == [] and closed
    finally:
        b.close()


# ---------------------------------------------------------------------------
# heartbeat serving gauges (the fleet's JSQ payload)
# ---------------------------------------------------------------------------


def test_heartbeat_extra_gauges_roundtrip_and_core_keys_win(tmp_path):
    w = HeartbeatWriter(str(tmp_path), process_index=3)
    assert w.beat(7, step_s=0.5, extra={
        "serve_active_slots": 2, "serve_queue_depth": 5,
        "serve_free_pages": 11, "spec_accept_ratio": 0.75,
        # a hostile gauge must never mask liveness: core keys win
        "time": 1.0, "step": 999})
    beats = read_heartbeats(str(tmp_path))
    (rec,) = beats.values()
    assert rec["serve_active_slots"] == 2
    assert rec["serve_queue_depth"] == 5
    assert rec["serve_free_pages"] == 11
    assert rec["spec_accept_ratio"] == 0.75
    assert rec["step"] == 7          # core beat fields won
    assert rec["time"] > 1e9
    # richer schema tolerated by every existing reader
    ages = beat_ages(beats)
    assert list(ages) and all(a >= 0 for a in ages.values())
    rep = StragglerMonitor(ratio=2.0).update(beats)
    assert rep["hosts"] == 1


# ---------------------------------------------------------------------------
# fleet config block
# ---------------------------------------------------------------------------


def test_fleet_config_defaults_and_validation():
    cfg = DeepSpeedFleetConfig({})
    assert (cfg.replicas, cfg.min_replicas, cfg.max_replicas) == (1, 1, 4)
    assert cfg.slo_p99_s == 2.0
    cfg = DeepSpeedFleetConfig({"fleet": {"replicas": 2,
                                          "max_replicas": 8,
                                          "slo_p99_s": 0.5}})
    assert cfg.replicas == 2 and cfg.slo_p99_s == 0.5
    for bad in ({"replicas": 0}, {"min_replicas": 3, "max_replicas": 2},
                {"replicas": 9}, {"slo_p99_s": 0},
                {"scale_up_window_s": -1}, {"max_restarts": -1},
                {"heartbeat_timeout_s": -2}, {"replicas": True},
                {"backoff_base_s": "fast"}):
        with pytest.raises(DeepSpeedConfigError):
            DeepSpeedFleetConfig({"fleet": bad})


# ---------------------------------------------------------------------------
# router semantics over fake socket replicas (the launch_fn test seam)
# ---------------------------------------------------------------------------


class FakeProc:
    """Popen-shaped handle the router supervises."""

    def __init__(self):
        self.rc = None
        self.terminated = False

    def poll(self):
        return self.rc

    def terminate(self):
        self.terminated = True
        if self.rc is None:
            self.rc = -15

    def kill(self):
        self.rc = -9

    def wait(self, timeout=None):
        if self.rc is None:
            raise subprocess.TimeoutExpired("fake", timeout or 0)
        return self.rc


class FakeReplica:
    """A scripted replica: real socket to the router, test-driven
    frames."""

    def __init__(self, addr, replica_id):
        self.id = replica_id
        self.proc = FakeProc()
        self.sock = socket.create_connection(addr, timeout=5.0)
        self.sock.settimeout(5.0)
        self.reader = FrameReader()
        self.submits = []
        self.saw_shutdown = False
        send_frame(self.sock, {"kind": "hello", "replica": replica_id,
                               "pid": 0})

    def pump(self):
        frames, _ = drain_socket(self.sock, self.reader)
        self.submits.extend(f for f in frames
                            if f.get("kind") == "submit")
        if any(f.get("kind") == "shutdown" for f in frames):
            self.saw_shutdown = True
        return frames

    def admit(self, rid):
        send_frame(self.sock, {"kind": "admit", "rid": rid})

    def tokens(self, rid, toks):
        send_frame(self.sock, {"kind": "token", "rid": rid,
                               "toks": list(toks)})

    def done(self, rid, reason="length", total=None):
        send_frame(self.sock, {"kind": "done", "rid": rid,
                               "reason": reason,
                               "tokens_total": total})

    def error(self, rid, err="boom"):
        send_frame(self.sock, {"kind": "error", "rid": rid,
                               "error": err})

    def die(self, rc=13):
        self.proc.rc = rc
        self.sock.close()


class Fleet:
    """Router + fake-replica harness with a fake autoscale clock."""

    def __init__(self, tmp_path, fleet=None):
        self.clock = [1000.0]
        self.fakes = {}
        # term_grace_s small: fake procs never exit on their own, and
        # close()'s graceful-drain window would otherwise wait it out
        cfg = {"fleet": {"heartbeat_timeout_s": 0.0,
                         "backoff_base_s": 0.01,
                         "term_grace_s": 0.2,
                         "spawn_timeout_s": 1e9, **(fleet or {})}}
        self.router = FleetRouter(
            cfg, fleet_dir=str(tmp_path / "fleet"),
            spawn_fn=self._spawn, now_fn=lambda: self.clock[0])

    def _spawn(self, replica_id, attempt):
        fake = FakeReplica(self.router.addr, replica_id)
        self.fakes[replica_id] = fake
        return fake.proc

    def start(self):
        self.router.start()
        return self

    def pump(self, n=6):
        """A few router+fake iterations — localhost frames land fast,
        but never assume a single poll saw them."""
        for _ in range(n):
            self.router.poll(0.01)
            for f in self.fakes.values():
                if f.proc.rc is None:
                    f.pump()

    def advance(self, dt):
        self.clock[0] += dt


def test_jsq_tie_breaks_deterministically_lowest_id(tmp_path):
    fl = Fleet(tmp_path, {"replicas": 2, "max_replicas": 2}).start()
    try:
        reqs = [fl.router.submit([1, 2], max_new_tokens=4)
                for _ in range(4)]
        deadline = time.monotonic() + 5
        while (len(fl.fakes[0].submits) + len(fl.fakes[1].submits) < 4
               and time.monotonic() < deadline):
            fl.pump(1)
        # equal loads tie-break to the LOWEST replica id, alternating
        # as outstanding counts grow: r0 gets rids 1,3 — r1 gets 2,4
        assert [f["rid"] for f in fl.fakes[0].submits] == [1, 3]
        assert [f["rid"] for f in fl.fakes[1].submits] == [2, 4]
        assert [r.replica for r in reqs] == [0, 1, 0, 1]
    finally:
        fl.router.close()


def test_jsq_reads_heartbeat_queue_gauges(tmp_path):
    fl = Fleet(tmp_path, {"replicas": 2, "max_replicas": 2}).start()
    try:
        # replica 0 reports a deep engine-side queue via its beat: the
        # next admission must go to replica 1 despite the id tie
        w = HeartbeatWriter(fl.router.fleet_dir, process_index=0)
        w.beat(1, extra={"serve_queue_depth": 5,
                         "serve_active_slots": 2})
        fl.router._last_beats_read = 0.0  # bypass the read throttle
        fl.router.poll(0.01)
        assert fl.router._beats[0]["serve_queue_depth"] == 5
        fl.router.submit([1], max_new_tokens=2)
        deadline = time.monotonic() + 5
        while not fl.fakes[1].submits and time.monotonic() < deadline:
            fl.pump(1)
        assert [f["rid"] for f in fl.fakes[1].submits] == [1]
        assert not fl.fakes[0].submits
    finally:
        fl.router.close()


def test_failover_queued_vs_midstream(tmp_path):
    """THE failover contract: a dead replica's queued-but-unstarted
    requests re-dispatch (order preserved, completing normally); the
    one whose tokens already streamed fails typed ReplicaFailure."""
    fl = Fleet(tmp_path, {"replicas": 2, "max_replicas": 2}).start()
    try:
        r1 = fl.router.submit([1], max_new_tokens=4)
        r2 = fl.router.submit([2], max_new_tokens=4)
        r3 = fl.router.submit([3], max_new_tokens=4)
        deadline = time.monotonic() + 5
        while len(fl.fakes[0].submits) < 2 and \
                time.monotonic() < deadline:
            fl.pump(1)
        assert [f["rid"] for f in fl.fakes[0].submits] == [1, 3]
        # rid 1 starts streaming on replica 0; rid 3 stays queued there
        fl.fakes[0].admit(1)
        fl.fakes[0].tokens(1, [42, 43])
        fl.pump()
        assert r1.started and r1.tokens == [42, 43]
        assert not r3.started
        fl.fakes[0].die(13)
        deadline = time.monotonic() + 5
        while not r1.done.is_set() and time.monotonic() < deadline:
            fl.pump(1)
        # mid-stream: typed failure naming the replica
        assert isinstance(r1.error, ReplicaFailure)
        assert r1.error.replica == 0
        with pytest.raises(ReplicaFailure):
            r1.result(timeout=1)
        # queued-but-unstarted: failed over to replica 1, completes
        deadline = time.monotonic() + 5
        while len(fl.fakes[1].submits) < 2 and \
                time.monotonic() < deadline:
            fl.pump(1)
        assert [f["rid"] for f in fl.fakes[1].submits] == [2, 3]
        assert r3.failovers == 1 and r3.error is None
        fl.fakes[1].admit(2)
        fl.fakes[1].tokens(2, [7])
        fl.fakes[1].done(2, total=1)
        fl.fakes[1].admit(3)
        fl.fakes[1].tokens(3, [8, 9])
        fl.fakes[1].done(3, total=2)
        fl.pump()
        assert r2.result(timeout=5) == [7]
        assert r3.result(timeout=5) == [8, 9]
        # a completed request resets the give-up budget
        assert fl.router._consec_failures == 0
    finally:
        fl.router.close()


def test_replica_error_frame_fails_one_request_only(tmp_path):
    """Per-request isolation (the engine's Orca discipline, surfaced
    through the wire): an ``error`` frame fails exactly that request —
    the replica keeps its slot pool and the fleet keeps routing."""
    fl = Fleet(tmp_path, {"replicas": 1, "max_replicas": 1}).start()
    try:
        r1 = fl.router.submit([1], max_new_tokens=2)
        r2 = fl.router.submit([2], max_new_tokens=2)
        deadline = time.monotonic() + 5
        while len(fl.fakes[0].submits) < 2 and \
                time.monotonic() < deadline:
            fl.pump(1)
        fl.fakes[0].error(1, "ValueError('empty prompt')")
        fl.fakes[0].admit(2)
        fl.fakes[0].tokens(2, [5])
        fl.fakes[0].done(2, total=1)
        fl.pump()
        assert r1.error is not None and "empty prompt" in str(r1.error)
        assert r2.result(timeout=5) == [5]
        assert 0 in fl.router.replicas  # replica survived
    finally:
        fl.router.close()


def test_autoscale_up_on_sustained_breach_with_hysteresis_and_max(
        tmp_path):
    fl = Fleet(tmp_path, {"replicas": 1, "max_replicas": 3,
                          "slo_p99_s": 1.0, "scale_up_window_s": 10.0,
                          "scale_down_window_s": 1e6}).start()
    try:
        # a request nobody admits: its age IS the breach signal (a
        # wedged fleet produces no admission samples at all)
        fl.router.submit([1], max_new_tokens=2)
        fl.pump()
        fl.advance(2.0)          # older than the SLO -> breach begins
        fl.pump(1)
        assert len(fl.router.replicas) == 1  # breach not sustained yet
        fl.advance(5.0)
        fl.pump(1)
        assert len(fl.router.replicas) == 1  # still inside the window
        fl.advance(6.0)          # breach sustained > scale_up_window_s
        fl.pump(1)
        assert len(fl.router.replicas) == 2  # scaled up
        # hysteresis: the scale event reset the breach clock — no
        # second spawn until ANOTHER full window of sustained breach
        fl.advance(3.0)
        fl.pump(2)
        assert len(fl.router.replicas) == 2
        fl.advance(11.0)
        fl.pump(2)
        assert len(fl.router.replicas) == 3
        # max clamp: breach may rage on, the fleet stays at max
        fl.advance(30.0)
        fl.pump(3)
        assert len(fl.router.replicas) == 3
    finally:
        fl.router.close()


def test_autoscale_down_on_sustained_slack_with_min_clamp(tmp_path):
    fl = Fleet(tmp_path, {"replicas": 2, "min_replicas": 1,
                          "max_replicas": 2, "slo_p99_s": 1.0,
                          "scale_up_window_s": 10.0,
                          "scale_down_window_s": 20.0}).start()
    try:
        # serve one request quickly: a healthy, then idle, fleet
        r = fl.router.submit([1], max_new_tokens=2)
        deadline = time.monotonic() + 5
        while not fl.fakes[0].submits and time.monotonic() < deadline:
            fl.pump(1)
        fl.fakes[0].admit(1)
        fl.fakes[0].tokens(1, [3])
        fl.fakes[0].done(1, total=1)
        fl.pump()
        assert r.result(timeout=5) == [3]
        # slack begins; not sustained yet -> no retire
        fl.advance(25.0)   # ages the wait sample out of both windows
        fl.pump(1)
        assert len(fl.router.replicas) == 2
        fl.advance(21.0)   # slack sustained > scale_down_window_s
        fl.pump(1)
        draining = [rep for rep in fl.router.replicas.values()
                    if rep.state == "draining"]
        assert [rep.id for rep in draining] == [1]  # highest id drains
        # the drained retiree exits 0 and is reaped
        deadline = time.monotonic() + 5
        while 1 in fl.router.replicas and time.monotonic() < deadline:
            fl.fakes[1].pump()
            if fl.fakes[1].saw_shutdown:
                fl.fakes[1].proc.rc = 0
            fl.router.poll(0.01)
        assert sorted(fl.router.replicas) == [0]
        # min clamp: slack forever, but the floor holds
        fl.advance(50.0)
        fl.pump(2)
        fl.advance(50.0)
        fl.pump(2)
        assert sorted(fl.router.replicas) == [0]
    finally:
        fl.router.close()


def test_give_up_typed_after_consecutive_spawn_failures(tmp_path):
    calls = []

    def bad_spawn(replica_id, attempt):
        calls.append(replica_id)
        raise RuntimeError("no capacity")

    router = FleetRouter(
        {"fleet": {"replicas": 1, "max_restarts": 2,
                   "backoff_base_s": 0.01, "backoff_max_s": 0.02}},
        fleet_dir=str(tmp_path / "fleet"), spawn_fn=bad_spawn)
    queued = router.submit([1], max_new_tokens=2)
    with pytest.raises(FleetGiveUpError) as ei:
        router.start()
    assert ei.value.restarts == 3          # budget 2 -> third strike
    assert "no capacity" in ei.value.last_failure
    assert len(calls) == 3
    # the give-up failed every in-flight request typed and dumped the
    # supervisor flight record for the post-mortem
    assert isinstance(queued.error, FleetGiveUpError)
    rec_path = os.path.join(router.fleet_dir,
                            "flightrec_supervisor.json")
    with open(rec_path) as f:
        rec = json.load(f)
    assert rec["stages"]["fleet"]["events"]
    # closed: further submits are refused
    with pytest.raises(RuntimeError):
        router.submit([1])


def test_spawn_timeout_counts_as_failure(tmp_path):
    """A replica that never says hello is a failed spawn: killed,
    counted against the give-up budget."""
    fl = Fleet(tmp_path, {"replicas": 1, "max_restarts": 0})
    fl.router.cfg = DeepSpeedFleetConfig(
        {"fleet": {"replicas": 1, "max_restarts": 0,
                   "spawn_timeout_s": 5.0, "backoff_base_s": 0.01}})

    def mute_spawn(replica_id, attempt):
        proc = FakeProc()
        fl.fakes[replica_id] = type("F", (), {"proc": proc})()
        return proc

    fl.router.spawn_fn = mute_spawn
    fl.router._spawn("initial")
    fl.advance(6.0)  # past spawn_timeout_s
    with pytest.raises(FleetGiveUpError):
        fl.router.poll(0.01)


def test_garbage_connection_cannot_crash_router(tmp_path):
    """A port scanner (or corrupt framing) on the router's listen port
    fails ITSELF — poll keeps routing and real replicas keep serving."""
    fl = Fleet(tmp_path, {"replicas": 1, "max_replicas": 1}).start()
    try:
        scanner = socket.create_connection(fl.router.addr, timeout=5.0)
        scanner.sendall(b"\xff\xff\xff\xffGARBAGE")  # >16MiB length prefix
        fl.pump()  # must not raise
        r1 = fl.router.submit([1], max_new_tokens=2)
        deadline = time.monotonic() + 5
        while not fl.fakes[0].submits and time.monotonic() < deadline:
            fl.pump(1)
        fl.fakes[0].admit(1)
        fl.fakes[0].tokens(1, [9])
        fl.fakes[0].done(1, total=1)
        fl.pump()
        assert r1.result(timeout=5) == [9]
        scanner.close()
    finally:
        fl.router.close()


def test_close_fails_inflight_typed_and_is_idempotent(tmp_path):
    fl = Fleet(tmp_path, {"replicas": 1, "max_replicas": 1}).start()
    r1 = fl.router.submit([1], max_new_tokens=2)
    fl.pump()
    fl.router.close()
    assert isinstance(r1.error, FleetClosedError)
    with pytest.raises(FleetClosedError):
        r1.result(timeout=1)
    fl.router.close()  # idempotent
    assert fl.fakes[0].proc.rc is not None  # replica torn down


def test_fleet_events_ledger_and_heartbeat_age_metrics(tmp_path):
    """The router's events.jsonl is the fleet's request ledger +
    per-replica liveness export: every submit has a completion record,
    and metrics records carry heartbeat_age_s{replica=...}."""
    fl = Fleet(tmp_path, {"replicas": 1, "max_replicas": 1}).start()
    try:
        w = HeartbeatWriter(fl.router.fleet_dir, process_index=0)
        w.beat(1, extra={"serve_active_slots": 0})
        r1 = fl.router.submit([1], max_new_tokens=2)
        deadline = time.monotonic() + 5
        while not fl.fakes[0].submits and time.monotonic() < deadline:
            fl.pump(1)
        fl.fakes[0].admit(1)
        fl.fakes[0].tokens(1, [4])
        fl.fakes[0].done(1, total=1)
        fl.pump()
        assert r1.result(timeout=5) == [4]
        fl.router._last_beats_read = 0.0
        fl.router._last_metrics_write = 0.0
        fl.router.poll(0.01)
    finally:
        fl.router.close()
    recs = []
    with open(os.path.join(fl.router.fleet_dir, "events.jsonl")) as f:
        for line in f:
            recs.append(json.loads(line))
    kinds = [r["kind"] for r in recs]
    assert "fleet_submit" in kinds and "fleet_request" in kinds
    done = next(r for r in recs if r["kind"] == "fleet_request")
    assert done["rid"] == 1 and done["error"] is None
    assert done["queue_wait_s"] is not None
    # the LAST metrics record: the first may predate the beat file
    mrec = [r for r in recs if r["kind"] == "metrics"][-1]
    ages = [m for m in mrec["metrics"]
            if m["name"] == "heartbeat_age_s"]
    assert ages and ages[0]["labels"]["replica"] == "0"
    assert ages[0]["value"] is not None and ages[0]["value"] >= 0


# ---------------------------------------------------------------------------
# diagnose: the fleet-directory post-mortem
# ---------------------------------------------------------------------------


def test_diagnose_fleet_directory_correlation(tmp_path, capsys):
    from deepspeed_tpu.telemetry.cli import diagnose
    d = tmp_path / "fleet"
    (d / "replica_0").mkdir(parents=True)
    (d / "replica_1").mkdir()
    with open(d / "replica_0" / "flightrec_5.json", "w") as f:
        json.dump({"version": 1, "reason": "serve poison", "step": 5,
                   "error": "RuntimeError('boom')",
                   "stages": {"serve": {"events": [
                       {"t": 100.0, "kind": "poison",
                        "error": "RuntimeError('boom')"}]}}}, f)
    events = [
        {"kind": "fleet_submit", "t": 99.0, "rid": 1},
        {"kind": "fleet_submit", "t": 99.1, "rid": 2},
        {"kind": "fleet_submit", "t": 99.2, "rid": 3},
        {"kind": "replica_dead", "t": 100.5, "replica": 0,
         "reason": "replica 0 exited rc=13", "failed_over": 1},
        {"kind": "fleet_request", "t": 101.0, "rid": 1,
         "error": "ReplicaFailure('mid-stream')", "started": True,
         "failovers": 0},
        {"kind": "fleet_request", "t": 101.5, "rid": 2, "error": None,
         "started": True, "failovers": 1, "queue_wait_s": 0.3},
    ]
    with open(d / "events.jsonl", "w") as f:
        for e in events:
            f.write(json.dumps(e) + "\n")
    report = diagnose(str(d))
    out = capsys.readouterr().out
    assert report["fleet_replica_dirs"] == 2
    assert report["fleet_failover_count"] == 1
    assert report["fleet_dangling_requests"] == 1   # rid 3 never done
    assert report["fleet_failed_requests"] == 1
    assert report["fleet_first_dead_replica"] == 0
    assert report["fleet_first_failing_replica"] == "replica_0"
    assert "failed over" in out and "DANGLING" in out
    assert "replica_0" in out


def test_diagnose_non_fleet_dir_unchanged(tmp_path, capsys):
    """A plain telemetry dir must not grow fleet rows."""
    from deepspeed_tpu.telemetry.cli import diagnose
    with open(tmp_path / "events.jsonl", "w") as f:
        f.write(json.dumps({"kind": "step", "step": 1}) + "\n")
    report = diagnose(str(tmp_path))
    out = capsys.readouterr().out
    assert "failed over" not in out and "DANGLING" not in out
    assert "fleet_failover_count" not in report
    assert "fleet_replica_dirs" not in report


# ---------------------------------------------------------------------------
# subprocess e2e: real replicas behind the router
# ---------------------------------------------------------------------------


def _e2e_config(replicas, *, slots=4, telemetry=False, **fleet_over):
    return {
        "serving": {"slots": slots, "max_seq_len": 64,
                    "prefill_len": 8, "queue_capacity": 256,
                    "flush_interval_ticks": 5},
        "telemetry": {"enabled": telemetry},
        "fleet": {"replicas": replicas, "min_replicas": 1,
                  "max_replicas": max(replicas, 2),
                  "slo_p99_s": 30.0, "scale_up_window_s": 5.0,
                  "scale_down_window_s": 600.0,
                  "spawn_timeout_s": 120.0, "backoff_base_s": 0.2,
                  "heartbeat_timeout_s": 60.0, **fleet_over},
        "fleet_model": {"vocab_size": 128, "n_positions": 64,
                        "d_model": 32, "n_layer": 2, "n_head": 4,
                        "attn_impl": "dense", "seed": 0},
    }


def _prompts(n, seed=0):
    rng = np.random.default_rng(seed)
    return [[int(t) for t in rng.integers(0, 128, (5,))]
            for _ in range(n)]


def test_e2e_single_replica_fleet_matches_bare_engine(tmp_path):
    """The parity bar: a 1-replica fleet emits the SAME greedy stream
    as a bare ServeEngine for the same request trace (the replica
    builds identical params from the shared fleet_model seed), and the
    replica's zero-recompile property survives the wire."""
    from deepspeed_tpu.inference.replica import build_engine
    cfg = _e2e_config(1, telemetry=True)
    prompts = _prompts(6)

    eng = build_engine(cfg, str(tmp_path / "bare"), 99)
    bare = [eng.submit(p, max_new_tokens=6) for p in prompts]
    eng.run_until_idle()
    bare_toks = [r.tokens for r in bare]
    bare_reasons = [r.finish_reason for r in bare]
    eng.close()

    d = str(tmp_path / "fleet")
    router = FleetRouter(cfg, fleet_dir=d)
    try:
        router.start()
        reqs = [router.submit(p, max_new_tokens=6) for p in prompts]
        router.run_until_idle(max_s=120)
        assert [r.tokens for r in reqs] == bare_toks
        assert [r.finish_reason for r in reqs] == bare_reasons
        assert all(r.queue_wait_s is not None for r in reqs)
    finally:
        router.close()
    # the replica's telemetry landed in its own subdir; its compile
    # tracking pins the decode program at zero recompiles through the
    # whole mixed trace (the bare-engine contract, preserved per
    # replica)
    rep_dir = os.path.join(d, "replica_0")
    assert os.path.isdir(rep_dir)
    prom = os.path.join(rep_dir, "metrics.prom")
    if os.path.isfile(prom):
        with open(prom) as f:
            for line in f:
                if line.startswith("recompiles_total") \
                        and "decode_step" in line:
                    assert float(line.rsplit(None, 1)[1]) == 0.0


def test_e2e_burst_larger_than_engine_queue_capacity(tmp_path):
    """Overload regression: the router dispatches unbounded, but the
    replica's engine queue is a BLOCKING bounded channel — a burst
    beyond serving.queue_capacity must park in the replica's host-side
    backlog and drain as the engine steps, never deadlock the
    single-threaded replica loop."""
    cfg = _e2e_config(1, slots=2)
    cfg["serving"]["queue_capacity"] = 4
    router = FleetRouter(cfg, fleet_dir=str(tmp_path / "fleet"))
    try:
        router.start()
        reqs = [router.submit(p, max_new_tokens=4)
                for p in _prompts(12, seed=5)]   # 3x the queue bound
        router.run_until_idle(max_s=120)
        assert all(r.error is None for r in reqs), \
            [repr(r.error) for r in reqs if r.error]
        assert all(len(r.tokens) == 4 for r in reqs)
    finally:
        router.close()


def test_e2e_replica_kill_fails_over_unstarted(tmp_path,
                                               monkeypatch):
    """Kill one of two REAL replicas mid-stream: every queued-but-
    unstarted request completes via failover (zero lost), mid-stream
    casualties fail typed, and the ledger agrees."""
    # slow the serving ticks so the kill reliably lands mid-stream
    monkeypatch.setenv("DS_STAGE_DELAY_S", "serve:0.05")
    reset_fault_injection()
    cfg = _e2e_config(2, slots=2)
    d = str(tmp_path / "fleet")
    router = FleetRouter(cfg, fleet_dir=d)
    try:
        router.start()
        initial = sorted(router.replicas)
        reqs = [router.submit(p, max_new_tokens=8)
                for p in _prompts(16, seed=3)]
        # wait until both replicas are streaming (started requests on
        # each), so the kill hits a mix of started + queued work
        deadline = time.monotonic() + 60
        while time.monotonic() < deadline:
            router.poll(0.02)
            started_by = {rid: any(r.started and r.replica == rid
                                   for r in reqs)
                          for rid in initial}
            if all(started_by.values()):
                break
        assert all(started_by.values()), "replicas never streamed"
        victim = max(router.replicas.values(),
                     key=lambda r: len(r.outstanding)).id
        router.kill_replica(victim)
        router.run_until_idle(max_s=120)
        failed = [r for r in reqs if r.error is not None]
        # zero queued-but-unstarted requests lost
        assert all(r.started for r in failed)
        assert all(isinstance(r.error, ReplicaFailure) for r in failed)
        survivors = [r for r in reqs if r.error is None]
        assert survivors and all(len(r.tokens) == 8 for r in survivors)
        assert sum(r.failovers for r in reqs) > 0
    finally:
        router.close()
    # the ledger agrees: every submit completed, failures all started
    recs = []
    with open(os.path.join(d, "events.jsonl")) as f:
        for line in f:
            recs.append(json.loads(line))
    submits = [r for r in recs if r["kind"] == "fleet_submit"]
    dones = {r["rid"]: r for r in recs
             if r["kind"] == "fleet_request"}
    assert len(dones) == len(submits)
    assert all(r["started"] for r in dones.values() if r["error"])
    assert any(r["kind"] == "replica_dead" and r["failed_over"] > 0
               for r in recs)
